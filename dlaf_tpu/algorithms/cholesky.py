"""Cholesky factorization — local and distributed.

TPU-native counterpart of the reference's ``factorization/cholesky``
(``factorization/cholesky/impl.h:134-276``; public API ``cholesky.h:36,62``):
the right-looking tile algorithm — ``potrf`` on the diagonal block, panel
``trsm``, trailing ``herk``/``gemm`` update — re-designed for XLA:

* The per-``k`` loop is unrolled at *trace time* (the tile count is static),
  so every step has static shapes and the whole factorization is ONE compiled
  program. The reference's look-ahead machinery (round-robin panels,
  priorities, ``impl.h:187-189``) is unnecessary: XLA sees the full dependency
  DAG and overlaps panel ``k+1`` with trailing update ``k`` on its own.
* Within a step the trailing update is a single batched einsum over local
  tiles — the MXU-idiomatic form of the reference's per-tile ``herk``/``gemm``
  task fan-out.
* Distributed (``call_L`` analog, ``impl.h:174-276``): SPMD ``shard_map`` over
  the 2D mesh. The diagonal tile is broadcast with two mask+psum hops (the
  reference's diag-tile column broadcast), every rank solves the panel rows it
  owns, the panel is row-broadcast and all-gathered to build the transposed
  panel (the reference's ``broadcast_panel`` + ``panelT``), and rank-local
  masks derived from ``axis_index`` keep the update inside the trailing lower
  triangle.

Only the lower/upper triangle of the input (per ``uplo``) is read; the other
triangle passes through, matching LAPACK/reference semantics.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from .. import obs
from ..config import register_program_cache
from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..common.asserts import dlaf_assert
from ..health import info as hinfo
from ..matrix import util_distribution as ud
from ..matrix.matrix import Matrix
from ..matrix.panel import (DistContext, pad_diag_identity_dyn,
                            transpose_col_to_rows, transpose_row_to_cols,
                            uniform_slot_start)
from ..matrix.tiling import (storage_tile_grid, global_to_tiles_donated,
                             to_global, quiet_donation, donate_argnums_kw)
from ..tile_ops import blas as tb
from ..tile_ops import lapack as tl
from ..tile_ops import mixed as mx
from ..tile_ops import ozaki as oz
from ..tile_ops import pallas_panel as ppan
from ..tile_ops.pallas_kernels import masked_trailing_update, supports_pallas_update
from ..types import ceil_div, telescope_segments, telescope_windows, total_ops

# back-compat alias (tests import the old private name)
_telescope_segments = telescope_segments


# ---------------------------------------------------------------------------
# Local (single device) — reference impl.h:134-171
# ---------------------------------------------------------------------------

#: Valid cholesky_trailing strategies (see config.Configuration); bench.py
#: sweeps this set on the measured hardware.
#: Trailing-update formulations. "scan" is the lax.scan step mode; unlike
#: "ozaki" (which forces the MXU route for f64/c128) it selects its panel
#: and trailing routes from the f64_trsm/f64_gemm knobs, identically on
#: 1 device and on a grid.
VALID_TRAILING = ("loop", "biggemm", "invgemm", "xla", "ozaki", "scan")



def _oz_product(x, y):
    """``x @ y`` on the error-free int8/bf16 MXU route (complex picks the
    4-real-product composition) — the lookahead split's column strip, on
    the same route as the bulk it was split from."""
    mm = oz.matmul_c128 if jnp.iscomplexobj(x) else oz.matmul_f64
    return mm(x, y, slices=tb._oz_slices())


def _count_step_modes(algo: str, overlapped: int, serialized: int) -> None:
    """Trace-time tile-step accounting for the lookahead pipeline: how many
    steps of the compiled program were emitted in the overlapped (next-
    panel-column-first) order vs the plain serialized order."""
    if obs.metrics_active():
        if overlapped:
            obs.counter("dlaf_cholesky_steps_total", algo=algo,
                        mode="overlapped").inc(overlapped)
        if serialized:
            obs.counter("dlaf_cholesky_steps_total", algo=algo,
                        mode="serialized").inc(serialized)


@register_program_cache
@functools.partial(jax.jit, static_argnames=("uplo", "nb", "trailing",
                                             "lookahead", "with_info",
                                             "panel_fused", "step_fused",
                                             "panel_interpret", "route"),
                   donate_argnums=0)
def _cholesky_local(a, *, uplo: str, nb: int, trailing: str = "loop",
                    lookahead: bool = False, with_info: bool = False,
                    panel_fused: bool = False, step_fused: bool = False,
                    panel_interpret: bool = False,
                    route: tuple = ()):
    # ``route`` is the active autotune route's cache-key component
    # (docs/autotune.md): the builders read route-sensitive knobs at
    # trace time (_oz_slices / trsm_panel route), so a route change must
    # be a different compiled program, never a stale-trace reuse
    n = a.shape[0]
    # "ozaki": route the flops-dominant trailing update through int8 MXU
    # passes (tile_ops.ozaki) — f64 and complex128 (4-real-product form);
    # other dtypes keep the native whole-gemm form (static, trace time)
    use_oz = trailing == "ozaki" and a.dtype in (jnp.float64, jnp.complex128)
    if trailing == "ozaki" and not use_oz:
        trailing = "biggemm"
    if trailing == "xla" and n:
        # whole-matrix XLA cholesky: the compiler's own fused/blocked
        # factorization (a TPU-native option the reference cannot take —
        # its local algorithm must hand-block; ours may delegate blocking
        # to XLA). Triangle pass-through semantics preserved.
        from jax import lax

        if uplo == "L":
            ah = jnp.tril(a) + jnp.conj(jnp.tril(a, -1)).T
            l = lax.linalg.cholesky(ah)
            out = jnp.tril(l) + jnp.triu(a, 1)
        else:
            ah = jnp.triu(a) + jnp.conj(jnp.triu(a, 1)).T
            l = lax.linalg.cholesky(ah)
            out = jnp.triu(jnp.conj(l).T) + jnp.tril(a, -1)
        # in-graph info (health.info): a pure extra output on the final
        # factor — the factor subgraph is untouched either way
        return (out, hinfo.local_factor_info(out)) if with_info else out
    nt = ceil_div(n, nb) if n else 0
    # lookahead carry: the next panel column's (diag block, below-diag
    # block) values as step k's SSA outputs, so step k+1's potrf/trsm
    # chain consumes them directly instead of reading `a` after the bulk
    # trailing scatter — the dependency XLA needs to overlap panel k+1
    # with the bulk herk/gemm of step k (reference look-ahead,
    # ``factorization/cholesky/impl.h:147-156,187-189``)
    la = None
    for k in range(nt):
        if obs.metrics_active():
            # trace-time tile-op accounting (once per compiled program):
            # one potrf + (nt-k-1) panel-solve tiles per step, and the
            # trailing update's tile-pair count under the loop schedule
            tail = nt - k - 1
            obs.counter("dlaf_algo_tile_ops_total", algo="cholesky",
                        op="potrf").inc()
            obs.counter("dlaf_algo_tile_ops_total", algo="cholesky",
                        op="trsm").inc(tail)
            obs.counter("dlaf_algo_tile_ops_total", algo="cholesky",
                        op="herk").inc(tail)
            obs.counter("dlaf_algo_tile_ops_total", algo="cholesky",
                        op="gemm").inc(tail * (tail - 1) // 2)
            _count_step_modes("cholesky", *((1, 0) if lookahead and tail
                                            else (0, 1)))
        k0, k1 = k * nb, min((k + 1) * nb, n)
        blk = a[k0:k1, k0:k1] if la is None else la[0]
        if step_fused and k1 < n:
            # step_impl route (docs/pallas_panel.md "Fused step kernel"):
            # ONE pallas_call per blocked step — potrf ladder + whole
            # strip solve + the adjacent trailing column/row strip, with
            # the factor, its inverse, and the solved leading strip
            # block VMEM-resident between the three ops. The remaining
            # trailing update is the row/column-trimmed rest-herk of the
            # lookahead split (same dots, same per-cell application
            # order), so the la on/off contract stays bitwise on this
            # route regardless of the lookahead knob.
            m = n - k1
            w = min(nb, m)
            ppan.count_step_kernel("fused")
            if uplo == "L":
                colsrc = a[k1:, k0:k1] if la is None else la[1]
                diag, panel, new_col = ppan.fused_step(
                    "L", blk, colsrc, a[k1:, k1:k1 + w],
                    interpret=panel_interpret)
                a = a.at[k0:k1, k0:k1].set(diag)
                a = a.at[k1:, k0:k1].set(panel)
                a = a.at[k1:, k1:k1 + w].set(new_col)
                la = ((new_col[:w], new_col[w:] if k1 + w < n else None)
                      if lookahead else None)
                if trailing == "loop":
                    for j in range(k + 2, nt):
                        j0, j1 = j * nb, min((j + 1) * nb, n)
                        pj = panel[j0 - k1: j1 - k1]
                        dj = tb.herk("L", "N", pj, a[j0:j1, j0:j1],
                                     alpha=-1.0)
                        a = a.at[j0:j1, j0:j1].set(dj)
                        if j1 < n:
                            below = tb.gemm(panel[j1 - k1:], pj,
                                            a[j1:, j0:j1], alpha=-1.0,
                                            beta=1.0, op_b="C")
                            a = a.at[j1:, j0:j1].set(below)
                elif m > w:
                    pr = panel[w:]
                    upd = pr @ jnp.conj(pr).T
                    mask = jnp.tril(jnp.ones((m - w, m - w), dtype=bool))
                    a = a.at[k1 + w:, k1 + w:].add(jnp.where(mask, -upd, 0))
            else:
                rowsrc = a[k0:k1, k1:] if la is None else la[1]
                diag, panel, new_row = ppan.fused_step(
                    "U", blk, rowsrc, a[k1:k1 + w, k1:],
                    interpret=panel_interpret)
                a = a.at[k0:k1, k0:k1].set(diag)
                a = a.at[k0:k1, k1:].set(panel)
                a = a.at[k1:k1 + w, k1:].set(new_row)
                la = ((new_row[:, :w], new_row[:, w:]
                       if k1 + w < n else None) if lookahead else None)
                if trailing == "loop":
                    for j in range(k + 2, nt):
                        j0, j1 = j * nb, min((j + 1) * nb, n)
                        pj = panel[:, j0 - k1: j1 - k1]
                        dj = tb.herk("U", "C", pj, a[j0:j1, j0:j1],
                                     alpha=-1.0)
                        a = a.at[j0:j1, j0:j1].set(dj)
                        if j1 < n:
                            right = tb.gemm(pj, panel[:, j1 - k1:],
                                            a[j0:j1, j1:], alpha=-1.0,
                                            beta=1.0, op_a="C")
                            a = a.at[j0:j1, j1:].set(right)
                elif m > w:
                    pr = panel[:, w:]
                    upd = jnp.conj(pr).T @ pr
                    mask = jnp.triu(jnp.ones((m - w, m - w), dtype=bool))
                    a = a.at[k1 + w:, k1 + w:].add(jnp.where(mask, -upd, 0))
            continue
        if use_oz:
            # latency-bound panel ops in mixed precision (f32 seed + Newton,
            # tile_ops.mixed): emulated-f64 potrf/trsm are the wall-clock
            # bottleneck on TPU, not the trailing flops. The fused form
            # shares the f32 seed solves between factor and inverse — one
            # f32 cholesky + one f32 solve per step instead of two solves.
            # Counted under impl="xla" like every non-fused panel kernel
            # (the mixed form is still an XLA op chain)
            ppan.count_panel_kernel("xla", "potrf")
            fac, fac_inv = mx.potrf_inv_refined(uplo, blk)
            other = "U" if uplo == "L" else "L"
            diag = fac + tb.tri_mask(blk, other, k=-1)
        else:
            # panel_impl route (docs/pallas_panel.md): the fused Pallas
            # potrf collapses XLA's blocked-cholesky thunk chain into one
            # VMEM-resident kernel; "xla" keeps tl.potrf
            fac_inv = None
            diag = ppan.panel_potrf(uplo, blk, fused=panel_fused,
                                  interpret=panel_interpret)
        a = a.at[k0:k1, k0:k1].set(diag)
        if k1 == n:
            break
        m = n - k1
        # strip-bearing step on the composed-op chain (step_impl route
        # accounting — the fused branch above counts impl="fused")
        ppan.count_step_kernel("xla")
        if uplo == "L":
            # panel: A[k1:, k] <- A[k1:, k] Lkk^-H   (tile::trsm, high-prio
            # in the reference impl.h:147-156; here XLA schedules it) —
            # under lookahead the panel source is the carried next-column
            # value from step k-1, not an `a` read
            colsrc = a[k1:, k0:k1] if la is None else la[1]
            if use_oz:
                # refined explicit inverse (from the fused step above) ->
                # the panel solve is one gemm instead of an emulated trsm;
                # the gemm itself rides the int8 MXU path like the trailing
                # update (native emulated-f64 gemm is ~3x slower)
                ppan.count_panel_kernel("xla", "solve")
                panel = tb.mm_mxu(colsrc, jnp.conj(fac_inv).T)
            elif trailing == "invgemm":
                ppan.count_panel_kernel("xla", "solve")
                # explicit small triangular inverse, panel formed on the MXU
                dinv = tb.trsm("L", "L", "N", "N", diag,
                               jnp.eye(k1 - k0, dtype=a.dtype))
                panel = colsrc @ jnp.conj(dinv).T
            elif panel_fused:
                # one grid-batched Pallas kernel for the whole strip
                panel = ppan.panel_solve("R", "L", "C", "N", diag, colsrc,
                                       fused=True, interpret=panel_interpret)
            else:
                ppan.count_panel_kernel("xla", "solve")
                panel = tb.trsm("R", "L", "C", "N", diag, colsrc)
            a = a.at[k1:, k0:k1].set(panel)
            la = None
            if trailing == "loop":
                # trailing per block column: herk on the diagonal block + one
                # gemm below it — exact n^3/3 flops (reference impl.h:242-271)
                for j in range(k + 1, nt):
                    j0, j1 = j * nb, min((j + 1) * nb, n)
                    pj = panel[j0 - k1: j1 - k1]
                    dj = tb.herk("L", "N", pj, a[j0:j1, j0:j1], alpha=-1.0)
                    a = a.at[j0:j1, j0:j1].set(dj)
                    below = None
                    if j1 < n:
                        below = tb.gemm(panel[j1 - k1:], pj, a[j1:, j0:j1],
                                        alpha=-1.0, beta=1.0, op_b="C")
                        a = a.at[j1:, j0:j1].set(below)
                    if lookahead and j == k + 1:
                        # the loop schedule already emits column k+1 first;
                        # carrying its values is what frees step k+1 from
                        # the later columns' scatter chain
                        la = (dj, below)
            elif lookahead:
                # next-panel-column strip first (consumed by step k+1 via
                # the carry), then the remaining trailing as a (m-w)^2
                # herk of the row-trimmed panel — same dots, same per-cell
                # application order as the single masked product
                w = min(nb, m)
                pj = panel[:w]
                updc = (_oz_product(panel, jnp.conj(pj).T) if use_oz
                        else panel @ jnp.conj(pj).T)
                cmask = jnp.arange(m)[:, None] >= jnp.arange(w)[None, :]
                # x + where(mask, -upd, 0): the exact per-cell application
                # the serial masked add performs (bitwise, zeros included)
                new_col = a[k1:, k1:k1 + w] + jnp.where(cmask, -updc, 0)
                a = a.at[k1:, k1:k1 + w].set(new_col)
                la = (new_col[:w], new_col[w:] if k1 + w < n else None)
                if m > w:
                    pr = panel[w:]
                    if use_oz:
                        upd = (oz.herk_c128(pr, slices=tb._oz_slices())
                               if jnp.iscomplexobj(pr)
                               else oz.syrk_f64(pr, slices=tb._oz_slices()))
                    else:
                        upd = pr @ jnp.conj(pr).T
                    mask = jnp.tril(jnp.ones((m - w, m - w), dtype=bool))
                    a = a.at[k1 + w:, k1 + w:].add(jnp.where(mask, -upd, 0))
            else:
                # ONE full trailing update, masked to the lower triangle;
                # "ozaki" forms it with int8 MXU passes instead of the
                # software-emulated f64 gemm
                if use_oz:
                    upd = (oz.herk_c128(panel, slices=tb._oz_slices())
                           if jnp.iscomplexobj(panel)
                           else oz.syrk_f64(panel, slices=tb._oz_slices()))
                else:
                    upd = panel @ jnp.conj(panel).T
                mask = jnp.tril(jnp.ones((m, m), dtype=bool))
                a = a.at[k1:, k1:].add(jnp.where(mask, -upd, 0))
        else:
            # upper: A = U^H U; panel is a block row
            rowsrc = a[k0:k1, k1:] if la is None else la[1]
            if use_oz:
                ppan.count_panel_kernel("xla", "solve")
                panel = tb.mm_mxu(jnp.conj(fac_inv).T, rowsrc)
            elif trailing == "invgemm":
                ppan.count_panel_kernel("xla", "solve")
                dinv = tb.trsm("L", "U", "N", "N", diag,
                               jnp.eye(k1 - k0, dtype=a.dtype))
                panel = jnp.conj(dinv).T @ rowsrc
            elif panel_fused:
                panel = ppan.panel_solve("L", "U", "C", "N", diag, rowsrc,
                                       fused=True, interpret=panel_interpret)
            else:
                ppan.count_panel_kernel("xla", "solve")
                panel = tb.trsm("L", "U", "C", "N", diag, rowsrc)
            a = a.at[k0:k1, k1:].set(panel)
            la = None
            if trailing == "loop":
                for j in range(k + 1, nt):
                    j0, j1 = j * nb, min((j + 1) * nb, n)
                    pj = panel[:, j0 - k1: j1 - k1]
                    dj = tb.herk("U", "C", pj, a[j0:j1, j0:j1], alpha=-1.0)
                    a = a.at[j0:j1, j0:j1].set(dj)
                    right = None
                    if j1 < n:
                        right = tb.gemm(pj, panel[:, j1 - k1:], a[j0:j1, j1:],
                                        alpha=-1.0, beta=1.0, op_a="C")
                        a = a.at[j0:j1, j1:].set(right)
                    if lookahead and j == k + 1:
                        la = (dj, right)
            elif lookahead:
                # next block-row strip first (carried), rest as the
                # column-trimmed herk — the mirrored split
                w = min(nb, m)
                pt = jnp.conj(jnp.swapaxes(panel, -1, -2))
                updr = (_oz_product(pt[:w], jnp.conj(pt).T) if use_oz
                        else jnp.conj(panel[:, :w]).T @ panel)
                rmask = jnp.arange(w)[:, None] <= jnp.arange(m)[None, :]
                new_row = a[k1:k1 + w, k1:] + jnp.where(rmask, -updr, 0)
                a = a.at[k1:k1 + w, k1:].set(new_row)
                la = (new_row[:, :w], new_row[:, w:] if k1 + w < n else None)
                if m > w:
                    ptr = pt[w:]
                    if use_oz:
                        upd = (oz.herk_c128(ptr, slices=tb._oz_slices())
                               if jnp.iscomplexobj(ptr)
                               else oz.syrk_f64(ptr, slices=tb._oz_slices()))
                    else:
                        pr = panel[:, w:]
                        upd = jnp.conj(pr).T @ pr
                    mask = jnp.triu(jnp.ones((m - w, m - w), dtype=bool))
                    a = a.at[k1 + w:, k1 + w:].add(jnp.where(mask, -upd, 0))
            else:
                if use_oz:
                    pt = jnp.conj(jnp.swapaxes(panel, -1, -2))
                    upd = (oz.herk_c128(pt, slices=tb._oz_slices())
                           if jnp.iscomplexobj(panel)
                           else oz.syrk_f64(pt, slices=tb._oz_slices()))
                else:
                    upd = jnp.conj(panel).T @ panel
                mask = jnp.triu(jnp.ones((m, m), dtype=bool))
                a = a.at[k1:, k1:].add(jnp.where(mask, -upd, 0))
    return (a, hinfo.local_factor_info(a)) if with_info else a


@register_program_cache
@functools.partial(jax.jit, static_argnames=("uplo", "nb", "use_mxu",
                                             "use_mixed", "lookahead",
                                             "with_info", "panel_fused",
                                             "step_fused",
                                             "panel_interpret", "route"),
                   donate_argnums=0)
def _cholesky_local_scan(a, *, uplo: str, nb: int, use_mxu: bool = False,
                         use_mixed: bool = False, lookahead: bool = False,
                         with_info: bool = False, panel_fused: bool = False,
                         step_fused: bool = False,
                         panel_interpret: bool = False, route: tuple = ()):
    """``lax.scan`` formulation of the local factorization: ONE compiled
    step body, looped ``nt`` times with uniform full-size shapes.

    Why it exists: the unrolled trace (:func:`_cholesky_local`) compiles in
    time linear in ``nt`` with a ~19 s/step constant on the v5e tunnel's
    chipless AOT toolchain (docs/DESIGN.md) and its per-step intermediates
    are all simultaneously visible to the allocator. The scanned form
    compiles O(1) programs and reuses carry buffers, at the documented
    price of uniform-shape work: the panel is the FULL block column (rows
    above the pivot masked) and the trailing update is a FULL (n, n)
    masked product every step — ~3x the exact trailing flops. The right
    trade when compile latency or HBM liveness binds, not when flops do
    (bench.py sweeps both).

    The panel and trailing routes follow the same knobs as the distributed
    scan builder (:func:`_build_dist_cholesky_scan`): ``use_mixed``
    (``f64_trsm="mixed"``) factors panels via the mixed-precision fused
    factor+inverse, ``use_mxu`` (``f64_gemm="mxu"``) contracts the trailing
    product on the ozaki MXU path. Both default off, so the same dtype and
    ``trailing="scan"`` config resolves identically on 1 device and on a
    grid (round-2 advisory: the previous hardwired f64 route made the scan
    variant pathological off-TPU). Triangle pass-through semantics match
    the unrolled path.
    """
    n = a.shape[0]
    if n == 0:
        return (a, jnp.zeros((), jnp.int32)) if with_info else a
    nt = ceil_div(n, nb)
    npad = nt * nb - n
    if npad:
        # pad to uniform blocks with an identity tail: chol([[A,0],[0,I]])
        # = [[L,0],[0,I]] and the pad rows/cols never touch the result
        a = jnp.pad(a, ((0, npad), (0, npad)))
        a = a.at[jnp.arange(n, nt * nb), jnp.arange(n, nt * nb)].set(1)
    other = "U" if uplo == "L" else "L"

    def make_step(m):
        rows = jnp.arange(m)

        def step(acc, k):
            k0 = k * nb
            blk = jax.lax.dynamic_slice(acc, (k0, k0), (nb, nb))
            ppan.count_step_kernel("fused" if step_fused else "xla")
            if use_mixed:
                ppan.count_panel_kernel("xla", "potrf")
                fac, fac_inv = mx.potrf_inv_refined(uplo, blk)
                diag = fac + tb.tri_mask(blk, other, k=-1)
            elif step_fused:
                # step_impl route, scan form: the potrf is DEFERRED into
                # the fused factor+solve kernel below (the trailing
                # update's traced-index masks keep it outside the
                # kernel, so the scan forms fuse the 2-op panel chain)
                fac_inv = diag = None
            else:
                fac_inv = None
                diag = ppan.panel_potrf(uplo, blk, fused=panel_fused,
                                      interpret=panel_interpret)
            if diag is not None:
                acc = jax.lax.dynamic_update_slice(acc, diag, (k0, k0))
            below = rows >= k0 + nb      # (m,) rows/cols past the pivot
            if uplo == "L":
                col = jax.lax.dynamic_slice(acc, (0, k0), (m, nb))
                if use_mixed:
                    ppan.count_panel_kernel("xla", "solve")
                    inv_t = jnp.conj(fac_inv).T
                    pfull = tb.mm_mxu(col, inv_t) if use_mxu else col @ inv_t
                elif step_fused:
                    # col's pivot rows hold the unfactored blk; the
                    # write-back + explicit diag update below restore
                    # the factored tile
                    diag, pfull = ppan.fused_factor_solve(
                        "L", blk, col, interpret=panel_interpret)
                elif panel_fused:
                    pfull = ppan.panel_solve("R", "L", "C", "N", diag, col,
                                           fused=True,
                                           interpret=panel_interpret)
                else:
                    ppan.count_panel_kernel("xla", "solve")
                    pfull = tb.trsm("R", "L", "C", "N", diag, col)
                panel = jnp.where(below[:, None], pfull, 0)
                acc = jax.lax.dynamic_update_slice(
                    acc, jnp.where(below[:, None], pfull, col), (0, k0))
                if step_fused:
                    acc = jax.lax.dynamic_update_slice(acc, diag, (k0, k0))
                if use_mxu:
                    upd = (oz.herk_c128(panel, slices=tb._oz_slices())
                           if jnp.iscomplexobj(panel)
                           else oz.syrk_f64(panel, slices=tb._oz_slices()))
                else:
                    upd = panel @ jnp.conj(panel).T
                # panel is zero at rows <= pivot, so upd lives only in the
                # trailing block; restrict to the stored lower triangle
                tri = rows[:, None] >= rows[None, :]
                acc = acc - jnp.where(tri, upd, 0)
            else:
                row = jax.lax.dynamic_slice(acc, (k0, 0), (nb, m))
                if use_mixed:
                    ppan.count_panel_kernel("xla", "solve")
                    inv_t = jnp.conj(fac_inv).T
                    pfull = tb.mm_mxu(inv_t, row) if use_mxu else inv_t @ row
                elif step_fused:
                    diag, pfull = ppan.fused_factor_solve(
                        "U", blk, row, interpret=panel_interpret)
                elif panel_fused:
                    pfull = ppan.panel_solve("L", "U", "C", "N", diag, row,
                                           fused=True,
                                           interpret=panel_interpret)
                else:
                    ppan.count_panel_kernel("xla", "solve")
                    pfull = tb.trsm("L", "U", "C", "N", diag, row)
                panel = jnp.where(below[None, :], pfull, 0)
                acc = jax.lax.dynamic_update_slice(
                    acc, jnp.where(below[None, :], pfull, row), (k0, 0))
                if step_fused:
                    acc = jax.lax.dynamic_update_slice(acc, diag, (k0, k0))
                pt = jnp.conj(jnp.swapaxes(panel, -1, -2))
                if use_mxu:
                    upd = (oz.herk_c128(pt, slices=tb._oz_slices())
                           if jnp.iscomplexobj(panel)
                           else oz.syrk_f64(pt, slices=tb._oz_slices()))
                else:
                    upd = pt @ jnp.conj(pt).T
                tri = rows[:, None] <= rows[None, :]
                acc = acc - jnp.where(tri, upd, 0)
            return acc, None

        return step

    def syrk_like(x):
        """Masked-panel self-product on the configured trailing route: the
        scan forms' one bulk product (x zeroed above its pivot)."""
        if use_mxu:
            return (oz.herk_c128(x, slices=tb._oz_slices())
                    if jnp.iscomplexobj(x)
                    else oz.syrk_f64(x, slices=tb._oz_slices()))
        return x @ jnp.conj(x).T

    def make_step_la(m):
        """Software-pipelined step body (``cholesky_lookahead=1``): the
        bulk trailing product of step k-1 is DEFERRED into body k, where
        it carries no dependency on body k's latency-bound potrf/trsm
        chain — XLA overlaps the two inside one iteration, which a
        sequential ``lax.scan`` body can never do across iterations. The
        next panel column's strip is updated eagerly (it is what frees
        the following body's panel chain), so per-cell application order
        — bulk(k-1) before strip(k) — matches the serial body exactly
        and results stay bitwise identical."""
        rows = jnp.arange(m)

        def step(carry, k):
            acc, pp = carry      # pp: previous step's masked panel
            k0 = k * nb
            blk = jax.lax.dynamic_slice(acc, (k0, k0), (nb, nb))
            ppan.count_step_kernel("fused" if step_fused else "xla")
            if use_mixed:
                ppan.count_panel_kernel("xla", "potrf")
                fac, fac_inv = mx.potrf_inv_refined(uplo, blk)
                diag = fac + tb.tri_mask(blk, other, k=-1)
            elif step_fused:
                # potrf deferred into the fused factor+solve kernel
                fac_inv = diag = None
            else:
                fac_inv = None
                diag = ppan.panel_potrf(uplo, blk, fused=panel_fused,
                                      interpret=panel_interpret)
            if diag is not None:
                acc = jax.lax.dynamic_update_slice(acc, diag, (k0, k0))
            below = rows >= k0 + nb
            tri = (rows[:, None] >= rows[None, :] if uplo == "L"
                   else rows[:, None] <= rows[None, :])
            valid1 = k0 + 2 * nb <= m    # next block col/row exists
            if uplo == "L":
                col = jax.lax.dynamic_slice(acc, (0, k0), (m, nb))
                if use_mixed:
                    ppan.count_panel_kernel("xla", "solve")
                    inv_t = jnp.conj(fac_inv).T
                    pfull = tb.mm_mxu(col, inv_t) if use_mxu else col @ inv_t
                elif step_fused:
                    diag, pfull = ppan.fused_factor_solve(
                        "L", blk, col, interpret=panel_interpret)
                elif panel_fused:
                    pfull = ppan.panel_solve("R", "L", "C", "N", diag, col,
                                           fused=True,
                                           interpret=panel_interpret)
                else:
                    ppan.count_panel_kernel("xla", "solve")
                    pfull = tb.trsm("R", "L", "C", "N", diag, col)
                panel = jnp.where(below[:, None], pfull, 0)
                acc = jax.lax.dynamic_update_slice(
                    acc, jnp.where(below[:, None], pfull, col), (0, k0))
                if step_fused:
                    acc = jax.lax.dynamic_update_slice(acc, diag, (k0, k0))
                # deferred bulk of step k-1: its next-col (block col k)
                # was applied in body k-1, the rest lands here
                pupd = syrk_like(pp)
                pmask = tri & (rows[None, :] >= k0 + nb)
                acc = acc - jnp.where(pmask, pupd, 0)
                # eager next-column strip from THIS panel
                nstrip = jax.lax.dynamic_slice(panel, (k0 + nb, 0),
                                               (nb, nb))
                updc = (_oz_product(panel, jnp.conj(nstrip).T) if use_mxu
                        else panel @ jnp.conj(nstrip).T)
                ccur = jax.lax.dynamic_slice(acc, (0, k0 + nb), (m, nb))
                cols1 = k0 + nb + jnp.arange(nb)
                cmask = (rows[:, None] >= cols1[None, :]) & valid1
                acc = jax.lax.dynamic_update_slice(
                    acc, ccur - jnp.where(cmask, updc, 0), (0, k0 + nb))
            else:
                row = jax.lax.dynamic_slice(acc, (k0, 0), (nb, m))
                if use_mixed:
                    ppan.count_panel_kernel("xla", "solve")
                    inv_t = jnp.conj(fac_inv).T
                    pfull = tb.mm_mxu(inv_t, row) if use_mxu else inv_t @ row
                elif step_fused:
                    diag, pfull = ppan.fused_factor_solve(
                        "U", blk, row, interpret=panel_interpret)
                elif panel_fused:
                    pfull = ppan.panel_solve("L", "U", "C", "N", diag, row,
                                           fused=True,
                                           interpret=panel_interpret)
                else:
                    ppan.count_panel_kernel("xla", "solve")
                    pfull = tb.trsm("L", "U", "C", "N", diag, row)
                panel = jnp.where(below[None, :], pfull, 0)
                acc = jax.lax.dynamic_update_slice(
                    acc, jnp.where(below[None, :], pfull, row), (k0, 0))
                if step_fused:
                    acc = jax.lax.dynamic_update_slice(acc, diag, (k0, k0))
                ppt = jnp.conj(jnp.swapaxes(pp, -1, -2))
                pupd = syrk_like(ppt)
                pmask = tri & (rows[:, None] >= k0 + nb)
                acc = acc - jnp.where(pmask, pupd, 0)
                pt = jnp.conj(jnp.swapaxes(panel, -1, -2))
                nstrip = jax.lax.dynamic_slice(pt, (k0 + nb, 0), (nb, nb))
                # nstrip = conj(panel_block)^T, so nstrip @ panel IS the
                # strip of conj(panel)^T @ panel (same dots as serial)
                updr = (_oz_product(nstrip, jnp.conj(pt).T) if use_mxu
                        else nstrip @ panel)
                rcur = jax.lax.dynamic_slice(acc, (k0 + nb, 0), (nb, m))
                rows1 = k0 + nb + jnp.arange(nb)
                rmask = (rows1[:, None] <= rows[None, :]) & valid1
                acc = jax.lax.dynamic_update_slice(
                    acc, rcur - jnp.where(rmask, updr, 0), (k0 + nb, 0))
            return (acc, panel), None

        return step

    # telescoped segments: each segment scans the SHRINKING trailing
    # submatrix (completed panel columns live outside it and are final),
    # so the uniform full-size masked work tracks the live trailing block
    # instead of the original matrix — premium drops from ~3x toward
    # ~1.7x at O(log nt) step programs instead of O(1) (still far below
    # the unrolled form's O(nt) on the ~19 s/step AOT toolchain).
    # Under lookahead the pending panel is carried ACROSS segments (the
    # dropped slots are zero — the panel is masked below its pivot), so
    # no flush products are ever paid; the last step's pending is
    # identically zero and simply dropped.
    off = 0
    pp = None
    for seg_len in telescope_segments(nt):
        m_seg = (nt - off) * nb
        sub = a[off * nb:, off * nb:]
        if lookahead:
            _count_step_modes("cholesky_scan", seg_len, 0)
            if pp is None:
                pp = (jnp.zeros((m_seg, nb), a.dtype) if uplo == "L"
                      else jnp.zeros((nb, m_seg), a.dtype))
            else:
                pp = pp[-m_seg:] if uplo == "L" else pp[:, -m_seg:]
            (sub, pp), _ = jax.lax.scan(make_step_la(m_seg), (sub, pp),
                                        jnp.arange(seg_len))
        else:
            _count_step_modes("cholesky_scan", 0, seg_len)
            sub, _ = jax.lax.scan(make_step(m_seg), sub,
                                  jnp.arange(seg_len))
        a = a.at[off * nb:, off * nb:].set(sub)
        off += seg_len
    out = a[:n, :n]
    return (out, hinfo.local_factor_info(out)) if with_info else out





# ---------------------------------------------------------------------------
# Distributed — reference impl.h:174-276
# ---------------------------------------------------------------------------

def _masked_oz_update(afl, bfl, pairmask, nrows, ncols, mb, interpret):
    """Exact-flop f64 trailing contraction: peel Ozaki slices of the
    flattened row/column operands (both contracting their last axis) and
    run the PREDICATED fused kernel — tile pairs outside ``pairmask`` skip
    their int8 MXU dots entirely (reference's herk-vs-gemm flop discipline,
    ``cholesky/impl.h:242-271``). Returns the (nrows, ncols, mb, mb) f64
    update, unmasked at element level (caller applies its triangle mask)."""
    from ..tile_ops.pallas_ozaki import masked_slice_product

    s = tb._oz_slices()
    sa = oz._scale(afl, axis=-1)
    sb = oz._scale(bfl, axis=-1)
    ia = jnp.stack(oz._peel_slices(oz._normalize(afl, sa), s))
    ib = jnp.stack(oz._peel_slices(oz._normalize(bfl, sb), s))
    hi, lo = masked_slice_product(
        ia.reshape(s, nrows, mb, mb), ib.reshape(s, ncols, mb, mb),
        pairmask.astype(jnp.int32), interpret=interpret,
        dot=oz._slice_dot_impl())
    acc = (hi.astype(jnp.float64) + lo.astype(jnp.float64)) * 4.0
    return (acc * sa.reshape(nrows, 1, mb, 1)) * sb.reshape(1, ncols, 1, mb)


def _build_dist_cholesky(dist, mesh, uplo, use_pallas, pallas_interpret,
                         use_mxu=False, use_mixed=False, cplx=False,
                         use_oz_pallas=False, lookahead=False,
                         comm_la=False, with_info=False, panel_fused=False,
                         step_fused=False):
    """Build the shard_map'd factorization program for one (dist, mesh, uplo).

    ``use_mxu`` routes the trailing tile-pair contraction through the
    error-free int8 MXU path (tile_ops.ozaki; ``cplx`` picks the complex128
    composition), following the ``f64_gemm="mxu"`` knob; ``use_mixed`` (f64
    AND complex128, following ``f64_trsm="mixed"``) factors/solves the panel
    with the half-precision-seed-plus-Newton helpers (tile_ops.mixed,
    Hermitian-correct) instead of emulated potrf/trsm. ``use_oz_pallas``
    (real f64, ``ozaki_impl="pallas"``) further predicates the mxu
    contraction per tile pair so masked-out pairs skip the MXU work —
    exact flops instead of rectangle-then-mask.

    The returned function maps tile storage -> tile storage. All index
    arithmetic below is trace-time (static per k); only data and the
    rank-dependent validity masks are traced values.

    uplo='U' is the mirrored sweep (reference ``call_U``): the panel is the
    block *row* ``k`` (``trsm('L','U','C','N')`` per tile), broadcast along
    the column axis, all-gathered along the row axis to index the transposed
    panel by local trailing rows, and the trailing update
    ``A[i,j] -= U[k,i]^H U[k,j]`` touches the upper-triangle tile pairs.

    Each step is three phases — ``panel_chain`` (fused diag ``bcast2d`` +
    potrf + panel trsm + panel broadcast + transposed-panel all_gather),
    ``step_pre`` (diag/panel writes + the lookahead next-column strip) and
    ``step_bulk`` (the bulk trailing product) — so ``comm_la``
    (``comm_lookahead=1``, docs/comm_overlap.md) can emit step k+1's
    ENTIRE panel chain, collectives included, BEFORE step k's bulk
    product: the chain reads only the carried post-strip column values,
    never ``lt`` after the bulk scatter, which is exactly the dependency
    shape that lets XLA run the ICI transfer concurrently with the bulk
    MXU gemms (the reference hides the same transfer behind the trailing
    update, ``broadcast_panel.h`` + ``impl.h:147-156``). Phase order of
    ``lt`` mutations is identical in both modes, so results are bitwise
    the same with the knob on or off.
    """
    nt = dist.nr_tiles.row
    mb = dist.block_size.row
    n = dist.size.row
    Pr, Qc = dist.grid_size.row, dist.grid_size.col
    sr, sc = dist.source_rank.row, dist.source_rank.col
    _, _, ltr, ltc = storage_tile_grid(dist)

    def local_rows_global(lu, rr, count):
        """Global tile rows of local row slots lu..lu+count-1 (traced rr)."""
        return (lu + jnp.arange(count)) * Pr + rr

    def local_cols_global(lu, rc, count):
        return (lu + jnp.arange(count)) * Qc + rc

    def _indices(k):
        """Trace-time per-step index bundle (owners, pivot slots, uniform
        trailing slot starts)."""
        owner_r = ud.rank_global_tile(k, Pr, sr)
        owner_c = ud.rank_global_tile(k, Qc, sc)
        kr = ud.local_tile_from_global_tile(k, Pr)
        kc = ud.local_tile_from_global_tile(k, Qc)
        lu_r = max(0, -(-(k + 2 - Pr) // Pr))
        lu_c = max(0, -(-(k + 2 - Qc) // Qc))
        return owner_r, owner_c, kr, kc, lu_r, lu_c

    def panel_chain(lt, k, la):
        """Panel chain of step k: fused diag broadcast (one collective,
        :func:`cc.bcast2d`) + potrf + panel trsm + panel broadcast +
        transposed-panel all_gather (reference impl.h:215-231 +
        broadcast_panel.h:101-193). With the lookahead carry
        ``la = (tiles, lu)`` (step k-1's post-strip column/row values) the
        chain reads NO ``lt`` value at all — it is independent of step
        k-1's bulk trailing product, which is what allows ``comm_la`` to
        emit it (collectives included) ahead of that product. The carried
        tiles are trusted only under the owner masks, exactly like the
        PR-2 carry. Returns ``(lkk, pan, vbcast, vtrans)``; ``pan`` is
        None past the last trailing step, ``vtrans`` None when no rank
        has trailing columns (rows for uplo='U')."""
        rr = (cc.this_rank(ROW_AXIS) - sr) % Pr
        rc = (cc.this_rank(COL_AXIS) - sc) % Qc
        owner_r, owner_c, kr, kc, lu_r, lu_c = _indices(k)

        # -- diag tile -> everyone (reference: col bcast impl.h:215-219);
        # uplo='U' carries a block ROW, indexed by column slots
        cand = lt[kr, kc] if la is None \
            else la[0][(kr if uplo == "L" else kc) - la[1]]
        diag = cc.bcast2d(cand, owner_r, owner_c)
        ts = min(mb, n - k * mb)
        if ts < mb:  # pad short edge tile with identity to keep potrf defined
            pad = (jnp.arange(mb) >= ts)
            diag = jnp.where(pad[:, None] | pad[None, :], 0, diag) \
                + jnp.diag(pad.astype(diag.dtype))
        # redundant tiny compute on every rank; mixed mode swaps the
        # latency-bound emulated-f64 potrf for the f32-seed + Newton form
        # (fused with the explicit inverse the panel solve consumes, so
        # each step pays one f32 cholesky + ONE f32 solve, not two)
        lkk_inv = None
        # step_impl route, distributed form: potrf + whole-strip solve as
        # ONE fused pallas_call (the trailing slab stays outside — it
        # needs the POST-collective transposed panel, so only the 2-op
        # chain can fuse here). Deferred past the early-outs: the final
        # step and strip-less shards keep the plain potrf.
        fuse_step = step_fused and not use_mixed and k < nt - 1 and (
            (ltr - lu_r) if uplo == "L" else (ltc - lu_c)) > 0
        if use_mixed:
            ppan.count_panel_kernel("xla", "potrf")
            other = "U" if uplo == "L" else "L"
            fac, lkk_inv = mx.potrf_inv_refined(uplo, diag)
            lkk = fac + tb.tri_mask(diag, other, k=-1)
        elif fuse_step:
            lkk = None   # factored inside the fused kernel below
        else:
            # panel_impl route (docs/pallas_panel.md): fused VMEM potrf
            # kernel or XLA's blocked-cholesky thunk chain
            lkk = ppan.panel_potrf(uplo, diag, fused=panel_fused,
                                 interpret=pallas_interpret)
        if k == nt - 1:
            return lkk, None, None, None

        if uplo == "L":
            nrows = ltr - lu_r
            if nrows == 0:
                return lkk, None, None, None
            g_rows = local_rows_global(lu_r, rr, nrows)
            row_valid = (g_rows > k) & (g_rows < nt)
            ppan.count_step_kernel("fused" if fuse_step else "xla")
            # trsm_panel: native batched solve, or (f64_trsm="mixed")
            # refined inverse + matmul that follows the f64_gemm routing
            # (inverse precomputed by the fused potrf step); the panel
            # source is the carried next-column when pipelined (non-owner
            # ranks' carried tiles are stale pre-bulk values, but every
            # use of `pan` is gated by the owner-column keep/bcast masks)
            colsrc = lt[lu_r:, kc] if la is None else la[0][lu_r - la[1]:]
            if fuse_step:
                lkk, pan = ppan.fused_factor_solve(
                    "L", diag, colsrc, interpret=pallas_interpret)
            else:
                pan = ppan.panel_solve("R", "L", "C", "N", lkk, colsrc,
                                     fused=panel_fused,
                                     interpret=pallas_interpret,
                                     inv_a=lkk_inv)
            pan = jnp.where(row_valid[:, None, None], pan,
                            jnp.zeros_like(pan))
            # -- panel broadcast (reference broadcast_panel.h:101-193) ---
            # row-wise: every rank gets the panel tiles for its local rows
            vr = cc.bcast(pan, COL_AXIS, owner_c)
            ncols = ltc - lu_c
            if ncols == 0:
                return lkk, pan, vr, None
            g_cols = local_cols_global(lu_c, rc, ncols)
            col_valid = (g_cols > k) & (g_cols < nt)
            # transposed panel: all_gather along 'row' -> all panel tiles,
            # then gather the tiles matching my local trailing columns
            vc = transpose_col_to_rows(DistContext(dist), vr, lu_r, g_cols)
            vc = jnp.where(col_valid[:, None, None], vc, jnp.zeros_like(vc))
            return lkk, pan, vr, vc

        # uplo='U': panel is the block row k (reference ``call_U``)
        ncols = ltc - lu_c
        if ncols == 0:
            return lkk, None, None, None
        g_cols = local_cols_global(lu_c, rc, ncols)
        col_valid = (g_cols > k) & (g_cols < nt)
        ppan.count_step_kernel("fused" if fuse_step else "xla")
        rowsrc = lt[kr, lu_c:] if la is None else la[0][lu_c - la[1]:]
        if fuse_step:
            lkk, pan = ppan.fused_factor_solve(
                "U", diag, rowsrc, interpret=pallas_interpret)
        else:
            pan = ppan.panel_solve("L", "U", "C", "N", lkk, rowsrc,
                                 fused=panel_fused,
                                 interpret=pallas_interpret,
                                 inv_a=lkk_inv)
        pan = jnp.where(col_valid[:, None, None], pan, jnp.zeros_like(pan))
        # col-wise down the mesh, then all_gather along the column axis
        # to index the transposed panel by local rows
        vcp = cc.bcast(pan, ROW_AXIS, owner_r)
        nrows = ltr - lu_r
        if nrows == 0:
            return lkk, pan, vcp, None
        g_rows = local_rows_global(lu_r, rr, nrows)
        row_valid = (g_rows > k) & (g_rows < nt)
        vrp = transpose_row_to_cols(DistContext(dist), vcp, lu_c, g_rows)
        vrp = jnp.where(row_valid[:, None, None], vrp, jnp.zeros_like(vrp))
        return lkk, pan, vcp, vrp

    def step_pre(lt, k, ch):
        """Write step k's factored diag + panel and apply the lookahead
        next-column (next-row for 'U') strip; returns ``(lt, la_next)``
        with ``la_next = (post-strip tiles, lu)`` — the SSA carry feeding
        both step k+1's panel chain and its strip indexing."""
        lkk, pan, vb, vt = ch
        rr = (cc.this_rank(ROW_AXIS) - sr) % Pr
        rc = (cc.this_rank(COL_AXIS) - sc) % Qc
        owner_r, owner_c, kr, kc, lu_r, lu_c = _indices(k)
        is_owner_r = cc.this_rank(ROW_AXIS) == owner_r
        is_owner_c = cc.this_rank(COL_AXIS) == owner_c

        # owner writes the factored diagonal back
        upd_tile = jnp.where(is_owner_r & is_owner_c, lkk, lt[kr, kc])
        lt = lt.at[kr, kc].set(upd_tile)
        if pan is None:
            return lt, None

        if uplo == "L":
            nrows = ltr - lu_r
            g_rows = local_rows_global(lu_r, rr, nrows)
            row_valid = (g_rows > k) & (g_rows < nt)
            # owner column keeps the factored panel (others their tiles)
            keep = (is_owner_c & row_valid)[:, None, None]
            lt = lt.at[lu_r:, kc].set(jnp.where(keep, pan, lt[lu_r:, kc]))
            if vt is None or not (lookahead and k + 1 < nt):
                return lt, None
            # -- next panel column first (reference's high-priority
            # first-column herk, impl.h:147-156): one tile-column einsum
            # against MY kc1-slot transposed-panel tile (exactly the tile
            # the bulk product would have used — bitwise-identical dots),
            # emitted before the bulk and carried to step k+1
            vr, vc = vb, vt
            kc1 = ud.local_tile_from_global_tile(k + 1, Qc)
            owner_c1 = ud.rank_global_tile(k + 1, Qc, sc)
            pk1 = vc[kc1 - lu_c]
            own_c1 = cc.this_rank(COL_AXIS) == owner_c1
            below1 = row_valid & (g_rows > k + 1)
            ondiag1 = row_valid & (g_rows == k + 1)
            if use_mxu:
                mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
                updc = mmfn(vr.reshape(nrows * mb, mb), jnp.conj(pk1).T,
                            slices=tb._oz_slices()).reshape(nrows, mb, mb)
            else:
                updc = jnp.einsum("rab,db->rad", vr, jnp.conj(pk1),
                                  preferred_element_type=vr.dtype)
            tril1 = jnp.tril(jnp.ones((mb, mb), dtype=bool))
            m3 = (below1[:, None, None] | (ondiag1[:, None, None] & tril1)) \
                & own_c1
            new_col = lt[lu_r:, kc1] - jnp.where(m3, updc,
                                                 jnp.zeros_like(updc))
            lt = lt.at[lu_r:, kc1].set(new_col)
            return lt, (new_col, lu_r)

        # uplo='U'
        ncols = ltc - lu_c
        g_cols = local_cols_global(lu_c, rc, ncols)
        col_valid = (g_cols > k) & (g_cols < nt)
        keep = (is_owner_r & col_valid)[:, None, None]
        lt = lt.at[kr, lu_c:].set(jnp.where(keep, pan, lt[kr, lu_c:]))
        if vt is None or not (lookahead and k + 1 < nt):
            return lt, None
        # next block row first (mirrored split): my kr1-slot
        # transposed-panel tile, carried to step k+1
        vc, vr = vb, vt
        kr1 = ud.local_tile_from_global_tile(k + 1, Pr)
        owner_r1 = ud.rank_global_tile(k + 1, Pr, sr)
        pk1 = vr[kr1 - lu_r]
        own_r1 = cc.this_rank(ROW_AXIS) == owner_r1
        above1 = col_valid & (g_cols > k + 1)
        ondiag1 = col_valid & (g_cols == k + 1)
        if use_mxu:
            mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
            updr = mmfn(jnp.swapaxes(jnp.conj(pk1), -1, -2),
                        jnp.swapaxes(vc, -1, -2).reshape(
                            ncols * mb, mb).T,
                        slices=tb._oz_slices()).reshape(
                            mb, ncols, mb).transpose(1, 0, 2)
        else:
            updr = jnp.einsum("ba,cbd->cad", jnp.conj(pk1), vc,
                              preferred_element_type=vc.dtype)
        triu1 = jnp.triu(jnp.ones((mb, mb), dtype=bool))
        m3 = (above1[:, None, None] | (ondiag1[:, None, None] & triu1)) \
            & own_r1
        new_row = lt[kr1, lu_c:] - jnp.where(m3, updr,
                                             jnp.zeros_like(updr))
        lt = lt.at[kr1, lu_c:].set(new_row)
        return lt, (new_row, lu_c)

    def step_bulk(lt, k, ch, stripped):
        """Bulk trailing product of step k (reference impl.h:242-271);
        ``stripped`` excludes the eagerly-updated next column/row."""
        lkk, pan, vb, vt = ch
        if pan is None or vt is None:
            return lt
        rr = (cc.this_rank(ROW_AXIS) - sr) % Pr
        rc = (cc.this_rank(COL_AXIS) - sc) % Qc
        _, _, _, _, lu_r, lu_c = _indices(k)
        nrows, ncols = ltr - lu_r, ltc - lu_c
        g_rows = local_rows_global(lu_r, rr, nrows)
        g_cols = local_cols_global(lu_c, rc, ncols)
        row_valid = (g_rows > k) & (g_rows < nt)
        col_valid = (g_cols > k) & (g_cols < nt)
        pair = row_valid[:, None] & col_valid[None, :]

        if uplo == "L":
            # A[i,j] -= L[i,k] L[j,k]^H for trailing lower-triangle tiles:
            # strictly-lower tiles full update, diagonal tiles lower
            # triangle only (the matrix's upper triangle passes through
            # untouched, like the reference's herk vs gemm split)
            vr, vc = vb, vt
            below = pair & (g_rows[:, None] > g_cols[None, :])
            ondiag = pair & (g_rows[:, None] == g_cols[None, :])
            if stripped:
                # the bulk excludes column k+1 (already applied)
                notnext = g_cols != k + 1
                below = below & notnext[None, :]
                ondiag = ondiag & notnext[None, :]
            if use_pallas:
                # predicated Pallas kernel: masked-out tile pairs skip the
                # MXU work entirely (exact flops, not rectangle-then-mask)
                mode = below.astype(jnp.int32) + 2 * ondiag.astype(jnp.int32)
                new_block = masked_trailing_update(lt[lu_r:, lu_c:], vr, vc,
                                                   mode,
                                                   interpret=pallas_interpret)
                return lt.at[lu_r:, lu_c:].set(new_block)
            if use_mxu and use_oz_pallas:
                # predicated fused kernel: dead tile pairs skip the MXU work
                upd = _masked_oz_update(
                    vr.reshape(nrows * mb, mb),
                    jnp.conj(vc).reshape(ncols * mb, mb),
                    below | ondiag, nrows, ncols, mb, pallas_interpret)
            elif use_mxu:
                # same contraction through int8 MXU passes: flatten the tile
                # batch into one (nrows*mb) x mb by (ncols*mb) x mb product
                mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
                full = mmfn(vr.reshape(nrows * mb, mb),
                            jnp.conj(vc).reshape(ncols * mb, mb).T,
                            slices=tb._oz_slices())
                upd = full.reshape(nrows, mb, ncols, mb).transpose(0, 2, 1, 3)
            else:
                upd = jnp.einsum("rab,cdb->rcad", vr, jnp.conj(vc),
                                 preferred_element_type=vr.dtype)
            tril_m = jnp.tril(jnp.ones((mb, mb), dtype=bool))
            mask4 = below[:, :, None, None] \
                | (ondiag[:, :, None, None] & tril_m)
            upd = jnp.where(mask4, upd, jnp.zeros_like(upd))
            return lt.at[lu_r:, lu_c:].add(-upd)

        # uplo='U': A[i,j] -= U[k,i]^H U[k,j], upper triangle
        vc, vr = vb, vt
        above = pair & (g_rows[:, None] < g_cols[None, :])
        ondiag = pair & (g_rows[:, None] == g_cols[None, :])
        if stripped:
            notnext = g_rows != k + 1
            above = above & notnext[:, None]
            ondiag = ondiag & notnext[:, None]
        if use_pallas:
            # transposed tiles keep the kernel's vr @ vc^T contraction;
            # mode 3 = within-tile upper triangle on diagonal tiles
            mode = above.astype(jnp.int32) + 3 * ondiag.astype(jnp.int32)
            new_block = masked_trailing_update(
                lt[lu_r:, lu_c:], jnp.swapaxes(vr, -1, -2),
                jnp.swapaxes(vc, -1, -2), mode, interpret=pallas_interpret)
            return lt.at[lu_r:, lu_c:].set(new_block)
        if use_mxu and use_oz_pallas:
            ar = jnp.swapaxes(jnp.conj(vr), -1, -2).reshape(nrows * mb, mb)
            bc = jnp.swapaxes(vc, -1, -2).reshape(ncols * mb, mb)
            upd = _masked_oz_update(ar, bc, above | ondiag,
                                    nrows, ncols, mb, pallas_interpret)
        elif use_mxu:
            mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
            ar = jnp.swapaxes(jnp.conj(vr), -1, -2).reshape(nrows * mb, mb)
            bc = jnp.swapaxes(vc, -1, -2).reshape(ncols * mb, mb)
            full = mmfn(ar, bc.T, slices=tb._oz_slices())
            upd = full.reshape(nrows, mb, ncols, mb).transpose(0, 2, 1, 3)
        else:
            upd = jnp.einsum("rba,cbd->rcad", jnp.conj(vr), vc,
                             preferred_element_type=vr.dtype)
        triu_m = jnp.triu(jnp.ones((mb, mb), dtype=bool))
        mask4 = above[:, :, None, None] | (ondiag[:, :, None, None] & triu_m)
        upd = jnp.where(mask4, upd, jnp.zeros_like(upd))
        return lt.at[lu_r:, lu_c:].add(-upd)

    def chain_comm_counts(k):
        """Collectives ``panel_chain(k)`` emits per mesh axis (trace-time
        statics mirroring the chain's early-exit structure): the fused
        diag bcast2d counts once on each axis; a full chain adds the
        panel broadcast on one axis and the transposed-panel all_gather
        on the other."""
        _, _, _, _, lu_r, lu_c = _indices(k)
        nrows, ncols = ltr - lu_r, ltc - lu_c
        row = col = 1
        if k < nt - 1:
            if uplo == "L" and nrows > 0:
                col += 1                      # panel bcast along 'col'
                if ncols > 0:
                    row += 1                  # transpose all_gather
            elif uplo == "U" and ncols > 0:
                row += 1
                if nrows > 0:
                    col += 1
        return row, col

    def factorize(lt):
        la = None
        ch_next = None
        for k in range(nt):
            # uniform per-step phase scopes (`cholesky.step<k>.<phase>`,
            # docs/observability.md critical-path attribution): the names
            # land on the compiled program's op metadata, so the critpath
            # joiner can put every device interval on its (step, phase).
            # Names carry no repeat index — identical across runs, so
            # histograms never fork. Counters are all trace-time.
            with obs.named_span(f"cholesky.step{k:03d}"):
                if obs.metrics_active():
                    obs.counter("dlaf_algo_tile_ops_total",
                                algo="cholesky_dist", op="potrf").inc()
                    obs.counter("dlaf_algo_tile_ops_total",
                                algo="cholesky_dist", op="trailing_pairs"
                                ).inc((ltr - max(0, -(-(k + 2 - Pr) // Pr)))
                                      * (ltc - max(0, -(-(k + 2 - Qc) // Qc))))
                    _count_step_modes(
                        "cholesky_dist",
                        *((1, 0) if lookahead and k + 1 < nt else (0, 1)))
                if comm_la:
                    # comm look-ahead (docs/comm_overlap.md): step k+1's
                    # panel chain — its bcast2d/bcast/all_gather included
                    # — is emitted between step k's strip and step k's
                    # bulk product, reading only the carried strip values.
                    # The hoisted chain is scoped as step k+1's PANEL even
                    # though it executes inside step k's window — that is
                    # the overlap the critpath report must see.
                    if ch_next is not None:
                        ch = ch_next
                    else:
                        with obs.named_span(f"cholesky.step{k:03d}.panel"):
                            ch = panel_chain(lt, k, la)
                    with obs.named_span(f"cholesky.step{k:03d}.strip"):
                        lt, la = step_pre(lt, k, ch)
                    ch_next = None
                    if k + 1 < nt and la is not None:
                        with obs.named_span(
                                f"cholesky.step{k + 1:03d}.panel"):
                            ch_next = panel_chain(None, k + 1, la)
                        n_row, n_col = chain_comm_counts(k + 1)
                        cc.record_overlapped("cholesky_dist", ROW_AXIS,
                                             n_row)
                        cc.record_overlapped("cholesky_dist", COL_AXIS,
                                             n_col)
                    with obs.named_span(f"cholesky.step{k:03d}.bulk"):
                        lt = step_bulk(lt, k, ch, la is not None)
                else:
                    with obs.named_span(f"cholesky.step{k:03d}.panel"):
                        ch = panel_chain(lt, k, la)
                    with obs.named_span(f"cholesky.step{k:03d}.strip"):
                        lt, la = step_pre(lt, k, ch)
                    with obs.named_span(f"cholesky.step{k:03d}.bulk"):
                        lt = step_bulk(lt, k, ch, la is not None)
        if with_info:
            return lt, _dist_factor_info(lt, dist)
        return lt

    return shard_map(factorize, mesh=mesh, in_specs=P(ROW_AXIS, COL_AXIS),
                     out_specs=(P(ROW_AXIS, COL_AXIS), P()) if with_info
                     else P(ROW_AXIS, COL_AXIS), check_vma=False)


def _dist_factor_info(lt, dist):
    """In-graph distributed info (called INSIDE the factorization's
    shard_map, after the last step): each rank scans the diagonals of the
    diagonal tiles it OWNS (health.info owner masks) and the per-rank
    bad-column vectors merge via an all-reduce max over both mesh axes —
    disjoint owner masks make max an OR. Pure extra outputs; the factor
    subgraph is untouched, and nothing here syncs with the host."""
    Pr, Qc = dist.grid_size.row, dist.grid_size.col
    sr, sc = dist.source_rank.row, dist.source_rank.col
    n = dist.size.row
    if n == 0:
        return jnp.zeros((), jnp.int32)
    rr = (cc.this_rank(ROW_AXIS) - sr) % Pr
    rc = (cc.this_rank(COL_AXIS) - sc) % Qc
    vec = hinfo.dist_diag_bad(lt, rr, rc, Pr=Pr, Qc=Qc,
                              nt=dist.nr_tiles.row,
                              mb=dist.block_size.row, n=n)
    vec = cc.all_reduce(vec, ROW_AXIS, "max")
    vec = cc.all_reduce(vec, COL_AXIS, "max")
    return hinfo.first_bad_info(vec > 0)


def _build_dist_cholesky_scan(dist, mesh, uplo, use_mxu=False,
                              use_mixed=False, cplx=False,
                              use_oz_pallas=False, pallas_interpret=False,
                              lookahead=False, with_info=False,
                              panel_fused=False, step_fused=False):
    """``lax.scan`` form of the distributed factorization: ONE compiled
    step body looped ``nt`` times inside the ``shard_map``.

    Same motivation as :func:`_cholesky_local_scan` (the hardware
    toolchain's ~19 s/step unrolled-compile constant — docs/DESIGN.md —
    puts north-star tile counts at tens of minutes cold), same uniform-
    shape price: every step solves the panel over ALL local row slots and
    updates the ALL-pairs trailing grid under traced validity masks
    (~2x panel work, ~3x trailing flops vs the unrolled exact schedule).
    All per-``k`` index math — owner ranks, local slot of the pivot,
    global tile indices, edge-tile extents — is traced arithmetic on the
    scan counter; tile reads/writes at the pivot use dynamic slices.
    ``use_oz_pallas`` recovers EXACT trailing flops inside the scan: the
    predicated per-tile-pair kernel takes its mode mask as data, so the
    traced per-step masks predicate the MXU work directly.
    """
    nt = dist.nr_tiles.row
    mb = dist.block_size.row
    n = dist.size.row
    Pr, Qc = dist.grid_size.row, dist.grid_size.col
    _, _, ltr, ltc = storage_tile_grid(dist)

    def make_step(lu_r0, lu_c0, ltr_s, ltc_s):
        """Step body over the sliced local grid ``lt[lu_r0:, lu_c0:]`` — the
        telescoped segment's trailing view. For every k in the segment the
        pivot's local slot satisfies ``kr >= lu_r0`` (kr = k // P and the
        segment starts at ``k_start`` with ``lu_r0 = k_start // P``), so
        slot indices shift by the static offsets and validity masks do the
        rest."""

        def step(lt, k):
            # block-cyclic index math through DistContext (shared with
            # the scan solve in triangular.py — single owner)
            ctx = DistContext(dist)
            owner_r, owner_c = ctx.owner_r(k), ctx.owner_c(k)
            kr = ctx.kr(k) - lu_r0
            kc = ctx.kc(k) - lu_c0
            is_owner_r = ctx.rank_r == owner_r
            is_owner_c = ctx.rank_c == owner_c

            # -- diag tile -> everyone (one fused 2D collective) --------
            cand = jax.lax.dynamic_slice(lt, (kr, kc, 0, 0),
                                         (1, 1, mb, mb))[0, 0]
            diag = cc.bcast2d(cand, owner_r, owner_c)
            ts = jnp.minimum(mb, n - k * mb)
            pad = jnp.arange(mb) >= ts   # short-edge mask
            diag = pad_diag_identity_dyn(diag, ts)
            # step_impl route, scan form: potrf deferred into the fused
            # factor+solve kernel at the panel-solve site (the diag
            # write-back then trails the column/row write)
            fuse_step = step_fused and not use_mixed
            ppan.count_step_kernel("fused" if fuse_step else "xla")
            if use_mixed:
                ppan.count_panel_kernel("xla", "potrf")
                other = "U" if uplo == "L" else "L"
                fac, lkk_inv = mx.potrf_inv_refined(uplo, diag)
                lkk = fac + tb.tri_mask(diag, other, k=-1)
            elif fuse_step:
                lkk_inv = lkk = None
            else:
                lkk_inv = None
                lkk = ppan.panel_potrf(uplo, diag, fused=panel_fused,
                                     interpret=pallas_interpret)

            def write_diag(lt, lkk, fallback=None):
                # un-pad: the written diagonal tile keeps stored edge
                # zeros. ``fallback`` is the non-owner tile value —
                # ``cand`` before the column/row write, the CURRENT tile
                # after it (the write-back may have put a solved panel
                # tile into the pivot slot on owner-column ranks that
                # are not the pivot-row owner)
                lkk_w = jnp.where(pad[:, None] | pad[None, :], cand, lkk)
                upd_tile = jnp.where(is_owner_r & is_owner_c, lkk_w,
                                     cand if fallback is None else fallback)
                return jax.lax.dynamic_update_slice(
                    lt, upd_tile[None, None], (kr, kc, 0, 0))

            def pivot_tile(lt):
                return jax.lax.dynamic_slice(
                    lt, (kr, kc, 0, 0), (1, 1, mb, mb))[0, 0]

            if lkk is not None:
                lt = write_diag(lt, lkk)

            g_rows = ctx.g_rows(lu_r0, ltr_s)
            g_cols = ctx.g_cols(lu_c0, ltc_s)
            row_valid = (g_rows > k) & (g_rows < nt)
            col_valid = (g_cols > k) & (g_cols < nt)

            if uplo == "L":
                # -- panel trsm over the segment's local row slots -------
                colk = jax.lax.dynamic_slice(
                    lt, (0, kc, 0, 0), (ltr_s, 1, mb, mb))[:, 0]
                if fuse_step:
                    lkk, pan = ppan.fused_factor_solve(
                        "L", diag, colk, interpret=pallas_interpret)
                else:
                    pan = ppan.panel_solve("R", "L", "C", "N", lkk, colk,
                                         fused=panel_fused,
                                         interpret=pallas_interpret,
                                         inv_a=lkk_inv)
                pan = jnp.where(row_valid[:, None, None], pan, 0)
                keep = (is_owner_c & row_valid)[:, None, None]
                lt = jax.lax.dynamic_update_slice(
                    lt, jnp.where(keep, pan, colk)[:, None], (0, kc, 0, 0))
                if fuse_step:
                    # colk predates the factor; fix the pivot tile now
                    lt = write_diag(lt, lkk, fallback=pivot_tile(lt))

                # -- panel broadcast + transposed panel ------------------
                vr = cc.bcast(pan, COL_AXIS, owner_c)
                vc = transpose_col_to_rows(DistContext(dist), vr, lu_r0,
                                           g_cols)
                vc = jnp.where(col_valid[:, None, None], vc, 0)

                # -- trailing update over the segment's pair grid --------
                pair = row_valid[:, None] & col_valid[None, :]
                below = pair & (g_rows[:, None] > g_cols[None, :])
                ondiag = pair & (g_rows[:, None] == g_cols[None, :])
                if use_mxu and use_oz_pallas:
                    upd = _masked_oz_update(
                        vr.reshape(ltr_s * mb, mb),
                        jnp.conj(vc).reshape(ltc_s * mb, mb),
                        below | ondiag, ltr_s, ltc_s, mb, pallas_interpret)
                elif use_mxu:
                    mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
                    full = mmfn(vr.reshape(ltr_s * mb, mb),
                                jnp.conj(vc).reshape(ltc_s * mb, mb).T,
                                slices=tb._oz_slices())
                    upd = full.reshape(ltr_s, mb, ltc_s,
                                       mb).transpose(0, 2, 1, 3)
                else:
                    upd = jnp.einsum("rab,cdb->rcad", vr, jnp.conj(vc),
                                     preferred_element_type=vr.dtype)
                tri_m = jnp.tril(jnp.ones((mb, mb), dtype=bool))
            else:
                # -- mirrored sweep: panel is block row kr ---------------
                rowk = jax.lax.dynamic_slice(
                    lt, (kr, 0, 0, 0), (1, ltc_s, mb, mb))[0]
                if fuse_step:
                    lkk, pan = ppan.fused_factor_solve(
                        "U", diag, rowk, interpret=pallas_interpret)
                else:
                    pan = ppan.panel_solve("L", "U", "C", "N", lkk, rowk,
                                         fused=panel_fused,
                                         interpret=pallas_interpret,
                                         inv_a=lkk_inv)
                pan = jnp.where(col_valid[:, None, None], pan, 0)
                keep = (is_owner_r & col_valid)[:, None, None]
                lt = jax.lax.dynamic_update_slice(
                    lt, jnp.where(keep, pan, rowk)[None], (kr, 0, 0, 0))
                if fuse_step:
                    lt = write_diag(lt, lkk, fallback=pivot_tile(lt))

                vcp = cc.bcast(pan, ROW_AXIS, owner_r)
                vrp = transpose_row_to_cols(DistContext(dist), vcp, lu_c0,
                                            g_rows)
                vrp = jnp.where(row_valid[:, None, None], vrp, 0)

                pair = row_valid[:, None] & col_valid[None, :]
                below = pair & (g_rows[:, None] < g_cols[None, :])
                ondiag = pair & (g_rows[:, None] == g_cols[None, :])
                if use_mxu and use_oz_pallas:
                    ar = jnp.swapaxes(jnp.conj(vrp),
                                      -1, -2).reshape(ltr_s * mb, mb)
                    bc2 = jnp.swapaxes(vcp, -1, -2).reshape(ltc_s * mb, mb)
                    upd = _masked_oz_update(ar, bc2, below | ondiag,
                                            ltr_s, ltc_s, mb,
                                            pallas_interpret)
                elif use_mxu:
                    mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
                    ar = jnp.swapaxes(jnp.conj(vrp),
                                      -1, -2).reshape(ltr_s * mb, mb)
                    bc2 = jnp.swapaxes(vcp, -1, -2).reshape(ltc_s * mb, mb)
                    full = mmfn(ar, bc2.T, slices=tb._oz_slices())
                    upd = full.reshape(ltr_s, mb, ltc_s,
                                       mb).transpose(0, 2, 1, 3)
                else:
                    upd = jnp.einsum("rba,cbd->rcad", jnp.conj(vrp), vcp,
                                     preferred_element_type=vrp.dtype)
                tri_m = jnp.triu(jnp.ones((mb, mb), dtype=bool))

            mask4 = below[:, :, None, None] \
                | (ondiag[:, :, None, None] & tri_m)
            lt = lt - jnp.where(mask4, upd, 0)
            return lt, None

        return step

    def _pair_upd(xr, xc):
        """All-pairs tile product over (row tiles, transposed-col tiles) on
        the configured trailing route — shared by the serial body's eager
        update and the pipelined body's deferred one."""
        ltr_s, ltc_s = xr.shape[0], xc.shape[0]
        if use_mxu:
            mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
            full = mmfn(xr.reshape(ltr_s * mb, mb),
                        jnp.conj(xc).reshape(ltc_s * mb, mb).T,
                        slices=tb._oz_slices())
            return full.reshape(ltr_s, mb, ltc_s, mb).transpose(0, 2, 1, 3)
        return jnp.einsum("rab,cdb->rcad", xr, jnp.conj(xc),
                          preferred_element_type=xr.dtype)

    def make_step_la(lu_r0, lu_c0, ltr_s, ltc_s):
        """Software-pipelined step body (``cholesky_lookahead=1``): carry
        ``(lt, prev_vr, prev_vc)`` — step k-1's masked panel broadcast +
        transposed panel — and apply its BULK trailing product inside body
        k, where it is independent of body k's latency-bound potrf/trsm
        chain (a sequential scan body can only overlap work within one
        iteration). The next panel column's tile strip is updated eagerly
        so body k+1's pivot column is current; per-cell application order
        matches the serial body (bulk k-1 before strip k), keeping
        results bitwise identical on the native routes."""

        def step(carry, k):
            lt, pvr, pvc = carry
            ctx = DistContext(dist)
            owner_r, owner_c = ctx.owner_r(k), ctx.owner_c(k)
            kr = ctx.kr(k) - lu_r0
            kc = ctx.kc(k) - lu_c0
            is_owner_r = ctx.rank_r == owner_r
            is_owner_c = ctx.rank_c == owner_c

            # -- diag tile -> everyone (one fused 2D collective; pivot
            # column is current: it took the k-1 strip eagerly and the
            # k-2 bulk in body k-1). Emitted — like this body's panel
            # bcast/all_gather below — BEFORE the deferred bulk of step
            # k-1, so the scan form's collectives overlap the bulk MXU
            # product by construction (docs/comm_overlap.md).
            cand = jax.lax.dynamic_slice(lt, (kr, kc, 0, 0),
                                         (1, 1, mb, mb))[0, 0]
            diag = cc.bcast2d(cand, owner_r, owner_c)
            ts = jnp.minimum(mb, n - k * mb)
            pad = jnp.arange(mb) >= ts
            diag = pad_diag_identity_dyn(diag, ts)
            # step_impl route: potrf fused with the strip solve below
            fuse_step = step_fused and not use_mixed
            ppan.count_step_kernel("fused" if fuse_step else "xla")
            if use_mixed:
                ppan.count_panel_kernel("xla", "potrf")
                other = "U" if uplo == "L" else "L"
                fac, lkk_inv = mx.potrf_inv_refined(uplo, diag)
                lkk = fac + tb.tri_mask(diag, other, k=-1)
            elif fuse_step:
                lkk_inv = lkk = None
            else:
                lkk_inv = None
                lkk = ppan.panel_potrf(uplo, diag, fused=panel_fused,
                                     interpret=pallas_interpret)

            def write_diag(lt, lkk, fallback=None):
                lkk_w = jnp.where(pad[:, None] | pad[None, :], cand, lkk)
                upd_tile = jnp.where(is_owner_r & is_owner_c, lkk_w,
                                     cand if fallback is None else fallback)
                return jax.lax.dynamic_update_slice(
                    lt, upd_tile[None, None], (kr, kc, 0, 0))

            def pivot_tile(lt):
                return jax.lax.dynamic_slice(
                    lt, (kr, kc, 0, 0), (1, 1, mb, mb))[0, 0]

            if lkk is not None:
                lt = write_diag(lt, lkk)

            g_rows = ctx.g_rows(lu_r0, ltr_s)
            g_cols = ctx.g_cols(lu_c0, ltc_s)
            row_valid = (g_rows > k) & (g_rows < nt)
            col_valid = (g_cols > k) & (g_cols < nt)
            valid1 = k + 1 < nt

            if uplo == "L":
                colk = jax.lax.dynamic_slice(
                    lt, (0, kc, 0, 0), (ltr_s, 1, mb, mb))[:, 0]
                if fuse_step:
                    lkk, pan = ppan.fused_factor_solve(
                        "L", diag, colk, interpret=pallas_interpret)
                else:
                    pan = ppan.panel_solve("R", "L", "C", "N", lkk, colk,
                                         fused=panel_fused,
                                         interpret=pallas_interpret,
                                         inv_a=lkk_inv)
                pan = jnp.where(row_valid[:, None, None], pan, 0)
                keep = (is_owner_c & row_valid)[:, None, None]
                lt = jax.lax.dynamic_update_slice(
                    lt, jnp.where(keep, pan, colk)[:, None], (0, kc, 0, 0))
                if fuse_step:
                    lt = write_diag(lt, lkk, fallback=pivot_tile(lt))
                vr = cc.bcast(pan, COL_AXIS, owner_c)
                vc = transpose_col_to_rows(DistContext(dist), vr, lu_r0,
                                           g_cols)
                vc = jnp.where(col_valid[:, None, None], vc, 0)

                # -- deferred bulk of step k-1 (its column-k strip was
                # applied eagerly in body k-1, so exclude column k) ------
                rv_p = (g_rows > k - 1) & (g_rows < nt)
                cv_p = (g_cols > k - 1) & (g_cols < nt) & (g_cols != k)
                pairp = rv_p[:, None] & cv_p[None, :]
                belowp = pairp & (g_rows[:, None] > g_cols[None, :])
                ondiagp = pairp & (g_rows[:, None] == g_cols[None, :])
                if use_mxu and use_oz_pallas:
                    updp = _masked_oz_update(
                        pvr.reshape(ltr_s * mb, mb),
                        jnp.conj(pvc).reshape(ltc_s * mb, mb),
                        belowp | ondiagp, ltr_s, ltc_s, mb,
                        pallas_interpret)
                else:
                    updp = _pair_upd(pvr, pvc)
                tri_m = jnp.tril(jnp.ones((mb, mb), dtype=bool))
                mask4p = belowp[:, :, None, None] \
                    | (ondiagp[:, :, None, None] & tri_m)
                lt = lt - jnp.where(mask4p, updp, 0)

                # -- eager next-column strip from THIS panel -------------
                kc1 = ctx.kc(k + 1) - lu_c0
                own_c1 = ctx.rank_c == ctx.owner_c(k + 1)
                pk1 = jax.lax.dynamic_slice(vc, (kc1, 0, 0),
                                            (1, mb, mb))[0]
                below1 = (g_rows > k + 1) & (g_rows < nt)
                ondiag1 = g_rows == k + 1
                if use_mxu:
                    mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
                    updc = mmfn(vr.reshape(ltr_s * mb, mb),
                                jnp.conj(pk1).T,
                                slices=tb._oz_slices()).reshape(
                                    ltr_s, mb, mb)
                else:
                    updc = jnp.einsum("rab,db->rad", vr, jnp.conj(pk1),
                                      preferred_element_type=vr.dtype)
                m3 = (below1[:, None, None]
                      | (ondiag1[:, None, None] & tri_m)) \
                    & (own_c1 & valid1)
                colcur = jax.lax.dynamic_slice(
                    lt, (0, kc1, 0, 0), (ltr_s, 1, mb, mb))
                lt = jax.lax.dynamic_update_slice(
                    lt, colcur - jnp.where(m3, updc, 0)[:, None],
                    (0, kc1, 0, 0))
                return (lt, vr, vc), None

            # -- mirrored sweep (uplo='U') ------------------------------
            rowk = jax.lax.dynamic_slice(
                lt, (kr, 0, 0, 0), (1, ltc_s, mb, mb))[0]
            if fuse_step:
                lkk, pan = ppan.fused_factor_solve(
                    "U", diag, rowk, interpret=pallas_interpret)
            else:
                pan = ppan.panel_solve("L", "U", "C", "N", lkk, rowk,
                                     fused=panel_fused,
                                     interpret=pallas_interpret,
                                     inv_a=lkk_inv)
            pan = jnp.where(col_valid[:, None, None], pan, 0)
            keep = (is_owner_r & col_valid)[:, None, None]
            lt = jax.lax.dynamic_update_slice(
                lt, jnp.where(keep, pan, rowk)[None], (kr, 0, 0, 0))
            if fuse_step:
                lt = write_diag(lt, lkk, fallback=pivot_tile(lt))
            vcp = cc.bcast(pan, ROW_AXIS, owner_r)
            vrp = transpose_row_to_cols(DistContext(dist), vcp, lu_c0,
                                        g_rows)
            vrp = jnp.where(row_valid[:, None, None], vrp, 0)

            # deferred bulk of step k-1 (row-k strip applied in body k-1)
            rv_p = (g_rows > k - 1) & (g_rows < nt) & (g_rows != k)
            cv_p = (g_cols > k - 1) & (g_cols < nt)
            pairp = rv_p[:, None] & cv_p[None, :]
            abovep = pairp & (g_rows[:, None] < g_cols[None, :])
            ondiagp = pairp & (g_rows[:, None] == g_cols[None, :])
            if use_mxu and use_oz_pallas:
                ar = jnp.swapaxes(jnp.conj(pvr),
                                  -1, -2).reshape(ltr_s * mb, mb)
                bc2 = jnp.swapaxes(pvc, -1, -2).reshape(ltc_s * mb, mb)
                updp = _masked_oz_update(ar, bc2, abovep | ondiagp,
                                         ltr_s, ltc_s, mb,
                                         pallas_interpret)
            elif use_mxu:
                mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
                ar = jnp.swapaxes(jnp.conj(pvr),
                                  -1, -2).reshape(ltr_s * mb, mb)
                bc2 = jnp.swapaxes(pvc, -1, -2).reshape(ltc_s * mb, mb)
                full = mmfn(ar, bc2.T, slices=tb._oz_slices())
                updp = full.reshape(ltr_s, mb, ltc_s,
                                    mb).transpose(0, 2, 1, 3)
            else:
                updp = jnp.einsum("rba,cbd->rcad", jnp.conj(pvr), pvc,
                                  preferred_element_type=pvc.dtype)
            tri_m = jnp.triu(jnp.ones((mb, mb), dtype=bool))
            mask4p = abovep[:, :, None, None] \
                | (ondiagp[:, :, None, None] & tri_m)
            lt = lt - jnp.where(mask4p, updp, 0)

            # eager next-row strip from THIS panel
            kr1 = ctx.kr(k + 1) - lu_r0
            own_r1 = ctx.rank_r == ctx.owner_r(k + 1)
            pk1 = jax.lax.dynamic_slice(vrp, (kr1, 0, 0), (1, mb, mb))[0]
            above1 = (g_cols > k + 1) & (g_cols < nt)
            ondiag1 = g_cols == k + 1
            if use_mxu:
                mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
                updr = mmfn(jnp.swapaxes(jnp.conj(pk1), -1, -2),
                            jnp.swapaxes(vcp, -1, -2).reshape(
                                ltc_s * mb, mb).T,
                            slices=tb._oz_slices()).reshape(
                                mb, ltc_s, mb).transpose(1, 0, 2)
            else:
                updr = jnp.einsum("ba,cbd->cad", jnp.conj(pk1), vcp,
                                  preferred_element_type=vcp.dtype)
            m3 = (above1[:, None, None]
                  | (ondiag1[:, None, None] & tri_m)) \
                & (own_r1 & valid1)
            rowcur = jax.lax.dynamic_slice(
                lt, (kr1, 0, 0, 0), (1, ltc_s, mb, mb))
            lt = jax.lax.dynamic_update_slice(
                lt, rowcur - jnp.where(m3, updr, 0)[None],
                (kr1, 0, 0, 0))
            return (lt, vrp, vcp), None

        return step

    def factorize(lt):
        # telescoped segments (see _cholesky_local_scan): each segment
        # scans only the remaining trailing slice of the local grid, so
        # the uniform masked work tracks the live trailing block.
        # Adjacent segments whose slice offsets coincide (large grids:
        # the local grid can't shrink every halving) coalesce into one
        # scan — no duplicate identically-shaped step programs
        # (types.telescope_windows, shared by all telescoped builders).
        # Under lookahead the pending panel pair is carried ACROSS
        # segments (dropped slots hold rows/cols behind the window and
        # are zero by the panel masks); the final step's pending is
        # identically zero, so nothing is ever flushed.
        pvr = pvc = None
        for (lu_r0, lu_c0), k0_seg, seg_len in telescope_windows(
                nt, lambda k_start, _len: (uniform_slot_start(k_start, Pr),
                                           uniform_slot_start(k_start, Qc))):
            ltr_s, ltc_s = ltr - lu_r0, ltc - lu_c0
            sub = lt[lu_r0:, lu_c0:]
            if lookahead:
                _count_step_modes("cholesky_dist_scan", seg_len, 0)
                # the pipelined body emits its diag bcast2d + panel bcast
                # + transposed-panel all_gather ahead of the deferred
                # bulk product of step k-1 — per step: 2 collectives per
                # mesh axis run while the bulk MXU product is in flight
                cc.record_overlapped("cholesky_dist_scan", ROW_AXIS,
                                     2 * seg_len)
                cc.record_overlapped("cholesky_dist_scan", COL_AXIS,
                                     2 * seg_len)
                if pvr is None:
                    pvr = jnp.zeros((ltr_s, mb, mb), lt.dtype)
                    pvc = jnp.zeros((ltc_s, mb, mb), lt.dtype)
                else:
                    pvr, pvc = pvr[-ltr_s:], pvc[-ltc_s:]
                # scan bodies carry the index-free `cholesky.scanstep`
                # scope: ONE traced body serves every iteration, so
                # per-step critpath reconstruction uses occurrence order
                # (docs/observability.md, one-traced-body limitation)
                (sub, pvr, pvc), _ = jax.lax.scan(
                    obs.scoped_step(
                        "cholesky.scanstep",
                        make_step_la(lu_r0, lu_c0, ltr_s, ltc_s)),
                    (sub, pvr, pvc), jnp.arange(k0_seg, k0_seg + seg_len))
            else:
                _count_step_modes("cholesky_dist_scan", 0, seg_len)
                sub, _ = jax.lax.scan(
                    obs.scoped_step(
                        "cholesky.scanstep",
                        make_step(lu_r0, lu_c0, ltr_s, ltc_s)), sub,
                    jnp.arange(k0_seg, k0_seg + seg_len))
            lt = lt.at[lu_r0:, lu_c0:].set(sub)
        if with_info:
            return lt, _dist_factor_info(lt, dist)
        return lt

    return shard_map(factorize, mesh=mesh, in_specs=P(ROW_AXIS, COL_AXIS),
                     out_specs=(P(ROW_AXIS, COL_AXIS), P()) if with_info
                     else P(ROW_AXIS, COL_AXIS), check_vma=False)


@register_program_cache
@functools.lru_cache(maxsize=64)
def _dist_cholesky_cached(dist, mesh, dtype, uplo, use_pallas,
                          pallas_interpret, use_mxu, use_mixed,
                          use_oz_pallas=False, scan=False, donate=False,
                          lookahead=False, comm_la=False, with_info=False,
                          panel_fused=False, step_fused=False, route=()):
    # dtype stays in the cache key: storage dtype changes retrace the jit
    # anyway, but distinct keys keep program caches per element type.
    # ``route`` (the active autotune route, docs/autotune.md) is a pure
    # cache-key member: the builders read the routed knobs (_oz_slices /
    # trsm_panel) at trace time, so a route change must land in a
    # DIFFERENT compiled program — never an in-place retrace
    donate_kw = donate_argnums_kw(donate, 0)
    if scan:
        # comm_la is not a scan cache key: the pipelined scan body already
        # emits its collectives ahead of the deferred bulk (callers
        # normalize it to False — see cholesky())
        return jax.jit(_build_dist_cholesky_scan(
            dist, mesh, uplo, use_mxu=use_mxu, use_mixed=use_mixed,
            cplx=dtype.startswith("complex"),
            use_oz_pallas=use_oz_pallas,
            pallas_interpret=pallas_interpret,
            lookahead=lookahead, with_info=with_info,
            panel_fused=panel_fused, step_fused=step_fused), **donate_kw)
    return jax.jit(_build_dist_cholesky(dist, mesh, uplo, use_pallas,
                                        pallas_interpret, use_mxu=use_mxu,
                                        use_mixed=use_mixed,
                                        cplx=dtype.startswith("complex"),
                                        use_oz_pallas=use_oz_pallas,
                                        lookahead=lookahead,
                                        comm_la=comm_la,
                                        with_info=with_info,
                                        panel_fused=panel_fused,
                                        step_fused=step_fused),
                   **donate_kw)




# ---------------------------------------------------------------------------
# Public API (reference factorization/cholesky.h:36,62)
# ---------------------------------------------------------------------------

def cholesky(uplo: str, mat: Matrix, *, donate: bool = False,
             with_info: bool = False):
    """Factorize the Hermitian positive-definite ``mat`` in the ``uplo``
    triangle: L L^H (uplo='L') or U^H U (uplo='U').

    Under ``DLAF_AUTOTUNE`` (docs/autotune.md) the call first consults
    the autotune route table for this (n-bucket, nb, dtype, platform)
    site — the selected precision route rides the builder cache keys, so
    a learned route change dispatches a different compiled program
    without retracing the old one — and, when ``mat`` survives the call
    (``donate=False``), feeds the factor's cheap Hutchinson residual
    probe back into the table (escalate on breach / relax after K
    comfortable probes). Donated inputs skip the probe: there is nothing
    left to compare against.

    See :func:`_cholesky` for the factorization semantics proper
    (info contract, donation, builder routing).
    """
    from .. import autotune

    steer = autotune.steering_for_matrix("cholesky", mat)
    if steer is None:
        return _cholesky(uplo, mat, donate=donate, with_info=with_info)
    with steer.applied():
        out = _cholesky(uplo, mat, donate=donate, with_info=with_info,
                        route=steer.route.key())
    if not donate and steer.probe_due:
        res = out[0] if with_info else out
        steer.observe(
            obs.accuracy.cholesky_residual(uplo, mat, res),
            c=60.0, of=res.storage, attrs={"entry": "cholesky",
                                           "uplo": uplo})
    return out


def _cholesky(uplo: str, mat: Matrix, *, donate: bool = False,
              with_info: bool = False, route: tuple = ()):
    """Factorize the Hermitian positive-definite ``mat`` in the ``uplo``
    triangle: L L^H (uplo='L') or U^H U (uplo='U').

    Local (1x1 grid) or distributed over ``mat.grid``'s mesh, like the
    reference's two overloads. Returns a new Matrix whose ``uplo`` triangle
    holds the factor; the other triangle passes through.

    ``with_info=True`` returns ``(factor, info)`` instead — the reference's
    ``potrfInfo`` contract lifted to the blocked algorithm: ``info`` is an
    int32 DEVICE scalar, 0 on success or the 1-based first failing global
    column, computed in-graph inside the same compiled program (no host
    sync; fetching it — ``int(info)`` — is the caller's explicit decision,
    e.g. :func:`dlaf_tpu.health.robust_cholesky`'s recovery point). The
    factor is bitwise identical with the flag on or off: detection is a
    pure extra output on the final factor's diagonal (distributed: combined
    across ranks via max over the owner masks). Precision of the column
    locator follows the backend's NaN semantics — see
    ``tile_ops/lapack.py:potrf_info`` and docs/robustness.md.

    ``donate=True`` donates ``mat``'s device storage to the factorization
    (the reference's in-place semantics, ``factorization/cholesky.h:36``:
    its ``mat_a`` IS overwritten): ``mat`` must not be used afterwards.
    This removes one full-matrix HBM buffer from the peak live set — the
    difference between fitting and OOM near the single-chip ceiling
    (N=16384 asked ~14-16 GB of 15.75 with all step forms pre-donation).
    Internal stage hand-offs (layout transform -> factorization -> layout
    transform) are always donated; they are owned by this function.
    """
    dlaf_assert(uplo in ("L", "U"), f"cholesky: uplo must be 'L' or 'U', got {uplo!r}")
    from ..config import get_configuration, resolve_platform_auto

    trailing = resolve_platform_auto(
        get_configuration().cholesky_trailing, knob="cholesky_trailing",
        tpu_choice="ozaki", other_choice="loop",
        detail="ozaki trailing measured 112.8/351.0 GF/s at N=4096/8192 "
               "vs 42-47 for loop/xla — 2026-08-01 v5e session")
    dlaf_assert(trailing in VALID_TRAILING,
                f"cholesky_trailing must be one of {VALID_TRAILING}, got {trailing!r}")
    dlaf_assert(mat.size.row == mat.size.col, "cholesky: matrix must be square")
    dlaf_assert(mat.block_size.row == mat.block_size.col,
                "cholesky: block must be square")
    cfg = get_configuration()
    dt = np.dtype(mat.dtype)
    n = mat.size.row
    grid_shape = (mat.dist.grid_size.row, mat.dist.grid_size.col)
    # look-ahead step order (docs/lookahead.md): pipelined when the knob
    # resolves 1; the whole-matrix "xla" delegation has no step structure
    # to pipeline. comm_lookahead (docs/comm_overlap.md) extends the
    # carry across the collectives of the unrolled distributed builder —
    # it rides the SSA carry, so it requires lookahead too.
    from ..config import (resolved_cholesky_lookahead,
                          resolved_comm_lookahead)

    lookahead = resolved_cholesky_lookahead() and trailing != "xla"
    comm_la = lookahead and resolved_comm_lookahead()
    # fused Pallas panel route (panel_impl knob, docs/pallas_panel.md):
    # resolved ONCE per entry (single owner pallas_panel.panel_uses_fused
    # — dtype/block policy + injection gate + fallback accounting) and
    # threaded into every builder as a static/cache-key argument; the
    # whole-matrix "xla" trailing delegation has no panel chain to route
    panel_fused = trailing != "xla" and ppan.panel_uses_fused(
        dt, mat.block_size.row)
    # fused STEP route (step_impl knob, docs/pallas_panel.md "Fused step
    # kernel"): one pallas_call per blocked step — resolved once here
    # (single owner pallas_panel.step_uses_fused: dtype/block/VMEM
    # policy + injection gate + site="step" fallback accounting) and
    # threaded into every builder as a static/cache-key argument
    step_fused = trailing != "xla" and ppan.step_uses_fused(
        dt, mat.block_size.row)
    # entry span: host wall around trace+dispatch, unfenced (device
    # completion is the caller's fence — the miniapp span carries the
    # honest GFlop/s); attrs and the reference flop model build lazily
    entry_span = obs.entry_span("cholesky", lambda: dict(
        flops=total_ops(dt, n**3 / 6, n**3 / 6),
        n=n, nb=mat.block_size.row, uplo=uplo, dtype=dt.name,
        trailing=trailing, lookahead=int(lookahead),
        comm_lookahead=int(comm_la),
        panel_impl="fused" if panel_fused else "xla",
        step_impl="fused" if step_fused else "xla",
        **({"autotune_route": dict(route)} if route else {}),
        grid=f"{grid_shape[0]}x{grid_shape[1]}"))
    # the scan formulations follow the f64_gemm/f64_trsm knobs (identical
    # resolution local and distributed, single owner in tile_ops.blas);
    # the unrolled local path selects its route via cholesky_trailing
    use_mxu = tb.f64_gemm_uses_mxu(dt, mat.block_size.row)
    use_mixed = tb.trsm_panel_uses_mixed(dt)
    if mat.grid is None or mat.grid.num_devices == 1:
        with entry_span, quiet_donation():
            a = to_global(mat.storage, mat.dist, donate)
            # program telemetry (DLAF_PROGRAM_TELEMETRY): compile wall /
            # retraces / HBM footprint per site; off = the same jitted
            # callables, bitwise no-op (docs/observability.md)
            # off-TPU the fused panel kernels run in interpret mode
            # (same convention as the pallas trailing kernels)
            panel_interp = jax.default_backend() != "tpu"
            if trailing == "scan":
                out = obs.telemetry.call(
                    "cholesky.local_scan", _cholesky_local_scan, a,
                    uplo=uplo, nb=mat.block_size.row, use_mxu=use_mxu,
                    use_mixed=use_mixed, lookahead=lookahead,
                    with_info=with_info, panel_fused=panel_fused,
                    step_fused=step_fused,
                    panel_interpret=(panel_fused or step_fused)
                    and panel_interp,
                    route=route)
            else:
                out = obs.telemetry.call(
                    "cholesky.local", _cholesky_local, a, uplo=uplo,
                    nb=mat.block_size.row, trailing=trailing,
                    lookahead=lookahead, with_info=with_info,
                    panel_fused=panel_fused, step_fused=step_fused,
                    panel_interpret=(panel_fused or step_fused)
                    and panel_interp,
                    route=route)
            info = None
            if with_info:
                out, info = out
            res = mat.with_storage(global_to_tiles_donated(out, mat.dist))
            return (res, info) if with_info else res
    platform = next(iter(mat.grid.mesh.devices.flat)).platform
    # exact-flop predicated contraction (ozaki_impl="pallas"): real f64
    # only (complex keeps the 4-real-product composition), within the
    # masked kernel's per-cell VMEM bound
    from ..health.registry import route_available
    from ..tile_ops.pallas_ozaki import MASKED_MB_MAX

    from ..config import _route_override

    oz_impl = _route_override("ozaki_impl") or cfg.ozaki_impl
    want_oz_pallas = use_mxu and oz_impl == "pallas"
    use_oz_pallas = (want_oz_pallas and dt == np.dtype(np.float64)
                     and mat.block_size.row <= MASKED_MB_MAX)
    if use_oz_pallas and not route_available("pallas", "ozaki_pallas"):
        # the pallas -> XLA chain under the unified degradation policy:
        # counted, announced, and a raise in strict mode
        use_oz_pallas = False
    elif want_oz_pallas and not use_oz_pallas:
        # route POLICY, not degradation (complex keeps the documented
        # 4-real-product composition; oversized blocks exceed the kernel's
        # VMEM bound): announce once, never count or strict-raise
        obs.get_logger("health").warning_once(
            ("ozaki_pallas_policy", dt.name, mat.block_size.row),
            f"ozaki_impl=pallas does not apply to dtype={dt.name} "
            f"mb={mat.block_size.row} (needs float64, mb<={MASKED_MB_MAX});"
            " using the jnp slice reduction",
            dtype=dt.name, mb=mat.block_size.row)
    scan_mode = trailing == "scan"
    fn = _dist_cholesky_cached(mat.dist, mat.grid.mesh, dt.name, uplo,
                               # the f32/bf16 pallas trailing kernel is
                               # unrolled-only; normalize it out of scan
                               # cache keys. use_oz_pallas works in BOTH
                               # modes (its mode mask is data).
                               (not scan_mode)
                               and supports_pallas_update(mat.dtype, platform)
                               and not use_mxu,
                               platform != "tpu",
                               use_mxu, use_mixed,
                               use_oz_pallas,
                               scan=scan_mode, donate=donate,
                               lookahead=lookahead,
                               # scan bodies overlap by construction; the
                               # hoist (and cache key) is unrolled-only
                               comm_la=comm_la and not scan_mode,
                               with_info=with_info,
                               panel_fused=panel_fused,
                               step_fused=step_fused, route=route)
    with entry_span, quiet_donation():
        if with_info:
            storage, info = obs.telemetry.call("cholesky.dist", fn,
                                               mat.storage)
            return mat.with_storage(storage), info
        return mat.with_storage(
            obs.telemetry.call("cholesky.dist", fn, mat.storage))
