"""Triangular solve and triangular multiply — local and distributed.

TPU-native counterpart of the reference's ``solver/triangular``
(``solver/triangular/api.h:20-51``, ``impl.h``: all 8 Left/Right x Lower/Upper
x NoTrans/Trans combos, local + distributed) and ``multiplication/triangular``
(``multiplication/triangular/api.h:20-43``).

Local variants ARE one XLA op: ``TriangularSolve`` / masked matmul — XLA's
implementation is already the blocked substitution the reference hand-codes,
so the TPU-idiomatic "algorithm" is the direct lowering.

Distributed variants run the blocked substitution/accumulation over tile
rows/columns inside shard_map, using the panel-exchange helpers
(:mod:`dlaf_tpu.matrix.panel`): the diagonal tile travels with two mask+psum
hops, row/column panels with one, transposed selections with an all_gather —
and the per-``k`` trailing update is one batched einsum (dense rectangle, so
unlike Cholesky there is no triangle waste).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from .. import obs
from ..config import register_program_cache
from ..common.asserts import dlaf_assert
from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..matrix.distribution import assert_slot_aligned
from ..matrix.matrix import Matrix
from ..matrix.panel import (DistContext, bcast_diag, bcast_diag_dyn, col_panel,
                            col_panel_dyn, pad_diag_identity,
                            pad_diag_identity_dyn, row_panel, row_panel_dyn,
                            transpose_col_to_rows, transpose_row_to_cols,
                            uniform_slot_start)
from ..matrix.tiling import (tiles_to_global, global_to_tiles_donated,
                             to_global, quiet_donation, donate_argnums_kw)
from ..tile_ops import blas as tb
from ..tile_ops import pallas_panel as ppan
from ..types import telescope_windows, total_ops


def _tile_op(t, op: str):
    if op == "N":
        return t
    x = jnp.swapaxes(t, -1, -2)
    return jnp.conj(x) if op == "C" else x


# ---------------------------------------------------------------------------
# Local: direct XLA lowering
# ---------------------------------------------------------------------------

def _rhs_chunk_width(side: str, b_shape, dtype) -> int:
    """Trace-time: free-axis chunk width for a local whole-matrix solve,
    0 = unchunked (config ``trsm_rhs_chunk``; see the knob docstring).
    rhs free-axis slices are mathematically independent in a triangular
    solve, so mapping over chunks is bitwise-identical — it only bounds
    the live mxu-route workspaces (slices/partials/products) to one
    chunk's width."""
    m, n = b_shape
    free, solve_dim = (n, m) if side == "L" else (m, n)
    # auto chunks only where the measured OOM lives — TPU, mxu-routed
    # emulated dtypes, both dimensions large (session 4g: HEGST d/16384
    # twosolve RESOURCE_EXHAUSTED with donation already applied)
    return tb.resolve_chunk_width("trsm_rhs_chunk", dtype, solve_dim,
                                  free, solve_dim, free)


# the rhs operand (argnum 1) is always the entry point's freshly built
# global-layout array — donating it bounds peak HBM by one full matrix
@register_program_cache
@functools.partial(jax.jit, static_argnames=("side", "uplo", "op", "diag"),
                   donate_argnums=1)
def _solve_local(a, b, alpha, *, side, uplo, op, diag):
    cw = _rhs_chunk_width(side, b.shape, b.dtype)
    if not cw:
        return tb.trsm(side, uplo, op, diag, a, b, alpha=alpha)
    from jax import lax

    m, n = b.shape
    free = n if side == "L" else m
    nc = -(-free // cw)
    pad = nc * cw - free          # zero columns/rows solve to zero
    if side == "L":
        bp = jnp.pad(b, ((0, 0), (0, pad)))
        # slice each column chunk on the fly (a transposed (nc, m, cw)
        # operand stack would be a second full-matrix HBM temp — on the
        # exact path built to avoid one)
        out = lax.map(
            lambda i: tb.trsm(side, uplo, op, diag, a,
                              lax.dynamic_slice(bp, (jnp.zeros((), i.dtype), i),
                                                (m, cw)),
                              alpha=alpha),
            jnp.arange(nc, dtype=jnp.int32) * cw)
        return jnp.moveaxis(out, 0, 1).reshape(m, nc * cw)[:, :free]
    bp = jnp.pad(b, ((0, pad), (0, 0)))
    out = lax.map(
        lambda bc: tb.trsm(side, uplo, op, diag, a, bc, alpha=alpha),
        bp.reshape(nc, cw, n))
    return out.reshape(nc * cw, n)[:free]


@register_program_cache
@functools.partial(jax.jit, static_argnames=("side", "uplo", "op", "diag"),
                   donate_argnums=1)
def _mult_local(a, b, alpha, *, side, uplo, op, diag):
    return tb.trmm(side, uplo, op, diag, a, b, alpha=alpha)


# ---------------------------------------------------------------------------
# Distributed substitution (solve) — reference solver/triangular/impl.h
# ---------------------------------------------------------------------------

def _build_dist_solve(dist_a, dist_b, mesh, side, uplo, op, diag, dtype,
                      panel_fused=False, panel_interpret=False):
    nt = dist_a.nr_tiles.row
    n = dist_a.size.row
    mb = dist_a.block_size.row

    def prog(lta, ltb):
        ctx_a = DistContext(dist_a)
        ctx_b = DistContext(dist_b)
        eff_lower = (uplo == "L") == (op == "N")
        if side == "L":
            forward = eff_lower
        else:
            forward = not eff_lower
        order = range(nt) if forward else range(nt - 1, -1, -1)
        # uniform per-step phase scopes (`trsm.step<k>.<phase>`, shared
        # convention with cholesky — docs/observability.md critical-path
        # attribution). Backward sweeps keep the GLOBAL step index k in
        # the name; the critpath joiner orders steps by time, not index.
        for k in order:
            with obs.named_span(f"trsm.step{k:03d}.panel"):
                akk = bcast_diag(ctx_a, lta, k)
                if k == nt - 1:  # short edge tile: keep the solve nonsingular
                    akk = pad_diag_identity(akk, min(mb, n - k * mb))
            if side == "L":
                with obs.named_span(f"trsm.step{k:03d}.panel"):
                    # solve op(Akk) Xk = Bk for tile row k of B (all
                    # local cols) — pivot-diag solve on the panel_impl
                    # route (fused Pallas strip kernel or the XLA chain;
                    # docs/pallas_panel.md)
                    bk = row_panel(ctx_b, ltb, k, 0)
                    xk = ppan.panel_solve("L", uplo, op, diag, akk, bk,
                                          fused=panel_fused,
                                          interpret=panel_interpret)
                    own = ctx_b.rank_r == ctx_b.owner_r(k)
                    row = ctx_b.kr(k)
                    ltb = ltb.at[row].set(jnp.where(own, xk, ltb[row]))
                # remaining rows i: B[i,:] -= E[i,k] @ Xk
                if forward:
                    lu = ctx_b.row_start(k + 1)
                    sl = slice(lu, ctx_b.ltr)
                else:
                    lu = 0
                    sl = slice(0, min(ctx_b.ltr, (k - 1) // ctx_b.P + 1) if k else 0)
                count = sl.stop - sl.start if sl.stop is not None else 0
                if count <= 0:
                    continue
                with obs.named_span(f"trsm.step{k:03d}.bulk"):
                    g = ctx_b.g_rows(lu, count)
                    rem = (g > k) if forward else (g < k)
                    rem = rem & (g < nt)
                    if op == "N":
                        e = col_panel(ctx_a, lta, k, lu)[:count]  # A[i,k] my rows
                    else:
                        rk = row_panel(ctx_a, lta, k, 0)      # A[k,j] my cols
                        e = _tile_op(transpose_row_to_cols(ctx_a, rk, 0, g), op)
                    e = jnp.where(rem[:, None, None], e, jnp.zeros_like(e))
                    upd = tb.contract("rab,cbd->rcad", e, xk)
                    ltb = ltb.at[sl].add(-upd)
            else:
                with obs.named_span(f"trsm.step{k:03d}.panel"):
                    # solve Xk op(Akk) = Bk for tile col k of B (all
                    # local rows)
                    bk = col_panel(ctx_b, ltb, k, 0)
                    xk = ppan.panel_solve("R", uplo, op, diag, akk, bk,
                                          fused=panel_fused,
                                          interpret=panel_interpret)
                    own = ctx_b.rank_c == ctx_b.owner_c(k)
                    col = ctx_b.kc(k)
                    ltb = ltb.at[:, col].set(jnp.where(own, xk, ltb[:, col]))
                if forward:
                    lu = ctx_b.col_start(k + 1)
                    sl = slice(lu, ctx_b.ltc)
                else:
                    lu = 0
                    sl = slice(0, min(ctx_b.ltc, (k - 1) // ctx_b.Q + 1) if k else 0)
                count = sl.stop - sl.start
                if count <= 0:
                    continue
                with obs.named_span(f"trsm.step{k:03d}.bulk"):
                    g = ctx_b.g_cols(lu, count)
                    rem = (g > k) if forward else (g < k)
                    rem = rem & (g < nt)
                    if op == "N":
                        e = row_panel(ctx_a, lta, k, 0)[lu: lu + count]  # A[k,j]
                    else:
                        ck = col_panel(ctx_a, lta, k, 0)      # A[i,k] my rows
                        e = _tile_op(transpose_col_to_rows(ctx_a, ck, 0, g), op)
                    e = jnp.where(rem[:, None, None], e, jnp.zeros_like(e))
                    upd = tb.contract("rab,cbd->rcad", xk, e)
                    ltb = ltb.at[:, sl].add(-upd)
        return ltb

    def run(lta, ltb, alpha):
        return prog(lta, alpha * ltb)

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS), P()),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


def _build_dist_solve_scan(dist_a, dist_b, mesh, side, uplo, op, diag, dtype,
                           lookahead=False, comm_la=False,
                           panel_fused=False, panel_interpret=False):
    """``lax.scan`` form of the distributed solve (config
    ``dist_step_mode="scan"``): one compiled step body per telescoped
    segment, looped over the segment's steps — the same O(1)-compile /
    uniform-masked-shapes trade as the scan Cholesky (see
    ``cholesky._build_dist_cholesky_scan`` and docs/DESIGN.md). Per-``k``
    index math is traced arithmetic; pivot row/column access uses dynamic
    slices. The swept axis of B (rows for side='L', cols for 'R') is
    TELESCOPED: forward substitutions slice the live bottom ``[lu0:]``
    of the slot axis per segment, backward substitutions the live top
    ``[:ub]``, so the uniform masked trailing update tracks the shrinking
    live region instead of paying all slots every step; A's panel reads
    and the transpose-exchange windows shrink with it. B's orthogonal
    axis never shrinks (every step solves the full pivot panel)."""
    nt = dist_a.nr_tiles.row
    n = dist_a.size.row
    mb = dist_a.block_size.row

    def prog(lta, ltb):
        ctx_a = DistContext(dist_a)
        ctx_b = DistContext(dist_b)
        eff_lower = (uplo == "L") == (op == "N")
        forward = eff_lower if side == "L" else not eff_lower
        # swept-axis grid/slot extents (B rows for 'L', B cols for 'R')
        # and A's transpose-exchange axis (the opposite one of A)
        p_swept = ctx_b.P if side == "L" else ctx_b.Q
        lt_swept = ctx_b.ltr if side == "L" else ctx_b.ltc
        q_orth = ctx_a.Q if side == "L" else ctx_a.P
        lt_orth = ctx_a.ltc if side == "L" else ctx_a.ltr

        def make_step(lu0, cnt, lq0, cnt_q):
            """Step body over the swept-axis window ``[lu0, lu0+cnt)`` of
            B's slots (``lq0``/``cnt_q``: matching window of A's
            transpose-exchange axis). Every pivot of the segment lies
            inside the window; validity masks do the rest."""

            def step(sub, i):
                k = i if forward else nt - 1 - i
                akk = bcast_diag_dyn(ctx_a, lta, k)
                akk = pad_diag_identity_dyn(akk, jnp.minimum(mb, n - k * mb))
                if side == "L":
                    bk = row_panel_dyn(ctx_b, sub, k, row_off=lu0)
                    xk = ppan.panel_solve("L", uplo, op, diag, akk, bk,
                                          fused=panel_fused,
                                          interpret=panel_interpret)
                    own = ctx_b.rank_r == ctx_b.owner_r(k)
                    row = ctx_b.kr(k) - lu0
                    cur = jax.lax.dynamic_slice(
                        sub, (row, 0, 0, 0), (1,) + sub.shape[1:])[0]
                    sub = jax.lax.dynamic_update_slice(
                        sub, jnp.where(own, xk, cur)[None], (row, 0, 0, 0))
                    g = ctx_b.g_rows(lu0, cnt)
                    rem = ((g > k) if forward else (g < k)) & (g < nt)
                    if op == "N":
                        e = col_panel_dyn(ctx_a, lta, k, lu=lu0, count=cnt)
                    else:
                        rk = row_panel_dyn(ctx_a, lta, k, lu=lq0,
                                           count=cnt_q)
                        e = _tile_op(
                            transpose_row_to_cols(ctx_a, rk, lq0, g), op)
                    e = jnp.where(rem[:, None, None], e, jnp.zeros_like(e))
                    upd = tb.contract("rab,cbd->rcad", e, xk)
                    return sub - upd, None
                bk = col_panel_dyn(ctx_b, sub, k, col_off=lu0)
                xk = ppan.panel_solve("R", uplo, op, diag, akk, bk,
                                      fused=panel_fused,
                                      interpret=panel_interpret)
                own = ctx_b.rank_c == ctx_b.owner_c(k)
                col = ctx_b.kc(k) - lu0
                cur = jax.lax.dynamic_slice(
                    sub, (0, col, 0, 0),
                    (sub.shape[0], 1) + sub.shape[2:])[:, 0]
                sub = jax.lax.dynamic_update_slice(
                    sub, jnp.where(own, xk, cur)[:, None], (0, col, 0, 0))
                g = ctx_b.g_cols(lu0, cnt)
                rem = ((g > k) if forward else (g < k)) & (g < nt)
                if op == "N":
                    e = row_panel_dyn(ctx_a, lta, k, lu=lu0, count=cnt)
                else:
                    ck = col_panel_dyn(ctx_a, lta, k, lu=lq0, count=cnt_q)
                    e = _tile_op(
                        transpose_col_to_rows(ctx_a, ck, lq0, g), op)
                e = jnp.where(rem[:, None, None], e, jnp.zeros_like(e))
                upd = tb.contract("rab,cbd->rcad", xk, e)
                return sub - upd, None

            return step

        def make_step_la(lu0, cnt, lq0, cnt_q):
            """Software-pipelined step body (``cholesky_lookahead=1`` —
            the same next-pivot-first split as the pipelined Cholesky):
            carry ``(sub, pe, pxk)`` = the previous step's masked panel
            operands, and apply their BULK update inside this body, where
            it is independent of this body's latency-bound trsm — while
            the NEXT pivot row/column's strip is updated eagerly so the
            following body's solve reads current data. Per-slot
            application order matches the serial body (bulk k-1 before
            strip k), so results are bitwise identical on the native
            route.

            ``comm_la`` (``comm_lookahead=1``, docs/comm_overlap.md)
            additionally hoists this step's A-panel read — the
            ``col_panel``/``row_panel`` broadcast, and for op != 'N' the
            transpose-exchange all_gather — AHEAD of the deferred bulk
            product: the panel reads only the constant ``lta``, so the
            collective can run on the ICI while the bulk contraction is
            in flight. The pivot solve's own panel broadcast and the
            fused diag ``bcast2d`` already precede the bulk either way.
            Pure emission reorder of identical values — bitwise-equal
            results with the knob on or off."""

            def step(carry, i):
                sub, pe, pxk = carry
                k = i if forward else nt - 1 - i
                knext = k + 1 if forward else k - 1
                akk = bcast_diag_dyn(ctx_a, lta, k)
                akk = pad_diag_identity_dyn(akk, jnp.minimum(mb, n - k * mb))
                if side == "L":
                    bk = row_panel_dyn(ctx_b, sub, k, row_off=lu0)
                    xk = ppan.panel_solve("L", uplo, op, diag, akk, bk,
                                          fused=panel_fused,
                                          interpret=panel_interpret)
                    own = ctx_b.rank_r == ctx_b.owner_r(k)
                    row = ctx_b.kr(k) - lu0
                    cur = jax.lax.dynamic_slice(
                        sub, (row, 0, 0, 0), (1,) + sub.shape[1:])[0]
                    sub = jax.lax.dynamic_update_slice(
                        sub, jnp.where(own, xk, cur)[None], (row, 0, 0, 0))
                    g = ctx_b.g_rows(lu0, cnt)
                    rem = ((g > k) if forward else (g < k)) & (g < nt)

                    def epanel():
                        if op == "N":
                            e = col_panel_dyn(ctx_a, lta, k, lu=lu0,
                                              count=cnt)
                        else:
                            rk = row_panel_dyn(ctx_a, lta, k, lu=lq0,
                                               count=cnt_q)
                            e = _tile_op(
                                transpose_row_to_cols(ctx_a, rk, lq0, g), op)
                        return jnp.where(rem[:, None, None], e,
                                         jnp.zeros_like(e))

                    if comm_la:
                        # A-panel collectives emitted BEFORE the deferred
                        # bulk of step k-1 (pe is pre-masked)
                        e = epanel()
                        sub = sub - tb.contract("rab,cbd->rcad", pe, pxk)
                    else:
                        sub = sub - tb.contract("rab,cbd->rcad", pe, pxk)
                        e = epanel()
                    # eager next-pivot-row strip (slot holds global row
                    # knext only on its owner; gval-gating keeps every
                    # other rank's slot in the pending set instead)
                    rnext = ctx_b.kr(knext) - lu0
                    gval = jax.lax.dynamic_slice(g, (rnext,), (1,))[0]
                    hit = (gval == knext) & (knext >= 0) & (knext < nt)
                    er = jax.lax.dynamic_slice(e, (rnext, 0, 0),
                                               (1, mb, mb))[0]
                    updn = tb.contract("ab,cbd->cad", er, xk)
                    rcur = jax.lax.dynamic_slice(
                        sub, (rnext, 0, 0, 0), (1,) + sub.shape[1:])[0]
                    sub = jax.lax.dynamic_update_slice(
                        sub, (rcur - jnp.where(hit, updn, 0))[None],
                        (rnext, 0, 0, 0))
                    pe_next = jnp.where((rem & (g != knext))[:, None, None],
                                        e, jnp.zeros_like(e))
                    return (sub, pe_next, xk), None
                bk = col_panel_dyn(ctx_b, sub, k, col_off=lu0)
                xk = ppan.panel_solve("R", uplo, op, diag, akk, bk,
                                      fused=panel_fused,
                                      interpret=panel_interpret)
                own = ctx_b.rank_c == ctx_b.owner_c(k)
                col = ctx_b.kc(k) - lu0
                cur = jax.lax.dynamic_slice(
                    sub, (0, col, 0, 0),
                    (sub.shape[0], 1) + sub.shape[2:])[:, 0]
                sub = jax.lax.dynamic_update_slice(
                    sub, jnp.where(own, xk, cur)[:, None], (0, col, 0, 0))
                g = ctx_b.g_cols(lu0, cnt)
                rem = ((g > k) if forward else (g < k)) & (g < nt)

                def epanel():
                    if op == "N":
                        e = row_panel_dyn(ctx_a, lta, k, lu=lu0, count=cnt)
                    else:
                        ck = col_panel_dyn(ctx_a, lta, k, lu=lq0,
                                           count=cnt_q)
                        e = _tile_op(
                            transpose_col_to_rows(ctx_a, ck, lq0, g), op)
                    return jnp.where(rem[:, None, None], e,
                                     jnp.zeros_like(e))

                if comm_la:
                    e = epanel()
                    sub = sub - tb.contract("rab,cbd->rcad", pxk, pe)
                else:
                    sub = sub - tb.contract("rab,cbd->rcad", pxk, pe)
                    e = epanel()
                cnext = ctx_b.kc(knext) - lu0
                gval = jax.lax.dynamic_slice(g, (cnext,), (1,))[0]
                hit = (gval == knext) & (knext >= 0) & (knext < nt)
                ec = jax.lax.dynamic_slice(e, (cnext, 0, 0),
                                           (1, mb, mb))[0]
                updn = tb.contract("rab,bd->rad", xk, ec)
                ccur = jax.lax.dynamic_slice(
                    sub, (0, cnext, 0, 0),
                    (sub.shape[0], 1) + sub.shape[2:])[:, 0]
                sub = jax.lax.dynamic_update_slice(
                    sub, (ccur - jnp.where(hit, updn, 0))[:, None],
                    (0, cnext, 0, 0))
                pe_next = jnp.where((rem & (g != knext))[:, None, None],
                                    e, jnp.zeros_like(e))
                return (sub, pe_next, xk), None

            return step

        # telescoped segments over the swept axis (see
        # cholesky._build_dist_cholesky_scan); the transpose-exchange
        # window only splits segments when op != "N" actually uses it
        def window(pos, seg_len):
            # slot bounds via uniform_slot_start — the declared single
            # owner (matrix/panel.py); k//p would be identical today
            if forward:
                lo, loq = (uniform_slot_start(pos, p_swept),
                           uniform_slot_start(pos, q_orth))
                win = (lo, lt_swept - lo)
                winq = (loq, lt_orth - loq)
            else:
                k_hi = nt - 1 - pos
                win = (0, min(lt_swept,
                              uniform_slot_start(k_hi, p_swept) + 1))
                winq = (0, min(lt_orth,
                               uniform_slot_start(k_hi, q_orth) + 1))
            return (win, winq if op != "N" else (0, lt_orth))

        # under lookahead the pending operands carry ACROSS segments (the
        # slots a shrinking window drops are zero by the rem mask — the
        # serial windows already prove they hold no live tiles); the last
        # step's pending is identically zero, so nothing is flushed
        pe = pxk = None
        prev_lu0 = 0
        for ((lu0, cnt), (lq0, cnt_q)), i0, seg_len in \
                telescope_windows(nt, window):
            sub = jax.lax.slice_in_dim(ltb, lu0, lu0 + cnt,
                                       axis=0 if side == "L" else 1)
            if lookahead:
                # collectives emitted ahead of the deferred bulk
                # (docs/comm_overlap.md): the diag bcast2d (one per
                # axis) and the pivot panel broadcast (swept axis)
                # precede it in the pipelined body regardless of the
                # comm knob; comm_la additionally hoists the A-panel
                # read — one broadcast on the opposite axis for
                # op='N', else the source-panel broadcast plus the
                # transpose-exchange all_gather
                n_row = 1 + (side == "L")   # bcast2d + pivot panel bcast
                n_col = 1 + (side == "R")
                if comm_la:
                    if op == "N":
                        n_col += side == "L"   # opposite-axis panel bcast
                        n_row += side == "R"
                    else:                      # source bcast + all_gather
                        n_row += 1
                        n_col += 1
                cc.record_overlapped("triangular_solve_scan",
                                     ROW_AXIS, n_row * seg_len)
                cc.record_overlapped("triangular_solve_scan",
                                     COL_AXIS, n_col * seg_len)
                if pe is None:
                    pe = jnp.zeros((cnt, mb, mb), ltb.dtype)
                    orth = ltb.shape[1] if side == "L" else ltb.shape[0]
                    pxk = jnp.zeros((orth, mb, mb), ltb.dtype)
                else:
                    pe = pe[lu0 - prev_lu0: lu0 - prev_lu0 + cnt]
                prev_lu0 = lu0
                # index-free scope: one traced body for all iterations —
                # critpath reconstructs per-step timing by occurrence
                # order (docs/observability.md one-traced-body note)
                (sub, pe, pxk), _ = jax.lax.scan(
                    obs.scoped_step("trsm.scanstep",
                                    make_step_la(lu0, cnt, lq0, cnt_q)),
                    (sub, pe, pxk), jnp.arange(i0, i0 + seg_len))
            else:
                sub, _ = jax.lax.scan(
                    obs.scoped_step("trsm.scanstep",
                                    make_step(lu0, cnt, lq0, cnt_q)), sub,
                    jnp.arange(i0, i0 + seg_len))
            if side == "L":
                ltb = ltb.at[lu0:lu0 + cnt].set(sub)
            else:
                ltb = ltb.at[:, lu0:lu0 + cnt].set(sub)
        return ltb

    def run(lta, ltb, alpha):
        return prog(lta, alpha * ltb)

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS), P()),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


# ---------------------------------------------------------------------------
# Distributed accumulation (multiply) — reference multiplication/triangular
# ---------------------------------------------------------------------------

def _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag):
    """Triangle masking of a pivot panel for the multiply builders: the
    diagonal slot gets the (unit-)triangle-masked tile, strict slots the
    full tile, everything else zero. ``strict``: boolean per-slot mask of
    the strictly-included side (direction already resolved by the
    caller's eff_lower/side logic)."""
    ondiag = (g == k)
    dt = tb.tri_mask(e, uplo if op == "N" else ("U" if uplo == "L" else "L"))
    dt = _unit_diag(dt, diag)
    return jnp.where(ondiag[:, None, None], dt,
                     jnp.where(strict[:, None, None] & (g < nt)[:, None, None],
                               e, jnp.zeros_like(e)))


def _build_dist_mult(dist_a, dist_b, mesh, side, uplo, op, diag, dtype):
    nt = dist_a.nr_tiles.row

    def prog(lta, ltb):
        ctx_a = DistContext(dist_a)
        ctx_b = DistContext(dist_b)
        eff_lower = (uplo == "L") == (op == "N")
        # does step k touch output slots g >= k (True) or g <= k (False)?
        ascending = eff_lower if side == "L" else not eff_lower
        out = jnp.zeros_like(ltb)
        for k in range(nt):
            if side == "L":
                # static accumulation window: step k only reaches output
                # rows on the strict-plus-diagonal side of k
                if ascending:
                    lu = ctx_b.row_start(k)
                    sl = slice(lu, ctx_b.ltr)
                else:
                    lu, sl = 0, slice(0, min(ctx_b.ltr, k // ctx_b.P + 1))
                cnt = sl.stop - sl.start
                if cnt <= 0:
                    continue
                with obs.named_span(f"trmm.step{k:03d}.panel"):
                    bk = row_panel(ctx_b, ltb, k, 0)      # B[k,:] my cols
                    g = ctx_b.g_rows(lu, cnt)
                    if op == "N":
                        e = col_panel(ctx_a, lta, k, lu)[:cnt]  # A[i,k]
                    else:
                        # transpose-exchange windowed to the reachable tiles
                        # (g >= k ascending / g <= k descending)
                        if ascending:
                            lq = uniform_slot_start(k, ctx_a.Q)
                            rk = row_panel(ctx_a, lta, k, lq)
                        else:
                            lq = 0
                            rk = row_panel(ctx_a, lta, k, 0)[
                                :min(ctx_a.ltc,
                                     uniform_slot_start(k, ctx_a.Q) + 1)]
                        e = _tile_op(transpose_row_to_cols(ctx_a, rk, lq, g),
                                     op)
                    strict = (g > k) if eff_lower else (g < k)
                    e = _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag)
                with obs.named_span(f"trmm.step{k:03d}.bulk"):
                    upd = tb.contract("rab,cbd->rcad", e, bk)
                    out = out.at[sl].add(upd)
            else:
                if ascending:
                    lu = ctx_b.col_start(k)
                    sl = slice(lu, ctx_b.ltc)
                else:
                    lu, sl = 0, slice(0, min(ctx_b.ltc, k // ctx_b.Q + 1))
                cnt = sl.stop - sl.start
                if cnt <= 0:
                    continue
                with obs.named_span(f"trmm.step{k:03d}.panel"):
                    bk = col_panel(ctx_b, ltb, k, 0)      # B[:,k] my rows
                    g = ctx_b.g_cols(lu, cnt)
                    if op == "N":
                        e = row_panel(ctx_a, lta, k, lu)[:cnt]  # A[k,j]
                    else:
                        if ascending:
                            lq = uniform_slot_start(k, ctx_a.P)
                            ck = col_panel(ctx_a, lta, k, lq)
                        else:
                            lq = 0
                            ck = col_panel(ctx_a, lta, k, 0)[
                                :min(ctx_a.ltr,
                                     uniform_slot_start(k, ctx_a.P) + 1)]
                        e = _tile_op(transpose_col_to_rows(ctx_a, ck, lq, g),
                                     op)
                    strict = (g > k) if not eff_lower else (g < k)
                    e = _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag)
                with obs.named_span(f"trmm.step{k:03d}.bulk"):
                    upd = tb.contract("rab,cbd->rcad", bk, e)
                    out = out.at[:, sl].add(upd)
        return out

    def run(lta, ltb, alpha):
        return alpha * prog(lta, ltb)

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS), P()),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


def _build_dist_mult_scan(dist_a, dist_b, mesh, side, uplo, op, diag, dtype):
    """``lax.scan`` form of the distributed multiply, TELESCOPED over the
    triangular axis: step ``k`` only touches output slots on one side of
    the diagonal (``g >= k`` or ``g <= k`` depending on side/uplo/op), so
    each telescoped segment accumulates into just the still-reachable
    window of the output — the windows shrink (or start small and grow)
    exactly like the solve's. ``k`` always ascends (accumulation order is
    the unrolled one); the pivot panel of B spans its full orthogonal
    extent every step."""
    nt = dist_a.nr_tiles.row

    def prog(lta, ltb):
        ctx_a = DistContext(dist_a)
        ctx_b = DistContext(dist_b)
        eff_lower = (uplo == "L") == (op == "N")
        # does step k touch output slots g >= k (True) or g <= k (False)?
        ascending = eff_lower if side == "L" else not eff_lower
        p_out = ctx_b.P if side == "L" else ctx_b.Q
        lt_out = ctx_b.ltr if side == "L" else ctx_b.ltc
        q_orth = ctx_a.Q if side == "L" else ctx_a.P
        lt_orth = ctx_a.ltc if side == "L" else ctx_a.ltr

        def make_step(lu0, cnt, lq0, cnt_q):
            def step(sub, k):
                if side == "L":
                    bk = row_panel_dyn(ctx_b, ltb, k)
                    g = ctx_b.g_rows(lu0, cnt)
                    if op == "N":
                        e = col_panel_dyn(ctx_a, lta, k, lu=lu0, count=cnt)
                    else:
                        rk = row_panel_dyn(ctx_a, lta, k, lu=lq0,
                                           count=cnt_q)
                        e = _tile_op(
                            transpose_row_to_cols(ctx_a, rk, lq0, g), op)
                    strict = (g > k) if eff_lower else (g < k)
                    e = _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag)
                    return sub + tb.contract("rab,cbd->rcad", e, bk), None
                bk = col_panel_dyn(ctx_b, ltb, k)
                g = ctx_b.g_cols(lu0, cnt)
                if op == "N":
                    e = row_panel_dyn(ctx_a, lta, k, lu=lu0, count=cnt)
                else:
                    ck = col_panel_dyn(ctx_a, lta, k, lu=lq0, count=cnt_q)
                    e = _tile_op(
                        transpose_col_to_rows(ctx_a, ck, lq0, g), op)
                strict = (g > k) if not eff_lower else (g < k)
                e = _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag)
                return sub + tb.contract("rab,cbd->rcad", bk, e), None

            return step

        def window(pos, seg_len):
            if ascending:
                lo, loq = (uniform_slot_start(pos, p_out),
                           uniform_slot_start(pos, q_orth))
                win = (lo, lt_out - lo)
                winq = (loq, lt_orth - loq)
            else:
                k_hi = pos + seg_len - 1
                win = (0, min(lt_out, uniform_slot_start(k_hi, p_out) + 1))
                winq = (0, min(lt_orth,
                               uniform_slot_start(k_hi, q_orth) + 1))
            return (win, winq if op != "N" else (0, lt_orth))

        out = jnp.zeros_like(ltb)
        for ((lu0, cnt), (lq0, cnt_q)), k0s, seg_len in \
                telescope_windows(nt, window):
            sub = jax.lax.slice_in_dim(out, lu0, lu0 + cnt,
                                       axis=0 if side == "L" else 1)
            sub, _ = jax.lax.scan(make_step(lu0, cnt, lq0, cnt_q), sub,
                                  jnp.arange(k0s, k0s + seg_len))
            if side == "L":
                out = out.at[lu0:lu0 + cnt].set(sub)
            else:
                out = out.at[:, lu0:lu0 + cnt].set(sub)
        return out

    def run(lta, ltb, alpha):
        return alpha * prog(lta, ltb)

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS), P()),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


def _unit_diag(t, diag):
    if diag != "U":
        return t
    n = t.shape[-1]
    d = jnp.diagonal(t, axis1=-2, axis2=-1)
    return t - d[..., None] * jnp.eye(n, dtype=t.dtype) + jnp.eye(n, dtype=t.dtype)


# ---------------------------------------------------------------------------
# Public API (reference solver/triangular.h, multiplication/triangular.h)
# ---------------------------------------------------------------------------

@register_program_cache
@functools.lru_cache(maxsize=128)
def _dist_solve_cached(dist_a, dist_b, mesh, side, uplo, op, diag, dtype,
                       scan=False, donate_b=False, lookahead=False,
                       comm_la=False, panel_fused=False,
                       panel_interpret=False, route=()):
    # ``route``: the active autotune route's cache-key component
    # (docs/autotune.md) — the builders read the routed knobs
    # (trsm_panel's mixed/native split, _oz_slices) at trace time, so a
    # route change must be a different compiled program
    if scan:
        built = _build_dist_solve_scan(dist_a, dist_b, mesh, side, uplo, op,
                                       diag, dtype, lookahead=lookahead,
                                       comm_la=comm_la,
                                       panel_fused=panel_fused,
                                       panel_interpret=panel_interpret)
    else:
        built = _build_dist_solve(dist_a, dist_b, mesh, side, uplo, op,
                                  diag, dtype, panel_fused=panel_fused,
                                  panel_interpret=panel_interpret)
    return jax.jit(built, **donate_argnums_kw(donate_b, 1))


@register_program_cache
@functools.lru_cache(maxsize=128)
def _dist_mult_cached(dist_a, dist_b, mesh, side, uplo, op, diag, dtype,
                      scan=False):
    build = _build_dist_mult_scan if scan else _build_dist_mult
    return jax.jit(build(dist_a, dist_b, mesh, side, uplo, op, diag, dtype))


def _check_args(side, a: Matrix, b: Matrix):
    dlaf_assert(a.size.row == a.size.col, "triangular: A must be square")
    need = b.size.row if side == "L" else b.size.col
    dlaf_assert(a.size.row == need, f"triangular: A size {a.size} vs B {b.size}")
    dlaf_assert(a.block_size.row == a.block_size.col, "A block must be square")
    k = b.block_size.row if side == "L" else b.block_size.col
    dlaf_assert(a.block_size.row == k, "A/B block sizes must agree")


def triangular_solve(side: str, uplo: str, op: str, diag: str, alpha,
                     a: Matrix, b: Matrix, *, donate_b: bool = False,
                     with_info: bool = False):
    """``X: op(A) X = alpha B`` (side='L') or ``X op(A) = alpha B`` ('R');
    all 8 combos, local + distributed (reference ``solver::triangular``).

    Under ``DLAF_AUTOTUNE`` (docs/autotune.md) the distributed pivot
    chain's precision route (``f64_trsm`` / ``f64_gemm_slices`` /
    ``panel_impl``) is selected from the route table for this
    (n-bucket, nb, dtype, platform) site — op key ``trsm`` — and the
    solve's Hutchinson residual probe feeds the table back when ``b``
    survives the call (``donate_b=False``); see :func:`_triangular_solve`
    for the solve semantics proper.
    """
    from .. import autotune

    steer = autotune.steering_for_matrix("trsm", a)
    if steer is None:
        return _triangular_solve(side, uplo, op, diag, alpha, a, b,
                                 donate_b=donate_b, with_info=with_info)
    with steer.applied():
        out = _triangular_solve(side, uplo, op, diag, alpha, a, b,
                                donate_b=donate_b, with_info=with_info,
                                route=steer.route.key())
    if not donate_b and steer.probe_due:
        res = out[0] if with_info else out
        steer.observe(
            obs.accuracy.trsm_residual(side, uplo, op, diag, alpha,
                                       a, b, res),
            c=60.0, of=res.storage,
            attrs={"entry": "triangular_solve",
                   "combo": f"{side}{uplo}{op}{diag}"})
    return out


def _triangular_solve(side: str, uplo: str, op: str, diag: str, alpha,
                      a: Matrix, b: Matrix, *, donate_b: bool = False,
                      with_info: bool = False, route: tuple = ()):
    """``X: op(A) X = alpha B`` (side='L') or ``X op(A) = alpha B`` ('R');
    all 8 combos, local + distributed (reference ``solver::triangular``).

    ``donate_b=True`` donates ``b``'s device storage (the reference solves
    in place into ``mat_b``, ``solver/triangular/impl.h``); ``b`` must not
    be used afterwards. Internal stage hand-offs are always donated.

    ``with_info=True`` returns ``(X, info)`` — the singular-diagonal
    detection analogous to ``cholesky``'s info: an int32 device scalar, 0
    when every diagonal entry of ``A`` is finite and nonzero, else the
    1-based first singular global column (a zero/non-finite triangular
    diagonal makes the solve blow up silently). Computed in-graph from
    ``A``'s stored diagonal (health.matrix_diag_info) with no host sync;
    ``diag='U'`` (implicit unit diagonal) is never singular, so info is
    the constant 0 there."""
    _check_args(side, a, b)
    info = None
    if with_info:
        from ..health import matrix_diag_info

        info = (jnp.zeros((), jnp.int32) if diag == "U"
                else matrix_diag_info(a, singular=True))
    # reference flop model (miniapp_triangular_solver): m n^2/2 muls+adds
    # on the solve dimension n = A's order, free dimension the other
    sdim = a.size.row
    free = b.size.col if side == "L" else b.size.row
    # fused panel route applies to the DISTRIBUTED pivot-diag chain only
    # (the local solve is one whole-matrix op — no per-step panel chain);
    # resolved once here so the entry span and the builders agree
    dist_run = not (a.grid is None or a.grid.num_devices == 1)
    panel_fused = dist_run and ppan.panel_uses_fused(np.dtype(a.dtype),
                                                     a.block_size.row)
    entry_span = obs.entry_span("triangular_solve", lambda: dict(
        flops=total_ops(np.dtype(b.dtype), free * sdim**2 / 2,
                        free * sdim**2 / 2),
        side=side, uplo=uplo, op=op, diag=diag, m=b.size.row,
        n=b.size.col, nb=b.block_size.row, dtype=np.dtype(b.dtype).name,
        panel_impl="fused" if panel_fused else "xla",
        **({"autotune_route": dict(route)} if route else {}),
        grid=f"{b.dist.grid_size.row}x{b.dist.grid_size.col}"))
    if not dist_run:
        with entry_span, quiet_donation():
            bm = to_global(b.storage, b.dist, donate_b)
            am = tiles_to_global(a.storage, a.dist)
            out = _solve_local(am, bm, jnp.asarray(alpha, bm.dtype),
                               side=side, uplo=uplo, op=op, diag=diag)
            res = b.with_storage(global_to_tiles_donated(out, b.dist))
            return (res, info) if with_info else res
    # the distributed builders combine A's per-slot panels with B's slots
    # on the swept axis — misalignment corrupts silently, so contract it
    assert_slot_aligned(a.dist, b.dist, rows=side == "L", cols=side == "R",
                        what="triangular_solve(A, B)")
    from ..config import (resolve_step_mode, resolved_cholesky_lookahead,
                          resolved_comm_lookahead)

    scan_mode = resolve_step_mode(a.dist.nr_tiles.row) == "scan"
    # the pipelined scan body (same knob as the Cholesky look-ahead;
    # docs/lookahead.md); comm_lookahead additionally hoists the A-panel
    # collectives ahead of the deferred bulk (docs/comm_overlap.md)
    la = scan_mode and resolved_cholesky_lookahead()
    # pivot-diag chain on the fused Pallas route when panel_impl says so
    # (docs/pallas_panel.md); panel_fused resolved above, a cache-key arg
    platform = next(iter(a.grid.mesh.devices.flat)).platform
    fn = _dist_solve_cached(a.dist, b.dist, a.grid.mesh, side, uplo, op, diag,
                            np.dtype(a.dtype).name,
                            scan=scan_mode, donate_b=donate_b,
                            lookahead=la,
                            comm_la=la and resolved_comm_lookahead(),
                            panel_fused=panel_fused,
                            panel_interpret=panel_fused
                            and platform != "tpu", route=route)
    with entry_span, quiet_donation():
        # program telemetry (DLAF_PROGRAM_TELEMETRY): off = passthrough
        res = b.with_storage(obs.telemetry.call(
            "triangular_solve.dist", fn, a.storage, b.storage,
            jnp.asarray(alpha, b.dtype)))
        return (res, info) if with_info else res


def triangular_multiply(side: str, uplo: str, op: str, diag: str, alpha,
                        a: Matrix, b: Matrix) -> Matrix:
    """``B <- alpha op(A) B`` (side='L') or ``alpha B op(A)`` ('R');
    reference ``multiplication::triangular`` (8 local, LLN/LUN/RLN/RUN + the
    transposed forms distributed)."""
    _check_args(side, a, b)
    sdim = a.size.row
    free = b.size.col if side == "L" else b.size.row
    entry_span = obs.entry_span("triangular_multiply", lambda: dict(
        flops=total_ops(np.dtype(b.dtype), free * sdim**2 / 2,
                        free * sdim**2 / 2),
        side=side, uplo=uplo, op=op, diag=diag, m=b.size.row,
        n=b.size.col, nb=b.block_size.row, dtype=np.dtype(b.dtype).name,
        grid=f"{b.dist.grid_size.row}x{b.dist.grid_size.col}"))
    if a.grid is None or a.grid.num_devices == 1:
        with entry_span, quiet_donation():
            am = tiles_to_global(a.storage, a.dist)
            bm = tiles_to_global(b.storage, b.dist)
            out = _mult_local(am, bm, jnp.asarray(alpha, bm.dtype),
                              side=side, uplo=uplo, op=op, diag=diag)
            return b.with_storage(global_to_tiles_donated(out, b.dist))
    assert_slot_aligned(a.dist, b.dist, rows=side == "L", cols=side == "R",
                        what="triangular_multiply(A, B)")
    from ..config import resolve_step_mode

    fn = _dist_mult_cached(a.dist, b.dist, a.grid.mesh, side, uplo, op, diag,
                           np.dtype(a.dtype).name,
                           scan=resolve_step_mode(a.dist.nr_tiles.row)
                           == "scan")
    with entry_span:
        return b.with_storage(obs.telemetry.call(
            "triangular_multiply.dist", fn, a.storage, b.storage,
            jnp.asarray(alpha, b.dtype)))
