"""Triangular solve and triangular multiply — local and distributed.

TPU-native counterpart of the reference's ``solver/triangular``
(``solver/triangular/api.h:20-51``, ``impl.h``: all 8 Left/Right x Lower/Upper
x NoTrans/Trans combos, local + distributed) and ``multiplication/triangular``
(``multiplication/triangular/api.h:20-43``).

Local variants ARE one XLA op: ``TriangularSolve`` / masked matmul — XLA's
implementation is already the blocked substitution the reference hand-codes,
so the TPU-idiomatic "algorithm" is the direct lowering.

Distributed variants run the blocked substitution/accumulation over tile
rows/columns inside shard_map, using the panel-exchange helpers
(:mod:`dlaf_tpu.matrix.panel`): the diagonal tile travels with two mask+psum
hops, row/column panels with one, transposed selections with an all_gather —
and the per-``k`` trailing update is one batched einsum (dense rectangle, so
unlike Cholesky there is no triangle waste).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..config import register_program_cache
from ..common.asserts import dlaf_assert
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..matrix.distribution import assert_slot_aligned
from ..matrix.matrix import Matrix
from ..matrix.panel import (DistContext, bcast_diag, bcast_diag_dyn, col_panel,
                            col_panel_dyn, pad_diag_identity,
                            pad_diag_identity_dyn, row_panel, row_panel_dyn,
                            transpose_col_to_rows, transpose_row_to_cols)
from ..matrix.tiling import global_to_tiles, tiles_to_global
from ..tile_ops import blas as tb


def _tile_op(t, op: str):
    if op == "N":
        return t
    x = jnp.swapaxes(t, -1, -2)
    return jnp.conj(x) if op == "C" else x


# ---------------------------------------------------------------------------
# Local: direct XLA lowering
# ---------------------------------------------------------------------------

@register_program_cache
@functools.partial(jax.jit, static_argnames=("side", "uplo", "op", "diag"))
def _solve_local(a, b, alpha, *, side, uplo, op, diag):
    return tb.trsm(side, uplo, op, diag, a, b, alpha=alpha)


@register_program_cache
@functools.partial(jax.jit, static_argnames=("side", "uplo", "op", "diag"))
def _mult_local(a, b, alpha, *, side, uplo, op, diag):
    return tb.trmm(side, uplo, op, diag, a, b, alpha=alpha)


# ---------------------------------------------------------------------------
# Distributed substitution (solve) — reference solver/triangular/impl.h
# ---------------------------------------------------------------------------

def _build_dist_solve(dist_a, dist_b, mesh, side, uplo, op, diag, dtype):
    nt = dist_a.nr_tiles.row
    n = dist_a.size.row
    mb = dist_a.block_size.row

    def prog(lta, ltb):
        ctx_a = DistContext(dist_a)
        ctx_b = DistContext(dist_b)
        eff_lower = (uplo == "L") == (op == "N")
        if side == "L":
            forward = eff_lower
        else:
            forward = not eff_lower
        order = range(nt) if forward else range(nt - 1, -1, -1)
        for k in order:
            akk = bcast_diag(ctx_a, lta, k)
            if k == nt - 1:  # short edge tile: keep the solve nonsingular
                akk = pad_diag_identity(akk, min(mb, n - k * mb))
            if side == "L":
                # solve op(Akk) Xk = Bk for tile row k of B (all local cols)
                bk = row_panel(ctx_b, ltb, k, 0)
                xk = tb.trsm_panel("L", uplo, op, diag, akk, bk)
                own = ctx_b.rank_r == ctx_b.owner_r(k)
                row = ctx_b.kr(k)
                ltb = ltb.at[row].set(jnp.where(own, xk, ltb[row]))
                # remaining rows i: B[i,:] -= E[i,k] @ Xk
                if forward:
                    lu = ctx_b.row_start(k + 1)
                    sl = slice(lu, ctx_b.ltr)
                else:
                    lu = 0
                    sl = slice(0, min(ctx_b.ltr, (k - 1) // ctx_b.P + 1) if k else 0)
                count = sl.stop - sl.start if sl.stop is not None else 0
                if count <= 0:
                    continue
                g = ctx_b.g_rows(lu, count)
                rem = (g > k) if forward else (g < k)
                rem = rem & (g < nt)
                if op == "N":
                    e = col_panel(ctx_a, lta, k, lu)[:count]  # A[i,k] my rows
                else:
                    rk = row_panel(ctx_a, lta, k, 0)      # A[k,j] my cols
                    e = _tile_op(transpose_row_to_cols(ctx_a, rk, 0, g), op)
                e = jnp.where(rem[:, None, None], e, jnp.zeros_like(e))
                upd = tb.contract("rab,cbd->rcad", e, xk)
                ltb = ltb.at[sl].add(-upd)
            else:
                # solve Xk op(Akk) = Bk for tile col k of B (all local rows)
                bk = col_panel(ctx_b, ltb, k, 0)
                xk = tb.trsm_panel("R", uplo, op, diag, akk, bk)
                own = ctx_b.rank_c == ctx_b.owner_c(k)
                col = ctx_b.kc(k)
                ltb = ltb.at[:, col].set(jnp.where(own, xk, ltb[:, col]))
                if forward:
                    lu = ctx_b.col_start(k + 1)
                    sl = slice(lu, ctx_b.ltc)
                else:
                    lu = 0
                    sl = slice(0, min(ctx_b.ltc, (k - 1) // ctx_b.Q + 1) if k else 0)
                count = sl.stop - sl.start
                if count <= 0:
                    continue
                g = ctx_b.g_cols(lu, count)
                rem = (g > k) if forward else (g < k)
                rem = rem & (g < nt)
                if op == "N":
                    e = row_panel(ctx_a, lta, k, 0)[lu: lu + count]  # A[k,j]
                else:
                    ck = col_panel(ctx_a, lta, k, 0)      # A[i,k] my rows
                    e = _tile_op(transpose_col_to_rows(ctx_a, ck, 0, g), op)
                e = jnp.where(rem[:, None, None], e, jnp.zeros_like(e))
                upd = tb.contract("rab,cbd->rcad", xk, e)
                ltb = ltb.at[:, sl].add(-upd)
        return ltb

    def run(lta, ltb, alpha):
        return prog(lta, alpha * ltb)

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS), P()),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


def _build_dist_solve_scan(dist_a, dist_b, mesh, side, uplo, op, diag, dtype):
    """``lax.scan`` form of the distributed solve (config
    ``dist_step_mode="scan"``): one compiled step body looped ``nt`` times
    — the same O(1)-compile / uniform-masked-shapes trade as the scan
    Cholesky (see ``cholesky._build_dist_cholesky_scan`` and
    docs/DESIGN.md). Per-``k`` index math is traced arithmetic; pivot
    row/column access uses dynamic slices; the trailing update covers all
    local slots under a traced remaining-tiles mask."""
    nt = dist_a.nr_tiles.row
    n = dist_a.size.row
    mb = dist_a.block_size.row

    def prog(lta, ltb):
        ctx_a = DistContext(dist_a)
        ctx_b = DistContext(dist_b)
        eff_lower = (uplo == "L") == (op == "N")
        forward = eff_lower if side == "L" else not eff_lower

        def step(ltb, i):
            k = i if forward else nt - 1 - i
            akk = bcast_diag_dyn(ctx_a, lta, k)
            akk = pad_diag_identity_dyn(akk, jnp.minimum(mb, n - k * mb))
            if side == "L":
                bk = row_panel_dyn(ctx_b, ltb, k)
                xk = tb.trsm_panel("L", uplo, op, diag, akk, bk)
                own = ctx_b.rank_r == ctx_b.owner_r(k)
                row = ctx_b.kr(k)
                cur = jax.lax.dynamic_slice(
                    ltb, (row, 0, 0, 0), (1,) + ltb.shape[1:])[0]
                ltb = jax.lax.dynamic_update_slice(
                    ltb, jnp.where(own, xk, cur)[None], (row, 0, 0, 0))
                g = ctx_b.g_rows(0, ctx_b.ltr)
                rem = ((g > k) if forward else (g < k)) & (g < nt)
                if op == "N":
                    e = col_panel_dyn(ctx_a, lta, k)
                else:
                    rk = row_panel_dyn(ctx_a, lta, k)
                    e = _tile_op(transpose_row_to_cols(ctx_a, rk, 0, g), op)
                e = jnp.where(rem[:, None, None], e, jnp.zeros_like(e))
                upd = tb.contract("rab,cbd->rcad", e, xk)
                return ltb - upd, None
            bk = col_panel_dyn(ctx_b, ltb, k)
            xk = tb.trsm_panel("R", uplo, op, diag, akk, bk)
            own = ctx_b.rank_c == ctx_b.owner_c(k)
            col = ctx_b.kc(k)
            cur = jax.lax.dynamic_slice(
                ltb, (0, col, 0, 0),
                (ltb.shape[0], 1) + ltb.shape[2:])[:, 0]
            ltb = jax.lax.dynamic_update_slice(
                ltb, jnp.where(own, xk, cur)[:, None], (0, col, 0, 0))
            g = ctx_b.g_cols(0, ctx_b.ltc)
            rem = ((g > k) if forward else (g < k)) & (g < nt)
            if op == "N":
                e = row_panel_dyn(ctx_a, lta, k)
            else:
                ck = col_panel_dyn(ctx_a, lta, k)
                e = _tile_op(transpose_col_to_rows(ctx_a, ck, 0, g), op)
            e = jnp.where(rem[:, None, None], e, jnp.zeros_like(e))
            upd = tb.contract("rab,cbd->rcad", xk, e)
            return ltb - upd, None

        ltb, _ = jax.lax.scan(step, ltb, jnp.arange(nt))
        return ltb

    def run(lta, ltb, alpha):
        return prog(lta, alpha * ltb)

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS), P()),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


# ---------------------------------------------------------------------------
# Distributed accumulation (multiply) — reference multiplication/triangular
# ---------------------------------------------------------------------------

def _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag):
    """Triangle masking of a pivot panel for the multiply builders: the
    diagonal slot gets the (unit-)triangle-masked tile, strict slots the
    full tile, everything else zero. ``strict``: boolean per-slot mask of
    the strictly-included side (direction already resolved by the
    caller's eff_lower/side logic)."""
    ondiag = (g == k)
    dt = tb.tri_mask(e, uplo if op == "N" else ("U" if uplo == "L" else "L"))
    dt = _unit_diag(dt, diag)
    return jnp.where(ondiag[:, None, None], dt,
                     jnp.where(strict[:, None, None] & (g < nt)[:, None, None],
                               e, jnp.zeros_like(e)))


def _build_dist_mult(dist_a, dist_b, mesh, side, uplo, op, diag, dtype):
    nt = dist_a.nr_tiles.row

    def prog(lta, ltb):
        ctx_a = DistContext(dist_a)
        ctx_b = DistContext(dist_b)
        eff_lower = (uplo == "L") == (op == "N")
        out = jnp.zeros_like(ltb)
        for k in range(nt):
            if side == "L":
                bk = row_panel(ctx_b, ltb, k, 0)          # B[k,:] my cols
                g = ctx_b.g_rows(0, ctx_b.ltr)
                if op == "N":
                    e = col_panel(ctx_a, lta, k, 0)       # A[i,k]
                else:
                    rk = row_panel(ctx_a, lta, k, 0)
                    e = _tile_op(transpose_row_to_cols(ctx_a, rk, 0, g), op)
                strict = (g > k) if eff_lower else (g < k)
                e = _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag)
                upd = tb.contract("rab,cbd->rcad", e, bk)
                out = out + upd
            else:
                bk = col_panel(ctx_b, ltb, k, 0)          # B[:,k] my rows
                g = ctx_b.g_cols(0, ctx_b.ltc)
                if op == "N":
                    e = row_panel(ctx_a, lta, k, 0)       # A[k,j]
                else:
                    ck = col_panel(ctx_a, lta, k, 0)
                    e = _tile_op(transpose_col_to_rows(ctx_a, ck, 0, g), op)
                strict = (g > k) if not eff_lower else (g < k)
                e = _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag)
                upd = tb.contract("rab,cbd->rcad", bk, e)
                out = out + upd
        return out

    def run(lta, ltb, alpha):
        return alpha * prog(lta, ltb)

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS), P()),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


def _build_dist_mult_scan(dist_a, dist_b, mesh, side, uplo, op, diag, dtype):
    """``lax.scan`` form of the distributed multiply: the unrolled body is
    already uniform-shaped (no slot shrink), so the scan version only
    swaps the pivot panel reads for their traced-``k`` dynamic forms and
    carries the accumulator — O(1) compile, identical flops."""
    nt = dist_a.nr_tiles.row

    def prog(lta, ltb):
        ctx_a = DistContext(dist_a)
        ctx_b = DistContext(dist_b)
        eff_lower = (uplo == "L") == (op == "N")

        def step(out, k):
            if side == "L":
                bk = row_panel_dyn(ctx_b, ltb, k)
                g = ctx_b.g_rows(0, ctx_b.ltr)
                if op == "N":
                    e = col_panel_dyn(ctx_a, lta, k)
                else:
                    rk = row_panel_dyn(ctx_a, lta, k)
                    e = _tile_op(transpose_row_to_cols(ctx_a, rk, 0, g), op)
                strict = (g > k) if eff_lower else (g < k)
                e = _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag)
                return out + tb.contract("rab,cbd->rcad", e, bk), None
            bk = col_panel_dyn(ctx_b, ltb, k)
            g = ctx_b.g_cols(0, ctx_b.ltc)
            if op == "N":
                e = row_panel_dyn(ctx_a, lta, k)
            else:
                ck = col_panel_dyn(ctx_a, lta, k)
                e = _tile_op(transpose_col_to_rows(ctx_a, ck, 0, g), op)
            strict = (g > k) if not eff_lower else (g < k)
            e = _mask_tri_panel(e, g, k, nt, strict, uplo, op, diag)
            return out + tb.contract("rab,cbd->rcad", bk, e), None

        out, _ = jax.lax.scan(step, jnp.zeros_like(ltb), jnp.arange(nt))
        return out

    def run(lta, ltb, alpha):
        return alpha * prog(lta, ltb)

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS), P()),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


def _unit_diag(t, diag):
    if diag != "U":
        return t
    n = t.shape[-1]
    d = jnp.diagonal(t, axis1=-2, axis2=-1)
    return t - d[..., None] * jnp.eye(n, dtype=t.dtype) + jnp.eye(n, dtype=t.dtype)


# ---------------------------------------------------------------------------
# Public API (reference solver/triangular.h, multiplication/triangular.h)
# ---------------------------------------------------------------------------

@register_program_cache
@functools.lru_cache(maxsize=128)
def _dist_solve_cached(dist_a, dist_b, mesh, side, uplo, op, diag, dtype,
                       scan=False):
    build = _build_dist_solve_scan if scan else _build_dist_solve
    return jax.jit(build(dist_a, dist_b, mesh, side, uplo, op, diag, dtype))


@register_program_cache
@functools.lru_cache(maxsize=128)
def _dist_mult_cached(dist_a, dist_b, mesh, side, uplo, op, diag, dtype,
                      scan=False):
    build = _build_dist_mult_scan if scan else _build_dist_mult
    return jax.jit(build(dist_a, dist_b, mesh, side, uplo, op, diag, dtype))


def _check_args(side, a: Matrix, b: Matrix):
    dlaf_assert(a.size.row == a.size.col, "triangular: A must be square")
    need = b.size.row if side == "L" else b.size.col
    dlaf_assert(a.size.row == need, f"triangular: A size {a.size} vs B {b.size}")
    dlaf_assert(a.block_size.row == a.block_size.col, "A block must be square")
    k = b.block_size.row if side == "L" else b.block_size.col
    dlaf_assert(a.block_size.row == k, "A/B block sizes must agree")


def triangular_solve(side: str, uplo: str, op: str, diag: str, alpha,
                     a: Matrix, b: Matrix) -> Matrix:
    """``X: op(A) X = alpha B`` (side='L') or ``X op(A) = alpha B`` ('R');
    all 8 combos, local + distributed (reference ``solver::triangular``)."""
    _check_args(side, a, b)
    if a.grid is None or a.grid.num_devices == 1:
        am = tiles_to_global(a.storage, a.dist)
        bm = tiles_to_global(b.storage, b.dist)
        out = _solve_local(am, bm, jnp.asarray(alpha, bm.dtype),
                           side=side, uplo=uplo, op=op, diag=diag)
        return b.with_storage(global_to_tiles(out, b.dist))
    # the distributed builders combine A's per-slot panels with B's slots
    # on the swept axis — misalignment corrupts silently, so contract it
    assert_slot_aligned(a.dist, b.dist, rows=side == "L", cols=side == "R",
                        what="triangular_solve(A, B)")
    from ..config import resolve_step_mode

    fn = _dist_solve_cached(a.dist, b.dist, a.grid.mesh, side, uplo, op, diag,
                            np.dtype(a.dtype).name,
                            scan=resolve_step_mode(a.dist.nr_tiles.row)
                            == "scan")
    return b.with_storage(fn(a.storage, b.storage, jnp.asarray(alpha, b.dtype)))


def triangular_multiply(side: str, uplo: str, op: str, diag: str, alpha,
                        a: Matrix, b: Matrix) -> Matrix:
    """``B <- alpha op(A) B`` (side='L') or ``alpha B op(A)`` ('R');
    reference ``multiplication::triangular`` (8 local, LLN/LUN/RLN/RUN + the
    transposed forms distributed)."""
    _check_args(side, a, b)
    if a.grid is None or a.grid.num_devices == 1:
        am = tiles_to_global(a.storage, a.dist)
        bm = tiles_to_global(b.storage, b.dist)
        out = _mult_local(am, bm, jnp.asarray(alpha, bm.dtype),
                          side=side, uplo=uplo, op=op, diag=diag)
        return b.with_storage(global_to_tiles(out, b.dist))
    assert_slot_aligned(a.dist, b.dist, rows=side == "L", cols=side == "R",
                        what="triangular_multiply(A, B)")
    from ..config import resolve_step_mode

    fn = _dist_mult_cached(a.dist, b.dist, a.grid.mesh, side, uplo, op, diag,
                           np.dtype(a.dtype).name,
                           scan=resolve_step_mode(a.dist.nr_tiles.row)
                           == "scan")
    return b.with_storage(fn(a.storage, b.storage, jnp.asarray(alpha, b.dtype)))
