"""Max-norm of a (triangular part of a) distributed matrix.

TPU-native counterpart of the reference's ``auxiliary::norm``
(``auxiliary/norm/mc.h:29-108``): per-tile ``lange``/``lantr`` partial maxima
folded locally, then reduced across ranks (the reference uses a blocking
``sync::reduce(MPI_MAX)`` to a target rank; here a ``pmax`` over both mesh
axes — every rank gets the result, which XLA DCEs where unused).

Supports norm='M' (max absolute value) over uplo 'L' (lower triangle,
Hermitian use-case) or 'G' (whole matrix), matching the reference's scope.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..config import register_program_cache
from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..matrix.matrix import Matrix
from ..matrix.tiling import storage_tile_grid, tiles_to_global


def _build_dist_norm(dist, mesh, uplo: str):
    nt = dist.nr_tiles
    mb, nb = dist.block_size.row, dist.block_size.col
    Pr, Qc = dist.grid_size.row, dist.grid_size.col
    sr, sc = dist.source_rank.row, dist.source_rank.col
    _, _, ltr, ltc = storage_tile_grid(dist)

    def local_norm(lt):
        rr = (cc.this_rank(ROW_AXIS) - sr) % Pr
        rc = (cc.this_rank(COL_AXIS) - sc) % Qc
        g_rows = jnp.arange(ltr) * Pr + rr          # global tile rows
        g_cols = jnp.arange(ltc) * Qc + rc
        valid = (g_rows[:, None] < nt.row) & (g_cols[None, :] < nt.col)
        if uplo == "L":
            keep_full = valid & (g_rows[:, None] > g_cols[None, :])
            keep_diag = valid & (g_rows[:, None] == g_cols[None, :])
            tril_m = jnp.tril(jnp.ones((mb, nb), dtype=bool))
            mask = (keep_full[:, :, None, None]
                    | (keep_diag[:, :, None, None] & tril_m))
        else:
            mask = valid[:, :, None, None]
        vals = jnp.where(mask, jnp.abs(lt), 0)
        m = jnp.max(vals) if lt.size else jnp.zeros((), vals.dtype)
        m = cc.all_reduce(m, ROW_AXIS, "max")
        m = cc.all_reduce(m, COL_AXIS, "max")
        return m.reshape(1, 1)

    return shard_map(local_norm, mesh=mesh, in_specs=P(ROW_AXIS, COL_AXIS),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


@register_program_cache
@functools.lru_cache(maxsize=64)
def _dist_norm_cached(dist, mesh, uplo):
    return jax.jit(_build_dist_norm(dist, mesh, uplo))


def max_norm(mat: Matrix, uplo: str = "G") -> float:
    """Largest absolute element of ``mat`` (or its lower triangle)."""
    if mat.size.is_empty():
        return 0.0
    if mat.grid is None or mat.grid.num_devices == 1:
        a = tiles_to_global(mat.storage, mat.dist)
        if uplo == "L":
            a = jnp.tril(a)
        return float(jnp.max(jnp.abs(a)))
    out = _dist_norm_cached(mat.dist, mat.grid.mesh, uplo)(mat.storage)
    return float(np.asarray(out).max())
