"""Generalized-to-standard eigenproblem transform (HEGST).

TPU-native counterpart of the reference's ``eigensolver/gen_to_std``
(``gen_to_std/api.h:21-23``, ``impl.h:200-740``): given the Cholesky factor of
B, transform ``A x = lambda B x`` to standard form:

    uplo='L':  A <- inv(L) A inv(L)^H        (B = L L^H)
    uplo='U':  A <- inv(U^H) A inv(U)        (B = U^H U)

The reference hand-blocks the two-sided update (per-k ``hegst`` diag, panel
``trsm``+``hemm``, trailing ``her2k``/``gemm``) to exploit Hermitian symmetry.
The TPU-native formulation: Hermitianize A from its stored triangle, then
apply TWO whole-matrix triangular solves — each is a fully parallel blocked
substitution (local: one XLA TriangularSolve; distributed: the shard_map
substitution of :mod:`.triangular`). This trades the ~2x symmetry saving for
two perfectly MXU-shaped dense sweeps with no panel round-trips — the right
trade on a systolic array, and it reuses the verified solver path end to end.

Local + distributed, both uplos (reference parity: local L/U + distributed
L/U).
"""

from __future__ import annotations

from ..common.asserts import dlaf_assert
from ..matrix import ops as mops
from ..matrix.matrix import Matrix
from .triangular import triangular_solve


def gen_to_std(uplo: str, a: Matrix, b_factor: Matrix) -> Matrix:
    """Transform ``a`` (Hermitian, stored in ``uplo``) using ``b_factor`` =
    the Cholesky factor of B (same ``uplo``). Returns the transformed A with
    its opposite triangle passing through unchanged."""
    dlaf_assert(a.size == b_factor.size, "gen_to_std: A/B size mismatch")
    dlaf_assert(a.block_size == b_factor.block_size, "gen_to_std: block mismatch")
    ah = mops.hermitianize(a, uplo)
    if uplo == "L":
        x = triangular_solve("L", "L", "N", "N", 1.0, b_factor, ah)
        y = triangular_solve("R", "L", "C", "N", 1.0, b_factor, x)
    else:
        x = triangular_solve("L", "U", "C", "N", 1.0, b_factor, ah)
        y = triangular_solve("R", "U", "N", "N", 1.0, b_factor, x)
    return mops.merge_triangle(y, a, uplo)
