"""Generalized-to-standard eigenproblem transform (HEGST).

TPU-native counterpart of the reference's ``eigensolver/gen_to_std``
(``gen_to_std/api.h:21-23``, ``impl.h:200-740``): given the Cholesky factor of
B, transform ``A x = lambda B x`` to standard form:

    uplo='L':  A <- inv(L) A inv(L)^H        (B = L L^H)
    uplo='U':  A <- inv(U^H) A inv(U)        (B = U^H U)

Two formulations (config knob ``hegst_impl``):

* ``"blocked"`` (default) — the reference's flop discipline (~n^3 real ops):
  per-``k`` two-sided update — hegst on the diagonal block, panel trsm +
  two half-weight hemm's, her2k trailing update exploiting Hermitian
  symmetry, and the trailing triangular solve of the panel realized as
  DEFERRED incremental updates in BOTH forms: at each later step, the
  step's solved row/column fans one gemm into the remaining region — the
  reference's reshuffle ("the tasks of the final huge TRSM have been
  reshuffled to avoid extra communication of the matrix L",
  ``impl.h:330-335``). Distributed, each panel broadcast thereby serves
  the trailing update AND the pending solves of all previous panels;
  locally it keeps every unrolled step a small fixed op set instead of a
  per-step recursive whole-trailing trsm the AOT compile budget could
  not afford.

* ``"twosolve"`` — Hermitianize A, then TWO whole-matrix triangular solves
  (each a fully parallel blocked substitution). ~2x the flops, but two
  perfectly MXU-shaped dense sweeps with no panel round-trips and O(1)
  step count; kept as the fallback/cross-check and as the scan-mode
  route: a masked uniform-shape scan of the blocked form would pay the
  usual ~3x masked-work premium on its n^3 (~3n^3) — MORE than
  twosolve's 2n^3 dense flops — so at step counts where the compile
  hatch matters, twosolve IS the optimal scan-mode HEGST, not a
  placeholder (``dist_step_mode`` auto/scan routes here).

Local + distributed, both uplos (reference parity: local L/U + distributed
L/U, ``call_L``/``call_U``).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from .. import obs
from ..config import get_configuration, register_program_cache
from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..common.asserts import dlaf_assert
from ..matrix import ops as mops
from ..matrix import util_distribution as ud
from ..matrix.distribution import assert_slot_aligned
from ..matrix.matrix import Matrix
from ..matrix.panel import (DistContext, transpose_col_to_rows,
                            transpose_row_to_cols)
from ..matrix.tiling import (storage_tile_grid, tiles_to_global,
                             global_to_tiles_donated,
                             quiet_donation, donate_argnums_kw)
from ..tile_ops import blas as tb
from ..tile_ops import mixed as mx
from ..tile_ops import pallas_panel as ppan
from ..tile_ops import ozaki as oz
from ..types import ceil_div
from .triangular import triangular_solve


def _gen_to_std_twosolve(uplo: str, a: Matrix, b_factor: Matrix,
                         donate: bool = False) -> Matrix:
    """Two-whole-solve formulation (see module docstring). ``ah`` and ``x``
    are owned intermediates — each solve consumes its rhs, so at most two
    full matrices of this chain are live at once; ``donate`` additionally
    consumes ``a`` at the final triangle merge."""
    ah = mops.hermitianize(a, uplo)
    if uplo == "L":
        x = triangular_solve("L", "L", "N", "N", 1.0, b_factor, ah,
                             donate_b=True)
        y = triangular_solve("R", "L", "C", "N", 1.0, b_factor, x,
                             donate_b=True)
    else:
        x = triangular_solve("L", "U", "C", "N", 1.0, b_factor, ah,
                             donate_b=True)
        y = triangular_solve("R", "U", "N", "N", 1.0, b_factor, x,
                             donate_b=True)
    return mops.merge_triangle(y, a, uplo, donate_orig=donate)


# ---------------------------------------------------------------------------
# Local blocked form (reference impl.h:169-266 call_L / call_U local)
# ---------------------------------------------------------------------------

def _hegst_diag(uplo: str, akk, lkk, inv=None, fused=False,
                interpret=False):
    """Transformed diagonal block, full Hermitian form: W = inv(L) herm(Akk)
    inv(L)^H (uplo='L') / inv(U^H) herm(Akk) inv(U) (uplo='U'). The two
    block-size solves follow the f64_trsm knob via trsm_panel — or, under
    ``panel_impl="fused"`` (``fused=True``, docs/pallas_panel.md), the
    fused Pallas panel-solve kernels; ``inv`` is the optional precomputed
    refined inverse of ``lkk``'s triangle, shared with the step's panel
    solve so the mixed route derives it ONCE."""
    ah = tb.hermitian_from(akk, uplo)
    if uplo == "L":
        w = ppan.panel_solve("L", "L", "N", "N", lkk, ah, inv_a=inv,
                             fused=fused, interpret=interpret)
        w = ppan.panel_solve("R", "L", "C", "N", lkk, w, inv_a=inv,
                             fused=fused, interpret=interpret)
    else:
        w = ppan.panel_solve("L", "U", "C", "N", lkk, ah, inv_a=inv,
                             fused=fused, interpret=interpret)
        w = ppan.panel_solve("R", "U", "N", "N", lkk, w, inv_a=inv,
                             fused=fused, interpret=interpret)
    # the algorithm reads W as Hermitian-stored from its uplo triangle (the
    # reference's hemmPanelTile does the same with the written tile)
    return tb.hermitian_from(w, uplo)


def _step_inv(uplo: str, lkk):
    """Refined triangle inverse for one step's solves, or None when the
    config routes trsm_panel natively."""
    if tb.trsm_panel_uses_mixed(lkk.dtype):
        return mx.tri_inv_refined(tb.tri_mask(lkk, uplo),
                                  lower=(uplo == "L"))
    return None


@register_program_cache
# both operands are the entry point's freshly built global-layout copies
# (the caller's matrices are re-read only at the final triangle merge)
@functools.partial(jax.jit, static_argnames=("uplo", "nb", "lookahead",
                                             "panel_fused",
                                             "panel_interpret", "route"),
                   donate_argnums=(0, 1))
def _hegst_local_blocked(a, l, *, uplo: str, nb: int, lookahead: bool = False,
                         panel_fused: bool = False,
                         panel_interpret: bool = False, route: tuple = ()):
    """Unrolled blocked two-sided transform on the global 2D array.

    Per step (uplo='L', LAPACK xHEGST itype=1 structure, which the
    reference's tile loop realizes — ``impl.h:207-264``):
    deferred-solve update of all PREVIOUS panel columns (row k solved
    with Lkk, one gemm fans it into the rows below — the same
    incremental realization of the trailing inv(L22) solve as the
    distributed builder, so each step is a small fixed op set instead
    of a per-step recursive whole-trailing trsm whose unrolled program
    would dwarf the AOT compile budget); diag hegst; P <- P inv(Lkk)^H;
    P -= 1/2 L21 W; A22 -= P L21^H + L21 P^H (her2k, one gemm +
    transpose here); P -= 1/2 L21 W. uplo='U' is the mirrored row-panel
    sweep. Exact slice shapes per step; the opposite triangle of ``a``
    passes through untouched (merged by the caller).
    """
    n = a.shape[0]
    nt = ceil_div(n, nb)
    # lookahead carry (next diag block, next panel source) — the same
    # next-panel-column-first her2k split as the pipelined Cholesky
    # (docs/lookahead.md): step k+1's hegst-diag solves and panel trsm
    # consume step k's strip values directly instead of reading `a` after
    # the bulk her2k scatter
    la = None
    for k in range(nt):
        k0, k1 = k * nb, min((k + 1) * nb, n)
        lkk = l[k0:k1, k0:k1]
        lkk_inv = _step_inv(uplo, lkk)
        if uplo == "L":
            if k0 > 0:
                # deferred trailing-solve: row k of every previous panel
                # column, then one gemm into the rows below
                rowk = tb.trsm_panel("L", "L", "N", "N", lkk,
                                     a[k0:k1, :k0], inv_a=lkk_inv)
                a = a.at[k0:k1, :k0].set(rowk)
                if k1 < n:
                    a = a.at[k1:, :k0].add(-tb.gemm(l[k1:, k0:k1], rowk))
            w = _hegst_diag(uplo, a[k0:k1, k0:k1] if la is None else la[0],
                            lkk, inv=lkk_inv, fused=panel_fused,
                            interpret=panel_interpret)
            a = a.at[k0:k1, k0:k1].set(w)
            if k1 == n:
                continue
            p = a[k1:, k0:k1] if la is None else la[1]
            l21 = l[k1:, k0:k1]
            p = ppan.panel_solve("R", "L", "C", "N", lkk, p, inv_a=lkk_inv,
                                 fused=panel_fused,
                                 interpret=panel_interpret)
            p = p - 0.5 * tb.gemm(l21, w)
            la = None
            if lookahead:
                # next block column of the her2k first (carried), rest as
                # a row-trimmed her2k of the remaining trailing block
                wn = min(nb, n - k1)
                mt = n - k1
                strip = tb.gemm(p, l21[:wn], op_b="C") \
                    + tb.gemm(l21, p[:wn], op_b="C")
                smask = jnp.arange(mt)[:, None] >= jnp.arange(wn)[None, :]
                new_col = a[k1:, k1:k1 + wn] - jnp.where(smask, strip, 0)
                a = a.at[k1:, k1:k1 + wn].set(new_col)
                la = (new_col[:wn], new_col[wn:])
                if mt > wn:
                    a = a.at[k1 + wn:, k1 + wn:].set(
                        tb.her2k("L", "N", p[wn:], l21[wn:],
                                 a[k1 + wn:, k1 + wn:], alpha=-1.0))
            else:
                a = a.at[k1:, k1:].set(
                    tb.her2k("L", "N", p, l21, a[k1:, k1:], alpha=-1.0))
            p = p - 0.5 * tb.gemm(l21, w)
            a = a.at[k1:, k0:k1].set(p)
        else:
            if k0 > 0:
                colk = tb.trsm_panel("R", "U", "N", "N", lkk,
                                     a[:k0, k0:k1], inv_a=lkk_inv)
                a = a.at[:k0, k0:k1].set(colk)
                if k1 < n:
                    a = a.at[:k0, k1:].add(-tb.gemm(colk, l[k0:k1, k1:]))
            w = _hegst_diag(uplo, a[k0:k1, k0:k1] if la is None else la[0],
                            lkk, inv=lkk_inv, fused=panel_fused,
                            interpret=panel_interpret)
            a = a.at[k0:k1, k0:k1].set(w)
            if k1 == n:
                continue
            p = a[k0:k1, k1:] if la is None else la[1]
            u12 = l[k0:k1, k1:]
            p = ppan.panel_solve("L", "U", "C", "N", lkk, p, inv_a=lkk_inv,
                                 fused=panel_fused,
                                 interpret=panel_interpret)
            p = p - 0.5 * tb.gemm(w, u12)
            la = None
            if lookahead:
                # mirrored: next block row of the her2k first (carried)
                wn = min(nb, n - k1)
                mt = n - k1
                strip = tb.gemm(p[:, :wn], u12, op_a="C") \
                    + tb.gemm(u12[:, :wn], p, op_a="C")
                smask = jnp.arange(wn)[:, None] <= jnp.arange(mt)[None, :]
                new_row = a[k1:k1 + wn, k1:] - jnp.where(smask, strip, 0)
                a = a.at[k1:k1 + wn, k1:].set(new_row)
                la = (new_row[:, :wn], new_row[:, wn:])
                if mt > wn:
                    a = a.at[k1 + wn:, k1 + wn:].set(
                        tb.her2k("U", "C", p[:, wn:], u12[:, wn:],
                                 a[k1 + wn:, k1 + wn:], alpha=-1.0))
            else:
                a = a.at[k1:, k1:].set(
                    tb.her2k("U", "C", p, u12, a[k1:, k1:], alpha=-1.0))
            p = p - 0.5 * tb.gemm(w, u12)
            a = a.at[k0:k1, k1:].set(p)
    return a


# ---------------------------------------------------------------------------
# Distributed blocked form (reference impl.h:268-740 call_L / call_U)
# ---------------------------------------------------------------------------

def _pair_product(x_tiles, y_tiles, cplx: bool, use_mxu: bool):
    """All-pairs tile product ``out[r, c] = x[r] @ conj(y[c])^T`` over two
    tile batches (the distributed gemm fan-out of one her2k term /
    deferred-solve sweep), optionally flattened through the int8/bf16 MXU
    path (``f64_gemm="mxu"``)."""
    if use_mxu:
        nr, mb = x_tiles.shape[0], x_tiles.shape[-2]
        nc = y_tiles.shape[0]
        mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
        full = mmfn(x_tiles.reshape(nr * mb, -1),
                    jnp.conj(y_tiles).reshape(nc * mb, -1).T,
                    slices=tb._oz_slices())
        return full.reshape(nr, mb, nc, mb).transpose(0, 2, 1, 3)
    return jnp.einsum("rab,cdb->rcad", x_tiles, jnp.conj(y_tiles),
                      preferred_element_type=x_tiles.dtype)


def _col_strip_product(x_tiles, y_tile, cplx: bool, use_mxu: bool):
    """``out[r] = x_tiles[r] @ conj(y_tile)^T`` — one tile COLUMN of the
    all-pairs product (the lookahead split's next-column strip), same
    route as :func:`_pair_product`."""
    if use_mxu:
        nr, mb = x_tiles.shape[0], x_tiles.shape[-2]
        mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
        return mmfn(x_tiles.reshape(nr * mb, -1), jnp.conj(y_tile).T,
                    slices=tb._oz_slices()).reshape(nr, mb, mb)
    return jnp.einsum("rab,db->rad", x_tiles, jnp.conj(y_tile),
                      preferred_element_type=x_tiles.dtype)


def _row_strip_product(x_tile, y_tiles, cplx: bool, use_mxu: bool):
    """``out[c] = x_tile @ conj(y_tiles[c])^T`` — one tile ROW of the
    all-pairs product (the mirrored uplo='U' strip)."""
    if use_mxu:
        nc, mb = y_tiles.shape[0], y_tiles.shape[-2]
        mmfn = oz.matmul_c128 if cplx else oz.matmul_f64
        full = mmfn(x_tile, jnp.conj(y_tiles).reshape(nc * mb, mb).T,
                    slices=tb._oz_slices())
        return full.reshape(mb, nc, mb).transpose(1, 0, 2)
    return jnp.einsum("ab,cdb->cad", x_tile, jnp.conj(y_tiles),
                      preferred_element_type=y_tiles.dtype)


def _build_dist_hegst(dist, mesh, uplo: str, use_mxu=False, cplx=False,
                      lookahead=False, comm_la=False, panel_fused=False,
                      panel_interpret=False):
    """shard_map'd blocked HEGST over the 2D mesh, k-loop unrolled.

    Per step k (uplo='L'): broadcast the L diag + col-panel (row-wise and
    transposed — the same panel machinery as the distributed Cholesky);
    FIRST apply the deferred trailing-solve contributions to all previous
    panel columns (row k: A_kj <- inv(L_kk) A_kj, then A_ij -= L_ik A_kj —
    the reference's reshuffled huge-TRSM, ``impl.h:327-372``); then hegst
    the diagonal block (redundantly on every rank, like the dist
    Cholesky's potrf), panel trsm + first half-hemm, broadcast the A
    panel, her2k trailing as two all-pairs tile products, second
    half-hemm. uplo='U' mirrors with row panels / the upper triangle.
    All index bounds are static per k; validity masks are the only traced
    rank-dependent values.

    Phased like the distributed Cholesky (``panel_chain`` / ``step_pre``
    / ``step_bulk``) so ``comm_la`` (``comm_lookahead=1``,
    docs/comm_overlap.md) can emit step k+1's panel chain — the L-panel
    broadcasts (constant operand!), the fused diag ``bcast2d``s, the
    A-panel broadcast and both transposed-panel all_gathers — BEFORE
    step k's bulk her2k product: the chain reads only ``ll`` and the
    carried post-strip values, never ``lt`` after the bulk scatter. The
    deferred-solve broadcast (``akj``/``ajk``) reads ``lt`` rows/cols
    behind the pivot and stays in its serial position — the documented
    exception (docs/comm_overlap.md). Phase order of ``lt`` mutations is
    identical in both modes, so results are bitwise the same with the
    knob on or off.
    """
    nt = dist.nr_tiles.row
    mb = dist.block_size.row
    n = dist.size.row
    Pr, Qc = dist.grid_size.row, dist.grid_size.col
    sr, sc = dist.source_rank.row, dist.source_rank.col
    _, _, ltr, ltc = storage_tile_grid(dist)

    def pad_lkk(lkk, k):
        ts = min(mb, n - k * mb)
        if ts < mb:  # identity pad keeps the edge-tile solves defined
            pad = jnp.arange(mb) >= ts
            lkk = jnp.where(pad[:, None] | pad[None, :], 0, lkk) \
                + jnp.diag(pad.astype(lkk.dtype))
        return lkk

    def _indices(k):
        owner_r = ud.rank_global_tile(k, Pr, sr)
        owner_c = ud.rank_global_tile(k, Qc, sc)
        kr = ud.local_tile_from_global_tile(k, Pr)
        kc = ud.local_tile_from_global_tile(k, Qc)
        lu_r = max(0, -(-(k + 2 - Pr) // Pr))
        lu_c = max(0, -(-(k + 2 - Qc) // Qc))
        return owner_r, owner_c, kr, kc, lu_r, lu_c

    # chain tuples: (lkk, lkk_inv, vpan_l, akk, w, pan, vb_a, vt_a, vt_l)
    # with vpan_l the broadcast L panel, vb_a the broadcast A panel and
    # vt_* the transposed panels; trailing entries None past the static
    # early-exit points (mirroring the serial step's early returns).

    def chain_L(lt, ll, k, la, rr, rc):
        owner_r, owner_c, kr, kc, lu_r, lu_c = _indices(k)
        is_owner_c = cc.this_rank(COL_AXIS) == owner_c

        # -- L diag -> everyone (one fused 2D collective; constant ll) ----
        lkk = pad_lkk(cc.bcast2d(ll[kr, kc], owner_r, owner_c), k)
        # lkk is already triangular: refined inverse computed ONCE per
        # step, shared by the prev-panel solve, diag hegst and panel trsm
        lkk_inv = _step_inv("L", lkk)

        # -- L col-panel (rows > k) row-broadcast (constant ll) -----------
        nrows = ltr - lu_r
        g_rows = (lu_r + jnp.arange(max(nrows, 1))) * Pr + rr
        row_valid = (g_rows > k) & (g_rows < nt)
        vr_l = None
        if nrows > 0:
            vr_l = cc.bcast(jnp.where((is_owner_c & row_valid)[:, None, None],
                                      ll[lu_r:, kc], 0), COL_AXIS, owner_c)
            vr_l = jnp.where(row_valid[:, None, None], vr_l, 0)

        # -- diag hegst (redundant on every rank) -------------------------
        # lookahead carry (next-column strip of step k-1,
        # docs/lookahead.md): the hegst-diag chain consumes it directly —
        # correct on the owner (the only contributor bcast/keep select)
        cand = lt[kr, kc] if la is None else la[0][kr - la[1]]
        akk = cc.bcast2d(cand, owner_r, owner_c)
        w = _hegst_diag("L", akk, lkk, inv=lkk_inv, fused=panel_fused,
                        interpret=panel_interpret)
        if k == nt - 1 or nrows == 0:
            return lkk, lkk_inv, vr_l, akk, w, None, None, None, None

        # -- panel: trsm right with Lkk + first half-hemm -----------------
        pan = ppan.panel_solve("R", "L", "C", "N", lkk,
                               lt[lu_r:, kc] if la is None
                               else la[0][lu_r - la[1]:],
                               inv_a=lkk_inv, fused=panel_fused,
                               interpret=panel_interpret)
        pan = pan - 0.5 * jnp.einsum("rab,bd->rad", vr_l, w)
        pan = jnp.where(row_valid[:, None, None], pan, 0)
        ncols = ltc - lu_c
        if ncols == 0:
            return lkk, lkk_inv, vr_l, akk, w, pan, None, None, None

        # -- A panel broadcast + transposed panels ------------------------
        g_cols = (lu_c + jnp.arange(ncols)) * Qc + rc
        col_valid = (g_cols > k) & (g_cols < nt)
        ctx = DistContext(dist)
        keep = (is_owner_c & row_valid)[:, None, None]
        vr_a = cc.bcast(jnp.where(keep, pan, 0), COL_AXIS, owner_c)
        vc_a = transpose_col_to_rows(ctx, vr_a, lu_r, g_cols)
        vc_l = transpose_col_to_rows(ctx, vr_l, lu_r, g_cols)
        vc_a = jnp.where(col_valid[:, None, None], vc_a, 0)
        vc_l = jnp.where(col_valid[:, None, None], vc_l, 0)
        return lkk, lkk_inv, vr_l, akk, w, pan, vr_a, vc_a, vc_l

    def step_pre_L(lt, k, ch, rr, rc):
        lkk, lkk_inv, vr_l, akk, w, pan, vr_a, vc_a, vc_l = ch
        owner_r, owner_c, kr, kc, lu_r, lu_c = _indices(k)
        is_owner_r = cc.this_rank(ROW_AXIS) == owner_r
        is_owner_c = cc.this_rank(COL_AXIS) == owner_c
        nrows = ltr - lu_r
        g_rows = (lu_r + jnp.arange(max(nrows, 1))) * Pr + rr
        row_valid = (g_rows > k) & (g_rows < nt)

        # -- deferred trailing-solve updates of previous panels -----------
        # (reference impl.h:327-372: only tasks involving the k-th panel
        # of L run at iteration k, so every previous panel updates here).
        # The akj broadcast reads lt rows behind the pivot — the one
        # collective comm_la does NOT hoist (docs/comm_overlap.md).
        lc_ub = ceil_div(k, Qc)   # max local cols with global col < k
        if lc_ub > 0:
            g_pcols = jnp.arange(lc_ub) * Qc + rc
            pcol_valid = g_pcols < k
            rowk = lt[kr, :lc_ub]
            rowk_new = tb.trsm_panel("L", "L", "N", "N", lkk, rowk,
                                     inv_a=lkk_inv)
            keepp = (is_owner_r & pcol_valid)[:, None, None]
            lt = lt.at[kr, :lc_ub].set(jnp.where(keepp, rowk_new, rowk))
            akj = cc.bcast(jnp.where(keepp, rowk_new, 0), ROW_AXIS, owner_r)
            if nrows > 0:
                upd = _pair_product(vr_l, jnp.conj(jnp.swapaxes(
                    akj, -1, -2)), cplx, use_mxu)
                mask4 = (row_valid[:, None] & pcol_valid[None, :]
                         )[:, :, None, None]
                lt = lt.at[lu_r:, :lc_ub].add(-jnp.where(mask4, upd, 0))

        # -- diag write ---------------------------------------------------
        lt = lt.at[kr, kc].set(jnp.where(is_owner_r & is_owner_c,
                                         tb.tri_mask(w, "L")
                                         + tb.tri_mask(akk, "U", k=-1),
                                         lt[kr, kc]))
        if pan is None:
            return lt, None

        keep = (is_owner_c & row_valid)[:, None, None]
        lt = lt.at[lu_r:, kc].set(jnp.where(keep, pan, lt[lu_r:, kc]))
        if vc_l is None:
            # no trailing columns on any rank; finish the second half-hemm
            pan2 = pan - 0.5 * jnp.einsum("rab,bd->rad", vr_l, w)
            lt = lt.at[lu_r:, kc].set(
                jnp.where(keep, pan2, lt[lu_r:, kc]))
            return lt, None
        if not (lookahead and k + 1 < nt):
            return lt, None

        # next panel column of the her2k first (my kc1-slot transposed
        # tiles — the exact tiles the bulk pair product would use),
        # carried to step k+1's hegst-diag/panel chain
        tril_m = jnp.tril(jnp.ones((mb, mb), dtype=bool))
        kc1 = ud.local_tile_from_global_tile(k + 1, Qc)
        owner_c1 = ud.rank_global_tile(k + 1, Qc, sc)
        own_c1 = cc.this_rank(COL_AXIS) == owner_c1
        updc = _col_strip_product(vr_a, vc_l[kc1 - lu_c], cplx, use_mxu) \
            + _col_strip_product(vr_l, vc_a[kc1 - lu_c], cplx, use_mxu)
        below1 = row_valid & (g_rows > k + 1)
        ondiag1 = row_valid & (g_rows == k + 1)
        m3 = (below1[:, None, None] | (ondiag1[:, None, None] & tril_m)) \
            & own_c1
        new_col = lt[lu_r:, kc1] - jnp.where(m3, updc,
                                             jnp.zeros_like(updc))
        lt = lt.at[lu_r:, kc1].set(new_col)
        return lt, (new_col, lu_r)

    def step_bulk_L(lt, k, ch, stripped, rr, rc):
        lkk, lkk_inv, vr_l, akk, w, pan, vr_a, vc_a, vc_l = ch
        if pan is None or vc_l is None:
            return lt
        owner_r, owner_c, kr, kc, lu_r, lu_c = _indices(k)
        is_owner_c = cc.this_rank(COL_AXIS) == owner_c
        nrows, ncols = ltr - lu_r, ltc - lu_c
        g_rows = (lu_r + jnp.arange(nrows)) * Pr + rr
        g_cols = (lu_c + jnp.arange(ncols)) * Qc + rc
        row_valid = (g_rows > k) & (g_rows < nt)
        col_valid = (g_cols > k) & (g_cols < nt)
        keep = (is_owner_c & row_valid)[:, None, None]

        # -- her2k trailing: A_ij -= P_i L_jk^H + L_ik P_j^H --------------
        pair = row_valid[:, None] & col_valid[None, :]
        below = pair & (g_rows[:, None] > g_cols[None, :])
        ondiag = pair & (g_rows[:, None] == g_cols[None, :])
        tril_m = jnp.tril(jnp.ones((mb, mb), dtype=bool))
        if stripped:
            notnext = g_cols != k + 1
            below = below & notnext[None, :]
            ondiag = ondiag & notnext[None, :]
        upd = _pair_product(vr_a, vc_l, cplx, use_mxu) \
            + _pair_product(vr_l, vc_a, cplx, use_mxu)
        mask4 = below[:, :, None, None] | (ondiag[:, :, None, None] & tril_m)
        lt = lt.at[lu_r:, lu_c:].add(-jnp.where(mask4, upd, 0))

        # -- second half-hemm on the panel --------------------------------
        pan2 = pan - 0.5 * jnp.einsum("rab,bd->rad", vr_l, w)
        lt = lt.at[lu_r:, kc].set(jnp.where(keep, pan2, lt[lu_r:, kc]))
        return lt

    def chain_U(lt, ll, k, la, rr, rc):
        owner_r, owner_c, kr, kc, lu_r, lu_c = _indices(k)
        is_owner_r = cc.this_rank(ROW_AXIS) == owner_r

        ukk = pad_lkk(cc.bcast2d(ll[kr, kc], owner_r, owner_c), k)
        ukk_inv = _step_inv("U", ukk)

        # -- U row-panel (cols > k) col-broadcast (constant ll) -----------
        ncols = ltc - lu_c
        g_cols = (lu_c + jnp.arange(max(ncols, 1))) * Qc + rc
        col_valid = (g_cols > k) & (g_cols < nt)
        vc_u = None
        if ncols > 0:
            vc_u = cc.bcast(jnp.where((is_owner_r & col_valid)[:, None, None],
                                      ll[kr, lu_c:], 0), ROW_AXIS, owner_r)
            vc_u = jnp.where(col_valid[:, None, None], vc_u, 0)

        cand = lt[kr, kc] if la is None else la[0][kc - la[1]]
        akk = cc.bcast2d(cand, owner_r, owner_c)
        w = _hegst_diag("U", akk, ukk, inv=ukk_inv, fused=panel_fused,
                        interpret=panel_interpret)
        if k == nt - 1 or ncols == 0:
            return ukk, ukk_inv, vc_u, akk, w, None, None, None, None

        # -- panel: trsm left with Ukk^H + first half-hemm ----------------
        pan = ppan.panel_solve("L", "U", "C", "N", ukk,
                               lt[kr, lu_c:] if la is None
                               else la[0][lu_c - la[1]:],
                               inv_a=ukk_inv, fused=panel_fused,
                               interpret=panel_interpret)
        pan = pan - 0.5 * jnp.einsum("ab,rbd->rad", w, vc_u)
        pan = jnp.where(col_valid[:, None, None], pan, 0)
        nrows = ltr - lu_r
        if nrows == 0:
            return ukk, ukk_inv, vc_u, akk, w, pan, None, None, None

        g_rows = (lu_r + jnp.arange(nrows)) * Pr + rr
        row_valid = (g_rows > k) & (g_rows < nt)
        ctx = DistContext(dist)
        keep = (is_owner_r & col_valid)[:, None, None]
        vc_a = cc.bcast(jnp.where(keep, pan, 0), ROW_AXIS, owner_r)
        vr_a = transpose_row_to_cols(ctx, vc_a, lu_c, g_rows)
        vr_u = transpose_row_to_cols(ctx, vc_u, lu_c, g_rows)
        vr_a = jnp.where(row_valid[:, None, None], vr_a, 0)
        vr_u = jnp.where(row_valid[:, None, None], vr_u, 0)
        return ukk, ukk_inv, vc_u, akk, w, pan, vc_a, vr_a, vr_u

    def step_pre_U(lt, k, ch, rr, rc):
        ukk, ukk_inv, vc_u, akk, w, pan, vc_a, vr_a, vr_u = ch
        owner_r, owner_c, kr, kc, lu_r, lu_c = _indices(k)
        is_owner_r = cc.this_rank(ROW_AXIS) == owner_r
        is_owner_c = cc.this_rank(COL_AXIS) == owner_c
        ncols = ltc - lu_c
        g_cols = (lu_c + jnp.arange(max(ncols, 1))) * Qc + rc
        col_valid = (g_cols > k) & (g_cols < nt)

        # -- deferred right-solve updates of previous panel rows ----------
        # (the ajk broadcast reads lt cols behind the pivot — the one
        # collective comm_la does NOT hoist, docs/comm_overlap.md)
        lr_ub = ceil_div(k, Pr)   # max local rows with global row < k
        if lr_ub > 0:
            g_prows = jnp.arange(lr_ub) * Pr + rr
            prow_valid = g_prows < k
            colk = lt[:lr_ub, kc]
            colk_new = tb.trsm_panel("R", "U", "N", "N", ukk, colk,
                                     inv_a=ukk_inv)
            keepp = (is_owner_c & prow_valid)[:, None, None]
            lt = lt.at[:lr_ub, kc].set(jnp.where(keepp, colk_new, colk))
            ajk = cc.bcast(jnp.where(keepp, colk_new, 0), COL_AXIS, owner_c)
            if ncols > 0:
                # A_ji -= A_jk U_ki: pair product with x = A_jk tiles,
                # y[c] = conj(U_ki)^T so conj(y)^T = U_ki
                upd = _pair_product(ajk, jnp.conj(jnp.swapaxes(
                    vc_u, -1, -2)), cplx, use_mxu)
                mask4 = (prow_valid[:, None] & col_valid[None, :]
                         )[:, :, None, None]
                lt = lt.at[:lr_ub, lu_c:].add(-jnp.where(mask4, upd, 0))

        lt = lt.at[kr, kc].set(jnp.where(is_owner_r & is_owner_c,
                                         tb.tri_mask(w, "U")
                                         + tb.tri_mask(akk, "L", k=-1),
                                         lt[kr, kc]))
        if pan is None:
            return lt, None

        keep = (is_owner_r & col_valid)[:, None, None]
        lt = lt.at[kr, lu_c:].set(jnp.where(keep, pan, lt[kr, lu_c:]))
        if vr_u is None:
            pan2 = pan - 0.5 * jnp.einsum("ab,rbd->rad", w, vc_u)
            lt = lt.at[kr, lu_c:].set(jnp.where(keep, pan2, lt[kr, lu_c:]))
            return lt, None
        if not (lookahead and k + 1 < nt):
            return lt, None

        # mirrored split: next block row of the her2k first (carried)
        triu_m = jnp.triu(jnp.ones((mb, mb), dtype=bool))
        kr1 = ud.local_tile_from_global_tile(k + 1, Pr)
        owner_r1 = ud.rank_global_tile(k + 1, Pr, sr)
        own_r1 = cc.this_rank(ROW_AXIS) == owner_r1
        xa = jnp.conj(jnp.swapaxes(vr_a[kr1 - lu_r], -1, -2))
        xu = jnp.conj(jnp.swapaxes(vr_u[kr1 - lu_r], -1, -2))
        updr = _row_strip_product(
            xa, jnp.conj(jnp.swapaxes(vc_u, -1, -2)), cplx, use_mxu) \
            + _row_strip_product(
                xu, jnp.conj(jnp.swapaxes(vc_a, -1, -2)), cplx, use_mxu)
        above1 = col_valid & (g_cols > k + 1)
        ondiag1 = col_valid & (g_cols == k + 1)
        m3 = (above1[:, None, None] | (ondiag1[:, None, None] & triu_m)) \
            & own_r1
        new_row = lt[kr1, lu_c:] - jnp.where(m3, updr,
                                             jnp.zeros_like(updr))
        lt = lt.at[kr1, lu_c:].set(new_row)
        return lt, (new_row, lu_c)

    def step_bulk_U(lt, k, ch, stripped, rr, rc):
        ukk, ukk_inv, vc_u, akk, w, pan, vc_a, vr_a, vr_u = ch
        if pan is None or vr_u is None:
            return lt
        owner_r, owner_c, kr, kc, lu_r, lu_c = _indices(k)
        is_owner_r = cc.this_rank(ROW_AXIS) == owner_r
        nrows, ncols = ltr - lu_r, ltc - lu_c
        g_rows = (lu_r + jnp.arange(nrows)) * Pr + rr
        g_cols = (lu_c + jnp.arange(ncols)) * Qc + rc
        row_valid = (g_rows > k) & (g_rows < nt)
        col_valid = (g_cols > k) & (g_cols < nt)
        keep = (is_owner_r & col_valid)[:, None, None]

        # -- her2k trailing (upper): A_ij -= P_i^H U_kj + U_ki^H P_j ------
        # tile (i, j), i < j: A_ij -= conj(P_ki)^T U_kj + conj(U_ki)^T P_kj
        pair = row_valid[:, None] & col_valid[None, :]
        above = pair & (g_rows[:, None] < g_cols[None, :])
        ondiag = pair & (g_rows[:, None] == g_cols[None, :])
        triu_m = jnp.triu(jnp.ones((mb, mb), dtype=bool))
        if stripped:
            notnext = g_rows != k + 1
            above = above & notnext[:, None]
            ondiag = ondiag & notnext[:, None]
        upd = _pair_product(jnp.conj(jnp.swapaxes(vr_a, -1, -2)),
                            jnp.conj(jnp.swapaxes(vc_u, -1, -2)),
                            cplx, use_mxu) \
            + _pair_product(jnp.conj(jnp.swapaxes(vr_u, -1, -2)),
                            jnp.conj(jnp.swapaxes(vc_a, -1, -2)),
                            cplx, use_mxu)
        mask4 = above[:, :, None, None] | (ondiag[:, :, None, None] & triu_m)
        lt = lt.at[lu_r:, lu_c:].add(-jnp.where(mask4, upd, 0))

        pan2 = pan - 0.5 * jnp.einsum("ab,rbd->rad", w, vc_u)
        lt = lt.at[kr, lu_c:].set(jnp.where(keep, pan2, lt[kr, lu_c:]))
        return lt

    chain, step_pre, step_bulk = (
        (chain_L, step_pre_L, step_bulk_L) if uplo == "L"
        else (chain_U, step_pre_U, step_bulk_U))

    def chain_comm_counts(k):
        """Collectives ``chain(k)`` emits per mesh axis (trace-time
        statics mirroring the chain's early-exit structure): two fused
        bcast2d (L diag + A diag) on each axis, the factor-panel
        broadcast whenever trailing slots exist, and — on a full chain —
        the A-panel broadcast plus the two transposed-panel
        all_gathers."""
        _, _, _, _, lu_r, lu_c = _indices(k)
        nrows, ncols = ltr - lu_r, ltc - lu_c
        if uplo == "L":
            full = k < nt - 1 and nrows > 0 and ncols > 0
            row = 2 + (2 if full else 0)
            col = 2 + (1 if nrows > 0 else 0) + (1 if full else 0)
        else:
            full = k < nt - 1 and ncols > 0 and nrows > 0
            row = 2 + (1 if ncols > 0 else 0) + (1 if full else 0)
            col = 2 + (2 if full else 0)
        return row, col

    def transform(lt, ll):
        rr = (cc.this_rank(ROW_AXIS) - sr) % Pr
        rc = (cc.this_rank(COL_AXIS) - sc) % Qc
        la = None
        ch_next = None
        # uniform per-step phase scopes (`hegst.step<k>.<phase>`, shared
        # convention with cholesky — docs/observability.md critical-path
        # attribution); the comm_la-hoisted chain is scoped as step k+1's
        # PANEL even though it executes inside step k's window
        for k in range(nt):
            if comm_la:
                # step k+1's panel chain (collectives included) emitted
                # between step k's strip and step k's bulk her2k
                if ch_next is not None:
                    ch = ch_next
                else:
                    with obs.named_span(f"hegst.step{k:03d}.panel"):
                        ch = chain(lt, ll, k, la, rr, rc)
                with obs.named_span(f"hegst.step{k:03d}.strip"):
                    lt, la = step_pre(lt, k, ch, rr, rc)
                ch_next = None
                if k + 1 < nt and la is not None:
                    with obs.named_span(f"hegst.step{k + 1:03d}.panel"):
                        ch_next = chain(None, ll, k + 1, la, rr, rc)
                    n_row, n_col = chain_comm_counts(k + 1)
                    cc.record_overlapped("hegst_dist", ROW_AXIS, n_row)
                    cc.record_overlapped("hegst_dist", COL_AXIS, n_col)
                with obs.named_span(f"hegst.step{k:03d}.bulk"):
                    lt = step_bulk(lt, k, ch, la is not None, rr, rc)
            else:
                with obs.named_span(f"hegst.step{k:03d}.panel"):
                    ch = chain(lt, ll, k, la, rr, rc)
                with obs.named_span(f"hegst.step{k:03d}.strip"):
                    lt, la = step_pre(lt, k, ch, rr, rc)
                with obs.named_span(f"hegst.step{k:03d}.bulk"):
                    lt = step_bulk(lt, k, ch, la is not None, rr, rc)
        return lt

    return shard_map(transform, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS)),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


@register_program_cache
@functools.lru_cache(maxsize=64)
def _dist_hegst_cached(dist, mesh, dtype, uplo, use_mxu, donate=False,
                       lookahead=False, comm_la=False, panel_fused=False,
                       panel_interpret=False, route=()):
    # ``route``: active autotune route as a pure cache-key member
    # (docs/autotune.md) — the builder reads the routed knobs
    # (_oz_slices / trsm_panel) at trace time
    return jax.jit(_build_dist_hegst(dist, mesh, uplo, use_mxu=use_mxu,
                                     cplx=dtype.startswith("complex"),
                                     lookahead=lookahead, comm_la=comm_la,
                                     panel_fused=panel_fused,
                                     panel_interpret=panel_interpret),
                   **donate_argnums_kw(donate, 0))


def gen_to_std(uplo: str, a: Matrix, b_factor: Matrix, *,
               donate: bool = False, with_info: bool = False):
    """Transform ``a`` (Hermitian, stored in ``uplo``) using ``b_factor`` =
    the Cholesky factor of B (same ``uplo``); see :func:`_gen_to_std`.

    Under ``DLAF_AUTOTUNE`` (docs/autotune.md) the blocked forms'
    precision route is selected from the route table (op key ``hegst``),
    and — when ``a`` survives the call — the transform's Hutchinson
    residual probe feeds the table back. The twosolve form inherits its
    routes through the triangular solver's own ``trsm`` steering (its
    pivot chains live there), but the HEGST probe still reports here.
    """
    from .. import autotune

    steer = autotune.steering_for_matrix("hegst", a)
    if steer is None:
        return _gen_to_std(uplo, a, b_factor, donate=donate,
                           with_info=with_info)
    with steer.applied():
        out = _gen_to_std(uplo, a, b_factor, donate=donate,
                          with_info=with_info, route=steer.route.key())
    if not donate and steer.probe_due:
        res = out[0] if with_info else out
        steer.observe(
            obs.accuracy.hegst_residual(uplo, a, b_factor, res),
            c=100.0, of=res.storage, attrs={"entry": "gen_to_std",
                                            "uplo": uplo})
    return out


def _gen_to_std(uplo: str, a: Matrix, b_factor: Matrix, *,
                donate: bool = False, with_info: bool = False,
                route: tuple = ()):
    """Transform ``a`` (Hermitian, stored in ``uplo``) using ``b_factor`` =
    the Cholesky factor of B (same ``uplo``). Returns the transformed A with
    its opposite triangle passing through unchanged.

    ``donate=True`` permits consuming ``a``'s device storage (the
    reference transforms mat_a in place, ``eigensolver/gen_to_std``);
    ``a`` must not be used afterwards. ``b_factor`` is never consumed
    (callers reuse the factor across runs).

    ``with_info=True`` returns ``(out, info)`` — the singular-diagonal
    detection analogous to the triangular solve's: info is an int32 device
    scalar, 0 when ``b_factor``'s diagonal is finite and nonzero, else the
    1-based first singular global column (HEGST solves against that
    diagonal, so a zero/NaN entry poisons the transform silently).
    In-graph, no host sync (health.matrix_diag_info)."""
    dlaf_assert(uplo in ("L", "U"), f"gen_to_std: bad uplo {uplo!r}")
    info = None
    if with_info:
        from ..health import matrix_diag_info

        info = matrix_diag_info(b_factor, singular=True)
    dlaf_assert(a.size == b_factor.size, "gen_to_std: A/B size mismatch")
    dlaf_assert(a.block_size == b_factor.block_size, "gen_to_std: block mismatch")
    from ..config import resolve_step_mode

    from ..config import resolve_platform_auto
    from ..types import total_ops

    cfg = get_configuration()
    hegst_impl = resolve_platform_auto(
        cfg.hegst_impl, knob="hegst_impl", tpu_choice="twosolve",
        other_choice="blocked",
        detail="twosolve measured 385.3 GF/s at 5.2e-11 residual vs "
               "blocked 298.4 at 2.2e-9 on d/8192/256 — dense MXU sweeps "
               "beat latency-bound panel round-trips; session 4d, "
               "2026-08-02 v5e")
    distributed = a.grid is not None and a.grid.num_devices > 1
    # reference HEGST flop model (miniapp_gen_to_std): n^3/2 muls+adds —
    # the model, not the route's actual flops (twosolve spends ~2x)
    n = a.size.row
    # the scan step mode's O(1)-compile guarantee flows through the
    # triangular solver's scan form; BOTH blocked builders (local and
    # distributed) unroll all nt per-k steps inside one jit, so both
    # reroute — at ~19 s/step on the TPU AOT toolchain an unrolled
    # local blocked run would pay the exact O(nt) cold compile the
    # auto step mode exists to avoid (round-3 advisory)
    use_twosolve = hegst_impl == "twosolve" or \
        resolve_step_mode(a.dist.nr_tiles.row) == "scan"
    # fused panel route for the BLOCKED forms' diag hegst + panel trsm
    # chain (docs/pallas_panel.md); twosolve has no per-step panel chain
    # of its own — its pivot solves route inside triangular_solve
    panel_fused = not use_twosolve and ppan.panel_uses_fused(
        np.dtype(a.dtype), a.block_size.row)
    entry_span = obs.entry_span("gen_to_std", lambda: dict(
        flops=total_ops(np.dtype(a.dtype), n**3 / 2, n**3 / 2),
        n=n, nb=a.block_size.row, uplo=uplo,
        dtype=np.dtype(a.dtype).name,
        impl="twosolve" if use_twosolve else hegst_impl,
        panel_impl="fused" if panel_fused else "xla",
        **({"autotune_route": dict(route)} if route else {}),
        grid=f"{a.dist.grid_size.row}x{a.dist.grid_size.col}"))
    if use_twosolve:
        with entry_span:
            res = _gen_to_std_twosolve(uplo, a, b_factor, donate=donate)
            return (res, info) if with_info else res
    # blocked forms take the same look-ahead split as the pipelined
    # Cholesky (docs/lookahead.md); twosolve inherits it through the
    # triangular solver's own scan-mode gate above. comm_lookahead
    # (docs/comm_overlap.md) hoists the distributed builder's panel
    # collectives ahead of the bulk her2k — it rides the carry, so it
    # requires lookahead too.
    from ..config import (resolved_cholesky_lookahead,
                          resolved_comm_lookahead)

    lookahead = resolved_cholesky_lookahead()
    comm_la = lookahead and resolved_comm_lookahead()
    if not distributed:
        with entry_span, quiet_donation():
            g = tiles_to_global(a.storage, a.dist)
            lg = tiles_to_global(b_factor.storage, b_factor.dist)
            # program telemetry (DLAF_PROGRAM_TELEMETRY): off = passthrough
            out = obs.telemetry.call(
                "gen_to_std.local", _hegst_local_blocked, g, lg, uplo=uplo,
                nb=a.block_size.row, lookahead=lookahead,
                panel_fused=panel_fused,
                panel_interpret=panel_fused
                and jax.default_backend() != "tpu", route=route)
            out_m = a.with_storage(global_to_tiles_donated(out, a.dist))
        res = mops.merge_triangle(out_m, a, uplo, donate_orig=donate)
        return (res, info) if with_info else res
    # the blocked builder shares one set of slot indices between A and L
    # (diag/panel reads of ll at A's kr/kc) — both axes must align
    assert_slot_aligned(a.dist, b_factor.dist, rows=True, cols=True,
                        what="gen_to_std(A, B_factor)")
    dt = np.dtype(a.dtype)
    use_mxu = tb.f64_gemm_uses_mxu(dt, a.block_size.row)
    platform = next(iter(a.grid.mesh.devices.flat)).platform
    fn = _dist_hegst_cached(a.dist, a.grid.mesh, dt.name, uplo, use_mxu,
                            donate=donate, lookahead=lookahead,
                            comm_la=comm_la, panel_fused=panel_fused,
                            panel_interpret=panel_fused
                            and platform != "tpu", route=route)
    with entry_span, quiet_donation():
        res = a.with_storage(obs.telemetry.call(
            "gen_to_std.dist", fn, a.storage, b_factor.storage))
        return (res, info) if with_info else res
