"""Batched many-problem entry points (leading batch axis).

The production serving regime (ROADMAP item 1; arXiv:2112.09017's
batch-small-problems idiom, already proven inside the level-batched D&C
merge driver of PR 6) is millions of SMALL solve/EVP requests, where
per-request dispatch/retrace/compile latency — not the MXU — bounds
throughput. This module promotes that idiom to the public API: one
vmapped program factors/solves/diagonalizes a whole ``(B, n, n)`` batch
per dispatch, compiled once per shape bucket and served warm from the
:mod:`dlaf_tpu.serve` program cache.

Three entry points, each the vmapped form of a pinned singleton kernel:

* :func:`cholesky_batched` — per-lane Cholesky over the ``uplo``
  triangle, riding the whole-matrix XLA route of the local builder
  (``_cholesky_local(trailing="xla")``): for serve-sized problems the
  blocked panel chain buys nothing, and the fused whole-matrix
  factorization is the one route whose vmapped lanes are **bitwise
  identical** to the unbatched singleton program on the supported
  backends (pinned by tests/test_serve.py).
* :func:`solve_batched` — per-lane triangular solve (all
  side/uplo/op/diag combos, per-lane ``alpha``), the batched form of
  ``_solve_local``.
* :func:`eigh_batched` — per-lane Hermitian eigendecomposition of the
  ``uplo`` triangle (ascending eigenvalues + eigenvector columns).

Parity contract (docs/serving.md): a batched dispatch and a loop of
B=1 dispatches of the SAME bucket program are bitwise identical lane
for lane — XLA's batched lowerings are lane-deterministic and
batch-size-invariant, so pad lanes are provably inert. The rank-2
(no-batch-axis) lowering of the triangular solve differs from its
batched form at the ~1 ulp level on some backends, which is why the
singleton comparator IS the B=1 program (``*_batched`` with ``B == 1``)
rather than a differently-lowered scalar entry; the Cholesky and eigh
kernels are additionally bitwise against their unbatched forms.

``with_info=True`` returns a per-element int32 info VECTOR ``(B,)`` —
the singleton info contract (:mod:`dlaf_tpu.health.info`) vmapped:
0 per clean lane, else the 1-based first failing/singular column of
that lane. :func:`dlaf_tpu.health.robust_cholesky_batched` is the
recovery driver over it (re-shifts and re-dispatches only the failed
lanes).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import obs
from ..common.asserts import dlaf_assert
from ..health import info as hinfo
from ..types import total_ops
from .cholesky import _cholesky_local
from .triangular import _solve_local

#: Default block size of the batched bucket programs. The whole-matrix
#: serve routes do not block internally, but ``nb`` stays a first-class
#: bucket-key member (ISSUE 11) so a future blocked batched route slots
#: in without a cache-key migration.
DEFAULT_NB = 256


def default_nb(n: int) -> int:
    return max(1, min(int(n), DEFAULT_NB))


# ---------------------------------------------------------------------------
# Singleton kernels (the functions the bucket programs vmap)
# ---------------------------------------------------------------------------

def cholesky_one(a, *, uplo: str, nb: int, with_info: bool = False):
    """ONE lane of the batched Cholesky: the local builder's whole-matrix
    XLA route (triangle pass-through semantics preserved; in-graph info
    composition shared with ``cholesky(..., with_info=True)``)."""
    return _cholesky_local.__wrapped__(a, uplo=uplo, nb=nb, trailing="xla",
                                       with_info=with_info)


def solve_one(a, b, alpha, *, side: str, uplo: str, op: str, diag: str,
              with_info: bool = False):
    """ONE lane of the batched triangular solve: ``op(A) X = alpha B``
    (side='L') / ``X op(A) = alpha B`` (side='R') over the ``uplo``
    triangle. ``with_info`` adds the singular-diagonal detection of
    ``health.matrix_diag_info`` (zero OR non-finite diagonal; constant 0
    for unit-diagonal solves, which never read the stored diagonal)."""
    x = _solve_local.__wrapped__(a, b, alpha, side=side, uplo=uplo, op=op,
                                 diag=diag)
    if not with_info:
        return x
    if diag == "U":
        info = jnp.zeros((), jnp.int32)
    else:
        d = jnp.diagonal(a)
        info = hinfo.first_bad_info(hinfo.bad_diag_mask(d, singular=True))
    return x, info


def eigh_one(a, *, uplo: str, with_info: bool = False):
    """ONE lane of the batched Hermitian eigensolver: eigenvalues
    (ascending) + eigenvector columns of the matrix whose ``uplo``
    triangle is stored in ``a`` (the other triangle is ignored — the
    library-wide triangle contract, built explicitly here so the
    backend's symmetrization can never read pass-through data).
    ``with_info`` flags non-finite eigenvalues (1-based first bad
    index), the in-graph convergence-corruption signal."""
    if uplo == "L":
        ah = jnp.tril(a) + jnp.conj(jnp.tril(a, -1)).swapaxes(-1, -2)
    else:
        ah = jnp.triu(a) + jnp.conj(jnp.triu(a, 1)).swapaxes(-1, -2)
    w, v = jnp.linalg.eigh(ah, symmetrize_input=False)
    if not with_info:
        return w, v
    return w, v, hinfo.first_bad_info(~jnp.isfinite(w))


# ---------------------------------------------------------------------------
# Public batched entry points
# ---------------------------------------------------------------------------

def _check_batch(a, what: str) -> tuple:
    dlaf_assert(hasattr(a, "ndim") and a.ndim == 3,
                f"{what}: expected a (B, n, n) batch, got "
                f"shape {getattr(a, 'shape', None)}")
    b_, n, n2 = a.shape
    dlaf_assert(n == n2, f"{what}: lanes must be square, got {a.shape}")
    dlaf_assert(b_ >= 1, f"{what}: empty batch")
    return b_, n


def cholesky_batched(uplo: str, a, *, nb: int = None,
                     with_info: bool = False, donate: bool = False,
                     service=None):
    """Cholesky-factorize every lane of the ``(B, n, n)`` batch ``a`` in
    its ``uplo`` triangle with ONE compiled, vmapped program served from
    the :mod:`dlaf_tpu.serve` program cache (warm after
    ``serve.warmup``; per-bucket hit/miss/compile metrics either way).

    Returns the ``(B, n, n)`` factor batch (per-lane ``uplo`` triangle =
    factor, other triangle passes through), plus a per-lane int32 info
    vector when ``with_info=True``. ``donate=True`` donates ``a``'s
    buffer to the dispatch (the queue's hot path — the padded batch it
    owns); ``a`` must not be used afterwards.
    """
    dlaf_assert(uplo in ("L", "U"),
                f"cholesky_batched: uplo must be 'L' or 'U', got {uplo!r}")
    b_, n = _check_batch(a, "cholesky_batched")
    from ..serve.programs import cholesky_spec, get_service

    dt = np.dtype(a.dtype)
    spec = cholesky_spec(batch=b_, n=n, nb=nb or default_nb(n),
                         dtype=dt.name, uplo=uplo, with_info=with_info,
                         donate=donate)
    svc = service if service is not None else get_service()
    entry_span = obs.entry_span("cholesky_batched", lambda: dict(
        flops=b_ * total_ops(dt, n**3 / 6, n**3 / 6), batch=b_, n=n,
        nb=spec.nb, uplo=uplo, dtype=dt.name))
    with entry_span:
        return svc.run(spec, a)


def solve_batched(side: str, uplo: str, op: str, diag: str, alpha, a, b,
                  *, nb: int = None, with_info: bool = False,
                  donate_b: bool = False, service=None):
    """Triangular-solve every lane: ``op(A_i) X_i = alpha_i B_i``
    (side='L') / ``X_i op(A_i) = alpha_i B_i`` (side='R') for the
    ``(B, n, n)`` triangle batch ``a`` and ``(B, n, nrhs)`` (side='L';
    ``(B, nrhs, n)`` side='R') rhs batch ``b``, one vmapped bucket
    program per (n, nrhs, dtype, side/uplo/op/diag) key. ``alpha`` may
    be a scalar or a per-lane ``(B,)`` vector (a traced operand — it is
    never part of the bucket key). ``with_info=True`` adds the per-lane
    singular-diagonal info vector. ``donate_b=True`` donates the rhs
    buffer (the entry's output aliases it)."""
    for name, val, choices in (("side", side, ("L", "R")),
                               ("uplo", uplo, ("L", "U")),
                               ("op", op, ("N", "T", "C")),
                               ("diag", diag, ("N", "U"))):
        dlaf_assert(val in choices,
                    f"solve_batched: {name} must be one of {choices}, "
                    f"got {val!r}")
    b_, n = _check_batch(a, "solve_batched")
    dlaf_assert(hasattr(b, "ndim") and b.ndim == 3 and b.shape[0] == b_,
                f"solve_batched: rhs must be (B, ., .) with B={b_}, got "
                f"shape {getattr(b, 'shape', None)}")
    solve_dim = b.shape[1] if side == "L" else b.shape[2]
    nrhs = b.shape[2] if side == "L" else b.shape[1]
    dlaf_assert(solve_dim == n,
                f"solve_batched: rhs solve dimension {solve_dim} != n={n}")
    from ..serve.programs import get_service, solve_spec

    dt = np.dtype(a.dtype)
    spec = solve_spec(batch=b_, n=n, nrhs=nrhs, nb=nb or default_nb(n),
                      dtype=dt.name, side=side, uplo=uplo, transa=op,
                      diag=diag, with_info=with_info, donate=donate_b)
    svc = service if service is not None else get_service()
    alpha_vec = jnp.broadcast_to(jnp.asarray(alpha, dtype=dt), (b_,))
    entry_span = obs.entry_span("solve_batched", lambda: dict(
        flops=b_ * total_ops(dt, n**2 * nrhs / 2, n**2 * nrhs / 2),
        batch=b_, n=n, nrhs=nrhs, nb=spec.nb, side=side, uplo=uplo, op=op,
        diag=diag, dtype=dt.name))
    with entry_span:
        return svc.run(spec, a, b, alpha_vec)


def eigh_batched(uplo: str, a, *, nb: int = None, with_info: bool = False,
                 donate: bool = False, service=None):
    """Eigendecompose every Hermitian lane of the ``(B, n, n)`` batch
    ``a`` (``uplo`` triangle stored; the other triangle is ignored) with
    one vmapped bucket program. Returns ``(w, v)`` — eigenvalues
    ``(B, n)`` ascending, eigenvector columns ``(B, n, n)`` — plus the
    per-lane non-finite-eigenvalue info vector when ``with_info=True``.
    """
    dlaf_assert(uplo in ("L", "U"),
                f"eigh_batched: uplo must be 'L' or 'U', got {uplo!r}")
    b_, n = _check_batch(a, "eigh_batched")
    from ..serve.programs import eigh_spec, get_service

    dt = np.dtype(a.dtype)
    spec = eigh_spec(batch=b_, n=n, nb=nb or default_nb(n), dtype=dt.name,
                     uplo=uplo, with_info=with_info, donate=donate)
    svc = service if service is not None else get_service()
    entry_span = obs.entry_span("eigh_batched", lambda: dict(
        flops=b_ * total_ops(dt, 5 * n**3 / 3, 5 * n**3 / 3), batch=b_,
        n=n, nb=spec.nb, uplo=uplo, dtype=dt.name))
    with entry_span:
        return svc.run(spec, a)


#: spec.op -> the singleton kernel the bucket program vmaps (consumed by
#: serve.programs.program_builder and the graphcheck serve specs).
SINGLETON_KERNELS = {
    "cholesky": cholesky_one,
    "solve": solve_one,
    "eigh": eigh_one,
}
