"""General (sub-)matrix multiplication.

TPU-native counterpart of the reference's ``multiplication/general``
(``multiplication/general/api.h:23`` ``GeneralSub::callNN``: local NN gemm
over the tile range [a, b] — the reference's naive triple tile loop,
``impl.h:25-43``, used by the D&C eigenvector multiply). Here the tile range
is an element-range slice and the product is ONE XLA dot on the slice.

Also provides the full distributed gemm (an extension over the reference's
local-only scope) via the GSPMD global view: annotate shardings, let XLA
pick the SUMMA-style collective schedule.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..tile_ops import blas as tb
from ..config import register_program_cache
from ..common.asserts import dlaf_assert
from ..matrix.matrix import Matrix
from ..matrix.tiling import global_to_tiles, tiles_to_global


@register_program_cache
@functools.lru_cache(maxsize=128)
def _gemm_cached(dist_a, dist_b, dist_c, sharding, a0, a1, alpha_beta_static=None):
    def prog(sa, sb, sc, alpha, beta):
        ga = tiles_to_global(sa, dist_a)
        gb = tiles_to_global(sb, dist_b)
        gc = tiles_to_global(sc, dist_c)
        sl = slice(a0, a1)
        prod = tb.mm(ga[sl, sl], gb[sl, sl])
        gc = gc.at[sl, sl].set(alpha * prod + beta * gc[sl, sl])
        return global_to_tiles(gc, dist_c)

    kw = {}
    if sharding is not None:
        kw = dict(in_shardings=(sharding, sharding, sharding, None, None),
                  out_shardings=sharding)
    return jax.jit(prog, **kw)


def general_sub_multiply(alpha, a: Matrix, b: Matrix, beta, c: Matrix,
                         tile_begin: int, tile_end: int) -> Matrix:
    """``C[r,r] = alpha A[r,r] B[r,r] + beta C[r,r]`` with ``r`` the element
    range covered by tiles [tile_begin, tile_end) (reference
    ``GeneralSub::callNN``)."""
    dlaf_assert(a.block_size == b.block_size == c.block_size,
                "general_sub_multiply: block sizes must agree")
    nb = a.block_size.row
    a0 = tile_begin * nb
    a1 = min(tile_end * nb, a.size.row)
    sh = None if (a.grid is None or a.grid.num_devices == 1) else a.grid.tile_sharding()
    fn = _gemm_cached(a.dist, b.dist, c.dist, sh, a0, a1)
    alpha = jnp.asarray(alpha, c.dtype)
    beta = jnp.asarray(beta, c.dtype)
    return c.with_storage(fn(a.storage, b.storage, c.storage, alpha, beta))
