"""Row/column permutations over an index range.

TPU-native counterpart of the reference's ``permutations::permute``
(``permutations/general/api.h:22``, ``impl.h:40-155`` + CUDA gather kernel
``perms.cu:58-120``): out-of-place ``out[i] = in[perm[i]]`` along rows or
columns restricted to a tile range, used by the D&C merge. On TPU this is a
single XLA gather (``jnp.take``) — the custom CUDA kernel disappears.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..common.asserts import dlaf_assert
from ..matrix.matrix import Matrix
from ..matrix.tiling import global_to_tiles, tiles_to_global


def permute_array(coord: str, perm, arr):
    """``out[i] = in[perm[i]]`` along rows ('Row') or columns ('Col') of a
    plain (device) array — the gather primitive shared by the Matrix-level
    :func:`permute` and the D&C merge assembly (the reference's two callers
    of its permutation kernel, ``perms.cu:58-120``: workspace index sorts
    inside the merge, and matrix-level permutes)."""
    dlaf_assert(coord in ("Row", "Col"), f"bad coord {coord!r}")
    return jnp.take(arr, jnp.asarray(perm), axis=0 if coord == "Row" else 1)


def permute(coord: str, perm, mat: Matrix, tile_begin: int = 0,
            tile_end: int | None = None) -> Matrix:
    """Permute rows (coord='Row') or columns ('Col') of the element range
    covered by tiles [tile_begin, tile_end); identity elsewhere."""
    dlaf_assert(coord in ("Row", "Col"), f"bad coord {coord!r}")
    nb = mat.block_size.row if coord == "Row" else mat.block_size.col
    ext = mat.size.row if coord == "Row" else mat.size.col
    a0 = tile_begin * nb
    a1 = ext if tile_end is None else min(tile_end * nb, ext)
    g = tiles_to_global(mat.storage, mat.dist)
    idx = jnp.asarray(perm) + a0
    if coord == "Row":
        sub = permute_array("Row", idx, g)
        g = g.at[a0:a1, :].set(sub)
    else:
        sub = permute_array("Col", idx, g)
        g = g.at[:, a0:a1].set(sub)
    return mat.with_storage(global_to_tiles(g, mat.dist))
