"""Row/column permutations over an index range.

TPU-native counterpart of the reference's ``permutations::permute``
(``permutations/general/api.h:22``, ``impl.h:40-155`` + CUDA gather kernel
``perms.cu:58-120``): out-of-place ``out[i] = in[perm[i]]`` along rows or
columns restricted to a tile range, used by the D&C merge. On TPU the local
form is a single XLA gather (``jnp.take``) — the custom CUDA kernel
disappears.

Distributed form: the reference's kernel operates on LOCAL tiles only; the
Matrix-level distributed permute here is one ``shard_map`` program per call
shape — an ``all_gather`` along the permuted mesh axis restricted to the
slot window covering the affected tile range, followed by a per-rank static
gather (the source positions are trace-time tables indexed by
``lax.axis_index``). Communication is one collective of the affected rows
(O(range x local-extent) per rank, riding ICI); no rank ever materializes
the full matrix and nothing round-trips through the host (the round-3
gather-densify this replaces).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..comm.grid import COL_AXIS, ROW_AXIS
from ..common.asserts import dlaf_assert, dlaf_assert_heavy
from ..config import register_program_cache
from ..matrix.matrix import Matrix
from ..matrix.tiling import global_to_tiles, storage_tile_grid, tiles_to_global


def permute_array(coord: str, perm, arr):
    """``out[i] = in[perm[i]]`` along rows ('Row') or columns ('Col') of a
    plain (device) array — the gather primitive shared by the Matrix-level
    :func:`permute` and the D&C merge assembly (the reference's two callers
    of its permutation kernel, ``perms.cu:58-120``: workspace index sorts
    inside the merge, and matrix-level permutes)."""
    dlaf_assert(coord in ("Row", "Col"), f"bad coord {coord!r}")
    return jnp.take(arr, jnp.asarray(perm), axis=0 if coord == "Row" else 1)


def _gather_tables(nper: int, src: int, lt: int, bsz: int, a0: int, a1: int,
                   perm: np.ndarray, l0: int, w: int):
    """Per-mesh-coordinate gather tables for the distributed permute along
    one axis: for each (mesh coord p, local slot l, intra-tile offset r),
    the flat index into the gathered window ``(nper*w*bsz,)`` of the source
    position, and whether the position is inside the permuted range.

    Storage convention (matrix/tiling.py): slot ``l`` on mesh coordinate
    ``p`` holds global tile ``t = l*nper + (p - src) % nper``; tile ``t``
    lives on coordinate ``(t % nper + src) % nper`` at slot ``t // nper``.
    """
    rp = (np.arange(nper) - src) % nper                       # (nper,)
    t = np.arange(lt)[None, :] * nper + rp[:, None]           # (nper, lt)
    g = (t[:, :, None] * bsz + np.arange(bsz)).reshape(nper, lt * bsz)
    in_range = (g >= a0) & (g < a1)
    s = np.where(in_range,
                 perm[np.clip(g - a0, 0, max(len(perm) - 1, 0))] + a0, 0)
    ts, rs = s // bsz, s % bsz
    ps = (ts % nper + src) % nper
    ls = ts // nper - l0
    idx = np.where(in_range, ps * (w * bsz) + ls * bsz + rs, 0)
    return (jnp.asarray(idx.astype(np.int32)),
            jnp.asarray(in_range))


@register_program_cache
@functools.lru_cache(maxsize=64)
def _dist_permute_cached(dist, mesh, coord: str, l0: int, w: int):
    """jitted shard_map permute program for one (distribution, coord,
    slot-window) shape; the per-call permutation content rides in as the
    table/mask arguments, so distinct permutations of the same range share
    one compiled program."""
    Pr, Qc = dist.grid_size.row, dist.grid_size.col
    _, _, ltr, ltc = storage_tile_grid(dist)
    mb, nb = dist.block_size.row, dist.block_size.col

    def body(t, table, mask):
        if coord == "Row":
            i = jax.lax.axis_index(ROW_AXIS)
            idx, msk = jnp.take(table, i, axis=0), jnp.take(mask, i, axis=0)
            tw = jax.lax.slice_in_dim(t, l0, l0 + w, axis=0)
            g = jax.lax.all_gather(tw, ROW_AXIS)  # (Pr, w, ltc, mb, nb)
            g2 = g.transpose(0, 1, 3, 2, 4).reshape(Pr * w * mb, ltc, nb)
            lf = t.transpose(0, 2, 1, 3).reshape(ltr * mb, ltc, nb)
            new = jnp.where(msk[:, None, None],
                            jnp.take(g2, idx, axis=0), lf)
            return new.reshape(ltr, mb, ltc, nb).transpose(0, 2, 1, 3)
        i = jax.lax.axis_index(COL_AXIS)
        idx, msk = jnp.take(table, i, axis=0), jnp.take(mask, i, axis=0)
        tw = jax.lax.slice_in_dim(t, l0, l0 + w, axis=1)
        g = jax.lax.all_gather(tw, COL_AXIS)      # (Qc, ltr, w, mb, nb)
        g2 = g.transpose(0, 2, 4, 1, 3).reshape(Qc * w * nb, ltr, mb)
        lf = t.transpose(1, 3, 0, 2).reshape(ltc * nb, ltr, mb)
        new = jnp.where(msk[:, None, None], jnp.take(g2, idx, axis=0), lf)
        return new.reshape(ltc, nb, ltr, mb).transpose(2, 0, 3, 1)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(ROW_AXIS, COL_AXIS), P(), P()),
                   out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)
    return jax.jit(fn)


def permute(coord: str, perm, mat: Matrix, tile_begin: int = 0,
            tile_end: int | None = None) -> Matrix:
    """Permute rows (coord='Row') or columns ('Col') of the element range
    covered by tiles [tile_begin, tile_end); identity elsewhere.

    The distributed path requires a concrete (host) ``perm`` — the gather
    tables are trace-time data, which is what keeps the compiled program
    reusable across permutations of the same range."""
    dlaf_assert(coord in ("Row", "Col"), f"bad coord {coord!r}")
    nb = mat.block_size.row if coord == "Row" else mat.block_size.col
    ext = mat.size.row if coord == "Row" else mat.size.col
    a0 = tile_begin * nb
    a1 = ext if tile_end is None else min(tile_end * nb, ext)
    if a1 <= a0:
        return mat
    distributed = mat.grid is not None and mat.grid.num_devices > 1
    if not distributed:
        g = tiles_to_global(mat.storage, mat.dist)
        idx = jnp.asarray(perm) + a0
        if coord == "Row":
            g = g.at[a0:a1, :].set(permute_array("Row", idx, g))
        else:
            g = g.at[:, a0:a1].set(permute_array("Col", idx, g))
        return mat.with_storage(global_to_tiles(g, mat.dist))
    pm = np.asarray(perm)
    dlaf_assert(pm.ndim == 1 and len(pm) == a1 - a0,
                f"permute: perm length {len(pm)} != range {a1 - a0}")
    dlaf_assert_heavy(pm.min() >= 0 and pm.max() < a1 - a0,
                      "permute: perm indices outside the tile range")
    dist = mat.dist
    nper = dist.grid_size.row if coord == "Row" else dist.grid_size.col
    src = dist.source_rank.row if coord == "Row" else dist.source_rank.col
    _, _, ltr, ltc = storage_tile_grid(dist)
    lt = ltr if coord == "Row" else ltc
    t0, t1 = a0 // nb, -(-a1 // nb)
    l0, w = t0 // nper, (t1 - 1) // nper - t0 // nper + 1
    table, mask = _gather_tables(nper, src, lt, nb, a0, a1, pm, l0, w)
    fn = _dist_permute_cached(dist, mat.grid.mesh, coord, l0, w)
    return mat.with_storage(fn(mat.storage, table, mask))
