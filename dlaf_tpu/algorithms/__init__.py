"""L6 algorithms — public API (reference's free-function layer:
``factorization::cholesky``, ``solver::triangular``,
``multiplication::triangular``/``general``, ``eigensolver::genToStd``,
``permutations::permute``, ``auxiliary::norm``)."""

from .batched import cholesky_batched, eigh_batched, solve_batched
from .cholesky import cholesky
from .qr import t_factor
from .gen_to_std import gen_to_std
from .general import general_sub_multiply
from .norm import max_norm
from .permutations import permute
from .triangular import triangular_multiply, triangular_solve

__all__ = [
    "cholesky",
    "cholesky_batched",
    "eigh_batched",
    "solve_batched",
    "t_factor",
    "gen_to_std",
    "general_sub_multiply",
    "max_norm",
    "permute",
    "triangular_multiply",
    "triangular_solve",
]
