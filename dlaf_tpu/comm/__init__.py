"""L4 communication — public API (reference ``communication/``:
CommunicatorGrid + collective verbs over mesh axes)."""

from .grid import COL_AXIS, ROW_AXIS, Grid

__all__ = ["COL_AXIS", "ROW_AXIS", "Grid"]
