"""L4 communication — public API (reference ``communication/``:
CommunicatorGrid + collective verbs over mesh axes + the blocking
``sync`` tier for tests/checks)."""

from . import sync
from .grid import COL_AXIS, ROW_AXIS, Grid
from .multihost import initialize_multihost, multihost_grid, process_info

__all__ = ["COL_AXIS", "ROW_AXIS", "Grid", "sync"]
