"""Collective verbs over mesh axes, usable inside ``shard_map``.

TPU-native counterpart of the reference's L4 async tile collectives
(``communication/kernels/{broadcast,all_reduce,reduce,p2p,p2p_allsum}.h``).
The reference wraps nonblocking MPI calls in sender adaptors, serialized
per-communicator by ``Pipeline`` and polled from a dedicated "mpi" thread pool
(``sender/transform_mpi.h:56-98``). On TPU all of that machinery collapses
into XLA collectives over ICI: ordering is XLA program order inside the traced
step, overlap is XLA's latency hiding, and there is nothing to poll.

Each verb takes an ``axis`` (``'row'`` or ``'col'`` — see
:mod:`dlaf_tpu.comm.grid`). Broadcast *along* the row axis communicates among
ranks of the same grid column (the reference's column communicator) and vice
versa. Source/destination ranks must be trace-time constants, which they are
in the per-``k`` factorization loops (the loop is unrolled at trace time).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .grid import COL_AXIS, ROW_AXIS  # re-export for convenience  # noqa: F401
from .. import _compat
from .. import obs


#: Trace-time payload-corruption hook, installed ONLY by
#: ``health.inject.corrupt_collective`` (fault-injection drills); None in
#: production, so the cost is one module-attribute check per traced
#: collective. The hook sees (kind, axis, payload) and returns the —
#: possibly poisoned — payload.
_INJECT_HOOK = None


def _maybe_inject(kind: str, axis: str, x):
    if _INJECT_HOOK is None:
        return x
    return _INJECT_HOOK(kind, axis, x)


def _record(kind: str, axis: str, x) -> None:
    """Per-collective accounting (the per-kind/per-axis byte counters
    arXiv:2112.09017 credits its ICI tuning to): payload element count ×
    itemsize, attributed to the mesh axis. Shapes/dtypes are static even
    for traced operands, so this costs nothing at run time — counts
    accumulate when a program is TRACED (once per compiled program), which
    is exactly the per-program traffic model the tuning sessions need.
    With metrics off this is one attribute read and a return."""
    if not obs.metrics_active():
        return
    nbytes = int(x.size) * x.dtype.itemsize if hasattr(x, "size") else 0
    obs.counter("dlaf_comm_collective_count_total",
                kind=kind, axis=axis).inc()
    obs.counter("dlaf_comm_collective_bytes_total",
                kind=kind, axis=axis).inc(nbytes)


def this_rank(axis: str):
    """This device's coordinate along ``axis`` (reference ``Communicator::rank``)."""
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Number of ranks along ``axis`` (reference ``Communicator::size``)."""
    return _compat.axis_size(axis)


def bcast(x, axis: str, src: int):
    """Broadcast ``x`` from rank ``src`` along ``axis``
    (reference ``scheduleSendBcast``/``scheduleRecvBcast``,
    ``kernels/broadcast.h:62-115``).

    Two implementations (config knob ``bcast_impl``):

    * ``"psum"`` (default) — mask-then-psum: contributions from non-source
      ranks are zeroed, so the all-reduce returns exactly the source
      value. On a TPU ring this lowers to one all-reduce over ICI; XLA
      fuses the masking. For axis size p and payload V it moves
      ~2V(p-1)/p per link (reduce-scatter + all-gather) — within 2x of
      the V(p-1)/p one-to-all lower bound, the right shape for the
      bandwidth-bound panel broadcasts.
    * ``"tree"`` — binomial doubling over ``ppermute`` rounds: ceil(log2 p)
      serialized collective-permutes, each moving the full payload on
      disjoint links. ~log2(p) link latencies vs the ring's ~2(p-1), at
      log2(p)x the per-link traffic — the candidate winner for SMALL
      payloads (diagonal tiles) where hop latency dominates. (A one-hop
      multicast is not expressible: XLA collective-permute requires
      unique sources AND destinations.)

    First multi-chip access must A/B the two on real ICI (round-2 review
    carried this); the knob makes both measurable with the same programs.
    """
    from ..config import get_configuration

    _record("bcast", axis, x)
    x = _maybe_inject("bcast", axis, x)
    if get_configuration().bcast_impl == "tree":
        return _bcast_tree(x, axis, src)
    mask = (this_rank(axis) == src).astype(x.dtype)
    return lax.psum(x * mask, axis)


def _bcast_tree(x, axis: str, src: int):
    """Binomial-tree broadcast: at round r (r = 1, 2, 4, ...), ranks
    ``src .. src+r-1`` (cyclically) send to ``src+r .. src+2r-1`` in one
    ``ppermute`` with disjoint pairs. Handles non-power-of-2 axis sizes."""
    p = axis_size(axis)
    dist = (this_rank(axis) - src) % p
    val = x
    r = 1
    while r < p:
        npairs = min(r, p - r)
        perm = [((src + i) % p, (src + i + r) % p) for i in range(npairs)]
        sent = lax.ppermute(val, axis, perm=perm)
        take = (dist >= r) & (dist < min(2 * r, p))
        val = jnp.where(take, sent, val)
        r *= 2
    return val


def bcast2d(x, owner_r: int, owner_c: int):
    """Broadcast ``x`` from the single rank ``(owner_r, owner_c)`` to the
    whole 2D mesh in ONE collective (the diagonal-tile broadcast of every
    blocked factorization step — reference ``cholesky/impl.h:215-219``).

    Replaces the two-hop ``bcast(bcast(x, 'row', r), 'col', c)``: under the
    default mask+psum realization the two hops are two serialized
    all-reduces on the step critical path; here the payload is masked to
    the owning rank and ONE ``psum`` over BOTH mesh axes delivers it —
    XLA lowers this to a single all-reduce over the combined replica
    groups. Bitwise-identical to the two-hop form: either way the result
    is the owner's value plus exact zeros (the same masked-add discipline,
    including the ``-0.0 + 0.0 -> +0.0`` flattening any psum with more
    than one participant performs).

    ``bcast_impl="tree"`` has no 2-axis fusion (ppermute pairs live on one
    axis), so it keeps the two-hop binomial trees.

    Accounting: recorded once per axis under kind ``"bcast2d"`` so the
    per-axis byte counters see the same per-axis payload the two-hop form
    charged; the injection hook fires once (kind ``"bcast2d"``), and
    ``health.inject.corrupt_collective("bcast", ...)`` matches it too.
    """
    from ..config import get_configuration

    _record("bcast2d", ROW_AXIS, x)
    _record("bcast2d", COL_AXIS, x)
    x = _maybe_inject("bcast2d", ROW_AXIS, x)
    if get_configuration().bcast_impl == "tree":
        return _bcast_tree(_bcast_tree(x, ROW_AXIS, owner_r),
                           COL_AXIS, owner_c)
    mask = ((this_rank(ROW_AXIS) == owner_r)
            & (this_rank(COL_AXIS) == owner_c)).astype(x.dtype)
    return lax.psum(x * mask, (ROW_AXIS, COL_AXIS))


def record_overlapped(algo: str, axis: str, n: int = 1) -> None:
    """Trace-time accounting of HOISTED collectives (``comm_lookahead``,
    docs/comm_overlap.md): each collective a distributed builder emits
    BEFORE the preceding step's bulk trailing product — i.e. a transfer
    XLA can run on the ICI while the MXU grinds the bulk gemms — bumps
    ``dlaf_comm_overlapped_total{algo,axis}`` once per compiled program.
    Same trace-time semantics as the byte counters above."""
    if obs.metrics_active() and n:
        obs.counter("dlaf_comm_overlapped_total", algo=algo,
                    axis=axis).inc(n)


def all_reduce(x, axis: str, op: str = "sum"):
    """All-reduce along ``axis`` (reference ``scheduleAllReduce``,
    ``kernels/all_reduce.h:67-138``). The rooted :func:`reduce` lowers
    through here, so its traffic is accounted under this kind too."""
    _record("all_reduce", axis, x)
    x = _maybe_inject("all_reduce", axis, x)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def reduce(x, axis: str, root: int, op: str = "sum"):
    """Reduce to ``root`` (reference ``scheduleReduceRecvInPlace`` +
    ``scheduleReduceSend``, ``kernels/reduce.h:36-124``).

    SPMD realization: the reduction runs as an all-reduce (one XLA
    collective; there is no partial-reduce primitive), and non-root ranks
    get ZEROS — the reference's contract defines only the root's output
    tile, and zeroing makes accidental reads of non-root results surface
    in tests instead of silently working and then breaking under a real
    rooted implementation.
    """
    full = all_reduce(x, axis, op)
    return jnp.where(this_rank(axis) == root, full,
                     jnp.zeros_like(full))


def send_recv(x, axis: str, src: int, dst: int):
    """Point-to-point move of ``x`` from ``src`` to ``dst`` along ``axis``
    (reference ``scheduleSend``/``scheduleRecv``, ``kernels/p2p.h:34-105``).

    Returns the sent value on ``dst``; other ranks get zeros. Lowered to an
    XLA collective-permute (one ICI hop for neighbours).
    """
    _record("send_recv", axis, x)
    return lax.ppermute(x, axis, perm=[(src, dst)])


def all_sum_p2p(x, axis: str):
    """Sum over an axis intended for the 2-rank case (reference
    ``scheduleAllSumP2P``, ``kernels/p2p_allsum.h:39-60``: a send/recv pair
    plus local add). XLA's psum already specializes the 2-rank ring."""
    _record("all_sum_p2p", axis, x)
    return lax.psum(x, axis)


def all_gather(x, axis: str, *, tiled: bool = False, concat_axis: int = 0):
    """Gather ``x`` from every rank along ``axis``; result has a new leading
    axis of size ``axis_size``, or is concatenated along array axis
    ``concat_axis`` when ``tiled``. Used by panel broadcast to give every rank
    the full panel (reference ``broadcast_panel.h`` achieves the same with
    per-tile bcasts)."""
    _record("all_gather", axis, x)
    x = _maybe_inject("all_gather", axis, x)
    return lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    """Tiled all-to-all along ``axis`` (the layout-transpose verb of the
    distributed chase back-transform, eigensolver/back_transform.py: each
    rank scatters ``split_axis`` slices and concatenates the received
    ones along ``concat_axis``). The reference pipelines per-tile sends
    instead (``bt_band_to_tridiag/impl.h``); on ICI one all_to_all moves
    V(p-1)/p per link in a single collective. Accounted and injectable
    like every other verb."""
    _record("all_to_all", axis, x)
    x = _maybe_inject("all_to_all", axis, x)
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def barrier_value(x, axis: str):
    """Order-enforcing no-op: returns ``x`` after a reduction over a token.

    The reference fences benchmark timing with ``MPI_Barrier``
    (``miniapp_cholesky.cpp:134-146``); inside one traced program XLA order
    suffices, so this exists for cross-program fencing in miniapps.
    """
    z = jnp.zeros((), x.dtype)
    _record("barrier", axis, z)
    token = lax.psum(z, axis)
    return x + token
