"""2D process grid over TPU devices.

TPU-native counterpart of the reference's ``Communicator`` /
``CommunicatorGrid`` (``communication/communicator.h:37-93``,
``communicator_grid.h:42-109``). The reference builds row/col MPI
sub-communicators from a parent communicator with row-major or col-major rank
ordering; here the grid *is* a ``jax.sharding.Mesh`` with axes ``('row',
'col')``, and the row/col "sub-communicators" are the mesh axes themselves —
every collective verb in :mod:`.collectives` takes an axis name.

JAX is single-controller SPMD: there is no per-process rank at the Python
level. Code that needs "my grid coordinates" runs inside ``shard_map`` and
asks :func:`dlaf_tpu.comm.collectives.this_rank`.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..common.asserts import dlaf_assert
from ..common.index2d import GridSize2D

#: Mesh axis names: 'row' indexes grid rows (the reference's column
#: communicator direction — ranks in the same grid *column* differ in 'row'),
#: 'col' indexes grid columns.
ROW_AXIS = "row"
COL_AXIS = "col"


class Grid:
    """A rows x cols device grid (reference ``CommunicatorGrid``).

    ``ordering`` controls how the flat device list fills the grid, mirroring
    the reference's ``common::Ordering`` ctor argument: "row-major" assigns
    device ``i`` to grid position ``(i // cols, i % cols)``, "col-major" to
    ``(i % rows, i // rows)``.
    """

    def __init__(self, rows: int, cols: int, devices=None, ordering: str = "row-major"):
        if devices is None:
            devices = jax.devices()
        dlaf_assert(rows * cols <= len(devices),
                    f"grid {rows}x{cols} needs {rows * cols} devices, have {len(devices)}")
        devices = list(devices)[: rows * cols]
        if ordering == "row-major":
            dev2d = np.array(devices, dtype=object).reshape(rows, cols)
        elif ordering == "col-major":
            dev2d = np.array(devices, dtype=object).reshape(cols, rows).T
        else:
            raise ValueError(f"unknown ordering {ordering!r}")
        self._mesh = Mesh(dev2d, (ROW_AXIS, COL_AXIS))
        self._ordering = ordering

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def size(self) -> GridSize2D:
        """Grid extents (reference ``CommunicatorGrid::size``)."""
        return GridSize2D(self._mesh.shape[ROW_AXIS], self._mesh.shape[COL_AXIS])

    @property
    def num_devices(self) -> int:
        return self.size.row * self.size.col

    @property
    def ordering(self) -> str:
        return self._ordering

    def tile_sharding(self) -> NamedSharding:
        """Sharding for block-cyclic tile storage arrays
        (leading two dims = storage tile grid, sharded over row/col)."""
        return NamedSharding(self._mesh, PartitionSpec(ROW_AXIS, COL_AXIS))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self._mesh, PartitionSpec())

    def __str__(self) -> str:
        return f"Grid({self.size.row}x{self.size.col}, {self._ordering})"


def single_device_grid() -> Grid:
    """1x1 grid on the default device (reference single-rank communicator)."""
    return Grid(1, 1)
