"""Blocking host-side communication tier.

TPU-native counterpart of the reference's third comm tier, the blocking
``sync::`` wrappers (``communication/sync/broadcast.h:28-76``,
``sync/reduce.h``, ``sync/all_reduce.h``, ``sync/basic.h:28-164``,
``functions_sync.h``): used by tests and result checking, never by
algorithm hot paths.

In the reference every rank owns only its shard, so checking a result
means blocking MPI traffic (gather-by-broadcast, reduce to a master
rank). Under the single-controller SPMD model the host process already
addresses every shard; the blocking tier therefore becomes *device→host*
movement rather than rank→rank movement: pull shards with
``jax.device_get`` (which blocks until the producing computation is
done) and combine on host with numpy. The verbs keep the reference's
names and its "tests/checks only" role — algorithm hot paths use the
compiled ICI collectives in :mod:`dlaf_tpu.comm.collectives` instead,
exactly as the reference splits ``sync::`` from the async sender tier.

Rank→rank p2p (``sync::basic::send_to/receive_from``) has no residue
here: there is no second controller to exchange with, and host code can
read any shard directly via ``gather_shards``.
"""

from __future__ import annotations

import numpy as np


from ..common.sync import hard_fence
from ..matrix import memory

__all__ = ["gather", "gather_shards", "all_reduce", "reduce", "barrier"]


def gather(mat) -> np.ndarray:
    """Blocking gather of a distributed ``Matrix`` to one host array.

    The reference test suite's ``matrix_local.h`` gather: every rank
    broadcasts its tiles (``sync::broadcast``) until all ranks hold the
    global matrix. Here: one blocking device→host pull of the tile
    storage, then the inverse block-cyclic re-tile on host.
    ``Matrix.to_numpy`` delegates to this.
    """
    from ..matrix import tiling

    return np.asarray(
        tiling.tiles_to_global(memory.fetch(mat.storage), mat.dist))


def gather_shards(x) -> list[np.ndarray]:
    """Per-rank host copies of a sharded array, in device order
    (the blocking analog of each rank reading its local part;
    reference ``sync::basic::receive_from`` at the test master)."""
    if hasattr(x, "addressable_shards"):
        return [memory.fetch(s.data) for s in x.addressable_shards]
    return [memory.fetch(x) if hasattr(x, "devices") else np.asarray(x)]


def all_reduce(values, op: str = "sum"):
    """Blocking host fold of per-rank partial values
    (reference ``sync::allReduceInPlace``, ``sync/all_reduce.h``)."""
    ops = {"sum": np.sum, "max": np.max, "min": np.min,
           "prod": np.prod}
    if op not in ops:
        raise ValueError(f"unsupported reduce op {op!r}")
    return ops[op](np.stack([np.asarray(v) for v in values]), axis=0)


def reduce(values, root: int = 0, op: str = "sum"):
    """Blocking reduce "to ``root``" (reference ``sync::reduce``,
    ``sync/reduce.h``). The host plays every rank, so the result is the
    same object regardless of ``root``; the argument is kept for
    call-site parity with the reference's signature."""
    del root
    return all_reduce(values, op)


#: Blocking completion fence (reference ``MPI_Barrier`` in the miniapp
#: timing protocol, ``miniapp_cholesky.cpp:134-146``); see
#: :func:`dlaf_tpu.common.sync.hard_fence` for the tunnel-proof design.
barrier = hard_fence
