"""Multi-host bring-up: ICI+DCN grids spanning TPU pods/slices.

The reference scales past one node with MPI: ``mpi_init`` establishes the
process world (``communication/init.h:14-44``) and ``CommunicatorGrid``
spans it. The TPU-native equivalents:

* process world      -> ``jax.distributed.initialize`` (one controller
  process per host; coordinator address/process-id discovery is automatic
  on Cloud TPU and explicit elsewhere) — :func:`initialize_multihost`.
* rank               -> ``jax.process_index()``.
* grid over the world -> a 2D mesh over ``jax.devices()`` (ALL processes'
  devices, in a topology-aware order) — :func:`multihost_grid`.

Physics of the axes: within a slice, neighboring devices talk over ICI
(fast); across slices/pods the boundary is DCN (slow). ``multihost_grid``
keeps the *contiguous-minor* axis of the device order inside a slice where
the grid shape allows: when the per-slice device count is a multiple of
``cols``, ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` lays
the 'col' axis (and the minor rows) entirely inside each slice, so the
high-traffic panel broadcasts ride ICI and only the outer 'row' axis
crosses DCN. Otherwise a slice-major reshape heuristic is used — in that
regime a 'col' axis wider than one slice necessarily crosses DCN at slice
boundaries (there is no layout that avoids it). Single-slice or CPU worlds
use a plain device-order reshape.

Data loading in the multi-controller model: each process creates ONLY its
addressable shards; :func:`dlaf_tpu.matrix.matrix.Matrix.from_element_fn`
evaluates the element function per local tile, so no host ever materializes
the global matrix — the analog of the reference's per-rank tile allocation.

This module is glue, not magic: on a single-process run every function is a
cheap no-op/alias, which is also how it is exercised in CI (the logic that
*can* be tested without a pod — axis assignment, ordering, shard-count
math — is; the ``jax.distributed`` call itself is a pass-through).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax

from ..common.asserts import dlaf_assert
from .grid import COL_AXIS, ROW_AXIS, Grid


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         timeout: Optional[float] = 300.0,
                         connect_attempts: int = 3,
                         connect_backoff_s: float = 1.0) -> None:
    """Establish the cross-host process world (the ``mpi_init`` analog).

    On Cloud TPU all arguments are auto-discovered; elsewhere pass the
    coordinator's ``host:port``, the world size, and this process's id.
    Must run before any other JAX call in the process (same rule as the
    reference's "MPI_Init before everything", ``communication/init.h``).
    No-op when the world has a single process and no coordinator is given.

    ``timeout`` bounds each coordinator-connect attempt (seconds; None =
    the JAX default). The connect runs on the shared
    :mod:`dlaf_tpu.health.policy` engine: a transient bring-up failure
    (timeout / connection refused / unreachable — :func:`_is_bringup_
    failure`) retries up to ``connect_attempts`` times with exponential
    backoff from ``connect_backoff_s`` (deterministic seeded jitter; one
    ``dlaf_retry_total{site="multihost.connect"}`` + ``resilience``
    record per retry), because a coordinator that is still scheduling is
    the COMMON pod bring-up race. Caller bugs (double init, bad args)
    raise immediately with their own message. Exhaustion keeps the
    pinned contract: a RuntimeError naming the coordinator, the world
    shape, and the usual causes — actionable from a single host's log.
    """
    if coordinator_address is None and num_processes in (None, 1):
        return  # single-controller run — nothing to establish
    import inspect

    from ..health.policy import RetryPolicy, with_policy

    kwargs = {}
    if timeout is not None:
        # older jax lines lack the kwarg; the bound is best-effort there
        params = inspect.signature(jax.distributed.initialize).parameters
        if "initialization_timeout" in params:
            kwargs["initialization_timeout"] = int(timeout)

    def _connect():
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kwargs)

    policy = RetryPolicy(max_attempts=max(int(connect_attempts), 1),
                         backoff_base_s=float(connect_backoff_s),
                         retryable=_is_bringup_failure)
    try:
        with_policy("multihost.connect", _connect, policy=policy)
    except Exception as e:
        if not _is_bringup_failure(e):
            raise   # caller bugs (double init, bad args) keep their message
        world = f"{num_processes} process(es)" if num_processes else "auto"
        raise RuntimeError(
            f"multi-host bring-up failed: could not establish the process "
            f"world (coordinator={coordinator_address!r}, world={world}, "
            f"process_id={process_id!r}"
            + (f", timeout={int(timeout)}s" if timeout is not None else "")
            + f"): {e}. Check that (1) the coordinator host:port is "
            "reachable from this host (firewall/VPC rules), (2) EVERY "
            "process of the world starts within the timeout with the SAME "
            "coordinator address and world size, and (3) process ids are "
            "unique in [0, world). On Cloud TPU, omit all arguments — "
            "discovery is automatic.") from e
    # pin the now-authoritative rank onto the observability layer and
    # re-resolve the metrics path: a DLAF_METRICS_PATH ``%r`` template
    # expanded before the distributed runtime came up would have labeled
    # every host rank 0 — and every host would append to the same file,
    # the interleaving the per-rank convention exists to prevent
    from .. import obs
    from ..config import get_configuration

    obs.set_rank(jax.process_index())
    cfg = get_configuration()
    if "%r" in (cfg.metrics_path or ""):
        obs.configure(log_level=cfg.log, metrics_path=cfg.metrics_path,
                      trace_dir=cfg.trace_dir or cfg.profile_dir,
                      program_telemetry=cfg.program_telemetry)


def _is_bringup_failure(e: BaseException) -> bool:
    """Does this look like a coordinator-connect failure (worth the
    actionable bring-up diagnosis) rather than a caller bug? Double
    initialization or bad arguments must keep their own message — sending
    an operator to debug firewalls for those would be worse than no
    wrapping at all."""
    if isinstance(e, (TimeoutError, ConnectionError, OSError)):
        return True
    text = str(e).lower()
    return any(s in text for s in ("timeout", "deadline", "unavailable",
                                   "connect", "refused", "unreachable"))


def slice_groups(devices: Sequence) -> dict:
    """Group devices by their slice/granule (``slice_index`` where the
    platform exposes it; one group otherwise) — the ICI islands."""
    groups: dict = {}
    for d in devices:
        key = getattr(d, "slice_index", 0)
        groups.setdefault(key, []).append(d)
    return groups


def multihost_grid(rows: Optional[int] = None, cols: Optional[int] = None,
                   *, devices: Optional[Sequence] = None) -> Grid:
    """A 2D grid over every device of every process, topology-aware.

    Axis policy (the scaling-relevant decision): the 'col' axis is laid out
    inside ICI islands wherever the factorization allows, so panel
    broadcasts along rows of the matrix (the hot collective of the
    right-looking algorithms) stay on ICI; the 'row' axis absorbs the
    DCN boundary. With ``rows``/``cols`` omitted, the squarest
    factorization of the world size with that property is chosen.
    """
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if rows is None or cols is None:
        rows = int(np.sqrt(n))
        while n % rows:
            rows -= 1
        cols = n // rows
    dev2d = layout_2d(devs, rows, cols)
    g = Grid.__new__(Grid)
    from jax.sharding import Mesh

    g._mesh = Mesh(dev2d, (ROW_AXIS, COL_AXIS))
    g._ordering = "row-major"
    return g


def layout_2d(devs: Sequence, rows: int, cols: int) -> np.ndarray:
    """The topology-aware (rows, cols) device layout — pure function of the
    device sequence and its slice grouping, so the ICI/DCN axis decisions
    are testable without a pod (fake devices with ``slice_index`` work)."""
    n = len(devs)
    dlaf_assert(rows * cols == n,
                f"multihost grid {rows}x{cols} must use all {n} devices")
    groups = slice_groups(devs)
    dev2d = None
    if len(groups) > 1:
        sizes = {len(g) for g in groups.values()}
        dlaf_assert(len(sizes) == 1, "hetero slice sizes unsupported")
        per = sizes.pop()
        if per % cols == 0:
            # grid factors over the slice size: route through the canonical
            # helper so the 'col' axis (and the minor rows) sit entirely
            # inside each slice — the documented ICI guarantee
            try:
                from jax.experimental import mesh_utils

                dev2d = np.asarray(mesh_utils.create_hybrid_device_mesh(
                    (per // cols, cols), (len(groups), 1), devices=devs))
            except Exception:
                dev2d = None  # helper unavailable/unhappy: reshape heuristic
        if dev2d is None:
            if cols % per == 0 or per % cols == 0:
                # slice-major order: consecutive 'col' neighbors share a
                # slice where possible; a col axis spanning whole slices
                # DOES cross DCN at slice boundaries
                ordered = [d for k in sorted(groups) for d in groups[k]]
            else:
                ordered = devs
            dev2d = np.array(ordered, dtype=object).reshape(rows, cols)
    else:
        dev2d = np.array(devs, dtype=object).reshape(rows, cols)
    return dev2d


def process_info() -> tuple:
    """(process_index, process_count) — the reference's (rank, size) at the
    host level."""
    return jax.process_index(), jax.process_count()
