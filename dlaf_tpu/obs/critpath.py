"""Critical-path and stall attribution from device traces.

Reconstructs the executed per-step timeline of the pipelined builders
(cholesky, trsm, trmm, hegst, red2band, bt_r2b) by joining device
intervals from a profiler trace to the per-step ``named_scope`` structure
recovered from compiled HLO (``schedule`` records emitted by
``obs.telemetry.aot_compile``).  Per step k it reports the measured
panel / strip / bulk / collective / copy walls, the idle *gap* between
step k's last op and step k+1's first dependent op, the critical path
through the step DAG, a bound classification, and Amdahl-style what-if
projections ("collectives free -> wall -X%", "gaps closed -> +Y GF/s").

Usage:
    python -m dlaf_tpu.obs.critpath TRACE MERGED.jsonl [options]

    TRACE           profiler trace file (*.trace.json[.gz]) or a
                    directory to search for the newest one
    MERGED.jsonl    merged observability artifact; must contain the
                    ``schedule`` records for the traced programs

Options:
    -o PATH             append critpath/whatif JSONL records to PATH
    --json PATH         write the full report as JSON to PATH
    --top N             show at most N steps per program (default 32)
    --steps N           scan-built programs: force the step count when it
                        cannot be inferred from the trace
    --inject-gap SPEC   testing: shift the device timeline to open an
                        artificial gap, SPEC = <algo>.step<k>=<ms>
                        (e.g. cholesky.step002=5 injects 5 ms of idle
                        immediately before step 2 in every run)
    --distill PATH      write a minimal replayable trace JSON to PATH

Exit codes: 0 ok, 1 no per-step attribution possible, 2 bad arguments.
"""

from __future__ import annotations

import gzip
import json
import os
import re
import sys
import time
from typing import Any

from .devtrace import (
    _fallback_windows,
    _intersect_len,
    _is_device_event,
    _meta_maps,
    _union,
    classify_op,
    distill as _devtrace_distill,
    host_span_events,
    load_trace,
)
from .sinks import SCHEMA_VERSION

PHASES = ("panel", "strip", "bulk", "other")

# Bound classes, in reporting order.  "panel" folds in the strip phase
# (both sit on the panel-chain critical path), "comm"/"copy" are the
# collective/copy categories regardless of phase, "gap" is measured idle.
BOUNDS = ("panel", "bulk", "comm", "copy", "gap")

# op_name metadata scope patterns.  Innermost (last) match wins so a
# comm-lookahead panel chain hoisted into step k's outer scope but tagged
# ``<algo>.step<k+1>.panel`` is attributed to step k+1.
_STEP_RE = re.compile(r"([A-Za-z0-9_]+)\.step(\d+)(?:\.(panel|strip|bulk))?")
_SCAN_RE = re.compile(r"([A-Za-z0-9_]+)\.scanstep(?:\.(panel|strip|bulk))?")
_OP_RE = re.compile(r'%?([\w.\-]+) = .*op_name="([^"]*)"')
_MODULE_RE = re.compile(r"^HloModule ([\w.\-]+)", re.MULTILINE)


# ---------------------------------------------------------------------------
# schedule extraction (compile time)


def schedule_from_hlo(hlo_text: str) -> dict[str, Any]:
    """Parse optimized HLO text into a schedule map.

    Returns ``{"module": name, "ops": {instr_name: [algo, step, phase]}}``
    where ``step`` is an int for unrolled builders and ``-1`` for scan
    bodies (a scan body is traced once for all iterations, so its ops
    carry no step index; the joiner reconstructs iterations from
    occurrence order).  Instructions without a step scope are omitted.
    """
    m = _MODULE_RE.search(hlo_text)
    module = m.group(1) if m else ""
    ops: dict[str, list[Any]] = {}
    for line in hlo_text.splitlines():
        om = _OP_RE.search(line)
        if om is None:
            continue
        name, op_name = om.group(1), om.group(2)
        hits = list(_STEP_RE.finditer(op_name))
        if hits:
            h = hits[-1]  # innermost scope wins
            ops[name] = [h.group(1), int(h.group(2)), h.group(3) or "other"]
            continue
        sm = list(_SCAN_RE.finditer(op_name))
        if sm:
            h = sm[-1]
            ops[name] = [h.group(1), -1, h.group(2) or "other"]
    return {"module": module, "ops": ops}


def schedule_record(site: str, hlo_text: str) -> dict[str, Any] | None:
    """Build a ``schedule`` JSONL record from compiled HLO, or ``None``
    when the program carries no per-step scopes (nothing to join)."""
    sched = schedule_from_hlo(hlo_text)
    if not sched["ops"]:
        return None
    algos: dict[str, dict[str, Any]] = {}
    for algo, step, _phase in sched["ops"].values():
        a = algos.setdefault(algo, {"steps": 0, "scan": False})
        if step < 0:
            a["scan"] = True
        else:
            a["steps"] = max(a["steps"], step + 1)
    return {
        "type": "schedule",
        "v": SCHEMA_VERSION,
        "ts": time.time(),
        "site": site,
        "module": sched["module"],
        "n_ops": len(sched["ops"]),
        "algos": algos,
        "ops": [[k, *v] for k, v in sched["ops"].items()],
    }


def _op_maps(records: list[dict]) -> tuple[dict, dict, dict]:
    """Collapse schedule records into lookup maps.

    Returns ``(by_module_op, by_op, algo_meta)`` where the first keys on
    ``(module, instr)``, the second on bare ``instr`` (fallback when a
    device event carries no hlo_module), and the third maps algo ->
    {"steps", "scan"} merged across programs.
    """
    by_mod: dict[tuple[str, str], list] = {}
    by_op: dict[str, list] = {}
    meta: dict[str, dict] = {}
    for rec in records:
        if rec.get("type") != "schedule":
            continue
        module = rec.get("module", "")
        for entry in rec.get("ops", ()):
            name, algo, step, phase = entry[0], entry[1], int(entry[2]), entry[3]
            by_mod[(module, name)] = [algo, step, phase]
            by_op[name] = [algo, step, phase]
        for algo, a in (rec.get("algos") or {}).items():
            cur = meta.setdefault(algo, {"steps": 0, "scan": False})
            cur["steps"] = max(cur["steps"], int(a.get("steps", 0)))
            cur["scan"] = cur["scan"] or bool(a.get("scan", False))
    return by_mod, by_op, meta


# ---------------------------------------------------------------------------
# device-event join


def _scheduled_events(events: list[dict], records: list[dict]):
    """Join raw trace events to the schedule.

    Returns ``(joined, busy_total_s, busy_sched_modules_s)`` where
    ``joined`` is a list of dicts with keys lo/hi (seconds), algo, step,
    phase, cat, name, domain.  ``busy_sched_modules_s`` counts device busy
    restricted to modules that have a schedule (the coverage denominator:
    unrelated programs in the trace must not dilute coverage).
    """
    by_mod, by_op, _meta = _op_maps(records)
    if not by_op:
        raise ValueError(
            "artifact contains no schedule records; run with "
            "DLAF_PROGRAM_TELEMETRY=1 so obs.telemetry.aot_compile can "
            "record the per-step HLO schedule"
        )
    modules = {m for (m, _n) in by_mod}
    procs, _threads = _meta_maps(events)
    joined: list[dict] = []
    busy_total = 0.0
    busy_sched = 0.0
    for e in events:
        if e.get("ph") != "X" or not _is_device_event(e, procs):
            continue
        dur = float(e.get("dur", 0.0))
        if dur <= 0.0:
            continue
        busy_total += dur
        args = e.get("args") or {}
        op = args.get("hlo_op") or e.get("name", "")
        module = args.get("hlo_module", "")
        if module in modules:
            busy_sched += dur
        entry = by_mod.get((module, op)) if module else None
        if entry is None:
            entry = by_op.get(op)
        if entry is None:
            continue
        cat, _kind = classify_op(e.get("name", ""))
        ts = float(e["ts"])
        pid = e.get("pid")
        proc = procs.get(pid, "")
        joined.append(
            {
                "lo": ts * 1e-6,
                "hi": (ts + dur) * 1e-6,
                "algo": entry[0],
                "step": int(entry[1]),
                "phase": entry[2],
                "cat": cat or "compute",
                "name": e.get("name", ""),
                "domain": pid if "/device:" in proc.lower() else (pid, e.get("tid")),
            }
        )
    denom = busy_sched if busy_sched > 0.0 else busy_total
    return joined, busy_total * 1e-6, denom * 1e-6


def _run_windows(events: list[dict], records: list[dict]):
    """Per-run host windows, newest-devtrace style.

    Prefers in-trace host span events matching the span vocabulary in
    ``records`` (annotation join); falls back to rebasing per-rank span
    records onto the device-time origin (mirror-less traces).  Returns
    ``(windows, join)`` with windows sorted by start, each
    ``(lo_s, hi_s, name)``.
    """
    span_names = {r.get("name") for r in records if r.get("type") == "span"}
    span_names.discard(None)
    procs, _threads = _meta_maps(events)
    devs = []  # µs, as _fallback_windows expects
    for e in events:
        if e.get("ph") == "X" and float(e.get("dur", 0) or 0) > 0 and _is_device_event(e, procs):
            ts = float(e["ts"])
            devs.append((ts, ts + float(e["dur"])))
    hosts = host_span_events(events, span_names)
    join = "annotation"
    if not hosts:
        hosts = _fallback_windows(records, devs)
        join = "rebase"
    windows = sorted(
        ((lo * 1e-6, hi * 1e-6, name) for (lo, hi, name) in hosts),
        key=lambda w: (w[0], -(w[1])),
    )
    return windows, join


def _assign_runs(joined: list[dict], windows) -> None:
    """Tag every joined event with a run id (innermost containing host
    window, by window identity).  Without windows: a single run for scan
    programs, and step-index-drop segmentation for unrolled ones."""
    if windows:
        from bisect import bisect_right

        # nested/overlapping windows (miniapp.run > factor > entry span,
        # or one run's spans mirrored from several ranks) collapse into
        # one physical-run interval each
        merged = _union([(lo, hi) for (lo, hi, _name) in windows])
        starts = [lo for lo, _hi in merged]
        for ev in joined:
            mid = 0.5 * (ev["lo"] + ev["hi"])
            # containing interval, else the nearest preceding one (device
            # ops dispatched after the host span closed stay in their run)
            ev["run"] = max(0, bisect_right(starts, mid) - 1)
        return
    # no windows at all: synthetic traces / stripped fixtures
    by_algo: dict[str, list[dict]] = {}
    for ev in joined:
        by_algo.setdefault(ev["algo"], []).append(ev)
    for evs in by_algo.values():
        evs.sort(key=lambda e: e["lo"])
        run = 0
        prev_step = -1
        for ev in evs:
            if 0 <= ev["step"] < prev_step:
                run += 1
            if ev["step"] >= 0:
                prev_step = ev["step"]
            ev["run"] = run


def _scan_steps(evs: list[dict], steps_hint: int | None) -> None:
    """Assign step indices to one run of a scan-built program.

    A scan body is traced once, so every iteration executes the same
    instruction set once per device; the anchor — the (op, device) pair
    whose occurrence count matches the expected iteration total (or the
    modal count across pairs) — marks iteration boundaries and events
    bucket by start time.
    """
    from bisect import bisect_right
    from collections import Counter

    occ: dict[tuple, list[float]] = {}
    for ev in evs:
        occ.setdefault((ev["name"], ev["domain"]), []).append(ev["lo"])
    if not occ:
        return
    counts = Counter(len(v) for v in occ.values())
    if steps_hint and steps_hint in counts:
        target = steps_hint
    elif steps_hint and any(c <= steps_hint for c in counts):
        # inner device loops repeat per iteration; the closest count not
        # exceeding the expected iteration total is the body's own rank
        target = max(c for c in counts if c <= steps_hint)
    else:
        target = counts.most_common(1)[0][0]
    anchors = [key for key, v in occ.items() if len(v) == target]
    # earliest-starting anchor bounds each iteration
    anchor = min(anchors, key=lambda k: min(occ[k]))
    bounds = sorted(occ[anchor])
    for ev in evs:
        ev["step"] = max(0, bisect_right(bounds, ev["lo"]) - 1)


# ---------------------------------------------------------------------------
# per-step accounting


def _detangle_shared(revs: list[dict]) -> None:
    """Re-assign CSE-shared instructions within one unrolled run.

    XLA deduplicates identical subcomputations across steps; the shared
    instruction keeps the FIRST emitter's op_name metadata, so its every
    execution would land in that step and stretch its window across the
    run.  Ops executing once in the run are reliably tagged; ops
    executing more than once keep their tag only when they fall inside
    that step's unique-op window, otherwise they move to the step whose
    window contains them (innermost on overlap), or the nearest one.
    """
    from collections import Counter

    # one execution per device is the unrolled norm — shared/CSE'd ops
    # stand out by repeating within a single overlap domain
    counts = Counter((e["name"], e["domain"]) for e in revs)
    win: dict[int, list[float]] = {}
    for e in revs:
        if counts[(e["name"], e["domain"])] == 1 and e["step"] >= 0:
            w = win.setdefault(e["step"], [e["lo"], e["hi"]])
            w[0] = min(w[0], e["lo"])
            w[1] = max(w[1], e["hi"])
    if not win:
        return
    for e in revs:
        if counts[(e["name"], e["domain"])] == 1:
            continue
        mid = 0.5 * (e["lo"] + e["hi"])
        tagged = win.get(e["step"])
        if tagged and tagged[0] <= mid <= tagged[1]:
            continue
        inside = [(hi - lo, k) for k, (lo, hi) in win.items() if lo <= mid <= hi]
        if inside:
            e["step"] = min(inside)[1]
        else:
            e["step"] = min(win, key=lambda k: min(abs(mid - win[k][0]),
                                                   abs(mid - win[k][1])))


def _infer_steps(algo: str, records: list[dict]) -> int | None:
    """Step count from the entry span's (n, nb) attrs — the scan joiner's
    default iteration total when ``--steps`` is not given."""
    for r in records:
        if r.get("type") != "span":
            continue
        name = r.get("name", "")
        attrs = r.get("attrs") or r
        n, nb = attrs.get("n"), attrs.get("nb")
        if n and nb and (name == algo or algo in name):
            return -(-int(n) // int(nb))
    return None


def _flops_for(algo: str, records: list[dict]) -> float | None:
    """Per-run flop count from the entry span records, if recorded."""
    best = None
    for r in records:
        if r.get("type") != "span":
            continue
        name = r.get("name", "")
        fl = (r.get("attrs") or {}).get("flops") or r.get("flops")
        if fl and (name == algo or algo in name):
            best = float(fl)
    return best


def _trimmed_window(sevs: list[dict], tail: float = 0.005) -> tuple[float, float]:
    """Duration-weighted robust window of one step's events.

    Near-zero-duration stragglers (fusion metadata pollution: a fused
    final-layout copy can carry a step-0 op_name) must not stretch the
    step across the run, so the window keeps the span holding all but a
    ``tail`` fraction of the step's busy time at each end.  Steps whose
    events are all zero-length fall back to the plain min/max.
    """
    total = sum(e["hi"] - e["lo"] for e in sevs)
    if total <= 0.0:
        return (min(e["lo"] for e in sevs), max(e["hi"] for e in sevs))
    cut = tail * total
    acc = 0.0
    lo = sevs[0]["lo"]
    for e in sorted(sevs, key=lambda e: e["lo"]):
        lo = e["lo"]
        acc += e["hi"] - e["lo"]
        if acc > cut:
            break
    acc = 0.0
    hi = sevs[-1]["hi"]
    for e in sorted(sevs, key=lambda e: e["hi"], reverse=True):
        hi = e["hi"]
        acc += e["hi"] - e["lo"]
        if acc > cut:
            break
    return (lo, hi) if lo < hi else (min(e["lo"] for e in sevs),
                                     max(e["hi"] for e in sevs))


def _step_table(evs: list[dict], n_steps: int) -> list[dict]:
    """Per-step walls, category exposure and boundary gaps for one run."""
    steps: list[dict] = []
    by_step: dict[int, list[dict]] = {}
    for ev in evs:
        by_step.setdefault(ev["step"], []).append(ev)
    for k in range(n_steps):
        sevs = by_step.get(k, [])
        if not sevs:
            steps.append({"step": k, "empty": True})
            continue
        lo, hi = _trimmed_window(sevs)
        phase_w = {}
        for ph in PHASES:
            u = _union([(e["lo"], e["hi"]) for e in sevs if e["phase"] == ph])
            if u:
                phase_w[ph] = sum(b - a for a, b in u)
        comm_u = _union([(e["lo"], e["hi"]) for e in sevs if e["cat"] == "collective"])
        copy_u = _union([(e["lo"], e["hi"]) for e in sevs if e["cat"] == "copy"])
        comp_u = _union(
            [(e["lo"], e["hi"]) for e in sevs if e["cat"] not in ("collective", "copy")]
        )
        busy_u = _union([(e["lo"], e["hi"]) for e in sevs])
        busy = sum(b - a for a, b in busy_u)
        comm = sum(b - a for a, b in comm_u)
        copy = sum(b - a for a, b in copy_u)
        comm_exposed = comm - _intersect_len(comm_u, comp_u)
        steps.append(
            {
                "step": k,
                "start_s": lo,
                "wall_s": hi - lo,
                "busy_s": busy,
                "idle_s": max(0.0, (hi - lo) - busy),
                "phases": phase_w,
                "comm_s": comm,
                "comm_exposed_s": max(0.0, comm_exposed),
                "copy_s": copy,
                "end_s": hi,
            }
        )
    # boundary gaps: idle between step k's last op and step k+1's first op,
    # clamped at zero when steps overlap (lookahead pipelining)
    for k in range(len(steps) - 1):
        a, b = steps[k], steps[k + 1]
        if a.get("empty") or b.get("empty"):
            continue
        a["gap_after_s"] = max(0.0, b["start_s"] - a["end_s"])
    return steps


def _bound_of(step: dict) -> str:
    """Classify what bounds a step: argmax over exposure per category."""
    ph = step.get("phases", {})
    panel = ph.get("panel", 0.0) + ph.get("strip", 0.0)
    bulk = ph.get("bulk", 0.0) + ph.get("other", 0.0)
    comm = step.get("comm_exposed_s", 0.0)
    copy = step.get("copy_s", 0.0)
    gap = step.get("gap_after_s", 0.0) + step.get("idle_s", 0.0)
    scores = {"panel": panel - comm - copy, "bulk": bulk, "comm": comm, "copy": copy, "gap": gap}
    scores["panel"] = max(0.0, scores["panel"])
    return max(BOUNDS, key=lambda b: scores[b])


def _critical_path(steps: list[dict], lookahead: bool) -> dict:
    """Longest path through the step DAG.

    Nodes are (step, phase) with measured walls; edges are
    panel_k -> strip_k -> bulk_k within a step, bulk_k -> bulk_{k+1}
    (trailing updates serialize on the matrix), and the next panel hangs
    off strip_k when lookahead overlaps it with bulk_k, else off bulk_k.
    Boundary gaps ride the cross-step edges.
    """
    dist: dict[tuple[int, str], float] = {}
    prev: dict[tuple[int, str], tuple[int, str] | None] = {}

    def relax(node, base, src, w):
        if base + w > dist.get(node, -1.0):
            dist[node] = base + w
            prev[node] = src

    for st in steps:
        if st.get("empty"):
            continue
        k = st["step"]
        ph = st.get("phases", {})
        gap = steps[k - 1].get("gap_after_s", 0.0) if 0 < k <= len(steps) else 0.0
        chain = [p for p in ("panel", "strip", "bulk", "other") if p in ph]
        for i, p in enumerate(chain):
            w = ph[p]
            node = (k, p)
            relax(node, gap, None, w)
            if i > 0:
                relax(node, dist[(k, chain[i - 1])], (k, chain[i - 1]), w)
            # cross-step dependencies from step k-1
            if i == 0:
                # the panel hangs off strip_{k-1} (lookahead overlap) or the
                # end of step k-1 entirely (serial)
                srcs = ("strip", "panel") if lookahead else ("bulk", "other", "strip", "panel")
            elif p in ("bulk", "other"):
                srcs = ("bulk", "other")  # trailing updates serialize
            else:
                srcs = ()
            for pp in srcs:
                src = (k - 1, pp)
                if src in dist:
                    relax(node, dist[src] + gap, src, w)
    if not dist:
        return {"length_s": 0.0, "nodes": []}
    last = max(dist, key=lambda n: dist[n])
    path = []
    node: tuple[int, str] | None = last
    while node is not None:
        path.append(f"step{node[0]:03d}.{node[1]}")
        node = prev.get(node)
    return {"length_s": dist[last], "nodes": list(reversed(path))}


def _mean_steps(per_run: list[list[dict]]) -> list[dict]:
    """Average per-step numbers across runs (element-wise over steps)."""
    if not per_run:
        return []
    n_steps = max(len(r) for r in per_run)
    out = []
    for k in range(n_steps):
        rows = [r[k] for r in per_run if k < len(r) and not r[k].get("empty")]
        if not rows:
            out.append({"step": k, "empty": True})
            continue
        agg: dict[str, Any] = {"step": k}
        for key in ("wall_s", "busy_s", "idle_s", "comm_s", "comm_exposed_s", "copy_s",
                    "gap_after_s"):
            vals = [r.get(key) for r in rows if r.get(key) is not None]
            if vals:
                agg[key] = sum(vals) / len(vals)
        phases: dict[str, float] = {}
        for ph in PHASES:
            vals = [r["phases"].get(ph) for r in rows if r["phases"].get(ph) is not None]
            if vals:
                phases[ph] = sum(vals) / len(vals)
        agg["phases"] = phases
        agg["bound"] = _bound_of(agg)
        out.append(agg)
    return out


def attribute(
    events: list[dict],
    records: list[dict],
    *,
    steps_hint: int | None = None,
) -> dict[str, Any]:
    """Join device events to schedule records and build the full report.

    Raises ``ValueError`` when the artifact has no schedule records or
    the trace has no device events to join.
    """
    joined, busy_total, busy_denom = _scheduled_events(events, records)
    if busy_total <= 0.0:
        raise ValueError("trace contains no device events (complete XSpace only?)")
    windows, join = _run_windows(events, records)
    _assign_runs(joined, windows)
    _by_mod, _by_op, meta = _op_maps(records)
    attributed = sum(e["hi"] - e["lo"] for e in joined)
    coverage = attributed / busy_denom if busy_denom > 0 else 0.0
    knobs = {}
    for rec in records:
        if rec.get("type") == "metrics" and rec.get("knobs"):
            knobs = rec["knobs"]
    lookahead = bool(knobs.get("cholesky_lookahead") or knobs.get("lookahead") or True)

    programs: dict[str, Any] = {}
    by_algo: dict[str, list[dict]] = {}
    for ev in joined:
        by_algo.setdefault(ev["algo"], []).append(ev)
    for algo, evs in sorted(by_algo.items()):
        am = meta.get(algo, {"steps": 0, "scan": False})
        scan = bool(am.get("scan")) and am.get("steps", 0) == 0
        runs: dict[int, list[dict]] = {}
        for ev in evs:
            runs.setdefault(ev.get("run", 0), []).append(ev)
        per_run_steps: list[list[dict]] = []
        run_walls: list[float] = []
        gaps_per_run: list[float] = []
        cp_lengths: list[float] = []
        comm_exposed_run: list[float] = []
        panel_exposed_run: list[float] = []
        copy_run: list[float] = []
        hint = steps_hint or (_infer_steps(algo, records) if scan else None)
        for _rid, revs in sorted(runs.items(), key=lambda kv: min(e["lo"] for e in kv[1])):
            if scan:
                _scan_steps(revs, hint)
            else:
                _detangle_shared(revs)
            n_steps = max((e["step"] for e in revs), default=-1) + 1
            if n_steps <= 0:
                continue
            table = _step_table(revs, n_steps)
            per_run_steps.append(table)
            lo = min(e["lo"] for e in revs)
            hi = max(e["hi"] for e in revs)
            run_walls.append(hi - lo)
            gaps_per_run.append(sum(s.get("gap_after_s", 0.0) for s in table))
            cp_lengths.append(_critical_path(table, lookahead)["length_s"])
            comm_u = _union([(e["lo"], e["hi"]) for e in revs if e["cat"] == "collective"])
            comp_u = _union(
                [(e["lo"], e["hi"]) for e in revs if e["cat"] not in ("collective", "copy")]
            )
            comm_exposed_run.append(
                max(0.0, sum(b - a for a, b in comm_u) - _intersect_len(comm_u, comp_u))
            )
            pan_u = _union(
                [(e["lo"], e["hi"]) for e in revs if e["phase"] in ("panel", "strip")]
            )
            blk_u = _union([(e["lo"], e["hi"]) for e in revs if e["phase"] in ("bulk", "other")])
            panel_exposed_run.append(
                max(0.0, sum(b - a for a, b in pan_u) - _intersect_len(pan_u, blk_u))
            )
            copy_run.append(
                sum(b - a for a, b in _union(
                    [(e["lo"], e["hi"]) for e in revs if e["cat"] == "copy"]))
            )
        if not per_run_steps:
            continue
        mean = _mean_steps(per_run_steps)
        n_runs = len(per_run_steps)
        wall = sum(run_walls) / n_runs
        gaps = sum(gaps_per_run) / n_runs
        cp = _critical_path(mean, lookahead)
        flops = _flops_for(algo, records)

        def project(saved_s: float, label: str) -> dict:
            new_wall = max(1e-12, wall - min(saved_s, wall))
            w: dict[str, Any] = {
                "scenario": label,
                "saved_s": saved_s,
                "wall_s": wall,
                "projected_wall_s": new_wall,
                "wall_pct": 100.0 * (wall - new_wall) / wall if wall > 0 else 0.0,
            }
            if flops:
                w["gflops"] = flops / wall / 1e9
                w["projected_gflops"] = flops / new_wall / 1e9
            return w

        whatifs = [
            project(sum(comm_exposed_run) / n_runs, "collectives_free"),
            project(gaps, "gaps_closed"),
            project(sum(panel_exposed_run) / n_runs, "panel_free"),
            project(sum(copy_run) / n_runs, "copies_free"),
        ]
        whatifs.sort(key=lambda w: -w["saved_s"])
        bounds = [s.get("bound") for s in mean if not s.get("empty")]
        overall = max(BOUNDS, key=lambda b: bounds.count(b)) if bounds else "gap"
        programs[algo] = {
            "scan": scan,
            "n_runs": n_runs,
            "n_steps": len(mean),
            "wall_s": wall,
            "gap_total_s": gaps,
            "critical_path_s": cp["length_s"],
            "critical_path": cp["nodes"],
            "bound": overall,
            "steps": mean,
            "whatif": whatifs,
        }
        if flops:
            programs[algo]["gflops"] = flops / wall / 1e9

    return {
        "device_busy_s": busy_total,
        "attributed_s": attributed,
        "coverage": coverage,
        "join": join,
        "events": len(joined),
        "lookahead": lookahead,
        "programs": programs,
    }


# ---------------------------------------------------------------------------
# gap injection (testing / CI drill)


def parse_inject(spec: str) -> tuple[str, int, float]:
    """Parse ``<algo>.step<k>=<ms>`` into (algo, step, seconds)."""
    m = re.fullmatch(r"([A-Za-z0-9_]+)\.step(\d+)=([0-9.]+)", spec.strip())
    if not m:
        raise ValueError(f"bad --inject-gap spec {spec!r}; want <algo>.step<k>=<ms>")
    return m.group(1), int(m.group(2)), float(m.group(3)) * 1e-3


def inject_gap(events: list[dict], records: list[dict], algo: str, step: int,
               seconds: float, *, steps_hint: int | None = None) -> int:
    """Shift the timeline so an idle gap of ``seconds`` opens immediately
    before ``step`` of ``algo`` in every run.

    Scheduled device events with step >= ``step`` shift by the delta;
    host windows straddling the boundary stretch so run segmentation
    still contains the shifted ops.  On a serial (non-overlapping)
    timeline the measured boundary gap grows by *exactly* the delta; with
    lookahead overlap the earlier step's tail eats into it, so the
    recovered gap is ``delta - overlap`` (still >> 0 for drill-sized
    deltas).  Mutates ``events`` in place; returns the number of runs
    injected into.
    """
    by_mod, by_op, _meta = _op_maps(records)
    joined, _bt, _bd = _scheduled_events(events, records)
    windows, _join = _run_windows(events, records)
    _assign_runs(joined, windows)
    runs: dict[int, list[dict]] = {}
    for ev in joined:
        if ev["algo"] == algo:
            runs.setdefault(ev.get("run", 0), []).append(ev)
    starts = []
    for revs in runs.values():
        if all(e["step"] < 0 for e in revs):
            _scan_steps(revs, steps_hint)
        sevs = [e["lo"] for e in revs if e["step"] == step]
        if sevs:
            starts.append(min(sevs))
    if not starts:
        return 0
    starts.sort()
    delta_us = seconds * 1e6
    procs, _threads = _meta_maps(events)
    run_ivs = _union([(lo, hi) for (lo, hi, _name) in windows])

    def run_end_us(t0: float) -> float:
        for lo, hi in run_ivs:
            if lo <= t0 <= hi:
                return hi * 1e6
        return float("inf")

    def sched_step(e) -> int | None:
        args = e.get("args") or {}
        op = args.get("hlo_op") or e.get("name", "")
        entry = by_mod.get((args.get("hlo_module", ""), op)) or by_op.get(op)
        if entry is None or entry[0] != algo:
            return None
        return int(entry[1])

    # process runs back-to-front so earlier shifts don't move later anchors
    for t0 in reversed(starts):
        t0_us = t0 * 1e6 - 0.5  # nudge so the boundary op itself shifts
        end_us = run_end_us(t0)
        for e in events:
            if e.get("ph") != "X":
                continue
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0) or 0.0)
            if ts + dur <= t0_us:
                continue
            if _is_device_event(e, procs):
                if ts < t0_us:
                    continue
                st = sched_step(e)
                # within the injected run, target-algo ops of EARLIER
                # steps keep their place even past the boundary (their
                # lookahead tail overlaps it); everything else shifts
                if st is not None and 0 <= st < step and ts < end_us:
                    continue
                e["ts"] = ts + delta_us
            elif ts >= t0_us:
                e["ts"] = ts + delta_us  # host event entirely after the boundary
            else:
                e["dur"] = dur + delta_us  # straddling host window stretches
    return len(starts)


# ---------------------------------------------------------------------------
# records + rendering


def records_from_report(report: dict, trace: str) -> list[dict]:
    ts = time.time()
    base = os.path.basename(trace)
    out = []
    for algo, prog in report.get("programs", {}).items():
        steps = []
        for s in prog["steps"]:
            if s.get("empty"):
                steps.append({"step": s["step"], "empty": True})
                continue
            steps.append(
                {
                    "step": s["step"],
                    "wall_s": round(s.get("wall_s", 0.0), 9),
                    "panel_s": round(
                        s["phases"].get("panel", 0.0) + s["phases"].get("strip", 0.0), 9),
                    "bulk_s": round(
                        s["phases"].get("bulk", 0.0) + s["phases"].get("other", 0.0), 9),
                    "comm_s": round(s.get("comm_s", 0.0), 9),
                    "comm_exposed_s": round(s.get("comm_exposed_s", 0.0), 9),
                    "copy_s": round(s.get("copy_s", 0.0), 9),
                    "idle_s": round(s.get("idle_s", 0.0), 9),
                    "gap_after_s": round(s.get("gap_after_s", 0.0), 9),
                    "bound": s.get("bound", "gap"),
                }
            )
        rec = {
            "type": "critpath",
            "v": SCHEMA_VERSION,
            "ts": ts,
            "trace": base,
            "algo": algo,
            "scan": prog["scan"],
            "join": report.get("join"),
            "coverage": round(report.get("coverage", 0.0), 6),
            "n_runs": prog["n_runs"],
            "n_steps": prog["n_steps"],
            "wall_s": round(prog["wall_s"], 9),
            "gap_total_s": round(prog["gap_total_s"], 9),
            "critical_path_s": round(prog["critical_path_s"], 9),
            "critical_path": prog["critical_path"],
            "bound": prog["bound"],
            "steps": steps,
        }
        if "gflops" in prog:
            rec["gflops"] = round(prog["gflops"], 3)
        out.append(rec)
        for w in prog["whatif"]:
            wrec = {
                "type": "whatif",
                "v": SCHEMA_VERSION,
                "ts": ts,
                "trace": base,
                "algo": algo,
                "scenario": w["scenario"],
                "saved_s": round(w["saved_s"], 9),
                "wall_s": round(w["wall_s"], 9),
                "projected_wall_s": round(w["projected_wall_s"], 9),
                "wall_pct": round(w["wall_pct"], 3),
            }
            if "projected_gflops" in w:
                wrec["gflops"] = round(w["gflops"], 3)
                wrec["projected_gflops"] = round(w["projected_gflops"], 3)
            out.append(wrec)
    return out


def _fmt_ms(s: float) -> str:
    return f"{s * 1e3:8.3f}"


def format_report(report: dict, top_n: int = 32) -> str:
    lines = []
    lines.append(
        f"critpath: {report['events']} scheduled device events, "
        f"coverage {report['coverage']:.1%} (join={report['join']}, "
        f"device busy {report['device_busy_s'] * 1e3:.3f} ms)"
    )
    for algo, prog in report.get("programs", {}).items():
        hdr = (
            f"\n{algo}: {prog['n_steps']} steps x {prog['n_runs']} runs"
            f"{' (scan)' if prog['scan'] else ''}, wall {_fmt_ms(prog['wall_s']).strip()} ms, "
            f"gaps {_fmt_ms(prog['gap_total_s']).strip()} ms, "
            f"critical path {_fmt_ms(prog['critical_path_s']).strip()} ms, "
            f"bound: {prog['bound']}"
        )
        if "gflops" in prog:
            hdr += f", {prog['gflops']:.1f} GF/s"
        lines.append(hdr)
        lines.append(
            "  step     wall ms  panel ms   bulk ms   comm ms  exp.comm   copy ms"
            "   idle ms    gap ms  bound"
        )
        for s in prog["steps"][:top_n]:
            if s.get("empty"):
                lines.append(f"  {s['step']:4d}  (no device events)")
                continue
            ph = s.get("phases", {})
            panel = ph.get("panel", 0.0) + ph.get("strip", 0.0)
            bulk = ph.get("bulk", 0.0) + ph.get("other", 0.0)
            lines.append(
                f"  {s['step']:4d}  {_fmt_ms(s.get('wall_s', 0.0))}  {_fmt_ms(panel)}"
                f"  {_fmt_ms(bulk)}  {_fmt_ms(s.get('comm_s', 0.0))}"
                f"  {_fmt_ms(s.get('comm_exposed_s', 0.0))}  {_fmt_ms(s.get('copy_s', 0.0))}"
                f"  {_fmt_ms(s.get('idle_s', 0.0))}  {_fmt_ms(s.get('gap_after_s', 0.0))}"
                f"  {s.get('bound', '')}"
            )
        if len(prog["steps"]) > top_n:
            lines.append(f"  ... {len(prog['steps']) - top_n} more steps")
        lines.append(f"  critical path: {' -> '.join(prog['critical_path'])}")
        lines.append("  what-if:")
        for w in prog["whatif"]:
            line = (
                f"    {w['scenario']:<17} saves {_fmt_ms(w['saved_s']).strip()} ms "
                f"-> wall -{w['wall_pct']:.1f}%"
            )
            if "projected_gflops" in w:
                line += f", {w['gflops']:.1f} -> {w['projected_gflops']:.1f} GF/s"
            lines.append(line)
    if not report.get("programs"):
        lines.append("(no per-step programs attributed)")
    return "\n".join(lines)


def load_records(path: str) -> list[dict]:
    records = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records


# ---------------------------------------------------------------------------
# CLI


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = json_path = distill_path = inject = None
    top_n = 32
    steps_hint = None
    positional = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(__doc__)
            return 0
        if a == "-o":
            i += 1
            out_path = argv[i]
        elif a == "--json":
            i += 1
            json_path = argv[i]
        elif a == "--distill":
            i += 1
            distill_path = argv[i]
        elif a == "--top":
            i += 1
            top_n = int(argv[i])
        elif a == "--steps":
            i += 1
            steps_hint = int(argv[i])
        elif a == "--inject-gap":
            i += 1
            inject = argv[i]
        elif a.startswith("-"):
            print(f"critpath: unknown option {a}", file=sys.stderr)
            return 2
        else:
            positional.append(a)
        i += 1
    if len(positional) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    trace_path, jsonl_path = positional
    try:
        events = load_trace(trace_path)
        records = load_records(jsonl_path)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"critpath: {exc}", file=sys.stderr)
        return 2
    try:
        if inject is not None:
            algo, step, seconds = parse_inject(inject)
            n = inject_gap(events, records, algo, step, seconds, steps_hint=steps_hint)
            print(
                f"critpath: injected {seconds * 1e3:.1f} ms before "
                f"{algo}.step{step:03d} in {n} runs",
                file=sys.stderr,
            )
        report = attribute(events, records, steps_hint=steps_hint)
    except ValueError as exc:
        print(f"critpath: {exc}", file=sys.stderr)
        return 1
    # artifacts before stdout: a SIGPIPE from a closed pager must not lose them
    if out_path:
        recs = records_from_report(report, trace_path)
        with open(out_path, "a", encoding="utf-8") as fh:
            for rec in recs:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
    if json_path:
        with open(json_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if distill_path:
        kept = _devtrace_distill(events, records)
        payload = json.dumps({"traceEvents": kept})
        if distill_path.endswith(".gz"):
            with gzip.open(distill_path, "wt", encoding="utf-8") as fh:
                fh.write(payload)
        else:
            with open(distill_path, "w", encoding="utf-8") as fh:
                fh.write(payload)
        print(f"critpath: distilled {len(kept)} events -> {distill_path}", file=sys.stderr)
    print(format_report(report, top_n))
    if not report.get("programs"):
        print("critpath: WARNING: no per-step programs attributed", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
