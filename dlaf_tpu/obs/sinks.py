"""Event sinks and the JSONL artifact schema.

Two output formats, per the observability design (ISSUE 1):

* **JSON lines** (:class:`JsonlSink`) — one self-describing event object
  per line, append-only, the same artifact convention as the repo's
  ``BENCH_*.json`` round files. Everything the tracer/metrics/logger emit
  flows through here when ``DLAF_METRICS_PATH`` is set.
* **Prometheus text exposition** (:func:`prometheus_text`, over a registry
  snapshot) — for scraping; see :mod:`dlaf_tpu.obs.metrics`.

Schema (version 1). Every record carries ``v`` (int schema version),
``type`` (str) and ``ts`` (float, unix seconds). Per type:

``span``
    ``name`` str, ``dur_s`` finite float >= 0, ``depth`` int >= 0,
    ``parent`` str or null, ``attrs`` object. Optional ``flops`` (finite
    number) and ``gflops`` (finite number, derived = flops / dur_s / 1e9).
    Optional ``fenced: false`` marks spans whose wall clock is host
    trace+dispatch only (async JAX work, no device fence inside the
    region) — such records never carry ``gflops``.
``metrics``
    ``metrics``: list of snapshot entries — ``name`` str, ``kind``
    "counter" | "gauge" | "histogram", ``labels`` object; counters/gauges
    carry finite ``value``; histograms carry ``count``/``sum``/``min``/
    ``max`` and ``buckets`` (list of [le, count]).
``log``
    ``level`` str, ``logger`` str, ``msg`` str, ``fields`` object.
``bench_result``
    ``payload`` object (free-form; bench.py's measurement line).
``program``
    Program-telemetry record (:mod:`dlaf_tpu.obs.telemetry`, the
    ``DLAF_PROGRAM_TELEMETRY`` knob): ``site`` str, ``event``
    "compile" | "retrace", finite ``compile_s`` >= 0 (compile events;
    optional ``trace_s``), optional ``hbm`` object of finite byte gauges
    (``args``/``output``/``temp``/``peak`` from
    ``compiled.memory_analysis()``), ``attrs`` object.
``accuracy``
    Numerical-quality record (:mod:`dlaf_tpu.obs.accuracy`, the
    ``DLAF_ACCURACY`` knob; docs/accuracy.md): ``site`` str, ``metric``
    str, ``platform`` str, ``n``/``nb`` non-negative ints, ``dtype``
    str, ``attrs`` object; ``value`` finite >= 0 — or null with
    ``nonfinite: true``, the corruption signal the accuracy gate treats
    as an automatic regression. Budgeted metrics additionally carry
    finite ``bound_ratio = value / (c * n * eps_eff)`` >= 0 plus the
    ``c``/``eps_eff`` they were normalized with (informational metrics,
    e.g. the D&C deflation fraction, omit all three); a record may not
    carry both ``bound_ratio`` and ``nonfinite``.

``resilience``
    Resilience-layer record (:mod:`dlaf_tpu.health.policy` /
    ``.circuit`` / ``.resume`` and the serve queue's overload path;
    docs/robustness.md): ``site`` str, ``event`` one of
    ``retry`` | ``give_up`` | ``deadline`` | ``circuit_open`` |
    ``circuit_half_open`` | ``circuit_close`` | ``shed`` | ``expired`` |
    ``checkpoint`` | ``preempt`` | ``resume``, ``attrs`` object;
    ``retry``/``give_up``/``deadline`` events carry a non-negative int
    ``attempt`` and ``retry`` a finite ``delay_s >= 0`` (the
    deterministic backoff actually applied). The
    ``--require-resilience`` CI obligation: >= 1 ``retry`` or ``resume``
    record (the recovery actually exercised), AND no
    ``dlaf_circuit_state`` gauge left at the open value (2) in the LAST
    metrics snapshot — an artifact that ends with a tripped breaker must
    fail the gate, not scrape as healthy.

``serve``
    Serving-layer record (:mod:`dlaf_tpu.serve`, docs/serving.md), two
    events: ``dispatch`` — one batched bucket dispatch (``op`` str,
    ``bucket_n`` int >= 1, ``nrhs`` int >= 0, ``dtype`` str, ``lanes``
    int in [0, batch], ``batch`` int >= 1, ``cache`` "hit" | "miss",
    finite ``dispatch_s`` >= 0) — and ``request`` — one served request
    (``op`` str, ``n`` int >= 1, ``bucket_n`` >= n, ``dtype`` str,
    finite ``queue_s``/``total_s`` >= 0, ``attrs`` object). The
    ``--require-serve`` CI obligation (a WARMED steady-state serving
    artifact): >= 1 dispatch with >= 2 occupied lanes, every dispatch a
    cache hit (zero misses — the post-warmup contract), >= 1 request
    with finite latency, >= 1 ``accuracy`` record from site ``serve``
    with finite value AND bound_ratio, and no
    ``dlaf_retrace_total{site=serve.*}`` counter at >= 2 (a serve
    program traced twice = an evicted/cold bucket recompiled
    mid-stream).

``flight_trigger``
    Header record of a flight-recorder dump (:mod:`dlaf_tpu.obs.flight`,
    the ``DLAF_FLIGHT_RECORDER`` knob): ``reason`` one of
    :data:`FLIGHT_REASONS`, ``dump_seq`` int >= 1, ``records`` int >= 0
    (ring depth at the dump), ``attrs`` object. It appears only in the
    standalone ``<metrics_path>.flight.jsonl`` incident artifact — the
    ``--require-flight`` CI obligation: >= 1 ``flight_trigger`` record
    AND >= 1 ordinary record after it (an incident dump with no
    pre-trigger context captured nothing worth gating on).

``autotune``
    One precision-route decision (:mod:`dlaf_tpu.autotune`, the
    ``DLAF_AUTOTUNE`` knob; docs/autotune.md): ``site`` non-empty str
    (the route-table key label), ``op``/``dtype``/``platform`` non-empty
    strs, ``n_bucket``/``nb`` non-negative ints, ``reason`` one of
    :data:`AUTOTUNE_REASONS`, ``rung_old``/``rung_new`` non-negative
    ints consistent with the reason (``escalate``: new > old;
    ``relax``: new < old; ``hold``/``exhausted``: new == old),
    ``route_old``/``route_new`` objects (the knob overrides in effect),
    ``probe`` finite >= 0 — or null with ``nonfinite: true`` (a
    corrupted estimate, treated as a breach) — and ``attrs`` object.
    The ``--require-autotune`` CI obligation: >= 1 ``escalate`` or
    ``relax`` decision (the loop actually moved a route — a hold-only
    artifact proves nothing about closure), and NO site whose LAST
    decision is ``exhausted`` — an artifact that ends with a ladder
    pinned at its top under a breach is an open incident and must fail
    the gate, exactly like an open breaker under
    ``--require-resilience``.

``devtrace``
    Device-timeline attribution summary (:mod:`dlaf_tpu.obs.devtrace`,
    ISSUE 14; docs/observability.md device-time attribution): ``trace``
    non-empty str (the profiler artifact's basename), finite
    ``device_busy_s``/``attributed_s`` >= 0, ``coverage`` finite in
    [0, 1] (attributed / total device busy), ``join``
    "annotation" | "rebase" (how phases were matched), ``phases`` object
    of per-phase cells — finite ``busy_s``/``wall_s`` >= 0 (a NaN wall
    is a schema error: the "no NaN walls" leg of ``--require-devtrace``),
    ``categories`` object of finite seconds, optional finite ``flops``/
    ``measured_gflops`` (the measured-MFU join) — and ``attrs`` object.

``measured_overlap``
    Measured comm/compute overlap for one (``algo``, ``axis``) — the
    device-timeline counterpart of the structural
    ``dlaf_comm_overlapped_total`` trace-time counters: non-empty
    ``algo``/``axis`` strs (``axis`` is ``"all"`` when the trace carries
    no replica-group metadata — Chrome traces do not), finite
    ``collective_s``/``overlapped_s``/``mxu_busy_s`` >= 0 with
    ``overlapped_s <= collective_s`` (every field phase-scoped:
    ``mxu_busy_s`` is the MXU time attributed to THIS algo, so
    ``overlapped_s / mxu_busy_s`` is a meaningful ratio),
    ``overlap_frac`` finite in [0, 1],
    ``kinds`` object of finite per-collective-kind seconds, ``attrs``
    object. Emitted only for phases with POSITIVE attributed collective
    time, so an artifact whose trace attributed zero collectives carries
    no such record and fails ``--require-devtrace``.

Every record additionally carries an optional ``rank`` (int >= 0,
``jax.process_index()``) — stamped by the sink once the rank is known, so
multi-host artifacts merge per rank (``python -m dlaf_tpu.obs.aggregate``;
``DLAF_METRICS_PATH`` accepts a ``%r`` per-rank template so ranks never
interleave one file) — and optional trace correlation (ISSUE 13,
:mod:`dlaf_tpu.obs.context`): ``trace_id`` (non-empty str for
request-scoped records, non-empty list of non-empty strs for
batch-scoped ones — a dispatch, its retries, its compiles) and
``span_id`` (non-empty str, one per batch dispatch), both stamped by the
sink from the active ``obs.trace_context``. ``serve`` dispatch records
may carry a ``stages`` object of finite non-negative stage walls
(``compose_s``/``program_s``/``fetch_s``/``unpad_s``) — joined to member
requests via ``span_id`` by ``obs.aggregate --trace`` (the per-request
waterfall).

:func:`validate_file` is the single schema owner consumed by tests and the
CI gate (``python -m dlaf_tpu.obs.validate``): it rejects unparsable lines,
missing fields, and non-finite numerics (a NaN GFlop/s must fail the tier,
not scrape as a number). The append-only bench history
(``.bench_history.jsonl``) has its own line schema, also owned here
(:func:`validate_history_records`, :func:`append_history_line` — the
validator CLI's ``--history`` mode): a malformed or non-finite history
line must fail loudly, not silently skew the replayed-history headline.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Optional

SCHEMA_VERSION = 1

KNOWN_TYPES = ("span", "metrics", "log", "bench_result", "program",
               "accuracy", "serve", "resilience", "flight_trigger",
               "devtrace", "measured_overlap", "autotune",
               "schedule", "critpath", "whatif", "fleet")

#: Documented attribution-coverage floor of ``--require-devtrace``
#: (docs/observability.md device-time attribution): a devtrace record
#: must attribute at least this fraction of total device busy time to
#: algorithm phases — below it, the per-phase walls describe a minority
#: of the timeline and must not gate (or pass) anything.
DEVTRACE_COVERAGE_FLOOR = 0.5

#: Documented coverage floor of ``--require-critpath`` (docs/
#: observability.md critical-path attribution): a critpath record must
#: join at least this fraction of the scheduled programs' device busy
#: time to per-step scopes — below it the per-step walls, gaps and bound
#: classifications describe a minority of the step timeline and must not
#: gate (or pass) anything.
CRITPATH_COVERAGE_FLOOR = 0.5

#: Bound vocabulary of critpath step/program classification
#: (obs.critpath.BOUNDS, duplicated here so validation never imports the
#: joiner).
CRITPATH_BOUNDS = ("panel", "bulk", "comm", "copy", "gap")

#: What-if scenario vocabulary (obs.critpath projections).
WHATIF_SCENARIOS = ("collectives_free", "gaps_closed", "panel_free",
                    "copies_free")

#: The resilience record's event vocabulary (schema above).
RESILIENCE_EVENTS = ("retry", "give_up", "deadline", "circuit_open",
                     "circuit_half_open", "circuit_close", "shed",
                     "expired", "checkpoint", "preempt", "resume",
                     "drain")

#: The flight recorder's trigger vocabulary (docs/observability.md live
#: operations; trigger sites in :mod:`dlaf_tpu.obs.flight`).
FLIGHT_REASONS = ("breaker_open", "overload_shed",
                  "factorization_exhausted", "accuracy_breach",
                  "healthz_failure", "slo_breach_burst",
                  "autotune_exhausted", "fleet_worker_down")

#: The fleet record's event vocabulary (docs/fleet.md; emitted by
#: :class:`dlaf_tpu.fleet.router.Router` — the router is the ONLY
#: writer, so the fleet audit trail is a single ordered decision log).
#: ``route``/``redispatch``/``handback`` are ticket-scoped (carry
#: ``seq`` + the active trace context); the rest are membership-scoped.
FLEET_EVENTS = ("route", "redispatch", "handback", "worker_up",
                "worker_dead", "heartbeat_timeout", "draining",
                "drained", "probe", "ticket_lost")

#: The autotune decision vocabulary (docs/autotune.md; decision core in
#: :func:`dlaf_tpu.autotune.table.decide`).
AUTOTUNE_REASONS = ("escalate", "relax", "hold", "exhausted")


def expand_rank_template(path: str) -> str:
    """Resolve a ``%r`` per-rank placeholder in a metrics path — but ONLY
    when the rank is already known (:func:`dlaf_tpu.obs._state.
    current_rank`'s non-forcing resolution). Before any backend exists the
    template is returned unexpanded: forcing ``jax.process_index()`` here
    would initialize the local backend, and on a multi-host worker that
    happens exactly where it must not — before ``initialize_multihost``'s
    ``jax.distributed.initialize`` (which both breaks bring-up and pins
    rank 0 on every host). The sink expands the deferred template at
    first write instead, and ``initialize_multihost`` re-configures with
    the authoritative rank."""
    if "%r" not in path:
        return path
    from ._state import current_rank

    rank = current_rank()
    return path if rank is None else path.replace("%r", str(rank))


class JsonlSink:
    """Append-only JSON-lines writer; thread-safe, line-buffered so a
    killed process still leaves a readable prefix."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = None

    def write(self, record: dict) -> None:
        record.setdefault("v", SCHEMA_VERSION)
        record.setdefault("ts", time.time())
        if "rank" not in record:
            # stamp the process rank once known (lazy: resolving it must
            # not force a jax import from a bare log call)
            from ._state import current_rank

            rank = current_rank()
            if rank is not None:
                record["rank"] = rank
        # request-scoped trace correlation (ISSUE 13): the active
        # obs.trace_context's trace_id/span_id land on EVERY record type
        # written under it — one ContextVar read when no context is live
        from .context import record_stamp

        record_stamp(record)
        from ._state import STATE

        if STATE.flight is not None:
            # flight ring capture, pre-serialization and pre-file-write:
            # the moments before an incident survive a lost sink file
            STATE.flight.capture(record)
        line = json.dumps(record, default=str)
        with self._lock:
            if self._f is None:
                if "%r" in self.path:
                    # deferred %r template (configure() could not resolve
                    # the rank without forcing backend init): expand now —
                    # by first write a backend exists for any real run —
                    # and record the resolved path so a later configure()
                    # with the authoritative rank reopens cleanly. If the
                    # rank is STILL unknown (pre-distributed-init log
                    # writes), use a per-process placeholder: claiming
                    # rank 0 would make every late-initializing host of a
                    # shared filesystem append to rank 0's file — the
                    # misattributed interleaving %r exists to prevent.
                    from ._state import current_rank

                    rank = current_rank()
                    import os as _os

                    self.path = self.path.replace(
                        "%r", str(rank) if rank is not None
                        else f"u{_os.getpid()}")
                self._f = open(self.path, "a", buffering=1)
            self._f.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and math.isfinite(x)


def _validate_span(r: dict, where: str, errors: list) -> None:
    if not isinstance(r.get("name"), str) or not r.get("name"):
        errors.append(f"{where}: span without a name")
    if not _finite(r.get("dur_s")) or r.get("dur_s", -1) < 0:
        errors.append(f"{where}: span dur_s missing/non-finite/negative")
    if not isinstance(r.get("depth"), int) or r.get("depth", -1) < 0:
        errors.append(f"{where}: span depth missing or negative")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: span attrs must be an object")
    for key in ("flops", "gflops"):
        if key in r and not _finite(r[key]):
            errors.append(f"{where}: span {key} non-finite")
    if r.get("fenced") is False and "gflops" in r:
        # the tracer never derives throughput from unfenced dispatch
        # wall; hold third-party emitters to the same contract
        errors.append(f"{where}: unfenced span must not carry gflops")
    if r.get("name") == "robust_cholesky.attempt":
        # retry spans are the recovery audit trail (docs/robustness.md):
        # each must say WHICH attempt with WHAT shift, or the artifact
        # cannot reconstruct the recovery history
        attrs = r.get("attrs") or {}
        for key in ("attempt", "shift"):
            if not _finite(attrs.get(key)):
                errors.append(
                    f"{where}: retry span missing finite attr {key!r}")


def _validate_program(r: dict, where: str, errors: list) -> None:
    if not isinstance(r.get("site"), str) or not r.get("site"):
        errors.append(f"{where}: program record without a site")
    event = r.get("event")
    if event not in ("compile", "retrace"):
        errors.append(f"{where}: program event must be compile|retrace, "
                      f"got {event!r}")
    if event == "compile":
        # a compile event without a finite compile wall is exactly the
        # kind of silent telemetry hole the knob exists to close
        if not _finite(r.get("compile_s")) or r.get("compile_s", -1) < 0:
            errors.append(f"{where}: program compile_s "
                          "missing/non-finite/negative")
    elif "compile_s" in r and (not _finite(r["compile_s"])
                               or r["compile_s"] < 0):
        # optional on other events, but non-finite numerics are schema
        # errors everywhere (same treatment as trace_s below)
        errors.append(f"{where}: program compile_s non-finite/negative")
    if "trace_s" in r and (not _finite(r["trace_s"]) or r["trace_s"] < 0):
        errors.append(f"{where}: program trace_s non-finite/negative")
    hbm = r.get("hbm")
    if hbm is not None:
        if not isinstance(hbm, dict):
            errors.append(f"{where}: program hbm must be an object")
        else:
            for key, v in hbm.items():
                if not _finite(v):
                    errors.append(f"{where}: program hbm[{key!r}] "
                                  "non-finite")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: program attrs must be an object")


def _validate_accuracy(r: dict, where: str, errors: list) -> None:
    for key in ("site", "metric", "platform", "dtype"):
        if not isinstance(r.get(key), str) or not r.get(key):
            errors.append(f"{where}: accuracy record without a {key}")
    for key in ("n", "nb"):
        if not isinstance(r.get(key), int) or isinstance(r.get(key), bool) \
                or r.get(key, -1) < 0:
            errors.append(f"{where}: accuracy {key} must be a non-negative "
                          "int")
    value = r.get("value")
    if r.get("nonfinite") is True:
        if value is not None:
            errors.append(f"{where}: nonfinite accuracy record must carry "
                          "value null")
        if "bound_ratio" in r:
            # a NaN estimate has no meaningful budget ratio; carrying one
            # would let a corrupted run scrape as a (finite) number
            errors.append(f"{where}: nonfinite accuracy record must not "
                          "carry bound_ratio")
    elif not _finite(value) or value < 0:
        errors.append(f"{where}: accuracy value missing/non-finite/negative "
                      "(use value null + nonfinite true for corrupted "
                      "estimates)")
    for key in ("bound_ratio", "c", "eps_eff"):
        if key in r and (not _finite(r[key]) or r[key] < 0):
            errors.append(f"{where}: accuracy {key} non-finite/negative")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: accuracy attrs must be an object")


def _validate_serve(r: dict, where: str, errors: list) -> None:
    event = r.get("event")
    if event not in ("dispatch", "request"):
        errors.append(f"{where}: serve event must be dispatch|request, "
                      f"got {event!r}")
        return
    for key in ("op", "dtype"):
        if not isinstance(r.get(key), str) or not r.get(key):
            errors.append(f"{where}: serve record without a {key}")
    if not isinstance(r.get("bucket_n"), int) \
            or isinstance(r.get("bucket_n"), bool) or r.get("bucket_n", 0) < 1:
        errors.append(f"{where}: serve bucket_n must be a positive int")
    if event == "dispatch":
        lanes, batch = r.get("lanes"), r.get("batch")
        if not isinstance(r.get("nrhs"), int) \
                or isinstance(r.get("nrhs"), bool) or r.get("nrhs", -1) < 0:
            errors.append(f"{where}: serve dispatch nrhs must be a "
                          "non-negative int")
        if not isinstance(batch, int) or isinstance(batch, bool) or batch < 1:
            errors.append(f"{where}: serve dispatch batch must be a "
                          "positive int")
        if not isinstance(lanes, int) or isinstance(lanes, bool) \
                or lanes < 0 or (isinstance(batch, int) and lanes > batch):
            errors.append(f"{where}: serve dispatch lanes must be an int "
                          "in [0, batch]")
        if r.get("cache") not in ("hit", "miss"):
            errors.append(f"{where}: serve dispatch cache must be "
                          f"hit|miss, got {r.get('cache')!r}")
        if not _finite(r.get("dispatch_s")) or r.get("dispatch_s", -1) < 0:
            errors.append(f"{where}: serve dispatch_s "
                          "missing/non-finite/negative")
        stages = r.get("stages")
        if stages is not None:
            if not isinstance(stages, dict):
                errors.append(f"{where}: serve dispatch stages must be an "
                              "object")
            else:
                for key, v in stages.items():
                    if not _finite(v) or v < 0:
                        errors.append(f"{where}: serve dispatch stages"
                                      f"[{key!r}] non-finite/negative")
    else:
        if not isinstance(r.get("n"), int) or isinstance(r.get("n"), bool) \
                or r.get("n", 0) < 1:
            errors.append(f"{where}: serve request n must be a positive int")
        elif isinstance(r.get("bucket_n"), int) \
                and r["bucket_n"] < r["n"]:
            errors.append(f"{where}: serve request bucket_n < n — the "
                          "bucket must be a ceiling")
        for key in ("queue_s", "total_s"):
            if not _finite(r.get(key)) or r.get(key, -1) < 0:
                errors.append(f"{where}: serve request {key} "
                              "missing/non-finite/negative")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: serve attrs must be an object")


def _validate_resilience(r: dict, where: str, errors: list) -> None:
    if not isinstance(r.get("site"), str) or not r.get("site"):
        errors.append(f"{where}: resilience record without a site")
    event = r.get("event")
    if event not in RESILIENCE_EVENTS:
        errors.append(f"{where}: resilience event must be one of "
                      f"{RESILIENCE_EVENTS}, got {event!r}")
    if event in ("retry", "give_up", "deadline"):
        attempt = r.get("attempt")
        if not isinstance(attempt, int) or isinstance(attempt, bool) \
                or attempt < 0:
            errors.append(f"{where}: resilience {event} record needs a "
                          "non-negative int attempt")
    if event == "retry" and (not _finite(r.get("delay_s"))
                             or r.get("delay_s", -1) < 0):
        errors.append(f"{where}: resilience retry record needs finite "
                      "delay_s >= 0 (the backoff actually applied)")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: resilience attrs must be an object")


def _validate_fleet(r: dict, where: str, errors: list) -> None:
    """Fleet decision record (docs/fleet.md): ``event`` from
    :data:`FLEET_EVENTS`, ``worker`` a non-negative int (the replica the
    decision is ABOUT), and for ticket-scoped events (route, redispatch,
    handback, ticket_lost) the router ticket ``seq`` — those records are
    also trace-stamped so a ticket's full journey joins on trace_id."""
    event = r.get("event")
    if event not in FLEET_EVENTS:
        errors.append(f"{where}: fleet event must be one of "
                      f"{FLEET_EVENTS}, got {event!r}")
    worker = r.get("worker")
    if not isinstance(worker, int) or isinstance(worker, bool) or worker < 0:
        errors.append(f"{where}: fleet record needs a non-negative int "
                      f"worker, got {worker!r}")
    if event in ("route", "redispatch", "handback", "ticket_lost"):
        seq = r.get("seq")
        if not isinstance(seq, int) or isinstance(seq, bool) or seq < 0:
            errors.append(f"{where}: fleet {event} record needs a "
                          f"non-negative int seq, got {seq!r}")
        if not isinstance(r.get("trace_id"), str) or not r.get("trace_id"):
            errors.append(f"{where}: fleet {event} record must be "
                          "trace-stamped (joinable to its request)")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: fleet attrs must be an object")


def _validate_devtrace(r: dict, where: str, errors: list) -> None:
    if not isinstance(r.get("trace"), str) or not r.get("trace"):
        errors.append(f"{where}: devtrace record without a trace name")
    for key in ("device_busy_s", "attributed_s"):
        if not _finite(r.get(key)) or r.get(key, -1) < 0:
            errors.append(f"{where}: devtrace {key} "
                          "missing/non-finite/negative")
    cov = r.get("coverage")
    if not _finite(cov) or not 0.0 <= cov <= 1.0:
        errors.append(f"{where}: devtrace coverage must be finite in "
                      f"[0, 1], got {cov!r}")
    if r.get("join") not in ("annotation", "rebase"):
        errors.append(f"{where}: devtrace join must be "
                      f"annotation|rebase, got {r.get('join')!r}")
    phases = r.get("phases")
    if not isinstance(phases, dict):
        errors.append(f"{where}: devtrace phases must be an object")
    else:
        for name, cell in phases.items():
            w = f"{where} phase[{name!r}]"
            if not isinstance(cell, dict):
                errors.append(f"{w}: must be an object")
                continue
            # the "no NaN walls" leg: every per-phase wall is finite
            for key in ("busy_s", "wall_s"):
                if not _finite(cell.get(key)) or cell.get(key, -1) < 0:
                    errors.append(f"{w}: {key} "
                                  "missing/non-finite/negative")
            cats = cell.get("categories")
            if not isinstance(cats, dict):
                errors.append(f"{w}: categories must be an object")
            else:
                for cat, v in cats.items():
                    if not _finite(v) or v < 0:
                        errors.append(f"{w}: categories[{cat!r}] "
                                      "non-finite/negative")
            for key in ("flops", "measured_gflops"):
                if key in cell and (not _finite(cell[key])
                                    or cell[key] < 0):
                    errors.append(f"{w}: {key} non-finite/negative")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: devtrace attrs must be an object")


def _validate_measured_overlap(r: dict, where: str, errors: list) -> None:
    for key in ("algo", "axis"):
        if not isinstance(r.get(key), str) or not r.get(key):
            errors.append(f"{where}: measured_overlap record without "
                          f"a {key}")
    for key in ("collective_s", "overlapped_s", "mxu_busy_s"):
        if not _finite(r.get(key)) or r.get(key, -1) < 0:
            errors.append(f"{where}: measured_overlap {key} "
                          "missing/non-finite/negative")
    if _finite(r.get("collective_s")) and _finite(r.get("overlapped_s")) \
            and r["overlapped_s"] > r["collective_s"]:
        errors.append(f"{where}: measured_overlap overlapped_s > "
                      "collective_s (overlap cannot exceed the "
                      "collective time it overlaps)")
    frac = r.get("overlap_frac")
    if not _finite(frac) or not 0.0 <= frac <= 1.0:
        errors.append(f"{where}: measured_overlap overlap_frac must be "
                      f"finite in [0, 1], got {frac!r}")
    kinds = r.get("kinds")
    if kinds is not None:
        if not isinstance(kinds, dict):
            errors.append(f"{where}: measured_overlap kinds must be an "
                          "object")
        else:
            for kind, v in kinds.items():
                if not _finite(v) or v < 0:
                    errors.append(f"{where}: measured_overlap kinds"
                                  f"[{kind!r}] non-finite/negative")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: measured_overlap attrs must be an "
                      "object")


def _validate_schedule(r: dict, where: str, errors: list) -> None:
    for key in ("site", "module"):
        if not isinstance(r.get(key), str) or not r.get(key):
            errors.append(f"{where}: schedule record without a {key}")
    ops = r.get("ops")
    if not isinstance(ops, list) or not ops:
        errors.append(f"{where}: schedule record without ops")
        return
    for j, entry in enumerate(ops):
        if (not isinstance(entry, list) or len(entry) != 4
                or not isinstance(entry[0], str)
                or not isinstance(entry[1], str)
                or not isinstance(entry[2], int)
                or not isinstance(entry[3], str)):
            errors.append(f"{where}: schedule ops[{j}] must be "
                          "[instr, algo, step, phase]")
            break
    algos = r.get("algos")
    if not isinstance(algos, dict) or not algos:
        errors.append(f"{where}: schedule record without algos summary")


def _validate_critpath(r: dict, where: str, errors: list) -> None:
    if not isinstance(r.get("trace"), str) or not r.get("trace"):
        errors.append(f"{where}: critpath record without a trace name")
    if not isinstance(r.get("algo"), str) or not r.get("algo"):
        errors.append(f"{where}: critpath record without an algo")
    cov = r.get("coverage")
    if not _finite(cov) or not 0.0 <= cov <= 1.0:
        errors.append(f"{where}: critpath coverage must be finite in "
                      f"[0, 1], got {cov!r}")
    if r.get("join") not in ("annotation", "rebase"):
        errors.append(f"{where}: critpath join must be "
                      f"annotation|rebase, got {r.get('join')!r}")
    for key in ("n_runs", "n_steps"):
        if not isinstance(r.get(key), int) or isinstance(r.get(key), bool) \
                or r.get(key, 0) < 1:
            errors.append(f"{where}: critpath {key} must be a positive "
                          "int")
    for key in ("wall_s", "gap_total_s", "critical_path_s"):
        if not _finite(r.get(key)) or r.get(key, -1) < 0:
            errors.append(f"{where}: critpath {key} "
                          "missing/non-finite/negative")
    if r.get("bound") not in CRITPATH_BOUNDS:
        errors.append(f"{where}: critpath bound must be one of "
                      f"{CRITPATH_BOUNDS}, got {r.get('bound')!r}")
    steps = r.get("steps")
    if not isinstance(steps, list) or not steps:
        errors.append(f"{where}: critpath record without steps")
        return
    for s in steps:
        if not isinstance(s, dict):
            errors.append(f"{where}: critpath step entries must be "
                          "objects")
            break
        w = f"{where} step[{s.get('step')!r}]"
        if not isinstance(s.get("step"), int):
            errors.append(f"{w}: missing step index")
        if s.get("empty"):
            continue
        # the "no NaN walls" leg: every per-step wall is finite
        for key in ("wall_s", "panel_s", "bulk_s", "comm_s",
                    "comm_exposed_s", "copy_s", "idle_s", "gap_after_s"):
            if key == "gap_after_s" and key not in s:
                continue  # the last step has no following boundary
            if not _finite(s.get(key)) or s.get(key, -1) < 0:
                errors.append(f"{w}: {key} missing/non-finite/negative")
        if s.get("bound") not in CRITPATH_BOUNDS:
            errors.append(f"{w}: bound must be one of "
                          f"{CRITPATH_BOUNDS}, got {s.get('bound')!r}")


def _validate_whatif(r: dict, where: str, errors: list) -> None:
    if not isinstance(r.get("algo"), str) or not r.get("algo"):
        errors.append(f"{where}: whatif record without an algo")
    if r.get("scenario") not in WHATIF_SCENARIOS:
        errors.append(f"{where}: whatif scenario must be one of "
                      f"{WHATIF_SCENARIOS}, got {r.get('scenario')!r}")
    for key in ("saved_s", "wall_s", "projected_wall_s"):
        if not _finite(r.get(key)) or r.get(key, -1) < 0:
            errors.append(f"{where}: whatif {key} "
                          "missing/non-finite/negative")
    if _finite(r.get("wall_s")) and _finite(r.get("projected_wall_s")) \
            and r["projected_wall_s"] > r["wall_s"] + 1e-12:
        errors.append(f"{where}: whatif projected_wall_s > wall_s "
                      "(removing work cannot slow the run)")
    pct = r.get("wall_pct")
    if not _finite(pct) or not 0.0 <= pct <= 100.0:
        errors.append(f"{where}: whatif wall_pct must be finite in "
                      f"[0, 100], got {pct!r}")


def _validate_autotune(r: dict, where: str, errors: list) -> None:
    for key in ("site", "op", "dtype", "platform"):
        if not isinstance(r.get(key), str) or not r.get(key):
            errors.append(f"{where}: autotune record without a {key}")
    for key in ("n_bucket", "nb", "rung_old", "rung_new"):
        if not isinstance(r.get(key), int) or isinstance(r.get(key), bool) \
                or r.get(key, -1) < 0:
            errors.append(f"{where}: autotune {key} must be a non-negative "
                          "int")
    reason = r.get("reason")
    if reason not in AUTOTUNE_REASONS:
        errors.append(f"{where}: autotune reason must be one of "
                      f"{AUTOTUNE_REASONS}, got {reason!r}")
    old, new = r.get("rung_old"), r.get("rung_new")
    if isinstance(old, int) and isinstance(new, int):
        # a record whose rung transition contradicts its reason would let
        # a decision trail lie about what the controller actually did
        if reason == "escalate" and not new > old:
            errors.append(f"{where}: autotune escalate must raise the "
                          f"rung (old {old}, new {new})")
        if reason == "relax" and not new < old:
            errors.append(f"{where}: autotune relax must lower the rung "
                          f"(old {old}, new {new})")
        if reason in ("hold", "exhausted") and new != old:
            errors.append(f"{where}: autotune {reason} must keep the "
                          f"rung (old {old}, new {new})")
    probe = r.get("probe")
    if r.get("nonfinite") is True:
        if probe is not None:
            errors.append(f"{where}: nonfinite autotune record must carry "
                          "probe null")
    elif not _finite(probe) or probe < 0:
        errors.append(f"{where}: autotune probe missing/non-finite/"
                      "negative (use probe null + nonfinite true for "
                      "corrupted estimates)")
    for key in ("route_old", "route_new"):
        if not isinstance(r.get(key), dict):
            errors.append(f"{where}: autotune {key} must be an object")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: autotune attrs must be an object")


def _validate_flight_trigger(r: dict, where: str, errors: list) -> None:
    if r.get("reason") not in FLIGHT_REASONS:
        errors.append(f"{where}: flight_trigger reason must be one of "
                      f"{FLIGHT_REASONS}, got {r.get('reason')!r}")
    for key in ("dump_seq", "records"):
        if not isinstance(r.get(key), int) or isinstance(r.get(key), bool) \
                or r.get(key, -1) < 0:
            errors.append(f"{where}: flight_trigger {key} must be a "
                          "non-negative int")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: flight_trigger attrs must be an object")


def _validate_trace_stamp(r: dict, where: str, errors: list) -> None:
    """Optional trace correlation fields, any record type: ``trace_id``
    a non-empty str (request scope) or non-empty list of non-empty strs
    (batch scope); ``span_id`` a non-empty str."""
    tid = r.get("trace_id")
    if tid is not None:
        if isinstance(tid, str):
            if not tid:
                errors.append(f"{where}: trace_id must be non-empty")
        elif isinstance(tid, list):
            if not tid or any(not isinstance(t, str) or not t for t in tid):
                errors.append(f"{where}: trace_id list must be non-empty "
                              "with non-empty string members")
        else:
            errors.append(f"{where}: trace_id must be a string or a list "
                          f"of strings, got {type(tid).__name__}")
    sid = r.get("span_id")
    if sid is not None and (not isinstance(sid, str) or not sid):
        errors.append(f"{where}: span_id must be a non-empty string")


def _validate_metrics(r: dict, where: str, errors: list) -> None:
    entries = r.get("metrics")
    if not isinstance(entries, list):
        errors.append(f"{where}: metrics record without a metrics list")
        return
    for i, m in enumerate(entries):
        w = f"{where} metric[{i}]"
        if not isinstance(m.get("name"), str) or not m.get("name"):
            errors.append(f"{w}: missing name")
        kind = m.get("kind")
        if kind not in ("counter", "gauge", "histogram"):
            errors.append(f"{w}: bad kind {kind!r}")
        elif kind == "histogram":
            for key in ("count", "sum"):
                if not _finite(m.get(key)):
                    errors.append(f"{w}: histogram {key} non-finite")
        elif not _finite(m.get("value")):
            errors.append(f"{w}: {kind} value non-finite")
        if not isinstance(m.get("labels", {}), dict):
            errors.append(f"{w}: labels must be an object")


def validate_records(records, require_spans=False, require_gflops=False,
                     require_collectives=False, require_retries=False,
                     require_fallbacks=False, require_comm_overlap=False,
                     require_dc_batch=False, require_bt_overlap=False,
                     require_telemetry=False, require_accuracy=False,
                     require_serve=False, require_resilience=False,
                     require_flight=False, require_devtrace=False,
                     require_autotune=False, require_critpath=False,
                     require_fleet=False) -> list:
    """Validate parsed records; returns a list of error strings (empty =
    valid). ``require_*`` add the CI smoke-tier artifact obligations:
    at least one span, at least one span with finite derived gflops,
    collective byte counters in some metrics snapshot, at least one
    ``robust_cholesky.attempt`` retry span (with its attempt/shift
    attrs — the fault-injection smoke), a positive
    ``dlaf_fallback_total`` counter, (``require_comm_overlap``)
    positive finite ``dlaf_comm_overlapped_total{algo,axis}`` counters
    plus finite per-axis ``dlaf_comm_collective_bytes_total`` for BOTH
    mesh axes — the comm look-ahead audit trail (docs/comm_overlap.md) —,
    (``require_dc_batch``) a positive finite
    ``dlaf_dc_merges_total{mode="batched"}`` counter (the level-batched
    D&C audit trail, docs/eigensolver_perf.md), and
    (``require_bt_overlap``) a positive finite
    ``dlaf_comm_overlapped_total`` counter whose algo label starts with
    ``bt_`` (the pipelined back-transform's hoisted collectives), and
    (``require_telemetry``) the program-telemetry audit trail
    (docs/observability.md): >= 1 finite compile-seconds observation,
    finite HBM accounting, and retrace evidence — each leg satisfiable
    by EITHER a metrics snapshot (``dlaf_compile_seconds`` histogram /
    ``dlaf_hbm_bytes`` gauge / ``dlaf_retrace_total`` counter) or the
    per-event ``program`` records, so a run killed before the final
    snapshot landed still validates on its record trail — and
    (``require_accuracy``) at least one ``accuracy`` record with a finite
    value AND a finite ``bound_ratio`` (the DLAF_ACCURACY audit trail,
    docs/accuracy.md: an informational-only or all-nonfinite artifact
    must not satisfy the accuracy obligation), and (``require_serve``)
    the warmed steady-state serving obligation (docs/serving.md): >= 1
    ``serve`` dispatch record with >= 2 occupied lanes, ZERO dispatch
    records with ``cache: miss``, >= 1 request record with finite
    latency, >= 1 accuracy record from site ``serve`` (finite value +
    bound_ratio), and no serve-site retrace evidence at count >= 2 (a
    ``dlaf_retrace_total{site=serve.*}`` counter >= 2, or two program
    retrace records for one serve site — either means a bucket program
    recompiled mid-stream, the exact latency cliff warmup exists to
    prevent), and (``require_resilience``) the resilience audit trail
    (docs/robustness.md): >= 1 ``resilience`` record proving recovery
    actually ran (event ``retry`` or ``resume``), and NO
    ``dlaf_circuit_state`` gauge still at the open value (2) in the last
    metrics snapshot — a run that ended with a breaker tripped failed,
    whatever else it recorded — and (``require_flight``) the
    flight-recorder incident obligation (docs/observability.md): >= 1
    ``flight_trigger`` record with a known reason AND >= 1 ordinary
    (pre-trigger) record, so an incident dump that captured no context
    fails the drill — and (``require_devtrace``) the device-timeline
    attribution obligation (ISSUE 14, docs/observability.md): >= 1
    ``measured_overlap`` record with finite ``overlap_frac`` and
    POSITIVE attributed collective time (a trace that attributed zero
    collectives measured nothing about comm/compute overlap), and >= 1
    ``devtrace`` record with attribution coverage >=
    :data:`DEVTRACE_COVERAGE_FLOOR` (the schema validation above
    already rejects NaN phase walls unconditionally) — and
    (``require_autotune``) the closed-loop precision-steering obligation
    (docs/autotune.md): >= 1 ``autotune`` record with reason
    ``escalate`` or ``relax`` (the loop actually moved a route), and NO
    site whose LAST decision is ``exhausted`` (an artifact ending with
    the ladder pinned at its top under a breach is an open incident and
    must be REJECTED, like an open breaker) — and (``require_critpath``)
    the per-step critical-path attribution obligation (ISSUE 16,
    docs/observability.md): >= 1 ``critpath`` record with >= 1 step and
    join coverage >= :data:`CRITPATH_COVERAGE_FLOOR` (below the floor
    the per-step walls/gaps/bounds describe a minority of the scheduled
    timeline), and >= 1 ``whatif`` projection record (the headroom
    ranking the attribution exists to produce) — and (``require_fleet``)
    the multi-replica zero-loss obligation (docs/fleet.md): >= 1
    ``fleet`` record with event ``route`` (the router actually routed),
    ZERO ``ticket_lost`` records (a lost ticket is the exact failure the
    fleet tier exists to prevent — any occurrence REJECTS the artifact),
    and every ``worker_dead`` whose reason is not ``drained`` (an
    ungraceful death) must be answered by >= 1 ``redispatch`` record
    somewhere in the artifact — a crash with no failover is a silent
    at-least-once violation."""
    errors = []
    n_spans = n_gflops = n_coll = n_retries = n_fallbacks = 0
    n_dc_batched = n_bt_overlap = n_accuracy = 0
    n_compile_obs = n_hbm = n_retrace = 0
    n_serve_batched = n_serve_miss = n_serve_requests = 0
    n_serve_accuracy = 0
    n_resilience_proof = 0
    n_flight_triggers = n_flight_context = 0
    n_overlap_proof = n_devtrace_covered = 0
    n_autotune_moves = 0
    n_critpath_covered = n_whatif = 0
    n_fleet_routes = n_fleet_redispatch = n_fleet_lost = 0
    n_fleet_ungraceful_dead = 0
    autotune_last = {}                # site -> last decision reason seen
    devtrace_coverages = []
    critpath_coverages = []
    circuit_state = {}                # site -> latest gauge value seen
    serve_retrace_sites = {}          # serve.* site -> trace evidence count
    overlap_axes, byte_axes = set(), set()
    for i, r in enumerate(records):
        where = f"record {i}"
        if not isinstance(r, dict):
            errors.append(f"{where}: not an object")
            continue
        rtype = r.get("type")
        if rtype not in KNOWN_TYPES:
            errors.append(f"{where}: unknown type {rtype!r}")
            continue
        if not _finite(r.get("ts")):
            errors.append(f"{where}: missing/non-finite ts")
        if r.get("v") != SCHEMA_VERSION:
            errors.append(f"{where}: schema version {r.get('v')!r} != "
                          f"{SCHEMA_VERSION}")
        if "rank" in r and (not isinstance(r["rank"], int)
                            or isinstance(r["rank"], bool)
                            or r["rank"] < 0):
            errors.append(f"{where}: rank must be a non-negative int, "
                          f"got {r['rank']!r}")
        _validate_trace_stamp(r, where, errors)
        if rtype != "flight_trigger":
            n_flight_context += 1
        if rtype == "flight_trigger":
            _validate_flight_trigger(r, where, errors)
            if r.get("reason") in FLIGHT_REASONS:
                n_flight_triggers += 1
        elif rtype == "devtrace":
            _validate_devtrace(r, where, errors)
            if _finite(r.get("coverage")):
                devtrace_coverages.append(float(r["coverage"]))
                if r["coverage"] >= DEVTRACE_COVERAGE_FLOOR:
                    n_devtrace_covered += 1
        elif rtype == "measured_overlap":
            _validate_measured_overlap(r, where, errors)
            if _finite(r.get("overlap_frac")) \
                    and _finite(r.get("collective_s")) \
                    and r["collective_s"] > 0:
                n_overlap_proof += 1
        elif rtype == "schedule":
            _validate_schedule(r, where, errors)
        elif rtype == "critpath":
            _validate_critpath(r, where, errors)
            if _finite(r.get("coverage")):
                critpath_coverages.append(float(r["coverage"]))
                if r["coverage"] >= CRITPATH_COVERAGE_FLOOR \
                        and isinstance(r.get("n_steps"), int) \
                        and r["n_steps"] >= 1:
                    n_critpath_covered += 1
        elif rtype == "whatif":
            _validate_whatif(r, where, errors)
            n_whatif += 1
        elif rtype == "fleet":
            _validate_fleet(r, where, errors)
            event = r.get("event")
            if event == "route":
                n_fleet_routes += 1
            elif event == "redispatch":
                n_fleet_redispatch += 1
            elif event == "ticket_lost":
                n_fleet_lost += 1
            elif event == "worker_dead" \
                    and (r.get("attrs") or {}).get("reason") != "drained":
                n_fleet_ungraceful_dead += 1
        elif rtype == "autotune":
            _validate_autotune(r, where, errors)
            if r.get("reason") in ("escalate", "relax"):
                n_autotune_moves += 1
            if isinstance(r.get("site"), str) \
                    and r.get("reason") in AUTOTUNE_REASONS:
                # records are ordered: this ends at each site's LAST
                # decision — the state the run finished in
                autotune_last[r["site"]] = r["reason"]
        elif rtype == "program":
            _validate_program(r, where, errors)
            if r.get("event") == "compile" and _finite(r.get("compile_s")):
                n_compile_obs += 1
            # program records are first-class telemetry evidence for ALL
            # three --require-telemetry legs: a run killed before the
            # final metrics snapshot landed still wrote its audit trail
            if r.get("event") == "retrace":
                n_retrace += 1
                site = r.get("site")
                if isinstance(site, str) and site.startswith("serve."):
                    serve_retrace_sites[site] = \
                        serve_retrace_sites.get(site, 0) + 1
            hbm = r.get("hbm")
            if isinstance(hbm, dict) and hbm \
                    and all(_finite(v) for v in hbm.values()):
                n_hbm += 1
        elif rtype == "accuracy":
            _validate_accuracy(r, where, errors)
            if _finite(r.get("value")) and _finite(r.get("bound_ratio")):
                n_accuracy += 1
                if r.get("site") == "serve":
                    n_serve_accuracy += 1
        elif rtype == "resilience":
            _validate_resilience(r, where, errors)
            if r.get("event") in ("retry", "resume"):
                n_resilience_proof += 1
        elif rtype == "serve":
            _validate_serve(r, where, errors)
            if r.get("event") == "dispatch":
                if isinstance(r.get("lanes"), int) and r["lanes"] >= 2 \
                        and r.get("cache") == "hit":
                    n_serve_batched += 1
                if r.get("cache") == "miss":
                    n_serve_miss += 1
            elif r.get("event") == "request" \
                    and _finite(r.get("total_s")):
                n_serve_requests += 1
        elif rtype == "span":
            _validate_span(r, where, errors)
            n_spans += 1
            if _finite(r.get("gflops")):
                n_gflops += 1
            if r.get("name") == "robust_cholesky.attempt" and \
                    (r.get("attrs") or {}).get("attempt", 0) >= 1:
                # attempt 0 is the plain factorization; only a shifted
                # RE-attempt proves the recovery path ran
                n_retries += 1
        elif rtype == "metrics":
            _validate_metrics(r, where, errors)
            for m in r.get("metrics") or []:
                if not isinstance(m, dict):
                    continue
                # histogram checks come BEFORE the finite-value guard:
                # histograms carry count/sum, never a 'value'
                if m.get("name") == "dlaf_compile_seconds" \
                        and m.get("kind") == "histogram" \
                        and isinstance(m.get("count"), int) \
                        and m["count"] >= 1 and _finite(m.get("sum")):
                    n_compile_obs += 1
                if not _finite(m.get("value")):
                    continue
                if m.get("name") == "dlaf_comm_collective_bytes_total" \
                        and m["value"] > 0:
                    n_coll += 1
                    axis = (m.get("labels") or {}).get("axis")
                    if axis:
                        byte_axes.add(axis)
                if m.get("name") == "dlaf_comm_overlapped_total" \
                        and m["value"] > 0:
                    labels = m.get("labels") or {}
                    if labels.get("algo") and labels.get("axis"):
                        overlap_axes.add(labels["axis"])
                        if str(labels["algo"]).startswith("bt_"):
                            n_bt_overlap += 1
                if m.get("name") == "dlaf_dc_merges_total" \
                        and m["value"] > 0 \
                        and (m.get("labels") or {}).get("mode") == "batched":
                    n_dc_batched += 1
                if m.get("name") == "dlaf_fallback_total" and m["value"] > 0:
                    n_fallbacks += 1
                if m.get("name") == "dlaf_circuit_state":
                    # records are ordered, so this ends at the LAST
                    # snapshot's value per site — the state the run
                    # finished in
                    site = (m.get("labels") or {}).get("site", "")
                    circuit_state[site] = float(m["value"])
                if m.get("name") == "dlaf_hbm_bytes":
                    n_hbm += 1
                if m.get("name") == "dlaf_retrace_total" and m["value"] >= 1:
                    n_retrace += 1
                    site = (m.get("labels") or {}).get("site", "")
                    if str(site).startswith("serve.") and m["value"] >= 2:
                        serve_retrace_sites[site] = max(
                            serve_retrace_sites.get(site, 0),
                            int(m["value"]))
        elif rtype == "log":
            if not isinstance(r.get("msg"), str):
                errors.append(f"{where}: log without msg")
    if require_spans and n_spans == 0:
        errors.append("artifact contains no span records")
    if require_gflops and n_gflops == 0:
        errors.append("artifact contains no span with finite derived gflops")
    if require_collectives and n_coll == 0:
        errors.append("artifact contains no positive "
                      "dlaf_comm_collective_bytes_total counter")
    if require_retries and n_retries == 0:
        errors.append("artifact contains no robust_cholesky.attempt "
                      "retry span (attempt >= 1)")
    if require_fallbacks and n_fallbacks == 0:
        errors.append("artifact contains no positive dlaf_fallback_total "
                      "counter")
    if require_dc_batch and n_dc_batched == 0:
        errors.append("artifact contains no positive "
                      "dlaf_dc_merges_total{mode=batched} counter")
    if require_bt_overlap and n_bt_overlap == 0:
        errors.append("artifact contains no positive "
                      "dlaf_comm_overlapped_total counter with a bt_* algo")
    if require_telemetry:
        if n_compile_obs == 0:
            errors.append("artifact contains no finite compile-seconds "
                          "observation (program record or "
                          "dlaf_compile_seconds histogram)")
        if n_hbm == 0:
            errors.append("artifact contains no finite HBM accounting "
                          "(dlaf_hbm_bytes gauge or program-record hbm)")
        if n_retrace == 0:
            errors.append("artifact contains no retrace evidence "
                          "(dlaf_retrace_total counter >= 1 or program "
                          "retrace record)")
    if require_accuracy and n_accuracy == 0:
        errors.append("artifact contains no accuracy record with finite "
                      "value and bound_ratio")
    if require_serve:
        if n_serve_batched == 0:
            errors.append("artifact contains no batched serve dispatch "
                          "(dispatch record with lanes >= 2, cache hit)")
        if n_serve_miss > 0:
            errors.append(f"artifact contains {n_serve_miss} serve "
                          "dispatch(es) with cache miss — a warmed "
                          "steady-state stream must be all hits")
        if n_serve_requests == 0:
            errors.append("artifact contains no serve request record with "
                          "finite latency")
        if n_serve_accuracy == 0:
            errors.append("artifact contains no per-request accuracy "
                          "record (site serve, finite value+bound_ratio)")
        hot = sorted(s for s, c in serve_retrace_sites.items() if c >= 2)
        if hot:
            errors.append("serve bucket program(s) retraced mid-stream "
                          f"(count >= 2): {hot}")
    if require_resilience:
        if n_resilience_proof == 0:
            errors.append("artifact contains no resilience retry/resume "
                          "record (recovery never exercised)")
        open_sites = sorted(s for s, v in circuit_state.items() if v >= 2)
        if open_sites:
            errors.append("circuit breaker(s) left open at artifact end "
                          f"(dlaf_circuit_state >= 2): {open_sites}")
    if require_flight:
        if n_flight_triggers == 0:
            errors.append("artifact contains no flight_trigger record "
                          "with a known reason (no incident dump)")
        if n_flight_context == 0:
            errors.append("flight artifact carries no pre-trigger context "
                          "records (the ring captured nothing)")
    if require_devtrace:
        if n_overlap_proof == 0:
            errors.append("artifact contains no measured_overlap record "
                          "with finite overlap_frac and positive "
                          "attributed collective time (the device "
                          "timeline attributed no collectives)")
        if n_devtrace_covered == 0:
            got = (f" (got {['%.3f' % c for c in devtrace_coverages]})"
                   if devtrace_coverages else "")
            errors.append("artifact contains no devtrace record with "
                          "attribution coverage >= "
                          f"{DEVTRACE_COVERAGE_FLOOR}{got}")
    if require_critpath:
        if n_critpath_covered == 0:
            got = (f" (got {['%.3f' % c for c in critpath_coverages]})"
                   if critpath_coverages else "")
            errors.append("artifact contains no critpath record with "
                          ">= 1 step and join coverage >= "
                          f"{CRITPATH_COVERAGE_FLOOR}{got}")
        if n_whatif == 0:
            errors.append("artifact contains no whatif projection record "
                          "(critpath attribution produced no headroom "
                          "ranking)")
    if require_autotune:
        if n_autotune_moves == 0:
            errors.append("artifact contains no autotune escalate/relax "
                          "decision record (the closed loop never moved "
                          "a route)")
        exhausted = sorted(s for s, reason in autotune_last.items()
                           if reason == "exhausted")
        if exhausted:
            errors.append("autotune ladder(s) left exhausted at artifact "
                          f"end (last decision 'exhausted'): {exhausted}")
    if require_fleet:
        if n_fleet_routes == 0:
            errors.append("artifact contains no fleet route record (the "
                          "router never dispatched anything)")
        if n_fleet_lost > 0:
            errors.append(f"artifact contains {n_fleet_lost} fleet "
                          "ticket_lost record(s) — the zero-loss "
                          "contract (docs/fleet.md) is violated")
        if n_fleet_ungraceful_dead > 0 and n_fleet_redispatch == 0:
            errors.append(f"artifact contains {n_fleet_ungraceful_dead} "
                          "ungraceful fleet worker death(s) but no "
                          "redispatch record — failover never ran")
    if require_comm_overlap:
        if not {"row", "col"} <= overlap_axes:
            errors.append("artifact lacks positive finite "
                          "dlaf_comm_overlapped_total{algo,axis} counters "
                          f"for both mesh axes (got {sorted(overlap_axes)})")
        if not {"row", "col"} <= byte_axes:
            errors.append("artifact lacks finite per-axis "
                          "dlaf_comm_collective_bytes_total for both mesh "
                          f"axes (got {sorted(byte_axes)})")
    return errors


def read_records(path: str) -> list:
    """Parse a JSONL artifact; raises ValueError on an unparsable line."""
    records = []
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                records.append(json.loads(raw))
            except ValueError as e:
                raise ValueError(f"{path}:{ln}: unparsable JSON ({e})")
    return records


def validate_file(path: str, **require) -> list:
    """Errors for the artifact at ``path`` (empty list = schema-valid)."""
    try:
        records = read_records(path)
    except (OSError, ValueError) as e:
        return [str(e)]
    return validate_records(records, **require)


# ---------------------------------------------------------------------------
# History line schemas (.bench_history.jsonl / .accuracy_history.jsonl)
# ---------------------------------------------------------------------------
# Bare measurement lines (no v/type/ts envelope — the bench file predates
# the obs schema and BASELINE.md cites it verbatim), but schema-owned
# HERE — ONE validating reader parameterized by ``kind`` — so bench.py's
# replayed-history headline lookup, scripts/bench_gate.py, and
# scripts/accuracy_gate.py all read through the same code path and never
# silently ingest a malformed or non-finite entry (ISSUE 8 satellite: no
# second bespoke history parser).

#: ``kind`` -> (numeric fields, string fields): numeric fields must be
#: finite; string fields must be non-empty strings.
HISTORY_KINDS = {
    "bench": (("gflops", "t", "n", "nb"),
              ("variant", "platform", "dtype", "ts", "source")),
    "accuracy": (("value", "bound_ratio", "n", "nb"),
                 ("site", "metric", "platform", "dtype", "ts", "source")),
}

#: Backward-compatible aliases for the original bench-only schema names.
HISTORY_NUMERIC_FIELDS, HISTORY_STRING_FIELDS = HISTORY_KINDS["bench"]


def validate_history_line(line: dict, kind: str = "bench") -> list:
    """Error strings for ONE history measurement line (empty = valid)."""
    errors = []
    if not isinstance(line, dict):
        return [f"{kind} history line is not an object"]
    numeric, strings = HISTORY_KINDS[kind]
    for key in numeric:
        if not _finite(line.get(key)):
            errors.append(f"{kind} history field {key!r} missing/non-finite "
                          f"(got {line.get(key)!r})")
    for key in strings:
        if not isinstance(line.get(key), str) or not line.get(key):
            errors.append(f"{kind} history field {key!r} missing/empty")
    return errors


def validate_history_records(records, kind: str = "bench") -> list:
    errors = []
    for i, line in enumerate(records):
        for e in validate_history_line(line, kind):
            errors.append(f"entry {i}: {e}")
    return errors


def read_history_records(path: str, kind: str = "bench") -> list:
    """Parse + validate an append-only measurement history; raises
    ValueError on an unparsable or schema-invalid line (loud by contract:
    a bad line would otherwise skew every replayed-history headline and
    every gate baseline derived from the file)."""
    records = read_records(path)
    errors = validate_history_records(records, kind)
    if errors:
        raise ValueError(f"{path}: invalid {kind} history: "
                         + "; ".join(errors[:5])
                         + (f" (+{len(errors) - 5} more)"
                            if len(errors) > 5 else ""))
    return records


def append_history_line(path: str, line: dict, kind: str = "bench") -> dict:
    """Validate + append one measurement line to a history log (the
    single write path — scripts/measure_common routes through here).
    Raises ValueError instead of writing a line the readers would have
    to reject."""
    errors = validate_history_line(line, kind)
    if errors:
        raise ValueError(f"refusing to append invalid {kind} history line: "
                         + "; ".join(errors))
    with open(path, "a") as f:
        f.write(json.dumps(line) + "\n")
    return line


def accuracy_record_to_history_line(rec: dict) -> Optional[dict]:
    """Project one ``accuracy`` JSONL record onto the accuracy-history
    line shape (the ``--fresh`` ingestion of scripts/accuracy_gate.py —
    shared here so the gate and any future appender agree on the
    mapping). Returns None for records that carry no gateable budget
    (informational metrics without ``bound_ratio``); a nonfinite record
    maps to ``bound_ratio: inf`` — NOT JSON-appendable, by design: the
    gate must trip on it, never archive it."""
    if rec.get("type") != "accuracy":
        return None
    if rec.get("nonfinite") is True:
        value = ratio = float("inf")
    elif _finite(rec.get("value")) and _finite(rec.get("bound_ratio")):
        value, ratio = rec["value"], rec["bound_ratio"]
    else:
        return None
    return {"site": rec.get("site"), "metric": rec.get("metric"),
            "platform": rec.get("platform"), "dtype": rec.get("dtype"),
            "n": rec.get("n"), "nb": rec.get("nb"),
            "value": value, "bound_ratio": ratio}
