"""Event sinks and the JSONL artifact schema.

Two output formats, per the observability design (ISSUE 1):

* **JSON lines** (:class:`JsonlSink`) — one self-describing event object
  per line, append-only, the same artifact convention as the repo's
  ``BENCH_*.json`` round files. Everything the tracer/metrics/logger emit
  flows through here when ``DLAF_METRICS_PATH`` is set.
* **Prometheus text exposition** (:func:`prometheus_text`, over a registry
  snapshot) — for scraping; see :mod:`dlaf_tpu.obs.metrics`.

Schema (version 1). Every record carries ``v`` (int schema version),
``type`` (str) and ``ts`` (float, unix seconds). Per type:

``span``
    ``name`` str, ``dur_s`` finite float >= 0, ``depth`` int >= 0,
    ``parent`` str or null, ``attrs`` object. Optional ``flops`` (finite
    number) and ``gflops`` (finite number, derived = flops / dur_s / 1e9).
    Optional ``fenced: false`` marks spans whose wall clock is host
    trace+dispatch only (async JAX work, no device fence inside the
    region) — such records never carry ``gflops``.
``metrics``
    ``metrics``: list of snapshot entries — ``name`` str, ``kind``
    "counter" | "gauge" | "histogram", ``labels`` object; counters/gauges
    carry finite ``value``; histograms carry ``count``/``sum``/``min``/
    ``max`` and ``buckets`` (list of [le, count]).
``log``
    ``level`` str, ``logger`` str, ``msg`` str, ``fields`` object.
``bench_result``
    ``payload`` object (free-form; bench.py's measurement line).

:func:`validate_file` is the single schema owner consumed by tests and the
CI gate (``python -m dlaf_tpu.obs.validate``): it rejects unparsable lines,
missing fields, and non-finite numerics (a NaN GFlop/s must fail the tier,
not scrape as a number).
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Optional

SCHEMA_VERSION = 1

KNOWN_TYPES = ("span", "metrics", "log", "bench_result")


class JsonlSink:
    """Append-only JSON-lines writer; thread-safe, line-buffered so a
    killed process still leaves a readable prefix."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = None

    def write(self, record: dict) -> None:
        record.setdefault("v", SCHEMA_VERSION)
        record.setdefault("ts", time.time())
        line = json.dumps(record, default=str)
        with self._lock:
            if self._f is None:
                self._f = open(self.path, "a", buffering=1)
            self._f.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and math.isfinite(x)


def _validate_span(r: dict, where: str, errors: list) -> None:
    if not isinstance(r.get("name"), str) or not r.get("name"):
        errors.append(f"{where}: span without a name")
    if not _finite(r.get("dur_s")) or r.get("dur_s", -1) < 0:
        errors.append(f"{where}: span dur_s missing/non-finite/negative")
    if not isinstance(r.get("depth"), int) or r.get("depth", -1) < 0:
        errors.append(f"{where}: span depth missing or negative")
    if not isinstance(r.get("attrs", {}), dict):
        errors.append(f"{where}: span attrs must be an object")
    for key in ("flops", "gflops"):
        if key in r and not _finite(r[key]):
            errors.append(f"{where}: span {key} non-finite")
    if r.get("fenced") is False and "gflops" in r:
        # the tracer never derives throughput from unfenced dispatch
        # wall; hold third-party emitters to the same contract
        errors.append(f"{where}: unfenced span must not carry gflops")
    if r.get("name") == "robust_cholesky.attempt":
        # retry spans are the recovery audit trail (docs/robustness.md):
        # each must say WHICH attempt with WHAT shift, or the artifact
        # cannot reconstruct the recovery history
        attrs = r.get("attrs") or {}
        for key in ("attempt", "shift"):
            if not _finite(attrs.get(key)):
                errors.append(
                    f"{where}: retry span missing finite attr {key!r}")


def _validate_metrics(r: dict, where: str, errors: list) -> None:
    entries = r.get("metrics")
    if not isinstance(entries, list):
        errors.append(f"{where}: metrics record without a metrics list")
        return
    for i, m in enumerate(entries):
        w = f"{where} metric[{i}]"
        if not isinstance(m.get("name"), str) or not m.get("name"):
            errors.append(f"{w}: missing name")
        kind = m.get("kind")
        if kind not in ("counter", "gauge", "histogram"):
            errors.append(f"{w}: bad kind {kind!r}")
        elif kind == "histogram":
            for key in ("count", "sum"):
                if not _finite(m.get(key)):
                    errors.append(f"{w}: histogram {key} non-finite")
        elif not _finite(m.get("value")):
            errors.append(f"{w}: {kind} value non-finite")
        if not isinstance(m.get("labels", {}), dict):
            errors.append(f"{w}: labels must be an object")


def validate_records(records, require_spans=False, require_gflops=False,
                     require_collectives=False, require_retries=False,
                     require_fallbacks=False, require_comm_overlap=False,
                     require_dc_batch=False, require_bt_overlap=False) -> list:
    """Validate parsed records; returns a list of error strings (empty =
    valid). ``require_*`` add the CI smoke-tier artifact obligations:
    at least one span, at least one span with finite derived gflops,
    collective byte counters in some metrics snapshot, at least one
    ``robust_cholesky.attempt`` retry span (with its attempt/shift
    attrs — the fault-injection smoke), a positive
    ``dlaf_fallback_total`` counter, (``require_comm_overlap``)
    positive finite ``dlaf_comm_overlapped_total{algo,axis}`` counters
    plus finite per-axis ``dlaf_comm_collective_bytes_total`` for BOTH
    mesh axes — the comm look-ahead audit trail (docs/comm_overlap.md) —,
    (``require_dc_batch``) a positive finite
    ``dlaf_dc_merges_total{mode="batched"}`` counter (the level-batched
    D&C audit trail, docs/eigensolver_perf.md), and
    (``require_bt_overlap``) a positive finite
    ``dlaf_comm_overlapped_total`` counter whose algo label starts with
    ``bt_`` (the pipelined back-transform's hoisted collectives)."""
    errors = []
    n_spans = n_gflops = n_coll = n_retries = n_fallbacks = 0
    n_dc_batched = n_bt_overlap = 0
    overlap_axes, byte_axes = set(), set()
    for i, r in enumerate(records):
        where = f"record {i}"
        if not isinstance(r, dict):
            errors.append(f"{where}: not an object")
            continue
        rtype = r.get("type")
        if rtype not in KNOWN_TYPES:
            errors.append(f"{where}: unknown type {rtype!r}")
            continue
        if not _finite(r.get("ts")):
            errors.append(f"{where}: missing/non-finite ts")
        if r.get("v") != SCHEMA_VERSION:
            errors.append(f"{where}: schema version {r.get('v')!r} != "
                          f"{SCHEMA_VERSION}")
        if rtype == "span":
            _validate_span(r, where, errors)
            n_spans += 1
            if _finite(r.get("gflops")):
                n_gflops += 1
            if r.get("name") == "robust_cholesky.attempt" and \
                    (r.get("attrs") or {}).get("attempt", 0) >= 1:
                # attempt 0 is the plain factorization; only a shifted
                # RE-attempt proves the recovery path ran
                n_retries += 1
        elif rtype == "metrics":
            _validate_metrics(r, where, errors)
            for m in r.get("metrics") or []:
                if not isinstance(m, dict) or not _finite(m.get("value")):
                    continue
                if m.get("name") == "dlaf_comm_collective_bytes_total" \
                        and m["value"] > 0:
                    n_coll += 1
                    axis = (m.get("labels") or {}).get("axis")
                    if axis:
                        byte_axes.add(axis)
                if m.get("name") == "dlaf_comm_overlapped_total" \
                        and m["value"] > 0:
                    labels = m.get("labels") or {}
                    if labels.get("algo") and labels.get("axis"):
                        overlap_axes.add(labels["axis"])
                        if str(labels["algo"]).startswith("bt_"):
                            n_bt_overlap += 1
                if m.get("name") == "dlaf_dc_merges_total" \
                        and m["value"] > 0 \
                        and (m.get("labels") or {}).get("mode") == "batched":
                    n_dc_batched += 1
                if m.get("name") == "dlaf_fallback_total" and m["value"] > 0:
                    n_fallbacks += 1
        elif rtype == "log":
            if not isinstance(r.get("msg"), str):
                errors.append(f"{where}: log without msg")
    if require_spans and n_spans == 0:
        errors.append("artifact contains no span records")
    if require_gflops and n_gflops == 0:
        errors.append("artifact contains no span with finite derived gflops")
    if require_collectives and n_coll == 0:
        errors.append("artifact contains no positive "
                      "dlaf_comm_collective_bytes_total counter")
    if require_retries and n_retries == 0:
        errors.append("artifact contains no robust_cholesky.attempt "
                      "retry span (attempt >= 1)")
    if require_fallbacks and n_fallbacks == 0:
        errors.append("artifact contains no positive dlaf_fallback_total "
                      "counter")
    if require_dc_batch and n_dc_batched == 0:
        errors.append("artifact contains no positive "
                      "dlaf_dc_merges_total{mode=batched} counter")
    if require_bt_overlap and n_bt_overlap == 0:
        errors.append("artifact contains no positive "
                      "dlaf_comm_overlapped_total counter with a bt_* algo")
    if require_comm_overlap:
        if not {"row", "col"} <= overlap_axes:
            errors.append("artifact lacks positive finite "
                          "dlaf_comm_overlapped_total{algo,axis} counters "
                          f"for both mesh axes (got {sorted(overlap_axes)})")
        if not {"row", "col"} <= byte_axes:
            errors.append("artifact lacks finite per-axis "
                          "dlaf_comm_collective_bytes_total for both mesh "
                          f"axes (got {sorted(byte_axes)})")
    return errors


def read_records(path: str) -> list:
    """Parse a JSONL artifact; raises ValueError on an unparsable line."""
    records = []
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                records.append(json.loads(raw))
            except ValueError as e:
                raise ValueError(f"{path}:{ln}: unparsable JSON ({e})")
    return records


def validate_file(path: str, **require) -> list:
    """Errors for the artifact at ``path`` (empty list = schema-valid)."""
    try:
        records = read_records(path)
    except (OSError, ValueError) as e:
        return [str(e)]
    return validate_records(records, **require)
