"""CLI validator for DLAF_METRICS_PATH artifacts (the CI gate).

    python -m dlaf_tpu.obs.validate <artifact.jsonl> [flags]

Flags:
    --require-spans         fail unless >= 1 span record
    --require-gflops        fail unless >= 1 span has finite derived gflops
    --require-collectives   fail unless a metrics snapshot carries a
                            positive dlaf_comm_collective_bytes_total
    --require-retries       fail unless >= 1 robust_cholesky.attempt span
                            with attempt >= 1 (an actual shifted retry —
                            the fault-injection smoke's audit trail)
    --require-fallbacks     fail unless a metrics snapshot carries a
                            positive dlaf_fallback_total
    --require-comm-overlap  fail unless a metrics snapshot carries positive
                            finite dlaf_comm_overlapped_total{algo,axis}
                            counters AND finite per-axis
                            dlaf_comm_collective_bytes_total for BOTH mesh
                            axes (the comm look-ahead audit trail,
                            docs/comm_overlap.md)
    --require-dc-batch      fail unless a metrics snapshot carries a
                            positive dlaf_dc_merges_total{mode=batched}
                            counter (the level-batched D&C audit trail,
                            docs/eigensolver_perf.md)
    --require-bt-overlap    fail unless a metrics snapshot carries a
                            positive dlaf_comm_overlapped_total counter
                            with a bt_* algo label (the pipelined
                            back-transform's hoisted collectives)
    --require-telemetry     fail unless the artifact carries the program
                            telemetry audit trail (DLAF_PROGRAM_TELEMETRY,
                            docs/observability.md): >= 1 finite
                            compile-seconds observation, finite HBM
                            accounting, and retrace evidence — each leg
                            satisfiable by a metrics snapshot OR by the
                            per-event program records
    --require-accuracy      fail unless >= 1 accuracy record carries a
                            finite value AND a finite bound_ratio (the
                            DLAF_ACCURACY audit trail, docs/accuracy.md;
                            informational-only or all-nonfinite artifacts
                            do not satisfy it)
    --require-serve         fail unless the artifact carries a warmed
                            steady-state serving trail (docs/serving.md):
                            >= 1 batched serve dispatch (lanes >= 2,
                            cache hit), ZERO cache-miss dispatches, >= 1
                            request record with finite latency, >= 1
                            per-request accuracy record (site serve),
                            and no serve bucket program retraced twice
                            (dlaf_retrace_total{site=serve.*} < 2)
    --require-resilience    fail unless the artifact carries the
                            resilience audit trail (docs/robustness.md):
                            >= 1 ``resilience`` record with event retry
                            or resume (recovery actually exercised), and
                            NO dlaf_circuit_state gauge left at the open
                            value (2) in the last metrics snapshot — a
                            run that ended with a tripped breaker must
                            fail the gate, not scrape as healthy
    --require-flight        validate the file as a flight-recorder
                            incident dump (docs/observability.md live
                            operations): >= 1 flight_trigger record with
                            a known reason AND >= 1 ordinary pre-trigger
                            record captured by the ring
    --require-autotune      fail unless the artifact carries the
                            closed-loop precision-steering trail
                            (DLAF_AUTOTUNE, docs/autotune.md): >= 1
                            autotune record with reason escalate|relax
                            (the loop actually moved a route), and no
                            site whose LAST decision is 'exhausted' —
                            an artifact ending with the ladder pinned
                            at its top under a breach is an open
                            incident and must be REJECTED
    --require-devtrace      fail unless the artifact carries the
                            device-timeline attribution trail (ISSUE 14,
                            docs/observability.md): >= 1 measured_overlap
                            record with finite overlap_frac and POSITIVE
                            attributed collective device time, and >= 1
                            devtrace record with attribution coverage >=
                            the documented floor
                            (sinks.DEVTRACE_COVERAGE_FLOOR); NaN phase
                            walls are schema errors regardless
    --require-critpath      fail unless the artifact carries the
                            per-step critical-path attribution trail
                            (ISSUE 16, docs/observability.md): >= 1
                            critpath record with >= 1 step and join
                            coverage >= the documented floor
                            (sinks.CRITPATH_COVERAGE_FLOOR), and >= 1
                            whatif projection record; NaN step walls
                            are schema errors regardless
    --require-fleet         fail unless the artifact carries the
                            multi-replica zero-loss trail (docs/
                            fleet.md): >= 1 fleet record with event
                            route (the router actually dispatched),
                            ZERO ticket_lost records (any lost ticket
                            REJECTS the artifact — the exact failure
                            the fleet tier exists to prevent), and
                            every ungraceful worker_dead (reason !=
                            drained) answered by >= 1 redispatch
                            record (failover actually ran)
    --history               validate the file as an append-only bench
                            history log (.bench_history.jsonl: bare
                            measurement lines — finite gflops/t/n/nb,
                            non-empty variant/platform/dtype/ts/source)
                            instead of an obs artifact; incompatible with
                            the --require-* flags
    --accuracy-history      validate the file as an append-only accuracy
                            history log (.accuracy_history.jsonl: finite
                            value/bound_ratio/n/nb, non-empty site/metric/
                            platform/dtype/ts/source); incompatible with
                            --history and the --require-* flags
    --prom                  print the last metrics snapshot as Prometheus
                            text exposition after validating

Exit status 0 = schema-valid (and all required content present); 1 =
errors (printed one per line); 2 = usage error (unknown flag, or not
exactly one path). ``ci/run.sh smoke`` runs this over the miniapp
artifacts — missing or NaN fields fail the tier.
"""

from __future__ import annotations

import sys

from .metrics import prometheus_text
from .sinks import read_records, validate_history_records, validate_records


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    flags = {a for a in argv if a.startswith("--")}
    paths = [a for a in argv if not a.startswith("--")]
    known = {"--require-spans", "--require-gflops", "--require-collectives",
             "--require-retries", "--require-fallbacks",
             "--require-comm-overlap", "--require-dc-batch",
             "--require-bt-overlap", "--require-telemetry",
             "--require-accuracy", "--require-serve",
             "--require-resilience", "--require-flight",
             "--require-devtrace", "--require-autotune",
             "--require-critpath", "--require-fleet", "--history",
             "--accuracy-history", "--prom"}
    requires = {f for f in flags if f.startswith("--require-")}
    history_modes = flags & {"--history", "--accuracy-history"}
    if len(paths) != 1 or flags - known \
            or (history_modes and requires) or len(history_modes) > 1:
        print(__doc__, file=sys.stderr)
        return 2
    path = paths[0]
    try:
        records = read_records(path)
    except (OSError, ValueError) as e:
        print(f"INVALID {path}: {e}", file=sys.stderr)
        return 1
    if history_modes:
        kind = "accuracy" if "--accuracy-history" in flags else "bench"
        errors = validate_history_records(records, kind)
        if errors:
            for e in errors:
                print(f"INVALID {path}: {e}", file=sys.stderr)
            return 1
        print(f"VALID {path}: {len(records)} {kind} history entries")
        return 0
    errors = validate_records(
        records,
        require_spans="--require-spans" in flags,
        require_gflops="--require-gflops" in flags,
        require_collectives="--require-collectives" in flags,
        require_retries="--require-retries" in flags,
        require_fallbacks="--require-fallbacks" in flags,
        require_comm_overlap="--require-comm-overlap" in flags,
        require_dc_batch="--require-dc-batch" in flags,
        require_bt_overlap="--require-bt-overlap" in flags,
        require_telemetry="--require-telemetry" in flags,
        require_accuracy="--require-accuracy" in flags,
        require_serve="--require-serve" in flags,
        require_resilience="--require-resilience" in flags,
        require_flight="--require-flight" in flags,
        require_devtrace="--require-devtrace" in flags,
        require_autotune="--require-autotune" in flags,
        require_critpath="--require-critpath" in flags,
        require_fleet="--require-fleet" in flags)
    if errors:
        for e in errors:
            print(f"INVALID {path}: {e}", file=sys.stderr)
        return 1
    n_spans = sum(r.get("type") == "span" for r in records)
    n_logs = sum(r.get("type") == "log" for r in records)
    n_progs = sum(r.get("type") == "program" for r in records)
    n_acc = sum(r.get("type") == "accuracy" for r in records)
    n_serve = sum(r.get("type") == "serve" for r in records)
    n_res = sum(r.get("type") == "resilience" for r in records)
    n_flight = sum(r.get("type") == "flight_trigger" for r in records)
    n_devtrace = sum(r.get("type") in ("devtrace", "measured_overlap")
                     for r in records)
    n_autotune = sum(r.get("type") == "autotune" for r in records)
    n_critpath = sum(r.get("type") in ("schedule", "critpath", "whatif")
                     for r in records)
    n_fleet = sum(r.get("type") == "fleet" for r in records)
    snaps = [r for r in records if r.get("type") == "metrics"]
    ranks = sorted({r["rank"] for r in records if "rank" in r})
    extra = f", {n_progs} program events" if n_progs else ""
    extra += f", {n_acc} accuracy records" if n_acc else ""
    extra += f", {n_serve} serve records" if n_serve else ""
    extra += f", {n_res} resilience records" if n_res else ""
    extra += f", {n_flight} flight triggers" if n_flight else ""
    extra += f", {n_devtrace} devtrace records" if n_devtrace else ""
    extra += f", {n_autotune} autotune decisions" if n_autotune else ""
    extra += f", {n_critpath} critpath records" if n_critpath else ""
    extra += f", {n_fleet} fleet records" if n_fleet else ""
    extra += f", ranks {ranks}" if ranks else ""
    print(f"VALID {path}: {len(records)} records ({n_spans} spans, "
          f"{len(snaps)} metrics snapshots, {n_logs} logs{extra})")
    if "--prom" in flags and snaps:
        sys.stdout.write(prometheus_text(snaps[-1]["metrics"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
