"""Request-scoped trace correlation (ISSUE 13, docs/observability.md).

One context-local slot holding ``(trace_id, span_id)``; the JSONL sink
(:mod:`dlaf_tpu.obs.sinks`) stamps both onto EVERY record written while
the context is active — ``request``, ``dispatch``, span, ``accuracy``,
``resilience``, ``program`` — so a single ID joins a request's whole
causal chain from ``Queue.submit`` through retry/breaker decisions to
its per-lane accuracy record, with zero per-record plumbing at the emit
sites.

Conventions (the serving layer is the reference user, serve/queue.py):

* ``trace_id`` — one 16-hex-char ID per REQUEST, generated at
  ``Queue.submit``. Records scoped to one request carry it as a string;
  records scoped to a whole batch (a dispatch record, the retry records
  of a batched dispatch, a program compile triggered by the batch) carry
  the LIST of member trace IDs — ``obs.aggregate --trace <id>`` matches
  both.
* ``span_id`` — one 16-hex-char ID per batch DISPATCH, shared by the
  dispatch record and every member request's records; it is the join key
  between a request and the stage timings of the dispatch that served it.

Cost contract: with no context entered, the stamp check in the sink is
one ``ContextVar.get`` returning the ``None`` default — no allocation.
``contextvars`` (not a bare thread-local) so the IDs survive executor
hops the way the rest of the tracing machinery expects.
"""

from __future__ import annotations

import contextlib
import contextvars
import uuid

#: (trace, span_id) of the active context, or None. ``trace`` is a str,
#: a tuple of strs (batch scope), or None (span_id-only contexts).
_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "dlaf_trace_ctx", default=None)


def new_trace_id() -> str:
    """A fresh 16-hex-char request trace ID."""
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    """A fresh 16-hex-char dispatch span ID."""
    return uuid.uuid4().hex[:16]


@contextlib.contextmanager
def trace_context(trace_id=None, span_id=None):
    """Stamp ``trace_id``/``span_id`` onto every record emitted inside.

    ``trace_id`` may be a single ID (request scope), a list/tuple of IDs
    (batch scope — e.g. every member of a dispatch), or None to keep the
    enclosing context's trace while overriding only ``span_id``.
    Entering with both None is a no-op passthrough. Contexts nest; the
    innermost non-None value of each slot wins."""
    outer = _CTX.get()
    if trace_id is None and span_id is None:
        yield
        return
    if isinstance(trace_id, (list, tuple, set)):
        trace = tuple(str(t) for t in trace_id) or None
    elif trace_id is not None:
        trace = str(trace_id)
    else:
        trace = outer[0] if outer else None
    if span_id is None and outer:
        span_id = outer[1]
    token = _CTX.set((trace, str(span_id) if span_id is not None else None))
    try:
        yield
    finally:
        _CTX.reset(token)


def current_trace():
    """``(trace, span_id)`` of the active context — ``trace`` a str or
    tuple of strs — or ``(None, None)``."""
    ctx = _CTX.get()
    return ctx if ctx is not None else (None, None)


def single_trace_id():
    """The active trace ID when the context is request-scoped (a single
    string), else None — exemplar capture only attributes a latency
    observation to ONE request, never to a whole batch."""
    ctx = _CTX.get()
    return ctx[0] if ctx is not None and isinstance(ctx[0], str) else None


def record_stamp(record: dict) -> None:
    """Stamp the active context onto ``record`` (sink write path): sets
    ``trace_id`` (str, or list for batch scope) and ``span_id`` unless
    the emitter already provided them."""
    ctx = _CTX.get()
    if ctx is None:
        return
    trace, span_id = ctx
    if trace is not None and "trace_id" not in record:
        record["trace_id"] = list(trace) if isinstance(trace, tuple) \
            else trace
    if span_id is not None and "span_id" not in record:
        record["span_id"] = span_id


def trace_matches(record: dict, trace_id: str) -> bool:
    """Whether ``record`` belongs to ``trace_id`` — equal to its string
    ``trace_id``, or a member of its batch-scope list (the join predicate
    of ``obs.aggregate --trace``)."""
    tid = record.get("trace_id")
    if isinstance(tid, str):
        return tid == trace_id
    if isinstance(tid, (list, tuple)):
        return trace_id in tid
    return False
