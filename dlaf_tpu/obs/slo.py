"""Rolling-window SLO latency tracking (ISSUE 13, docs/observability.md).

One entry point, :func:`observe` (exposed as ``obs.observe_latency``):
feed one end-to-end latency for ``(op, bucket)`` and the module

* records it into ``dlaf_serve_latency_seconds{op,bucket}`` — the
  cumulative histogram whose buckets carry exemplar trace IDs on the
  live ``/metrics`` endpoint — and its attached
  :class:`~dlaf_tpu.obs.metrics.SlidingWindow` (ring of fixed-size epoch
  buckets: bounded memory, deterministic under the injectable clock);
* refreshes the ``dlaf_serve_latency_window{op,bucket,q}`` gauges for
  q in {0.5, 0.95, 0.99} from the window (numpy-linear
  :func:`~dlaf_tpu.obs.metrics.quantile` — the SAME computation
  bench.py's serve/overload arms report, by construction);
* counts one ``dlaf_slo_breach_total{op}`` when the latency exceeds the
  ``DLAF_SLO_P99_MS`` objective (0 = no objective, nothing counted).
  Per-observation burn counting, not a windowed-p99 comparison: every
  over-objective request burns budget the moment it completes, so the
  counter is deterministic and monotone — alerting math (burn rate over
  window) belongs to the scraper;
* trips the flight recorder with reason ``slo_breach_burst`` when at
  least ``DLAF_SLO_BURST`` breaches (default 5; 0 = off) land inside
  one SLO window for one op (ISSUE 14 satellite): the recorder's
  per-reason cooldown turns a sustained latency storm into ONE incident
  artifact holding the pre-burst ring instead of a re-dump per breach.
  Breach stamps ride the same injectable clock as the windows, so the
  drill is deterministic under a fake clock.

The window length comes from ``DLAF_SLO_WINDOW_S``; both serve-queue
request completions and :func:`dlaf_tpu.health.policy.with_policy`
successes record here (``op`` = the policy site for the latter), so the
same percentile machinery covers the serving path and every
policy-guarded call site. All no-op when metrics are off (the facade
gates before calling in).
"""

from __future__ import annotations

import time

#: Window quantiles exported as gauges, with their label spellings —
#: lexicographically ascending, which is also how the exposition sorts
#: them (pinned by tests/test_live_telemetry.py).
QUANTILES = ((0.5, "0.5"), (0.95, "0.95"), (0.99, "0.99"))

#: Histogram fed per observation (its window backs the gauges).
LATENCY_HISTOGRAM = "dlaf_serve_latency_seconds"

#: Gauge family holding the windowed quantiles.
WINDOW_GAUGE = "dlaf_serve_latency_window"

#: Counter of observations over the DLAF_SLO_P99_MS objective.
BREACH_COUNTER = "dlaf_slo_breach_total"

#: Injectable clock driving the epoch ring (tests pin expiry with a fake
#: clock; one module clock so every (op, bucket) window agrees on "now").
_clock = time.monotonic

#: Per-op breach timestamps inside the current SLO window (the
#: ``slo_breach_burst`` trigger state; pruned per observation, cleared
#: by :func:`set_clock`).
_breaches: dict = {}


def set_clock(clock=None) -> None:
    """Swap the window clock (tests); None restores ``time.monotonic``.
    Only windows created AFTER the swap use it — call before the first
    observation of the series under test. Clears the breach-burst
    stamps (they are meaningless across a clock swap)."""
    global _clock
    _clock = clock if clock is not None else time.monotonic
    _breaches.clear()


def _note_breach(op: str, cfg) -> None:
    """One over-objective observation: prune stamps older than the SLO
    window, and when the op's in-window breach count reaches
    ``slo_burst``, dump the flight ring (the recorder's per-reason
    cooldown dedups a storm into one artifact)."""
    burst = int(getattr(cfg, "slo_burst", 0) or 0)
    if burst <= 0:
        return
    window = max(float(cfg.slo_window_s), 1e-9)
    now = _clock()
    stamps = _breaches.setdefault(op, [])
    stamps.append(now)
    while stamps and now - stamps[0] > window:
        stamps.pop(0)
    if len(stamps) >= burst:
        from . import flight

        flight.trigger("slo_breach_burst", op=op, breaches=len(stamps),
                       window_s=window, burst=burst)


def observe(op: str, seconds: float, bucket: str = "") -> None:
    """Record one latency (module docstring). Callers gate on
    ``metrics_active()`` — this function assumes the registry is live."""
    from . import registry
    from ..config import get_configuration

    from .metrics import quantiles

    cfg = get_configuration()
    reg = registry()
    h = reg.histogram(LATENCY_HISTOGRAM, op=op, bucket=bucket)
    window = h.windowed(window_s=max(float(cfg.slo_window_s), 1e-9),
                        clock=_clock)
    h.observe(seconds)
    # one window copy + one sort for all three gauges (metrics.quantiles)
    vals = quantiles(window.samples(), [q for q, _ in QUANTILES])
    for (q, label), v in zip(QUANTILES, vals):
        reg.gauge(WINDOW_GAUGE, op=op, bucket=bucket, q=label).set(v)
    slo_ms = float(cfg.slo_p99_ms)
    if slo_ms > 0 and seconds * 1e3 > slo_ms:
        reg.counter(BREACH_COUNTER, op=op).inc()
        _note_breach(op, cfg)
