"""Flight recorder: the last N records, dumped on incident triggers
(ISSUE 13, docs/observability.md live operations).

A bounded in-memory ring of the most recent ``DLAF_FLIGHT_RECORDER``
JSONL records — ALL types, captured pre-serialization on the sink's
write path (after the ts/rank/trace stamps, before the file write, so
the ring survives a lost or rank-remote sink file). On a trigger event
the ring is dumped ATOMICALLY (temp file + ``os.replace``) as a
standalone JSONL artifact next to the main one
(``<metrics_path>.flight.jsonl``): one ``flight_trigger`` header record
naming the reason, then the ring verbatim — the moments BEFORE the
incident, exactly what a post-hoc artifact of a crashed process loses.

Trigger vocabulary (:data:`dlaf_tpu.obs.sinks.FLIGHT_REASONS` is the
schema owner) and their call sites:

* ``breaker_open`` — any circuit breaker transitions to open
  (health/circuit.py);
* ``overload_shed`` — the serve queue sheds at the admission bound
  (serve/queue.py);
* ``factorization_exhausted`` — robust recovery raises
  ``FactorizationError`` (health/recovery.py);
* ``accuracy_breach`` — an accuracy record lands with
  ``bound_ratio > 1`` or a non-finite estimate (obs/accuracy.py);
* ``healthz_failure`` — the live ``/healthz`` endpoint fails to build
  its payload (obs/exporter.py);
* ``slo_breach_burst`` — >= ``DLAF_SLO_BURST`` over-objective latencies
  inside one rolling SLO window for one op (obs/slo.py, ISSUE 14);
* ``autotune_exhausted`` — an accuracy probe breached the budget at the
  TOP rung of a precision ladder: no safer route exists
  (autotune/controller.py, ISSUE 15; docs/autotune.md);
* ``fleet_worker_down`` — the fleet router reaped a dead replica still
  holding unacknowledged tickets (fleet/router.py, ISSUE 18;
  docs/fleet.md) — the ring captures the routing decisions that led
  into the failover.

Per-reason cooldown (default 60 s, injectable clock): the FIRST shed of
a burst dumps; the next thousand do not re-dump the same ring. Dumps
from different reasons within the cooldown still land (a breaker opening
during a shed storm is new information) — each dump REPLACES the
artifact, so the file always holds the ring as of the latest trigger,
with ``dump_seq`` in the header recording how many triggers fired.
A clean run writes nothing: the artifact's very existence is the
incident signal CI's must-not-trip leg asserts on.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

from ._state import STATE


class FlightRecorder:
    """The ring + dump machinery (module docstring). ``capacity`` is the
    ring depth (the knob value); ``path`` overrides the default
    ``<sink path>.flight.jsonl`` dump target (resolved lazily at dump
    time so a ``%r`` metrics template that the sink expands late still
    lands next to the real artifact)."""

    __slots__ = ("capacity", "cooldown_s", "clock", "dump_seq", "_path",
                 "_ring", "_lock", "_last_dump")

    def __init__(self, capacity: int, path: Optional[str] = None,
                 cooldown_s: float = 60.0, clock=time.monotonic):
        if capacity < 1:
            raise ValueError(f"FlightRecorder: capacity must be >= 1, "
                             f"got {capacity}")
        self.capacity = int(capacity)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.dump_seq = 0
        self._path = path
        self._ring = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._last_dump: dict = {}       # reason -> clock() of last dump

    def capture(self, record: dict) -> None:
        """Append one (already-stamped) record to the ring."""
        with self._lock:
            self._ring.append(record)

    def path(self) -> Optional[str]:
        """The dump target: the explicit path, else the live sink's
        resolved path + ``.flight.jsonl`` (None when neither exists —
        nowhere to dump)."""
        if self._path:
            return self._path
        sink = STATE.sink
        return f"{sink.path}.flight.jsonl" if sink is not None else None

    def trigger(self, reason: str, **attrs) -> Optional[str]:
        """Dump the ring for ``reason`` unless the same reason dumped
        within the cooldown; returns the artifact path when a dump
        happened (None: cooled down, or no dump target)."""
        path = self.path()
        if path is None:
            return None
        with self._lock:
            now = self.clock()
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last_dump[reason] = now
            self.dump_seq += 1
            header = {"v": 1, "type": "flight_trigger", "ts": time.time(),
                      "reason": reason, "dump_seq": self.dump_seq,
                      "records": len(self._ring),
                      "attrs": {k: v for k, v in attrs.items()}}
            records = list(self._ring)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for r in records:
                f.write(json.dumps(r, default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())
        # manifest-at-once discipline (matrix/checkpoint.py's): the
        # artifact either exists complete or not at all — a kill mid-dump
        # must not leave a torn incident record
        os.replace(tmp, path)
        return path


def trigger(reason: str, **attrs) -> Optional[str]:
    """Module-level trigger hook for the incident sites: no-op (None)
    when the recorder is unarmed (``DLAF_FLIGHT_RECORDER`` unset) —
    callers pay one attribute read. Never raises: a failing dump must
    not convert an incident into a crash at the incident site."""
    rec = STATE.flight
    if rec is None:
        return None
    try:
        return rec.trigger(reason, **attrs)
    except Exception:
        from .logging import get_logger

        get_logger("obs.flight").error(
            f"flight-recorder dump failed for reason {reason!r}")
        return None
