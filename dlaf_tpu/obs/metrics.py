"""Metrics registry: counters, gauges, histograms + Prometheus exposition.

The reference delegates runtime counters to pika's performance counters
(SURVEY §5); the TPU rebuild wants the per-collective byte accounting that
arXiv:2112.09017 credits its ICI tuning wins to, so the registry is a
first-class subsystem here. Semantics:

* **Counter** — monotone accumulator (``inc``). Collective counts/bytes,
  tile-op counts.
* **Gauge** — last-write-wins scalar (``set``).
* **Histogram** — count/sum/min/max plus cumulative bucket counts over
  fixed upper bounds (powers of two by default, Prometheus ``le``
  convention). Span durations.

Handles are cheap objects bound to their registry slot: call sites fetch
them via :func:`Registry.counter` etc. (get-or-create keyed on
``(kind, name, labels)``). The module-level no-op twins (``NOOP_COUNTER``
...) are what :mod:`dlaf_tpu.obs` hands out when observability is off —
method calls on them do nothing and allocate nothing.
"""

from __future__ import annotations

import math
import threading
from typing import Optional

#: Default histogram upper bounds: powers of two from 1 us to ~17 min,
#: in seconds — span durations from tile ops to whole-pipeline runs.
DEFAULT_BUCKETS = tuple(2.0 ** e for e in range(-20, 11))


class Counter:
    __slots__ = ("name", "labels", "value", "lock")

    def __init__(self, name: str, labels: dict, lock=None):
        self.name = name
        self.labels = labels
        self.value = 0.0
        # the owning registry shares its lock so mutation excludes
        # snapshot(); spans run on arbitrary threads (trace.py keeps a
        # per-thread span stack) and bare ``+=`` would lose increments
        self.lock = lock or threading.Lock()

    def inc(self, n=1) -> None:
        with self.lock:
            self.value += n

    def snapshot(self) -> dict:
        # callers serialize via the registry lock (Registry.snapshot)
        return {"name": self.name, "kind": "counter", "labels": self.labels,
                "value": self.value}


class Gauge:
    __slots__ = ("name", "labels", "value", "lock")

    def __init__(self, name: str, labels: dict, lock=None):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self.lock = lock or threading.Lock()

    def set(self, v) -> None:
        v = float(v)
        with self.lock:
            self.value = v

    def snapshot(self) -> dict:
        return {"name": self.name, "kind": "gauge", "labels": self.labels,
                "value": self.value}


class Histogram:
    __slots__ = ("name", "labels", "bounds", "bucket_counts", "count",
                 "sum", "min", "max", "lock")

    def __init__(self, name: str, labels: dict, bounds=DEFAULT_BUCKETS,
                 lock=None):
        self.name = name
        self.labels = labels
        self.bounds = tuple(bounds)
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +inf overflow
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.lock = lock or threading.Lock()

    def observe(self, v) -> None:
        v = float(v)
        with self.lock:
            # count/sum/buckets move together, or a concurrent snapshot
            # breaks the Prometheus invariant bucket{le="+Inf"} == count
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self.bucket_counts[i] += 1
                    return
            self.bucket_counts[-1] += 1

    def cumulative_buckets(self):
        """Prometheus-convention cumulative ``[le, count]`` pairs, the
        final one ``["+Inf", count]``."""
        out, acc = [], 0
        for b, c in zip(self.bounds, self.bucket_counts):
            acc += c
            out.append([b, acc])
        out.append(["+Inf", acc + self.bucket_counts[-1]])
        return out

    def snapshot(self) -> dict:
        return {"name": self.name, "kind": "histogram", "labels": self.labels,
                "count": self.count, "sum": self.sum,
                "min": self.min if self.count else 0.0,
                "max": self.max if self.count else 0.0,
                "buckets": self.cumulative_buckets()}


class _NoopCounter:
    __slots__ = ()

    def inc(self, n=1) -> None:
        pass


class _NoopGauge:
    __slots__ = ()

    def set(self, v) -> None:
        pass


class _NoopHistogram:
    __slots__ = ()

    def observe(self, v) -> None:
        pass


#: Singletons the facade returns when observability is off: no state, no
#: per-call allocation (the acceptance criterion's no-op fast path).
NOOP_COUNTER = _NoopCounter()
NOOP_GAUGE = _NoopGauge()
NOOP_HISTOGRAM = _NoopHistogram()


def _labels_key(labels: dict):
    return tuple(sorted(labels.items()))


class Registry:
    """Get-or-create metric store keyed on ``(kind, name, labels)``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}

    def _get(self, kind, cls, name, labels, **kw):
        key = (kind, name, _labels_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    # metrics share the registry lock: snapshot() holds it,
                    # so no update can tear a histogram mid-serialization
                    m = cls(name, labels, lock=self._lock, **kw)
                    self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, bounds: Optional[tuple] = None,
                  **labels) -> Histogram:
        kw = {"bounds": bounds} if bounds is not None else {}
        return self._get("histogram", Histogram, name, labels, **kw)

    def snapshot(self) -> list:
        with self._lock:
            return [m.snapshot() for m in self._metrics.values()]

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    # text exposition 0.0.4 label escaping: backslash, double-quote, and
    # line feed (an unescaped newline would split the sample line)
    inner = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", "\\\\")
                         .replace('"', '\\"').replace("\n", "\\n"))
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_num(v) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def prometheus_text(snapshot: list) -> str:
    """Prometheus text exposition (format 0.0.4) of a registry snapshot
    (the list :func:`Registry.snapshot` returns)."""
    by_name: dict = {}
    for m in snapshot:
        by_name.setdefault((m["name"], m["kind"]), []).append(m)
    lines = []
    for (name, kind), entries in sorted(by_name.items()):
        lines.append(f"# TYPE {name} {kind}")
        # deterministic series order within a family: sorted by labels,
        # not by registry insertion order (two runs of the same program
        # must scrape identically — diffs in CI artifacts stay readable)
        entries = sorted(entries,
                         key=lambda m: sorted(m.get("labels", {}).items()))
        for m in entries:
            labels = m.get("labels", {})
            if kind == "histogram":
                for le, cnt in m["buckets"]:
                    lb = dict(labels)
                    lb["le"] = le if isinstance(le, str) else _prom_num(le)
                    lines.append(f"{name}_bucket{_prom_labels(lb)} {cnt}")
                lines.append(f"{name}_sum{_prom_labels(labels)} "
                             f"{_prom_num(m['sum'])}")
                lines.append(f"{name}_count{_prom_labels(labels)} "
                             f"{m['count']}")
            else:
                lines.append(f"{name}{_prom_labels(labels)} "
                             f"{_prom_num(m['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")
