"""Metrics registry: counters, gauges, histograms + Prometheus exposition.

The reference delegates runtime counters to pika's performance counters
(SURVEY §5); the TPU rebuild wants the per-collective byte accounting that
arXiv:2112.09017 credits its ICI tuning wins to, so the registry is a
first-class subsystem here. Semantics:

* **Counter** — monotone accumulator (``inc``). Collective counts/bytes,
  tile-op counts.
* **Gauge** — last-write-wins scalar (``set``).
* **Histogram** — count/sum/min/max plus cumulative bucket counts over
  fixed upper bounds (powers of two by default, Prometheus ``le``
  convention). Span durations.

Handles are cheap objects bound to their registry slot: call sites fetch
them via :func:`Registry.counter` etc. (get-or-create keyed on
``(kind, name, labels)``). The module-level no-op twins (``NOOP_COUNTER``
...) are what :mod:`dlaf_tpu.obs` hands out when observability is off —
method calls on them do nothing and allocate nothing.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

#: Default histogram upper bounds: powers of two from 1 us to ~17 min,
#: in seconds — span durations from tile ops to whole-pipeline runs.
DEFAULT_BUCKETS = tuple(2.0 ** e for e in range(-20, 11))


def _quantile_sorted(vals, q: float) -> float:
    """Linear-interpolated q-quantile of an ALREADY-SORTED non-empty
    list (:func:`quantile` has the contract; :func:`quantiles` shares
    the sort across several q)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile: q={q} must be in [0, 1]")
    pos = (len(vals) - 1) * float(q)
    lo = int(math.floor(pos))
    hi = int(math.ceil(pos))
    a, b = vals[lo], vals[hi]
    t = pos - lo
    # numpy's _lerp: the t >= 0.5 branch anchors on b so the two ends
    # are exact and the result is monotone — mirrored here so the
    # equality pin holds to the bit, not just approximately
    return b - (b - a) * (1.0 - t) if t >= 0.5 else a + (b - a) * t


def quantile(values, q: float) -> float:
    """The q-quantile (q in [0, 1]) of ``values`` with numpy's default
    linear interpolation — bit-identical to ``np.quantile(values, q)``
    on the same sample, which is the pin that lets bench.py's
    serve/overload arms and the rolling SLO window report THE SAME p99
    for the same latencies (ISSUE 13 satellite: one quantile
    implementation, not three hand-sorted ones). NaN for an empty
    sample."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return float("nan")
    return _quantile_sorted(vals, q)


def quantiles(values, qs) -> list:
    """Several quantiles of the same sample with ONE sort (the
    per-observation SLO gauge refresh asks for p50/p95/p99 together —
    three independent :func:`quantile` calls would sort the window
    three times). NaN-filled for an empty sample."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return [float("nan")] * len(qs)
    return [_quantile_sorted(vals, q) for q in qs]


class SlidingWindow:
    """Rolling-window sample store for latency quantiles (ISSUE 13):
    a ring of ``epochs`` fixed-capacity epoch buckets, each covering
    ``window_s / epochs`` seconds of the injectable ``clock``. A sample
    lands in the current epoch's bucket; an epoch older than the window
    is overwritten when its ring slot comes around again and excluded
    from :meth:`samples` meanwhile — memory is bounded at
    ``epochs * cap`` floats regardless of traffic, and behavior is a
    pure function of the (clock, observe) sequence, so tests drive it
    deterministically with a fake clock. Overflow beyond ``cap`` samples
    per epoch is dropped and counted (:attr:`dropped`) — visibly, never
    silently reweighted."""

    __slots__ = ("window_s", "epochs", "cap", "clock", "dropped",
                 "_epoch_len", "_ring", "_stamps", "_lock")

    def __init__(self, window_s: float = 60.0, epochs: int = 6,
                 cap: int = 256, clock=time.monotonic, lock=None):
        if not window_s > 0 or epochs < 1 or cap < 1:
            raise ValueError("SlidingWindow: window_s > 0, epochs >= 1, "
                             f"cap >= 1 required (got {window_s}, {epochs},"
                             f" {cap})")
        self.window_s = float(window_s)
        self.epochs = int(epochs)
        self.cap = int(cap)
        self.clock = clock
        self.dropped = 0
        self._epoch_len = self.window_s / self.epochs
        self._ring = [[] for _ in range(self.epochs)]
        self._stamps = [None] * self.epochs
        self._lock = lock or threading.Lock()

    def _epoch(self) -> int:
        return int(self.clock() // self._epoch_len)

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            e = self._epoch()
            slot = e % self.epochs
            if self._stamps[slot] != e:
                self._ring[slot] = []       # the slot's old epoch expired
                self._stamps[slot] = e
            if len(self._ring[slot]) < self.cap:
                self._ring[slot].append(v)
            else:
                self.dropped += 1

    def samples(self) -> list:
        """All samples still inside the window (live epochs only)."""
        with self._lock:
            e = self._epoch()
            out = []
            for slot in range(self.epochs):
                stamp = self._stamps[slot]
                if stamp is not None and 0 <= e - stamp < self.epochs:
                    out.extend(self._ring[slot])
            return out

    def count(self) -> int:
        return len(self.samples())

    def quantile(self, q: float) -> float:
        """Windowed q-quantile (numpy-linear, :func:`quantile`); NaN when
        the window is empty."""
        return quantile(self.samples(), q)


class Counter:
    __slots__ = ("name", "labels", "value", "lock")

    def __init__(self, name: str, labels: dict, lock=None):
        self.name = name
        self.labels = labels
        self.value = 0.0
        # the owning registry shares its lock so mutation excludes
        # snapshot(); spans run on arbitrary threads (trace.py keeps a
        # per-thread span stack) and bare ``+=`` would lose increments
        self.lock = lock or threading.Lock()

    def inc(self, n=1) -> None:
        with self.lock:
            self.value += n

    def snapshot(self) -> dict:
        # callers serialize via the registry lock (Registry.snapshot)
        return {"name": self.name, "kind": "counter", "labels": self.labels,
                "value": self.value}


class Gauge:
    __slots__ = ("name", "labels", "value", "lock")

    def __init__(self, name: str, labels: dict, lock=None):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self.lock = lock or threading.Lock()

    def set(self, v) -> None:
        v = float(v)
        with self.lock:
            self.value = v

    def snapshot(self) -> dict:
        return {"name": self.name, "kind": "gauge", "labels": self.labels,
                "value": self.value}


class Histogram:
    __slots__ = ("name", "labels", "bounds", "bucket_counts", "count",
                 "sum", "min", "max", "lock", "window", "exemplars")

    def __init__(self, name: str, labels: dict, bounds=DEFAULT_BUCKETS,
                 lock=None):
        self.name = name
        self.labels = labels
        self.bounds = tuple(bounds)
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +inf overflow
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.lock = lock or threading.Lock()
        self.window = None       # optional SlidingWindow (windowed())
        self.exemplars = {}      # bucket index -> [trace_id, value]

    def windowed(self, window_s: Optional[float] = None,
                 epochs: int = 6, cap: int = 256,
                 clock=time.monotonic) -> SlidingWindow:
        """The histogram's attached rolling-window quantile estimator
        (created on first call; later calls return the SAME window and
        ignore the sizing arguments — one window per series). Every
        subsequent :meth:`observe` feeds it alongside the cumulative
        buckets; the window has its OWN lock (it is also read from
        scrape threads) and bounded memory (class docstring)."""
        with self.lock:
            if self.window is None:
                self.window = SlidingWindow(
                    window_s if window_s is not None else 60.0,
                    epochs=epochs, cap=cap, clock=clock)
            return self.window

    def observe(self, v) -> None:
        v = float(v)
        # exemplar: attribute this observation to the active REQUEST
        # trace when there is exactly one (batch-scope contexts carry a
        # list and are never exemplars) — resolved before taking the
        # lock, one ContextVar read when no context is live
        from .context import single_trace_id

        tid = single_trace_id()
        with self.lock:
            # count/sum/buckets move together, or a concurrent snapshot
            # breaks the Prometheus invariant bucket{le="+Inf"} == count
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            slot = len(self.bounds)
            for i, b in enumerate(self.bounds):
                if v <= b:
                    slot = i
                    break
            self.bucket_counts[slot] += 1
            if tid is not None:
                self.exemplars[slot] = [tid, v]
        if self.window is not None:
            # outside the registry lock: the window owns its own lock
            # (a shared non-reentrant lock would deadlock here)
            self.window.observe(v)

    def cumulative_buckets(self):
        """Prometheus-convention cumulative ``[le, count]`` pairs, the
        final one ``["+Inf", count]``."""
        out, acc = [], 0
        for b, c in zip(self.bounds, self.bucket_counts):
            acc += c
            out.append([b, acc])
        out.append(["+Inf", acc + self.bucket_counts[-1]])
        return out

    def snapshot(self) -> dict:
        snap = {"name": self.name, "kind": "histogram",
                "labels": self.labels,
                "count": self.count, "sum": self.sum,
                "min": self.min if self.count else 0.0,
                "max": self.max if self.count else 0.0,
                "buckets": self.cumulative_buckets()}
        if self.exemplars:
            # keyed by bucket INDEX (matching the cumulative list's
            # positions, +Inf last) so exposition can attach each
            # exemplar to its bucket line
            snap["exemplars"] = {i: list(ex)
                                 for i, ex in self.exemplars.items()}
        return snap


class _NoopCounter:
    __slots__ = ()

    def inc(self, n=1) -> None:
        pass


class _NoopGauge:
    __slots__ = ()

    def set(self, v) -> None:
        pass


class _NoopWindow:
    __slots__ = ()

    def observe(self, v) -> None:
        pass

    def samples(self) -> list:
        return []

    def count(self) -> int:
        return 0

    def quantile(self, q) -> float:
        return float("nan")


class _NoopHistogram:
    __slots__ = ()

    def observe(self, v) -> None:
        pass

    def windowed(self, *args, **kwargs):
        return NOOP_WINDOW


#: Singletons the facade returns when observability is off: no state, no
#: per-call allocation (the acceptance criterion's no-op fast path).
NOOP_COUNTER = _NoopCounter()
NOOP_GAUGE = _NoopGauge()
NOOP_HISTOGRAM = _NoopHistogram()
NOOP_WINDOW = _NoopWindow()


def _labels_key(labels: dict):
    return tuple(sorted(labels.items()))


class Registry:
    """Get-or-create metric store keyed on ``(kind, name, labels)``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}

    def _get(self, kind, cls, name, labels, **kw):
        key = (kind, name, _labels_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    # metrics share the registry lock: snapshot() holds it,
                    # so no update can tear a histogram mid-serialization
                    m = cls(name, labels, lock=self._lock, **kw)
                    self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, bounds: Optional[tuple] = None,
                  **labels) -> Histogram:
        kw = {"bounds": bounds} if bounds is not None else {}
        return self._get("histogram", Histogram, name, labels, **kw)

    def snapshot(self) -> list:
        with self._lock:
            return [m.snapshot() for m in self._metrics.values()]

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    # text exposition 0.0.4 label escaping: backslash, double-quote, and
    # line feed (an unescaped newline would split the sample line)
    inner = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", "\\\\")
                         .replace('"', '\\"').replace("\n", "\\n"))
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_num(v) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def prometheus_text(snapshot: list, exemplars: bool = False) -> str:
    """Prometheus text exposition (format 0.0.4) of a registry snapshot
    (the list :func:`Registry.snapshot` returns).

    ``exemplars=True`` additionally appends OpenMetrics-style exemplars
    to histogram bucket lines that carry one —
    ``name_bucket{le="0.25"} 7 # {trace_id="3f2a..."} 0.21`` — joining a
    latency bucket to ONE request's trace ID (docs/observability.md live
    operations). Off by default: the classic 0.0.4 grammar has no
    exemplar clause, so artifacts and the ``--prom`` CLI stay exactly as
    before; the live ``/metrics`` endpoint opts in."""
    by_name: dict = {}
    for m in snapshot:
        by_name.setdefault((m["name"], m["kind"]), []).append(m)
    lines = []
    for (name, kind), entries in sorted(by_name.items()):
        lines.append(f"# TYPE {name} {kind}")
        # deterministic series order within a family: sorted by labels,
        # not by registry insertion order (two runs of the same program
        # must scrape identically — diffs in CI artifacts stay readable)
        entries = sorted(entries,
                         key=lambda m: sorted(m.get("labels", {}).items()))
        for m in entries:
            labels = m.get("labels", {})
            if kind == "histogram":
                ex = m.get("exemplars") or {} if exemplars else {}
                for i, (le, cnt) in enumerate(m["buckets"]):
                    lb = dict(labels)
                    lb["le"] = le if isinstance(le, str) else _prom_num(le)
                    line = f"{name}_bucket{_prom_labels(lb)} {cnt}"
                    hit = ex.get(i, ex.get(str(i)))
                    if hit:
                        tid, v = hit
                        line += (' # {trace_id="%s"} %s'
                                 % (tid, _prom_num(float(v))))
                    lines.append(line)
                lines.append(f"{name}_sum{_prom_labels(labels)} "
                             f"{_prom_num(m['sum'])}")
                lines.append(f"{name}_count{_prom_labels(labels)} "
                             f"{m['count']}")
            else:
                lines.append(f"{name}{_prom_labels(labels)} "
                             f"{_prom_num(m['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")
