"""Span-based tracer: nested host-side spans + device-timeline names.

A span measures host wall clock around a region (an algorithm entry, a
pipeline stage, a timed miniapp run) and, when active, also enters a
``jax.profiler.TraceAnnotation`` so profiler timelines carry the same
names. Builders that run at *trace time* (the unrolled per-``k`` loops)
use :func:`named_span` instead — a ``jax.named_scope`` whose cost is paid
once at trace time and whose names land in the compiled program's op
metadata (the device timeline), never in the runtime hot path.

Nesting is tracked per-thread; each emitted span record carries its
``depth`` and ``parent`` so ``scripts/profile_summary.py`` can rebuild the
call tree from the flat JSONL. Spans given ``flops`` derive GFlop/s at
exit — the per-step records BENCH rounds previously reverse-engineered
from stdout.

When observability is off, :func:`span`/:func:`named_span` return
module-level no-op singletons: zero per-call allocation (ISSUE 1
acceptance criterion).
"""

from __future__ import annotations

import threading
import time

from ._state import STATE


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, key, value) -> None:
        pass


#: Singletons for the disabled fast path. NOOP_CTX doubles as the
#: trace-time named_span no-op.
NOOP_SPAN = _NoopSpan()
NOOP_CTX = NOOP_SPAN

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class Span:
    """Reentrant context manager: one Span object per region entry (the
    same name may be nested or repeated freely)."""

    __slots__ = ("name", "attrs", "flops", "fenced", "t0", "dur_s", "depth",
                 "parent", "_ann")

    def __init__(self, name: str, flops=None, fenced=True, **attrs):
        self.name = name
        self.attrs = attrs
        self.flops = flops
        self.fenced = fenced
        self.t0 = None
        self.dur_s = None
        self._ann = None

    def set_attr(self, key, value) -> None:
        """Attach/override an attribute after entry (e.g. a route resolved
        mid-region)."""
        self.attrs[key] = value

    def __enter__(self):
        st = _stack()
        self.depth = len(st)
        self.parent = st[-1].name if st else None
        st.append(self)
        if STATE.annotate:
            _maybe_start_profiler()
            import jax

            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.dur_s = time.perf_counter() - self.t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:       # exotic exit order; keep the stack sane
            st.remove(self)
        self._emit()
        return False

    def _emit(self) -> None:
        if STATE.registry is not None:
            STATE.registry.histogram("dlaf_span_seconds",
                                     span=self.name).observe(self.dur_s)
        if STATE.sink is None:
            return
        rec = {"type": "span", "name": self.name, "dur_s": self.dur_s,
               "depth": self.depth, "parent": self.parent,
               "attrs": self.attrs}
        if not self.fenced:
            rec["fenced"] = False
        if self.flops is not None:
            rec["flops"] = float(self.flops)
            # derive GFlop/s only when the region's wall is honest work
            # (fenced): an unfenced span around async JAX dispatch would
            # report dispatch time as throughput — numbers past hardware
            # peak that then outrank the real ones in summaries
            if self.fenced and self.dur_s > 0:
                rec["gflops"] = float(self.flops) / self.dur_s / 1e9
        STATE.sink.write(rec)


def span(name: str, flops=None, fenced=True, **attrs):
    """A host-side span, or the no-op singleton when observability is off.

    ``flops``: flop count of the region — the emitted record then carries
    derived ``gflops`` (only when ``fenced``; callers whose region does not
    block on device completion pass ``fenced=False`` so the record keeps
    the flop model but never a dispatch-time throughput). Other keyword
    arguments become the span's attrs.
    """
    if not (STATE.metrics_on or STATE.annotate):
        return NOOP_SPAN
    return Span(name, flops=flops, fenced=fenced, **attrs)


def entry_span(name: str, attrs_fn):
    """Algorithm-entry span: unfenced (the library dispatches async work;
    device completion is the caller's fence, so no derived gflops), with
    lazily built attrs — ``attrs_fn`` is a zero-argument callable returning
    the attr dict (``flops`` allowed as a key) that is never invoked when
    observability is off, keeping flop models and attr strings off the
    disabled path (the cost contract)."""
    if not (STATE.metrics_on or STATE.annotate):
        return NOOP_SPAN
    kw = dict(attrs_fn())
    return Span(name, flops=kw.pop("flops", None), fenced=False, **kw)


def named_span(name: str):
    """Trace-time phase name for code inside ``jit``/``shard_map``: a
    ``jax.named_scope`` (op-metadata names on the device timeline, zero
    runtime cost) when observability is on; the no-op singleton otherwise.
    """
    if not (STATE.metrics_on or STATE.annotate):
        return NOOP_CTX
    import jax

    return jax.named_scope(name)


def scoped_step(name: str, fn):
    """Wrap a ``lax.scan`` step body so every op it traces carries the
    ``name`` scope. A scan body is traced ONCE for all iterations, so the
    scope can carry no step index — the critpath joiner reconstructs the
    per-iteration timeline from occurrence order instead (one execution
    of the body's instruction set per iteration; the one-traced-body
    limitation, docs/observability.md). Zero-cost pass-through when
    observability is off (``named_span`` returns the no-op singleton)."""
    if not (STATE.metrics_on or STATE.annotate):
        return fn

    def wrapped(*args):
        with named_span(name):
            return fn(*args)

    return wrapped


def current_span():
    """Innermost live Span of this thread, or None (attrs can be attached
    to it from helper layers without plumbing the object through)."""
    st = _stack()
    return st[-1] if st else None


def start_profiler(path: str) -> bool:
    """Start THE process-wide ``jax.profiler`` trace at ``path`` unless
    some owner (an obs span via ``DLAF_TRACE_DIR``, or a
    ``PhaseTimer(profile_dir=...)``) already claimed it; returns whether
    this call started it. The single ``STATE.profiler_started`` flag is
    the ownership protocol — every start/stop goes through here and
    :func:`stop_profiler` so two owners can never double-start the one
    trace jax allows per process.

    The python-call tracer is disabled (``python_tracer_level=0``): it
    floods the trace with ~1M ``$builtins isinstance``-grade events per
    unrolled build, and the Chrome-trace converter CAPS total events at
    ~1e6 — on a large traced run the flood evicts the XLA thunk events
    that device-time attribution (ISSUE 14, :mod:`dlaf_tpu.obs.
    devtrace`) exists to read. Host TraceMe events (our
    ``TraceAnnotation`` span mirrors) and the device op events are host-
    tracer products and survive. jax 0.4.x's public ``start_trace``
    exposes no options, so the option is injected by wrapping the
    ``ProfilerSession`` constructor the public call builds its session
    with — ``start_trace`` itself still runs (its single-trace lock,
    its backend-before-tracer ordering, and the tests' mock seam all
    stay jax's), and any layout mismatch degrades to an unwrapped call
    (a flooded-but-working trace beats no trace)."""
    if STATE.profiler_started:
        return False
    import contextlib

    import jax

    @contextlib.contextmanager
    def _quiet_python_tracer():
        try:
            from jax._src.lib import xla_client

            prof_mod = xla_client.profiler
            opts = prof_mod.ProfileOptions()
            opts.python_tracer_level = 0
            orig = prof_mod.ProfilerSession

            def session(*a, **k):
                return orig(opts) if not (a or k) else orig(*a, **k)

            prof_mod.ProfilerSession = session
        except Exception:
            yield
            return
        try:
            yield
        finally:
            prof_mod.ProfilerSession = orig

    # perfetto trace alongside the xplane: a gzipped JSON this container
    # can post-process WITHOUT tensorboard (scripts/profile_summary.py)
    with _quiet_python_tracer():
        jax.profiler.start_trace(path, create_perfetto_trace=True)
    STATE.profiler_started = True
    return True


def _maybe_start_profiler() -> None:
    """Start the process trace when a trace dir is configured (the
    green-field hook SURVEY §5 calls for); stopped by
    :func:`stop_profiler` (atexit-registered by configure)."""
    if STATE.trace_dir:
        start_profiler(STATE.trace_dir)


def stop_profiler() -> None:
    if STATE.profiler_started:
        import jax

        jax.profiler.stop_trace()
        STATE.profiler_started = False
        # the process trace is over: retire the trace config too, or the
        # next span in a long-lived process (pytest, a library caller)
        # silently starts a NEW trace into the same — possibly dead —
        # directory and keeps it open until interpreter exit. A fresh
        # configure(trace_dir=...) re-arms tracing explicitly.
        STATE.trace_dir = ""
        STATE.annotate = False
