"""Program telemetry: compile walls, retrace counts, HBM footprints.

The ``DLAF_PROGRAM_TELEMETRY`` knob (``Configuration.program_telemetry``,
layered like every other config field) arms a small AOT/jit
instrumentation layer that the algorithm entry points and the library's
cached-program sites route through. Three signals, per ``site`` label:

* ``dlaf_compile_seconds{site}`` — histogram of XLA compile wall per
  compiled program (trace wall recorded separately on the ``program``
  record). Today these numbers are buried in one-off probe scripts
  (``scripts/tpu_mem_probe.py`` / ``scripts/compile_scaling.py``); the
  library now owns the plumbing and the scripts call it.
* ``dlaf_retrace_total{site}`` — counter of traces (first trace = 1; a
  higher count is a retrace). This finally makes the documented
  "trace-time comm counters add again on retrace" caveat *detectable*:
  the collective byte counters are per-program models, and
  ``dlaf_retrace_total`` says how many programs contributed.
* ``dlaf_hbm_bytes{what=args|output|temp|peak,site}`` — gauges from
  ``compiled.memory_analysis()`` (the allocator's own accounting; the
  OOM-vs-fit oracle of the round-4 probe sessions).

Each compile additionally emits a ``program`` JSONL record (schema:
:mod:`dlaf_tpu.obs.sinks`) carrying the same numbers, so artifacts keep
per-program detail that gauges (last-write-wins) cannot.

Two call styles:

* :func:`call` — ambient instrumentation for library call sites:
  ``telemetry.call(site, jitted, *args, **static_kwargs)``. Off (the
  default), it is a pure passthrough to ``jitted(*args, **kwargs)`` —
  same callable, same program caches, bitwise no-op. On, the site runs
  through a keyed AOT ``lower()``/``compile()`` with the walls and the
  memory analysis recorded once per distinct program (keyed on the
  jitted callable + input avals/shardings + static kwargs; invalidated
  with the config program caches).
* :func:`aot_compile` — the explicit probe API: always measures,
  records only when the knob is on. ``scripts/tpu_mem_probe.py`` and
  ``scripts/compile_scaling.py`` are thin CLIs over this.

Builders whose traced bodies the library re-enters per group (e.g. the
level-batched D&C secular dispatch) instead call :func:`count_retrace`
from *inside* the traced body — a trace-time increment, zero runtime
cost, exactly the comm-counter discipline.
"""

from __future__ import annotations

import time
from typing import Any, NamedTuple, Optional

from ._state import STATE

#: ``memory_analysis()`` attribute -> gauge label. ``peak`` is derived:
#: args + output + temp - alias (the est_live the probe scripts printed).
_MEMORY_FIELDS = {
    "argument_size_in_bytes": "args",
    "output_size_in_bytes": "output",
    "temp_size_in_bytes": "temp",
    "alias_size_in_bytes": "alias",
    "generated_code_size_in_bytes": "code",
}

#: AOT program cache for :func:`call`: (site, id(fn), arg key) ->
#: (fn, compiled). fn is held strongly so id() cannot be recycled under a
#: live key. Cleared with the config program caches (knob changes rebuild
#: the underlying jitted callables, and these executables with them) and
#: LRU-bounded at :data:`MAX_PROGRAMS`: the underlying builder lru_caches
#: are bounded (32-64), and without a bound here every builder eviction
#: would pin its dead jitted callable + XLA executable forever in a
#: long-lived telemetry-on process.
_PROGRAMS: dict = {}

MAX_PROGRAMS = 256

_registered = False


class _CacheHandle:
    """config.register_program_cache adapter for the AOT program cache."""

    @staticmethod
    def cache_clear() -> None:
        _PROGRAMS.clear()


def _ensure_registered() -> None:
    global _registered
    if not _registered:
        _registered = True
        from ..config import register_program_cache

        register_program_cache(_CacheHandle)


def active() -> bool:
    """Fast-path gate (one attribute read) for instrumented sites."""
    return STATE.telemetry_on


def _registry():
    if STATE.registry is None:
        from .metrics import Registry

        STATE.registry = Registry()
    return STATE.registry


def count_retrace(site: str) -> None:
    """One trace of ``site``'s program happened (callable from inside a
    traced body — the increment runs at trace time, like the comm byte
    counters). No-op when the knob is off."""
    if not STATE.telemetry_on:
        return
    _registry().counter("dlaf_retrace_total", site=site).inc()
    if STATE.sink is not None:
        STATE.sink.write({"type": "program", "site": site,
                          "event": "retrace", "attrs": {}})


class AotProgram(NamedTuple):
    """Result of :func:`aot_compile`: the compiled executable plus the
    measured walls and the memory analysis (None where the backend
    offers none)."""

    compiled: Any
    trace_s: float
    compile_s: float
    memory: Optional[dict]


def memory_analysis_dict(compiled) -> Optional[dict]:
    """``compiled.memory_analysis()`` as a plain dict of byte counts
    (``args``/``output``/``temp``/``alias``/``code`` + derived ``peak``),
    or None when the backend provides no analysis."""
    try:
        m = compiled.memory_analysis()
    except Exception:
        return None
    if m is None:
        return None
    out = {}
    for field, label in _MEMORY_FIELDS.items():
        v = getattr(m, field, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[label] = float(v)
    if not out:
        return None
    out["peak"] = (out.get("args", 0.0) + out.get("output", 0.0)
                   + out.get("temp", 0.0) - out.get("alias", 0.0))
    return out


def record_compile(site: str, *, compile_s: float,
                   trace_s: Optional[float] = None,
                   memory: Optional[dict] = None, **attrs) -> None:
    """Record one compiled program: compile-seconds histogram, HBM
    gauges, and a ``program`` JSONL record. No-op when the knob is off
    (the explicit probe API measures regardless and only *records*
    through here)."""
    if not STATE.telemetry_on:
        return
    reg = _registry()
    reg.histogram("dlaf_compile_seconds", site=site).observe(compile_s)
    if memory:
        for what in ("args", "output", "temp", "peak"):
            if what in memory:
                reg.gauge("dlaf_hbm_bytes", what=what,
                          site=site).set(memory[what])
    if STATE.sink is not None:
        rec = {"type": "program", "site": site, "event": "compile",
               "compile_s": float(compile_s), "attrs": dict(attrs)}
        if trace_s is not None:
            rec["trace_s"] = float(trace_s)
        if memory:
            rec["hbm"] = {k: float(v) for k, v in memory.items()}
        STATE.sink.write(rec)


def aot_compile(site: str, jitted, *args, **kwargs) -> AotProgram:
    """Timed ``jitted.lower(*args, **kwargs).compile()`` + memory
    analysis — THE plumbing the probe scripts used to hand-roll. Always
    measures (it is an explicit call); feeds the registry/artifact only
    when the knob is on. ``args`` may be concrete arrays or
    ``jax.ShapeDtypeStruct`` specs (no execution happens here)."""
    t0 = time.perf_counter()
    lowered = jitted.lower(*args, **kwargs)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    memory = memory_analysis_dict(compiled)
    count_retrace(site)
    record_compile(site, compile_s=t2 - t1, trace_s=t1 - t0, memory=memory)
    record_schedule(site, compiled)
    return AotProgram(compiled, t1 - t0, t2 - t1, memory)


def record_schedule(site: str, compiled) -> None:
    """Record the per-step HLO schedule of a compiled program so the
    critpath joiner (``obs.critpath``) can attribute device intervals to
    ``<algo>.step<k>.<phase>`` scopes offline.  Emits one ``schedule``
    record per program carrying step scopes; silent no-op when the sink
    is off, the program has no step scopes, or the backend refuses to
    render optimized HLO text."""
    if not (STATE.telemetry_on and STATE.sink is not None):
        return
    try:
        hlo_text = compiled.as_text()
    except Exception:  # backend without text rendering — never fail the compile
        return
    from . import critpath

    rec = critpath.schedule_record(site, hlo_text)
    if rec is not None:
        STATE.sink.write(rec)


def _arg_key(x):
    # arrays key on their program-relevant identity (aval + sharding —
    # two layouts of one shape are different programs); everything else
    # is a static and keys on its value
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("aval", tuple(x.shape), str(x.dtype),
                getattr(x, "sharding", None))
    return x


def call(site: str, fn, *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` with program telemetry.

    Knob off: ``fn(*args, **kwargs)`` — the identical jitted callable,
    its own caches, bitwise no-op (the instrumented sites cost one
    attribute read). Knob on: the call is served by an AOT-compiled
    executable keyed on (site, fn, input avals/shardings, static
    kwargs); the first call per key records the trace/compile walls, a
    retrace count, and the HBM gauges. ``kwargs`` must be the jitted
    callable's *static* keyword arguments (they are baked into the
    compiled program); dynamic operands go positionally.
    """
    if not STATE.telemetry_on:
        return fn(*args, **kwargs)
    lower = getattr(fn, "lower", None)
    if lower is None:
        return fn(*args, **kwargs)    # not a jitted callable; nothing to AOT
    try:
        key = (site, id(fn), tuple(_arg_key(a) for a in args),
               tuple(sorted(kwargs.items())))
        hash(key)
    except TypeError:
        return fn(*args, **kwargs)    # unhashable statics; stay uninstrumented
    _ensure_registered()
    entry = _PROGRAMS.get(key)
    if entry is None:
        prog = aot_compile(site, fn, *args, **kwargs)
        while len(_PROGRAMS) >= MAX_PROGRAMS:
            _PROGRAMS.pop(next(iter(_PROGRAMS)))     # LRU: oldest first
        _PROGRAMS[key] = entry = (fn, prog.compiled)
    else:
        # keep insertion order ≈ recency so the bound evicts cold programs
        _PROGRAMS[key] = _PROGRAMS.pop(key)
    return entry[1](*args)


def _reset_for_tests() -> None:
    _PROGRAMS.clear()
