"""In-graph numerical-quality probes (the ``DLAF_ACCURACY`` knob).

The accuracy half of the observability stack (docs/accuracy.md; the perf
half is :mod:`dlaf_tpu.obs.telemetry`): jit-compiled, distributed-aware
estimators of the backward-error quantities the miniapp ``--check-result``
checks used to recompute on the host with O(n^3) numpy gemms —

* Cholesky relative residual ``|A - L L^H|_F / |A|_F`` (and the ``U^H U``
  form),
* triangular-solve residual ``|op(T) X - alpha B|_F / |B|_F``,
* HEGST (gen_to_std) residual ``|L C L^H - A|_F / |A|_F``,
* eigensolver quality: the Frobenius eigenpair residual
  ``|A Z - Z diag(lam)|_F / |A|_F`` (generalized: ``|A Z - B Z
  diag(lam)|_F``), the sampled per-pair maximum
  ``max_i |A z_i - lam_i z_i|_2 / |A|_F``, and the orthogonality defect
  ``|Z^H Z - I|_F``,
* the D&C merge tree's per-level deflation fraction (emitted by
  :mod:`dlaf_tpu.eigensolver.tridiag_solver`).

Estimator modes (the knob; ``Configuration.accuracy``):

* ``"1"`` — stochastic Hutchinson probe: for the residual matrix ``R``,
  ``|R Omega|_F / sqrt(k)`` with ``k`` seeded Rademacher columns is an
  unbiased estimate of ``|R|_F`` (``E |R w|_2^2 = |R|_F^2`` for unit-
  variance iid ``w``; relative std of the squared estimate is
  ``<= sqrt(2/k)``). Cost is O(n^2 k) device matvecs — NO full-matrix
  host fetch, no O(n^3) recompute.
* ``"full"`` — the exact Frobenius residual, computed as the same probe
  with ``Omega = I`` (``|R I|_F == |R|_F`` exactly): O(n^3) device work,
  still no host round trip.
* ``"0"`` — nothing is emitted during timed runs; an explicit check call
  still computes, using the ``"1"`` probe. The knob is a bitwise
  passthrough for the factor outputs either way: every estimator here is
  a separate program over the algorithm outputs, never fused into the
  factorization (pinned by tests/test_accuracy.py).

Distributed matrices are probed distributed: each rank contracts its own
block-cyclic tiles against the (replicated, trace-time-constant) probe
columns and partial products meet in ``comm.collectives.all_reduce`` over
both mesh axes — O(n k) ICI traffic, counted in the collective byte
counters like any other collective. The cross-rank reduction reassociates
the partial sums, so a distributed estimate matches the single-chip value
of the same factor to rounding (~ulps), not bitwise — the one documented
exception to the layer's bitwise contracts (docs/accuracy.md).

:func:`emit` is the one record shape: every estimate lands as an
``accuracy`` JSONL record (site, metric, value, ``bound_ratio =
value / (c * n * eps_eff)`` with the platform-honest
:func:`dlaf_tpu.miniapp.checks.effective_eps`, n, nb, dtype, platform,
knob attrs; rank stamped by the sink) plus a
``dlaf_accuracy_ratio{site,metric}`` gauge —
``python -m dlaf_tpu.obs.validate --require-accuracy`` and
``scripts/accuracy_gate.py`` consume them.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional

import numpy as np

from ..config import register_program_cache

#: Probe columns of the stochastic ("1") mode. k=8 bounds the relative
#: std of the squared-norm estimate by sqrt(2/8) = 50%; with the fixed
#: seed the estimate is deterministic, and tests pin it within a factor
#: of 4 of the exact residual (comfortably inside 4 sigma).
DEFAULT_PROBES = 8
#: Seed of the Rademacher probe columns (and the eigenpair column
#: sample). Fixed: estimates must be reproducible run-to-run so the
#: accuracy gate compares like with like.
PROBE_SEED = 20260804

def _tiny(x):
    """Smallest normal of ``x``'s (real) dtype — the zero-denominator
    guard must be representable in the computation dtype (a fixed
    1e-300 would round to 0.0f on the float32 path and let 0/0 NaN an
    uncorrupted all-zero reference)."""
    import jax.numpy as jnp

    return jnp.finfo(jnp.asarray(x).dtype).tiny


def resolved_mode(mode: Optional[str] = None) -> str:
    """The effective estimator mode: the argument if given, else the
    ``DLAF_ACCURACY`` knob — with ``"0"`` (telemetry off) resolving to
    the ``"1"`` probe for explicit check calls."""
    if mode is None:
        from ..config import get_configuration

        mode = get_configuration().accuracy
    return "1" if mode == "0" else mode


def enabled() -> bool:
    """True when timed runs should compute and emit accuracy records
    (``DLAF_ACCURACY`` != "0")."""
    from ..config import get_configuration

    return get_configuration().accuracy != "0"


def _probe_columns(n: int, mode: str, k: int, seed: int):
    """``(omega, scale)``: the (n, k) float64 Rademacher probe block and
    the ``1/sqrt(k)`` Hutchinson normalization — or ``(None, 1.0)``
    signaling the exact identity probe (mode "full")."""
    if mode == "full":
        return None, 1.0
    k = max(1, min(k, max(n, 1)))
    rng = np.random.default_rng(seed)
    om = (rng.integers(0, 2, size=(n, k)) * 2 - 1).astype(np.float64)
    return om, 1.0 / math.sqrt(k)


def _sample_columns(n: int, mode: str, k: int, seed: int) -> np.ndarray:
    """Seeded eigenpair column sample (mode "1") or every column
    (mode "full")."""
    if mode == "full" or k >= n:
        return np.arange(n)
    return np.sort(np.random.default_rng(seed + 1).choice(
        n, size=k, replace=False))


# ---------------------------------------------------------------------------
# Tile-level building blocks (used inside the shard_map bodies)
# ---------------------------------------------------------------------------

def _tile_coords(dist):
    """Per-rank global tile coordinates inside a shard_map body:
    ``(g_rows, g_cols)`` for this rank's (ltr, ltc, mb, nb) local tile
    view (the block-cyclic map of matrix/tiling.py)."""
    import jax.numpy as jnp

    from ..comm import collectives as cc
    from ..comm.grid import COL_AXIS, ROW_AXIS
    from ..matrix.tiling import storage_tile_grid

    Pr, Qc = dist.grid_size.row, dist.grid_size.col
    sr, sc = dist.source_rank.row, dist.source_rank.col
    _, _, ltr, ltc = storage_tile_grid(dist)
    rr = (cc.this_rank(ROW_AXIS) - sr) % Pr
    rc = (cc.this_rank(COL_AXIS) - sc) % Qc
    return jnp.arange(ltr) * Pr + rr, jnp.arange(ltc) * Qc + rc


def _masked(lt, dist, g_rows, g_cols, mask: str):
    """This rank's local tiles with everything outside ``mask`` zeroed:
    ``"G"`` whole matrix (pad tiles dropped), ``"L"``/``"U"`` the lower/
    upper triangle including the diagonal, ``"SL"``/``"SU"`` the strict
    triangles. Triangular masks require square tiles."""
    import jax.numpy as jnp

    nt = dist.nr_tiles
    mb, nb = dist.block_size.row, dist.block_size.col
    valid = (g_rows[:, None] < nt.row) & (g_cols[None, :] < nt.col)
    if mask == "G":
        m = valid[:, :, None, None]
    else:
        assert mb == nb, "triangular masks require square tiles"
        lower = mask in ("L", "SL")
        strict = mask in ("SL", "SU")
        if lower:
            keep_full = valid & (g_rows[:, None] > g_cols[None, :])
            tri = jnp.tril(jnp.ones((mb, nb), dtype=bool),
                           -1 if strict else 0)
        else:
            keep_full = valid & (g_rows[:, None] < g_cols[None, :])
            tri = jnp.triu(jnp.ones((mb, nb), dtype=bool),
                           1 if strict else 0)
        keep_diag = valid & (g_rows[:, None] == g_cols[None, :])
        m = keep_full[:, :, None, None] | (keep_diag[:, :, None, None] & tri)
    return jnp.where(m, lt, jnp.zeros((), lt.dtype))


def _fit_rows(x, rows: int):
    """Pad (with zero rows) or slice ``x`` to exactly ``rows`` rows."""
    import jax.numpy as jnp

    n = x.shape[0]
    if n == rows:
        return x
    if n > rows:
        return x[:rows]
    return jnp.pad(x, ((0, rows - n), (0, 0)))


def _psum2(x):
    """Sum over both mesh axes (byte-counted, injectable collectives)."""
    from ..comm import collectives as cc
    from ..comm.grid import COL_AXIS, ROW_AXIS

    return cc.all_reduce(cc.all_reduce(x, ROW_AXIS, "sum"), COL_AXIS, "sum")


def _mv(tiles, om, dist, g_rows, g_cols, op: str = "N"):
    """Replicated ``op(T) @ om`` from this rank's (masked) local tiles:
    the rank's partial product is scattered to its global row (col for
    the transposed ops) blocks and all-reduced over both mesh axes, so
    every rank returns the full product. ``om`` is a replicated (rows, k)
    value, padded/sliced to the storage extent internally; the result is
    sliced to the matrix's logical extent. ``op``: "N" (``T @ om``),
    "T" (``T^T @ om``), "C" (``T^H @ om``)."""
    import jax.numpy as jnp

    from ..matrix.tiling import storage_tile_grid

    mb, nb = dist.block_size.row, dist.block_size.col
    _, _, ltr, ltc = storage_tile_grid(dist)
    Gr, Gc = dist.grid_size.row * ltr, dist.grid_size.col * ltc
    om = om.astype(tiles.dtype)
    if op == "N":
        om_t = _fit_rows(om, Gc * nb).reshape(Gc, nb, -1)[g_cols]
        y = jnp.einsum("ijab,jbk->iak", tiles, om_t)
        part = jnp.zeros((Gr, mb, y.shape[-1]), y.dtype).at[g_rows].set(y)
        return _psum2(part.reshape(Gr * mb, -1))[: dist.size.row]
    om_t = _fit_rows(om, Gr * mb).reshape(Gr, mb, -1)[g_rows]
    t = jnp.conj(tiles) if op == "C" else tiles
    w = jnp.einsum("ijab,iak->jbk", t, om_t)
    part = jnp.zeros((Gc, nb, w.shape[-1]), w.dtype).at[g_cols].set(w)
    return _psum2(part.reshape(Gc * nb, -1))[: dist.size.col]


def _mv_herm(lt, om, dist, g_rows, g_cols, uplo: str):
    """Hermitian matvec from one stored triangle: ``A_h @ om`` with
    ``A_h = tri(A) + stri(A)^H`` (the miniapp checks' ``_hermfull``
    convention — the stored diagonal is used as-is)."""
    tri = _masked(lt, dist, g_rows, g_cols, uplo)
    strict = _masked(lt, dist, g_rows, g_cols, "S" + uplo)
    return (_mv(tri, om, dist, g_rows, g_cols, "N")
            + _mv(strict, om, dist, g_rows, g_cols, "C"))


def _sq(x):
    """Frobenius norm squared (real scalar, works for complex)."""
    import jax.numpy as jnp

    return jnp.sum(jnp.real(x * jnp.conj(x)))


def _herm_sq(lt, dist, g_rows, g_cols, uplo: str):
    """|A_h|_F^2 from one stored triangle (strict part counted twice —
    its conjugate mirror has the same magnitudes)."""
    return (_sq(_masked(lt, dist, g_rows, g_cols, uplo))
            + _sq(_masked(lt, dist, g_rows, g_cols, "S" + uplo)))


def _rel(num2, den2, scale: float):
    """``sqrt(num2) * scale / sqrt(den2)`` with an underflow guard."""
    import jax.numpy as jnp

    den = jnp.sqrt(den2)
    return jnp.sqrt(num2) * scale / jnp.maximum(den, _tiny(den))


def _shard_scalar(fn, mesh, n_in: int, extra_specs=()):
    """Wrap a shard_map body returning one replicated (s,)-vector of
    metric values as a jitted program: per-rank (1, 1, s) outputs over
    the mesh (the norm.py idiom); callers read ``[0, 0]``."""
    import jax

    from .._compat import shard_map
    from ..comm.grid import COL_AXIS, ROW_AXIS
    from jax.sharding import PartitionSpec as P

    def wrapped(*args):
        out = fn(*args)
        return out.reshape(1, 1, -1)

    spec = tuple([P(ROW_AXIS, COL_AXIS)] * n_in) + tuple(extra_specs)
    return jax.jit(shard_map(wrapped, mesh=mesh, in_specs=spec,
                             out_specs=P(ROW_AXIS, COL_AXIS),
                             check_vma=False))


# ---------------------------------------------------------------------------
# Cholesky: |A - L L^H|_F / |A|_F  (uplo U: |A - U^H U|_F / |A|_F)
# ---------------------------------------------------------------------------

@register_program_cache
@functools.lru_cache(maxsize=64)
def _local_cholesky_prog(dist, uplo: str, mode: str, k: int, seed: int):
    import jax
    import jax.numpy as jnp

    from ..matrix.tiling import tiles_to_global

    om_np, scale = _probe_columns(dist.size.row, mode, k, seed)

    def fn(a_st, f_st):
        a = tiles_to_global(a_st, dist)
        f = tiles_to_global(f_st, dist)
        t = jnp.tril(f) if uplo == "L" else jnp.triu(f)
        if om_np is None:
            z = t @ t.conj().T if uplo == "L" else t.conj().T @ t
            r = a - z
        else:
            om = jnp.asarray(om_np).astype(a.dtype)
            z = t @ (t.conj().T @ om) if uplo == "L" \
                else t.conj().T @ (t @ om)
            r = a @ om - z
        return _rel(_sq(r), _sq(a), scale)

    return jax.jit(fn)


@register_program_cache
@functools.lru_cache(maxsize=64)
def _dist_cholesky_prog(dist, mesh, uplo: str, mode: str, k: int, seed: int):
    import jax.numpy as jnp

    n = dist.size.row
    om_np, scale = _probe_columns(n, mode, k, seed)
    if om_np is None:
        om_np = np.eye(n)

    def local(lt_a, lt_f):
        g_rows, g_cols = _tile_coords(dist)
        a_t = _masked(lt_a, dist, g_rows, g_cols, "G")
        f_t = _masked(lt_f, dist, g_rows, g_cols, uplo)
        om = jnp.asarray(om_np).astype(lt_a.dtype)
        ya = _mv(a_t, om, dist, g_rows, g_cols, "N")
        if uplo == "L":
            w = _mv(f_t, om, dist, g_rows, g_cols, "C")
            z = _mv(f_t, w, dist, g_rows, g_cols, "N")
        else:
            w = _mv(f_t, om, dist, g_rows, g_cols, "N")
            z = _mv(f_t, w, dist, g_rows, g_cols, "C")
        den2 = _psum2(_sq(a_t))
        return _rel(_sq(ya - z), den2, scale)[None]

    return _shard_scalar(local, mesh, 2)


def cholesky_residual(uplo: str, a, factor, mode: Optional[str] = None) -> float:
    """Relative Cholesky residual of ``factor`` against the original
    ``a`` (both :class:`~dlaf_tpu.matrix.matrix.Matrix`, local or
    distributed): ``|A - L L^H|_F / |A|_F`` (or the ``U^H U`` form),
    estimated per the mode (module docstring)."""
    mode = resolved_mode(mode)
    if a.size.is_empty():
        return 0.0
    if a.grid is None or a.grid.num_devices == 1:
        prog = _local_cholesky_prog(a.dist, uplo, mode, DEFAULT_PROBES,
                                    PROBE_SEED)
        return float(prog(a.storage, factor.storage))
    prog = _dist_cholesky_prog(a.dist, a.grid.mesh, uplo, mode,
                               DEFAULT_PROBES, PROBE_SEED)
    return float(np.asarray(prog(a.storage, factor.storage))[0, 0, 0])


# ---------------------------------------------------------------------------
# Triangular solve: |op(T) X - alpha B|_F / |B|_F
# ---------------------------------------------------------------------------

@register_program_cache
@functools.lru_cache(maxsize=64)
def _local_trsm_prog(dist_a, dist_b, side, uplo, op, diag, alpha,
                     mode, k, seed):
    import jax
    import jax.numpy as jnp

    from ..matrix.tiling import tiles_to_global

    om_np, scale = _probe_columns(dist_b.size.col, mode, k, seed)

    def tri_op(t):
        t = jnp.tril(t) if uplo == "L" else jnp.triu(t)
        if diag == "U":
            eye = jnp.eye(t.shape[0], dtype=t.dtype)
            t = t - jnp.diag(jnp.diag(t)) + eye
        return {"N": t, "T": t.T, "C": t.conj().T}[op]

    def fn(a_st, b_st, x_st):
        t = tri_op(tiles_to_global(a_st, dist_a))
        b = tiles_to_global(b_st, dist_b)
        x = tiles_to_global(x_st, dist_b)
        if om_np is None:
            r = (t @ x if side == "L" else x @ t) - alpha * b
        else:
            om = jnp.asarray(om_np).astype(b.dtype)
            tx = t @ (x @ om) if side == "L" else x @ (t @ om)
            r = tx - alpha * (b @ om)
        return _rel(_sq(r), _sq(b), scale)

    return jax.jit(fn)


@register_program_cache
@functools.lru_cache(maxsize=64)
def _dist_trsm_prog(dist_a, dist_b, mesh, side, uplo, op, diag, alpha,
                    mode, k, seed):
    import jax.numpy as jnp

    ncols = dist_b.size.col
    om_np, scale = _probe_columns(ncols, mode, k, seed)
    if om_np is None:
        om_np = np.eye(ncols)
    mask = uplo if diag == "N" else ("SL" if uplo == "L" else "SU")

    def local(lt_a, lt_b, lt_x):
        ga_r, ga_c = _tile_coords(dist_a)
        gb_r, gb_c = _tile_coords(dist_b)
        t_t = _masked(lt_a, dist_a, ga_r, ga_c, mask)
        b_t = _masked(lt_b, dist_b, gb_r, gb_c, "G")
        x_t = _masked(lt_x, dist_b, gb_r, gb_c, "G")
        om = jnp.asarray(om_np).astype(lt_b.dtype)
        bo = _mv(b_t, om, dist_b, gb_r, gb_c, "N")
        if side == "L":
            xo = _mv(x_t, om, dist_b, gb_r, gb_c, "N")
            tx = _mv(t_t, xo, dist_a, ga_r, ga_c, op)
            if diag == "U":
                tx = tx + xo
        else:
            to = _mv(t_t, om, dist_a, ga_r, ga_c, op)
            if diag == "U":
                to = to + om[: dist_a.size.row]
            tx = _mv(x_t, to, dist_b, gb_r, gb_c, "N")
        den2 = _psum2(_sq(b_t))
        return _rel(_sq(tx - alpha * bo), den2, scale)[None]

    return _shard_scalar(local, mesh, 3)


def trsm_residual(side, uplo, op, diag, alpha, a, b, x,
                  mode: Optional[str] = None) -> float:
    """Relative triangular-solve residual ``|op(T) X - alpha B|_F /
    |B|_F`` (side "R": ``|X op(T) - alpha B|_F``), estimated per mode."""
    mode = resolved_mode(mode)
    if b.size.is_empty():
        return 0.0
    if b.grid is None or b.grid.num_devices == 1:
        prog = _local_trsm_prog(a.dist, b.dist, side, uplo, op, diag,
                                float(alpha), mode, DEFAULT_PROBES,
                                PROBE_SEED)
        return float(prog(a.storage, b.storage, x.storage))
    prog = _dist_trsm_prog(a.dist, b.dist, b.grid.mesh, side, uplo, op,
                           diag, float(alpha), mode, DEFAULT_PROBES,
                           PROBE_SEED)
    return float(np.asarray(prog(a.storage, b.storage, x.storage))[0, 0, 0])


# ---------------------------------------------------------------------------
# HEGST (gen_to_std): |L C L^H - A|_F / |A|_F  (uplo U: |U^H C U - A|_F)
# ---------------------------------------------------------------------------

@register_program_cache
@functools.lru_cache(maxsize=64)
def _local_hegst_prog(dist, uplo, mode, k, seed):
    import jax
    import jax.numpy as jnp

    from ..matrix.tiling import tiles_to_global

    om_np, scale = _probe_columns(dist.size.row, mode, k, seed)

    def herm(x):
        tri = jnp.tril(x) if uplo == "L" else jnp.triu(x)
        strict = jnp.tril(x, -1) if uplo == "L" else jnp.triu(x, 1)
        return tri + strict.conj().T

    def fn(a_st, f_st, c_st):
        ah = herm(tiles_to_global(a_st, dist))
        f = tiles_to_global(f_st, dist)
        t = jnp.tril(f) if uplo == "L" else jnp.triu(f)
        ch = herm(tiles_to_global(c_st, dist))
        if om_np is None:
            z = t @ ch @ t.conj().T if uplo == "L" \
                else t.conj().T @ ch @ t
            r = z - ah
        else:
            om = jnp.asarray(om_np).astype(ah.dtype)
            if uplo == "L":
                z = t @ (ch @ (t.conj().T @ om))
            else:
                z = t.conj().T @ (ch @ (t @ om))
            r = z - ah @ om
        return _rel(_sq(r), _sq(ah), scale)

    return jax.jit(fn)


@register_program_cache
@functools.lru_cache(maxsize=64)
def _dist_hegst_prog(dist, mesh, uplo, mode, k, seed):
    import jax.numpy as jnp

    n = dist.size.row
    om_np, scale = _probe_columns(n, mode, k, seed)
    if om_np is None:
        om_np = np.eye(n)

    def local(lt_a, lt_f, lt_c):
        g_rows, g_cols = _tile_coords(dist)
        f_t = _masked(lt_f, dist, g_rows, g_cols, uplo)
        om = jnp.asarray(om_np).astype(lt_a.dtype)
        if uplo == "L":
            w1 = _mv(f_t, om, dist, g_rows, g_cols, "C")
            w2 = _mv_herm(lt_c, w1, dist, g_rows, g_cols, uplo)
            z = _mv(f_t, w2, dist, g_rows, g_cols, "N")
        else:
            w1 = _mv(f_t, om, dist, g_rows, g_cols, "N")
            w2 = _mv_herm(lt_c, w1, dist, g_rows, g_cols, uplo)
            z = _mv(f_t, w2, dist, g_rows, g_cols, "C")
        ya = _mv_herm(lt_a, om, dist, g_rows, g_cols, uplo)
        den2 = _psum2(_herm_sq(lt_a, dist, g_rows, g_cols, uplo))
        return _rel(_sq(z - ya), den2, scale)[None]

    return _shard_scalar(local, mesh, 3)


def hegst_residual(uplo: str, a, factor, out,
                   mode: Optional[str] = None) -> float:
    """Relative HEGST residual ``|L C L^H - A|_F / |A|_F`` (uplo "U":
    ``|U^H C U - A|_F``) with ``A``/``C`` hermitian-expanded from their
    stored ``uplo`` triangles, estimated per mode."""
    mode = resolved_mode(mode)
    if a.size.is_empty():
        return 0.0
    if a.grid is None or a.grid.num_devices == 1:
        prog = _local_hegst_prog(a.dist, uplo, mode, DEFAULT_PROBES,
                                 PROBE_SEED)
        return float(prog(a.storage, factor.storage, out.storage))
    prog = _dist_hegst_prog(a.dist, a.grid.mesh, uplo, mode,
                            DEFAULT_PROBES, PROBE_SEED)
    return float(np.asarray(
        prog(a.storage, factor.storage, out.storage))[0, 0, 0])


# ---------------------------------------------------------------------------
# Eigensolver: eigenpair residual + orthogonality
# ---------------------------------------------------------------------------

def _eigen_probe(n: int, mode: str, k: int, seed: int):
    """Combined probe block for the eigensolver estimators: ``k`` random
    Rademacher columns (the Frobenius/orthogonality estimates) followed
    by the sampled one-hot columns (exact per-eigenpair residual
    columns). Mode "full": the identity serves both."""
    om_np, scale = _probe_columns(n, mode, k, seed)
    if om_np is None:
        return np.eye(n), n, 1.0
    sel = _sample_columns(n, mode, k, seed)
    onehot = np.zeros((n, sel.shape[0]))
    onehot[sel, np.arange(sel.shape[0])] = 1.0
    return np.concatenate([om_np, onehot], axis=1), om_np.shape[1], scale


@register_program_cache
@functools.lru_cache(maxsize=64)
def _local_eigen_prog(dist, uplo, generalized, mode, k, seed):
    import jax
    import jax.numpy as jnp

    from ..matrix.tiling import tiles_to_global

    n = dist.size.row
    om_np, k_rand, scale = _eigen_probe(n, mode, k, seed)

    def herm(x):
        tri = jnp.tril(x) if uplo == "L" else jnp.triu(x)
        strict = jnp.tril(x, -1) if uplo == "L" else jnp.triu(x, 1)
        return tri + strict.conj().T

    def fn(a_st, z_st, b_st, lam):
        ah = herm(tiles_to_global(a_st, dist))
        z = tiles_to_global(z_st, dist)
        om = jnp.asarray(om_np).astype(z.dtype)
        lam_om = lam[:, None].astype(z.dtype) * om
        zo = z @ om
        zl = z @ lam_om
        if generalized:
            bh = herm(tiles_to_global(b_st, dist))
            r = ah @ zo - bh @ zl
        else:
            r = ah @ zo - zl
        g = z.conj().T @ zo - om
        den_raw = jnp.sqrt(_sq(ah))
        den = jnp.maximum(den_raw, _tiny(den_raw))
        fro = jnp.sqrt(_sq(r[:, :k_rand])) * scale / den
        # one-hot columns give exact residual columns; mode "full" has
        # no separate one-hot block — the identity makes EVERY column of
        # r an exact |A z_i - lam_i [B] z_i| column
        r_sel = r[:, k_rand:] if om_np.shape[1] > k_rand else r
        colmax = jnp.sqrt(jnp.max(jnp.sum(
            jnp.real(r_sel * jnp.conj(r_sel)), axis=0),
            initial=0.0)) / den
        orth = jnp.sqrt(_sq(g[:, :k_rand])) * scale
        return jnp.stack([fro, colmax, orth])

    return jax.jit(fn)


@register_program_cache
@functools.lru_cache(maxsize=64)
def _dist_eigen_prog(dist, mesh, uplo, generalized, mode, k, seed):
    import jax.numpy as jnp

    from jax.sharding import PartitionSpec as P

    n = dist.size.row
    om_np, k_rand, scale = _eigen_probe(n, mode, k, seed)

    def local(lt_a, lt_z, lt_b, lam):
        g_rows, g_cols = _tile_coords(dist)
        z_t = _masked(lt_z, dist, g_rows, g_cols, "G")
        om = jnp.asarray(om_np).astype(lt_z.dtype)
        lam_om = lam[:, None].astype(lt_z.dtype) * om
        zo = _mv(z_t, om, dist, g_rows, g_cols, "N")
        zl = _mv(z_t, lam_om, dist, g_rows, g_cols, "N")
        azo = _mv_herm(lt_a, zo, dist, g_rows, g_cols, uplo)
        if generalized:
            r = azo - _mv_herm(lt_b, zl, dist, g_rows, g_cols, uplo)
        else:
            r = azo - zl
        g = _mv(z_t, zo, dist, g_rows, g_cols, "C") - om
        den2 = _psum2(_herm_sq(lt_a, dist, g_rows, g_cols, uplo))
        den_raw = jnp.sqrt(den2)
        den = jnp.maximum(den_raw, _tiny(den_raw))
        fro = jnp.sqrt(_sq(r[:, :k_rand])) * scale / den
        # mode "full" has no separate one-hot block: every identity
        # column of r is an exact per-eigenpair residual column
        r_sel = r[:, k_rand:] if om_np.shape[1] > k_rand else r
        colmax = jnp.sqrt(jnp.max(jnp.sum(
            jnp.real(r_sel * jnp.conj(r_sel)), axis=0),
            initial=0.0)) / den
        orth = jnp.sqrt(_sq(g[:, :k_rand])) * scale
        return jnp.stack([fro, colmax, orth])

    return _shard_scalar(local, mesh, 3, extra_specs=(P(),))


def eigen_residuals(uplo: str, a, lam, z, b=None,
                    mode: Optional[str] = None) -> dict:
    """Eigensolver quality estimates for eigenpairs ``(lam, Z)`` of the
    hermitian ``a`` (generalized with ``b``): ``{"eigen_residual":
    |A Z - [B] Z diag(lam)|_F / |A|_F, "eigenpair_max": max over the
    sampled pairs of |A z_i - lam_i [B] z_i|_2 / |A|_F, "orthogonality":
    |Z^H Z - I|_F}``, estimated per mode."""
    mode = resolved_mode(mode)
    if a.size.is_empty():
        return {"eigen_residual": 0.0, "eigenpair_max": 0.0,
                "orthogonality": 0.0}
    lam_arr = np.asarray(lam, dtype=np.float64)
    generalized = b is not None
    b_st = b.storage if generalized else a.storage
    if a.grid is None or a.grid.num_devices == 1:
        prog = _local_eigen_prog(a.dist, uplo, generalized, mode,
                                 DEFAULT_PROBES, PROBE_SEED)
        out = np.asarray(prog(a.storage, z.storage, b_st, lam_arr))
    else:
        prog = _dist_eigen_prog(a.dist, a.grid.mesh, uplo, generalized,
                                mode, DEFAULT_PROBES, PROBE_SEED)
        out = np.asarray(prog(a.storage, z.storage, b_st, lam_arr))[0, 0]
    return {"eigen_residual": float(out[0]), "eigenpair_max": float(out[1]),
            "orthogonality": float(out[2])}


def array_orthogonality(q, mode: Optional[str] = None) -> float:
    """Orthogonality defect ``|Q^H Q - I|_F`` of a plain (device or
    host) square array, estimated per mode — the bench stage arms'
    cheap invariant for tridiag eigenvector blocks."""
    import jax.numpy as jnp

    mode = resolved_mode(mode)
    q = jnp.asarray(q)
    n = q.shape[0]
    if n == 0:
        return 0.0
    om_np, scale = _probe_columns(n, mode, DEFAULT_PROBES, PROBE_SEED)
    if om_np is None:
        g = q.conj().T @ q - jnp.eye(n, dtype=q.dtype)
    else:
        om = jnp.asarray(om_np).astype(q.dtype)
        g = q.conj().T @ (q @ om) - om
    return float(jnp.sqrt(_sq(g)) * scale)


# ---------------------------------------------------------------------------
# Record emission
# ---------------------------------------------------------------------------

def _platform_of(of=None) -> str:
    """Platform label for a record, judged from the device array that
    holds the checked result (``of``) when given, else the default
    backend — never forcing a backend up from a bare call."""
    if of is not None:
        devs = getattr(of, "devices", None)
        if callable(devs):
            try:
                return next(iter(devs())).platform
            except Exception:
                pass
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


@dataclasses.dataclass
class AccuracyResult:
    """One emitted estimate: the value, its analytic budget ``tol =
    c * n * eps_eff`` (None for informational metrics), and the
    normalized ``bound_ratio = value / tol`` the gate consumes."""

    site: str
    metric: str
    value: float
    finite: bool
    tol: Optional[float] = None
    bound_ratio: Optional[float] = None
    eps_eff: Optional[float] = None
    eps_label: str = ""

    @property
    def passed(self) -> bool:
        """Finite and within the analytic budget (informational metrics
        pass on finiteness alone)."""
        return self.finite and (self.tol is None or self.value < self.tol)


def emit(site: str, metric: str, value, *, n: int, nb: int, dtype,
         c: Optional[float] = None, of=None, attrs: Optional[dict] = None,
         mode: Optional[str] = None, record: bool = True) -> AccuracyResult:
    """Emit one ``accuracy`` JSONL record (+ the
    ``dlaf_accuracy_ratio{site,metric}`` gauge) and return the
    :class:`AccuracyResult`.

    ``c`` is the site's analytic tolerance factor (``tol = c * n *
    eps_eff`` with :func:`dlaf_tpu.miniapp.checks.effective_eps` judged
    from ``of`` — the device array holding the checked result — so
    TPU-emulated-f64 budgets stay honest); ``c=None`` marks an
    informational metric (e.g. the D&C deflation fraction) carrying no
    ``bound_ratio``. A non-finite ``value`` lands as ``value: null`` +
    ``nonfinite: true`` — the corruption signal the accuracy gate treats
    as an automatic regression. ``record=False`` computes without
    emitting (the gate's injection drill)."""
    v = float(value)
    finite = math.isfinite(v)
    mode = resolved_mode(mode)
    tol = ratio = eps = None
    label = ""
    if c is not None:
        from ..miniapp.checks import effective_eps

        eps, label = effective_eps(dtype, of=of)
        tol = float(c) * max(int(n), 1) * eps
        if finite and tol > 0:
            ratio = v / tol
    rec = {"site": site, "metric": metric, "platform": _platform_of(of),
           "n": int(n), "nb": int(nb), "dtype": np.dtype(dtype).name,
           "value": v if finite else None,
           "attrs": dict(attrs or {}, mode=mode)}
    if not finite:
        rec["nonfinite"] = True
    if ratio is not None:
        rec["bound_ratio"] = ratio
        rec["c"] = float(c)
        rec["eps_eff"] = eps
    if record:
        from . import counter, emit_event, gauge, metrics_active
        from . import flight as _flight

        emit_event("accuracy", **rec)
        if metrics_active():
            if ratio is not None:
                gauge("dlaf_accuracy_ratio", site=site,
                      metric=metric).set(ratio)
            if not finite:
                counter("dlaf_accuracy_nonfinite_total", site=site,
                        metric=metric).inc()
        if (ratio is not None and ratio > 1.0) or not finite:
            # a blown analytic budget (or a corrupted estimate — worse)
            # IS an incident: capture the flight ring AFTER this record
            # landed in it, so the dump includes the breaching record
            # itself (docs/observability.md trigger catalog)
            _flight.trigger("accuracy_breach", site=site, metric=metric,
                            bound_ratio=(float(ratio)
                                         if ratio is not None else None),
                            nonfinite=not finite)
    return AccuracyResult(site=site, metric=metric, value=v, finite=finite,
                          tol=tol, bound_ratio=ratio, eps_eff=eps,
                          eps_label=label)
