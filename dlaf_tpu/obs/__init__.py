"""dlaf_tpu.obs — structured tracing, metrics, and logging.

The observability layer ISSUE 1 calls for (and SURVEY §5 maps from the
reference's pika-delegated profiling): one subsystem under three knobs,
layered like every other :class:`dlaf_tpu.config.Configuration` field
(default < user struct < env < ``--dlaf:`` CLI):

* ``DLAF_LOG`` (``Configuration.log``) — leveled structured logging
  (debug/info/warning/error/off), :mod:`dlaf_tpu.obs.logging`.
* ``DLAF_METRICS_PATH`` (``Configuration.metrics_path``) — JSON-lines
  artifact receiving span records, metrics snapshots, and log events
  (:mod:`dlaf_tpu.obs.sinks`; schema validated by
  ``python -m dlaf_tpu.obs.validate``). Setting it turns the tracer and
  the metrics registry on.
* ``DLAF_TRACE_DIR`` (``Configuration.trace_dir``) — ``jax.profiler``
  trace directory; host spans then also carry
  ``jax.profiler.TraceAnnotation`` names onto the profiler timeline, and
  trace-time :func:`named_span` phases land in compiled-program op
  metadata.

Cost contract: with all three unset, every instrumented call site
resolves to a module-level no-op singleton — no allocation, one attribute
read — so the instrumentation in comm/algorithms/eigensolver hot paths is
free when off (verified by tests/test_obs.py).
"""

from __future__ import annotations

import atexit
import os
import time
from typing import Optional

from . import accuracy as accuracy
from . import logging as _logging
from . import metrics as _metrics
from . import sinks as _sinks
from . import telemetry as telemetry
from . import trace as _trace
from ._state import LOG_LEVELS, STATE, current_rank
from .logging import Logger, get_logger
from .metrics import (NOOP_COUNTER, NOOP_GAUGE, NOOP_HISTOGRAM, Counter,
                      Gauge, Histogram, Registry, prometheus_text)
from .sinks import (SCHEMA_VERSION, JsonlSink,
                    accuracy_record_to_history_line, append_history_line,
                    expand_rank_template, read_history_records, read_records,
                    validate_file, validate_history_records, validate_records)
from .trace import (NOOP_CTX, NOOP_SPAN, Span, current_span, entry_span,
                    named_span, span, start_profiler, stop_profiler)

__all__ = [
    "configure", "enabled", "metrics_active", "span", "entry_span",
    "named_span",
    "current_span", "counter", "gauge", "histogram", "registry",
    "get_logger", "emit_event", "emit_metrics_snapshot", "flush",
    "prometheus_text", "prometheus_snapshot_text", "validate_file",
    "validate_records", "read_records", "Span", "Counter", "Gauge",
    "Histogram", "Registry", "Logger", "JsonlSink", "SCHEMA_VERSION",
    "NOOP_SPAN", "NOOP_CTX", "NOOP_COUNTER", "NOOP_GAUGE", "NOOP_HISTOGRAM",
    "LOG_LEVELS", "start_profiler", "stop_profiler", "telemetry",
    "set_rank", "current_rank", "expand_rank_template",
    "append_history_line", "read_history_records", "validate_history_records",
    "accuracy", "accuracy_record_to_history_line",
]


def configure(log_level: str = "info", metrics_path: str = "",
              trace_dir: str = "", program_telemetry: bool = False) -> None:
    """(Re)configure the layer — called by ``config.initialize()`` with the
    resolved knobs, or lazily from the env by the first logging call in a
    process that never initializes the runtime.

    Reconfiguring with a different ``metrics_path`` closes the old sink
    (its file stays, a complete artifact); counters persist across
    reconfiguration within a process — they are process-lifetime
    accumulators, like the reference's performance counters.

    ``metrics_path`` may carry a ``%r`` placeholder, replaced by the
    process rank (``jax.process_index()``) so each host of a multi-host
    run appends to its own artifact instead of interleaving one file;
    merge them with ``python -m dlaf_tpu.obs.aggregate``.

    ``program_telemetry`` (the ``DLAF_PROGRAM_TELEMETRY`` knob) arms the
    AOT/jit instrumentation in :mod:`dlaf_tpu.obs.telemetry` — compile
    walls, retrace counters, and HBM gauges from the library's cached
    program sites. Off (default), every telemetry call site is a
    zero-cost passthrough.
    """
    level = str(log_level or "info").strip().lower()
    if level not in LOG_LEVELS:
        raise ValueError(f"DLAF_LOG={log_level!r}: must be one of "
                         f"{tuple(LOG_LEVELS)}")
    STATE.log_level = level
    STATE.log_level_num = LOG_LEVELS[level]
    metrics_path = _sinks.expand_rank_template(metrics_path or "")
    if STATE.sink is not None and STATE.sink.path != metrics_path:
        emit_metrics_snapshot()
        STATE.sink.close()
        STATE.sink = None
    if metrics_path and STATE.sink is None:
        STATE.sink = _sinks.JsonlSink(metrics_path)
    STATE.trace_dir = trace_dir or ""
    STATE.metrics_on = STATE.sink is not None
    STATE.annotate = bool(trace_dir)
    STATE.telemetry_on = bool(program_telemetry)
    if STATE.registry is None and (STATE.metrics_on or STATE.annotate
                                   or STATE.telemetry_on):
        STATE.registry = _metrics.Registry()
    if (STATE.metrics_on or STATE.annotate or STATE.telemetry_on) \
            and not STATE.atexit_registered:
        STATE.atexit_registered = True
        atexit.register(_shutdown)
    STATE.configured = True


def set_rank(rank: int) -> None:
    """Pin the rank stamped onto JSONL records (and ``%r`` expansions).
    :func:`dlaf_tpu.comm.multihost.initialize_multihost` calls this right
    after ``jax.distributed.initialize`` — a ``%r`` metrics path resolved
    before the distributed runtime came up would have labeled every host
    rank 0."""
    STATE.rank = int(rank)


def _shutdown() -> None:
    """Process exit: flush a final metrics snapshot and stop the profiler
    so artifacts are complete even when drivers forget to call flush()."""
    try:
        emit_metrics_snapshot()
    finally:
        _trace.stop_profiler()
        if STATE.sink is not None:
            STATE.sink.close()


def enabled() -> bool:
    """True when any observability output is active."""
    return STATE.metrics_on or STATE.annotate


def metrics_active() -> bool:
    """Fast-path gate for instrumentation call sites (one attribute read)."""
    return STATE.metrics_on


def registry() -> Registry:
    """The process registry (created on first use — usable directly even
    with the sinks off, e.g. for tests or embedding applications)."""
    if STATE.registry is None:
        STATE.registry = _metrics.Registry()
    return STATE.registry


def counter(name: str, **labels):
    """Registry counter handle, or the no-op singleton when metrics are
    off (zero per-call allocation at disabled call sites)."""
    if not STATE.metrics_on:
        return NOOP_COUNTER
    return STATE.registry.counter(name, **labels)


def gauge(name: str, **labels):
    if not STATE.metrics_on:
        return NOOP_GAUGE
    return STATE.registry.gauge(name, **labels)


def histogram(name: str, **labels):
    if not STATE.metrics_on:
        return NOOP_HISTOGRAM
    return STATE.registry.histogram(name, **labels)


def emit_event(rtype: str, **payload) -> None:
    """Append a free-form record (e.g. ``bench_result``) to the JSONL
    artifact; no-op when the sink is off."""
    if STATE.sink is not None:
        rec = {"type": rtype}
        rec.update(payload)
        STATE.sink.write(rec)


def emit_metrics_snapshot() -> None:
    """Write the registry's current state as one ``metrics`` record."""
    if STATE.sink is not None and STATE.registry is not None:
        snap = STATE.registry.snapshot()
        if snap:
            STATE.sink.write({"type": "metrics", "metrics": snap})


def flush() -> None:
    """Snapshot metrics now (drivers call this at the end of a run so the
    artifact is complete without relying on interpreter shutdown)."""
    emit_metrics_snapshot()


def prometheus_snapshot_text() -> str:
    """Prometheus text exposition of the live registry."""
    if STATE.registry is None:
        return ""
    return prometheus_text(STATE.registry.snapshot())


def _reset_for_tests() -> None:
    """Tear the layer back to the unconfigured default (tests only)."""
    try:
        # a test that left the process trace live must not leak it into
        # the rest of the session (it would record everything until exit)
        _trace.stop_profiler()
    except Exception:
        pass
    if STATE.sink is not None:
        STATE.sink.close()
    STATE.sink = None
    STATE.metrics_on = False
    STATE.annotate = False
    STATE.trace_dir = ""
    STATE.registry = None
    STATE.configured = False
    STATE.log_level = "info"
    STATE.log_level_num = LOG_LEVELS["info"]
    STATE.telemetry_on = False
    STATE.rank = None
    telemetry._reset_for_tests()
    _logging.reset_once()
