"""dlaf_tpu.obs — structured tracing, metrics, and logging.

The observability layer ISSUE 1 calls for (and SURVEY §5 maps from the
reference's pika-delegated profiling): one subsystem under three knobs,
layered like every other :class:`dlaf_tpu.config.Configuration` field
(default < user struct < env < ``--dlaf:`` CLI):

* ``DLAF_LOG`` (``Configuration.log``) — leveled structured logging
  (debug/info/warning/error/off), :mod:`dlaf_tpu.obs.logging`.
* ``DLAF_METRICS_PATH`` (``Configuration.metrics_path``) — JSON-lines
  artifact receiving span records, metrics snapshots, and log events
  (:mod:`dlaf_tpu.obs.sinks`; schema validated by
  ``python -m dlaf_tpu.obs.validate``). Setting it turns the tracer and
  the metrics registry on.
* ``DLAF_TRACE_DIR`` (``Configuration.trace_dir``) — ``jax.profiler``
  trace directory; host spans then also carry
  ``jax.profiler.TraceAnnotation`` names onto the profiler timeline, and
  trace-time :func:`named_span` phases land in compiled-program op
  metadata.

Cost contract: with all three unset, every instrumented call site
resolves to a module-level no-op singleton — no allocation, one attribute
read — so the instrumentation in comm/algorithms/eigensolver hot paths is
free when off (verified by tests/test_obs.py).
"""

from __future__ import annotations

import atexit
import os
import time
from typing import Optional

from . import accuracy as accuracy
from . import exporter as exporter
from . import flight as _flight
from . import logging as _logging
from . import metrics as _metrics
from . import sinks as _sinks
from . import slo as _slo
from . import telemetry as telemetry
from . import trace as _trace
from ._state import LOG_LEVELS, STATE, current_rank
from .context import (current_trace, new_span_id, new_trace_id,
                      single_trace_id, trace_context, trace_matches)
from .flight import FlightRecorder
from .logging import Logger, get_logger
from .metrics import (NOOP_COUNTER, NOOP_GAUGE, NOOP_HISTOGRAM, NOOP_WINDOW,
                      Counter, Gauge, Histogram, Registry, SlidingWindow,
                      prometheus_text, quantile)
from .sinks import (SCHEMA_VERSION, JsonlSink,
                    accuracy_record_to_history_line, append_history_line,
                    expand_rank_template, read_history_records, read_records,
                    validate_file, validate_history_records, validate_records)
from .trace import (NOOP_CTX, NOOP_SPAN, Span, current_span, entry_span,
                    named_span, scoped_step, span, start_profiler,
                    stop_profiler)

__all__ = [
    "configure", "enabled", "metrics_active", "span", "entry_span",
    "named_span", "scoped_step",
    "current_span", "counter", "gauge", "histogram", "registry",
    "get_logger", "emit_event", "emit_metrics_snapshot", "flush",
    "prometheus_text", "prometheus_snapshot_text", "validate_file",
    "validate_records", "read_records", "Span", "Counter", "Gauge",
    "Histogram", "Registry", "Logger", "JsonlSink", "SCHEMA_VERSION",
    "NOOP_SPAN", "NOOP_CTX", "NOOP_COUNTER", "NOOP_GAUGE", "NOOP_HISTOGRAM",
    "NOOP_WINDOW",
    "LOG_LEVELS", "start_profiler", "stop_profiler", "telemetry",
    "set_rank", "current_rank", "expand_rank_template",
    "append_history_line", "read_history_records", "validate_history_records",
    "accuracy", "accuracy_record_to_history_line",
    # ISSUE 13: live operational telemetry
    "trace_context", "current_trace", "new_trace_id", "new_span_id",
    "single_trace_id", "trace_matches", "observe_latency", "quantile",
    "SlidingWindow", "FlightRecorder", "exporter",
    # ISSUE 14: device-timeline attribution
    "devtrace",
]


def __getattr__(name: str):
    # lazy submodule: ``obs.devtrace`` is an offline analysis engine
    # (ISSUE 14) never needed on the record-emitting hot path, and an
    # eager import here would trip runpy's found-in-sys.modules warning
    # on every ``python -m dlaf_tpu.obs.devtrace`` invocation
    if name == "devtrace":
        import importlib

        return importlib.import_module(".devtrace", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def configure(log_level: str = "info", metrics_path: str = "",
              trace_dir: str = "", program_telemetry: bool = False,
              metrics_port: int = 0, flight_recorder: int = 0) -> None:
    """(Re)configure the layer — called by ``config.initialize()`` with the
    resolved knobs, or lazily from the env by the first logging call in a
    process that never initializes the runtime.

    Reconfiguring with a different ``metrics_path`` closes the old sink
    (its file stays, a complete artifact); counters persist across
    reconfiguration within a process — they are process-lifetime
    accumulators, like the reference's performance counters.

    ``metrics_path`` may carry a ``%r`` placeholder, replaced by the
    process rank (``jax.process_index()``) so each host of a multi-host
    run appends to its own artifact instead of interleaving one file;
    merge them with ``python -m dlaf_tpu.obs.aggregate``.

    ``program_telemetry`` (the ``DLAF_PROGRAM_TELEMETRY`` knob) arms the
    AOT/jit instrumentation in :mod:`dlaf_tpu.obs.telemetry` — compile
    walls, retrace counters, and HBM gauges from the library's cached
    program sites. Off (default), every telemetry call site is a
    zero-cost passthrough.

    ``metrics_port`` (``DLAF_METRICS_PORT``, ISSUE 13) starts the live
    ``/metrics`` + ``/healthz`` exporter (:mod:`dlaf_tpu.obs.exporter`)
    as a daemon thread on 127.0.0.1 — AND turns the registry on even
    without a sink, so a scrape-only deployment records. 0 (default):
    no thread, no socket. ``flight_recorder``
    (``DLAF_FLIGHT_RECORDER``) arms a bounded in-memory ring of the
    last N sink records, dumped atomically to
    ``<metrics_path>.flight.jsonl`` on incident triggers
    (:mod:`dlaf_tpu.obs.flight`); it needs a sink (the ring captures
    the sink's record stream) and warns once when armed without one.
    """
    level = str(log_level or "info").strip().lower()
    if level not in LOG_LEVELS:
        raise ValueError(f"DLAF_LOG={log_level!r}: must be one of "
                         f"{tuple(LOG_LEVELS)}")
    STATE.log_level = level
    STATE.log_level_num = LOG_LEVELS[level]
    metrics_path = _sinks.expand_rank_template(metrics_path or "")
    if STATE.sink is not None and STATE.sink.path != metrics_path:
        emit_metrics_snapshot()
        STATE.sink.close()
        STATE.sink = None
    if metrics_path and STATE.sink is None:
        STATE.sink = _sinks.JsonlSink(metrics_path)
    STATE.trace_dir = trace_dir or ""
    port = int(metrics_port or 0)
    if port < 0:
        raise ValueError(f"DLAF_METRICS_PORT={metrics_port!r}: must be "
                         ">= 0 (0 = exporter off)")
    STATE.metrics_on = STATE.sink is not None or port > 0
    STATE.annotate = bool(trace_dir)
    STATE.telemetry_on = bool(program_telemetry)
    if STATE.registry is None and (STATE.metrics_on or STATE.annotate
                                   or STATE.telemetry_on):
        STATE.registry = _metrics.Registry()
    # live exporter lifecycle: restart on a port change, stop on 0
    if port != STATE.exporter_port:
        exporter.stop()
        STATE.exporter_port = 0
        if port > 0:
            exporter.start(port)
            STATE.exporter_port = port
    # flight recorder: a ring of the knob's size over the sink stream
    cap = int(flight_recorder or 0)
    if cap < 0:
        raise ValueError(f"DLAF_FLIGHT_RECORDER={flight_recorder!r}: must "
                         "be >= 0 (0 = recorder off; N = ring depth)")
    if cap > 0 and STATE.sink is not None:
        if STATE.flight is None or STATE.flight.capacity != cap:
            STATE.flight = _flight.FlightRecorder(cap)
    else:
        if cap > 0:
            get_logger("obs").warning_once(
                ("flight_no_sink",),
                "DLAF_FLIGHT_RECORDER is set but DLAF_METRICS_PATH is "
                "not: the flight ring captures the sink's record stream, "
                "so the recorder stays unarmed")
        STATE.flight = None
    if (STATE.metrics_on or STATE.annotate or STATE.telemetry_on) \
            and not STATE.atexit_registered:
        STATE.atexit_registered = True
        atexit.register(_shutdown)
    STATE.configured = True


def set_rank(rank: int) -> None:
    """Pin the rank stamped onto JSONL records (and ``%r`` expansions).
    :func:`dlaf_tpu.comm.multihost.initialize_multihost` calls this right
    after ``jax.distributed.initialize`` — a ``%r`` metrics path resolved
    before the distributed runtime came up would have labeled every host
    rank 0."""
    STATE.rank = int(rank)


def _shutdown() -> None:
    """Process exit: flush a final metrics snapshot, stop the profiler,
    and shut the live exporter down so artifacts are complete even when
    drivers forget to call flush()."""
    try:
        emit_metrics_snapshot()
    finally:
        _trace.stop_profiler()
        exporter.stop()
        STATE.exporter_port = 0
        if STATE.sink is not None:
            STATE.sink.close()


def enabled() -> bool:
    """True when any observability output is active."""
    return STATE.metrics_on or STATE.annotate


def metrics_active() -> bool:
    """Fast-path gate for instrumentation call sites (one attribute read)."""
    return STATE.metrics_on


def registry() -> Registry:
    """The process registry (created on first use — usable directly even
    with the sinks off, e.g. for tests or embedding applications)."""
    if STATE.registry is None:
        STATE.registry = _metrics.Registry()
    return STATE.registry


def counter(name: str, **labels):
    """Registry counter handle, or the no-op singleton when metrics are
    off (zero per-call allocation at disabled call sites)."""
    if not STATE.metrics_on:
        return NOOP_COUNTER
    return STATE.registry.counter(name, **labels)


def gauge(name: str, **labels):
    if not STATE.metrics_on:
        return NOOP_GAUGE
    return STATE.registry.gauge(name, **labels)


def histogram(name: str, **labels):
    if not STATE.metrics_on:
        return NOOP_HISTOGRAM
    return STATE.registry.histogram(name, **labels)


def emit_event(rtype: str, **payload) -> None:
    """Append a free-form record (e.g. ``bench_result``) to the JSONL
    artifact; no-op when the sink is off."""
    if STATE.sink is not None:
        rec = {"type": rtype}
        rec.update(payload)
        STATE.sink.write(rec)


def emit_metrics_snapshot() -> None:
    """Write the registry's current state as one ``metrics`` record."""
    if STATE.sink is not None and STATE.registry is not None:
        snap = STATE.registry.snapshot()
        if snap:
            STATE.sink.write({"type": "metrics", "metrics": snap})


def flush() -> None:
    """Snapshot metrics now (drivers call this at the end of a run so the
    artifact is complete without relying on interpreter shutdown)."""
    emit_metrics_snapshot()


def prometheus_snapshot_text() -> str:
    """Prometheus text exposition of the live registry — and the
    documented zero-allocation no-op ("") when :func:`metrics_active` is
    false, matching the discipline of every other obs entry point: with
    metrics off there is nothing worth snapshotting (a registry may
    still exist from an annotate/telemetry-only configuration, but its
    exposition is not a metrics product). Pinned by
    tests/test_live_telemetry.py (ISSUE 13 satellite)."""
    if not STATE.metrics_on or STATE.registry is None:
        return ""
    return prometheus_text(STATE.registry.snapshot())


def observe_latency(op: str, seconds: float, bucket: str = "") -> None:
    """Feed one end-to-end latency into the rolling-window SLO tracker
    (:mod:`dlaf_tpu.obs.slo`): the ``dlaf_serve_latency_seconds{op,
    bucket}`` histogram (+ exemplar trace ID when called under a
    request-scoped :func:`trace_context`), the
    ``dlaf_serve_latency_window{op,bucket,q}`` gauges, and the
    ``dlaf_slo_breach_total{op}`` burn counter against
    ``DLAF_SLO_P99_MS``. No-op when metrics are off."""
    if not STATE.metrics_on:
        return
    _slo.observe(str(op), float(seconds), bucket=str(bucket))


def _reset_for_tests() -> None:
    """Tear the layer back to the unconfigured default (tests only)."""
    try:
        # a test that left the process trace live must not leak it into
        # the rest of the session (it would record everything until exit)
        _trace.stop_profiler()
    except Exception:
        pass
    if STATE.sink is not None:
        STATE.sink.close()
    exporter.stop()
    STATE.sink = None
    STATE.metrics_on = False
    STATE.annotate = False
    STATE.trace_dir = ""
    STATE.registry = None
    STATE.configured = False
    STATE.log_level = "info"
    STATE.log_level_num = LOG_LEVELS["info"]
    STATE.telemetry_on = False
    STATE.rank = None
    STATE.flight = None
    STATE.exporter_port = 0
    _slo.set_clock(None)
    telemetry._reset_for_tests()
    _logging.reset_once()
