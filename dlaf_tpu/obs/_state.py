"""Shared mutable state of the observability layer.

One module-level :data:`STATE` object, mutated only by
:func:`dlaf_tpu.obs.configure` (driven by ``config.initialize()``) and by
the lazy env-var fallback for processes that use the library without ever
initializing the runtime. Every hot-path check in the tracer/metrics/logger
is a read of one attribute here — no locks, no dict lookups — so call sites
stay allocation-free when observability is off.
"""

from __future__ import annotations

import os

#: DLAF_LOG levels, lowest first. "off" silences everything.
LOG_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40, "off": 99}


class _ObsState:
    __slots__ = ("configured", "log_level", "log_level_num", "metrics_on",
                 "annotate", "trace_dir", "sink", "registry",
                 "profiler_started", "atexit_registered", "telemetry_on",
                 "rank", "flight", "exporter_port")

    def __init__(self):
        self.configured = False
        self.log_level = "info"
        self.log_level_num = LOG_LEVELS["info"]
        self.metrics_on = False          # counters/spans record + JSONL sink
        self.annotate = False            # jax named_scope/TraceAnnotation on
        self.trace_dir = ""              # jax.profiler trace output dir
        self.sink = None                 # type: Optional[object]  # JsonlSink
        self.registry = None             # type: Optional[object]  # Registry
        self.profiler_started = False
        self.atexit_registered = False
        self.telemetry_on = False        # DLAF_PROGRAM_TELEMETRY knob
        self.rank = None                 # type: Optional[int]  # process rank
        self.flight = None               # type: Optional[object]  # recorder
        self.exporter_port = 0           # DLAF_METRICS_PORT in effect (0=off)


STATE = _ObsState()


def ensure_env_defaults() -> None:
    """Lazy fallback: pick up ``DLAF_LOG``/``DLAF_METRICS_PATH``/
    ``DLAF_TRACE_DIR`` straight from the environment when nothing has
    called :func:`dlaf_tpu.obs.configure` yet (library use without
    ``config.initialize()``). A later real configure() overrides this."""
    if STATE.configured:
        return
    from . import configure

    level = os.environ.get("DLAF_LOG", "info")
    if str(level).strip().lower() not in LOG_LEVELS:
        # this path is reached from informational log calls deep inside
        # library code (a knob-resolution notice, a native-load warning):
        # a misspelled env var must not turn those into a crash. The
        # explicit config.initialize() path still rejects bad values.
        import sys

        print(f"dlaf_tpu[warning] obs: DLAF_LOG={level!r} is not one of "
              f"{tuple(LOG_LEVELS)}; using 'info'", file=sys.stderr,
              flush=True)
        level = "info"
    def _int_env(name):
        raw = os.environ.get(name, "").strip()
        try:
            val = int(raw) if raw else 0
        except ValueError:
            val = -1
        if val < 0:
            import sys

            # same stance as the DLAF_LOG fallback above: a malformed
            # (or negative — configure() rejects those too) env var on
            # this lazy path warns instead of crashing a bare log call;
            # config.initialize() still rejects it loudly
            print(f"dlaf_tpu[warning] obs: {name}={raw!r} is not a "
                  "non-negative int; using 0 (off)", file=sys.stderr,
                  flush=True)
            return 0
        return val

    configure(log_level=level,
              metrics_path=os.environ.get("DLAF_METRICS_PATH", ""),
              trace_dir=os.environ.get("DLAF_TRACE_DIR", ""),
              program_telemetry=os.environ.get(
                  "DLAF_PROGRAM_TELEMETRY", "").strip().lower()
              in ("1", "true", "yes", "on"),
              metrics_port=_int_env("DLAF_METRICS_PORT"),
              flight_recorder=_int_env("DLAF_FLIGHT_RECORDER"))


def current_rank():
    """The process rank for record stamping: the rank an owner pinned via
    :func:`dlaf_tpu.obs.set_rank` (``initialize_multihost`` does), else
    ``jax.process_index()`` — but only once jax is imported AND a backend
    already exists. A bare log write must neither import jax nor trigger
    backend initialization (this repo never probes a possibly-wedged
    accelerator tunnel implicitly); records written before the backend
    comes up simply carry no ``rank`` field (optional by schema)."""
    if STATE.rank is not None:
        return STATE.rank
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return None     # no live backend: process_index would init one
    except ImportError:
        pass                # unknown jax layout: accept the init cost
    try:
        STATE.rank = int(jax.process_index())
    except Exception:
        return None
    return STATE.rank
