"""Live ``/metrics`` + ``/healthz`` endpoint (ISSUE 13,
docs/observability.md live operations).

A stdlib ``http.server`` daemon thread, armed by ``DLAF_METRICS_PORT``
(0 = off: zero threads, zero sockets — the obs no-op discipline), bound
to ``127.0.0.1`` (operators front it with their own proxy; the library
never opens a public socket). Two routes:

* ``GET /metrics`` — Prometheus text exposition of the LIVE registry
  (not a post-hoc snapshot record). Content-negotiated like real
  exporters: a client whose ``Accept`` header names
  ``application/openmetrics-text`` (Prometheus does when exemplar
  scraping is on) gets the OpenMetrics rendering — exemplar trace IDs
  on latency histogram buckets
  (:func:`dlaf_tpu.obs.metrics.prometheus_text`, ``exemplars=True``)
  plus the ``# EOF`` terminator — so every latency bucket names one
  request to go look at; everyone else gets classic 0.0.4 text with NO
  exemplar clauses, which the classic grammar has no syntax for (a
  clause there breaks the whole scrape).
* ``GET /healthz`` — one JSON object: per-queue ``Queue.stats()``
  (bucket depth/shed/expired + breaker state names, exactly the
  structure the method returns — pinned round-trip-faithful), every
  registered circuit breaker's state, the worst live
  ``dlaf_accuracy_ratio`` gauge, the rolling SLO window state (ISSUE 14
  satellite: one entry per (op, bucket) with the
  ``dlaf_serve_latency_window`` p50/p95/p99 gauge values — the SAME
  numbers the gauges scrape, pinned round-trip-faithful like the queue
  stats — plus the ``dlaf_slo_breach_total`` burn counters, so a
  scrape-only deployment with no JSONL sink still sees SLO state), and
  process rank / pid / uptime. A payload build failure answers 500 AND
  trips the flight recorder (``healthz_failure``): the moments before a
  health endpoint broke are exactly what the ring is for.

Queues register themselves at construction (weakrefs — a dropped queue
disappears from ``/healthz`` with no unregister protocol). Lifecycle is
owned by ``obs.configure``: reconfiguring the port restarts the server,
``obs._shutdown`` (atexit, next to the sink flush) and
``_reset_for_tests`` stop it.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import weakref
from typing import Optional

from ._state import STATE, current_rank

_server = None
_thread = None
_started_at: Optional[float] = None

#: weakrefs to live serve queues (see module docstring).
_QUEUES: list = []
_QUEUES_LOCK = threading.Lock()

#: weakrefs to live fleet routers (docs/fleet.md: the router process's
#: /healthz aggregates per-worker state through Router.fleet_view()).
_FLEETS: list = []


def register_queue(queue) -> None:
    """Expose ``queue`` on ``/healthz`` for its lifetime (weakref; called
    by ``serve.Queue.__init__`` — cheap enough to do unconditionally)."""
    with _QUEUES_LOCK:
        _QUEUES[:] = [r for r in _QUEUES if r() is not None]
        _QUEUES.append(weakref.ref(queue))


def live_queues() -> list:
    with _QUEUES_LOCK:
        alive = [(r, r()) for r in _QUEUES]
        _QUEUES[:] = [r for r, q in alive if q is not None]
        return [q for _, q in alive if q is not None]


def register_fleet(router) -> None:
    """Expose a fleet ``Router`` on ``/healthz`` for its lifetime
    (weakref; called by ``fleet.Router.__init__``)."""
    with _QUEUES_LOCK:
        _FLEETS[:] = [r for r in _FLEETS if r() is not None]
        _FLEETS.append(weakref.ref(router))


def live_fleets() -> list:
    with _QUEUES_LOCK:
        alive = [(r, r()) for r in _FLEETS]
        _FLEETS[:] = [r for r, f in alive if f is not None]
        return [f for _, f in alive if f is not None]


#: Content types the endpoint answers with (negotiated per request).
OPENMETRICS_CTYPE = "application/openmetrics-text; version=1.0.0; " \
                    "charset=utf-8"
CLASSIC_CTYPE = "text/plain; version=0.0.4; charset=utf-8"


def metrics_text(openmetrics: bool = False) -> str:
    """The /metrics body: live registry. ``openmetrics=True`` renders
    exemplars and the ``# EOF`` terminator (module docstring — only the
    OpenMetrics grammar HAS an exemplar clause; classic 0.0.4 scrapers
    choke on one)."""
    from .metrics import prometheus_text

    reg = STATE.registry
    if reg is None:
        return "# EOF\n" if openmetrics else ""
    text = prometheus_text(reg.snapshot(), exemplars=openmetrics)
    return text + "# EOF\n" if openmetrics else text


def healthz_payload() -> dict:
    """The /healthz JSON (module docstring). JSON-safe by construction:
    every non-finite number is mapped to None — a NaN must not produce
    the invalid-JSON token that breaks every scraper parsing it."""
    from ..health import circuit
    from .slo import QUANTILES, WINDOW_GAUGE, BREACH_COUNTER

    def safe(v):
        return float(v) if isinstance(v, (int, float)) \
            and not isinstance(v, bool) and math.isfinite(v) else None

    worst = None
    slo_rows: dict = {}
    breaches: dict = {}
    reg = STATE.registry
    if reg is not None:
        # the gauge's q label spellings (slo.QUANTILES) -> payload keys
        q_keys = {label: "p" + str(round(float(label) * 100))
                  for _, label in QUANTILES}
        for m in reg.snapshot():
            name = m.get("name")
            labels = m.get("labels") or {}
            if name == "dlaf_accuracy_ratio":
                v = safe(m.get("value"))
                if v is not None and (worst is None or v > worst):
                    worst = v
            elif name == WINDOW_GAUGE and labels.get("q") in q_keys:
                key = (labels.get("op", ""), labels.get("bucket", ""))
                cell = slo_rows.setdefault(
                    key, {"op": key[0], "bucket": key[1]})
                cell[q_keys[labels["q"]]] = safe(m.get("value"))
            elif name == BREACH_COUNTER:
                breaches[labels.get("op", "")] = safe(m.get("value"))
    payload = {
        "status": "ok",
        "rank": current_rank(),
        "pid": os.getpid(),
        "uptime_s": (time.monotonic() - _started_at
                     if _started_at is not None else 0.0),
        "queues": [q.stats() for q in live_queues()],
        "breakers": circuit.states(),
        "accuracy": {"worst_bound_ratio": worst},
        "slo": {"windows": [slo_rows[k] for k in sorted(slo_rows)],
                "breaches": breaches},
    }
    fleets = [f.fleet_view() for f in live_fleets()]
    if fleets:
        # router process only: cross-replica membership + ticket state
        # (local, non-blocking — a wedged worker must not wedge /healthz)
        payload["fleet"] = fleets
    return payload


def _make_handler():
    # http.server imported here, not at module top: the exporter module
    # is imported unconditionally by serve.Queue for registration, and
    # the un-armed path must stay import-light
    from http.server import BaseHTTPRequestHandler

    from . import flight

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            try:
                if path == "/metrics":
                    om = "application/openmetrics-text" in \
                        (self.headers.get("Accept") or "")
                    body = metrics_text(openmetrics=om).encode()
                    ctype = OPENMETRICS_CTYPE if om else CLASSIC_CTYPE
                elif path == "/healthz":
                    body = json.dumps(healthz_payload()).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404, "unknown path "
                                    "(serving /metrics and /healthz)")
                    return
            except Exception as e:
                # a broken health endpoint IS an incident: capture the
                # ring before answering 500 (docs/observability.md)
                flight.trigger("healthz_failure", path=path,
                               error=type(e).__name__)
                self.send_error(500, f"{type(e).__name__}: {e}")
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            # per-scrape stderr chatter routed to the leveled logger
            # instead of BaseHTTPRequestHandler's unconditional stderr
            from .logging import get_logger

            get_logger("obs.exporter").debug(fmt % args)

    return Handler


def start(port: int) -> int:
    """Start the daemon exporter on 127.0.0.1:``port`` (0 = OS-assigned,
    for tests); returns the BOUND port. Idempotent per running server —
    call :func:`stop` first to rebind."""
    global _server, _thread, _started_at
    if _server is not None:
        return _server.server_address[1]
    from http.server import ThreadingHTTPServer

    _server = ThreadingHTTPServer(("127.0.0.1", int(port)), _make_handler())
    _server.daemon_threads = True
    _started_at = time.monotonic()
    _thread = threading.Thread(target=_server.serve_forever,
                               name="dlaf-metrics-exporter", daemon=True)
    _thread.start()
    return _server.server_address[1]


def port() -> int:
    """The running exporter's bound port (0 = not running)."""
    return _server.server_address[1] if _server is not None else 0


def stop() -> None:
    """Shut the server down and join its thread (clean shutdown is part
    of the sink lifecycle: obs._shutdown calls this at exit)."""
    global _server, _thread, _started_at
    if _server is None:
        return
    _server.shutdown()
    _server.server_close()
    if _thread is not None:
        _thread.join(timeout=5.0)
    _server = _thread = None
    _started_at = None
