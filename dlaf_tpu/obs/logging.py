"""Leveled structured logger (the ``DLAF_LOG`` knob).

Replaces the scattered ``print("dlaf_tpu: ...", file=sys.stderr)``
diagnostics: one line format, five levels (debug/info/warning/error/off,
:data:`dlaf_tpu.obs._state.LOG_LEVELS`), a one-shot variant for the
resolve-once configuration notices, and — when a JSONL sink is active —
a structured ``log`` record per emitted line so artifacts carry the
diagnostics that previously had to be scraped from stdout/stderr tails.

Level resolution is layered exactly like every other knob: built-in
default ("info") < ``Configuration.log`` < ``DLAF_LOG`` env <
``--dlaf:log=<level>`` CLI (see :mod:`dlaf_tpu.config`).
"""

from __future__ import annotations

import sys
import threading

from ._state import LOG_LEVELS, STATE, ensure_env_defaults

_loggers: dict = {}
_once_lock = threading.Lock()
_once_seen: set = set()


class Logger:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def is_enabled(self, level: str) -> bool:
        ensure_env_defaults()
        return LOG_LEVELS[level] >= STATE.log_level_num

    def _emit(self, level: str, msg: str, fields: dict) -> None:
        if not self.is_enabled(level):
            return
        suffix = ""
        if fields:
            suffix = " [" + " ".join(f"{k}={v}" for k, v in fields.items()) \
                + "]"
        print(f"dlaf_tpu[{level}] {self.name}: {msg}{suffix}",
              file=sys.stderr, flush=True)
        if STATE.sink is not None:
            STATE.sink.write({"type": "log", "level": level,
                              "logger": self.name, "msg": msg,
                              "fields": fields})

    def debug(self, msg: str, **fields) -> None:
        self._emit("debug", msg, fields)

    def info(self, msg: str, **fields) -> None:
        self._emit("info", msg, fields)

    def warning(self, msg: str, **fields) -> None:
        self._emit("warning", msg, fields)

    def error(self, msg: str, **fields) -> None:
        self._emit("error", msg, fields)

    def warning_once(self, key, msg: str, **fields) -> None:
        """One-shot warning keyed on ``(logger, key)`` — the resolve-once
        configuration notices (f64_gemm=auto etc.) announce each distinct
        outcome exactly once per process."""
        if not self.is_enabled("warning"):
            # suppressed: leave the key unconsumed so a later
            # initialize() that raises the level still gets the one
            # announcement — "auto decisions must not be silent"
            return
        k = (self.name, key)
        with _once_lock:
            if k in _once_seen:
                return
            _once_seen.add(k)
        self._emit("warning", msg, fields)


def get_logger(name: str = "dlaf") -> Logger:
    lg = _loggers.get(name)
    if lg is None:
        lg = _loggers[name] = Logger(name)
    return lg


def reset_once() -> None:
    """Forget one-shot keys (tests; config cache invalidation)."""
    with _once_lock:
        _once_seen.clear()


def forget_once(logger_name: str, key) -> None:
    """Forget one ``warning_once`` key so the notice can re-announce
    (tests that capture a specific resolution notice)."""
    with _once_lock:
        _once_seen.discard((logger_name, key))


def once_seen_keys(logger_name: str) -> set:
    """Keys ``logger_name`` has already announced (tests: capture the
    pre-state so order-independent cleanup restores exactly it)."""
    with _once_lock:
        return {k for (ln, k) in _once_seen if ln == logger_name}
