"""Merge per-rank observability artifacts into one timeline.

    python -m dlaf_tpu.obs.aggregate rank0.jsonl rank1.jsonl ... \\
        [-o merged.jsonl] [--chrome trace.json] [--top N] [--align] \\
        [--trace <id>] [--top-slow N]

Multi-host runs write one ``DLAF_METRICS_PATH`` artifact per rank (the
``%r`` template — docs/observability.md); this tool merges them and
reports what single-rank summaries cannot see:

* **per-rank skew** — per span name: count/total wall per rank and the
  max-min skew across ranks (the DLA-Future per-rank task-timeline view,
  SURVEY §5: a straggler rank shows up as skew on the collective-bound
  spans);
* **collective imbalance** — per (counter, kind, axis): the per-rank
  count/byte values from each rank's last metrics snapshot and their
  max/min ratio (the ICI byte accounting of arXiv:2112.09017, now
  cross-rank);
* **measured span overlap** — per span name: each rank's share of its
  run wall, the cross-rank aligned fraction (how much of the name's wall
  coincides on all ranks), and the ``*_lookahead`` knob attrs the entry
  spans carried — the measured counterpart of the structural jaxpr pins
  (docs/lookahead.md, docs/comm_overlap.md);
* **accuracy** — per (site, metric): each rank's record count and worst
  ``bound_ratio`` from the merged ``accuracy`` records (the DLAF_ACCURACY
  trail, docs/accuracy.md), nonfinite estimates flagged loudly — a
  corrupted rank tops the table.

``--chrome`` exports the merged spans as Chrome/Perfetto trace events
(``pid`` = rank, host spans nested by time on one track, ``program``
compile events on their own track), so the obs timeline is visually
alignable with a ``DLAF_TRACE_DIR`` device trace in the same viewer.

**Clock caveat**: timestamps are per-host wall clocks. The cross-rank
aligned fractions and the Chrome timeline compare them directly, which
is honest only to the hosts' clock sync (NTP-grade skew ~ms is fine for
the >10 ms spans these artifacts carry; an unsynchronized pod is not).
``--align`` rebases each rank's timeline to its own earliest span start
before analysis/export — inter-host offset drops out, at the cost of
losing true cross-rank start ordering (the ``-o`` merged artifact always
keeps the raw timestamps).

``--trace <id>`` joins ONE request's whole causal chain (ISSUE 13): its
``serve`` request record, the dispatch that served it (via the shared
``span_id``), and every other record stamped with the trace ID —
rendered as the per-request waterfall (queue wait → dispatch compose →
program → fetch → unpad) plus the trace's record inventory.
``--top-slow N`` lists the N worst end-to-end requests with their trace
IDs, the triage entry point into ``--trace``. Both report-only modes
suppress the merge tables. ``scripts/profile_summary.py`` shares the
request-join code here too (:func:`request_rows`,
:func:`format_request_table`) — single owner, not a fork.

``scripts/profile_summary.py`` shares the skew-table code here (not a
fork) for its JSONL mode.
"""

from __future__ import annotations

import json
import os
import re
import sys

from .sinks import read_records

#: Entry-span attrs that select a pipelined program structure; surfaced
#: in the overlap report so "measured under which knobs" is in the table.
KNOB_ATTRS = ("lookahead", "comm_lookahead", "bt_lookahead",
              "dc_level_batch")

_RANK_IN_NAME = re.compile(r"(?:^|[._-])r(\d+)(?=$|[._-])")
#: the sink's unresolved-rank placeholder (``%r`` expanded before any
#: backend existed): ``u<pid>`` in place of the rank digits — matched
#: with or without the conventional literal ``r`` prefix of the
#: ``.r%r.`` template (a bare ``.%r.`` template yields ``.u<pid>.``)
_UNRESOLVED_IN_NAME = re.compile(r"(?:^|[._-])r?u(\d+)(?=$|[._-])")

#: pseudo-rank base for unresolved-rank artifacts: far above any real
#: rank, so pre-init records stay a visibly separate row in every report
#: instead of silently absorbing into whichever real rank shares their
#: argument position.
UNRESOLVED_RANK_BASE = 1_000_000


def infer_rank(path: str, position: int) -> int:
    """Rank for a file whose records carry none: the ``r<N>`` filename
    convention of the ``%r`` template; an unresolved-rank placeholder
    file (``ru<pid>``, written by pre-backend-init records) maps to
    ``UNRESOLVED_RANK_BASE + pid`` — a distinct, visibly-bogus rank —
    and anything else falls back to the argument position."""
    base = os.path.basename(path)
    m = _RANK_IN_NAME.search(base)
    if m:
        return int(m.group(1))
    m = _UNRESOLVED_IN_NAME.search(base)
    if m:
        return UNRESOLVED_RANK_BASE + int(m.group(1))
    return position


def merge_artifacts(paths) -> list:
    """Read + merge artifacts; every record is stamped with its rank
    (its own ``rank`` field when present, else the file's inferred rank)
    and the merged list is ordered by ``ts``. Raises ValueError/OSError
    on an unreadable artifact — a half-merged timeline would lie."""
    merged = []
    for pos, path in enumerate(paths):
        fallback = infer_rank(path, pos)
        for r in read_records(path):
            if isinstance(r, dict):
                r.setdefault("rank", fallback)
                merged.append(r)
    merged.sort(key=lambda r: (r.get("ts") or 0.0))
    return merged


def rebase_per_rank(records) -> list:
    """Shift each rank's records so its earliest SPAN start is t=0 (the
    ``--align`` mode): removes inter-host wall-clock offset from the
    cross-rank overlap/Chrome views at the cost of absolute time and
    true cross-rank start ordering. Returns new record dicts; ranks with
    no spans keep their timestamps."""
    base: dict = {}
    for r in records:
        if r.get("type") == "span":
            start = (r.get("ts") or 0.0) - (r.get("dur_s") or 0.0)
            rank = r.get("rank", 0)
            base[rank] = min(base.get(rank, start), start)
    out = []
    for r in records:
        rank = r.get("rank", 0)
        if rank in base and isinstance(r.get("ts"), (int, float)):
            r = dict(r, ts=r["ts"] - base[rank])
        out.append(r)
    return out


def spans_by_rank(records) -> dict:
    """{rank: [span records]} (spans only)."""
    out: dict = {}
    for r in records:
        if r.get("type") == "span":
            out.setdefault(r.get("rank", 0), []).append(r)
    return out


def rank_skew_rows(records) -> list:
    """Per span name: ``{"name", "per_rank": {rank: {"count", "total"}},
    "skew_s": max-min total across ranks}``, sorted by total wall."""
    per_name: dict = {}
    for rank, spans in spans_by_rank(records).items():
        for s in spans:
            cell = per_name.setdefault(s.get("name", "?"), {}) \
                .setdefault(rank, {"count": 0, "total": 0.0})
            cell["count"] += 1
            cell["total"] += s.get("dur_s", 0.0) or 0.0
    rows = []
    for name, per_rank in per_name.items():
        totals = [c["total"] for c in per_rank.values()]
        rows.append({"name": name, "per_rank": per_rank,
                     "total_s": sum(totals),
                     "skew_s": max(totals) - min(totals)})
    rows.sort(key=lambda row: -row["total_s"])
    return rows


def format_skew_table(rows, top_n: int = 25) -> list:
    """Printable lines for the per-rank skew table (shared with
    ``scripts/profile_summary.py`` — single owner, not a fork)."""
    ranks = sorted({rank for row in rows for rank in row["per_rank"]})
    head = "  ".join(f"r{rank:<2d} total(ms) xN".rjust(18) for rank in ranks)
    lines = [f"{'span':<32s} {head}  {'skew(ms)':>9s}"]
    for row in rows[:top_n]:
        cells = []
        for rank in ranks:
            c = row["per_rank"].get(rank)
            cells.append(f"{c['total'] * 1e3:12.2f} x{c['count']:<4d}"
                         if c else f"{'-':>12s}      ")
        lines.append(f"{row['name'][:32]:<32s} " + "  ".join(cells)
                     + f"  {row['skew_s'] * 1e3:9.2f}")
    return lines


def accuracy_rows(records) -> list:
    """Per (site, metric): per-rank record count, worst (max) finite
    ``bound_ratio``, worst value, and nonfinite count from the merged
    ``accuracy`` records (docs/accuracy.md) — nonfinite-first, then by
    worst ratio, so a corrupted rank tops the table."""
    per: dict = {}
    for r in records:
        if r.get("type") != "accuracy":
            continue
        cell = per.setdefault((r.get("site", "?"), r.get("metric", "?")), {}) \
            .setdefault(r.get("rank", 0),
                        {"count": 0, "worst_ratio": None, "worst_value": None,
                         "nonfinite": 0})
        cell["count"] += 1
        if r.get("nonfinite"):
            cell["nonfinite"] += 1
        for key, field in (("bound_ratio", "worst_ratio"),
                           ("value", "worst_value")):
            v = r.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and (cell[field] is None or v > cell[field]):
                cell[field] = v
    rows = []
    for (site, metric), per_rank in per.items():
        rows.append({
            "site": site, "metric": metric, "per_rank": per_rank,
            "nonfinite": sum(c["nonfinite"] for c in per_rank.values()),
            "worst_ratio": max((c["worst_ratio"] for c in per_rank.values()
                                if c["worst_ratio"] is not None),
                               default=None)})
    rows.sort(key=lambda row: (-row["nonfinite"],
                               -(row["worst_ratio"] or -1.0)))
    return rows


def format_accuracy_table(rows, top_n: int = 25) -> list:
    """Printable lines for the accuracy table (shared with
    ``scripts/profile_summary.py`` — single owner, not a fork)."""
    lines = []
    for row in rows[:top_n]:
        cells = []
        for rank, c in sorted(row["per_rank"].items()):
            if c["nonfinite"]:
                shown = "NONFINITE"
            elif c["worst_ratio"] is not None:
                shown = "%.3g" % c["worst_ratio"]
            elif c["worst_value"] is not None:
                # informational metric (no budget): show the raw value
                shown = "%.3g*" % c["worst_value"]
            else:
                shown = "-"
            cells.append("r%s=%s x%d" % (rank, shown, c["count"]))
        worst = "-" if row["worst_ratio"] is None \
            else "%.3g" % row["worst_ratio"]
        flag = "  !! NONFINITE" if row["nonfinite"] else ""
        lines.append("%s/%s: worst bound_ratio %s  [%s]%s"
                     % (row["site"], row["metric"], worst,
                        " ".join(cells), flag))
    return lines


def autotune_rows(records) -> list:
    """Per route-table site: the ordered decision trail from the merged
    ``autotune`` records (docs/autotune.md) — escalations/exhaustions
    first, then by decision count, so the sites the loop actually moved
    (or failed) top the section."""
    per: dict = {}
    for r in records:
        if r.get("type") != "autotune":
            continue
        site = r.get("site", "?")
        cell = per.setdefault(site, {"decisions": [], "escalations": 0,
                                     "exhausted": 0, "moves": 0})
        cell["decisions"].append(r)
        reason = r.get("reason")
        if reason == "escalate":
            cell["escalations"] += 1
        if reason == "exhausted":
            cell["exhausted"] += 1
        if reason in ("escalate", "relax"):
            cell["moves"] += 1
    rows = []
    for site, cell in per.items():
        last = cell["decisions"][-1]
        rows.append({"site": site, "decisions": cell["decisions"],
                     "count": len(cell["decisions"]),
                     "escalations": cell["escalations"],
                     "exhausted": cell["exhausted"],
                     "moves": cell["moves"],
                     "final_rung": last.get("rung_new"),
                     "final_reason": last.get("reason"),
                     "final_route": last.get("route_new")})
    rows.sort(key=lambda row: (-row["exhausted"], -row["escalations"],
                               -row["count"], row["site"]))
    return rows


def format_autotune_trail(rows, top_n: int = 10,
                          trail_n: int = 6) -> list:
    """Printable lines for the autotune decision-trail section (shared
    with ``scripts/profile_summary.py`` — single owner, not a fork):
    one summary line per site plus its last ``trail_n`` decisions."""
    lines = []
    for row in rows[:top_n]:
        flag = "  !! EXHAUSTED" if row["exhausted"] else ""
        route = row["final_route"] or {}
        route_s = " ".join(f"{k}={v}" for k, v in sorted(route.items())) \
            or "default"
        lines.append(
            "%s: %d decision(s), %d move(s), %d escalation(s); final "
            "rung %s (%s) via %s%s"
            % (row["site"], row["count"], row["moves"],
               row["escalations"], row["final_rung"], route_s,
               row["final_reason"], flag))
        for r in row["decisions"][-trail_n:]:
            probe = ("NONFINITE" if r.get("nonfinite")
                     else ("%.3g" % r["probe"]
                           if isinstance(r.get("probe"), (int, float))
                           else "-"))
            lines.append("  %-9s rung %s -> %s  probe %s"
                         % (r.get("reason"), r.get("rung_old"),
                            r.get("rung_new"), probe))
    return lines


#: Waterfall stage order: queue wait from the request record, then the
#: dispatch record's ``stages`` object (serve/queue.py emits them).
WATERFALL_STAGES = (("queue wait", None), ("compose", "compose_s"),
                    ("program", "program_s"), ("fetch", "fetch_s"),
                    ("unpad", "unpad_s"))


def request_rows(records) -> list:
    """Per-request rows joined across the trace convention (ISSUE 13):
    each ``serve`` request record, with the stage timings of the
    dispatch record sharing its ``span_id``. Sorted worst end-to-end
    latency first — the ``--top-slow`` order."""
    dispatches = {}
    for r in records:
        if r.get("type") == "serve" and r.get("event") == "dispatch" \
                and isinstance(r.get("span_id"), str):
            dispatches[r["span_id"]] = r
    rows = []
    for r in records:
        if r.get("type") != "serve" or r.get("event") != "request":
            continue
        d = dispatches.get(r.get("span_id"))
        rows.append({
            "trace_id": r.get("trace_id"),
            "span_id": r.get("span_id"),
            "rank": r.get("rank", 0),
            "op": r.get("op", "?"),
            "n": r.get("n"),
            "bucket_n": r.get("bucket_n"),
            "dtype": r.get("dtype", "?"),
            "queue_s": r.get("queue_s", 0.0) or 0.0,
            "total_s": r.get("total_s", 0.0) or 0.0,
            "stages": (d or {}).get("stages"),
            "dispatch_s": (d or {}).get("dispatch_s"),
            "lanes": (d or {}).get("lanes"),
        })
    rows.sort(key=lambda row: -row["total_s"])
    return rows


def _stage_values(row) -> list:
    """``[(label, seconds)]`` for one request row's waterfall."""
    out = [("queue wait", row["queue_s"])]
    for label, key in WATERFALL_STAGES[1:]:
        v = (row.get("stages") or {}).get(key)
        if isinstance(v, (int, float)):
            out.append((label, float(v)))
    return out


def format_request_table(rows, top_n: int = 5) -> list:
    """Printable lines for the slowest-requests table (shared with
    ``scripts/profile_summary.py`` — single owner, not a fork): one line
    per request, total + stage breakdown + trace ID."""
    lines = []
    for row in rows[:top_n]:
        stages = " | ".join(f"{label} {v * 1e3:.2f}"
                            for label, v in _stage_values(row))
        tid = row["trace_id"] if isinstance(row["trace_id"], str) \
            else "-"
        lines.append(f"{row['total_s'] * 1e3:10.2f} ms  {row['op']:<9s}"
                     f" n={row['n']}/{row['bucket_n']}  ({stages})"
                     f"  trace {tid}")
    return lines


def format_waterfall(row, width: int = 40) -> list:
    """The per-request waterfall: one bar-chart line per stage, scaled
    to the request's end-to-end wall."""
    total = max(row["total_s"], 1e-12)
    lines = [f"request: op={row['op']} n={row['n']} "
             f"bucket={row['bucket_n']} dtype={row['dtype']} "
             f"rank={row['rank']} lanes={row.get('lanes')}  "
             f"total {row['total_s'] * 1e3:.2f} ms"]
    for label, v in _stage_values(row):
        bar = "#" * max(int(round(width * v / total)), 1 if v > 0 else 0)
        lines.append(f"  {label:<12s} {v * 1e3:10.3f} ms  {bar}")
    if row.get("stages") is None:
        lines.append("  (no dispatch stage record joined — span_id "
                     "missing or dispatch record not in this artifact)")
    return lines


def trace_report(records, trace_id: str) -> list:
    """Printable report for ONE trace ID: the request waterfall(s) plus
    an inventory of every record stamped with the ID (request-scoped
    string match or batch-scope list membership). Empty list = the ID
    appears nowhere."""
    from .context import trace_matches

    matched = [r for r in records
               if isinstance(r, dict) and trace_matches(r, trace_id)]
    if not matched:
        return []
    lines = [f"== trace {trace_id}: {len(matched)} records =="]
    rows = [row for row in request_rows(matched)
            if row["trace_id"] == trace_id]
    for row in rows:
        lines.extend(format_waterfall(row))
    lines.append("records on this trace:")
    for r in matched:
        rtype = r.get("type", "?")
        what = r.get("name") or r.get("site") or r.get("op") or ""
        event = r.get("event") or r.get("metric") or ""
        scope = "batch" if isinstance(r.get("trace_id"), list) else "request"
        lines.append(f"  {rtype:<14s} {what:<24s} {event:<12s} "
                     f"[{scope} scope, rank {r.get('rank', 0)}]")
    return lines


def devtrace_rows(records) -> list:
    """Printable lines for any ``devtrace``/``measured_overlap`` records
    riding in the merged artifact (:mod:`dlaf_tpu.obs.devtrace` writes
    them; the full report lives in that CLI — this is the merge view)."""
    lines = []
    for r in records:
        if r.get("type") == "devtrace":
            lines.append(
                f"trace {r.get('trace', '?')}: device busy "
                f"{(r.get('device_busy_s') or 0.0) * 1e3:.2f} ms, "
                f"coverage {(r.get('coverage') or 0.0) * 100:.1f}% "
                f"(join={r.get('join', '?')}, rank {r.get('rank', 0)})")
    for r in records:
        if r.get("type") == "measured_overlap":
            lines.append(
                f"  {r.get('algo', '?')}/{r.get('axis', '?')}: "
                f"{(r.get('overlap_frac') or 0.0) * 100:.1f}% of "
                f"{(r.get('collective_s') or 0.0) * 1e3:.2f} ms "
                "collective time MXU-overlapped")
    return lines


def collective_imbalance(records) -> list:
    """Cross-rank imbalance of the collective counters: for each
    (counter name, kind, axis) in each rank's LAST metrics snapshot,
    the per-rank values and max/min ratio. Sorted by ratio."""
    last_snap: dict = {}
    for r in records:
        if r.get("type") == "metrics":
            last_snap[r.get("rank", 0)] = r       # ts-ordered: last wins
    per_key: dict = {}
    for rank, snap in last_snap.items():
        for m in snap.get("metrics") or []:
            if not isinstance(m, dict) or m.get("kind") != "counter":
                continue
            name = m.get("name", "")
            if "comm_collective" not in name:
                continue
            labels = m.get("labels") or {}
            key = (name, labels.get("kind", "?"), labels.get("axis", "?"))
            per_key.setdefault(key, {})[rank] = m.get("value", 0.0)
    rows = []
    for (name, kind, axis), per_rank in per_key.items():
        vals = list(per_rank.values())
        lo, hi = min(vals), max(vals)
        rows.append({"name": name, "kind": kind, "axis": axis,
                     "per_rank": per_rank,
                     "ratio": (hi / lo) if lo > 0 else float("inf")})
    rows.sort(key=lambda row: -row["ratio"])
    return rows


def _intervals(spans):
    """[(start, end)] per span list (ts is stamped at exit)."""
    out = []
    for s in spans:
        end = s.get("ts") or 0.0
        dur = s.get("dur_s") or 0.0
        out.append((end - dur, end))
    return sorted(out)


def _union(intervals):
    merged = []
    for lo, hi in intervals:
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    return merged


def overlap_report(records) -> dict:
    """Measured span overlap across the merged timeline.

    Per rank: run wall (first span start to last span end) and each span
    name's share of it. Per span name on >= 2 ranks: the cross-rank
    *aligned* fraction — |intersection of the name's union-intervals
    across ranks| / max per-rank total. Plus the ``*_lookahead``-family
    knob attrs the spans carried, so the numbers are attributable to a
    program structure.

    Cross-rank fractions compare per-host wall clocks directly; for
    hosts without NTP-grade sync, rebase first (:func:`rebase_per_rank`,
    the CLI's ``--align``)."""
    by_rank = spans_by_rank(records)
    per_rank_wall = {}
    name_intervals: dict = {}
    knobs: dict = {}
    for rank, spans in by_rank.items():
        iv = _intervals(spans)
        # wall = earliest start to LATEST END — not the end of the
        # latest-starting span (a nested step span inside a long entry
        # span would otherwise understate the wall and inflate shares)
        per_rank_wall[rank] = (max(hi for _, hi in iv)
                               - min(lo for lo, _ in iv)) if iv else 0.0
        for s in spans:
            end = s.get("ts") or 0.0
            dur = s.get("dur_s") or 0.0
            name_intervals.setdefault(s.get("name", "?"), {}) \
                .setdefault(rank, []).append((end - dur, end))
            attrs = s.get("attrs") or {}
            for k in KNOB_ATTRS:
                if k in attrs:
                    knobs.setdefault(k, set()).add(attrs[k])
    aligned = {}
    for name, per_rank in name_intervals.items():
        if len(per_rank) < 2:
            continue
        unions = [_union(sorted(iv)) for iv in per_rank.values()]
        inter = unions[0]
        for u in unions[1:]:
            inter = _intersect(inter, u)
        inter_len = sum(hi - lo for lo, hi in inter)
        denom = max(sum(hi - lo for lo, hi in u) for u in unions)
        aligned[name] = inter_len / denom if denom > 0 else 0.0
    shares = {}
    for name, per_rank in name_intervals.items():
        tot = {rank: sum(hi - lo for lo, hi in iv)
               for rank, iv in per_rank.items()}
        shares[name] = {rank: (tot[rank] / per_rank_wall[rank]
                               if per_rank_wall.get(rank) else 0.0)
                        for rank in tot}
    return {"rank_wall_s": per_rank_wall, "share": shares,
            "aligned": aligned,
            "knobs": {k: sorted(v) for k, v in knobs.items()}}


def _intersect(a, b):
    out, i, j = [], 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out.append((lo, hi))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def chrome_trace(records) -> dict:
    """Merged records as Chrome trace-event JSON: one process per rank
    (``pid`` = rank), host spans on track 0 (nested by time), program
    compile events on track 1. Times are microseconds relative to the
    earliest span start, the format's convention."""
    events = []
    starts = []
    for r in records:
        if r.get("type") == "span":
            starts.append((r.get("ts") or 0.0) - (r.get("dur_s") or 0.0))
        elif r.get("type") == "program" and r.get("event") == "compile":
            dur = (r.get("compile_s") or 0.0) + (r.get("trace_s") or 0.0)
            starts.append((r.get("ts") or 0.0) - dur)
    t0 = min(starts) if starts else 0.0
    ranks = sorted({r.get("rank", 0) for r in records})
    for rank in ranks:
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        events.append({"ph": "M", "name": "process_sort_index", "pid": rank,
                       "args": {"sort_index": rank}})
        events.append({"ph": "M", "name": "thread_name", "pid": rank,
                       "tid": 0, "args": {"name": "host spans"}})
        events.append({"ph": "M", "name": "thread_name", "pid": rank,
                       "tid": 1, "args": {"name": "program compiles"}})
    for r in records:
        rank = r.get("rank", 0)
        if r.get("type") == "span":
            dur = r.get("dur_s") or 0.0
            start = (r.get("ts") or 0.0) - dur
            args = dict(r.get("attrs") or {})
            args["depth"] = r.get("depth")
            if r.get("gflops") is not None:
                args["gflops"] = r["gflops"]
            events.append({"ph": "X", "name": r.get("name", "?"),
                           "pid": rank, "tid": 0,
                           "ts": (start - t0) * 1e6, "dur": dur * 1e6,
                           "args": args})
        elif r.get("type") == "program" and r.get("event") == "compile":
            dur = (r.get("compile_s") or 0.0) + (r.get("trace_s") or 0.0)
            start = (r.get("ts") or 0.0) - dur
            events.append({"ph": "X",
                           "name": f"compile {r.get('site', '?')}",
                           "pid": rank, "tid": 1,
                           "ts": (start - t0) * 1e6, "dur": dur * 1e6,
                           "args": {"compile_s": r.get("compile_s"),
                                    "trace_s": r.get("trace_s"),
                                    "hbm": r.get("hbm")}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = chrome_path = None
    top_n = 25
    align = False
    trace_id = None
    top_slow = None
    paths = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-o":
            i += 1
            out_path = argv[i] if i < len(argv) else None
        elif a == "--chrome":
            i += 1
            chrome_path = argv[i] if i < len(argv) else None
        elif a == "--top":
            i += 1
            try:
                top_n = int(argv[i]) if i < len(argv) else top_n
            except ValueError:
                print(__doc__, file=sys.stderr)
                return 2
        elif a == "--trace":
            i += 1
            trace_id = argv[i] if i < len(argv) else None
        elif a == "--top-slow":
            i += 1
            try:
                top_slow = int(argv[i]) if i < len(argv) else None
            except ValueError:
                print(__doc__, file=sys.stderr)
                return 2
        elif a == "--align":
            align = True
        elif a.startswith("-"):
            print(__doc__, file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1
    if not paths or (out_path is None and "-o" in argv) \
            or (chrome_path is None and "--chrome" in argv) \
            or (trace_id is None and "--trace" in argv) \
            or (top_slow is None and "--top-slow" in argv) \
            or (top_slow is not None and top_slow < 1):
        print(__doc__, file=sys.stderr)
        return 2
    try:
        records = merge_artifacts(paths)
    except (OSError, ValueError) as e:
        print(f"aggregate: {e}", file=sys.stderr)
        return 1
    if not records:
        print("aggregate: no records in any artifact", file=sys.stderr)
        return 1
    if trace_id is not None:
        # report-only mode: one request's causal chain (ISSUE 13)
        lines = trace_report(records, trace_id)
        if not lines:
            print(f"aggregate: trace {trace_id!r} appears in no record",
                  file=sys.stderr)
            return 1
        for line in lines:
            print(line)
        return 0
    if top_slow is not None:
        rows = request_rows(records)
        if not rows:
            print("aggregate: no serve request records to rank",
                  file=sys.stderr)
            return 1
        print(f"== top {min(top_slow, len(rows))} slowest requests "
              f"(of {len(rows)}) ==")
        for line in format_request_table(rows, top_slow):
            print(f"  {line}")
        return 0
    ranks = sorted({r.get("rank", 0) for r in records})
    print(f"== merged {len(records)} records from {len(paths)} artifact(s), "
          f"ranks {ranks}{' (per-rank aligned timelines)' if align else ''}"
          " ==")
    # --align: reports + chrome view per-rank-rebased timelines; the -o
    # merged artifact below always keeps the raw timestamps
    view = rebase_per_rank(records) if align else records

    rows = rank_skew_rows(view)
    if rows:
        print("\n== per-rank span skew ==")
        for line in format_skew_table(rows, top_n):
            print(f"  {line}")

    acc = accuracy_rows(view)
    if acc:
        print("\n== accuracy (worst bound_ratio per rank; docs/accuracy.md)"
              " ==")
        for line in format_accuracy_table(acc, top_n):
            print(f"  {line}")

    atn = autotune_rows(view)
    if atn:
        print("\n== autotune decision trail (docs/autotune.md) ==")
        for line in format_autotune_trail(atn, top_n):
            print(f"  {line}")

    imb = collective_imbalance(view)
    if imb:
        print("\n== collective imbalance (last snapshot per rank) ==")
        for row in imb[:top_n]:
            per = " ".join(f"r{rank}={int(v)}" for rank, v in
                           sorted(row["per_rank"].items()))
            ratio = "inf" if row["ratio"] == float("inf") \
                else f"{row['ratio']:.3f}"
            print(f"  {row['name']}{{kind={row['kind']},axis={row['axis']}}}"
                  f": {per}  max/min={ratio}")

    dt = devtrace_rows(view)
    if dt:
        print("\n== device-timeline attribution (obs.devtrace) ==")
        for line in dt:
            print(f"  {line}")

    ov = overlap_report(view)
    if ov["rank_wall_s"]:
        print("\n== measured span overlap ==")
        for rank in sorted(ov["rank_wall_s"]):
            print(f"  rank {rank}: wall {ov['rank_wall_s'][rank] * 1e3:.2f}"
                  " ms")
        for name, share in sorted(ov["share"].items()):
            per = " ".join(f"r{rank}={s * 100:.1f}%" for rank, s in
                           sorted(share.items()))
            al = (f"  aligned={ov['aligned'][name] * 100:.1f}%"
                  if name in ov["aligned"] else "")
            print(f"  {name}: share {per}{al}")
        if ov["knobs"]:
            knobs = " ".join(f"{k}={v}" for k, v in
                             sorted(ov["knobs"].items()))
            print(f"  knob attrs seen: {knobs}")

    if out_path:
        with open(out_path, "w") as f:
            for r in records:
                f.write(json.dumps(r, default=str) + "\n")
        print(f"\nmerged artifact: {out_path}")
    if chrome_path:
        with open(chrome_path, "w") as f:
            json.dump(chrome_trace(view), f)
        print(f"chrome trace: {chrome_path} (open in ui.perfetto.dev or "
              "chrome://tracing)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
