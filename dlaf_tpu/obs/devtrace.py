"""Device-timeline attribution from profiler traces (ISSUE 14).

Every overlap claim so far is structural (jaxpr pins: the hoisted
collective programs were *emitted*) and every MFU number is modeled
(``scripts/mfu_table.py`` rooflines) or wall-clock-derived. This module
reads the artifact a ``DLAF_TRACE_DIR`` run already lands — the
``plugins/profile/<ts>/*.trace.json.gz`` Chrome trace the span tracer
writes via ``create_perfetto_trace`` — and turns it into *measured*
per-phase device facts:

* **op classification** — every device-track interval is classified from
  its XLA op name (:func:`classify_op`): MXU work (``dot``/``conv``/
  solver ops and the fusions that contain one), collectives by kind
  (``all-reduce``, ``all-gather``, ``all-to-all``, ``collective-permute``,
  ``reduce-scatter``, ...), data movement (copies, transposes, slices),
  host callbacks (``custom-call``/infeed/outfeed), and residual
  elementwise compute. Device events are recognized by their
  ``hlo_op``/``hlo_module`` args (XLA:CPU thunk events) or by a
  ``/device:`` process name (TPU traces); profiler-infrastructure
  events (``ThunkExecutor::...``) are never ops.
* **phase join** — device intervals are attributed to algorithm phases
  through the host-span windows: the ``jax.profiler.TraceAnnotation``
  mirrors of the JSONL span records live on the host threads of the SAME
  trace clock, so the join needs no cross-clock arithmetic. The merged
  ``DLAF_METRICS_PATH`` artifact supplies the span-name *vocabulary*
  (host threads also carry thousands of jax-internal events — ``dce``,
  ``cholesky_expander`` — that must not become phases), the flop models,
  and the knob attrs. When a trace carries no annotation mirrors
  (third-party traces), the fallback join rebases the JSONL spans with
  :func:`dlaf_tpu.obs.aggregate.rebase_per_rank` (the ``--align``
  machinery) and the device events to the trace origin, matching windows
  on the rebased clocks.
* **measured overlap** — per attributed phase (``algo``): the fraction
  of collective device time that coincides with MXU-busy time in the
  same overlap domain (one device = one trace process on TPU, one
  executor thread on XLA:CPU — CPU thunks run serially, so CPU CI pins
  report *structure*: finite fractions, coverage, schema). The ``axis``
  field is ``"all"``: a Chrome trace carries no replica-group metadata,
  so the per-mesh-axis split of the ``dlaf_comm_overlapped_total``
  trace-time counters is not recoverable here (documented in
  docs/observability.md).
* **measured MFU** — entry-span flop models joined to the phase's
  attributed device-busy wall (union across tracks): the denominator of
  ``scripts/mfu_table.py --measured``, device time instead of host wall.

Two JSONL record types land in the schema (:mod:`dlaf_tpu.obs.sinks`):
one ``devtrace`` summary (per-phase busy walls, attribution coverage)
and one ``measured_overlap`` record per (algo, axis) with positive
attributed collective time. ``python -m dlaf_tpu.obs.validate
--require-devtrace`` gates on them: >= 1 finite ``measured_overlap``
record with positive collective time, coverage >=
:data:`~dlaf_tpu.obs.sinks.DEVTRACE_COVERAGE_FLOOR`, no NaN walls — an
artifact whose trace attributed ZERO collectives must be rejected, not
scraped as "overlap measured".

CLI::

    python -m dlaf_tpu.obs.devtrace <trace.json[.gz] | profile_dir> \\
        merged.r0.jsonl [more.jsonl ...] [-o enriched.jsonl] \\
        [--json report.json] [--distill small.trace.json.gz] [--top N]

Prints the attribution report; ``-o`` writes the input records plus the
new ``devtrace``/``measured_overlap`` records (the enriched artifact
``scripts/perf_diff.py`` diffs); ``--distill`` writes a reduced trace
(metadata + device ops + span-window host events only) — the committed
fixture convention under ``tests/fixtures/devtrace/``, small enough for
git, replayable without hardware. ``scripts/profile_summary.py``'s
trace mode routes through this module (:func:`newest_trace`,
:func:`track_tables`) — single parser owner, not a fork.

Exit status: 0 = report produced; 1 = unreadable trace/artifact or a
trace with no device op events (an empty attribution must fail loudly);
2 = usage.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys
import time

#: Collective op-name prefixes -> kind label (XLA HLO spelling; checked
#: before every other category so ``all-gather`` never classifies as a
#: data-movement ``gather``).
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "all-to-all",
                    "reduce-scatter", "collective-permute",
                    "collective-broadcast", "send", "recv")

#: Name tokens that mark MXU work (dots, convolutions, the solver ops,
#: and any fusion whose name embeds one — XLA names fusions after their
#: constituent ops, e.g. ``bitcast_dot_fusion.1``).
MXU_TOKENS = ("dot", "conv", "cholesky", "triangular-solve", "einsum")

#: Name tokens for data movement (copies/layout changes). ``slice``
#: covers ``dynamic-slice`` and ``dynamic-update-slice``.
COPY_TOKENS = ("copy", "transpose", "bitcast", "slice", "concatenate",
               "gather", "scatter", "broadcast", "reshape", "pad")

#: Name tokens for host round trips.
HOST_TOKENS = ("custom-call", "infeed", "outfeed", "host-")

#: Classification categories, display order.
CATEGORIES = ("mxu", "collective", "copy", "host_callback", "compute")


def classify_op(name: str):
    """``(category, kind)`` for one XLA op name — ``kind`` is the
    collective kind for collectives, None otherwise. Returns ``(None,
    None)`` for profiler-infrastructure events (``::``-qualified C++
    names, spaced descriptions) that are not ops."""
    if not name or "::" in name or " " in name:
        return None, None
    base = name.split(".")[0]
    for kind in COLLECTIVE_KINDS:
        if base.startswith(kind) or f"_{kind}" in base:
            return "collective", kind
    for tok in HOST_TOKENS:
        if tok in base:
            return "host_callback", None
    for tok in MXU_TOKENS:
        if tok in base:
            return "mxu", None
    for tok in COPY_TOKENS:
        if tok in base:
            return "copy", None
    return "compute", None


def newest_trace(root: str) -> str:
    """Newest ``*.trace.json.gz`` under ``root`` (the
    ``plugins/profile/<ts>/`` discovery convention of a
    ``DLAF_TRACE_DIR`` run). Prefers the Chrome trace over the perfetto
    one at equal recency (both carry the events; the Chrome one names
    processes in metadata events). Single owner — the
    ``scripts/profile_summary.py`` copy now lives here."""
    cands = sorted(
        glob.glob(os.path.join(root, "**", "*.trace.json.gz"),
                  recursive=True) +
        glob.glob(os.path.join(root, "**", "perfetto_trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime)
    if not cands:
        raise SystemExit(f"no *.trace.json.gz under {root}")
    chrome = [c for c in cands if not c.endswith("perfetto_trace.json.gz")]
    return (chrome or cands)[-1]


def load_trace(path: str) -> list:
    """Trace events from a Chrome trace file (gzipped or plain JSON; a
    directory is resolved through :func:`newest_trace`)."""
    if os.path.isdir(path):
        path = newest_trace(path)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


def _meta_maps(events):
    """(process names by pid, thread names by (pid, tid))."""
    procs, threads = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e.get("pid")] = (e.get("args") or {}).get("name", "")
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = \
                (e.get("args") or {}).get("name", "")
    return procs, threads


def _is_device_event(e, procs) -> bool:
    """A device-op interval: carries the XLA ``hlo_op``/``hlo_module``
    args (XLA:CPU thunk events) or lives on a ``/device:`` process
    (TPU traces)."""
    args = e.get("args") or {}
    if "hlo_op" in args or "hlo_module" in args:
        return True
    return str(procs.get(e.get("pid"), "")).startswith("/device:")


def device_events(events) -> list:
    """Classified device intervals: ``(start_us, end_us, category, kind,
    name, domain)`` for every complete (``ph == "X"``) device-op event.
    ``domain`` is the overlap domain — the process for ``/device:``
    tracks (a TPU device's streams overlap each other), the single
    executor thread on a host-process trace (XLA:CPU runs one virtual
    device per thread, serially)."""
    procs, _ = _meta_maps(events)
    out = []
    for e in events:
        if e.get("ph") != "X" or not _is_device_event(e, procs):
            continue
        cat, kind = classify_op(e.get("name", ""))
        if cat is None:
            continue
        start = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0) or 0.0)
        pid = e.get("pid")
        domain = pid if str(procs.get(pid, "")).startswith("/device:") \
            else (pid, e.get("tid"))
        out.append((start, start + dur, cat, kind, e.get("name", "?"),
                    domain))
    return out


def host_span_events(events, span_names) -> list:
    """``(start_us, end_us, name)`` for host-thread events whose names
    are in the JSONL span vocabulary — the TraceAnnotation mirrors that
    become phase windows. Host threads carry thousands of jax-internal
    events (``dce``, ``cholesky_expander``); only the vocabulary match
    keeps them out of the phase set."""
    procs, _ = _meta_maps(events)
    names = set(span_names)
    out = []
    for e in events:
        if e.get("ph") != "X" or e.get("name") not in names \
                or _is_device_event(e, procs):
            continue
        start = float(e.get("ts", 0.0))
        out.append((start, start + float(e.get("dur", 0.0) or 0.0),
                    e.get("name")))
    return out


def _union(intervals):
    """Union length-preserving merge of ``[(lo, hi)]`` (sorted input not
    required)."""
    merged = []
    for lo, hi in sorted(intervals):
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    return merged


def _intersect_len(a_sorted_union, b_sorted_union) -> float:
    out, i, j = 0.0, 0, 0
    a, b = a_sorted_union, b_sorted_union
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _fallback_windows(records, devs) -> list:
    """Phase windows when the trace carries no annotation mirrors:
    JSONL spans rebased per rank (the ``--align`` machinery of
    :mod:`dlaf_tpu.obs.aggregate`) onto the device-event origin —
    inter-clock offset drops out, honest to within dispatch skew."""
    from .aggregate import rebase_per_rank

    if not devs:
        return []
    t0 = min(lo for lo, *_ in devs)
    out = []
    for r in rebase_per_rank(records):
        if r.get("type") != "span":
            continue
        end = (r.get("ts") or 0.0) * 1e6 + t0
        dur = (r.get("dur_s") or 0.0) * 1e6
        out.append((end - dur, end, r.get("name", "?")))
    return out


def attribute(events, records) -> dict:
    """The attribution report joining one trace to one merged artifact.

    Returns::

        {"device_busy_s", "attributed_s", "coverage", "events",
         "domains", "join",                       # "annotation"|"rebase"
         "categories": {cat: seconds},            # whole-trace totals
         "phases": {name: {"busy_s",              # sum over tracks
                           "wall_s",              # union across tracks
                           "categories": {cat: s},
                           "flops", "measured_gflops"}},  # when modeled
         "overlap": [{"algo", "axis", "collective_s", "overlapped_s",
                      "overlap_frac", "mxu_busy_s",
                      "kinds": {kind: s}}, ...],
         "knobs": {attr: [values]}}

    ``coverage`` = attributed device busy / total device busy — the
    floor ``--require-devtrace`` enforces. Raises ValueError when the
    trace carries no device op events (an empty attribution must fail
    loudly, not report 100 % of nothing)."""
    devs = device_events(events)
    if not devs or not any(hi > lo for lo, hi, *_ in devs):
        # zero-duration-only traces would divide coverage by zero below;
        # both shapes mean the same thing — nothing to attribute
        raise ValueError("trace contains no device op events with "
                         "duration (hlo_op-tagged or /device:-track "
                         "intervals)")
    spans = [r for r in records if isinstance(r, dict)
             and r.get("type") == "span"]
    span_names = {s.get("name", "?") for s in spans}
    windows = host_span_events(events, span_names)
    join = "annotation"
    if not windows:
        windows = _fallback_windows(records, devs)
        join = "rebase"
    # innermost-wins attribution by sweep: device events visited in
    # midpoint order, windows activated by start and expired lazily, so
    # the join costs O((E + W) log E + E * nesting depth) instead of the
    # O(E x W) per-event scan (a raw miniapp trace is ~1e5-1e6 events)
    win_sorted = sorted(windows)
    order = sorted(range(len(devs)),
                   key=lambda i: devs[i][0] + devs[i][1])
    phase_by_event = [None] * len(devs)
    active: list = []
    wi = 0
    for i in order:
        mid = (devs[i][0] + devs[i][1]) / 2.0
        while wi < len(win_sorted) and win_sorted[wi][0] <= mid:
            active.append(win_sorted[wi])
            wi += 1
        if any(whi < mid for _, whi, _ in active):
            active = [w for w in active if w[1] >= mid]
        best = None
        for wlo, whi, wname in active:
            if wlo <= mid <= whi and (
                    best is None or whi - wlo < best[1] - best[0]):
                best = (wlo, whi, wname)
        if best is not None:
            phase_by_event[i] = best[2]

    total_busy = 0.0
    attributed = 0.0
    cat_totals = collections.Counter()
    phases: dict = {}
    mxu_by_domain: dict = {}
    coll_by_phase: dict = {}
    for i, (lo, hi, cat, kind, name, domain) in enumerate(devs):
        dur = (hi - lo) / 1e6
        total_busy += dur
        cat_totals[cat] += dur
        if cat == "mxu":
            mxu_by_domain.setdefault(domain, []).append((lo, hi))
        phase = phase_by_event[i]
        if phase is None:
            continue
        attributed += dur
        cell = phases.setdefault(phase, {"busy_s": 0.0, "_ivs": [],
                                         "categories":
                                             collections.Counter()})
        cell["busy_s"] += dur
        cell["_ivs"].append((lo, hi))
        cell["categories"][cat] += dur
        if cat == "collective":
            coll_by_phase.setdefault(phase, []).append(
                (lo, hi, kind, domain))
    for cell in phases.values():
        cell["wall_s"] = sum(hi - lo for lo, hi in
                             _union(cell.pop("_ivs"))) / 1e6
        cell["categories"] = dict(cell["categories"])
    # measured MFU: flop-modeled span names -> device busy wall
    flops_by_name = collections.Counter()
    for s in spans:
        f = s.get("flops")
        if isinstance(f, (int, float)) and not isinstance(f, bool) \
                and s.get("name") in phases:
            flops_by_name[s["name"]] += float(f)
    for name, f in flops_by_name.items():
        cell = phases[name]
        cell["flops"] = f
        if cell["wall_s"] > 0:
            cell["measured_gflops"] = f / cell["wall_s"] / 1e9
    # measured overlap per attributed phase: collective time coinciding
    # with MXU-busy time in the same overlap domain
    mxu_union = {d: _union(iv) for d, iv in mxu_by_domain.items()}
    overlap = []
    for phase, colls in sorted(coll_by_phase.items()):
        coll_s = sum(hi - lo for lo, hi, _, _ in colls) / 1e6
        if coll_s <= 0:
            continue
        overlapped = 0.0
        kinds = collections.Counter()
        for lo, hi, kind, domain in colls:
            kinds[kind] += (hi - lo) / 1e6
            overlapped += _intersect_len([(lo, hi)],
                                         mxu_union.get(domain, []))
        overlapped_s = min(overlapped / 1e6, coll_s)
        overlap.append({
            "algo": phase, "axis": "all",
            "collective_s": coll_s, "overlapped_s": overlapped_s,
            "overlap_frac": overlapped_s / coll_s,
            # phase-scoped like every sibling field (the MXU time
            # attributed to THIS phase), not the trace-global union —
            # overlapped_s / mxu_busy_s must be a meaningful ratio
            "mxu_busy_s": phases[phase]["categories"].get("mxu", 0.0),
            "kinds": dict(kinds)})
    from .aggregate import KNOB_ATTRS

    knobs: dict = {}
    for s in spans:
        for k in KNOB_ATTRS:
            if k in (s.get("attrs") or {}):
                knobs.setdefault(k, set()).add(s["attrs"][k])
    return {
        "device_busy_s": total_busy,
        "attributed_s": attributed,
        "coverage": attributed / total_busy,
        "events": len(devs),
        "domains": len({d for *_, d in devs}),
        "join": join,
        "categories": dict(cat_totals),
        "phases": phases,
        "overlap": overlap,
        "knobs": {k: sorted(v) for k, v in knobs.items()},
    }


def records_from_report(report: dict, trace: str) -> list:
    """The JSONL records the report lands as (schema:
    :mod:`dlaf_tpu.obs.sinks`): one ``devtrace`` summary plus one
    ``measured_overlap`` record per (algo, axis) with positive
    attributed collective time — a zero-collective attribution emits NO
    overlap record, which is exactly what ``--require-devtrace``
    rejects."""
    from .sinks import SCHEMA_VERSION

    ts = time.time()
    phases = {}
    for name, cell in report["phases"].items():
        out = {"busy_s": cell["busy_s"], "wall_s": cell["wall_s"],
               "categories": cell["categories"]}
        for key in ("flops", "measured_gflops"):
            if key in cell:
                out[key] = cell[key]
        phases[name] = out
    recs = [{
        "v": SCHEMA_VERSION, "type": "devtrace", "ts": ts,
        "trace": os.path.basename(trace),
        "device_busy_s": report["device_busy_s"],
        "attributed_s": report["attributed_s"],
        "coverage": report["coverage"],
        "join": report["join"],
        "phases": phases,
        "attrs": {"events": report["events"],
                  "domains": report["domains"],
                  "knobs": report["knobs"]},
    }]
    for row in report["overlap"]:
        recs.append({
            "v": SCHEMA_VERSION, "type": "measured_overlap", "ts": ts,
            "algo": row["algo"], "axis": row["axis"],
            "collective_s": row["collective_s"],
            "overlapped_s": row["overlapped_s"],
            "overlap_frac": row["overlap_frac"],
            "mxu_busy_s": row["mxu_busy_s"],
            "kinds": row["kinds"],
            "attrs": {"trace": os.path.basename(trace)},
        })
    return recs


def format_report(report: dict, top_n: int = 25) -> list:
    """Printable lines for one attribution report."""
    lines = [
        f"device busy {report['device_busy_s'] * 1e3:.2f} ms over "
        f"{report['events']} op events, {report['domains']} domain(s); "
        f"attributed {report['attributed_s'] * 1e3:.2f} ms "
        f"(coverage {report['coverage'] * 100:.1f}%, "
        f"join={report['join']})"]
    cats = " ".join(f"{c}={report['categories'].get(c, 0.0) * 1e3:.2f}ms"
                    for c in CATEGORIES if c in report["categories"])
    lines.append(f"by category: {cats}")
    ranked = sorted(report["phases"].items(),
                    key=lambda kv: -kv[1]["busy_s"])[:top_n]
    for name, cell in ranked:
        cats = " ".join(f"{c}={cell['categories'].get(c, 0.0) * 1e3:.2f}"
                        for c in CATEGORIES if c in cell["categories"])
        mfu = (f"  measured {cell['measured_gflops']:.2f} GF/s (device)"
               if "measured_gflops" in cell else "")
        lines.append(f"  {cell['busy_s'] * 1e3:10.2f} ms busy  "
                     f"wall {cell['wall_s'] * 1e3:10.2f} ms  "
                     f"{name}  [{cats}]{mfu}")
    for row in report["overlap"]:
        kinds = " ".join(f"{k}={v * 1e3:.2f}ms"
                         for k, v in sorted(row["kinds"].items()))
        lines.append(
            f"  overlap {row['algo']}/{row['axis']}: "
            f"{row['overlap_frac'] * 100:.1f}% of "
            f"{row['collective_s'] * 1e3:.2f} ms collective time "
            f"MXU-overlapped ({kinds})")
    if report["knobs"]:
        lines.append("  knob attrs seen: "
                     + " ".join(f"{k}={v}" for k, v in
                                sorted(report["knobs"].items())))
    return lines


def track_tables(events) -> list:
    """Per-track totals for the ``scripts/profile_summary.py`` trace
    mode (output contract owner moved here): ``[(track, total_ms,
    [(name, ms), ...])]`` sorted by total, complete events only."""
    procs, _ = _meta_maps(events)
    by_track = collections.defaultdict(collections.Counter)
    track_total = collections.Counter()
    for e in events:
        if e.get("ph") != "X":
            continue
        pid = e.get("pid")
        track = procs.get(pid, f"pid{pid}")
        dur = float(e.get("dur", 0) or 0) / 1e3    # us -> ms
        by_track[track][e.get("name", "?")] += dur
        track_total[track] += dur
    return [(track, total, by_track[track].most_common())
            for track, total in track_total.most_common()]


def distill(events, records) -> list:
    """The reduced trace for a committed fixture: metadata events,
    device op events, and the span-vocabulary host windows — everything
    :func:`attribute` consumes, nothing else (a raw miniapp trace
    carries ~700k jax-internal host events; the distilled one is
    git-sized). The distilled file replays bitwise through the same
    engine."""
    procs, _ = _meta_maps(events)
    span_names = {r.get("name", "?") for r in records
                  if isinstance(r, dict) and r.get("type") == "span"}
    keep = []
    for e in events:
        if e.get("ph") == "M":
            keep.append(e)
        elif e.get("ph") == "X" and (
                _is_device_event(e, procs)
                or e.get("name") in span_names):
            keep.append(e)
    return keep


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = json_path = distill_path = None
    top_n = 25
    paths = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-o":
            i += 1
            out_path = argv[i] if i < len(argv) else None
        elif a == "--json":
            i += 1
            json_path = argv[i] if i < len(argv) else None
        elif a == "--distill":
            i += 1
            distill_path = argv[i] if i < len(argv) else None
        elif a == "--top":
            i += 1
            try:
                top_n = int(argv[i]) if i < len(argv) else top_n
            except ValueError:
                print(__doc__, file=sys.stderr)
                return 2
        elif a.startswith("-"):
            print(__doc__, file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1
    if len(paths) < 2 \
            or (out_path is None and "-o" in argv) \
            or (json_path is None and "--json" in argv) \
            or (distill_path is None and "--distill" in argv):
        print(__doc__, file=sys.stderr)
        return 2
    trace_path, jsonl_paths = paths[0], paths[1:]
    from .aggregate import merge_artifacts

    try:
        if os.path.isdir(trace_path):
            trace_path = newest_trace(trace_path)
        events = load_trace(trace_path)
        records = merge_artifacts(jsonl_paths)
        report = attribute(events, records)
    except (OSError, ValueError) as e:
        print(f"devtrace: {e}", file=sys.stderr)
        return 1
    # artifacts land BEFORE the human-facing report: a downstream
    # consumer piping the report through `head` closes stdout early
    # (SIGPIPE), and that must never cost the enriched artifact
    recs = records_from_report(report, trace_path)
    if out_path:
        with open(out_path, "w") as f:
            for r in records + recs:
                f.write(json.dumps(r, default=str) + "\n")
    if json_path:
        with open(json_path, "w") as f:
            json.dump(report, f, indent=1, default=str)
    if distill_path:
        kept = distill(events, records)
        opener = gzip.open if distill_path.endswith(".gz") else open
        with opener(distill_path, "wt") as f:
            json.dump({"traceEvents": kept}, f)
    print(f"trace: {trace_path}")
    for line in format_report(report, top_n):
        print(line)
    if not report["overlap"]:
        print("devtrace: WARNING — zero attributed collective device "
              "time; no measured_overlap record emitted "
              "(--require-devtrace will reject this artifact)",
              file=sys.stderr)
    if out_path:
        print(f"enriched artifact: {out_path} (+{len(recs)} devtrace "
              "records)")
    if json_path:
        print(f"report json: {json_path}")
    if distill_path:
        print(f"distilled trace: {distill_path} ({len(kept)} of "
              f"{len(events)} events kept)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
