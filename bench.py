#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline config (BASELINE.md #1): miniapp_cholesky, double, N=4096, nb=256,
1x1 local grid, using the reference's fenced-timing protocol and flop model
(``miniapp/miniapp_cholesky.cpp:123-164``): GFLOPS = total_ops(n^3/6, n^3/6)/t.

No absolute baseline exists (the reference publishes no numbers —
BASELINE.md), so ``vs_baseline`` is 1.0 for the first recorded round.

Robustness (round-2 redesign after two distinct wedge modes):

* TPU plugin/tunnel init can hang (round 1: the probe timed out 3x and the
  round's artifact recorded a CPU fallback). The probe runs in a subprocess
  with a timeout and retries with pauses; if the accelerator never comes up
  the bench re-runs on the pure-CPU platform, clearly labeled.
* A single variant's XLA compile can hang (observed: the 'biggemm'
  emulated-f64 compile ran >45 min on the v5e tunnel). Every variant
  therefore runs in its OWN subprocess with a wall-clock timeout — a
  pathological variant is killed without losing the measurements that
  already landed.
* A fallback (non-TPU) sweep never takes the headline when a recorded TPU
  measurement of the same config exists in the git-tracked append-only
  ``.bench_history.jsonl``: the best such measurement is replayed as the
  headline (``"replayed": true`` + timestamp/source) and the live CPU
  numbers move to the ``live_fallback`` sidecar. Two rounds of wedge-time
  captures produced '[cpu]' headlines while 99-104 GF/s TPU measurements
  sat in history; the headline metric is the TPU result by contract.

All progress goes to stderr; stdout carries exactly one JSON line.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# healthy plugin init takes ~25 s; 240 s is generous while keeping the
# worst case (wedged tunnel: full probe + 2 short retries + pauses, then
# the CPU fallback) inside a driver-friendly total
PROBE_TIMEOUT_S = int(os.environ.get("DLAF_BENCH_PROBE_TIMEOUT", "240"))
#: wall-clock cap per variant subprocess: device init (~25 s) + compile
#: (minutes cold, seconds warm via the persistent cache) + 5 timed runs
VARIANT_TIMEOUT_S = int(os.environ.get("DLAF_BENCH_VARIANT_TIMEOUT", "900"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def probe_devices():
    """Which jax platform comes up in this environment? Returns the platform
    string, or None if nothing initializes (subprocess, timed out rather
    than hanging forever). The accelerator tunnel has been observed to
    wedge transiently, so a failed probe is retried a couple of times with
    a pause before giving up on the accelerator."""
    code = "import jax; print(jax.devices()[0].platform)"
    retries = int(os.environ.get("DLAF_BENCH_PROBE_RETRIES", "2"))
    for attempt in range(retries + 1):
        try:
            # full timeout once (cold plugin init is slow); a wedged tunnel
            # hangs rather than erroring, so retries get a short leash to
            # bound the worst case before the CPU fallback kicks in
            out = subprocess.run(
                [sys.executable, "-c", code], check=True,
                timeout=PROBE_TIMEOUT_S if attempt == 0 else 120,
                stdout=subprocess.PIPE).stdout.decode().strip()
            platform = out.splitlines()[-1] if out else "unknown"
            log(f"device probe: platform {platform!r}")
            return platform
        except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as e:
            log(f"device probe attempt {attempt + 1}/{retries + 1} failed: "
                f"{type(e).__name__}")
            if attempt < retries:
                time.sleep(int(os.environ.get("DLAF_BENCH_PROBE_PAUSE", "60")))
    return None


def cpu_env() -> dict:
    from dlaf_tpu.tpu_info import cpu_subprocess_env

    env = cpu_subprocess_env()
    env["DLAF_BENCH_CPU_FALLBACK"] = "1"
    return env


def _cache_dir() -> str:
    # persist compiled programs across runs/rounds: the unrolled
    # factorizations compile in minutes and run in milliseconds, so a warm
    # cache frees nearly the whole sweep budget for measurement
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".jax_cache")


#: eigensolver-pipeline stage arms (ISSUE 6): A/B the level-batched D&C
#: ("tridiag" vs "tridiag+dcb1") and the pipelined reflector-block
#: back-transform ("btr2b" vs "btr2b+btla1"), plus the chase
#: back-transform ("btb2t", its blocked/sweeps A/B rides the existing
#: bt_b2t_impl knob). Plain arms pin their knob to 0 via env so TPU
#: "auto" cannot blur the A/B; results carry a "workload" field so they
#: never take the cholesky headline. The mfu table's stage rows read
#: these labels (scripts/mfu_table.py _FAMILIES).
#: "fpanel" (ISSUE 10): the fused-Pallas-panel A/B arm — an f32 local
#: cholesky pair ("fpanel" pins DLAF_PANEL_IMPL=xla via env so the TPU
#: auto can't blur the comparison, "fpanel+fp1" pins fused; same
#: discipline as the "+la1"/comm arms). Sized off-TPU via
#: DLAF_BENCH_FPANEL_N (the fused kernels run in interpret mode there).
#: "fstep" (ISSUE 19): the fused-STEP A/B arm — the same f32 local
#: cholesky pair with "fstep" pinning DLAF_STEP_IMPL=xla (composed
#: per-op chain) and "fstep+fs1" pinning the one-pallas_call-per-step
#: fused kernel (docs/pallas_panel.md "Fused step kernel"); paired
#: accuracy records ride both arms, and bench_gate holds the pair's
#: presence as a must-trip leg. Sized off-TPU via DLAF_BENCH_FSTEP_N.
#: "serve" (ISSUE 11): the batched serving-layer arm — requests/s and
#: p99 latency of a seeded mixed-shape request stream through
#: serve.Queue over a WARM bucket set, vs a loop of singleton cholesky()
#: calls over the identical problems; results carry workload="serve"
#: (requests/s in the gflops slot, p99 seconds in t — a different
#: metric, so the cholesky headline must never pick it up) plus the
#: batched-vs-singles "speedup" field scripts/bench_gate.py holds to
#: the >= 3x ISSUE-11 floor. Sized via DLAF_BENCH_SERVE_N /
#: DLAF_BENCH_SERVE_REQS.
#: "overload" (ISSUE 12, docs/robustness.md): the overload-protection
#: arm — a burst stream at 2x the queue's DLAF_SERVE_MAX_DEPTH admission
#: bound; records accepted requests/s (gflops slot), p99 latency of the
#: ACCEPTED requests (t slot), shed rate, and the maximum pending depth
#: observed — asserting in-arm that depth never exceeded the bound and
#: no accepted ticket was stranded. workload="overload" keeps it out of
#: every headline. Sized via DLAF_BENCH_SERVE_N / DLAF_BENCH_OVERLOAD_DEPTH.
#: "autotune" (ISSUE 15, docs/autotune.md): the accuracy-steered
#: precision-route A/B arm — steady-state f64 cholesky GF/s under the
#: LEARNED route table (DLAF_AUTOTUNE=1, loop settled in-arm; the arm
#: also reports decisions/s) vs the PINNED worst-case route (autotune
#: off, f64_gemm_slices=8 + f64_trsm=native — the ladder's safety top).
#: The learned/pinned ratio rides as the "speedup" field
#: scripts/bench_gate.py holds to the history-free
#: --min-autotune-speedup floor; workload="autotune" keeps both numbers
#: out of every headline. Sized via DLAF_BENCH_AUTOTUNE_N.
#: "fleet" (ISSUE 18, docs/fleet.md): the multi-replica serve-tier arm —
#: the same seeded mixed-bucket stream through a fleet Router over ONE
#: real subprocess replica vs DLAF_BENCH_FLEET_WORKERS replicas; the
#: N-vs-1 requests/s ratio rides as the "speedup" field
#: scripts/bench_gate.py holds to the history-free --min-fleet-scaling
#: floor, and a mid-stream SIGKILL leg reports the zero-loss failover
#: cost as "recovery_s". workload="fleet" keeps every number out of the
#: headlines. Sized via DLAF_BENCH_FLEET_N / DLAF_BENCH_FLEET_REQS.
STAGE_BASES = ("tridiag", "btr2b", "btb2t", "fpanel", "fstep", "serve",
               "overload", "autotune", "fleet")


def _run_fpanel_variant(variant: str, platform: str,
                        workload: str = "fpanel") -> None:
    """Measure one fused-panel ("fpanel", ISSUE 10) or fused-step
    ("fstep", ISSUE 19) A/B arm (f32 local cholesky; the knob was
    pinned by the caller): same artifact/stdout protocol as the other
    arms, a dedicated ``workload`` label so the cholesky headline (a
    different dtype + flop tier) never picks it up. Off-TPU the fused
    route runs the kernels in interpret mode — tiny N keeps that inside
    the sweep budget while still exercising the full routed program."""
    import dlaf_tpu.config as config
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.miniapp.generators import hpd_element_fn
    from dlaf_tpu.types import total_ops

    n = int(os.environ.get(f"DLAF_BENCH_{workload.upper()}_N") or
            (os.environ.get("DLAF_BENCH_N", "4096")
             if platform == "tpu" else "256"))
    nb = min(int(os.environ.get("DLAF_BENCH_NB", "256")),
             max(n // 4, 32))    # keep a real multi-step panel chain
    cfg = config.get_configuration()
    log(f"[{variant}] fused-{'step' if workload == 'fstep' else 'panel'} "
        f"arm on {platform}: n={n} nb={nb} "
        f"panel_impl={cfg.panel_impl} step_impl={cfg.step_impl}")
    ref = Matrix.from_element_fn(hpd_element_fn(n, np.float32),
                                 GlobalElementSize(n, n),
                                 TileElementSize(nb, nb), dtype=np.float32)
    flops = total_ops(np.float32, n**3 / 6, n**3 / 6)

    def measure():
        mat = ref.with_storage(ref.storage + 0)
        return cholesky("L", mat, donate=True).storage

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from measure_common import append_history, best_time

    best_t, last = best_time(measure, reps=3, return_last=True)
    best_g = flops / best_t / 1e9
    log(f"[{variant}] best of 3: {best_t:.4f}s {best_g:.1f} GFlop/s")
    line = append_history(platform, n, nb, best_g, best_t,
                          source="bench.py", variant=variant,
                          dtype="float32", donate=True, workload=workload)
    from dlaf_tpu import obs
    from dlaf_tpu.obs import accuracy

    if accuracy.enabled():
        # paired accuracy record like every timed arm (docs/accuracy.md):
        # a wrong fused-kernel ladder shows up as a bound_ratio jump
        # right next to its GFlop/s number
        out = ref.with_storage(last)
        value = accuracy.cholesky_residual("L", ref, out)
        accuracy.emit("bench", "cholesky_residual", value, n=n, nb=nb,
                      c=60.0, dtype=np.float32, of=last,
                      attrs={"variant": variant})
    obs.emit_event("bench_result", payload=line)
    obs.flush()
    print(json.dumps(line), flush=True)


def _run_serve_variant(variant: str, platform: str) -> None:
    """Measure the serving layer (ISSUE 11, docs/serving.md): a seeded
    mixed-shape stream of Cholesky requests (a) end-to-end through a
    WARM serve.Queue — requests/s in the ``gflops`` history slot, p99
    latency seconds in ``t``; workload="serve" keeps both out of every
    cholesky lookup — and (b) as the ISSUE-11 acceptance ratio: the
    ``cholesky_batched`` entry over the warm bucket program vs a loop of
    singleton ``cholesky()`` calls over the identical problems at the
    same accuracy budget (per-request accuracy records land in this
    child's artifact under DLAF_ACCURACY=1). The entry/singles ratio is
    the ``speedup`` field scripts/bench_gate.py enforces >= 3x; the
    queue's own end-to-end ratio rides as ``queue_speedup``."""
    import dlaf_tpu.config as config
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.common.sync import hard_fence
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.obs import quantile
    from dlaf_tpu.serve import Queue, Request, get_service

    bn = int(os.environ.get("DLAF_BENCH_SERVE_N", "64"))
    n_reqs = int(os.environ.get("DLAF_BENCH_SERVE_REQS", "64"))
    batch = config.get_configuration().serve_batch
    rng = np.random.default_rng(bn * 1000 + n_reqs)
    # mixed shapes in the bucket's upper half: real padding traffic, one
    # warm bucket program (the steady-state regime the arm certifies)
    shapes = rng.integers(bn // 2 + 1, bn + 1, size=n_reqs)
    problems = []
    for n in shapes:
        x = rng.standard_normal((n, n))
        problems.append(x @ x.T + n * np.eye(n))
    reqs = [Request(op="cholesky", a=a) for a in problems]
    q = Queue(buckets=(bn,))
    q.warmup(reqs)
    log(f"[{variant}] serve arm on {platform}: bucket={bn} batch={batch} "
        f"requests={n_reqs} (warm: {len(q.service.specs())} programs)")

    def serve_pass():
        tickets = [q.submit(Request(op="cholesky", a=a)) for a in problems]
        q.flush()
        hard_fence(*[t.result() for t in tickets])
        return tickets

    best_t, p99 = float("inf"), float("nan")
    for i in range(3):
        t0 = time.perf_counter()
        tickets = serve_pass()
        t = time.perf_counter() - t0
        # p99 via the shared windowed-quantile estimator's computation
        # (obs.quantile is pinned bit-identical to np.percentile): the
        # SLO gauges, the aggregate request tables, and this arm report
        # THE SAME number for the same latencies (ISSUE 13 satellite)
        lat = [tk.total_s for tk in tickets]
        log(f"[{variant}] queue pass {i}: {t:.4f}s "
            f"{n_reqs / t:.1f} req/s p99 {quantile(lat, 0.99):.4f}s")
        if t < best_t:
            best_t, p99 = t, float(quantile(lat, 0.99))
    rps = n_reqs / best_t

    # the ISSUE-11 acceptance ratio: cholesky_batched (the batched ENTRY
    # over the warm bucket program) vs a loop of singleton cholesky()
    # calls over the identical problems — the queue's end-to-end
    # requests/s above additionally carries padding assembly and the
    # per-request record trail, reported separately
    from dlaf_tpu.serve import cholesky_batched

    padded = []
    for i in range(0, n_reqs, batch):
        chunk = problems[i:i + batch]
        ab = np.broadcast_to(np.eye(bn), (batch, bn, bn)).copy()
        for j, a in enumerate(chunk):
            ab[j, :len(a), :len(a)] = a
        padded.append(ab)
    hard_fence(*cholesky_batched("L", padded[0], with_info=True))   # warm
    best_tb = float("inf")
    for i in range(3):
        t0 = time.perf_counter()
        for ab in padded:
            hard_fence(*cholesky_batched("L", ab, with_info=True))
        t = time.perf_counter() - t0
        log(f"[{variant}] batched-entry pass {i}: {t:.4f}s "
            f"{n_reqs / t:.1f} req/s")
        best_tb = min(best_tb, t)
    rps_batched = n_reqs / best_tb

    # the singles comparator: the public singleton entry over the SAME
    # problems, warmed first (both sides judged warm — the serving claim
    # is about dispatch amortization, not about compile walls)
    mats = [Matrix.from_global(a, TileElementSize(len(a), len(a)))
            for a in problems]

    def singles_pass():
        outs = [cholesky("L", m.with_storage(m.storage + 0), donate=True)
                for m in mats]
        hard_fence(*[o.storage for o in outs])

    singles_pass()                       # warm every distinct shape
    best_ts = float("inf")
    for i in range(3):
        t0 = time.perf_counter()
        singles_pass()
        t = time.perf_counter() - t0
        log(f"[{variant}] singles pass {i}: {t:.4f}s "
            f"{n_reqs / t:.1f} req/s")
        best_ts = min(best_ts, t)
    rps_singles = n_reqs / best_ts
    speedup = rps_batched / rps_singles
    st = get_service().stats()
    log(f"[{variant}] queue {rps:.1f} req/s (p99 {p99:.4f}s); batched "
        f"entry {rps_batched:.1f} vs singles {rps_singles:.1f} req/s -> "
        f"speedup {speedup:.2f}x (queue {rps / rps_singles:.2f}x, cache "
        f"hit rate {st['hit_rate']:.3f})")

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from measure_common import append_history

    line = append_history(platform, bn, bn, rps, p99, source="bench.py",
                          variant=variant, dtype="float64",
                          workload="serve",
                          extra={"speedup": round(float(speedup), 3),
                                 "batched_rps": round(float(rps_batched), 2),
                                 "singles_rps": round(float(rps_singles),
                                                      2),
                                 "queue_speedup": round(
                                     float(rps / rps_singles), 3),
                                 "requests": n_reqs, "batch": batch,
                                 "hit_rate": st["hit_rate"]})
    from dlaf_tpu import obs

    obs.emit_event("bench_result", payload=line)
    obs.flush()
    print(json.dumps(line), flush=True)


def _run_overload_variant(variant: str, platform: str) -> None:
    """Measure the serving queue's overload protection (ISSUE 12,
    docs/robustness.md): a deterministic burst of 2x the
    ``DLAF_SERVE_MAX_DEPTH`` admission bound per pass — the queue must
    shed the overflow fast (OverloadError), keep pending depth at or
    under the bound, and serve every ACCEPTED request with bounded p99.
    Records accepted requests/s (gflops slot), accepted p99 seconds (t
    slot), the shed rate, and the max observed depth; workload="overload"
    keeps the line out of every headline. The arm FAILS (raises) if depth
    ever exceeds the bound or an accepted ticket is stranded — the
    queue-memory-bounded claim is asserted, not just logged."""
    from dlaf_tpu.health.errors import OverloadError
    from dlaf_tpu.obs import quantile
    from dlaf_tpu.serve import Queue, Request

    bn = int(os.environ.get("DLAF_BENCH_SERVE_N", "32"))
    max_depth = int(os.environ.get("DLAF_BENCH_OVERLOAD_DEPTH", "16"))
    rng = np.random.default_rng(bn * 31 + max_depth)
    n_reqs = 2 * max_depth              # the 2x-capacity burst
    problems = []
    for _ in range(n_reqs):
        n = int(rng.integers(bn // 2 + 1, bn + 1))
        x = rng.standard_normal((n, n))
        problems.append(x @ x.T + n * np.eye(n))
    # batch > max_depth: the bucket cannot drain mid-burst, so the
    # admission bound genuinely binds (arrival faster than dispatch —
    # the overload regime this arm certifies)
    q = Queue(buckets=(bn,), batch=n_reqs, deadline_s=1e9,
              max_depth=max_depth, shed=True)
    q.warmup([Request(op="cholesky", a=problems[0])])
    log(f"[{variant}] overload arm on {platform}: bucket={bn} "
        f"max_depth={max_depth} burst={n_reqs} (2x capacity)")
    best_t, p99 = float("inf"), float("nan")
    shed_total = accepted_total = 0
    max_seen = 0
    for i in range(3):
        tickets, shed = [], 0
        t0 = time.perf_counter()
        for a in problems:
            try:
                tickets.append(q.submit(Request(op="cholesky", a=a)))
            except OverloadError:
                shed += 1
            max_seen = max(max_seen, q.pending())
        q.flush()
        t = time.perf_counter() - t0
        stranded = [tk for tk in tickets
                    if not tk.done and tk.error is None]
        if stranded:
            raise RuntimeError(f"overload arm stranded {len(stranded)} "
                               "accepted ticket(s)")
        if max_seen > max_depth:
            raise RuntimeError(f"overload arm: pending depth {max_seen} "
                               f"exceeded DLAF_SERVE_MAX_DEPTH={max_depth}")
        lat = [tk.total_s for tk in tickets if tk.done]
        shed_total += shed
        accepted_total += len(tickets)
        # shared quantile estimator, not a second hand-rolled p99 (the
        # serve arm has the parity rationale)
        log(f"[{variant}] pass {i}: {t:.4f}s accepted={len(tickets)} "
            f"shed={shed} depth<= {max_seen} "
            f"p99 {quantile(lat, 0.99):.4f}s")
        if t < best_t:
            best_t, p99 = t, float(quantile(lat, 0.99))
    accepted_per_pass = accepted_total // 3
    rps = accepted_per_pass / best_t
    shed_rate = shed_total / (3 * n_reqs)
    st = q.stats()
    log(f"[{variant}] accepted {rps:.1f} req/s (p99 {p99:.4f}s), shed "
        f"rate {shed_rate:.2f}, max depth {max_seen}/{max_depth}, "
        f"queue stats {dict((k, v) for k, v in st.items() if k != 'buckets')}")

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from measure_common import append_history

    line = append_history(platform, bn, bn, rps, p99, source="bench.py",
                          variant=variant, dtype="float64",
                          workload="overload",
                          extra={"shed_rate": round(float(shed_rate), 3),
                                 "shed": shed_total,
                                 "accepted": accepted_total,
                                 "burst": n_reqs,
                                 "max_depth": max_depth,
                                 "max_depth_seen": max_seen})
    from dlaf_tpu import obs

    obs.emit_event("bench_result", payload=line)
    obs.flush()
    print(json.dumps(line), flush=True)


def _run_autotune_variant(variant: str, platform: str) -> None:
    """Measure the accuracy-steered precision autotuner (ISSUE 15,
    docs/autotune.md): steady-state f64 cholesky throughput under the
    LEARNED route table vs the PINNED worst-case route, plus the
    decision rate of the settling phase. Off-TPU every ladder rung is
    behavior-inert (the routed knobs only bind on the mxu/mixed paths),
    so the honest expectation there is parity minus the probe cost —
    exactly what the gate's 0.8x floor allows; on TPU the learned
    routes (s<8, fused reductions) are the win this arm certifies.
    (Measured on this container: ~0.72x at n=192 with probe-per-call —
    which is why bench_gate's history-free floor defaults to 0.5, not
    parity; scripts/bench_gate.py DEFAULT_MIN_AUTOTUNE_SPEEDUP.)"""
    import dlaf_tpu.autotune as autotune
    import dlaf_tpu.config as config
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.miniapp.generators import hpd_element_fn
    from dlaf_tpu.types import total_ops

    n = int(os.environ.get("DLAF_BENCH_AUTOTUNE_N") or
            (os.environ.get("DLAF_BENCH_N", "4096")
             if platform == "tpu" else "192"))
    nb = min(int(os.environ.get("DLAF_BENCH_NB", "256")),
             max(n // 3, 32))
    ref = Matrix.from_element_fn(hpd_element_fn(n, np.float64),
                                 GlobalElementSize(n, n),
                                 TileElementSize(nb, nb), dtype=np.float64)
    flops = total_ops(np.float64, n**3 / 6, n**3 / 6)
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from measure_common import append_history, best_time

    saved = {k: os.environ.get(k) for k in
             ("DLAF_AUTOTUNE", "DLAF_AUTOTUNE_TABLE",
              "DLAF_F64_GEMM_SLICES", "DLAF_F64_TRSM")}

    def _restore():
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.initialize()

    try:
        # learned arm: fresh in-memory table, loop armed; let the table
        # settle (enough comfortable probes to relax from the start rung
        # to the floor), counting the decision rate of the settling runs
        os.environ["DLAF_AUTOTUNE"] = "1"
        # the arm measures a FRESH in-memory table: an ambient
        # DLAF_AUTOTUNE_TABLE would warm-start it (settle would measure
        # nothing) AND persist every arm decision into the operator's —
        # possibly git-tracked — table
        os.environ.pop("DLAF_AUTOTUNE_TABLE", None)
        os.environ.pop("DLAF_F64_GEMM_SLICES", None)
        os.environ.pop("DLAF_F64_TRSM", None)
        cfg = config.initialize()
        autotune._reset_for_tests()
        ladder = autotune.LADDER_F64
        settle = max(2, int(cfg.autotune_relax_after) * ladder.start + 1)
        t0 = time.perf_counter()
        for _ in range(settle):
            cholesky("L", ref)
        learn_t = time.perf_counter() - t0
        decisions_per_s = settle / learn_t if learn_t > 0 else 0.0
        rungs = {label: e["rung"]
                 for label, e in autotune.get_table().snapshot().items()}
        log(f"[{variant}] settled after {settle} probe(s) in "
            f"{learn_t:.2f}s ({decisions_per_s:.2f} decisions/s); "
            f"rungs {rungs}")

        def measure_learned():
            # steady state INCLUDES the probe: that is what a steered
            # deployment actually pays per call
            return cholesky("L", ref).storage

        t_learned, _ = best_time(measure_learned, reps=3, return_last=True)
        g_learned = flops / t_learned / 1e9
        log(f"[{variant}] learned-table best of 3: {t_learned:.4f}s "
            f"{g_learned:.1f} GFlop/s")

        # pinned worst-case arm: the ladder's safety top as static knobs
        os.environ["DLAF_AUTOTUNE"] = "0"
        os.environ["DLAF_F64_GEMM_SLICES"] = "8"
        os.environ["DLAF_F64_TRSM"] = "native"
        config.initialize()

        def measure_pinned():
            return cholesky("L", ref).storage

        measure_pinned()                   # warm the pinned-route program
        t_pinned, _ = best_time(measure_pinned, reps=3, return_last=True)
        g_pinned = flops / t_pinned / 1e9
        speedup = g_learned / g_pinned if g_pinned > 0 else float("nan")
        log(f"[{variant}] pinned-worst best of 3: {t_pinned:.4f}s "
            f"{g_pinned:.1f} GFlop/s -> learned/pinned speedup "
            f"{speedup:.2f}x")
    finally:
        _restore()

    line = append_history(platform, n, nb, g_learned, t_learned,
                          source="bench.py", variant=variant,
                          dtype="float64", workload="autotune",
                          extra={"speedup": round(float(speedup), 3),
                                 "pinned_gflops": round(float(g_pinned), 3),
                                 "decisions_per_s": round(
                                     float(decisions_per_s), 3),
                                 "settle_probes": settle,
                                 "rungs": rungs})
    from dlaf_tpu import obs

    obs.emit_event("bench_result", payload=line)
    obs.flush()
    print(json.dumps(line), flush=True)


def _run_fleet_variant(variant: str, platform: str) -> None:
    """Measure the fleet serve tier (ISSUE 18, docs/fleet.md): the SAME
    seeded mixed-bucket cholesky/solve stream through a Router over ONE
    real subprocess replica, then over ``DLAF_BENCH_FLEET_WORKERS``
    replicas sharing the persistent compile cache — requests/s in the
    ``gflops`` history slot, p99 latency seconds in ``t``, and the
    N-vs-1 throughput ratio as the ``speedup`` field
    scripts/bench_gate.py holds to the history-free
    ``--min-fleet-scaling`` floor. The arm then re-runs the stream with
    a mid-flight SIGKILL of the replica holding unacked tickets and
    reports ``recovery_s`` (kill -> every ticket resolved, ZERO lost):
    the replica-kill drill's cost, measured rather than asserted away.
    workload="fleet" keeps all of it out of every headline."""
    import signal
    import subprocess

    from dlaf_tpu import obs
    from dlaf_tpu.fleet import Router
    from dlaf_tpu.obs import quantile
    from dlaf_tpu.serve import Request

    bn = int(os.environ.get("DLAF_BENCH_FLEET_N", "64"))
    n_reqs = int(os.environ.get("DLAF_BENCH_FLEET_REQS", "48"))
    n_workers = int(os.environ.get("DLAF_BENCH_FLEET_WORKERS", "3"))
    # the replica queues bucket by these knobs (children inherit the
    # env); two n-buckets x two ops = four bucket programs, so the
    # router's bucket co-location actually spreads across replicas
    os.environ["DLAF_SERVE_BUCKETS"] = f"{max(bn // 2, 8)},{bn}"
    os.environ["DLAF_SERVE_DEADLINE_MS"] = "60000"
    rng = np.random.default_rng(bn * 7 + n_reqs)
    problems = []
    for i in range(n_reqs):
        n = int(rng.integers(bn // 4 + 1, bn + 1))
        if i % 3 == 2:
            problems.append(dict(
                op="solve",
                a=np.tril(rng.standard_normal((n, n))) + 3 * np.eye(n),
                b=rng.standard_normal((n, 4))))
        else:
            x = rng.standard_normal((n, n))
            problems.append(dict(op="cholesky", a=x @ x.T + n * np.eye(n)))

    router = Router(port=0)
    wenv = dict(os.environ)
    if wenv.get("DLAF_METRICS_PATH"):
        # the replicas must not interleave writes into THIS child's
        # artifact: each gets its own rank-templated shard next to it
        wenv["DLAF_METRICS_PATH"] += ".fleet_w%r.jsonl"
    procs: dict = {}

    def spawn(k):
        procs[k] = subprocess.Popen(
            [sys.executable, "-m", "dlaf_tpu.fleet.worker",
             "--connect", f"127.0.0.1:{router.port}", "--worker", str(k)],
            env=wenv)

    def wait_up(count, timeout_s=180.0):
        deadline = time.monotonic() + timeout_s
        while True:
            states = router.stats()["workers"]
            if sum(1 for m in states.values()
                   if m["state"] == "up") >= count:
                return
            if time.monotonic() > deadline:
                raise RuntimeError(f"fleet replicas not up: {states}")
            router.poll()
            time.sleep(0.05)

    def pass_once():
        tickets = [router.submit(Request(**p)) for p in problems]
        router.flush()
        if not router.join(tickets, timeout_s=VARIANT_TIMEOUT_S):
            raise RuntimeError("fleet stream timed out")
        bad = [t for t in tickets if t.error is not None]
        assert not bad, f"{len(bad)} fleet tickets failed: {bad[0].error}"
        return tickets

    def measure(tag):
        pass_once()                  # warm: compile into the shared cache
        best, p99 = float("inf"), float("nan")
        for i in range(2):
            t0 = time.perf_counter()
            tickets = pass_once()
            t = time.perf_counter() - t0
            lat = [tk.total_s for tk in tickets
                   if isinstance(tk.total_s, (int, float))]
            log(f"[{variant}] {tag} pass {i}: {t:.4f}s "
                f"{n_reqs / t:.1f} req/s")
            if t < best:
                best, p99 = t, float(quantile(lat, 0.99)) if lat \
                    else float("nan")
        return n_reqs / best, p99

    spawn(0)
    wait_up(1)
    log(f"[{variant}] fleet arm on {platform}: bucket={bn} "
        f"requests={n_reqs} replicas=1 then {n_workers}")
    rps_1, _ = measure("1-replica")
    for k in range(1, n_workers):
        spawn(k)
    wait_up(n_workers)
    rps_n, p99_n = measure(f"{n_workers}-replica")
    scaling = rps_n / rps_1

    # the replica-kill recovery leg: strand a partial batch on one
    # replica (no flush yet), SIGKILL it, and clock kill -> last ticket
    tickets = [router.submit(Request(**p)) for p in problems]
    router.poll()
    pending = [t for t in tickets if not t.resolved()]
    recovery_s = 0.0
    if pending:
        victim = pending[0].attempts[-1]
        vpid = router.stats()["workers"][victim]["pid"]
        t_kill = time.perf_counter()
        os.kill(vpid, signal.SIGKILL)
        procs[victim].wait(timeout=60)
        router.flush()
        if not router.join(tickets, timeout_s=VARIANT_TIMEOUT_S):
            raise RuntimeError("fleet kill-recovery stream timed out")
        recovery_s = time.perf_counter() - t_kill
    st = router.stats()
    assert st["lost"] == 0, f"replica kill lost tickets: {st}"
    log(f"[{variant}] fleet {n_workers}x {rps_n:.1f} req/s vs 1x "
        f"{rps_1:.1f} -> scaling {scaling:.2f}x; kill recovery "
        f"{recovery_s:.3f}s ({st['redispatches']} redispatches, 0 lost)")
    router.drain_fleet()
    for p in procs.values():
        if p.poll() is None:
            p.terminate()
            p.wait(timeout=30)
    router.close()

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from measure_common import append_history

    line = append_history(platform, bn, bn, rps_n, p99_n,
                          source="bench.py", variant=variant,
                          dtype="float64", workload="fleet",
                          extra={"speedup": round(float(scaling), 3),
                                 "rps_1": round(float(rps_1), 2),
                                 "rps_n": round(float(rps_n), 2),
                                 "workers": n_workers,
                                 "requests": n_reqs,
                                 "recovery_s": round(float(recovery_s), 3),
                                 "redispatches": st["redispatches"]})
    obs.emit_event("bench_result", payload=line)
    obs.flush()
    print(json.dumps(line), flush=True)


def _run_stage_variant(variant: str, base: str, mods: set) -> None:
    """Measure one eigensolver-stage arm; same artifact/stdout protocol as
    the cholesky arms (bench_result record + one JSON line)."""
    import jax

    import dlaf_tpu.config as config
    from dlaf_tpu.common.sync import hard_fence
    from dlaf_tpu.types import total_ops

    os.environ.setdefault("DLAF_DC_LEVEL_BATCH",
                          "1" if "dcb1" in mods else "0")
    os.environ.setdefault("DLAF_BT_LOOKAHEAD",
                          "1" if "btla1" in mods else "0")
    if base == "fpanel":
        os.environ.setdefault("DLAF_PANEL_IMPL",
                              "fused" if "fp1" in mods else "xla")
    if base == "fstep":
        # plain arm pins the composed chain so TPU "auto" cannot blur
        # the A/B; "+fs1" pins the fused step kernel (ISSUE 19)
        os.environ.setdefault("DLAF_STEP_IMPL",
                              "fused" if "fs1" in mods else "xla")
    config.initialize()
    platform = jax.devices()[0].platform
    if base == "fpanel":
        _run_fpanel_variant(variant, platform)
        return
    if base == "fstep":
        _run_fpanel_variant(variant, platform, workload="fstep")
        return
    if base == "serve":
        _run_serve_variant(variant, platform)
        return
    if base == "overload":
        _run_overload_variant(variant, platform)
        return
    if base == "autotune":
        _run_autotune_variant(variant, platform)
        return
    if base == "fleet":
        _run_fleet_variant(variant, platform)
        return
    # stage arms default to a smaller N off-TPU: the local red2band that
    # feeds the bt arm compiles per-panel, and the CPU fallback sweep's
    # budget belongs to the headline arms
    n = int(os.environ.get("DLAF_BENCH_STAGE_N") or
            (os.environ.get("DLAF_BENCH_N", "4096")
             if platform == "tpu" else "1024"))
    nb = int(os.environ.get("DLAF_BENCH_NB", "256"))
    log(f"[{variant}] stage arm on {platform}: n={n} nb={nb}")
    rng = np.random.default_rng(n)
    if base == "tridiag":
        from dlaf_tpu.eigensolver.tridiag_solver import tridiag_solver

        d = rng.standard_normal(n)
        e = rng.standard_normal(n - 1)
        flops = total_ops(np.dtype(np.float64), 2 * n**3 / 3, 2 * n**3 / 3)

        def measure():
            return tridiag_solver(d, e, nb, use_device=True)[1]
    elif base == "btb2t":
        from dlaf_tpu.eigensolver.back_transform import bt_band_to_tridiag
        from dlaf_tpu.eigensolver.band_to_tridiag import band_to_tridiag

        b = min(nb, max(n // 8, 1))
        band = np.zeros((b + 1, n))
        band[0] = rng.standard_normal(n)
        for r in range(1, b + 1):
            band[r, : n - r] = rng.standard_normal(n - r)
        tri = band_to_tridiag(band, b)
        c = rng.standard_normal((n, n))
        flops = total_ops(np.dtype(np.float64), n**3, n**3)

        def measure():
            return bt_band_to_tridiag(tri, c)
    else:   # btr2b
        import jax.numpy as jnp

        from dlaf_tpu.common.index2d import TileElementSize
        from dlaf_tpu.eigensolver.back_transform import bt_reduction_to_band
        from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band
        from dlaf_tpu.matrix.matrix import Matrix

        x = rng.standard_normal((n, n))
        a = x @ x.T + n * np.eye(n)
        red = reduction_to_band(
            Matrix.from_global(a, TileElementSize(nb, nb)))
        hard_fence(red.matrix.storage)
        c = jnp.asarray(rng.standard_normal((n, n)))
        flops = total_ops(np.dtype(np.float64), n**3, n**3)

        def measure():
            return bt_reduction_to_band(red, c)

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    # the single timing-policy owner (1 warmup + fenced best-of-reps):
    # the stage arms must never drift from the other history entries
    from measure_common import append_history, best_time

    best_t, last = best_time(measure, reps=3, return_last=True)
    best_g = flops / best_t / 1e9
    log(f"[{variant}] best of 3: {best_t:.4f}s {best_g:.1f} GFlop/s")

    line = append_history(platform, n, nb, best_g, best_t,
                          source="bench.py", variant=variant,
                          dtype="float64", workload=base)
    from dlaf_tpu import obs
    from dlaf_tpu.obs import accuracy

    if base == "tridiag" and accuracy.enabled():
        # paired perf+accuracy record (DLAF_ACCURACY, docs/accuracy.md):
        # the D&C eigenvector block's orthogonality defect is the cheap
        # invariant this arm can check without a reference decomposition
        accuracy.emit("bench", "tridiag_orthogonality",
                      accuracy.array_orthogonality(last), n=n, nb=nb,
                      c=200.0, dtype=np.float64, of=last,
                      attrs={"variant": variant})
    obs.emit_event("bench_result", payload=line)
    obs.flush()
    print(json.dumps(line), flush=True)


def _emit_devtrace(variant: str) -> None:
    """Traced bench run (DLAF_TRACE_DIR armed + a metrics sink): stop
    the process trace so the profiler artifact lands, attribute the
    device timeline to this arm's spans (dlaf_tpu.obs.devtrace, ISSUE
    14), and append the devtrace/measured_overlap records to the SAME
    artifact — so a traced bench arm's artifact passes
    ``--require-devtrace`` and feeds ``scripts/perf_diff.py`` with
    measured per-phase device walls next to its bench_result. No-op on
    untraced runs; never fails the measurement (the number already
    landed)."""
    from dlaf_tpu import obs
    from dlaf_tpu.obs._state import STATE

    trace_root = STATE.trace_dir
    if not STATE.profiler_started or STATE.sink is None or not trace_root:
        return
    # NOTHING here may fail the child: the bench_result already flushed,
    # and the parent drops a nonzero-rc child's landed measurement — so
    # the whole post-measurement path (profiler stop, trace parse,
    # attribution, the sink writes themselves) degrades to a log line
    try:
        obs.stop_profiler()        # flush the profiler artifact to disk
        from dlaf_tpu.obs import devtrace

        path = devtrace.newest_trace(trace_root)
        records = obs.read_records(STATE.sink.path)
        report = devtrace.attribute(devtrace.load_trace(path), records)
        for rec in devtrace.records_from_report(report, path):
            obs.emit_event(rec.pop("type"), **rec)
        obs.flush()
    except SystemExit as e:        # newest_trace's empty-dir signal
        log(f"[{variant}] devtrace attribution skipped: {e}")
        return
    except Exception as e:
        log(f"[{variant}] devtrace attribution skipped: {e!r}")
        return
    log(f"[{variant}] devtrace: coverage {report['coverage'] * 100:.1f}%, "
        f"{len(report['overlap'])} measured_overlap record(s)")


def run_variant() -> None:
    """Child: measure ONE trailing variant (env DLAF_BENCH_VARIANT), print
    one JSON line {variant, platform, dtype, n, nb, gflops, t, ts, source,
    donate} on stdout (the exact dict measure_common.append_history wrote
    to .bench_history.jsonl — single schema owner)."""
    variant = os.environ["DLAF_BENCH_VARIANT"]
    dtype_name = os.environ.get("DLAF_BENCH_DTYPE", "float64")
    t_start = time.time()
    import jax

    jax.config.update("jax_enable_x64", True)
    os.environ.setdefault("DLAF_COMPILATION_CACHE_DIR", _cache_dir())
    # "<base>+la1" = the same trailing form under the PIPELINED step order
    # (config cholesky_lookahead=1); the plain arm pins lookahead=0 so the
    # pair is a real serialized-vs-pipelined A/B on every platform (the
    # auto knob would silently flip the plain arm on TPU). Explicit env
    # still wins via setdefault.
    base = variant
    la = None
    if variant.endswith("+la1"):
        base, la = variant[: -len("+la1")], "1"
    if base.split("+")[0] in STAGE_BASES:
        parts = base.split("+")
        _run_stage_variant(variant, parts[0], set(parts[1:]))
        _emit_devtrace(variant)
        return
    os.environ.setdefault("DLAF_CHOLESKY_LOOKAHEAD", la or "0")
    # "ozaki_concat"/"ozaki_dots" = the ozaki trailing with the group form
    # pinned (config ozaki_group) — labeled separately so the sweep A/Bs
    # the two group forms against the auto default (concat on TPU since
    # the 2026-08-01 dot_ab session) and the headline picks whichever
    # silicon prefers
    if base in ("ozaki_concat", "ozaki_dots"):
        os.environ["DLAF_CHOLESKY_TRAILING"] = "ozaki"
        os.environ.setdefault("DLAF_OZAKI_GROUP",
                              base.removeprefix("ozaki_"))
    else:
        os.environ["DLAF_CHOLESKY_TRAILING"] = base

    import dlaf_tpu.config as config

    config.initialize()
    platform = jax.devices()[0].platform
    log(f"[{variant}] devices: {jax.devices()} ({time.time() - t_start:.1f}s)")
    if base == "scan" and platform == "tpu":
        # the scan formulation follows the f64_gemm/f64_trsm knobs (it no
        # longer hardwires the MXU route); on TPU the measured scan config
        # is the MXU one, so resolve the knobs the way the product config
        # does there — explicit env still overrides, each knob on its own
        # variable's absence (an explicit DLAF_F64_TRSM alone must not be
        # clobbered)
        os.environ.setdefault("DLAF_F64_GEMM", "mxu")
        os.environ.setdefault("DLAF_F64_TRSM", "mixed")
        config.initialize()
        log(f"[{variant}] tpu: f64_gemm={os.environ['DLAF_F64_GEMM']} "
            f"f64_trsm={os.environ['DLAF_F64_TRSM']}")

    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.common.sync import hard_fence
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.miniapp.generators import hpd_element_fn
    from dlaf_tpu.types import total_ops

    n = int(os.environ.get("DLAF_BENCH_N", "4096"))
    nb = int(os.environ.get("DLAF_BENCH_NB", "256"))
    dtype = np.dtype(dtype_name).type
    try:
        jax.jit(lambda x: x * 2)(jax.numpy.ones((2,), dtype=dtype)
                                 ).block_until_ready()
    except Exception as e:  # platform without f64 support
        log(f"[{variant}] {dtype_name} unavailable ({e}); using float32")
        dtype = np.float32
    if dtype != np.float64 and base.startswith("ozaki"):
        # "ozaki*" is the emulated-f64 path; for other dtypes it statically
        # falls back to biggemm — keep the label truthful (the lookahead
        # suffix survives the relabel: the step order is orthogonal)
        os.environ["DLAF_CHOLESKY_TRAILING"] = base = "biggemm"
        variant = base + ("+la1" if la else "")
        config.initialize()
    ref = Matrix.from_element_fn(hpd_element_fn(n, dtype),
                                 GlobalElementSize(n, n),
                                 TileElementSize(nb, nb), dtype=dtype)
    best_g, best_t = 0.0, float("inf")
    # 1 warmup (compile) + 4 timed: compiles cost minutes, timed runs cost
    # milliseconds — extra repetitions capture the fast tail for free
    for i in range(5):
        mat = ref.with_storage(ref.storage + 0)
        hard_fence(mat.storage)
        t0 = time.perf_counter()
        # donate: the per-run copy is consumed exactly like the miniapp's
        # (the reference factors mat_a in place); the donated route is the
        # product default and the measured-fastest form (session 4g)
        out = cholesky("L", mat, donate=True)
        hard_fence(out.storage)
        t = time.perf_counter() - t0
        g = total_ops(dtype, n**3 / 6, n**3 / 6) / t / 1e9
        log(f"[{variant}] run {i}: {t:.4f}s {g:.1f} GFlop/s")
        if i > 0 and g > best_g:
            best_g, best_t = g, t
    # append-only measurement log: tunnel wedges must never cost an
    # already-landed hardware number (BASELINE.md cites this file).
    # measure_common.append_history is the single schema owner; the line it
    # returns (donate=True: this sweep's program aliases its input, a
    # different measured program from pre-donation entries — round-4
    # advisory) is also this child's stdout protocol.
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from measure_common import append_history

    line = append_history(platform, n, nb, best_g, best_t, source="bench.py",
                          variant=variant, dtype=np.dtype(dtype).name,
                          donate=True)
    from dlaf_tpu.obs import accuracy

    if accuracy.enabled():
        # paired perf+accuracy record for the A/B arm (DLAF_ACCURACY,
        # docs/accuracy.md): probe the LAST timed factor against the
        # retained reference — a bad Ozaki peel or a wrong lookahead mask
        # shows up here as a bound_ratio jump next to its GFlop/s number
        value = accuracy.cholesky_residual("L", ref, out)
        accuracy.emit("bench", "cholesky_residual", value, n=n, nb=nb,
                      c=60.0, dtype=dtype, of=out.storage,
                      attrs={"variant": variant})
    # primary result channel: the obs JSONL artifact (the parent points
    # DLAF_METRICS_PATH at a per-variant file and reads the bench_result
    # record back — structured, alongside this child's spans/counters —
    # instead of scraping the stdout tail). The stdout line stays for
    # humans and as the no-artifact fallback.
    from dlaf_tpu import obs

    obs.emit_event("bench_result", payload=line)
    obs.flush()
    _emit_devtrace(variant)
    print(json.dumps(line), flush=True)


# Entries recorded before the ozaki peel fix (commit 0807ec7; the fixed
# peel first ran on silicon in the 2026-08-02 ~04:19 UTC postfix batch)
# measured a numerically corrupted decomposition (~2^-8 off at
# data-dependent entries) and must not outrank post-fix measurements of
# the same config in the replayed headline.
PEEL_FIX_TS = "2026-08-02T04:00"


def best_recorded(platform: str, n: int, nb: int, path: str | None = None):
    """Best same-config measurement from the append-only history log
    (``.bench_history.jsonl``), or None. f64 entries only — the headline
    metric is BASELINE config #1's double precision. Post-peel-fix entries
    (ts >= PEEL_FIX_TS) are preferred; pre-fix entries are a fallback for
    configs never re-measured after the fix. ``path`` overrides the log
    location (tests).

    The log is read through the schema-validating history reader
    (``dlaf_tpu.obs.read_history_records``): a malformed or non-finite
    line raises ValueError — loudly failing the bench — instead of being
    silently skipped while it skews the replayed headline (ISSUE 7
    satellite; ``python -m dlaf_tpu.obs.validate --history`` is the
    standalone check)."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".bench_history.jsonl")
    from dlaf_tpu.obs import read_history_records

    best = best_prefix = None
    try:
        records = read_history_records(path)
    except OSError:
        return None     # no history yet — a legitimate first round
    for r in records:
        g = r.get("gflops")
        if not (r.get("platform") == platform and r.get("n") == n
                and r.get("nb") == nb and r.get("dtype") == "float64"
                # stage-arm entries carry different flop models
                and r.get("workload") in (None, "cholesky")):
            continue
        if str(r.get("ts", "")) >= PEEL_FIX_TS:
            if best is None or g > best["gflops"]:
                best = r
        elif best_prefix is None or g > best_prefix["gflops"]:
            best_prefix = r
    return best if best is not None else best_prefix


def assemble_headline(results, n, nb, hist_lookup=None) -> dict:
    """Build the driver's single JSON object from the sweep results.

    The headline metric is the framework's TPU result. When the live sweep
    ran on a fallback platform (wedged tunnel), the best git-tracked TPU
    measurement of this exact config from ``.bench_history.jsonl`` takes
    the headline — labeled ``"replayed": true`` with its timestamp and
    source — and the live CPU sweep is demoted to the ``live_fallback``
    sidecar. A live TPU run on a healthy tunnel always takes the headline.
    Reference measurement contract: ``miniapp/miniapp_cholesky.cpp:123-174``.
    """
    if hist_lookup is None:
        hist_lookup = best_recorded

    def replay_headline(hist):
        """The one shape of a history-replayed headline record."""
        return {
            "metric": (f"miniapp_cholesky {hist['dtype']} N={n} nb={nb} "
                       f"local GFlop/s [tpu] "
                       f"trailing={hist.get('variant', '?')}"),
            "value": hist["gflops"],
            "unit": "GFlop/s",
            "vs_baseline": 1.0,
            "replayed": True,
            "replayed_ts": hist.get("ts"),
            "replayed_source": hist.get("source", ".bench_history.jsonl"),
        }

    # the headline is BASELINE config #1 (cholesky); the eigensolver stage
    # arms measure different flop models and only ride in the artifact —
    # a sweep where every cholesky arm died must NOT publish a stage
    # number under the cholesky label: replay history or report nothing
    chol = [r for r in results if r.get("workload") in (None, "cholesky")]
    if not chol:
        hist = hist_lookup(platform="tpu", n=n, nb=nb)
        return replay_headline(hist) if hist else None
    best = max(chol, key=lambda r: r["gflops"])
    result = {
        "metric": (f"miniapp_cholesky {best['dtype']} N={n} nb={nb} "
                   f"local GFlop/s [{best['platform']}] "
                   f"trailing={best['variant']}"),
        "value": best["gflops"],
        "unit": "GFlop/s",
        "vs_baseline": 1.0,
    }
    if best["platform"] != "tpu":
        hist = hist_lookup(platform="tpu", n=n, nb=nb)
        if hist:
            result = replay_headline(hist)
            result["live_fallback"] = {
                k: best[k] for k in
                ("variant", "platform", "dtype", "gflops", "ts")
                if k in best}
    return result


def read_bench_result(path: str):
    """Last ``bench_result`` payload from a child's obs JSONL artifact, or
    None (missing/invalid file, or a child that died before emitting)."""
    try:
        from dlaf_tpu.obs import read_records
    except Exception:
        return None
    try:
        payloads = [r.get("payload") for r in read_records(path)
                    if r.get("type") == "bench_result"]
    except (OSError, ValueError):
        return None
    return payloads[-1] if payloads and isinstance(payloads[-1], dict) \
        else None


def sweep(platform: str) -> None:
    """Parent: run the variant sweep, each variant in a timeout-guarded
    subprocess; print the driver's single JSON line from the best result."""
    from dlaf_tpu.algorithms.cholesky import VALID_TRAILING

    # CPU regime either way: explicit fallback re-exec, or a plugin-less
    # environment whose only platform IS cpu (the int8-emulation variant
    # has no hardware to win on there)
    on_cpu = bool(os.environ.get("DLAF_BENCH_CPU_FALLBACK")) \
        or platform == "cpu"
    pinned = os.environ.get("DLAF_BENCH_TRAILING")
    # measured winner first (ozaki 91-99 GF/s vs xla 37-47 on the v5e
    # tunnel, honest hard_fence timing): if the time budget runs out or a
    # later variant wedges, the best measurement has already landed
    # the group-form A/B arm pins whichever form ozaki_group=auto does
    # NOT resolve to on this platform (concat on TPU, dots elsewhere),
    # so "ozaki" (the auto default) vs the pinned arm is a real A/B.
    # "+la1" arms re-run a form under the pipelined step order
    # (cholesky_lookahead=1) against the plain serialized arm — the
    # look-ahead A/B the bench artifact must carry on every run.
    # (trailing="xla" delegates the whole factorization to one fused XLA
    # cholesky — no step chain to pipeline, so it has no "+la1" arm; the
    # unrolled-order A/B rides the stepped forms instead)
    # the eigensolver stage A/B arms (tridiag dc_level_batch, btr2b
    # bt_lookahead — ISSUE 6) run LAST: the headline cholesky sweep owns
    # the budget, and the stage pairs are informational artifact rows
    ab_arm = "ozaki_dots" if platform == "tpu" else "ozaki_concat"
    # the fused-panel pair (ISSUE 10) rides after the stage arms: f32,
    # its own workload label, plain arm pinned to panel_impl=xla
    order = ["ozaki", "ozaki+la1", ab_arm, "xla", "scan", "scan+la1",
             "loop", "loop+la1", "biggemm", "biggemm+la1", "invgemm",
             "tridiag", "tridiag+dcb1", "btr2b", "btr2b+btla1", "btb2t",
             "fpanel", "fpanel+fp1", "fstep", "fstep+fs1", "serve",
             "overload", "autotune", "fleet"]

    def _known(v):
        b = v[: -len("+la1")] if v.endswith("+la1") else v
        return b in VALID_TRAILING or v == ab_arm \
            or v.split("+")[0] in STAGE_BASES

    variants = [pinned] if pinned else \
        [v for v in order if _known(v)] + \
        [v for v in VALID_TRAILING if v not in order]
    if on_cpu and not pinned:
        # the CPU fallback has fast native f64 — the int8-emulation variant
        # has no hardware to win on there; accelerators keep it leading
        variants = [v for v in variants if not v.startswith("ozaki")]
        variants = sorted(variants, key=lambda v: v != "xla")

    budget_s = float(os.environ.get("DLAF_BENCH_BUDGET", "1800"))
    sweep_t0 = time.perf_counter()
    results = []
    import tempfile

    # per-variant obs artifacts: the child's spans, collective byte
    # counters, and its bench_result record (the parent's result channel)
    art_dir = os.environ.get("DLAF_BENCH_OBS_DIR") or tempfile.mkdtemp(
        prefix="dlaf_bench_obs_")
    os.makedirs(art_dir, exist_ok=True)
    log(f"obs artifacts: {art_dir}")
    for vi, variant in enumerate(variants):
        if vi > 0 and time.perf_counter() - sweep_t0 > budget_s:
            log(f"budget {budget_s}s exhausted; skipping {variants[vi:]}")
            break
        if any(r["variant"] == variant for r in results):
            # a child may relabel itself (ozaki -> biggemm when f64 is
            # unavailable); don't re-measure the identical configuration
            log(f"[{variant}] already measured (child relabel); skipping")
            continue
        env = dict(os.environ)
        env["DLAF_BENCH_VARIANT"] = variant
        # every arm's artifact carries a paired accuracy record next to
        # its bench_result (docs/accuracy.md); explicit env still wins
        env.setdefault("DLAF_ACCURACY", "1")
        art = os.path.join(art_dir, f"{variant}.jsonl")
        # the sink appends: drop any artifact from a previous sweep in a
        # reused DLAF_BENCH_OBS_DIR so a child that dies before emitting
        # can't inherit a stale bench_result record
        if os.path.exists(art):
            os.unlink(art)
        env["DLAF_METRICS_PATH"] = art
        try:
            proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                  env=env, timeout=VARIANT_TIMEOUT_S,
                                  stdout=subprocess.PIPE)
            line = read_bench_result(art)
            if line is None:
                # no artifact (old child, crash before flush): stdout tail
                tail = proc.stdout.decode().strip().splitlines()[-1:]
                if proc.returncode == 0 and tail:
                    try:
                        line = json.loads(tail[0])
                    except ValueError:
                        line = None   # stray non-JSON final line
            if proc.returncode == 0 and line is not None:
                results.append(line)
            else:
                log(f"[{variant}] child rc={proc.returncode}, no result")
        except subprocess.TimeoutExpired:
            # the measurement may already have landed: the child flushes
            # its bench_result to the line-buffered artifact BEFORE the
            # post-measurement work (accuracy probe, devtrace
            # attribution of a large trace) that can eat the rest of the
            # budget — a timeout there must not discard a landed number
            line = read_bench_result(art)
            if line is not None:
                results.append(line)
                log(f"[{variant}] timed out after {VARIANT_TIMEOUT_S}s "
                    "AFTER its measurement landed; result recovered from "
                    "the artifact")
            else:
                log(f"[{variant}] timed out after {VARIANT_TIMEOUT_S}s; "
                    "killed (measurements from other variants are "
                    "unaffected)")
        except Exception as e:
            log(f"[{variant}] failed: {e!r}")
    if not results:
        log("no variant produced a measurement")
        sys.exit(1)
    n = int(os.environ.get("DLAF_BENCH_N", "4096"))
    nb = int(os.environ.get("DLAF_BENCH_NB", "256"))
    result = assemble_headline(results, n, nb)
    if result is None:
        # stage arms alone cannot stand in for the cholesky headline
        log("no cholesky variant produced a measurement (and no recorded "
            "TPU history to replay)")
        sys.exit(1)
    print(json.dumps(result), flush=True)

    chol = [r for r in results if r.get("workload") in (None, "cholesky")]
    best = max(chol, key=lambda r: r["gflops"]) if chol else None
    # informational MXU-tier number (stderr only — the headline metric
    # stays f64 per BASELINE config #1)
    if best is not None and best["dtype"] == "float64" \
            and time.perf_counter() - sweep_t0 < budget_s:
        env = dict(os.environ)
        env["DLAF_BENCH_VARIANT"] = best["variant"]
        env["DLAF_BENCH_DTYPE"] = "float32"
        try:
            proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                  env=env, timeout=VARIANT_TIMEOUT_S,
                                  stdout=subprocess.PIPE)
            line = proc.stdout.decode().strip().splitlines()[-1:]
            if line:
                log(f"[info] float32: {json.loads(line[0])['gflops']} GFlop/s")
        except Exception as e:
            log(f"[info] float32 probe failed: {e!r}")


def main() -> None:
    if os.environ.get("DLAF_BENCH_VARIANT"):
        run_variant()
        return
    if os.environ.get("DLAF_BENCH_CPU_FALLBACK"):
        sweep("cpu")
        return
    platform = probe_devices()
    if platform is not None:
        sweep(platform)
        return
    log("accelerator unavailable/wedged; re-running on pure-CPU platform. "
        "NOTE: a '[cpu]' metric is the fallback, not the framework's TPU "
        "result — BASELINE.md records the measured v5e number for this "
        "exact config; re-run on a healthy tunnel.")
    rc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                        env=cpu_env()).returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
