#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline config (BASELINE.md #1): miniapp_cholesky, double, N=4096, nb=256,
1x1 local grid, using the reference's fenced-timing protocol and flop model
(``miniapp/miniapp_cholesky.cpp:123-164``): GFLOPS = total_ops(n^3/6, n^3/6)/t.

No absolute baseline exists (the reference publishes no numbers —
BASELINE.md), so ``vs_baseline`` is 1.0 for the first recorded round.

Robustness: TPU plugin/tunnel initialization can wedge (observed: PJRT
client creation blocking indefinitely). The benchmark therefore first probes
device init in a subprocess with a timeout; if the accelerator path is
unavailable it re-runs itself on the pure-CPU platform (plugin registration
disabled) and reports the platform in the metric, rather than hanging the
driver. All progress goes to stderr; stdout carries exactly one JSON line.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# healthy plugin init takes ~25 s; 240 s is generous while keeping the
# worst case (wedged tunnel: full probe + 2 short retries + pauses, then
# the CPU fallback) inside a driver-friendly total
PROBE_TIMEOUT_S = int(os.environ.get("DLAF_BENCH_PROBE_TIMEOUT", "240"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def probe_devices() -> bool:
    """Can a jax device backend come up in this environment? (subprocess,
    timed out rather than hanging forever). The accelerator tunnel has been
    observed to wedge transiently, so a failed probe is retried a couple of
    times with a pause before giving up on the accelerator."""
    code = ("import jax, sys; d = jax.devices(); "
            "print(d[0].platform, file=sys.stderr)")
    retries = int(os.environ.get("DLAF_BENCH_PROBE_RETRIES", "2"))
    for attempt in range(retries + 1):
        try:
            # full timeout once (cold plugin init is slow); a wedged tunnel
            # hangs rather than erroring, so retries get a short leash to
            # bound the worst case before the CPU fallback kicks in
            subprocess.run([sys.executable, "-c", code], check=True,
                           timeout=PROBE_TIMEOUT_S if attempt == 0 else 120,
                           stdout=subprocess.DEVNULL)
            return True
        except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as e:
            log(f"device probe attempt {attempt + 1}/{retries + 1} failed: "
                f"{type(e).__name__}")
            if attempt < retries:
                time.sleep(int(os.environ.get("DLAF_BENCH_PROBE_PAUSE", "60")))
    return False


def cpu_env() -> dict:
    from dlaf_tpu.tpu_info import cpu_subprocess_env

    env = cpu_subprocess_env()
    env["DLAF_BENCH_CHILD"] = "1"
    return env


def run_bench() -> None:
    t_start = time.time()
    import jax

    jax.config.update("jax_enable_x64", True)
    # persist compiled programs across runs/rounds: the unrolled
    # factorizations compile in minutes and run in milliseconds, so a warm
    # cache frees nearly the whole sweep budget for measurement. Routed
    # through the ordinary config knob (the per-variant config.initialize()
    # calls below apply it before the first compile); an existing env
    # setting wins, like any DLAF_* override.
    os.environ.setdefault(
        "DLAF_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
    devs = jax.devices()
    platform = devs[0].platform
    log(f"devices: {devs} ({time.time() - t_start:.1f}s)")

    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.miniapp.generators import hpd_element_fn
    from dlaf_tpu.types import total_ops

    n, nb = 4096, 256
    dtype = np.float64
    try:
        jax.jit(lambda x: x * 2)(jax.numpy.ones((2,), dtype=dtype)).block_until_ready()
    except Exception as e:  # platform without f64 support
        log(f"float64 unavailable ({e}); falling back to float32")
        dtype = np.float32

    size = GlobalElementSize(n, n)
    block = TileElementSize(nb, nb)
    ref = Matrix.from_element_fn(hpd_element_fn(n, dtype), size, block, dtype=dtype)

    # Trailing-update strategy A/B (config knob cholesky_trailing): measure
    # each on the actual hardware, report the best. DLAF_BENCH_TRAILING pins
    # a single variant (skips the sweep).
    from dlaf_tpu.algorithms.cholesky import VALID_TRAILING

    pinned = os.environ.get("DLAF_BENCH_TRAILING")
    # measured winner first (ozaki 99 GF/s vs xla 47 / loop 43 on the v5e
    # tunnel, honest hard_fence timing): if the time budget runs out (or the
    # accelerator tunnel wedges mid-sweep) the best measurement has landed
    order = ["ozaki", "xla", "loop", "biggemm", "invgemm"]
    variants = [pinned] if pinned else \
        [v for v in order if v in VALID_TRAILING] + \
        [v for v in VALID_TRAILING if v not in order]
    if platform == "cpu" and not pinned:
        # the CPU fallback has fast native f64 — the int8-emulation variant
        # has no hardware to win on there and would eat the sweep budget;
        # accelerators (tpu or otherwise) keep it, leading
        variants = [v for v in variants if v != "ozaki"]
        variants = sorted(variants, key=lambda v: v != "xla")
    if dtype != np.float64:
        # "ozaki" is the emulated-f64 path; for other dtypes it statically
        # falls back to biggemm — skip the duplicate (compile minutes) and
        # keep the metric label truthful
        variants = [v for v in variants if v != "ozaki"] or ["loop"]
    budget_s = float(os.environ.get("DLAF_BENCH_BUDGET", "1500"))

    import dlaf_tpu.config as config

    def timed_run(ref_mat, dt, n):
        """One fenced factorization (the reference's miniapp protocol)."""
        from dlaf_tpu.common.sync import hard_fence

        mat = ref_mat.with_storage(ref_mat.storage + 0)
        hard_fence(mat.storage)
        t0 = time.perf_counter()
        out = cholesky("L", mat)
        hard_fence(out.storage)
        t = time.perf_counter() - t0
        return t, total_ops(dt, n**3 / 6, n**3 / 6) / t / 1e9

    best, best_variant = 0.0, variants[0]
    sweep_t0 = time.perf_counter()
    for vi, variant in enumerate(variants):
        if vi > 0 and time.perf_counter() - sweep_t0 > budget_s:
            log(f"budget {budget_s}s exhausted; skipping {variants[vi:]}")
            break
        os.environ["DLAF_CHOLESKY_TRAILING"] = variant
        config.initialize()
        try:
            # 1 warmup (compile) + 4 timed: compiles cost minutes, timed runs
            # cost milliseconds — extra repetitions capture the fast tail of
            # the run-to-run spread at zero budget cost
            for i in range(5):
                t, gflops = timed_run(ref, dtype, n)
                log(f"[{variant}] run {i}: {t:.4f}s {gflops:.1f} GFlop/s")
                if i > 0 and gflops > best:
                    best, best_variant = gflops, variant
        except Exception as e:
            log(f"[{variant}] failed: {e!r}")
    os.environ.pop("DLAF_CHOLESKY_TRAILING", None)
    config.initialize()
    if best == 0.0:
        log("all trailing variants failed; no measurement")
        sys.exit(1)

    # the driver's JSON line goes out FIRST: anything after this (the f32
    # info probe) can wedge on the accelerator without losing the landed
    # f64 measurement
    result = {
        "metric": (f"miniapp_cholesky {np.dtype(dtype).name} N={n} nb={nb} "
                   f"local GFlop/s [{platform}] trailing={best_variant}"),
        "value": round(best, 2),
        "unit": "GFlop/s",
        "vs_baseline": 1.0,
    }
    print(json.dumps(result), flush=True)

    # informational MXU-tier number (stderr only — the headline metric stays
    # f64 per BASELINE config #1): same fenced protocol at float32
    if dtype == np.float64 and time.perf_counter() - sweep_t0 < budget_s:
        try:
            os.environ["DLAF_CHOLESKY_TRAILING"] = best_variant
            config.initialize()
            ref32 = Matrix.from_element_fn(hpd_element_fn(n, np.float32),
                                           size, block, dtype=np.float32)
            for i in range(3):  # run 0 = compile warmup, like the f64 sweep
                t, g32 = timed_run(ref32, np.float32, n)
                if i > 0:
                    log(f"[info] float32 run {i}: {t:.4f}s {g32:.1f} GFlop/s")
        except Exception as e:
            log(f"[info] float32 probe failed: {e!r}")
        finally:
            os.environ.pop("DLAF_CHOLESKY_TRAILING", None)
            config.initialize()


def main() -> None:
    if os.environ.get("DLAF_BENCH_CHILD"):
        run_bench()
        return
    if probe_devices():
        os.environ["DLAF_BENCH_CHILD"] = "1"
        run_bench()
        return
    log("accelerator unavailable/wedged; re-running on pure-CPU platform. "
        "NOTE: a '[cpu]' metric is the fallback, not the framework's TPU "
        "result — BASELINE.md records the measured v5e number for this "
        "exact config; re-run on a healthy tunnel.")
    rc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                        env=cpu_env()).returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
