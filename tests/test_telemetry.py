"""Tests for ISSUE 7: program telemetry, rank-aware artifacts +
aggregation/Chrome export, and the bench-regression gate.

Covers: the DLAF_PROGRAM_TELEMETRY knob end-to-end (compile walls,
retrace counters, HBM gauges, the ``program`` record type,
``--require-telemetry``), the bitwise no-op contract (knob on == knob
off on the algorithm paths), the ``%r`` per-rank artifact template,
``dlaf_tpu.obs.aggregate`` (skew/imbalance/overlap + Chrome trace), the
schema-validated bench history path, and ``scripts/bench_gate.py``
(clean replay passes, an injected 20 % slowdown trips the gate).
"""

import json
import math
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)


@pytest.fixture(autouse=True)
def telemetry_reset():
    """Leave every test with the suite's default unobserved config."""
    yield
    for key in ("DLAF_METRICS_PATH", "DLAF_TRACE_DIR", "DLAF_LOG",
                "DLAF_PROGRAM_TELEMETRY"):
        os.environ.pop(key, None)
    obs._reset_for_tests()
    C.finalize()
    C.initialize()


def _hpd(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


def _telemetry_on(tmp_path, name="tele.jsonl"):
    path = str(tmp_path / name)
    C.initialize(C.Configuration(metrics_path=path, program_telemetry=True))
    return path


# ---------------------------------------------------------------------------
# program telemetry (tentpole)
# ---------------------------------------------------------------------------

def test_telemetry_call_records_compile_and_retrace(tmp_path):
    """telemetry.call: one compile record + retrace count per distinct
    program; a second same-shape call reuses the executable; a new shape
    is a retrace. The artifact validates under --require-telemetry."""
    path = _telemetry_on(tmp_path)
    f = jax.jit(lambda x: x * 2.0)
    a = jnp.ones((8, 8))
    out1 = obs.telemetry.call("toy", f, a)
    out2 = obs.telemetry.call("toy", f, a)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    obs.telemetry.call("toy", f, jnp.ones((4, 4)))
    obs.flush()
    recs = obs.read_records(path)
    compiles = [r for r in recs if r.get("type") == "program"
                and r.get("event") == "compile"]
    assert len(compiles) == 2               # 2 shapes -> 2 programs
    for r in compiles:
        assert r["site"] == "toy"
        assert math.isfinite(r["compile_s"]) and r["compile_s"] >= 0
        assert math.isfinite(r["trace_s"])
        assert all(math.isfinite(v) for v in r["hbm"].values())
        assert "peak" in r["hbm"]
    snap = [r for r in recs if r.get("type") == "metrics"][-1]["metrics"]
    retrace = [m for m in snap if m["name"] == "dlaf_retrace_total"]
    assert retrace and retrace[0]["labels"] == {"site": "toy"} \
        and retrace[0]["value"] == 2.0
    hbm = {(m["labels"]["what"]) for m in snap
           if m["name"] == "dlaf_hbm_bytes"}
    assert {"args", "output", "temp", "peak"} <= hbm
    assert obs.validate_file(path, require_telemetry=True) == []


def test_telemetry_off_is_passthrough():
    """Knob off: call() returns the jitted callable's own result and
    builds no program cache, no records, no registry metrics."""
    C.initialize()
    assert not obs.telemetry.active()
    f = jax.jit(lambda x: x + 1)
    out = obs.telemetry.call("toy", f, jnp.zeros((4,)))
    np.testing.assert_array_equal(np.asarray(out), np.ones((4,)))
    assert obs.telemetry._PROGRAMS == {}


def test_program_cache_is_bounded(tmp_path, monkeypatch):
    """The AOT program cache evicts LRU at MAX_PROGRAMS — a long-lived
    telemetry-on process sweeping many shapes must not pin every dead
    executable forever."""
    from dlaf_tpu.obs import telemetry

    _telemetry_on(tmp_path)
    monkeypatch.setattr(telemetry, "MAX_PROGRAMS", 3)
    f = jax.jit(lambda x: x + 1)
    for n in range(1, 6):
        obs.telemetry.call("bounded", f, jnp.zeros((n,)))
    assert len(telemetry._PROGRAMS) == 3
    # the newest shapes survived; re-calling one is a cache hit (no new
    # compile record)
    before = len([1 for k in telemetry._PROGRAMS])
    obs.telemetry.call("bounded", f, jnp.zeros((5,)))
    assert len(telemetry._PROGRAMS) == before


def test_aot_compile_probe_api(tmp_path):
    """aot_compile always measures (the probe scripts' contract) but only
    records when the knob is on."""
    C.initialize()                          # knob off
    f = jax.jit(lambda x: x @ x)
    spec = jax.ShapeDtypeStruct((16, 16), np.float64)
    prog = obs.telemetry.aot_compile("probe", f, spec)
    assert math.isfinite(prog.compile_s) and math.isfinite(prog.trace_s)
    assert prog.memory is not None and "peak" in prog.memory
    assert prog.memory["peak"] >= 0
    # executing the compiled program works (concrete args)
    out = prog.compiled(jnp.eye(16, dtype=np.float64))
    np.testing.assert_array_equal(np.asarray(out), np.eye(16))

    path = _telemetry_on(tmp_path)
    obs.telemetry.aot_compile("probe", f, spec)
    obs.flush()
    recs = obs.read_records(path)
    assert any(r.get("type") == "program" and r.get("event") == "compile"
               and r.get("site") == "probe" for r in recs)


def test_cholesky_local_bitwise_noop_and_telemetry(tmp_path):
    """The acceptance pin: knob off == knob on, bitwise, on the local
    cholesky path — and with the knob on the artifact carries the
    cholesky.local program telemetry."""
    n, nb = 64, 16
    a = _hpd(n)
    C.initialize()
    ref = cholesky_bytes(a, nb)

    path = _telemetry_on(tmp_path)
    assert obs.telemetry.active()
    got = cholesky_bytes(a, nb)
    np.testing.assert_array_equal(ref, got)   # exact — same program
    obs.flush()
    recs = obs.read_records(path)
    sites = {r.get("site") for r in recs if r.get("type") == "program"}
    assert "cholesky.local" in sites
    assert obs.validate_file(path, require_telemetry=True) == []


def cholesky_bytes(a, nb):
    from dlaf_tpu.algorithms.cholesky import cholesky

    mat = Matrix.from_global(a, TileElementSize(nb, nb))
    out = cholesky("L", mat)
    return np.asarray(out.to_numpy()).tobytes()


def test_cholesky_distributed_bitwise_noop(devices8):
    """Same pin on the distributed builder (2x2 grid): telemetry reroutes
    dispatch through the AOT executable; the numbers must not move."""
    from dlaf_tpu.comm.grid import Grid

    n, nb = 64, 16
    a = _hpd(n)

    def run():
        from dlaf_tpu.algorithms.cholesky import cholesky

        mat = Matrix.from_global(a, TileElementSize(nb, nb),
                                 grid=Grid(2, 2))
        return np.asarray(cholesky("L", mat).to_numpy()).tobytes()

    C.initialize()
    ref = run()
    C.initialize(C.Configuration(program_telemetry=True))
    assert obs.telemetry.active()
    got = run()
    assert ref == got
    # the registry carries the dist site's trace count even without a sink
    snap = obs.registry().snapshot()
    retr = [m for m in snap if m["name"] == "dlaf_retrace_total"
            and m["labels"].get("site") == "cholesky.dist"]
    assert retr and retr[0]["value"] >= 1


def test_triangular_solve_dist_telemetry_bitwise(tmp_path, devices8):
    """telemetry.call on the distributed triangular solve: bitwise, and
    the site lands in the artifact."""
    from dlaf_tpu.algorithms.triangular import triangular_solve
    from dlaf_tpu.comm.grid import Grid

    n, nb = 32, 8
    rng = np.random.default_rng(1)
    a = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    b = rng.standard_normal((n, n))

    def run():
        am = Matrix.from_global(a, TileElementSize(nb, nb), grid=Grid(2, 2))
        bm = Matrix.from_global(b, TileElementSize(nb, nb), grid=Grid(2, 2))
        return np.asarray(
            triangular_solve("L", "L", "N", "N", 1.0, am, bm)
            .to_numpy()).tobytes()

    C.initialize()
    ref = run()
    path = _telemetry_on(tmp_path)
    got = run()
    assert ref == got
    obs.flush()
    sites = {r.get("site") for r in obs.read_records(path)
             if r.get("type") == "program"}
    assert "triangular_solve.dist" in sites


# ---------------------------------------------------------------------------
# rank-aware artifacts (%r template, rank stamping)
# ---------------------------------------------------------------------------

def test_rank_template_and_stamping(tmp_path):
    """%r in DLAF_METRICS_PATH resolves to the process rank and every
    record carries the rank field."""
    jax.process_index()     # ensure a live backend: rank resolution is
    tpl = str(tmp_path / "art.r%r.jsonl")   # deliberately non-forcing
    C.initialize(C.Configuration(metrics_path=tpl))
    with obs.span("x"):
        pass
    obs.flush()
    rank = jax.process_index()
    path = tpl.replace("%r", str(rank))
    assert os.path.exists(path)
    recs = obs.read_records(path)
    assert recs and all(r.get("rank") == rank for r in recs)
    assert obs.validate_file(path) == []


def test_set_rank_overrides_stamp(tmp_path):
    path = str(tmp_path / "ranked.jsonl")
    C.initialize(C.Configuration(metrics_path=path))
    obs.set_rank(7)
    with obs.span("x"):
        pass
    assert all(r["rank"] == 7 for r in obs.read_records(path))


def test_rank_template_defers_without_backend(tmp_path, monkeypatch):
    """Before any backend exists the %r template must NOT force
    jax.process_index() (it would initialize the local backend — fatal
    on a multi-host worker that has yet to run jax.distributed
    .initialize); expansion defers to the sink's first write."""
    from dlaf_tpu.obs import _state, sinks

    monkeypatch.setattr(_state, "current_rank", lambda: None)
    tpl = str(tmp_path / "d.r%r.jsonl")
    assert sinks.expand_rank_template(tpl) == tpl       # deferred
    sink = sinks.JsonlSink(tpl)
    # the backend comes up (multihost init pinned rank 2) before the
    # first write: the deferred template resolves there
    monkeypatch.setattr(_state, "current_rank", lambda: 2)
    sink.write({"type": "log", "level": "info", "logger": "t", "msg": "m",
                "fields": {}})
    sink.close()
    assert sink.path.endswith("d.r2.jsonl") and os.path.exists(sink.path)
    assert obs.read_records(sink.path)[0]["rank"] == 2


# ---------------------------------------------------------------------------
# aggregation + Chrome export
# ---------------------------------------------------------------------------

def _write_rank_artifact(path, rank, t0, extra_metrics=()):
    sink = obs.JsonlSink(str(path))
    # two nested spans; ts is the EXIT time by schema
    sink.write({"type": "span", "name": "cholesky", "dur_s": 0.4,
                "depth": 1, "parent": "run", "attrs": {"lookahead": 1},
                "ts": t0 + 0.45, "rank": rank})
    sink.write({"type": "span", "name": "run", "dur_s": 0.5, "depth": 0,
                "parent": None, "attrs": {}, "ts": t0 + 0.5, "rank": rank})
    sink.write({"type": "program", "site": "cholesky.dist",
                "event": "compile", "compile_s": 0.1, "trace_s": 0.02,
                "hbm": {"peak": 1024.0}, "attrs": {}, "ts": t0 + 0.2,
                "rank": rank})
    sink.write({"type": "metrics", "ts": t0 + 0.6, "rank": rank,
                "metrics": [
                    {"name": "dlaf_comm_collective_bytes_total",
                     "kind": "counter",
                     "labels": {"kind": "bcast", "axis": "row"},
                     "value": 1000.0 * (1 + rank)},
                    *extra_metrics]})
    sink.close()


def test_aggregate_merges_and_reports(tmp_path, capsys):
    from dlaf_tpu.obs import aggregate as agg

    t0 = 1000.0
    p0, p1 = tmp_path / "a.r0.jsonl", tmp_path / "a.r1.jsonl"
    _write_rank_artifact(p0, 0, t0)
    _write_rank_artifact(p1, 1, t0 + 0.1)
    records = agg.merge_artifacts([str(p0), str(p1)])
    assert sorted({r["rank"] for r in records}) == [0, 1]
    # ts-ordered merge
    assert [r.get("ts") for r in records] == \
        sorted(r.get("ts") for r in records)

    rows = agg.rank_skew_rows(records)
    by_name = {row["name"]: row for row in rows}
    assert by_name["run"]["per_rank"][0]["count"] == 1
    assert by_name["run"]["skew_s"] == pytest.approx(0.0)

    imb = agg.collective_imbalance(records)
    assert imb and imb[0]["ratio"] == pytest.approx(2.0)

    ov = agg.overlap_report(records)
    assert set(ov["rank_wall_s"]) == {0, 1}
    # rank 1 starts 0.1 s late over a 0.4 s span -> 75% aligned
    assert ov["aligned"]["cholesky"] == pytest.approx(0.75, abs=1e-6)
    assert ov["knobs"] == {"lookahead": [1]}


def test_rebase_per_rank_removes_clock_offset(tmp_path):
    """--align: a constant inter-host clock offset must drop out of the
    cross-rank aligned fraction (simultaneous work on offset clocks
    reads ~0% aligned without it)."""
    from dlaf_tpu.obs import aggregate as agg

    t0 = 7000.0
    p0, p1 = tmp_path / "c.r0.jsonl", tmp_path / "c.r1.jsonl"
    _write_rank_artifact(p0, 0, t0)
    _write_rank_artifact(p1, 1, t0 + 50.0)   # 50 s clock offset: disjoint
    records = agg.merge_artifacts([str(p0), str(p1)])
    assert agg.overlap_report(records)["aligned"]["cholesky"] == 0.0
    aligned = agg.overlap_report(agg.rebase_per_rank(records))
    assert aligned["aligned"]["cholesky"] == pytest.approx(1.0)
    # walls are offset-invariant either way
    assert aligned["rank_wall_s"] == \
        agg.overlap_report(records)["rank_wall_s"]


def test_overlap_wall_spans_latest_end(tmp_path):
    """The per-rank wall runs to the LATEST span end, not the end of the
    latest-starting span: a short step span nested inside a long entry
    span must not understate the wall (and inflate every share)."""
    from dlaf_tpu.obs import aggregate as agg

    t0 = 5000.0
    p = tmp_path / "w.r0.jsonl"
    sink = obs.JsonlSink(str(p))
    sink.write({"type": "span", "name": "entry", "dur_s": 10.0, "depth": 0,
                "parent": None, "attrs": {}, "ts": t0 + 10.0, "rank": 0})
    sink.write({"type": "span", "name": "step", "dur_s": 1.0, "depth": 1,
                "parent": "entry", "attrs": {}, "ts": t0 + 2.0, "rank": 0})
    sink.close()
    ov = agg.overlap_report(agg.merge_artifacts([str(p)]))
    assert ov["rank_wall_s"][0] == pytest.approx(10.0)
    assert ov["share"]["entry"][0] == pytest.approx(1.0)
    assert ov["share"]["step"][0] == pytest.approx(0.1)


def test_aggregate_cli_chrome_and_merged(tmp_path, capsys):
    from dlaf_tpu.obs.aggregate import main

    t0 = 2000.0
    p0, p1 = tmp_path / "b.r0.jsonl", tmp_path / "b.r1.jsonl"
    _write_rank_artifact(p0, 0, t0)
    _write_rank_artifact(p1, 1, t0)
    merged = str(tmp_path / "merged.jsonl")
    chrome = str(tmp_path / "trace.json")
    assert main([str(p0), str(p1), "-o", merged, "--chrome", chrome]) == 0
    capsys.readouterr()
    # merged artifact is schema-valid and rank-complete
    assert obs.validate_file(merged) == []
    ranks = {r.get("rank") for r in obs.read_records(merged)}
    assert ranks == {0, 1}
    # chrome export: valid trace-event JSON, spans from EVERY rank,
    # process metadata naming each rank
    doc = json.load(open(chrome))
    evs = doc["traceEvents"]
    span_pids = {e["pid"] for e in evs
                 if e.get("ph") == "X" and e.get("tid") == 0}
    assert span_pids == {0, 1}
    names = {(e["pid"], e["args"]["name"]) for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {(0, "rank 0"), (1, "rank 1")}
    # program compiles ride their own track
    assert any(e.get("tid") == 1 and e.get("ph") == "X" for e in evs)
    # durations are microseconds: the 0.5 s span
    run_ev = [e for e in evs if e.get("ph") == "X" and e["name"] == "run"]
    assert run_ev and run_ev[0]["dur"] == pytest.approx(0.5e6)


def test_aggregate_cli_exit_codes(tmp_path, capsys):
    from dlaf_tpu.obs.aggregate import main

    assert main([]) == 2
    assert main(["--bogus", "x.jsonl"]) == 2
    missing = str(tmp_path / "missing.jsonl")
    assert main([missing]) == 1
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert main([empty]) == 1
    capsys.readouterr()


def test_aggregate_infers_rank_from_filename(tmp_path):
    from dlaf_tpu.obs.aggregate import (UNRESOLVED_RANK_BASE, infer_rank,
                                        merge_artifacts)

    assert infer_rank("metrics.r3.jsonl", 9) == 3
    assert infer_rank("mc_r12.jsonl", 9) == 12
    assert infer_rank("metrics.jsonl", 9) == 9
    # an unresolved-rank placeholder file (pre-backend-init writes) must
    # NOT absorb into a positional rank that may collide with a real one
    # — with or without the conventional 'r' template prefix
    assert infer_rank("metrics.ru4242.jsonl", 3) == \
        UNRESOLVED_RANK_BASE + 4242
    assert infer_rank("metrics.u4242.jsonl", 3) == \
        UNRESOLVED_RANK_BASE + 4242
    p = tmp_path / "c.r5.jsonl"
    sink = obs.JsonlSink(str(p))
    sink.write({"type": "log", "level": "info", "logger": "t", "msg": "m",
                "fields": {}})
    sink.close()
    recs = merge_artifacts([str(p)])
    # records that already carry a stamped rank keep it; only unstamped
    # ones inherit the filename rank — here the sink stamped the live
    # process rank, so strip it to exercise the fallback
    raw = [json.loads(line) for line in open(p)]
    for r in raw:
        r.pop("rank", None)
    with open(p, "w") as f:
        for r in raw:
            f.write(json.dumps(r) + "\n")
    recs = merge_artifacts([str(p)])
    assert all(r["rank"] == 5 for r in recs)


def test_profile_summary_shares_skew_table(tmp_path, capsys):
    """scripts/profile_summary.py JSONL mode prints the per-rank skew
    table through obs.aggregate (shared code, not a fork)."""
    import profile_summary

    t0 = 3000.0
    p = tmp_path / "ps.r0.jsonl"
    _write_rank_artifact(p, 0, t0)
    profile_summary.summarize_jsonl(str(p), 10)
    out = capsys.readouterr().out
    assert "per-rank span skew" in out
    assert "program telemetry" in out


# ---------------------------------------------------------------------------
# schema-validated bench history
# ---------------------------------------------------------------------------

def _history_line(**over):
    line = {"variant": "ozaki", "platform": "tpu", "dtype": "float64",
            "n": 4096, "nb": 256, "gflops": 100.0, "t": 0.229,
            "ts": "2026-08-03T00:00:00", "source": "test"}
    line.update(over)
    return line


def test_append_history_line_rejects_non_finite(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    obs.append_history_line(path, _history_line())
    with pytest.raises(ValueError, match="gflops"):
        obs.append_history_line(path, _history_line(gflops=float("nan")))
    with pytest.raises(ValueError, match="variant"):
        obs.append_history_line(path, _history_line(variant=""))
    # the bad lines never landed
    assert len(obs.read_history_records(path)) == 1


def test_measure_common_append_validates(tmp_path, monkeypatch):
    import measure_common

    monkeypatch.setattr(measure_common, "repo_root", lambda: str(tmp_path))
    line = measure_common.append_history("cpu", 64, 16, 1.5, 0.01,
                                         source="test", variant="loop")
    assert line["gflops"] == 1.5
    with pytest.raises(ValueError):
        measure_common.append_history("cpu", 64, 16, float("inf"), 0.01,
                                      source="test", variant="loop")
    hist = obs.read_history_records(str(tmp_path / ".bench_history.jsonl"))
    assert len(hist) == 1


def test_best_recorded_fails_loudly_on_malformed_history(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_module_tele", os.path.join(os.path.dirname(SCRIPTS),
                                          "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    path = str(tmp_path / "hist.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(_history_line()) + "\n")
        f.write('{"variant": "ozaki", "gflops": NaN}\n')
    with pytest.raises(ValueError):
        bench.best_recorded("tpu", 4096, 256, path=path)
    # a clean file still resolves
    with open(path, "w") as f:
        f.write(json.dumps(_history_line()) + "\n")
    assert bench.best_recorded("tpu", 4096, 256, path=path)["gflops"] == 100.0


def test_validate_cli_history_mode(tmp_path, capsys):
    from dlaf_tpu.obs.validate import main

    good = str(tmp_path / "good.jsonl")
    with open(good, "w") as f:
        f.write(json.dumps(_history_line()) + "\n")
    assert main([good, "--history"]) == 0
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps(_history_line(t=float("nan"))) + "\n")
    assert main([bad, "--history"]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# bench-regression gate
# ---------------------------------------------------------------------------

def _gate_history(tmp_path, gflops_by_key):
    path = str(tmp_path / "gate_hist.jsonl")
    with open(path, "w") as f:
        for (variant, platform), values in gflops_by_key.items():
            for g in values:
                f.write(json.dumps(_history_line(
                    variant=variant, platform=platform, gflops=g,
                    t=1.0 / max(g, 1e-9))) + "\n")
    return path


def test_bench_gate_clean_replay_and_injection(tmp_path, capsys):
    import bench_gate

    hist = _gate_history(tmp_path, {
        ("ozaki", "tpu"): [100.0, 104.0, 102.0, 98.0, 103.0],
        ("xla", "tpu"): [40.0, 41.0, 39.5],
    })
    assert bench_gate.main(["--history", hist, "--replay"]) == 0
    out = capsys.readouterr().out
    assert "no regressions" in out
    # the acceptance drill: 20% injected slowdown must exit nonzero
    assert bench_gate.main(["--history", hist, "--replay",
                            "--inject-slowdown", "0.2"]) == 1
    out = capsys.readouterr()
    assert "REGRESSION" in out.out


def test_bench_gate_fresh_artifacts(tmp_path, capsys):
    """Fresh measurements from an obs artifact's bench_result records:
    at baseline passes, 20% under baseline fails."""
    import bench_gate

    hist = _gate_history(tmp_path, {
        ("ozaki", "tpu"): [100.0, 104.0, 102.0, 98.0, 103.0]})

    def artifact(gflops):
        path = str(tmp_path / f"fresh_{gflops}.jsonl")
        sink = obs.JsonlSink(path)
        sink.write({"type": "bench_result",
                    "payload": _history_line(gflops=gflops)})
        sink.close()
        return path

    ok = artifact(101.0)
    assert bench_gate.main(["--history", hist, "--fresh", ok]) == 0
    slow = artifact(80.0)   # baseline median-of-best-3 = 103 -> floor 92.7
    assert bench_gate.main(["--history", hist, "--fresh", slow]) == 1
    capsys.readouterr()


def test_bench_gate_thin_history_is_report_only(tmp_path, capsys):
    import bench_gate

    hist = _gate_history(tmp_path, {("ozaki", "tpu"): [100.0, 101.0]})
    # 2 entries < --min-history 3: even a huge slowdown only reports
    assert bench_gate.main(["--history", hist, "--replay",
                            "--inject-slowdown", "0.5"]) == 0
    out = capsys.readouterr().out
    assert "THIN" in out and "report-only" in out


def test_bench_gate_new_key_is_report_only(tmp_path, capsys):
    import bench_gate

    hist = _gate_history(tmp_path, {
        ("ozaki", "tpu"): [100.0, 104.0, 102.0]})
    path = str(tmp_path / "new_key.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(_history_line(variant="brand_new",
                                         gflops=1.0)) + "\n")
    assert bench_gate.main(["--history", hist, "--fresh", path]) == 0
    out = capsys.readouterr().out
    assert "NEW" in out


def test_bench_gate_invalid_history_fails(tmp_path, capsys):
    import bench_gate

    bad = str(tmp_path / "bad_hist.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps(_history_line(gflops=float("nan"))) + "\n")
    assert bench_gate.main(["--history", bad, "--replay"]) == 1
    assert bench_gate.main(["--history", bad]) == 2   # no fresh, no replay
    capsys.readouterr()


def test_bench_gate_committed_history_replays_clean(capsys):
    """The real .bench_history.jsonl must pass its own gate (the CI
    smoke contract) and must flag the injected 20% drill."""
    import bench_gate

    assert bench_gate.main(["--replay"]) == 0
    assert bench_gate.main(["--replay", "--inject-slowdown", "0.2"]) == 1
    capsys.readouterr()
