"""Tests for block-cyclic index math.

Mirrors the reference's ``test/unit/matrix/test_util_distribution.cpp`` and
``test_distribution.cpp``: conversions are validated against a brute-force
enumeration of the block-cyclic assignment over grid-shape and source-rank
sweeps, including degenerate sizes.
"""

import pytest

from dlaf_tpu.common.index2d import (GlobalElementIndex, GlobalElementSize, GlobalTileIndex,
                                     GridSize2D, LocalTileIndex, RankIndex2D, TileElementSize)
from dlaf_tpu.matrix import util_distribution as ud
from dlaf_tpu.matrix.distribution import Distribution


def brute_force_axis(size, tile_size, grid, src):
    """Enumerate (global_tile -> (rank, local_tile)) the slow, obvious way."""
    nt = -(-size // tile_size) if size else 0
    owner = {}
    counts = {r: 0 for r in range(grid)}
    for t in range(nt):
        r = (src + t) % grid
        owner[t] = (r, counts[r])
        counts[r] += 1
    return nt, owner, counts


AXIS_CASES = [
    # (size, tile, grid, src)
    (0, 4, 3, 0), (1, 4, 1, 0), (10, 4, 1, 0), (10, 4, 3, 0), (10, 4, 3, 2),
    (12, 4, 3, 1), (16, 4, 4, 3), (17, 5, 2, 1), (4, 8, 3, 2), (100, 7, 5, 4),
]


@pytest.mark.parametrize("size,tile,grid,src", AXIS_CASES)
def test_axis_conversions_vs_bruteforce(size, tile, grid, src):
    nt, owner, counts = brute_force_axis(size, tile, grid, src)
    for t in range(nt):
        r, lt = owner[t]
        assert ud.rank_global_tile(t, grid, src) == r
        assert ud.local_tile_from_global_tile(t, grid) == lt
        assert ud.global_tile_from_local_tile(lt, grid, r, src) == t
    for r in range(grid):
        assert ud.local_nr_tiles(nt, grid, r, src) == counts[r]
        # local element count = sum of owned tile sizes
        expect_elems = sum(min(tile, size - t * tile) for t in range(nt) if owner[t][0] == r)
        assert ud.local_size(size, tile, grid, r, src) == expect_elems
        # next_local_tile: first local tile with global index >= t, for every
        # t in the valid domain [0, nt] (t == nt yields local_nr_tiles)
        for t in range(nt + 1):
            later = [owner[g][1] for g in range(t, nt) if owner[g][0] == r]
            expect = later[0] if later else counts[r]
            assert ud.next_local_tile_from_global_tile(t, grid, r, src) == expect


def test_element_tile_conversions():
    for el in range(23):
        t = ud.tile_from_element(el, 5)
        te = ud.tile_element_from_element(el, 5)
        assert 0 <= te < 5
        assert ud.element_from_tile_and_tile_element(t, te, 5) == el


GRID_CASES = [
    (GridSize2D(1, 1), RankIndex2D(0, 0), RankIndex2D(0, 0)),
    (GridSize2D(3, 2), RankIndex2D(1, 1), RankIndex2D(0, 0)),
    (GridSize2D(2, 3), RankIndex2D(0, 2), RankIndex2D(1, 2)),  # nonzero source rank
    (GridSize2D(4, 4), RankIndex2D(3, 0), RankIndex2D(2, 3)),
]


@pytest.mark.parametrize("grid,rank,src", GRID_CASES)
@pytest.mark.parametrize("m,n,mb,nb", [(0, 0, 4, 4), (10, 10, 4, 4), (13, 26, 5, 5),
                                       (26, 13, 4, 8), (3, 3, 8, 8)])
def test_distribution_2d(grid, rank, src, m, n, mb, nb):
    d = Distribution(GlobalElementSize(m, n), TileElementSize(mb, nb), grid, rank, src)
    ntr, owner_r, counts_r = brute_force_axis(m, mb, grid.row, src.row)
    ntc, owner_c, counts_c = brute_force_axis(n, nb, grid.col, src.col)
    assert (d.nr_tiles.row, d.nr_tiles.col) == (ntr, ntc)
    assert (d.local_nr_tiles.row, d.local_nr_tiles.col) == (counts_r[rank.row], counts_c[rank.col])

    for tr in range(ntr):
        for tc in range(ntc):
            gt = GlobalTileIndex(tr, tc)
            own = d.rank_global_tile(gt)
            assert (own.row, own.col) == (owner_r[tr][0], owner_c[tc][0])
            if own == rank:
                lt = d.local_tile_index(gt)
                assert (lt.row, lt.col) == (owner_r[tr][1], owner_c[tc][1])
                assert d.global_tile_index(lt) == gt
            # edge tile sizes
            ts = d.tile_size_of(gt)
            assert ts.row == min(mb, m - tr * mb)
            assert ts.col == min(nb, n - tc * nb)


def test_distribution_element_queries():
    d = Distribution(GlobalElementSize(13, 26), TileElementSize(5, 5),
                     GridSize2D(2, 3), RankIndex2D(1, 2), RankIndex2D(1, 1))
    for i in range(13):
        for j in range(26):
            ge = GlobalElementIndex(i, j)
            gt = d.global_tile_index(ge)
            te = d.tile_element_index(ge)
            assert d.global_element_index(gt, te) == ge
            assert d.rank_global_element(ge) == d.rank_global_tile(gt)


def test_local_tile_linear_index_colmajor():
    d = Distribution(GlobalElementSize(20, 20), TileElementSize(5, 5),
                     GridSize2D(2, 2), RankIndex2D(0, 0), RankIndex2D(0, 0))
    lnt = d.local_nr_tiles
    seen = [d.local_tile_linear_index(LocalTileIndex(r, c))
            for c in range(lnt.col) for r in range(lnt.row)]
    assert seen == list(range(lnt.row * lnt.col))
