"""Tests for auxiliary components: timer, views, printing, memory helpers,
tpu_info, kernel/band miniapps, scaling scripts."""

import subprocess
import sys

import numpy as np
import pytest

from dlaf_tpu.common.index2d import GlobalElementIndex, GlobalElementSize, \
    GlobalTileIndex, TileElementSize
from dlaf_tpu.common.timer import PhaseTimer, Timer
from dlaf_tpu.matrix import printing
from dlaf_tpu.matrix.distribution import Distribution
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.matrix.views import SubMatrixView, SubTileSpec


def test_timer():
    t = Timer()
    assert t.elapsed() >= 0
    pt = PhaseTimer()
    with pt.phase("a"):
        pass
    with pt.phase("a"):
        pass
    assert "a" in pt.report() and pt.report()["a"] >= 0


def test_submatrix_view():
    d = Distribution(GlobalElementSize(16, 16), TileElementSize(4, 4))
    v = SubMatrixView(d, GlobalElementIndex(5, 2))
    assert v.begin_tile == GlobalTileIndex(1, 0)
    spec = v.tile_spec(GlobalTileIndex(1, 0))
    assert spec == SubTileSpec(1, 2, 3, 2)
    spec2 = v.tile_spec(GlobalTileIndex(2, 1))
    assert spec2 == SubTileSpec(0, 0, 4, 4)


def test_printing(capsys):
    a = np.arange(4.0).reshape(2, 2)
    mat = Matrix.from_global(a, TileElementSize(2, 2))
    s = printing.print_numpy(mat, name="m")
    assert s.startswith("m = np.array(") and "dtype=np.float64" in s
    ns = {"np": np}
    exec(s, ns)
    np.testing.assert_array_equal(ns["m"], a)
    c = printing.print_csv(mat)
    assert c.splitlines()[0] == "0.0,1.0"


def test_memory_place():
    from dlaf_tpu.matrix import memory as mem

    x = mem.place(np.ones((4, 4)))
    assert x.shape == (4, 4) and hasattr(x, "devices")


def test_tpu_info():
    from dlaf_tpu import tpu_info

    devs = tpu_info.devices()
    assert len(devs) == 8
    assert all(d.platform == "cpu" for d in devs)


def test_effective_eps_platform_calibration(monkeypatch):
    """Residual-check eps: true dtype eps off-TPU; the double-f32
    emulation eps (2^-47, labeled — silicon-calibrated post peel-fix,
    see checks.EMULATED_F64_EPS) for 64-bit dtypes on TPU, where no
    code path can deliver 2^-53-grade results (miniapp/checks.py)."""
    from dlaf_tpu.miniapp import checks

    # CPU backend (this suite): nothing widened, no label
    for dt in (np.float32, np.float64, np.complex128):
        eps, label = checks.effective_eps(dt)
        assert eps == np.finfo(np.dtype(dt).type(0).real.dtype).eps
        assert label == ""

    monkeypatch.setattr(checks, "f64_is_emulated", lambda of=None: True)
    eps, label = checks.effective_eps(np.float64)
    assert eps == checks.EMULATED_F64_EPS and "2^-47" in label
    eps_c, label_c = checks.effective_eps(np.complex128)
    assert eps_c == checks.EMULATED_F64_EPS and label_c == label
    # f32 is native on TPU: untouched even when f64 is emulated
    eps32, label32 = checks.effective_eps(np.float32)
    assert eps32 == np.finfo(np.float32).eps and label32 == ""


def test_miniapp_kernel_and_band():
    from dlaf_tpu.miniapp.miniapp_kernel import run as krun

    res = krun(["--kernel", "gemm", "-m", "32", "--batch", "4", "--nruns", "1"])
    assert len(res) == 1 and res[0]["gflops"] > 0

    from dlaf_tpu.miniapp.miniapp_band_to_tridiag import run as brun

    res = brun(["-m", "64", "-b", "8", "--nruns", "1", "--check-result", "last"])
    assert len(res) == 1


def test_public_api_surface():
    """The reference's free-function layer is reachable from the subpackage
    roots (user-facing API contract)."""
    import numpy as np

    import dlaf_tpu.algorithms as alg
    import dlaf_tpu.eigensolver as eig
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix import Matrix

    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 16))
    a = x @ x.T + 16 * np.eye(16)
    m = Matrix.from_global(a, TileElementSize(4, 4))
    out = alg.cholesky("L", m).to_numpy()
    l = np.tril(out)
    assert np.linalg.norm(l @ l.T - a) < 1e-10 * np.linalg.norm(a)
    res = eig.eigensolver("L", m)
    np.testing.assert_allclose(res.eigenvalues, np.linalg.eigvalsh(a), atol=1e-9)


def test_checkpoint_roundtrip(tmp_path, devices8):
    """Matrix -> orbax checkpoint -> Matrix, local and distributed
    (the application-owned persistence hook; the reference has no
    checkpoint subsystem, SURVEY §5)."""
    import numpy as np

    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize
    from dlaf_tpu.matrix import checkpoint
    from dlaf_tpu.matrix.matrix import Matrix

    rng = np.random.default_rng(5)
    a = rng.standard_normal((24, 16))
    m = Matrix.from_global(a, TileElementSize(8, 8))
    checkpoint.save(str(tmp_path / "local"), m)
    m2 = checkpoint.load(str(tmp_path / "local"))
    np.testing.assert_array_equal(m2.to_numpy(), a)

    grid = Grid(2, 4)
    md = Matrix.from_global(a, TileElementSize(8, 8), grid=grid,
                            source_rank=RankIndex2D(1, 2))
    checkpoint.save(str(tmp_path / "dist"), md)
    md2 = checkpoint.load(str(tmp_path / "dist"), grid=grid)
    np.testing.assert_array_equal(md2.to_numpy(), a)
    assert md2.dist.source_rank == RankIndex2D(1, 2)


def test_miniapp_bt_band_to_tridiag():
    from dlaf_tpu.miniapp.miniapp_bt_band_to_tridiag import run as btrun

    res = btrun(["-m", "64", "-b", "8", "--nruns", "1", "--check-result", "last"])
    assert len(res) == 1 and res[0]["gflops"] > 0
    res = btrun(["-m", "64", "-b", "8", "--grid-rows", "2", "--grid-cols", "2",
                 "--nruns", "1", "--check-result", "last"])
    assert len(res) == 1


def test_miniapp_gen_eigensolver_standalone():
    from dlaf_tpu.miniapp.miniapp_gen_eigensolver import run as grun

    res = grun(["-m", "32", "-b", "8", "--nruns", "1", "--check-result", "last"])
    assert len(res) == 1 and res[0]["gflops"] > 0


def test_scaling_scripts():
    out = subprocess.run(
        [sys.executable, "scripts/gen_strong.py", "--miniapp", "cholesky",
         "-m", "1024", "-b", "128", "--grids", "1x1", "2x2"],
        capture_output=True, text=True, check=True, cwd="/root/repo").stdout
    assert out.count("miniapp_cholesky") == 2 and "--grid-rows 2" in out
    out = subprocess.run(
        [sys.executable, "scripts/gen_weak.py", "--m-per-device", "512",
         "-b", "128", "--grids", "1x1", "2x2"],
        capture_output=True, text=True, check=True, cwd="/root/repo").stdout
    assert "-m 512" in out and "-m 1024" in out


def test_plot_bench_parses(tmp_path):
    log = tmp_path / "run.log"
    log.write_text("[0] 1.5s 100.0GFlop/s dL (4096, 4096) (256, 256) (2, 2) 8 tpu\n"
                   "[1] 1.0s 150.0GFlop/s dL (4096, 4096) (256, 256) (2, 2) 8 tpu\n")
    out = subprocess.run(
        [sys.executable, "scripts/plot_bench.py", str(log)],
        capture_output=True, text=True, check=True, cwd="/root/repo").stdout
    assert "best=150.0GF/s" in out and "median=1.5" in out.replace("median=1.5000", "median=1.5")


def test_round_robin():
    from dlaf_tpu.common.round_robin import RoundRobin

    rr = RoundRobin(["a", "b", "c"])
    assert len(rr) == 3
    # nextResource cycles in order, wrapping (common/round_robin.h:24-30)
    assert [rr.next_resource() for _ in range(5)] == ["a", "b", "c", "a", "b"]
    assert rr.current_resource() == "b"  # re-read without advancing
    assert rr.current_resource() == "b"
    assert list(rr) == ["a", "b", "c"]  # pool iteration does not advance
    assert rr.next_resource() == "c"
    import pytest as _pytest
    with _pytest.raises(ValueError):
        RoundRobin([])


def test_profile_dir_hook(tmp_path):
    """--dlaf:profile-dir emits a jax.profiler trace (SURVEY §5 tracing;
    the green-field observability hook the reference lacks)."""
    from dlaf_tpu.miniapp.miniapp_cholesky import run as crun

    out = crun(["-m", "64", "-b", "16", "--nruns", "1",
                f"--dlaf:profile-dir={tmp_path}"])
    assert len(out) == 1
    assert any((tmp_path / p).exists() for p in ("plugins",)) or \
        any(tmp_path.iterdir())


def _load_bench_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_module", "/root/repo/bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_headline_live_tpu_wins():
    # a live TPU sweep takes the headline directly, no replay fields
    bench = _load_bench_module()
    results = [
        {"variant": "ozaki", "platform": "tpu", "dtype": "float64",
         "gflops": 95.0, "ts": "t1"},
        {"variant": "xla", "platform": "tpu", "dtype": "float64",
         "gflops": 41.0, "ts": "t2"},
    ]
    out = bench.assemble_headline(
        results, 4096, 256,
        hist_lookup=lambda **kw: {"gflops": 999.0, "dtype": "float64"})
    assert out["value"] == 95.0
    assert "[tpu]" in out["metric"] and "ozaki" in out["metric"]
    assert "replayed" not in out and "live_fallback" not in out


def test_bench_headline_fallback_replays_history():
    # a wedged-tunnel CPU sweep must NOT displace the recorded TPU result:
    # the headline is the replayed history entry, the live run a sidecar
    bench = _load_bench_module()
    results = [{"variant": "xla", "platform": "cpu", "dtype": "float64",
                "gflops": 13.6, "ts": "t-live"}]
    hist = {"variant": "ozaki", "platform": "tpu", "dtype": "float64",
            "n": 4096, "nb": 256, "gflops": 103.89,
            "ts": "2026-07-31T03:30:00", "source": "knob grid"}
    out = bench.assemble_headline(results, 4096, 256,
                                  hist_lookup=lambda **kw: hist)
    assert out["value"] == 103.89 and out["replayed"] is True
    assert "[tpu]" in out["metric"] and "trailing=ozaki" in out["metric"]
    assert out["replayed_ts"] == "2026-07-31T03:30:00"
    assert out["live_fallback"]["platform"] == "cpu"
    assert out["live_fallback"]["gflops"] == 13.6


def test_bench_headline_ignores_stage_arms():
    # the eigensolver stage arms (tridiag/btr2b — ISSUE 6) measure
    # different flop models; even a faster stage number must never take
    # the cholesky headline
    bench = _load_bench_module()
    results = [
        {"variant": "xla", "platform": "tpu", "dtype": "float64",
         "gflops": 41.0, "ts": "t1"},
        {"variant": "tridiag+dcb1", "platform": "tpu", "dtype": "float64",
         "gflops": 500.0, "workload": "tridiag", "ts": "t2"},
        {"variant": "btr2b+btla1", "platform": "tpu", "dtype": "float64",
         "gflops": 900.0, "workload": "btr2b", "ts": "t3"},
    ]
    out = bench.assemble_headline(results, 4096, 256,
                                  hist_lookup=lambda **kw: None)
    assert out["value"] == 41.0 and "xla" in out["metric"]


def test_bench_headline_ignores_fpanel_arms():
    # the fused-panel A/B pair (ISSUE 10) is an f32 arm with its own
    # workload label — a (cheap-dtype) faster number must never take the
    # f64 cholesky headline, and the pair must be known to the sweep
    bench = _load_bench_module()
    results = [
        {"variant": "loop", "platform": "tpu", "dtype": "float64",
         "gflops": 41.0, "ts": "t1"},
        {"variant": "fpanel+fp1", "platform": "tpu", "dtype": "float32",
         "gflops": 4000.0, "workload": "fpanel", "ts": "t2"},
    ]
    out = bench.assemble_headline(results, 4096, 256,
                                  hist_lookup=lambda **kw: None)
    assert out["value"] == 41.0 and "loop" in out["metric"]
    assert "fpanel" in bench.STAGE_BASES


def test_bench_headline_stage_arms_only():
    # every cholesky arm died, only stage arms landed: the headline is
    # the replayed TPU history entry when one exists, and None (sweep
    # exits nonzero) when it does not — never a mislabeled stage number
    bench = _load_bench_module()
    results = [
        {"variant": "tridiag+dcb1", "platform": "cpu", "dtype": "float64",
         "gflops": 500.0, "workload": "tridiag", "ts": "t"},
    ]
    hist = {"variant": "ozaki", "platform": "tpu", "dtype": "float64",
            "n": 4096, "nb": 256, "gflops": 103.89, "ts": "h"}
    out = bench.assemble_headline(results, 4096, 256,
                                  hist_lookup=lambda **kw: hist)
    assert out["value"] == 103.89 and out["replayed"] is True
    assert "trailing=ozaki" in out["metric"]
    out = bench.assemble_headline(results, 4096, 256,
                                  hist_lookup=lambda **kw: None)
    assert out is None


def test_bench_best_recorded_skips_stage_workloads(tmp_path):
    # history entries with a non-cholesky workload never feed the
    # replayed headline lookup
    import json

    bench = _load_bench_module()
    path = tmp_path / "hist.jsonl"
    # schema-complete lines (the validating history reader — obs.sinks —
    # rejects anything append_history could not have written)
    lines = [
        {"variant": "tridiag", "platform": "tpu", "dtype": "float64",
         "n": 2048, "nb": 256, "gflops": 777.0, "t": 0.01,
         "workload": "tridiag", "ts": "2026-08-03T00:00:00",
         "source": "test"},
        {"variant": "ozaki", "platform": "tpu", "dtype": "float64",
         "n": 2048, "nb": 256, "gflops": 99.0, "t": 0.01,
         "ts": "2026-08-03T00:00:00", "source": "test"},
    ]
    path.write_text("".join(json.dumps(x) + "\n" for x in lines))
    got = bench.best_recorded(platform="tpu", n=2048, nb=256,
                              path=str(path))
    assert got["gflops"] == 99.0 and got["variant"] == "ozaki"


def test_bench_headline_fallback_without_history():
    # no recorded TPU entry (fresh checkout): the live result stands,
    # honestly labeled with its platform
    bench = _load_bench_module()
    results = [{"variant": "xla", "platform": "cpu", "dtype": "float64",
                "gflops": 13.6, "ts": "t-live"}]
    out = bench.assemble_headline(results, 4096, 256,
                                  hist_lookup=lambda **kw: None)
    assert out["value"] == 13.6 and "[cpu]" in out["metric"]
    assert "replayed" not in out


def test_bench_best_recorded_real_history():
    # the committed .bench_history.jsonl must yield a TPU headline for the
    # driver's config (this is the replay source BENCH_r03 depends on)
    bench = _load_bench_module()
    hist = bench.best_recorded(platform="tpu", n=4096, nb=256)
    assert hist is not None and hist["gflops"] >= 103.0
    assert hist["dtype"] == "float64"
    # post-peel-fix preference: the config #1 replay must NOT pick a
    # pre-fix entry (they measured a corrupted decomposition; the stale
    # best is 119.6 pre-fix vs 117.7 post-fix)
    assert hist["ts"] >= bench.PEEL_FIX_TS


def test_bench_best_recorded_prefix_fallback(tmp_path):
    # a config only ever measured pre-fix still replays (labeled by its
    # own ts), rather than silently falling back to the CPU sidecar
    import json as _json
    bench = _load_bench_module()
    # schema-complete lines (the validating history reader — obs.sinks —
    # rejects anything append_history could not have written)
    rows = [
        {"platform": "tpu", "n": 2048, "nb": 256, "dtype": "float64",
         "gflops": 50.0, "t": 0.01, "variant": "ozaki",
         "ts": "2026-07-31T03:30:00", "source": "test"},
        {"platform": "tpu", "n": 2048, "nb": 256, "dtype": "float64",
         "gflops": 40.0, "t": 0.01, "variant": "ozaki",
         "ts": "2026-08-01T09:00:00", "source": "test"},
    ]
    hist_file = tmp_path / ".bench_history.jsonl"
    hist_file.write_text("\n".join(_json.dumps(r) for r in rows) + "\n")
    got = bench.best_recorded(platform="tpu", n=2048, nb=256,
                              path=str(hist_file))
    assert got is not None and got["gflops"] == 50.0
    # ...but one post-fix row beats every pre-fix row regardless of gflops
    with hist_file.open("a") as f:
        f.write(_json.dumps(
            {"platform": "tpu", "n": 2048, "nb": 256, "dtype": "float64",
             "gflops": 45.0, "t": 0.01, "variant": "ozaki",
             "ts": "2026-08-02T05:00:00", "source": "test"}) + "\n")
    got = bench.best_recorded(platform="tpu", n=2048, nb=256,
                              path=str(hist_file))
    assert got is not None and got["gflops"] == 45.0


@pytest.mark.parametrize("uplo", ["G", "L"])
def test_max_norm_local_and_distributed(uplo, devices8):
    # auxiliary::norm parity (reference auxiliary/norm/mc.h:29-108):
    # per-tile partial maxima folded locally then max-reduced over both
    # mesh axes; uplo='L' restricts to the stored lower triangle
    from dlaf_tpu.algorithms.norm import max_norm
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize

    rng = np.random.default_rng(7)
    a = rng.standard_normal((13, 13))
    a[11, 2] = 50.0    # strict-lower extreme
    a[1, 12] = -90.0   # strict-upper extreme (excluded under uplo='L')
    expect = np.abs(np.tril(a) if uplo == "L" else a).max()

    local = Matrix.from_global(a, TileElementSize(4, 4))
    assert np.isclose(max_norm(local, uplo), expect)

    dist = Matrix.from_global(a, TileElementSize(4, 4), grid=Grid(2, 4),
                              source_rank=RankIndex2D(1, 2))
    assert np.isclose(max_norm(dist, uplo), expect)

    empty = Matrix.from_global(np.zeros((0, 0)), TileElementSize(4, 4))
    assert max_norm(empty, uplo) == 0.0


def test_telescope_segments_properties():
    from dlaf_tpu.types import telescope_segments

    for steps in [0, 1, 2, 7, 8, 9, 11, 16, 31, 32, 64, 127, 128, 1000]:
        segs = telescope_segments(steps)
        assert sum(segs) == steps
        assert all(s > 0 for s in segs)
        # equal chunks: bounded program count, every chunk >= min size
        assert len(segs) <= 9   # max_segments + ragged tail
        if len(segs) > 1:
            assert all(s_ == segs[0] for s_ in segs[:-1])
            assert segs[-1] <= segs[0]
    assert telescope_segments(8) == (8,)
    assert telescope_segments(16) == (8, 8)
    assert telescope_segments(127) == (16,) * 7 + (15,)
    assert telescope_segments(64) == (8,) * 8


def test_telescope_windows_coalescing():
    """types.telescope_windows — the shared segment builder of every
    telescoped scan formulation: segments cover all steps exactly once in
    order, and adjacent segments with equal window descriptors merge into
    one (no duplicate identically-shaped step programs)."""
    from dlaf_tpu.types import telescope_windows

    # distinct windows: no merging, starts/lengths tile the step range
    segs = telescope_windows(32, lambda pos, _len: pos)
    assert [(s, l) for _, s, l in segs] == [(0, 8), (8, 8), (16, 8),
                                           (24, 8)]
    # slot-window style fn on a 4-rank axis: chunks whose k0 // 4 agree
    # coalesce (e.g. nt=32, chunks of 8 -> windows 0,2,4,6: distinct)
    segs = telescope_windows(32, lambda pos, _len: pos // 16)
    assert [(w, s, l) for w, s, l in segs] == [(0, 0, 16), (1, 16, 16)]
    # constant window: everything merges into ONE scan
    segs = telescope_windows(1000, lambda pos, _len: 0)
    assert segs == [(0, 0, 1000)]
    # length-dependent window (the reverse-sweep/bt form): merging keeps
    # coverage exact and ordered
    segs = telescope_windows(31, lambda pos, ln: (31 - pos - ln) // 8)
    assert sum(l for _, _, l in segs) == 31
    starts = [s for _, s, l in segs]
    assert starts == sorted(starts) and starts[0] == 0
    assert telescope_windows(0, lambda pos, _len: 0) == []


def test_summarize_session_parses_all_schemas(tmp_path, monkeypatch):
    """The session summarizer extracts the best line per step file for
    every miniapp schema variant and appends only TPU lines to the
    history log (redirected into tmp_path here)."""
    import importlib.util
    import json as _json

    out = tmp_path / "sess"
    out.mkdir()
    (out / "hegst.out").write_text(
        "[0] 12.0s 88.10GFlop/s zL (8192, 8192) (256, 256) (1, 1) 8 tpu\n"
        "[1] 10.0s 108.80GFlop/s zL (8192, 8192) (256, 256) (1, 1) 8 tpu\n"
        "check: PASSED residual=1e-10 tol=2e-9\n")
    (out / "eig.out").write_text(
        "[0] 300.0s 3.20GFlop/s dL evp (8192, 8192) (512, 512) (1, 1) 8 tpu\n"
        "[0] phases: reduction_to_band=100.0s\n")
    (out / "b2t.out").write_text(
        "[0] 175.0s 12.00GFlop/s d (32768, 32768) band=128 (1, 1) 8 host\n")
    (out / "cpu.out").write_text(
        "[0] 1.0s 5.00GFlop/s dL (1024, 1024) (256, 256) (1, 1) 1 cpu\n")

    spec = importlib.util.spec_from_file_location(
        "summarize_session", "/root/repo/scripts/summarize_session.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    import measure_common

    monkeypatch.setattr(measure_common, "repo_root", lambda: str(tmp_path))
    monkeypatch.setattr(sys, "argv", ["x", str(out)])
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        mod.main()
    summary = _json.loads(buf.getvalue().strip().splitlines()[-1])
    assert summary["hegst"] == {"gflops": 108.8, "platform": "tpu"}
    assert summary["eig"]["platform"] == "tpu"
    assert summary["b2t"]["platform"] == "host"
    hist = (tmp_path / ".bench_history.jsonl").read_text().splitlines()
    rows = [_json.loads(r) for r in hist]
    assert {r["variant"] for r in rows} == {"hegst", "eig"}  # tpu only
    h = next(r for r in rows if r["variant"] == "hegst")
    assert h["dtype"] == "complex128" and h["n"] == 8192 and h["t"] == 10.0


def test_layout_info_offsets_and_min_mem():
    """LayoutInfo parity (reference layout_info.h): tile offsets and
    minimal buffer size for both canonical layouts."""
    from dlaf_tpu.common.index2d import (LocalElementSize, LocalTileIndex,
                                         TileElementSize)
    from dlaf_tpu.matrix.layout_info import col_major_layout, tile_layout

    size = LocalElementSize(10, 7)
    block = TileElementSize(4, 4)
    cm = col_major_layout(size, block, ld=10)
    assert cm.nr_tiles == (3, 2)
    # col-major: vertical neighbor advances by block rows, horizontal by
    # block_cols * ld
    assert cm.tile_offset(LocalTileIndex(1, 0)) == 4
    assert cm.tile_offset(LocalTileIndex(0, 1)) == 4 * 10
    assert cm.tile_offset(LocalTileIndex(2, 1)) == 4 * 10 + 8
    # last element of the last (ragged 2x3) tile fits in min_mem_size
    assert cm.min_mem_size() == cm.tile_offset(LocalTileIndex(2, 1)) \
        + (3 - 1) * 10 + 2
    tl = tile_layout(size, block)
    assert tl.nr_tiles == (3, 2)
    # tile layout: contiguous tiles
    assert tl.tile_size_of(LocalTileIndex(2, 1)) == TileElementSize(2, 3)


def test_matrix_mirror_roundtrip(devices8):
    """MatrixMirror parity (reference matrix_mirror.h): D2H then H2D with
    the same layout reproduces the matrix, distributed included."""
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix import ops as mops
    from dlaf_tpu.matrix.matrix import Matrix

    rng = np.random.default_rng(5)
    a = rng.standard_normal((13, 13))
    m = Matrix.from_global(a, TileElementSize(4, 4), grid=Grid(2, 4))
    host = mops.mirror_to_host(m)
    np.testing.assert_array_equal(host, a)
    back = mops.mirror_to_device(host * 2, like=m)
    assert back.grid is m.grid and back.block_size == m.block_size
    np.testing.assert_array_equal(back.to_numpy(), a * 2)


def test_permute_array_rows_cols():
    import jax.numpy as jnp

    from dlaf_tpu.algorithms.permutations import permute_array

    a = np.arange(12.0).reshape(3, 4)
    perm = [2, 0, 1]
    np.testing.assert_array_equal(
        np.asarray(permute_array("Row", perm, jnp.asarray(a))), a[perm])
    permc = [3, 2, 1, 0]
    np.testing.assert_array_equal(
        np.asarray(permute_array("Col", permc, jnp.asarray(a))), a[:, permc])


def test_assert_tiers(monkeypatch):
    """3-tier assertion ladder (reference DLAF_ASSERT/_MODERATE/_HEAVY):
    plain asserts always fire; heavy fires only when enabled (the test
    session enables it via conftest)."""
    import dlaf_tpu.common.asserts as asserts

    with pytest.raises(asserts.DlafAssertError, match="boom"):
        asserts.dlaf_assert(False, "boom")
    # heavy is enabled in the suite (conftest sets the env)
    with pytest.raises(asserts.DlafAssertError):
        asserts.dlaf_assert_heavy(False, "heavy fires when enabled")
    asserts.dlaf_assert(True, "no fire")
    asserts.dlaf_assert_moderate(True, "no fire")


def test_sub_panel_view_width(devices8):
    from dlaf_tpu.common.index2d import (GlobalElementIndex,
                                         GlobalElementSize, TileElementSize)
    from dlaf_tpu.matrix.distribution import Distribution
    from dlaf_tpu.matrix.views import SubPanelView

    dist = Distribution(GlobalElementSize(16, 16), TileElementSize(4, 4))
    v = SubPanelView(dist, GlobalElementIndex(4, 12), width=4)
    assert v.begin_tile.row == 1 and v.begin_tile.col == 3
    assert v.cols() == 4
    edge = SubPanelView(dist, GlobalElementIndex(0, 14), width=4)
    assert edge.cols() == 2   # clamped at the matrix edge
