"""Device-timeline attribution tests (ISSUE 14, dlaf_tpu.obs.devtrace).

Covers the op classifier, the phase join (annotation + rebase fallback),
the measured-overlap computation on a synthetic TPU-shaped trace (where
collectives genuinely overlap MXU work across streams of one device),
the devtrace/measured_overlap record schema + the ``--require-devtrace``
accept/reject legs (zero-attributed-collectives must be REJECTED), the
hermetic replay of the committed ``tests/fixtures/devtrace/`` fixture
(the ``mfu_table.py --measured`` source), the CLI, and the
``scripts/perf_diff.py`` explainer with its must-trip injected-slowdown
drill. The overlap ORDERING assertion (``comm_lookahead=1`` >= ``=0``)
is TPU-gated like PR 2/4's A/B arms — XLA:CPU executes thunks serially,
so CPU CI pins report *structure* (finite fractions, coverage, schema),
never the ordering.
"""

import json
import math
import os
import subprocess
import sys

import jax
import pytest

from dlaf_tpu.obs import devtrace
from dlaf_tpu.obs.aggregate import merge_artifacts
from dlaf_tpu.obs.sinks import (DEVTRACE_COVERAGE_FLOOR, validate_records)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "fixtures", "devtrace")
FIXTURE_TRACE = os.path.join(FIXTURE, "trace.json.gz")
FIXTURE_JSONL = os.path.join(FIXTURE, "merged.jsonl")


# ---------------------------------------------------------------------------
# op classification
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,cat,kind", [
    ("dot.24", "mxu", None),
    ("bitcast_dot_fusion.1", "mxu", None),
    ("convolution.2", "mxu", None),
    ("all-reduce.11", "collective", "all-reduce"),
    ("all-gather.5", "collective", "all-gather"),
    ("reduce-scatter", "collective", "reduce-scatter"),
    ("collective-permute.3", "collective", "collective-permute"),
    ("gather.7", "copy", None),              # NOT all-gather
    ("copy_dynamic-update-slice_fusion", "copy", None),
    ("transpose.1", "copy", None),
    ("custom-call.2", "host_callback", None),
    ("add.174", "compute", None),
    ("while.1", "compute", None),
    ("partition-id", "compute", None),
])
def test_classify_op(name, cat, kind):
    assert devtrace.classify_op(name) == (cat, kind)


def test_classify_op_rejects_infra_events():
    for name in ("ThunkExecutor::Execute", "TfrtCpuExecutable::ExecuteHelper",
                 "ThunkExecutor::Execute (wait for completion)", ""):
        assert devtrace.classify_op(name) == (None, None)


# ---------------------------------------------------------------------------
# synthetic traces: phase join + overlap semantics
# ---------------------------------------------------------------------------

def _span_record(name, ts, dur_s, flops=None, **attrs):
    r = {"v": 1, "type": "span", "ts": ts, "name": name, "dur_s": dur_s,
         "depth": 0, "parent": None, "attrs": attrs, "rank": 0}
    if flops is not None:
        r["flops"] = flops
    return r


def _synth_tpu_trace():
    """One /device: process with two streams: an all-reduce on stream 1
    overlapping a dot on stream 2 for half its duration, plus a host
    thread carrying the span-annotation window around everything."""
    return [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "python"}},
        # host window [0, 1000] us named like the JSONL span
        {"ph": "X", "pid": 9, "tid": 1, "ts": 0.0, "dur": 1000.0,
         "name": "cholesky"},
        # stream 1: collective [100, 300]
        {"ph": "X", "pid": 1, "tid": 1, "ts": 100.0, "dur": 200.0,
         "name": "all-reduce.1"},
        # stream 2: dot [200, 500] -> overlap with the collective = 100us
        {"ph": "X", "pid": 1, "tid": 2, "ts": 200.0, "dur": 300.0,
         "name": "dot.1"},
        # stream 1: copy outside the window -> unattributed
        {"ph": "X", "pid": 1, "tid": 1, "ts": 2000.0, "dur": 100.0,
         "name": "copy.1"},
    ]


def test_synthetic_overlap_and_coverage():
    records = [_span_record("cholesky", 10.0, 1.0, flops=2e9,
                            comm_lookahead=1)]
    report = devtrace.attribute(_synth_tpu_trace(), records)
    # 600us of ops, 500 attributed (the trailing copy is outside)
    assert report["device_busy_s"] == pytest.approx(600e-6)
    assert report["attributed_s"] == pytest.approx(500e-6)
    assert report["coverage"] == pytest.approx(5.0 / 6.0)
    assert report["join"] == "annotation"
    (row,) = report["overlap"]
    # /device: process -> the overlap domain is the whole process, so
    # the dot's [200, 300] slice overlaps the collective
    assert row["algo"] == "cholesky" and row["axis"] == "all"
    assert row["collective_s"] == pytest.approx(200e-6)
    assert row["overlapped_s"] == pytest.approx(100e-6)
    assert row["overlap_frac"] == pytest.approx(0.5)
    assert row["kinds"] == {"all-reduce": pytest.approx(200e-6)}
    # mxu_busy_s is PHASE-scoped like every sibling field (the review
    # fix): the cholesky phase attributed 300us of MXU work
    assert row["mxu_busy_s"] == pytest.approx(300e-6)
    cell = report["phases"]["cholesky"]
    assert cell["categories"]["mxu"] == pytest.approx(300e-6)
    # measured MFU: flops / device-busy wall (union [100, 500] = 400us)
    assert cell["wall_s"] == pytest.approx(400e-6)
    assert cell["measured_gflops"] == pytest.approx(2e9 / 400e-6 / 1e9)
    assert report["knobs"] == {"comm_lookahead": [1]}


def test_cpu_thread_domains_do_not_cross_overlap():
    """On a host-process trace (XLA:CPU), each executor thread is its
    own device: a dot on thread B must NOT count as overlapping a
    collective on thread A."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 7, "tid": 5, "ts": 0.0, "dur": 1000.0,
         "name": "cholesky"},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 100.0, "dur": 200.0,
         "name": "all-reduce.1", "args": {"hlo_op": "all-reduce.1"}},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 100.0, "dur": 200.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
    ]
    report = devtrace.attribute(events, [_span_record("cholesky", 1.0, 1.0)])
    (row,) = report["overlap"]
    assert row["overlap_frac"] == 0.0 and row["collective_s"] > 0


def test_innermost_window_wins():
    events = [
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "python"}},
        {"ph": "X", "pid": 9, "tid": 1, "ts": 0.0, "dur": 1000.0,
         "name": "outer"},
        {"ph": "X", "pid": 9, "tid": 1, "ts": 100.0, "dur": 300.0,
         "name": "inner"},
        {"ph": "X", "pid": 2, "tid": 1, "ts": 200.0, "dur": 50.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
        {"ph": "X", "pid": 2, "tid": 1, "ts": 600.0, "dur": 50.0,
         "name": "dot.2", "args": {"hlo_op": "dot.2"}},
    ]
    records = [_span_record("outer", 1.0, 1.0),
               _span_record("inner", 1.0, 0.5)]
    report = devtrace.attribute(events, records)
    assert report["phases"]["inner"]["busy_s"] == pytest.approx(50e-6)
    assert report["phases"]["outer"]["busy_s"] == pytest.approx(50e-6)


def test_rebase_fallback_join():
    """A trace without annotation mirrors still joins: the JSONL spans
    are rebased (aggregate's --align machinery) onto the device-event
    origin."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 2, "tid": 1, "ts": 1000.0, "dur": 100.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
    ]
    # span of 1s whose rebased window is [0 us, 1e6 us] from the device
    # origin (ts is stamped at span EXIT, dur_s before it)
    records = [_span_record("cholesky", 1.0, 1.0)]
    report = devtrace.attribute(events, records)
    assert report["join"] == "rebase"
    assert report["coverage"] == pytest.approx(1.0)
    assert "cholesky" in report["phases"]


def test_empty_trace_fails_loudly():
    with pytest.raises(ValueError, match="no device op events"):
        devtrace.attribute([{"ph": "M", "name": "process_name", "pid": 1,
                             "args": {"name": "python"}}], [])
    # zero-duration-only device events are equally unattributable — a
    # loud ValueError, never a coverage division by zero
    with pytest.raises(ValueError, match="no device op events"):
        devtrace.attribute(
            [{"ph": "X", "pid": 1, "tid": 1, "ts": 5.0, "dur": 0.0,
              "name": "dot.1", "args": {"hlo_op": "dot.1"}}], [])


# ---------------------------------------------------------------------------
# records + validator obligations
# ---------------------------------------------------------------------------

def test_records_validate_and_require_devtrace_accepts():
    records = [_span_record("cholesky", 10.0, 1.0, flops=2e9)]
    report = devtrace.attribute(_synth_tpu_trace(), records)
    recs = devtrace.records_from_report(report, "t.json.gz")
    assert not validate_records(recs)
    assert not validate_records(recs, require_devtrace=True)
    types = [r["type"] for r in recs]
    assert types.count("devtrace") == 1
    assert types.count("measured_overlap") == 1


def test_require_devtrace_rejects_zero_attributed_collectives():
    """A trace whose attribution found NO collective time emits no
    measured_overlap record — and the artifact must be REJECTED."""
    events = [e for e in _synth_tpu_trace()
              if not e.get("name", "").startswith("all-reduce")]
    report = devtrace.attribute(events, [_span_record("cholesky", 1.0, 1.0)])
    assert report["overlap"] == []
    recs = devtrace.records_from_report(report, "t.json.gz")
    errors = validate_records(recs, require_devtrace=True)
    assert any("no measured_overlap" in e for e in errors)
    # but the records themselves are schema-valid
    assert not validate_records(recs)


def test_require_devtrace_rejects_low_coverage_and_nan_walls():
    records = [_span_record("cholesky", 10.0, 1.0)]
    report = devtrace.attribute(_synth_tpu_trace(), records)
    recs = devtrace.records_from_report(report, "t.json.gz")
    (dt,) = [r for r in recs if r["type"] == "devtrace"]
    dt["coverage"] = DEVTRACE_COVERAGE_FLOOR - 0.01
    errors = validate_records(recs, require_devtrace=True)
    assert any("coverage" in e for e in errors)
    dt["coverage"] = 0.9
    dt["phases"]["cholesky"]["wall_s"] = float("nan")
    errors = validate_records(recs)            # schema-level, no require
    assert any("wall_s" in e for e in errors)


# ---------------------------------------------------------------------------
# the committed fixture: hermetic replay (mfu_table --measured source)
# ---------------------------------------------------------------------------

def test_fixture_replays_hermetically():
    records = merge_artifacts([FIXTURE_JSONL])
    report = devtrace.attribute(devtrace.load_trace(FIXTURE_TRACE), records)
    assert report["join"] == "annotation"
    assert report["coverage"] >= DEVTRACE_COVERAGE_FLOOR
    assert report["overlap"], "fixture must carry attributed collectives"
    for row in report["overlap"]:
        assert math.isfinite(row["overlap_frac"])
        assert 0.0 <= row["overlap_frac"] <= 1.0
    assert "cholesky" in report["phases"]
    assert report["phases"]["cholesky"]["measured_gflops"] > 0
    recs = devtrace.records_from_report(report, FIXTURE_TRACE)
    assert not validate_records(recs, require_devtrace=True)


def test_fixture_distill_is_idempotent():
    records = merge_artifacts([FIXTURE_JSONL])
    events = devtrace.load_trace(FIXTURE_TRACE)
    again = devtrace.distill(events, records)
    assert devtrace.attribute(again, records) == \
        devtrace.attribute(events, records)


def test_mfu_table_measured_column_from_fixture():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import mfu_table

    dev = mfu_table.measured_device(FIXTURE)
    assert "cholesky" in dev
    assert "cpu" in dev["cholesky"]            # platform-labeled, always
    text = mfu_table.render(with_ici=False, dev=dev)
    assert "measured(dev) GF/s" in text
    assert dev["cholesky"] in text


# ---------------------------------------------------------------------------
# CLI + perf_diff explainer
# ---------------------------------------------------------------------------

def test_devtrace_cli_enriches_and_validates(tmp_path):
    out = str(tmp_path / "enriched.jsonl")
    r = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.devtrace", FIXTURE_TRACE,
         FIXTURE_JSONL, "-o", out], capture_output=True, text=True,
        cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "coverage" in r.stdout and "MXU-overlapped" in r.stdout
    v = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.validate", out,
         "--require-devtrace"], capture_output=True, text=True, cwd=REPO)
    assert v.returncode == 0, v.stderr
    # usage: no artifact path -> 2; unreadable trace -> 1
    assert subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.devtrace", FIXTURE_TRACE],
        capture_output=True, cwd=REPO).returncode == 2
    assert subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.devtrace",
         str(tmp_path / "nope.json.gz"), FIXTURE_JSONL],
        capture_output=True, cwd=REPO).returncode == 1


@pytest.fixture()
def enriched(tmp_path):
    records = merge_artifacts([FIXTURE_JSONL])
    report = devtrace.attribute(devtrace.load_trace(FIXTURE_TRACE), records)
    recs = devtrace.records_from_report(report, FIXTURE_TRACE)
    path = str(tmp_path / "enriched.jsonl")
    with open(path, "w") as f:
        for r in records + recs:
            f.write(json.dumps(r, default=str) + "\n")
    return path


def test_perf_diff_identity_passes(enriched):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         enriched, enriched], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no regression" in r.stdout


def test_perf_diff_inject_slowdown_names_the_phase(enriched):
    """The CI must-trip drill: an injected slowdown on one phase must
    produce exit 1 with that phase named in a REGRESSION line."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         enriched, enriched, "--inject-slowdown", "cholesky=0.5"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1, r.stdout + r.stderr
    reg_lines = [ln for ln in r.stdout.splitlines() if "REGRESSION" in ln]
    assert reg_lines and any("cholesky" in ln for ln in reg_lines)
    assert "regression(s); worst:" in r.stderr


def test_perf_diff_one_sided_family_is_not_a_regression(tmp_path, enriched):
    """A metric family present on only one side (a baseline predating
    the accuracy/devtrace instrumentation, a newly named span) is
    instrumentation skew: reported informationally, NEVER exit 1."""
    records = [json.loads(ln) for ln in open(enriched)]
    baseline = str(tmp_path / "old_baseline.jsonl")
    with open(baseline, "w") as f:
        for r in records:
            if r.get("type") != "accuracy":
                f.write(json.dumps(r) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         baseline, enriched], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "only in fresh; not comparable" in r.stdout


def test_perf_diff_rejects_empty_artifacts(tmp_path):
    empty = str(tmp_path / "empty.jsonl")
    with open(empty, "w") as f:
        f.write(json.dumps({"v": 1, "type": "log", "ts": 1.0,
                            "level": "info", "logger": "x", "msg": "y",
                            "fields": {}}) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         empty, empty], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1
    assert "nothing to attribute" in r.stderr


def test_bench_gate_regression_names_perf_diff(tmp_path):
    """A tripped bench gate must print the exact perf_diff invocation
    (ISSUE 14: one command from verdict to diagnosis)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_gate.py"),
         "--replay", "--inject-slowdown", "0.2"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1
    assert "scripts/perf_diff.py" in r.stderr


# ---------------------------------------------------------------------------
# TPU-gated: measured overlap ordering (the A/B the counters only imply)
# ---------------------------------------------------------------------------

@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="XLA:CPU executes thunks serially — the "
                           "measured overlap ordering only exists on a "
                           "device that actually overlaps ICI with MXU "
                           "work (PR 2/4 A/B discipline)")
def test_comm_lookahead_measured_overlap_ordering(tmp_path):
    """comm_lookahead=1 must measure >= the =0 arm's overlap fraction."""
    fracs = {}
    for la in (0, 1):
        env = dict(os.environ,
                   DLAF_METRICS_PATH=str(tmp_path / f"la{la}.r%r.jsonl"),
                   DLAF_TRACE_DIR=str(tmp_path / f"trace{la}"),
                   DLAF_CHOLESKY_LOOKAHEAD="1",
                   DLAF_COMM_LOOKAHEAD=str(la))
        subprocess.run(
            [sys.executable, "-m", "dlaf_tpu.miniapp.miniapp_cholesky",
             "-m", "1024", "-b", "256", "--grid-rows", "2",
             "--grid-cols", "2", "--nruns", "2"],
            check=True, env=env, cwd=REPO)
        records = merge_artifacts(
            sorted(str(p) for p in tmp_path.glob(f"la{la}.r*.jsonl")))
        report = devtrace.attribute(
            devtrace.load_trace(str(tmp_path / f"trace{la}")), records)
        fracs[la] = max((r["overlap_frac"] for r in report["overlap"]),
                        default=0.0)
    assert fracs[1] >= fracs[0]
