"""Triangular solve/multiply tests
(reference: test/unit/solver/test_triangular.cpp,
test/unit/multiplication/test_triangular.cpp): all combos, local +
distributed, several grids, rectangular B, edge tiles, all scalar types.
"""

import numpy as np
import pytest

from dlaf_tpu.algorithms.triangular import triangular_multiply, triangular_solve
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize

COMBOS = [(s, u, o, d)
          for s in "LR" for u in "LU" for o in "NTC" for d in "NU"]
SOLVE_COMBOS_SMALL = [("L", "L", "N", "N"), ("L", "U", "T", "N"), ("L", "U", "N", "U"),
                      ("L", "L", "C", "N"), ("R", "L", "N", "N"), ("R", "U", "C", "N"),
                      ("R", "L", "T", "U"), ("R", "U", "N", "N")]


def make_ab(n, m, dtype, side, seed=0):
    rng = np.random.default_rng(seed)
    adim = n if side == "L" else m
    a = rng.standard_normal((adim, adim))
    b = rng.standard_normal((n, m))
    if np.dtype(dtype).kind == "c":
        a = a + 1j * rng.standard_normal(a.shape)
        b = b + 1j * rng.standard_normal(b.shape)
    a = a + 2 * adim * np.eye(adim)   # well-conditioned triangles
    return a.astype(dtype), b.astype(dtype)


def np_tri(a, uplo, diag):
    t = np.tril(a) if uplo == "L" else np.triu(a)
    if diag == "U":
        np.fill_diagonal(t, 1.0)
    return t


def np_op(a, op):
    return {"N": a, "T": a.T, "C": a.conj().T}[op]


def mats(a, b, nb, nbb, grid=None, src=RankIndex2D(0, 0)):
    from dlaf_tpu.matrix.matrix import Matrix
    am = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid, source_rank=src)
    bm = Matrix.from_global(b, TileElementSize(nb, nbb), grid=grid, source_rank=src)
    return am, bm


def _tol(dtype):
    eps = np.finfo(np.dtype(dtype).type(0).real.dtype).eps
    return dict(rtol=500 * eps, atol=500 * eps)


@pytest.mark.parametrize("side,uplo,op,diag", COMBOS)
def test_solve_local_all_combos(side, uplo, op, diag):
    dtype = np.float64
    a, b = make_ab(12, 8, dtype, side)
    am, bm = mats(a, b, 4, 4)
    out = triangular_solve(side, uplo, op, diag, 1.5, am, bm).to_numpy()
    t = np_op(np_tri(a, uplo, diag), op)
    expect = np.linalg.solve(t, 1.5 * b) if side == "L" else (1.5 * b) @ np.linalg.inv(t)
    np.testing.assert_allclose(out, expect, **_tol(dtype))


@pytest.mark.parametrize("dtype", [np.float32, np.complex128])
@pytest.mark.parametrize("side,uplo,op,diag", SOLVE_COMBOS_SMALL[:4])
def test_solve_local_dtypes(side, uplo, op, diag, dtype):
    a, b = make_ab(12, 8, dtype, side)
    am, bm = mats(a, b, 4, 4)
    out = triangular_solve(side, uplo, op, diag, 1.0, am, bm).to_numpy()
    t = np_op(np_tri(a, uplo, diag), op)
    expect = np.linalg.solve(t, b) if side == "L" else b @ np.linalg.inv(t)
    np.testing.assert_allclose(out, expect, **_tol(dtype))


@pytest.mark.parametrize("grid_shape", [(2, 2), (2, 4), (4, 2)])
@pytest.mark.parametrize("side,uplo,op,diag", SOLVE_COMBOS_SMALL)
def test_solve_distributed(side, uplo, op, diag, grid_shape, devices8):
    dtype = np.float64
    n, m, nb = 16, 12, 4
    a, b = make_ab(n, m, dtype, side, seed=3)
    grid = Grid(*grid_shape)
    am, bm = mats(a, b, nb, nb, grid=grid, src=RankIndex2D(1 % grid_shape[0],
                                                           1 % grid_shape[1]))
    out = triangular_solve(side, uplo, op, diag, 2.0, am, bm).to_numpy()
    t = np_op(np_tri(a, uplo, diag), op)
    expect = np.linalg.solve(t, 2.0 * b) if side == "L" else (2.0 * b) @ np.linalg.inv(t)
    np.testing.assert_allclose(out, expect, **_tol(dtype))


@pytest.mark.parametrize("side,uplo,op,diag", SOLVE_COMBOS_SMALL)
def test_solve_distributed_mixed_trsm_knob(side, uplo, op, diag, devices8,
                                           monkeypatch):
    """f64_trsm="mixed" + f64_gemm="mxu": panel solves via refined inverse,
    applications and updates on the int8 path — results must stay f64-grade
    (reference accuracy budget)."""
    monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
    monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
    monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "4")
    import dlaf_tpu.config as config
    config.initialize()
    try:
        dtype = np.float64
        n, m, nb = 16, 12, 4
        a, b = make_ab(n, m, dtype, side, seed=7)
        am, bm = mats(a, b, nb, nb, grid=Grid(2, 4), src=RankIndex2D(1, 1))
        out = triangular_solve(side, uplo, op, diag, 1.0, am, bm).to_numpy()
        t = np_op(np_tri(a, uplo, diag), op)
        expect = np.linalg.solve(t, b) if side == "L" else b @ np.linalg.inv(t)
        np.testing.assert_allclose(out, expect, **_tol(dtype))
    finally:
        for v in ("DLAF_F64_TRSM", "DLAF_F64_GEMM", "DLAF_F64_GEMM_MIN_DIM"):
            monkeypatch.delenv(v)
        config.initialize()


def test_solve_distributed_edge_tiles(devices8):
    # non-divisible sizes: short edge tiles on both A and B
    dtype = np.float64
    n, m, nb = 13, 9, 4
    a, b = make_ab(n, m, dtype, "L", seed=5)
    grid = Grid(2, 4)
    am, bm = mats(a, b, nb, nb, grid=grid)
    out = triangular_solve("L", "L", "N", "N", 1.0, am, bm).to_numpy()
    expect = np.linalg.solve(np_tri(a, "L", "N"), b)
    np.testing.assert_allclose(out, expect, **_tol(dtype))
    assert np.isfinite(out).all()


@pytest.mark.parametrize("side,uplo,op,diag", COMBOS)
def test_multiply_local_all_combos(side, uplo, op, diag):
    dtype = np.float64
    a, b = make_ab(12, 8, dtype, side, seed=7)
    am, bm = mats(a, b, 4, 4)
    out = triangular_multiply(side, uplo, op, diag, 0.5, am, bm).to_numpy()
    t = np_op(np_tri(a, uplo, diag), op)
    expect = 0.5 * (t @ b if side == "L" else b @ t)
    np.testing.assert_allclose(out, expect, **_tol(dtype))


@pytest.mark.parametrize("grid_shape", [(2, 2), (2, 4)])
@pytest.mark.parametrize("side,uplo,op,diag",
                         [("L", "L", "N", "N"), ("L", "U", "N", "N"),
                          ("R", "L", "N", "N"), ("R", "U", "N", "U"),
                          ("L", "L", "C", "N"), ("R", "U", "T", "N")])
def test_multiply_distributed(side, uplo, op, diag, grid_shape, devices8):
    dtype = np.float64
    n, m, nb = 16, 12, 4
    a, b = make_ab(n, m, dtype, side, seed=11)
    grid = Grid(*grid_shape)
    am, bm = mats(a, b, nb, nb, grid=grid, src=RankIndex2D(0, 1))
    out = triangular_multiply(side, uplo, op, diag, 1.0, am, bm).to_numpy()
    t = np_op(np_tri(a, uplo, diag), op)
    expect = t @ b if side == "L" else b @ t
    np.testing.assert_allclose(out, expect, **_tol(dtype))


@pytest.mark.parametrize("dtype", [np.complex128])
def test_solve_distributed_complex(dtype, devices8):
    n, m, nb = 16, 8, 4
    a, b = make_ab(n, m, dtype, "L", seed=13)
    grid = Grid(2, 2)
    am, bm = mats(a, b, nb, nb, grid=grid)
    out = triangular_solve("L", "L", "C", "N", 1.0, am, bm).to_numpy()
    expect = np.linalg.solve(np_tri(a, "L", "N").conj().T, b)
    np.testing.assert_allclose(out, expect, **_tol(dtype))


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("grid_shape", [(2, 4), (4, 2)])
@pytest.mark.parametrize("side,uplo,op,diag", SOLVE_COMBOS_SMALL)
def test_solve_distributed_scan(side, uplo, op, diag, grid_shape, dtype,
                                devices8, monkeypatch):
    """dist_step_mode="scan": the lax.scan'd solve step (traced per-k
    index math, dynamic pivot slices) must match the unrolled result on
    every combo family, both sweep directions, ragged edge included."""
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        n, m, nb = 19, 13, 4   # ragged in both dimensions
        a, b = make_ab(n, m, dtype, side, seed=7)
        grid = Grid(*grid_shape)
        am, bm = mats(a, b, nb, nb, grid=grid,
                      src=RankIndex2D(1 % grid_shape[0], 1 % grid_shape[1]))
        out = triangular_solve(side, uplo, op, diag, 2.0, am, bm).to_numpy()
        t = np_op(np_tri(a, uplo, diag), op)
        expect = np.linalg.solve(t, 2.0 * b) if side == "L" \
            else (2.0 * b) @ np.linalg.inv(t)
        np.testing.assert_allclose(out, expect, **_tol(dtype))
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE")
        config.initialize()


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("grid_shape", [(2, 4), (4, 2)])
@pytest.mark.parametrize("side,uplo,op,diag",
                         [("L", "L", "N", "N"), ("L", "U", "C", "U"),
                          ("R", "U", "N", "N"), ("R", "L", "T", "U"),
                          ("L", "U", "N", "N"), ("R", "L", "N", "U")])
def test_multiply_distributed_scan(side, uplo, op, diag, grid_shape, dtype,
                                   devices8, monkeypatch):
    """dist_step_mode="scan" for the multiply: traced-k pivot panels,
    carried accumulator — must match numpy on ragged sizes."""
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        n, m, nb = 19, 13, 4
        a, b = make_ab(n, m, dtype, side, seed=9)
        grid = Grid(*grid_shape)
        am, bm = mats(a, b, nb, nb, grid=grid,
                      src=RankIndex2D(1 % grid_shape[0], 1 % grid_shape[1]))
        out = triangular_multiply(side, uplo, op, diag, 0.5, am, bm).to_numpy()
        t = np_op(np_tri(a, uplo, diag), op)
        expect = 0.5 * (t @ b) if side == "L" else 0.5 * (b @ t)
        np.testing.assert_allclose(out, expect, **_tol(dtype))
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE")
        config.initialize()


def test_solve_distributed_misaligned_sources_raise(devices8):
    """A and B at different source ranks address different global tiles at
    the same local slot; the distributed solver combines per-slot panels,
    so this must raise loudly instead of corrupting silently (round-3
    finding: a mismatched source produced max err ~0.26 and no error)."""
    from dlaf_tpu.common.asserts import DlafAssertError
    from dlaf_tpu.matrix.matrix import Matrix

    n, nb = 16, 4
    rng = np.random.default_rng(0)
    t = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    b = rng.standard_normal((n, n))
    grid = Grid(2, 4)
    am = Matrix.from_global(t, TileElementSize(nb, nb), grid=grid,
                            source_rank=RankIndex2D(1, 1))
    bm = Matrix.from_global(b, TileElementSize(nb, nb), grid=grid)
    with pytest.raises(DlafAssertError, match="row slots misaligned"):
        triangular_solve("L", "L", "N", "N", 1.0, am, bm)
    # side='R' checks COLUMN alignment; rows may differ freely there
    with pytest.raises(DlafAssertError, match="col slots misaligned"):
        triangular_solve("R", "L", "C", "N", 1.0, am, bm)
    with pytest.raises(DlafAssertError, match="misaligned"):
        triangular_multiply("L", "L", "N", "N", 1.0, am, bm)

@pytest.mark.parametrize("side,uplo,op,diag",
                         [("L", "L", "N", "N"), ("R", "U", "C", "N")])
@pytest.mark.parametrize("mxu", [False, True])
def test_trsm_rhs_chunk_bitwise_identical(side, uplo, op, diag, mxu,
                                          monkeypatch):
    """Free-axis chunking of the local whole-matrix solve (config
    ``trsm_rhs_chunk``) is bitwise-identical to the unchunked form —
    rhs columns (rows for side='R') are independent — on both the
    native and the mxu route, including a non-divisible free axis."""
    import dlaf_tpu.config as config

    n, m = (48, 37) if side == "L" else (37, 48)
    a, b = make_ab(n, m, np.float64, side, seed=7)
    nb = 8
    if mxu:
        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        # min_dim=32 > the requested chunk width of 16: the mxu arm also
        # verifies the clamp that keeps chunking from flipping per-gemm
        # routes (blas gates on min over ALL gemm dims incl. rhs width)
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "32")
    config.initialize()
    try:
        am, bm = mats(a, b, nb, nb)
        kept = triangular_solve(side, uplo, op, diag, 1.0, am, bm).to_numpy()
        monkeypatch.setenv("DLAF_TRSM_RHS_CHUNK", "16")
        config.initialize()
        if mxu:
            from dlaf_tpu.algorithms.triangular import _rhs_chunk_width
            assert _rhs_chunk_width(side, b.shape, np.float64) == 32
        am, bm = mats(a, b, nb, nb)
        chunked = triangular_solve(side, uplo, op, diag, 1.0, am,
                                   bm).to_numpy()
        np.testing.assert_array_equal(chunked, kept)
    finally:
        monkeypatch.delenv("DLAF_TRSM_RHS_CHUNK", raising=False)
        monkeypatch.delenv("DLAF_F64_GEMM", raising=False)
        monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM", raising=False)
        config.initialize()


@pytest.mark.parametrize("side,uplo,op", [("L", "L", "N"), ("R", "U", "C"),
                                          ("L", "U", "T")])
def test_solve_scan_lookahead_bitwise(side, uplo, op, devices8, monkeypatch):
    """The pipelined scan-solve body (cholesky_lookahead=1 — deferred bulk
    + eager next-pivot strip, docs/lookahead.md) must match the serial
    scan body BITWISE, at nt=11 (multi-segment windows, both transpose-
    exchange paths) on an offset grid — and so must comm_lookahead=1
    (the A-panel collectives hoisted ahead of the deferred bulk,
    docs/comm_overlap.md: emission reorder of identical values)."""
    import dlaf_tpu.config as config
    from dlaf_tpu.matrix.matrix import Matrix

    n, m, nb = 44, 12, 4   # A order 44 -> nt = 11
    a, b = make_ab(n if side == "L" else m,
                   m if side == "L" else n, np.float64, side, seed=13)
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
    grid, src = Grid(2, 4), RankIndex2D(1, 2)
    res = {}
    try:
        for la, comm in (("0", "0"), ("1", "0"), ("1", "1")):
            monkeypatch.setenv("DLAF_CHOLESKY_LOOKAHEAD", la)
            monkeypatch.setenv("DLAF_COMM_LOOKAHEAD", comm)
            config.initialize()
            am = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid,
                                    source_rank=src)
            bm = Matrix.from_global(b, TileElementSize(nb, nb), grid=grid,
                                    source_rank=src)
            res[la, comm] = triangular_solve(side, uplo, op, "N", 1.0, am,
                                             bm).to_numpy()
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE", raising=False)
        monkeypatch.delenv("DLAF_CHOLESKY_LOOKAHEAD", raising=False)
        monkeypatch.delenv("DLAF_COMM_LOOKAHEAD", raising=False)
        config.initialize()
    np.testing.assert_array_equal(res["1", "0"], res["0", "0"])
    np.testing.assert_array_equal(res["1", "1"], res["0", "0"])
    t = np_op(np_tri(a, uplo, "N"), op)
    want = np.linalg.solve(t, b) if side == "L" else \
        np.linalg.solve(t.T, b.T).T
    np.testing.assert_allclose(res["1", "1"], want, **_tol(np.float64))
