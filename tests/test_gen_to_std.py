"""gen_to_std (HEGST), matrix ops, and general multiply tests
(reference: test/unit/eigensolver/test_gen_to_std.cpp,
test/unit/multiplication/test_multiplication_general.cpp)."""

import numpy as np
import pytest

from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.algorithms.gen_to_std import gen_to_std
from dlaf_tpu.algorithms.general import general_sub_multiply
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize
from dlaf_tpu.matrix import ops as mops
from dlaf_tpu.matrix.matrix import Matrix


def _tol(dtype):
    eps = np.finfo(np.dtype(dtype).type(0).real.dtype).eps
    return dict(rtol=2000 * eps, atol=2000 * eps)


def herm(n, dtype, seed, pd=False):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    a = (x + x.conj().T) / 2
    if pd:
        a = x @ x.conj().T + n * np.eye(n)
    return a.astype(dtype)


def M(a, nb, grid=None, src=RankIndex2D(0, 0)):
    return Matrix.from_global(a, TileElementSize(nb, nb), grid=grid, source_rank=src)


@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
@pytest.mark.parametrize("impl", ["twosolve", "blocked"])
def test_gen_to_std_donate_matches_and_invalidates(impl, grid_shape,
                                                   devices8, monkeypatch):
    """``donate=True`` must be bit-identical to the kept form, consume
    ``a``'s storage, and never consume ``b_factor`` (callers reuse the
    factor across runs — the miniapp contract)."""
    import jax

    monkeypatch.setenv("DLAF_HEGST_IMPL", impl)
    import dlaf_tpu.config as config

    config.initialize()
    try:
        n, nb = 24, 4
        a = herm(n, np.float64, 3)
        b = herm(n, np.float64, 4, pd=True)
        grid = Grid(*grid_shape) if grid_shape else None
        bf = cholesky("L", M(b, nb, grid))
        kept = gen_to_std("L", M(a, nb, grid), bf).to_numpy()
        am = M(a, nb, grid)
        donated = gen_to_std("L", am, bf, donate=True)
        np.testing.assert_array_equal(donated.to_numpy(), kept)
        # NOTE: ``a``'s consumption is best-effort here — the final
        # triangle merge's output aliases the transformed intermediate,
        # so the backend may decline the second alias and leave ``a``
        # alive (donation = permission, not a guarantee). The contract
        # is only that ``a`` must not be used after the call.
        # b_factor survives — a second donated transform must still work
        out2 = gen_to_std("L", M(a, nb, grid), bf, donate=True)
        np.testing.assert_array_equal(out2.to_numpy(), kept)
    finally:
        monkeypatch.delenv("DLAF_HEGST_IMPL")
        config.initialize()


# -- matrix ops -------------------------------------------------------------

@pytest.mark.parametrize("grid_shape", [None, (2, 2), (2, 4)])
def test_transpose_hermitianize(grid_shape, devices8):
    grid = Grid(*grid_shape) if grid_shape else None
    a = herm(12, np.complex128, 1) + np.triu(np.ones((12, 12)), 1) * 0.3
    m = M(a, 4, grid)
    t = mops.transpose(m).to_numpy()
    np.testing.assert_allclose(t, a.conj().T, rtol=1e-14)
    h = mops.hermitianize(m, "L").to_numpy()
    tri = np.tril(a, -1)
    expect = tri + tri.conj().T + np.diag(np.real(np.diag(a)))
    np.testing.assert_allclose(h, expect, rtol=1e-14)
    assert np.allclose(h, h.conj().T)


def test_merge_triangle(devices8):
    a = np.arange(64, dtype=np.float64).reshape(8, 8)
    b = -np.ones((8, 8))
    out = mops.merge_triangle(M(a, 4, Grid(2, 2)), M(b, 4, Grid(2, 2)), "L").to_numpy()
    np.testing.assert_array_equal(np.tril(out), np.tril(a))
    np.testing.assert_array_equal(np.triu(out, 1), np.triu(b, 1))


# -- gen_to_std -------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.float64, np.complex128, np.float32])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("n,nb", [(12, 4), (13, 4), (8, 8)])
def test_gen_to_std_local(uplo, n, nb, dtype):
    a = herm(n, dtype, 2)
    b = herm(n, dtype, 3, pd=True)
    bf = cholesky(uplo, M(b, nb))
    out = gen_to_std(uplo, M(a, nb), bf).to_numpy()
    if uplo == "L":
        l = np.tril(bf.to_numpy())
        expect = np.linalg.solve(l, a) @ np.linalg.inv(l).conj().T
        np.testing.assert_allclose(np.tril(out), np.tril(expect), **_tol(dtype))
        np.testing.assert_array_equal(np.triu(out, 1), np.triu(a, 1))
    else:
        u = np.triu(bf.to_numpy())
        expect = np.linalg.solve(u.conj().T, a) @ np.linalg.inv(u)
        np.testing.assert_allclose(np.triu(out), np.triu(expect), **_tol(dtype))
        np.testing.assert_array_equal(np.tril(out, -1), np.tril(a, -1))


@pytest.mark.parametrize("grid_shape", [(2, 2), (2, 4), (4, 2)])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_gen_to_std_distributed(uplo, grid_shape, devices8):
    dtype = np.float64
    n, nb = 16, 4
    a = herm(n, dtype, 4)
    b = herm(n, dtype, 5, pd=True)
    grid = Grid(*grid_shape)
    src = RankIndex2D(1 % grid_shape[0], 1 % grid_shape[1])
    bf = cholesky("L", M(b, nb, grid, src)) if uplo == "L" else None
    if uplo == "U":
        # build U factor locally, distribute it
        u = np.linalg.cholesky(b).conj().T
        bfm = M(np.triu(u) + np.tril(b, -1), nb, grid, src)
    else:
        bfm = bf
    out = gen_to_std(uplo, M(a, nb, grid, src), bfm).to_numpy()
    if uplo == "L":
        l = np.tril(bfm.to_numpy())
        expect = np.linalg.solve(l, a) @ np.linalg.inv(l).conj().T
        np.testing.assert_allclose(np.tril(out), np.tril(expect), **_tol(dtype))
    else:
        u = np.triu(bfm.to_numpy())
        expect = np.linalg.solve(u.conj().T, a) @ np.linalg.inv(u)
        np.testing.assert_allclose(np.triu(out), np.triu(expect), **_tol(dtype))


def test_gen_to_std_matches_scipy_eigvals():
    # end check: eig(A, B) == eig(transformed standard problem)
    import scipy.linalg as sla

    n, nb = 12, 4
    a = herm(n, np.float64, 6)
    b = herm(n, np.float64, 7, pd=True)
    bf = cholesky("L", M(b, nb))
    c = gen_to_std("L", M(a, nb), bf).to_numpy()
    cfull = np.tril(c) + np.tril(c, -1).T
    w1 = np.linalg.eigvalsh(cfull)
    w2 = sla.eigh(a, b, eigvals_only=True)
    np.testing.assert_allclose(w1, w2, atol=1e-10)


# -- general sub multiply ---------------------------------------------------

@pytest.mark.parametrize("grid_shape", [None, (2, 2)])
def test_general_sub_multiply(grid_shape, devices8):
    grid = Grid(*grid_shape) if grid_shape else None
    n, nb = 16, 4
    rng = np.random.default_rng(8)
    a, b, c = (rng.standard_normal((n, n)) for _ in range(3))
    am, bm, cm = M(a, nb, grid), M(b, nb, grid), M(c, nb, grid)
    out = general_sub_multiply(2.0, am, bm, 0.5, cm, 1, 3).to_numpy()
    expect = c.copy()
    sl = slice(4, 12)
    expect[sl, sl] = 2.0 * a[sl, sl] @ b[sl, sl] + 0.5 * c[sl, sl]
    np.testing.assert_allclose(out, expect, rtol=1e-13, atol=1e-13)


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_gen_to_std_distributed_scan_mode(uplo, devices8, monkeypatch):
    """dist_step_mode="scan" flows through gen_to_std's composition of
    distributed solves (config #3's compile-time escape hatch at large
    tile counts comes for free from the solver's scan step)."""
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        n, nb = 21, 4
        rng = np.random.default_rng(5)
        x = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
        a = x @ x.conj().T + 2 * n * np.eye(n)
        y = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
        bmat = y @ y.conj().T + 2 * n * np.eye(n)
        l = np.linalg.cholesky(bmat) if uplo == "L" else \
            np.linalg.cholesky(bmat).conj().T
        grid = Grid(2, 4)
        am = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)
        lm = Matrix.from_global(l, TileElementSize(nb, nb), grid=grid)
        out = gen_to_std(uplo, am, lm).to_numpy()
        if uplo == "L":
            expect = np.linalg.inv(l) @ a @ np.linalg.inv(l).conj().T
        else:
            expect = np.linalg.inv(l).conj().T @ a @ np.linalg.inv(l)
        got = out if uplo != "L" else out  # full result matrix
        tri = np.tril if uplo == "L" else np.triu
        np.testing.assert_allclose(tri(got), tri(expect), atol=1e-10)
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE")
        config.initialize()


@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
def test_hegst_blocked_matches_twosolve(uplo, grid_shape, devices8,
                                        monkeypatch):
    """The two formulations (config knob hegst_impl) agree to rounding on
    the same inputs — the twosolve path is the blocked path's
    cross-check (reference impl.h:200-740 vs the dense two-solve form)."""
    import dlaf_tpu.config as config

    dtype = np.complex128
    n, nb = 21, 4
    a = herm(n, dtype, 11)
    b = herm(n, dtype, 12, pd=True)
    grid = Grid(*grid_shape) if grid_shape else None
    src = RankIndex2D(1, 2) if grid_shape else RankIndex2D(0, 0)
    l = np.linalg.cholesky(b)
    bf = np.tril(l) if uplo == "L" else np.triu(l.conj().T)
    outs = {}
    try:
        for impl in ("blocked", "twosolve"):
            monkeypatch.setenv("DLAF_HEGST_IMPL", impl)
            config.initialize()
            outs[impl] = gen_to_std(uplo, M(a, nb, grid, src),
                                    M(bf, nb, grid, src)).to_numpy()
    finally:
        monkeypatch.delenv("DLAF_HEGST_IMPL", raising=False)
        config.initialize()
    tri = np.tril if uplo == "L" else np.triu
    np.testing.assert_allclose(tri(outs["blocked"]), tri(outs["twosolve"]),
                               rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_hegst_blocked_mxu_mixed_knobs(uplo, grid_shape, devices8,
                                       monkeypatch):
    """Blocked HEGST under f64_gemm=mxu + f64_trsm=mixed (the TPU
    product-config route: MXU pair products + shared refined-inverse
    panel/deferred solves) matches the numpy reference at f64-grade
    residual — LOCAL (the _step_inv sharing across _hegst_diag and the
    deferred row/column solves) and distributed."""
    import dlaf_tpu.config as config

    monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
    monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "4")
    monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
    config.initialize()
    try:
        dtype = np.float64
        n, nb = 24, 4
        a = herm(n, dtype, 21)
        b = herm(n, dtype, 22, pd=True)
        l = np.linalg.cholesky(b)
        bf = np.tril(l) if uplo == "L" else np.triu(l.conj().T)
        grid = Grid(*grid_shape) if grid_shape else None
        out = gen_to_std(uplo, M(a, nb, grid), M(bf, nb, grid)).to_numpy()
        if uplo == "L":
            expect = np.linalg.solve(bf, a) @ np.linalg.inv(bf).conj().T
            np.testing.assert_allclose(np.tril(out), np.tril(expect),
                                       rtol=1e-9, atol=1e-9)
        else:
            expect = np.linalg.solve(bf.conj().T, a) @ np.linalg.inv(bf)
            np.testing.assert_allclose(np.triu(out), np.triu(expect),
                                       rtol=1e-9, atol=1e-9)
    finally:
        for k in ("DLAF_F64_GEMM", "DLAF_F64_GEMM_MIN_DIM", "DLAF_F64_TRSM"):
            monkeypatch.delenv(k, raising=False)
        config.initialize()


def test_hegst_distributed_misaligned_sources_raise(devices8):
    """The blocked HEGST shares one set of slot indices between A and the
    Cholesky factor — both axes must align, loudly (see the solver's
    misalignment test for the silent-corruption failure mode)."""
    from dlaf_tpu.common.asserts import DlafAssertError

    n, nb = 16, 4
    a = herm(n, np.float64, 30)
    b = herm(n, np.float64, 31, pd=True)
    l = np.linalg.cholesky(b)
    grid = Grid(2, 4)
    am = M(a, nb, grid, src=RankIndex2D(0, 0))
    lm = M(np.tril(l), nb, grid, src=RankIndex2D(1, 2))
    with pytest.raises(DlafAssertError, match="misaligned"):
        gen_to_std("L", am, lm)


@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_hegst_blocked_lookahead_matches(uplo, grid_shape, devices8,
                                         monkeypatch):
    """The blocked HEGST's next-column-first her2k split + carried
    diag/panel (cholesky_lookahead=1, docs/lookahead.md) must reproduce
    the serialized form exactly, local and distributed."""
    import dlaf_tpu.config as config

    monkeypatch.setenv("DLAF_HEGST_IMPL", "blocked")
    n, nb = 41, 4
    a = herm(n, np.float64, 21)
    b = herm(n, np.float64, 22, pd=True)
    grid = Grid(*grid_shape) if grid_shape else None
    src = RankIndex2D(1, 2) if grid_shape else RankIndex2D(0, 0)
    res = {}
    try:
        for la in ("0", "1"):
            monkeypatch.setenv("DLAF_CHOLESKY_LOOKAHEAD", la)
            config.initialize()
            bf = cholesky(uplo, M(b, nb, grid, src))
            res[la] = gen_to_std(uplo, M(a, nb, grid, src), bf).to_numpy()
    finally:
        monkeypatch.delenv("DLAF_HEGST_IMPL", raising=False)
        monkeypatch.delenv("DLAF_CHOLESKY_LOOKAHEAD", raising=False)
        config.initialize()
    # ulp-level only: XLA fuses the row-trimmed rest-her2k's gemms
    # differently from the whole-trailing her2k (observed: a few cells of
    # the ragged last block row at 1-2 ulp). The BITWISE contract is the
    # Cholesky one (test_cholesky.py); here the split must be value-equal
    # at fused-gemm reassociation level.
    np.testing.assert_allclose(res["1"], res["0"], rtol=1e-13, atol=1e-13)
    lz = np.linalg.cholesky(b)
    if uplo == "L":
        linv = np.linalg.inv(lz)
        want = np.tril(linv @ a @ linv.conj().T)
        got = np.tril(res["1"])
    else:
        uinv = np.linalg.inv(lz.conj().T)
        want = np.triu(uinv.conj().T @ a @ uinv)
        got = np.triu(res["1"])
    np.testing.assert_allclose(got, want, **_tol(np.float64))
