"""Checkpoint metadata validation + roundtrip (matrix/checkpoint.py).

The load path must reject size/block/grid/source-rank mismatches with a
ValueError NAMING the mismatched field — not surface them later as a
tiling-layer shape assertion. Skips cleanly when orbax is absent (the
checkpoint hook is optional; nothing in the algorithms depends on it).
"""

import numpy as np
import pytest

ocp = pytest.importorskip("orbax.checkpoint")

from dlaf_tpu.comm.grid import Grid  # noqa: E402
from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize  # noqa: E402
from dlaf_tpu.matrix import checkpoint  # noqa: E402
from dlaf_tpu.matrix.matrix import Matrix  # noqa: E402


def _mat(n=12, nb=4, grid=None, seed=0, src=RankIndex2D(0, 0)):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n))
    return Matrix.from_global(a, TileElementSize(nb, nb), grid=grid,
                              source_rank=src)


def _save_tree(path, storage, meta):
    """Write a raw checkpoint tree (the tampered-metadata fixture: orbax
    trees can't be edited in place, so mismatches are saved directly)."""
    with ocp.PyTreeCheckpointer() as ckpt:
        ckpt.save(str(path), {"storage": storage, "meta": meta}, force=True)


def _meta(size, block, grid, src):
    return {
        "size": np.array(size, dtype=np.int64),
        "block_size": np.array(block, dtype=np.int64),
        "grid_size": np.array(grid, dtype=np.int64),
        "source_rank": np.array(src, dtype=np.int64),
    }


def test_roundtrip_local(tmp_path):
    mat = _mat()
    checkpoint.save(str(tmp_path / "ckpt"), mat)
    back = checkpoint.load(str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(back.to_numpy(), mat.to_numpy())
    assert back.dist.size == mat.dist.size
    assert back.dist.block_size == mat.dist.block_size


def test_roundtrip_distributed(tmp_path, devices8):
    grid = Grid(2, 2)
    mat = _mat(16, 4, grid=grid, src=RankIndex2D(1, 0))
    checkpoint.save(str(tmp_path / "ckpt"), mat)
    back = checkpoint.load(str(tmp_path / "ckpt"), grid=Grid(2, 2))
    np.testing.assert_array_equal(back.to_numpy(), mat.to_numpy())
    assert back.dist.source_rank == mat.dist.source_rank


def test_grid_size_mismatch_names_field(tmp_path, devices8):
    mat = _mat()
    checkpoint.save(str(tmp_path / "ckpt"), mat)
    with pytest.raises(ValueError, match="grid_size mismatch"):
        checkpoint.load(str(tmp_path / "ckpt"), grid=Grid(2, 2))
    grid = Grid(2, 2)
    dmat = _mat(16, 4, grid=grid)
    checkpoint.save(str(tmp_path / "dckpt"), dmat)
    with pytest.raises(ValueError, match="grid_size mismatch"):
        checkpoint.load(str(tmp_path / "dckpt"))   # no grid passed


def test_missing_meta_field_names_field(tmp_path):
    mat = _mat()
    meta = _meta((12, 12), (4, 4), (1, 1), (0, 0))
    del meta["source_rank"]
    _save_tree(tmp_path / "ckpt", np.asarray(mat.storage), meta)
    with pytest.raises(ValueError, match="'source_rank' is missing"):
        checkpoint.load(str(tmp_path / "ckpt"))


def test_source_rank_outside_grid_names_field(tmp_path):
    mat = _mat()
    meta = _meta((12, 12), (4, 4), (1, 1), (1, 0))   # rank 1 on a 1x1 grid
    _save_tree(tmp_path / "ckpt", np.asarray(mat.storage), meta)
    with pytest.raises(ValueError, match="source_rank .* outside"):
        checkpoint.load(str(tmp_path / "ckpt"))


def test_block_size_mismatch_is_storage_inconsistency(tmp_path):
    """Tampered block_size: metadata says 6 but the storage was tiled at
    4 — the error names the inconsistency instead of raising from the
    tiling layer's shape assert."""
    mat = _mat(12, 4)
    meta = _meta((12, 12), (6, 6), (1, 1), (0, 0))
    _save_tree(tmp_path / "ckpt", np.asarray(mat.storage), meta)
    with pytest.raises(ValueError, match="storage shape .* inconsistent"):
        checkpoint.load(str(tmp_path / "ckpt"))


def test_size_mismatch_is_storage_inconsistency(tmp_path):
    mat = _mat(12, 4)
    meta = _meta((20, 20), (4, 4), (1, 1), (0, 0))
    _save_tree(tmp_path / "ckpt", np.asarray(mat.storage), meta)
    with pytest.raises(ValueError, match="storage shape .* inconsistent"):
        checkpoint.load(str(tmp_path / "ckpt"))


def test_malformed_meta_shape_names_field(tmp_path):
    mat = _mat()
    meta = _meta((12, 12), (4, 4), (1, 1), (0, 0))
    meta["size"] = np.array([12, 12, 12], dtype=np.int64)
    _save_tree(tmp_path / "ckpt", np.asarray(mat.storage), meta)
    with pytest.raises(ValueError, match="'size' has shape"):
        checkpoint.load(str(tmp_path / "ckpt"))
