"""Tests for ISSUE 18: the multi-replica fleet serve tier
(dlaf_tpu.fleet, docs/fleet.md).

Covers: the length-prefixed JSON transport (round-trip, oversize
refusal, idle vs EOF), Request/ProgramSpec wire round-trips, membership
state transitions at injected-clock edges, router fan-out correctness
against numpy, bucket co-location, the SIGKILL failover drill (worker
death -> every unacked ticket re-dispatched, zero loss), the
heartbeat-timeout drill (wedged worker -> suspect + forced-open breaker
-> re-dispatch -> half-open probe re-admission), the seeded
``inject.fail_fleet_dispatch`` drills (transient fault retries into the
SAME worker; sustained fault opens the breaker and re-routes to the
sibling), the warm-sibling retrace pin (re-dispatched bucket lands on a
warm program: retrace counter stays at first-compile), the
failover-disabled must-trip (``ticket_lost`` records + structured
``WorkerLostError`` + ``--require-fleet`` REJECTS), the graceful drain
contract (handback, ZERO re-dispatches), the ``fleet`` record schema +
``require_fleet`` validator obligations, and the aggregated fleet
``/healthz`` view.
"""

import gc
import os
import socket
import sys
import threading
import time
import weakref

import numpy as np
import pytest

import dlaf_tpu.config as C
from dlaf_tpu import health, obs
from dlaf_tpu.fleet import (Router, TransportClosed, TransportIdle,
                            connect_worker, recv_msg, send_msg,
                            worker_site)
from dlaf_tpu.fleet.membership import Membership
from dlaf_tpu.fleet.router import RemoteError, _bucket_of
from dlaf_tpu.health import inject
from dlaf_tpu.health.errors import FleetUnavailableError, WorkerLostError
from dlaf_tpu.obs.sinks import FLEET_EVENTS, validate_records
from dlaf_tpu.serve import (ProgramService, Queue, Request, cholesky_spec,
                            solve_spec)
from dlaf_tpu.serve import programs as serve_programs
from dlaf_tpu.serve.queue import array_from_wire, array_to_wire


@pytest.fixture(autouse=True)
def fleet_reset():
    """Each test leaves the default config, an empty default service,
    and closed breakers behind (mirrors test_serve.serve_reset)."""
    yield
    for key in ("DLAF_METRICS_PATH", "DLAF_PROGRAM_TELEMETRY",
                "DLAF_SERVE_BUCKETS", "DLAF_SERVE_BATCH",
                "DLAF_SERVE_DEADLINE_MS", "DLAF_FLEET_WORKERS",
                "DLAF_FLEET_FAILOVER", "DLAF_FLEET_HEARTBEAT_MS",
                "DLAF_FLEET_HEARTBEAT_TIMEOUT_MS",
                "DLAF_FLEET_RETRY_ATTEMPTS", "DLAF_FLIGHT_RECORDER"):
        os.environ.pop(key, None)
    obs._reset_for_tests()
    obs.telemetry._reset_for_tests()
    serve_programs._reset_for_tests()
    health.circuit.reset()
    C.finalize()
    C.initialize()


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _hpd(n, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n)).astype(dtype)
    return (x @ x.T + n * np.eye(n)).astype(dtype)


def _check_chol(ticket):
    a = np.asarray(ticket.request.a)
    fac = np.tril(ticket.result())
    np.testing.assert_allclose(fac @ fac.T,
                               np.tril(a) + np.tril(a, -1).T,
                               atol=1e-10 * len(a))


class _Fleet:
    """In-process drill fleet: a router with an injected clock + N
    worker protocol loops on daemon threads, each its own Queue over a
    SHARED ProgramService (the in-process stand-in for the shared
    persistent compile cache — docs/fleet.md warm-sibling contract)."""

    def __init__(self, n_workers=2, batch=1, router_kw=None, clock=None,
                 service=None):
        self.clock = clock if clock is not None else _FakeClock()
        self.router = Router(clock=self.clock, port=0,
                             **(router_kw or {}))
        self.service = service if service is not None else ProgramService()
        self.workers = []
        for k in range(n_workers):
            q = Queue(self.service, batch=batch, deadline_s=1e9,
                      buckets=(16,))
            w = connect_worker(self.router.port, k, queue=q,
                               idle_tick_s=0.01)
            threading.Thread(target=w.serve, daemon=True).start()
            self.workers.append(w)
        deadline = time.monotonic() + 10
        while len(self.router.stats()["workers"]) < n_workers:
            assert time.monotonic() < deadline, "workers never connected"
            self.router.poll()
            time.sleep(0.005)

    def close(self):
        self.router.close()


def _fleet_records(path):
    return [r for r in obs.read_records(path) if r.get("type") == "fleet"]


# ---------------------------------------------------------------------------
# Transport framing
# ---------------------------------------------------------------------------

class TestTransport:
    def test_round_trip(self):
        a, b = socket.socketpair()
        try:
            msg = {"kind": "submit", "seq": 7, "req": {"op": "cholesky"},
                   "unicode": "π≤1"}
            send_msg(a, msg)
            assert recv_msg(b) == msg
        finally:
            a.close()
            b.close()

    def test_eof_raises_closed(self):
        a, b = socket.socketpair()
        a.close()
        try:
            with pytest.raises(TransportClosed):
                recv_msg(b)
        finally:
            b.close()

    def test_idle_timeout_raises_idle_between_frames(self):
        a, b = socket.socketpair()
        try:
            b.settimeout(0.01)
            with pytest.raises(TransportIdle):
                recv_msg(b, idle_ok=True)
            # the stream is intact after an idle tick: a frame sent
            # afterwards still parses
            send_msg(a, {"kind": "ping"})
            assert recv_msg(b, idle_ok=True) == {"kind": "ping"}
        finally:
            a.close()
            b.close()

    def test_mid_frame_timeout_keeps_reading(self):
        import struct
        a, b = socket.socketpair()
        try:
            b.settimeout(0.01)
            payload = b'{"kind": "pong"}'
            a.sendall(struct.pack(">I", len(payload)) + payload[:4])

            def finish():
                time.sleep(0.05)       # several idle ticks mid-frame
                a.sendall(payload[4:])

            threading.Thread(target=finish, daemon=True).start()
            assert recv_msg(b, idle_ok=True) == {"kind": "pong"}
        finally:
            a.close()
            b.close()

    def test_oversize_frame_refused_both_ways(self, monkeypatch):
        from dlaf_tpu.fleet import transport
        monkeypatch.setattr(transport, "MAX_FRAME_BYTES", 64)
        a, b = socket.socketpair()
        try:
            with pytest.raises(ValueError, match="frame"):
                transport.send_msg(a, {"blob": "x" * 128})
            # a corrupt/oversize length prefix kills the stream on recv
            import struct
            a.sendall(struct.pack(">I", 1 << 20))
            with pytest.raises(TransportClosed, match="corrupt"):
                transport.recv_msg(b)
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# Wire codecs
# ---------------------------------------------------------------------------

class TestWire:
    def test_request_round_trip(self):
        a = _hpd(12, dtype=np.float32)
        b = np.ones((12, 3))
        req = Request(op="solve", a=a, b=b, uplo="U", side="L",
                      transa="T", diag="N", alpha=2.0, rid="r1",
                      deadline_s=1.5)
        back = Request.from_wire(req.to_wire())
        np.testing.assert_array_equal(np.asarray(back.a), a)
        np.testing.assert_array_equal(np.asarray(back.b), b)
        assert np.asarray(back.a).dtype == np.float32
        assert (back.op, back.uplo, back.side, back.transa, back.diag,
                back.alpha, back.rid, back.deadline_s) == \
            ("solve", "U", "L", "T", "N", 2.0, "r1", 1.5)

    def test_program_spec_round_trip_is_equal(self):
        spec = solve_spec(batch=4, n=16, nrhs=8, nb=8, dtype="float64",
                          side="R", uplo="U",
                          route=(("f64_gemm_slices", 5),))
        assert spec.from_wire(spec.to_wire()) == spec
        assert spec.from_wire(spec.to_wire()).site == spec.site


# ---------------------------------------------------------------------------
# Membership (pure clock-edge state machine)
# ---------------------------------------------------------------------------

class TestMembership:
    def test_lifecycle_and_timeout_edges(self):
        clock = _FakeClock()
        m = Membership(heartbeat_timeout_s=5.0, clock=clock)
        m.add(0, pid=11)
        m.add(1, pid=22)
        assert m.routable() == [0, 1]
        clock.t = 4.9
        assert m.timed_out(clock.t) == []
        clock.t = 5.1
        m.beat(1)                       # 1 is fresh, 0 went silent
        clock.t = 10.0
        assert m.timed_out(clock.t) == [0]
        assert m.state(0) == "suspect"
        assert m.routable() == [0, 1]   # suspect stays ROUTABLE
        assert m.timed_out(clock.t) == []      # flips only once
        m.beat(0)                       # any message re-ups a suspect
        assert m.state(0) == "up"

    def test_dead_and_draining_are_terminal(self):
        clock = _FakeClock()
        m = Membership(heartbeat_timeout_s=5.0, clock=clock)
        m.add(0)
        m.add(1)
        m.mark_dead(0, "eof")
        m.mark_draining(1)
        m.beat(0)
        m.beat(1)
        assert m.state(0) == "dead" and m.state(1) == "draining"
        assert m.routable() == []
        assert m.states()[0]["reason"] == "eof"


# ---------------------------------------------------------------------------
# Router fan-out (the tentpole happy path)
# ---------------------------------------------------------------------------

class TestRouterDispatch:
    def test_fan_out_results_and_bucket_colocation(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        C.initialize(C.Configuration(metrics_path=path))
        fleet = _Fleet(n_workers=2, batch=1)
        try:
            tickets = [fleet.router.submit(
                Request(op="cholesky", a=_hpd(12, seed=i)))
                for i in range(4)]
            assert fleet.router.join(tickets, timeout_s=60)
            for t in tickets:
                _check_chol(t)
                assert t.info == 0 and t.total_s >= 0.0
            # bucket co-location: one bucket -> one worker
            assert len({t.worker for t in tickets}) == 1
            st = fleet.router.stats()
            assert st["unresolved"] == 0 and st["lost"] == 0
        finally:
            fleet.close()
        obs.flush()
        recs = _fleet_records(path)
        ups = [r for r in recs if r["event"] == "worker_up"]
        routes = [r for r in recs if r["event"] == "route"]
        assert len(ups) == 2 and len(routes) == 4
        # ticket-scoped records are trace-stamped and join the request
        assert all(r.get("trace_id") for r in routes)
        assert sorted(r["seq"] for r in routes) == [0, 1, 2, 3]
        assert validate_records(obs.read_records(path),
                                require_fleet=True) == []

    def test_distinct_buckets_spread_across_workers(self):
        fleet = _Fleet(n_workers=2, batch=1)
        try:
            reqs = [Request(op="cholesky", a=_hpd(12)),
                    Request(op="cholesky", a=_hpd(12).astype(np.float32)),
                    Request(op="cholesky", a=_hpd(12), uplo="U"),
                    Request(op="solve", a=_hpd(12),
                            b=np.ones((12, 2)))]
            assert len({_bucket_of(r) for r in reqs}) == 4
            tickets = [fleet.router.submit(r) for r in reqs]
            assert fleet.router.join(tickets, timeout_s=60)
            assert len({t.worker for t in tickets}) == 2
        finally:
            fleet.close()

    def test_no_workers_fails_fast_and_keeps_nothing(self):
        router = Router(clock=_FakeClock(), port=0)
        try:
            with pytest.raises(FleetUnavailableError):
                router.submit(Request(op="cholesky", a=_hpd(12)))
            assert router.stats()["unresolved"] == 0
        finally:
            router.close()

    def test_worker_acked_failure_is_terminal_remote_error(self):
        """A worker that PROCESSED a request and acked a structured
        failure is final — at-least-once covers lost tickets only."""
        clock = _FakeClock()
        router = Router(clock=clock, port=0)
        try:
            stub = socket.create_connection(("127.0.0.1", router.port))
            stub.settimeout(5.0)
            send_msg(stub, {"kind": "hello", "worker": 0, "pid": 1})
            deadline = time.monotonic() + 10
            while not router.stats()["workers"]:
                assert time.monotonic() < deadline
                router.poll()
                time.sleep(0.005)
            t = router.submit(Request(op="cholesky", a=_hpd(12)))
            msg = recv_msg(stub)
            assert msg["kind"] == "submit" and msg["seq"] == t.seq
            send_msg(stub, {"kind": "result", "seq": t.seq, "ok": False,
                            "worker": 0,
                            "error": {"type": "OverloadError",
                                      "message": "queue full"}})
            assert router.join([t], timeout_s=10)
            with pytest.raises(RuntimeError, match="request failed"):
                t.result()
            assert isinstance(t.error, RemoteError)
            assert t.error.etype == "OverloadError"
            st = router.stats()
            assert st["redispatches"] == 0 and st["lost"] == 0
            stub.close()
        finally:
            router.close()


# ---------------------------------------------------------------------------
# Failover drills (SIGKILL stand-in + heartbeat timeout)
# ---------------------------------------------------------------------------

class TestFailover:
    def test_worker_kill_redispatches_every_unacked_ticket(self, tmp_path):
        """The replica-kill drill: a worker dies holding a full batch of
        unacknowledged tickets; every one re-dispatches to the sibling
        and completes — zero loss, and the artifact proves it."""
        path = str(tmp_path / "m.jsonl")
        C.initialize(C.Configuration(metrics_path=path))
        # batch=8 >> submits: tickets sit undispatched (unacked) in the
        # victim until the kill
        fleet = _Fleet(n_workers=2, batch=8)
        try:
            tickets = [fleet.router.submit(
                Request(op="cholesky", a=_hpd(12, seed=i)))
                for i in range(3)]
            victim = tickets[0].worker
            fleet.workers[victim].kill()          # SIGKILL stand-in
            deadline = time.monotonic() + 10
            while fleet.router.stats()["workers"][victim]["state"] \
                    != "dead":
                assert time.monotonic() < deadline
                fleet.router.poll()
                time.sleep(0.005)
            fleet.router.flush()
            assert fleet.router.join(tickets, timeout_s=60)
            sibling = 1 - victim
            for t in tickets:
                _check_chol(t)
                assert t.worker == sibling and t.redispatched == 1
                assert t.attempts == [victim, sibling]
            st = fleet.router.stats()
            assert st["redispatches"] == 3 and st["lost"] == 0
        finally:
            fleet.close()
        obs.flush()
        recs = _fleet_records(path)
        dead = [r for r in recs if r["event"] == "worker_dead"]
        redis = [r for r in recs if r["event"] == "redispatch"]
        assert len(dead) == 1 and dead[0]["attrs"]["reason"] == "eof"
        assert len(redis) == 3
        assert all(r["attrs"]["from"] == victim for r in redis)
        # a re-dispatch is joinable to its original route by trace_id
        routes = {r["trace_id"]: r for r in recs if r["event"] == "route"}
        assert all(r["trace_id"] in routes for r in redis)
        assert validate_records(obs.read_records(path),
                                require_fleet=True) == []

    def test_worker_death_trips_the_flight_recorder(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        C.initialize(C.Configuration(metrics_path=path,
                                     flight_recorder=64))
        dump = path + ".flight.jsonl"
        fleet = _Fleet(n_workers=2, batch=8)
        try:
            t = fleet.router.submit(Request(op="cholesky", a=_hpd(12)))
            fleet.workers[t.worker].kill()
            deadline = time.monotonic() + 10
            while not os.path.exists(dump):
                assert time.monotonic() < deadline
                fleet.router.poll()
                time.sleep(0.005)
            recs = obs.read_records(dump)
            trig = [r for r in recs if r.get("type") == "flight_trigger"]
            assert trig and trig[-1]["reason"] == "fleet_worker_down"
            assert trig[-1]["attrs"]["unacked"] == 1
            assert trig[-1]["attrs"]["failover"] is True
            assert validate_records(recs, require_flight=True) == []
        finally:
            fleet.close()

    def test_heartbeat_timeout_suspects_reroutes_and_readmits(self):
        """The wedged-worker drill, fully deterministic under the
        injected clock: a silent worker flips suspect, its breaker is
        forced open, its unacked ticket re-dispatches to the sibling;
        after the cooldown the NEXT dispatch probes it half-open and a
        successful ACK closes the breaker (re-admission)."""
        clock = _FakeClock()
        router = Router(clock=clock, port=0, heartbeat_s=1.0,
                        heartbeat_timeout_s=5.0)
        wedged = socket.create_connection(("127.0.0.1", router.port))
        wedged.settimeout(10.0)
        send_msg(wedged, {"kind": "hello", "worker": 0, "pid": 1})
        deadline = time.monotonic() + 10
        while not router.stats()["workers"]:
            assert time.monotonic() < deadline
            router.poll()
            time.sleep(0.005)
        try:
            # the only worker: the ticket lands on the wedge and is
            # never acked
            t1 = router.submit(Request(op="cholesky", a=_hpd(12)))
            assert t1.worker == 0
            assert recv_msg(wedged)["kind"] == "submit"
            # bring up a live sibling, then advance past the timeout
            fleet_q = Queue(ProgramService(), batch=1, deadline_s=1e9,
                            buckets=(16,))
            w1 = connect_worker(router.port, 1, queue=fleet_q,
                                idle_tick_s=0.01)
            threading.Thread(target=w1.serve, daemon=True).start()
            deadline = time.monotonic() + 10
            while len(router.stats()["workers"]) < 2:
                assert time.monotonic() < deadline
                router.poll()
                time.sleep(0.005)
            # a ping edge at t=1.5: the live sibling pongs (fresh beat),
            # the wedge stays silent — so only IT times out at t=6
            clock.t = 1.5
            router.poll()
            deadline = time.monotonic() + 10
            while router.stats()["workers"][1]["last_seen"] < 1.5:
                assert time.monotonic() < deadline, "sibling never ponged"
                router.poll()
                time.sleep(0.005)
            clock.t = 6.0
            router.poll()
            st = router.stats()
            assert st["workers"][0]["state"] == "suspect"
            assert st["workers"][1]["state"] == "up"
            assert st["breakers"][0] == "open"
            assert router.join([t1], timeout_s=60)
            _check_chol(t1)
            assert t1.worker == 1 and t1.redispatched == 1
            # cooldown elapsed: the next same-bucket dispatch is the
            # half-open probe back into worker 0 IF selection prefers it;
            # force preference by draining the sibling first
            router._send(1, {"kind": "drain"})
            deadline = time.monotonic() + 10
            while router.stats()["workers"][1]["state"] != "dead":
                assert time.monotonic() < deadline
                router.poll()
                time.sleep(0.005)
            clock.t = 6.0 + 31.0        # default cooldown 30s
            t2 = router.submit(Request(op="cholesky", a=_hpd(12, seed=9)))
            assert t2.worker == 0
            assert router.stats()["breakers"][0] == "half_open"
            msg = recv_msg(wedged)
            while msg["kind"] != "submit":
                msg = recv_msg(wedged)
            assert msg["seq"] == t2.seq
            # the wedge recovers: its ACK closes the breaker and re-ups
            # the suspect
            send_msg(wedged, {"kind": "result", "seq": t2.seq, "ok": True,
                              "worker": 0,
                              "arrays": [array_to_wire(np.eye(12))],
                              "info": 0, "queue_s": 0.0, "total_s": 0.0})
            assert router.join([t2], timeout_s=10)
            st = router.stats()
            assert st["breakers"][0] == "closed"
            assert st["workers"][0]["state"] == "up"
        finally:
            wedged.close()
            router.close()

    def test_failover_disabled_loses_loudly_and_validator_rejects(
            self, tmp_path):
        """The must-trip leg: with DLAF_FLEET_FAILOVER=0 a worker death
        poisons its unacked tickets with structured WorkerLostError and
        ``ticket_lost`` records — and ``require_fleet`` REJECTS the
        artifact."""
        path = str(tmp_path / "m.jsonl")
        C.initialize(C.Configuration(metrics_path=path))
        fleet = _Fleet(n_workers=2, batch=8,
                       router_kw={"failover": False})
        try:
            tickets = [fleet.router.submit(
                Request(op="cholesky", a=_hpd(12, seed=i)))
                for i in range(2)]
            victim = tickets[0].worker
            fleet.workers[victim].kill()
            assert fleet.router.join(tickets, timeout_s=30)
            for t in tickets:
                with pytest.raises(RuntimeError) as ei:
                    t.result()
                assert isinstance(ei.value.__cause__, WorkerLostError)
            st = fleet.router.stats()
            assert st["lost"] == 2 and st["redispatches"] == 0
        finally:
            fleet.close()
        obs.flush()
        recs = obs.read_records(path)
        lost = [r for r in recs if r.get("type") == "fleet"
                and r["event"] == "ticket_lost"]
        assert len(lost) == 2
        assert all(r["attrs"]["reason"] == "eof" for r in lost)
        errors = validate_records(recs, require_fleet=True)
        assert any("ticket_lost" in e for e in errors), errors
        # the same artifact passes WITHOUT the fleet obligation: the
        # schema itself is valid — only the zero-loss contract is broken
        assert validate_records(recs) == []


# ---------------------------------------------------------------------------
# Seeded dispatch-fault drills (inject.fail_fleet_dispatch)
# ---------------------------------------------------------------------------

class TestInjectedDispatchFaults:
    def test_transient_fault_retries_into_the_same_worker(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        C.initialize(C.Configuration(metrics_path=path))
        fleet = _Fleet(n_workers=2, batch=1)
        try:
            # learn the bucket's preferred worker with no fault injected
            t0 = fleet.router.submit(Request(op="cholesky", a=_hpd(12)))
            assert fleet.router.join([t0], timeout_s=60)
            preferred = t0.worker
            with inject.fail_fleet_dispatch(nth=0, count=1):
                t1 = fleet.router.submit(
                    Request(op="cholesky", a=_hpd(12, seed=5)))
            # one transient fault: attempt 2 lands on the SAME worker
            # (breaker threshold 3 keeps it admitted)
            assert t1.worker == preferred
            assert fleet.router.join([t1], timeout_s=60)
            _check_chol(t1)
        finally:
            fleet.close()
        obs.flush()
        recs = obs.read_records(path)
        retries = [r for r in recs if r.get("type") == "resilience"
                   and r["event"] == "retry"
                   and r["site"] == "fleet.dispatch"]
        assert len(retries) == 1

    def test_sustained_fault_opens_the_breaker_and_reroutes(
            self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        C.initialize(C.Configuration(metrics_path=path))
        fleet = _Fleet(n_workers=2, batch=1)
        try:
            t0 = fleet.router.submit(Request(op="cholesky", a=_hpd(12)))
            assert fleet.router.join([t0], timeout_s=60)
            preferred = t0.worker
            # 3 consecutive faults = the default breaker threshold: the
            # preferred worker's breaker opens mid-policy and attempt 4
            # re-routes to the sibling
            with inject.fail_fleet_dispatch(nth=0, count=3):
                t1 = fleet.router.submit(
                    Request(op="cholesky", a=_hpd(12, seed=5)))
                assert t1.worker == 1 - preferred
                assert fleet.router.stats()["breakers"][preferred] \
                    == "open"
            assert fleet.router.join([t1], timeout_s=60)
            _check_chol(t1)
        finally:
            fleet.close()

    def test_redispatched_bucket_reuses_the_siblings_warm_program(
            self, tmp_path):
        """The warm-failover pin (docs/fleet.md): after both workers are
        warm on a bucket, a kill-and-redispatch must NOT recompile —
        dlaf_retrace_total for the bucket's program site stays at its
        first-compile value (1), i.e. retrace <= 1 per bucket per
        worker over the whole drill."""
        path = str(tmp_path / "m.jsonl")
        C.initialize(C.Configuration(metrics_path=path,
                                     program_telemetry=True))
        fleet = _Fleet(n_workers=2, batch=2)
        try:
            spec = cholesky_spec(batch=2, n=16, nb=16, dtype="float64")
            walls = fleet.router.warmup([spec], timeout_s=300.0)
            assert sorted(walls) == [0, 1]
            site = spec.site
            warm = obs.registry().counter("dlaf_retrace_total",
                                          site=site).snapshot()["value"]
            assert warm == 1        # shared service: ONE compile total
            tickets = [fleet.router.submit(
                Request(op="cholesky", a=_hpd(16, seed=i)))
                for i in range(2)]
            victim = tickets[0].worker
            fleet.workers[victim].kill()
            deadline = time.monotonic() + 10
            while fleet.router.stats()["workers"][victim]["state"] \
                    != "dead":
                assert time.monotonic() < deadline
                fleet.router.poll()
                time.sleep(0.005)
            fleet.router.flush()
            assert fleet.router.join(tickets, timeout_s=60)
            for t in tickets:
                _check_chol(t)
            after = obs.registry().counter("dlaf_retrace_total",
                                           site=site).snapshot()["value"]
            assert after == warm, (warm, after)
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# Graceful drain (SIGTERM twin)
# ---------------------------------------------------------------------------

class TestGracefulDrain:
    def test_drain_hands_back_undispatched_with_zero_redispatches(
            self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        C.initialize(C.Configuration(metrics_path=path))
        fleet = _Fleet(n_workers=2, batch=8)
        try:
            tickets = [fleet.router.submit(
                Request(op="cholesky", a=_hpd(12, seed=i)))
                for i in range(3)]
            victim = tickets[0].worker
            fleet.workers[victim].request_drain()  # SIGTERM stand-in
            deadline = time.monotonic() + 15
            while fleet.router.stats()["workers"][victim]["state"] \
                    != "dead":
                assert time.monotonic() < deadline
                fleet.router.poll()
                time.sleep(0.005)
            fleet.router.flush()
            assert fleet.router.join(tickets, timeout_s=60)
            sibling = 1 - victim
            for t in tickets:
                _check_chol(t)
                assert t.worker == sibling
                assert t.redispatched == 0     # handback, NOT failover
            st = fleet.router.stats()
            assert st["handbacks"] == 3 and st["redispatches"] == 0
            assert st["lost"] == 0
            assert st["workers"][victim]["reason"] == "drained"
        finally:
            fleet.close()
        obs.flush()
        recs = _fleet_records(path)
        events = [r["event"] for r in recs]
        assert events.count("handback") == 3
        assert events.count("redispatch") == 0
        assert events.count("draining") == 1
        assert events.count("drained") == 1
        dead = [r for r in recs if r["event"] == "worker_dead"]
        assert [r["attrs"]["reason"] for r in dead] == ["drained"]
        # graceful death does NOT demand a redispatch record
        assert validate_records(obs.read_records(path),
                                require_fleet=True) == []


# ---------------------------------------------------------------------------
# Record schema + require_fleet obligations
# ---------------------------------------------------------------------------

def _rec(**over):
    base = {"type": "fleet", "v": 1, "ts": 1.0, "event": "route",
            "worker": 0, "seq": 3, "trace_id": "ab12" * 8, "attrs": {}}
    base.update(over)
    return base


def _membership_rec(**over):
    rec = _rec(**over)
    del rec["seq"], rec["trace_id"]
    return rec


class TestSchemaAndValidator:
    def test_valid_records_pass(self):
        ticket_scoped = ("route", "redispatch", "handback", "ticket_lost")
        recs = [_rec(event=e) if e in ticket_scoped
                else _membership_rec(event=e) for e in FLEET_EVENTS]
        assert validate_records(recs) == []

    @pytest.mark.parametrize("over,msg", [
        ({"event": "teleport"}, "fleet event"),
        ({"worker": None}, "worker"),
        ({"worker": -1}, "worker"),
        ({"worker": True}, "worker"),
        ({"seq": None}, "seq"),
        ({"seq": -2}, "seq"),
        ({"trace_id": None}, "trace-stamped"),
        ({"attrs": "x"}, "attrs"),
    ])
    def test_schema_rejections(self, over, msg):
        errors = validate_records([_rec(**over)])
        assert errors and msg in errors[0], errors

    def test_require_fleet_needs_a_route(self):
        errors = validate_records([_membership_rec(event="worker_up")],
                                  require_fleet=True)
        assert any("no fleet route" in e for e in errors), errors

    def test_require_fleet_rejects_any_ticket_lost(self):
        recs = [_rec(), _rec(event="ticket_lost", seq=4)]
        errors = validate_records(recs, require_fleet=True)
        assert any("ticket_lost" in e for e in errors), errors

    def test_require_fleet_demands_failover_after_ungraceful_death(self):
        dead = _membership_rec(event="worker_dead",
                               attrs={"reason": "eof"})
        errors = validate_records([_rec(), dead], require_fleet=True)
        assert any("failover never ran" in e for e in errors), errors
        # answered by a redispatch -> clean
        recs = [_rec(), dead, _rec(event="redispatch", seq=5)]
        assert validate_records(recs, require_fleet=True) == []
        # a DRAINED death demands nothing
        drained = _membership_rec(event="worker_dead",
                                  attrs={"reason": "drained"})
        assert validate_records([_rec(), drained],
                                require_fleet=True) == []

    def test_validate_cli_flag(self, tmp_path):
        from dlaf_tpu.obs import validate as vcli
        good = tmp_path / "good.jsonl"
        import json as _json
        good.write_text(_json.dumps(_rec()) + "\n")
        assert vcli.main([str(good), "--require-fleet"]) == 0
        bad = tmp_path / "bad.jsonl"
        lost = _rec(event="ticket_lost", seq=4)
        bad.write_text(_json.dumps(_rec()) + "\n"
                       + _json.dumps(lost) + "\n")
        assert vcli.main([str(bad), "--require-fleet"]) == 1
        assert vcli.main([str(bad)]) == 0


# ---------------------------------------------------------------------------
# Aggregated health
# ---------------------------------------------------------------------------

class TestFleetHealth:
    def test_healthz_aggregates_worker_payloads(self):
        fleet = _Fleet(n_workers=2, batch=1)
        try:
            view = fleet.router.healthz(timeout_s=30.0)
            assert view["status"] == "ok"
            assert sorted(view["workers"]) == [0, 1]
            for payload in view["workers"].values():
                assert payload["status"] == "ok"
                assert "queues" in payload and "breakers" in payload
            assert view["fleet"]["lost"] == 0
        finally:
            fleet.close()

    def test_router_lands_on_the_exporter_healthz(self):
        fleet = _Fleet(n_workers=1, batch=1)
        try:
            payload = obs.exporter.healthz_payload()
            assert "fleet" in payload
            # [-1]: the most recently registered router (earlier tests'
            # closed routers may not be collected yet)
            assert payload["fleet"][-1]["workers"][0]["state"] == "up"
        finally:
            fleet.close()

    def test_degraded_when_a_worker_is_dead(self):
        fleet = _Fleet(n_workers=2, batch=1)
        try:
            fleet.workers[0].kill()
            deadline = time.monotonic() + 10
            while fleet.router.stats()["workers"][0]["state"] != "dead":
                assert time.monotonic() < deadline
                fleet.router.poll()
                time.sleep(0.005)
            view = fleet.router.healthz(timeout_s=10.0)
            assert view["status"] == "degraded"
        finally:
            fleet.close()

    def test_close_releases_worker_threads_and_healthz_queues(self):
        """Regression: ``Router.close()`` must shutdown() its sockets,
        not just close() them — the reader threads' blocked recv holds
        the open file description, so a bare close() never sends FIN:
        the accept loop, the readers, and every in-process worker loop
        (and therefore its /healthz-registered Queue) leaked forever."""
        before = {t.ident for t in threading.enumerate()}
        fleet = _Fleet(n_workers=2, batch=1)
        queue_refs = [weakref.ref(w.queue) for w in fleet.workers]
        fleet.close()
        deadline = time.monotonic() + 10
        while True:
            leaked = [t for t in threading.enumerate()
                      if t.ident not in before and t.is_alive()]
            if not leaked:
                break
            assert time.monotonic() < deadline, \
                f"fleet threads leaked past close(): {leaked}"
            time.sleep(0.01)
        del fleet
        gc.collect()
        assert [r() for r in queue_refs] == [None, None], \
            "closed fleet's worker queues still reachable (would pin " \
            "dead queues onto /healthz)"
