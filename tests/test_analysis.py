"""Static-analysis layer (dlaf_tpu/analysis/, docs/static_analysis.md).

Every graphcheck invariant and lint rule gets three cases here: a
PASSING case (clean input produces no finding), a MUST-TRIP case (the
seeded-bad drill produces exactly the expected rule), and a SUPPRESSED
case (in-code ``dlaf: disable=RULE(reason)`` for lint, the committed-
baseline workflow for graph findings). Plus the depgraph traversal
vocabulary itself, pinned on toy programs with known structure.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from dlaf_tpu import _compat
from dlaf_tpu.analysis import (Finding, depgraph, diff_baseline, drills,
                               graphcheck, lint, load_baseline,
                               write_baseline)
from dlaf_tpu.analysis.__main__ import main as analysis_main


# ---------------------------------------------------------------------------
# depgraph: the traversal vocabulary on toy programs of known structure
# ---------------------------------------------------------------------------

def _toy_jaxpr():
    def fn(x):
        a = x * 2.0            # eqn 0 (mul)
        b = a + 1.0            # eqn 1 (add)    depends on mul
        c = x - 3.0            # eqn 2 (sub)    independent of mul
        return b @ c           # eqn 3 (dot_general)

    return depgraph.trace(fn, jax.ShapeDtypeStruct((4, 4), jnp.float64))


def test_depgraph_positions_and_closure():
    eqns = _toy_jaxpr().jaxpr.eqns
    [dot] = depgraph.positions(eqns, "dot_general")
    assert depgraph.depends_on(eqns, dot, "mul")
    [sub] = depgraph.positions(eqns, "sub")
    assert not depgraph.depends_on(eqns, sub, "mul")
    # closure of the dot's inputs contains all three producer eqns
    names = {e.primitive.name
             for e in depgraph.closure(eqns, eqns[dot].invars)}
    assert names == {"mul", "add", "sub"}


def test_depgraph_predicate_shorthand_and_is_bulk_dot():
    eqns = _toy_jaxpr().jaxpr.eqns
    by_name = depgraph.positions(eqns, "dot_general")
    by_pred = depgraph.positions(
        eqns, lambda e: e.primitive.name == "dot_general")
    assert by_name == by_pred and len(by_name) == 1
    assert depgraph.is_bulk_dot(eqns[by_name[0]], rank=2)
    assert not depgraph.is_bulk_dot(eqns[by_name[0]])   # default rank=4


def test_depgraph_shard_map_body_and_collectives(devices8):
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("row", "col"))

    def body(x):
        y = lax.psum(x, "row")
        return lax.all_gather(y, "col")

    fn = _compat.shard_map(body, mesh=mesh, in_specs=P("row", "col"),
                           out_specs=P(None, None), check_vma=False)
    sds = jax.ShapeDtypeStruct((4, 4), jnp.float64)
    eqns = depgraph.shard_map_body(fn, sds)
    colls = depgraph.collectives(eqns)
    assert [c.kind for c in colls] == ["psum", "all_gather"]
    assert colls[0].axes == ("row",) and colls[1].axes == ("col",)
    assert colls[0].shape == (2, 2)       # per-shard operand on the 2x2 mesh
    assert colls[0].dtype == "float64" and colls[0].nbytes == 4 * 8
    assert not colls[0].conditional
    # a non-shard_map program must refuse, not guess
    with pytest.raises(ValueError, match="shard_map"):
        depgraph.shard_map_body(lambda x: x + 1.0, sds)


def test_depgraph_scan_body_and_carry_slots():
    def fn(x):
        def body(carry, _):
            live, dead = carry
            live = live * 2.0
            return (live, dead), live.sum()

        (live, _dead), ys = lax.scan(body, (x, x + 1.0), None, length=3)
        return live, ys

    jaxpr = depgraph.trace(fn, jax.ShapeDtypeStruct((4,), jnp.float64))
    [scan] = depgraph.scan_eqns(jaxpr.jaxpr.eqns)
    body = depgraph.scan_body(jaxpr.jaxpr.eqns)
    assert any(e.primitive.name == "mul" for e in body)
    slots = depgraph.scan_carry_slots(scan)
    assert [s.dead for s in slots] == [False, True]
    assert depgraph.dropped_outputs(scan) == []   # ys is returned


def test_depgraph_carry_feeding_a_later_slot_is_read():
    """A carry var that is passthrough at its own slot AND returned at a
    later slot flows somewhere every iteration — it must NOT be dead
    (every occurrence counts, not just the first)."""
    def fn(x):
        def body(carry, _):
            a, _b = carry
            return (a, a), None

        (a, b), _ = lax.scan(body, (x, x + 1.0), None, length=3)
        return a + b

    jaxpr = depgraph.trace(fn, jax.ShapeDtypeStruct((4,), jnp.float64))
    [scan] = depgraph.scan_eqns(jaxpr.jaxpr.eqns)
    slots = depgraph.scan_carry_slots(scan)
    assert not slots[0].dead, slots
    with pytest.raises(ValueError, match="no scan"):
        depgraph.scan_body(_toy_jaxpr().jaxpr.eqns)


def test_depgraph_iter_eqns_paths():
    def fn(x):
        def body(c, _):
            return c * 2.0, None

        c, _ = lax.scan(body, x, None, length=2)
        return c

    jaxpr = depgraph.trace(fn, jax.ShapeDtypeStruct((4,), jnp.float64))
    paths = {e.primitive.name: path
             for path, e in depgraph.iter_eqns(jaxpr.jaxpr)}
    assert paths["scan"] == ()
    assert paths["mul"] == (("scan", "jaxpr"),)
    assert not depgraph.path_has_conditional(paths["mul"])


# ---------------------------------------------------------------------------
# graphcheck invariants: passing / must-trip / baseline-suppressed
# ---------------------------------------------------------------------------

def test_graphcheck_clean_program_has_no_findings():
    """PASSING case for every graph rule at once: an unconditional-
    collective, callback-free, f64-preserving, lean toy program."""
    fs = graphcheck.audit_jaxpr("toy", _toy_jaxpr())
    assert fs == []


@pytest.mark.parametrize("drill", sorted(drills.DRILLS))
def test_drills_trip_their_rules(drill, devices8):
    """MUST-TRIP case for every rule: each seeded-bad drill reports
    exactly the rules it was built to violate."""
    findings, expected = drills.run(drill)
    rules = {f.rule for f in findings}
    assert set(expected) <= rules, (drill, rules)


def test_graphcheck_repo_builders_audit_clean(devices8):
    """The acceptance pin: the full builder matrix audits clean (any
    future violation lands in CI with the rule named)."""
    findings = graphcheck.run()
    assert findings == [], [str(f) for f in findings]


def test_graphcheck_specs_are_not_vacuous(devices8):
    """Stale-audit guard: the audited programs must actually contain
    collectives and scans, or the invariants pin nothing."""
    with graphcheck.pinned_native_config():
        specs = graphcheck.program_specs()
        assert len(specs) >= 30
        dist = [s for s in specs if ".dist" in s.name]
        scans = [s for s in specs if "scan" in s.name]
        assert len(dist) >= 15 and scans
        ncoll = 0
        for spec in dist[:4] + scans[:2]:
            fn, args = spec.build()
            jaxpr = depgraph.trace(fn, *args)
            ncoll += len(depgraph.collectives(jaxpr.jaxpr))
        assert ncoll > 10


def test_graphcheck_hbm_denominator_is_per_shard(devices8):
    """Inside a shard_map body the blow-up budget denominator is the
    body's own (per-shard) input bytes — a 16x-per-shard broadcast
    temporary on a 2x2 mesh is only 4x the GLOBAL inputs and would
    otherwise slip under the 8x budget by exactly the mesh size."""
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("row", "col"))

    def body(x):
        big = jnp.broadcast_to(x, (16,) + x.shape) * 2.0
        return big.sum(axis=0)

    fn = _compat.shard_map(body, mesh=mesh, in_specs=P("row", "col"),
                           out_specs=P("row", "col"), check_vma=False)
    jaxpr = depgraph.trace(fn, jax.ShapeDtypeStruct((16, 16), jnp.float64))
    fs = graphcheck.audit_jaxpr("shardtoy", jaxpr)
    assert any(f.rule == "graph-hbm-blowup" for f in fs), \
        [str(f) for f in fs]


def test_graphcheck_hbm_factor_is_configurable():
    """The blow-up budget is a knob: the clean toy program trips once
    the budget drops below its honest ~1x intermediates."""
    fs = graphcheck.audit_jaxpr("toy", _toy_jaxpr(), hbm_factor=0.5)
    assert any(f.rule == "graph-hbm-blowup" for f in fs)


def test_baseline_workflow_suppresses_graph_findings(tmp_path, devices8):
    """SUPPRESSED case for graph rules: a finding whose key is in the
    committed baseline no longer fails the gate; fixing it reports the
    key as stale."""
    findings, _ = drills.run("hbm_blowup")
    assert findings
    base = tmp_path / "baseline.json"
    write_baseline(str(base), findings)
    new, stale = diff_baseline(findings, load_baseline(str(base)))
    assert new == [] and stale == []
    # fixed code -> no findings -> every baselined key reported stale
    new, stale = diff_baseline([], load_baseline(str(base)))
    assert new == [] and stale == sorted({f.key for f in findings})


def test_baseline_rejects_malformed(tmp_path):
    bad = tmp_path / "b.json"
    bad.write_text(json.dumps({"findings": "nope"}))
    with pytest.raises(ValueError, match="baseline"):
        load_baseline(str(bad))
    assert load_baseline(str(tmp_path / "missing.json")) == []


# ---------------------------------------------------------------------------
# lint rules: passing / must-trip / suppressed for each
# ---------------------------------------------------------------------------

ALGO_PATH = "dlaf_tpu/algorithms/fake.py"


def _rules(src, path=ALGO_PATH):
    return {f.rule for f in lint.lint_source(src, path)}


def test_lint_unregistered_knob_cases():
    trip = 'import os\nV = os.environ.get("DLAF_NOT_A_KNOB")\n'
    ok = 'import os\nV = os.environ.get("DLAF_LOG")\n'   # registered field
    sup = ('import os\nV = os.environ.get("DLAF_NOT_A_KNOB")'
           '  # dlaf: disable=lint-unregistered-knob(test hook)\n')
    assert "lint-unregistered-knob" in _rules(trip)
    assert "lint-unregistered-knob" not in _rules(ok)
    assert "lint-unregistered-knob" not in _rules(sup)
    # multi-line statements are suppressible from any of their lines
    multi = ('import os\nV = os.environ.get(\n'
             '    "DLAF_NOT_A_KNOB"'
             '  # dlaf: disable=lint-unregistered-knob(test hook)\n)\n')
    assert "lint-unregistered-knob" not in _rules(multi)
    # non-DLAF env reads are out of scope
    other = 'import os\nV = os.environ.get("JAX_PLATFORMS")\n'
    assert "lint-unregistered-knob" not in _rules(other)


def test_lint_traced_metric_cases():
    trip = ('from dlaf_tpu import obs\n'
            'def _build_x(dist, mesh):\n'
            '    def fn(s):\n'
            '        obs.counter("dlaf_x_total", mode="a").inc()\n'
            '        return s\n'
            '    return fn\n')
    guarded = trip.replace(
        '        obs.counter("dlaf_x_total", mode="a").inc()\n',
        '        if obs.metrics_active():\n'
        '            obs.counter("dlaf_x_total", mode="a").inc()\n')
    sup = trip.replace(
        '.inc()\n',
        '.inc()  # dlaf: disable=lint-unguarded-traced-metric(host-side '
        'builder accounting, runs once per build)\n')
    assert "lint-unguarded-traced-metric" in _rules(trip)
    assert "lint-unguarded-traced-metric" not in _rules(guarded)
    assert "lint-unguarded-traced-metric" not in _rules(sup)
    # outside the traced layers the rule does not apply
    assert "lint-unguarded-traced-metric" not in _rules(
        trip, "dlaf_tpu/health/fake.py")


def test_lint_np_in_traced_cases():
    trip = ('import jax\nimport numpy as np\n'
            '@jax.jit\n'
            'def f(a):\n'
            '    return np.abs(a)\n')
    # np on static index math at builder level (not in a nested def) is
    # the documented-legal pattern
    ok = ('import numpy as np\n'
          'def _build_x(dist, mesh, nb):\n'
          '    idx = np.arange(nb)\n'
          '    def fn(s):\n'
          '        return s[idx[0]]\n'
          '    return fn\n')
    sup = trip.replace(
        'return np.abs(a)\n',
        'return np.abs(a)  # dlaf: disable=lint-np-in-traced(constant-'
        'folded at trace time on purpose)\n')
    assert "lint-np-in-traced" in _rules(trip)
    assert "lint-np-in-traced" not in _rules(ok)
    assert "lint-np-in-traced" not in _rules(sup)
    # nested def inside a _build_* builder is a traced body
    nested = ('import numpy as np\n'
              'def _build_x(dist, mesh):\n'
              '    def fn(s):\n'
              '        return np.abs(s)\n'
              '    return fn\n')
    assert "lint-np-in-traced" in _rules(nested)
    # outside algorithms/eigensolver the rule does not apply
    assert "lint-np-in-traced" not in _rules(trip, "dlaf_tpu/comm/fake.py")


def test_lint_host_sync_cases():
    trip = ('import jax\n'
            'def f(a):\n'
            '    return jax.device_get(a)\n')
    printer = 'def f(x):\n    print(x)\n'
    sup = trip.replace(
        'return jax.device_get(a)\n',
        'return jax.device_get(a)  # dlaf: disable=lint-host-sync(debug '
        'helper, never on the hot path)\n')
    assert "lint-host-sync" in _rules(trip)
    assert "lint-host-sync" in _rules(printer)
    assert "lint-host-sync" not in _rules(sup)
    # allow-listed host boundaries: miniapps and the tridiag host stage
    assert "lint-host-sync" not in _rules(
        printer, "dlaf_tpu/miniapp/fake.py")
    assert "lint-host-sync" not in _rules(
        trip, "dlaf_tpu/eigensolver/tridiag_solver.py")
    # outside dlaf_tpu/ (tests, scripts) the rule does not apply
    assert "lint-host-sync" not in _rules(printer, "scripts/fake.py")


def test_lint_suppression_reason_cases():
    bare = ('import os\nV = os.environ.get("DLAF_NOT_A_KNOB")'
            '  # dlaf: disable=lint-unregistered-knob\n')
    rules = _rules(bare)
    # a reason-less suppression is itself a finding AND does not suppress
    assert "lint-suppression-reason" in rules
    assert "lint-unregistered-knob" in rules
    good = bare.replace("disable=lint-unregistered-knob",
                        "disable=lint-unregistered-knob(justified)")
    rules = _rules(good)
    assert "lint-suppression-reason" not in rules
    assert "lint-unregistered-knob" not in rules


def test_lint_env_write_is_not_a_read():
    """Setting an env var (propagating a knob to a child process) is a
    write — only Load-context subscripts count as unregistered reads."""
    write = 'import os\nos.environ["DLAF_NOT_A_KNOB"] = "1"\n'
    read = 'import os\nV = os.environ["DLAF_NOT_A_KNOB"]\n'
    assert "lint-unregistered-knob" not in _rules(write)
    assert "lint-unregistered-knob" in _rules(read)


def test_lint_empty_walk_refuses_to_pass(tmp_path):
    """Zero files scanned must raise, not report a vacuously clean
    gate (a wrong --root would otherwise disable the linter)."""
    with pytest.raises(FileNotFoundError, match="vacuously"):
        lint.run(str(tmp_path))
    with pytest.raises(SystemExit) as e:
        analysis_main(["--lint-only", "--root", str(tmp_path)])
    assert e.value.code == 2


def test_pinned_native_config_restores_caller_struct_config():
    """A programmatically-installed Configuration survives a graphcheck
    audit: the exit path re-installs the caller's active config, not
    the env-derived defaults."""
    import dlaf_tpu.config as config

    config.initialize(config.Configuration(dc_level_batch="1"))
    try:
        with graphcheck.pinned_native_config():
            assert config.get_configuration().dc_level_batch == "0"
        assert config.get_configuration().dc_level_batch == "1"
    finally:
        config.initialize(config.Configuration())


def test_lint_suppression_in_string_is_inert():
    """Only real COMMENT tokens suppress (or trip the bare-suppression
    rule): a docstring quoting the syntax is neither a phantom finding
    nor a silent suppressor."""
    doc = ('"""Usage: append # dlaf: disable=lint-host-sync to a '
           'line."""\n')
    assert _rules(doc) == set()
    # a string-literal marker on an offending line must NOT suppress
    quoted = ('import os\n'
              'V = os.environ.get("DLAF_NOT_A_KNOB"), '
              '"# dlaf: disable=lint-unregistered-knob(quoted)"\n')
    assert "lint-unregistered-knob" in _rules(quoted)


def test_lint_syntax_error_is_a_finding():
    assert "lint-syntax-error" in _rules("def f(:\n")


import os as _os

#: Repo root derived from this file, so the acceptance pins hold from
#: any pytest invocation directory.
REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))


def test_lint_repo_is_clean():
    """The acceptance pin: the tree lints clean against the committed
    (empty) baseline."""
    assert lint.run(REPO) == []


def test_lint_key_is_line_number_free():
    """Baseline keys must survive unrelated edits: the same violation
    at a different line keeps its key."""
    a = lint.lint_source('import os\nV = os.environ.get("DLAF_NOPE")\n',
                         ALGO_PATH)
    b = lint.lint_source('import os\n\n\nV = os.environ.get("DLAF_NOPE")\n',
                         ALGO_PATH)
    assert [f.key for f in a] == [f.key for f in b]
    assert a[0].site != b[0].site   # the human report still moves


# ---------------------------------------------------------------------------
# CLI: exit codes + baseline diff + drill semantics
# ---------------------------------------------------------------------------

def test_cli_lint_only_clean_and_failing(tmp_path, capsys):
    # clean tree, empty baseline -> 0
    assert analysis_main(["--lint-only", "--root", REPO]) == 0
    assert "PASSED" in capsys.readouterr().out
    # a seeded-bad file under a fake root -> 1 with the rule named
    root = tmp_path / "repo"
    (root / "dlaf_tpu" / "algorithms").mkdir(parents=True)
    (root / "dlaf_tpu" / "algorithms" / "bad.py").write_text(
        'import os\nV = os.environ.get("DLAF_NOT_A_KNOB")\n')
    assert analysis_main(["--lint-only", "--root", str(root)]) == 1
    out = capsys.readouterr().out
    assert "lint-unregistered-knob" in out and "NEW" in out


def test_cli_write_baseline_then_clean(tmp_path, capsys, devices8):
    root = tmp_path / "repo"
    (root / "dlaf_tpu" / "algorithms").mkdir(parents=True)
    bad = root / "dlaf_tpu" / "algorithms" / "bad.py"
    bad.write_text('import os\nV = os.environ.get("DLAF_NOT_A_KNOB")\n')
    base = root / ".analysis_baseline.json"
    # --write-baseline demands a FULL run: a partial one would overwrite
    # the shared baseline with only the selected checker's findings,
    # silently erasing the other checker's grandfathered keys
    with pytest.raises(SystemExit) as e:
        analysis_main(["--lint-only", "--root", str(root),
                       "--write-baseline"])
    assert e.value.code == 2
    assert analysis_main(["--root", str(root), "--write-baseline"]) == 0
    assert load_baseline(str(base))
    # grandfathered -> gate passes; fixing the file -> stale key report
    assert analysis_main(["--lint-only", "--root", str(root)]) == 0
    bad.write_text("\n")
    assert analysis_main(["--lint-only", "--root", str(root)]) == 0
    assert "stale" in capsys.readouterr().out


def test_cli_drill_exit_codes(capsys, devices8):
    """A drill must exit 1 (proof the gate can fail) and name its rule;
    a drill that stops tripping must exit 3, not 1."""
    assert analysis_main(["--drill", "lint_violation"]) == 1
    assert "lint-unregistered-knob" in capsys.readouterr().out
    # sabotage: a drill that produces no findings is a broken checker
    import dlaf_tpu.analysis.drills as drills_mod

    orig = drills_mod.DRILLS["lint_violation"]
    drills_mod.DRILLS["lint_violation"] = (lambda: [], orig[1])
    try:
        assert analysis_main(["--drill", "lint_violation"]) == 3
    finally:
        drills_mod.DRILLS["lint_violation"] = orig
    with pytest.raises(KeyError, match="unknown drill"):
        drills.run("nonexistent")
    # a typo'd drill name via the CLI is a usage error (2), NEVER the
    # rc=1 "drill tripped" success contract CI greps for
    with pytest.raises(SystemExit) as e:
        analysis_main(["--drill", "nonexistent"])
    assert e.value.code == 2


def test_committed_baseline_is_valid():
    """The committed baseline EXISTS (load_baseline maps a missing file
    to empty for the gate, so existence must be pinned separately),
    parses, and carries only known-rule keys (currently empty: the tree
    is clean end to end)."""
    path = _os.path.join(REPO, ".analysis_baseline.json")
    assert _os.path.exists(path), "committed baseline file is missing"
    keys = load_baseline(path)
    assert isinstance(keys, list)
    for k in keys:
        assert k.split("|", 1)[0].startswith(("graph-", "lint-")), k


def test_finding_str_and_key():
    f = Finding("lint-host-sync", "a.py:3", "msg", key_detail="a.py|x")
    assert f.key == "lint-host-sync|a.py|x"
    assert str(f) == "a.py:3: [lint-host-sync] msg"
    assert Finding("r", "s", "m").key == "r|s"
