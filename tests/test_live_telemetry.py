"""Tests for ISSUE 13: live operational telemetry.

Covers: the shared numpy-linear quantile (pinned bit-identical to
``np.percentile`` — the computation bench.py's serve/overload arms now
share with the SLO window), the SlidingWindow epoch ring (deterministic
expiry under a fake clock, bounded memory with counted drops), the
``obs.observe_latency`` SLO path (windowed ``q``-labelled gauges in
deterministic order, the ``dlaf_slo_breach_total`` burn counter against
``DLAF_SLO_P99_MS``), exemplar trace IDs on histogram buckets and their
text-format grammar, request-scoped trace correlation end to end
through the serve queue (one trace_id on request / dispatch / span /
accuracy / retry-resilience records, ``span_id`` as the dispatch join
key, ``obs.aggregate --trace`` waterfall + ``--top-slow``), the live
``/metrics`` + ``/healthz`` exporter (monotone counters across two
mid-stream scrapes, ``Queue.stats()`` JSON round-trip incl. breaker
state names, lifecycle, 404/500 + healthz-failure flight trigger), the
flight recorder (bounded ring, atomic dump, per-reason cooldown, every
trigger site, the must-NOT-trip clean run, ``--require-flight``), the
``prometheus_snapshot_text`` no-op pin, and the new config knobs
(docs/observability.md live operations).
"""

import json
import math
import os
import re
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import dlaf_tpu.config as C
from dlaf_tpu import health, obs
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import circuit, inject
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.obs import exporter, flight, slo
from dlaf_tpu.obs.metrics import SlidingWindow, prometheus_text, quantile
from dlaf_tpu.serve import Queue, Request
from dlaf_tpu.serve import programs as serve_programs


@pytest.fixture(autouse=True)
def live_reset():
    """Every test leaves default config, no metrics, no exporter thread,
    no breakers, and an empty default program service behind."""
    yield
    for key in ("DLAF_METRICS_PATH", "DLAF_METRICS_PORT",
                "DLAF_FLIGHT_RECORDER", "DLAF_SLO_P99_MS",
                "DLAF_SLO_WINDOW_S", "DLAF_ACCURACY"):
        os.environ.pop(key, None)
    obs._reset_for_tests()
    circuit.reset()
    serve_programs._reset_for_tests()
    C.finalize()
    C.initialize()


def _metrics_on(tmp_path, **cfg):
    path = str(tmp_path / "live.jsonl")
    C.initialize(C.Configuration(metrics_path=path, log="off", **cfg))
    return path


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _hpd(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


def _serve_stream(n_reqs=4, batch=2, n=12, bucket=16, seed=0):
    """A warm queue + a stream of completed cholesky tickets."""
    q = Queue(buckets=(bucket,), batch=batch, deadline_s=1e9)
    q.warmup([Request(op="cholesky", a=_hpd(n, seed))])
    tickets = [q.submit(Request(op="cholesky", a=_hpd(n, seed + i)))
               for i in range(n_reqs)]
    q.flush()
    for t in tickets:
        t.result()
    return q, tickets


# ---------------------------------------------------------------------------
# quantile: the one shared estimator (satellite)
# ---------------------------------------------------------------------------

def test_quantile_matches_numpy_percentile():
    """Pinned BIT-identical to np.percentile's linear interpolation on
    the same sample — the contract that makes the SLO gauges, the
    aggregate tables, and bench.py's serve/overload p99 report the same
    number for the same latencies."""
    rng = np.random.default_rng(7)
    for size in (1, 2, 3, 7, 64, 100):
        vals = rng.exponential(size=size).tolist()
        for q in (0.0, 0.5, 0.95, 0.99, 1.0, 0.123):
            assert quantile(vals, q) == float(np.quantile(vals, q))
        # and through percentile's own q*100/100 round-trip at the
        # percentiles the legacy bench code used
        for pct in (50, 95, 99):
            assert quantile(vals, pct / 100) == \
                float(np.percentile(vals, pct))


def test_quantile_empty_and_bad_q():
    assert math.isnan(quantile([], 0.5))
    with pytest.raises(ValueError):
        quantile([1.0], 1.5)
    with pytest.raises(ValueError):
        quantile([1.0], -0.1)


def test_quantiles_one_sort_matches_quantile():
    """metrics.quantiles (one sort for the whole gauge refresh) is
    element-wise identical to independent quantile() calls."""
    from dlaf_tpu.obs.metrics import quantiles

    vals = [0.3, 0.1, 0.9, 0.2, 0.7]
    qs = [0.5, 0.95, 0.99]
    assert quantiles(vals, qs) == [quantile(vals, q) for q in qs]
    assert all(math.isnan(v) for v in quantiles([], qs))


def test_bench_p99_matches_legacy_computation():
    """The ISSUE-13 satellite pin: the quantile bench.py now routes its
    serve/overload p99 through equals the np.percentile(lat, 99) those
    arms hand-computed before, on a fixed sample."""
    lat = [0.01, 0.5, 0.03, 0.2, 0.11, 0.07, 0.004, 0.9, 0.3, 0.06]
    assert quantile(lat, 0.99) == float(np.percentile(lat, 99))


# ---------------------------------------------------------------------------
# SlidingWindow: the epoch ring
# ---------------------------------------------------------------------------

def test_sliding_window_deterministic_expiry():
    clock = FakeClock()
    w = SlidingWindow(window_s=6.0, epochs=3, clock=clock)
    w.observe(1.0)
    clock.t = 1.0
    w.observe(2.0)
    assert sorted(w.samples()) == [1.0, 2.0]
    # advance one epoch (2 s): both still inside the 6 s window
    clock.t = 2.5
    w.observe(3.0)
    assert sorted(w.samples()) == [1.0, 2.0, 3.0]
    # advance past the window: epoch-0 samples expire, epoch-1's live
    clock.t = 6.1
    assert sorted(w.samples()) == [3.0]
    clock.t = 100.0
    assert w.samples() == []
    assert math.isnan(w.quantile(0.5))     # empty window: NaN, never 0


def test_sliding_window_bounded_memory_drops_counted():
    clock = FakeClock()
    w = SlidingWindow(window_s=10.0, epochs=2, cap=4, clock=clock)
    for i in range(10):
        w.observe(float(i))
    assert w.count() == 4          # bounded at cap per epoch
    assert w.dropped == 6          # overflow visible, never silent
    with pytest.raises(ValueError):
        SlidingWindow(window_s=0.0)


def test_histogram_windowed_is_singleton_and_fed():
    reg = obs.Registry()           # a bare registry, no sink needed
    h = reg.histogram("lat", op="x")
    clock = FakeClock()
    w = h.windowed(window_s=60.0, clock=clock)
    assert h.windowed(window_s=999.0) is w     # one window per series
    h.observe(0.25)
    h.observe(0.5)
    assert sorted(w.samples()) == [0.25, 0.5]
    assert w.quantile(1.0) == 0.5


# ---------------------------------------------------------------------------
# observe_latency: the SLO path
# ---------------------------------------------------------------------------

def test_observe_latency_gauges_and_breach_counter(tmp_path):
    _metrics_on(tmp_path, slo_p99_ms=100.0)
    for v in (0.01, 0.02, 0.05, 0.2, 0.3):       # 2 of 5 over 100 ms
        obs.observe_latency("serve.cholesky", v, bucket="64")
    snap = {(m["name"], tuple(sorted(m.get("labels", {}).items()))): m
            for m in obs.registry().snapshot()}
    breach = snap[("dlaf_slo_breach_total",
                   (("op", "serve.cholesky"),))]
    assert breach["value"] == 2
    for q in ("0.5", "0.95", "0.99"):
        g = snap[("dlaf_serve_latency_window",
                  (("bucket", "64"), ("op", "serve.cholesky"), ("q", q)))]
        assert g["value"] == quantile([0.01, 0.02, 0.05, 0.2, 0.3],
                                      float(q))
    # the cumulative histogram moved too
    h = snap[("dlaf_serve_latency_seconds",
              (("bucket", "64"), ("op", "serve.cholesky")))]
    assert h["count"] == 5


def test_observe_latency_no_objective_no_breach(tmp_path):
    _metrics_on(tmp_path)                         # slo_p99_ms = 0 (off)
    obs.observe_latency("op", 1e9)
    names = {m["name"] for m in obs.registry().snapshot()}
    assert "dlaf_slo_breach_total" not in names
    assert "dlaf_serve_latency_window" in names


def test_observe_latency_noop_when_metrics_off():
    C.initialize()
    assert not obs.metrics_active()
    obs.observe_latency("op", 0.5)                # must not blow up
    assert obs.prometheus_snapshot_text() == ""


def test_window_gauge_q_labels_sorted_deterministically(tmp_path):
    """The q label values sort lexicographically ascending in the
    exposition, and two snapshots render identically (ISSUE 13 test
    obligation)."""
    _metrics_on(tmp_path)
    obs.observe_latency("a", 0.1, bucket="8")
    text = obs.prometheus_snapshot_text()
    qs = re.findall(r'dlaf_serve_latency_window\{[^}]*q="([^"]+)"\}', text)
    assert qs == ["0.5", "0.95", "0.99"]
    assert obs.prometheus_snapshot_text() == text


def test_with_policy_success_feeds_window(tmp_path):
    _metrics_on(tmp_path)
    from dlaf_tpu.health.policy import with_policy

    assert with_policy("mysite", lambda: 41) == 41
    snap = obs.registry().snapshot()
    gauges = [m for m in snap if m["name"] == "dlaf_serve_latency_window"
              and m["labels"].get("op") == "mysite"]
    assert len(gauges) == 3        # one per quantile


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------

def test_exemplar_captured_only_in_request_scope(tmp_path):
    _metrics_on(tmp_path)
    h = obs.histogram("lat")
    with obs.trace_context(trace_id="aabbccdd00112233"):
        h.observe(0.1)
    with obs.trace_context(trace_id=["t1", "t2"], span_id="s1"):
        h.observe(0.2)             # batch scope: never an exemplar
    h.observe(0.3)                 # no context: no exemplar
    snap = [m for m in obs.registry().snapshot() if m["name"] == "lat"][0]
    exes = {tid for tid, _ in snap["exemplars"].values()}
    assert exes == {"aabbccdd00112233"}


def test_exemplar_text_grammar(tmp_path):
    """Exemplar lines parse under the text-format grammar — base sample
    first, then ``# {trace_id="..."} value`` — and the default
    exposition (exemplars off) never emits them."""
    _metrics_on(tmp_path)
    with obs.trace_context(trace_id="feedface01234567"):
        obs.histogram("lat", op="x").observe(0.1)
    snap = obs.registry().snapshot()
    text = prometheus_text(snap, exemplars=True)
    ex_lines = [ln for ln in text.splitlines() if " # {" in ln]
    assert ex_lines
    gram = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*_bucket\{[^}]*le="[^"]+"[^}]*\} '
        r'\d+ # \{trace_id="[0-9a-f]{1,32}"\} [0-9.eE+-]+$')
    for ln in ex_lines:
        assert gram.match(ln), ln
        # stripping the exemplar clause restores the classic grammar
        base = ln.split(" # ")[0]
        assert re.match(r'^\S+\{[^}]*\} \d+$', base)
    # the classic exposition (artifacts, --prom) carries no exemplars
    assert " # {" not in prometheus_text(snap)
    assert " # {" not in obs.prometheus_snapshot_text()


# ---------------------------------------------------------------------------
# trace context + sink stamping
# ---------------------------------------------------------------------------

def test_trace_context_stamps_every_record_type(tmp_path):
    path = _metrics_on(tmp_path)
    with obs.trace_context(trace_id="deadbeef00000001", span_id="span01"):
        obs.emit_event("resilience", site="s", event="retry", attempt=0,
                       delay_s=0.0, attrs={})
        with obs.span("work"):
            pass
        obs.emit_event("log", level="info", logger="t", msg="m")
    obs.emit_event("resilience", site="s", event="retry", attempt=0,
                   delay_s=0.0, attrs={})
    obs.flush()
    records = obs.read_records(path)
    inside = [r for r in records if r.get("trace_id") is not None]
    assert {r["type"] for r in inside} >= {"resilience", "span", "log"}
    for r in inside:
        assert r["trace_id"] == "deadbeef00000001"
        assert r["span_id"] == "span01"
    outside = [r for r in records if r["type"] == "resilience"
               and "trace_id" not in r]
    assert outside                 # the post-context record is unstamped
    assert not obs.validate_records(records)


def test_trace_context_nesting_and_batch_scope():
    from dlaf_tpu.obs.context import current_trace, trace_matches

    assert current_trace() == (None, None)
    with obs.trace_context(trace_id=["a", "b"], span_id="s1"):
        assert current_trace() == (("a", "b"), "s1")
        with obs.trace_context(trace_id="a"):       # request scope wins
            assert current_trace() == ("a", "s1")   # span inherited
        assert current_trace() == (("a", "b"), "s1")
    assert current_trace() == (None, None)
    assert trace_matches({"trace_id": "a"}, "a")
    assert trace_matches({"trace_id": ["a", "b"]}, "b")
    assert not trace_matches({"trace_id": ["a", "b"]}, "c")
    assert not trace_matches({}, "a")


def test_serve_trace_join_end_to_end(tmp_path):
    """THE acceptance pin: one trace_id appears on the request's serve
    record, the dispatch record (by membership), the span records, and
    its accuracy record; span_id joins request to dispatch."""
    os.environ["DLAF_ACCURACY"] = "1"
    path = _metrics_on(tmp_path, accuracy="1")
    q, tickets = _serve_stream(n_reqs=4, batch=2)
    obs.flush()
    records = obs.read_records(path)
    assert not obs.validate_records(records, require_serve=True)
    tid = tickets[0].trace_id
    from dlaf_tpu.obs.context import trace_matches

    mine = [r for r in records if trace_matches(r, tid)]
    types = {r["type"] for r in mine}
    assert {"serve", "span", "accuracy"} <= types
    events = {r.get("event") for r in mine if r["type"] == "serve"}
    assert events == {"request", "dispatch"}
    req = [r for r in mine if r["type"] == "serve"
           and r.get("event") == "request"][0]
    disp = [r for r in mine if r["type"] == "serve"
            and r.get("event") == "dispatch"][0]
    # request-scoped records carry the single ID; the dispatch carries
    # the member list; both share the dispatch's span_id
    assert req["trace_id"] == tid
    assert isinstance(disp["trace_id"], list) and tid in disp["trace_id"]
    assert req["span_id"] == disp["span_id"]
    # the dispatch's stages object is the waterfall's raw material
    assert set(disp["stages"]) == {"compose_s", "program_s", "fetch_s",
                                   "unpad_s"}
    assert all(v >= 0 for v in disp["stages"].values())
    # every ticket got a distinct trace ID
    assert len({t.trace_id for t in tickets}) == len(tickets)


def test_retry_records_carry_batch_trace(tmp_path):
    path = _metrics_on(tmp_path, serve_retry_attempts=2)
    q = Queue(buckets=(16,), batch=2, deadline_s=1e9,
              retry_attempts=2, retry_backoff_s=0.0)
    with inject.fail_dispatch(count=1):
        tickets = [q.submit(Request(op="cholesky", a=_hpd(12, i)))
                   for i in range(2)]
    for t in tickets:
        t.result()                 # retry recovered the dispatch
    obs.flush()
    records = obs.read_records(path)
    retries = [r for r in records if r.get("type") == "resilience"
               and r.get("event") == "retry"]
    assert retries
    member_ids = sorted(t.trace_id for t in tickets)
    for r in retries:
        assert sorted(r["trace_id"]) == member_ids      # batch scope
        assert isinstance(r["span_id"], str)


def test_aggregate_trace_and_top_slow_cli(tmp_path):
    os.environ["DLAF_ACCURACY"] = "1"
    path = _metrics_on(tmp_path, accuracy="1")
    q, tickets = _serve_stream(n_reqs=4, batch=2)
    obs.flush()
    obs._reset_for_tests()
    tid = tickets[0].trace_id
    r = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.aggregate", path,
         "--trace", tid], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert f"trace {tid}" in r.stdout
    for stage in ("queue wait", "compose", "program", "fetch", "unpad"):
        assert stage in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.aggregate", path,
         "--top-slow", "3"], capture_output=True, text=True)
    assert r2.returncode == 0
    assert "slowest requests" in r2.stdout
    assert len(re.findall(r"trace [0-9a-f]{16}", r2.stdout)) == 3
    # unknown trace: loud, exit 1; bad N: usage, exit 2
    assert subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.aggregate", path,
         "--trace", "nosuchtrace"], capture_output=True).returncode == 1
    assert subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.aggregate", path,
         "--top-slow", "0"], capture_output=True).returncode == 2


def test_profile_summary_requests_section(tmp_path):
    os.environ["DLAF_ACCURACY"] = "1"
    path = _metrics_on(tmp_path, accuracy="1")
    q, tickets = _serve_stream(n_reqs=4, batch=2)
    obs.flush()
    obs._reset_for_tests()
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "profile_summary.py"), path],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "== requests" in r.stdout
    assert tickets[0].trace_id in r.stdout or "trace " in r.stdout
    assert re.search(r"cholesky\s+\(4 reqs\): p50 .* p95 .* p99", r.stdout)


# ---------------------------------------------------------------------------
# live exporter
# ---------------------------------------------------------------------------

#: The Accept value Prometheus sends when exemplar scraping is on.
OPENMETRICS_ACCEPT = "application/openmetrics-text;version=1.0.0," \
                     "text/plain;version=0.0.4"


def _get(port, route, accept=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{route}")
    if accept:
        req.add_header("Accept", accept)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.read().decode()


def _counters(text):
    out = {}
    for ln in text.splitlines():
        if ln.startswith("#") or " " not in ln:
            continue
        name, val = ln.rsplit(" ", 1)
        if "_total{" in name or name.endswith("_total") \
                or "_count{" in name or name.endswith("_count"):
            out[name] = float(val)
    return out


def test_metrics_scrape_monotone_across_two_scrapes(tmp_path):
    """Scraping a LIVE serving process mid-stream: both scrapes parse,
    and every counter is monotone non-decreasing between them."""
    _metrics_on(tmp_path)
    port = exporter.start(0)
    q, _ = _serve_stream(n_reqs=2, batch=2, seed=0)
    _, scrape1 = _get(port, "/metrics")
    for i in range(2):
        t = q.submit(Request(op="cholesky", a=_hpd(12, 50 + i)))
    q.flush()
    _, scrape2 = _get(port, "/metrics")
    c1, c2 = _counters(scrape1), _counters(scrape2)
    assert c1 and set(c1) <= set(c2)
    for k, v in c1.items():
        assert c2[k] >= v, k
    assert c2['dlaf_serve_requests_total{op="cholesky"}'] == 4.0
    # content negotiation (real Prometheus behavior): the classic 0.0.4
    # rendering has NO exemplar clause — its grammar cannot express one
    # and a classic scraper would fail the whole scrape on it — while
    # an OpenMetrics Accept gets exemplars + the # EOF terminator
    assert " # {" not in scrape2
    _, om = _get(port, "/metrics", accept=OPENMETRICS_ACCEPT)
    assert " # {trace_id=" in om
    assert om.endswith("# EOF\n")
    assert 'dlaf_serve_requests_total{op="cholesky"} 4.0' in om


def test_healthz_roundtrips_queue_stats(tmp_path):
    _metrics_on(tmp_path)
    port = exporter.start(0)
    q, _ = _serve_stream(n_reqs=2, batch=2)
    status, body = _get(port, "/healthz")
    payload = json.loads(body)
    assert status == 200 and payload["status"] == "ok"
    # the queue's stats() round-trips faithfully through the JSON,
    # including the breaker state NAMES
    stats = json.loads(json.dumps(q.stats()))
    assert payload["queues"] == [stats]
    site, bucket = next(iter(stats["buckets"].items()))
    assert bucket["breaker"] == "closed"
    assert payload["breakers"][site] == "closed"
    assert payload["rank"] in (None, 0)
    assert payload["pid"] == os.getpid()
    assert payload["uptime_s"] >= 0


def test_exporter_lifecycle_and_404():
    C.initialize()
    assert exporter.port() == 0            # knob unset: no socket
    port = exporter.start(0)
    assert exporter.port() == port > 0
    status = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).status
    assert status == 200
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/nope")
    assert ei.value.code == 404
    exporter.stop()
    assert exporter.port() == 0
    with pytest.raises(Exception):
        _get(port, "/metrics")


def test_exporter_via_config_knob(tmp_path):
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    free_port = s.getsockname()[1]
    s.close()
    _metrics_on(tmp_path, metrics_port=free_port)
    assert exporter.port() == free_port
    status, _ = _get(free_port, "/metrics")
    assert status == 200
    # reconfiguring the knob to 0 stops the server
    C.initialize(C.Configuration(log="off"))
    assert exporter.port() == 0


def test_metrics_port_arms_registry_without_sink():
    """A scrape-only deployment (port set, no metrics path) still
    records: metrics_active() is on and the scrape shows counters."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    free_port = s.getsockname()[1]
    s.close()
    C.initialize(C.Configuration(metrics_port=free_port, log="off"))
    assert obs.metrics_active()
    obs.counter("scrape_only_total").inc()
    _, text = _get(free_port, "/metrics")
    assert "scrape_only_total 1" in text


def test_healthz_failure_trips_flight(tmp_path):
    path = _metrics_on(tmp_path, flight_recorder=32)
    port = exporter.start(0)
    q, _ = _serve_stream(n_reqs=2, batch=2)
    q.stats = lambda: 1 / 0                # break the payload build
    flight_path = path + ".flight.jsonl"
    assert not os.path.exists(flight_path)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/healthz")
    assert ei.value.code == 500
    assert os.path.exists(flight_path)
    header = obs.read_records(flight_path)[0]
    assert header["type"] == "flight_trigger"
    assert header["reason"] == "healthz_failure"


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_bounded_and_dump(tmp_path):
    clock = FakeClock()
    dump = str(tmp_path / "dump.flight.jsonl")
    rec = obs.FlightRecorder(capacity=5, path=dump, clock=clock)
    for i in range(12):
        rec.capture({"v": 1, "type": "log", "ts": float(i),
                     "level": "info", "logger": "t", "msg": str(i),
                     "i": i})
    out = rec.trigger("overload_shed", depth=9)
    assert out == dump
    records = obs.read_records(dump)
    header, body = records[0], records[1:]
    assert header["type"] == "flight_trigger"
    assert header["reason"] == "overload_shed"
    assert header["records"] == 5 and header["dump_seq"] == 1
    assert header["attrs"] == {"depth": 9}
    assert [r["i"] for r in body] == [7, 8, 9, 10, 11]   # the LAST 5
    # the dump artifact itself passes --require-flight
    assert not obs.validate_records(records, require_flight=True)


def test_flight_cooldown_per_reason(tmp_path):
    clock = FakeClock()
    dump = str(tmp_path / "dump.flight.jsonl")
    rec = obs.FlightRecorder(capacity=4, path=dump, cooldown_s=60.0,
                             clock=clock)
    rec.capture({"v": 1, "type": "log", "ts": 0.0, "level": "info",
                 "logger": "t", "msg": "m"})
    assert rec.trigger("overload_shed") == dump
    clock.t = 10.0
    assert rec.trigger("overload_shed") is None        # cooled down
    assert rec.trigger("breaker_open") == dump         # new reason lands
    assert rec.dump_seq == 2
    clock.t = 70.1
    assert rec.trigger("overload_shed") == dump        # cooldown elapsed
    assert obs.read_records(dump)[0]["dump_seq"] == 3


def test_flight_trigger_unarmed_is_noop(tmp_path):
    path = _metrics_on(tmp_path)                       # no knob
    assert flight.trigger("breaker_open") is None
    assert not os.path.exists(path + ".flight.jsonl")


def test_flight_requires_sink_warns(tmp_path):
    C.initialize(C.Configuration(flight_recorder=16))
    from dlaf_tpu.obs._state import STATE

    assert STATE.flight is None                        # unarmed, warned


def test_clean_serve_run_writes_no_flight_artifact(tmp_path):
    """The must-NOT-trip leg: an armed recorder on a clean stream dumps
    nothing — the artifact's existence IS the incident signal."""
    path = _metrics_on(tmp_path, flight_recorder=64)
    _serve_stream(n_reqs=4, batch=2)
    obs.flush()
    assert not os.path.exists(path + ".flight.jsonl")


def test_breaker_open_trips_flight_with_context(tmp_path):
    """Sustained dispatch failure -> breaker opens -> the dump exists,
    passes --require-flight, and holds the PRE-trigger serve/resilience
    records (the CI drill's contract)."""
    path = _metrics_on(tmp_path, flight_recorder=64, circuit_threshold=2)
    q = Queue(buckets=(16,), batch=1, deadline_s=1e9,
              retry_attempts=1, retry_backoff_s=0.0)
    q.submit(Request(op="cholesky", a=_hpd(12))).result()   # warm + clean
    flight_path = path + ".flight.jsonl"
    assert not os.path.exists(flight_path)
    with inject.fail_dispatch(count=100):
        for i in range(3):
            try:
                q.submit(Request(op="cholesky", a=_hpd(12, i)))
            except Exception:
                pass
    assert os.path.exists(flight_path)
    records = obs.read_records(flight_path)
    assert not obs.validate_records(records, require_flight=True)
    header = records[0]
    assert header["reason"] == "breaker_open"
    body_types = {r["type"] for r in records[1:]}
    assert "serve" in body_types          # the pre-trigger dispatches
    opens = [r for r in records[1:] if r.get("type") == "resilience"
             and r.get("event") == "circuit_open"]
    assert opens                          # the opening itself is in-ring


def test_overload_shed_trips_flight_once_per_burst(tmp_path):
    path = _metrics_on(tmp_path, flight_recorder=64)
    clock = FakeClock()
    q = Queue(buckets=(16,), batch=64, deadline_s=1e9, max_depth=2,
              shed=True, clock=clock)
    q.submit(Request(op="cholesky", a=_hpd(12, 0)))
    q.submit(Request(op="cholesky", a=_hpd(12, 1)))
    flight_path = path + ".flight.jsonl"
    n_shed = 0
    for i in range(5):                    # a shed burst
        with pytest.raises(health.OverloadError):
            q.submit(Request(op="cholesky", a=_hpd(12, 2 + i)))
        n_shed += 1
    assert os.path.exists(flight_path)
    header = obs.read_records(flight_path)[0]
    assert header["reason"] == "overload_shed"
    # per-reason cooldown: the burst dumped ONCE (fake clock never moved)
    assert header["dump_seq"] == 1
    shed_records = [r for r in obs.read_records(flight_path)[1:]
                    if r.get("event") == "shed"]
    assert shed_records               # the first shed is in its own dump


def test_factorization_exhausted_trips_flight(tmp_path):
    path = _metrics_on(tmp_path, flight_recorder=32)
    a = _hpd(8)
    a[2, 1] = a[1, 2] = np.nan            # unrecoverable by shifting
    m = Matrix.from_global(a, TileElementSize(4, 4))
    with pytest.raises(health.FactorizationError):
        health.robust_cholesky("L", m, max_attempts=2)
    flight_path = path + ".flight.jsonl"
    assert os.path.exists(flight_path)
    header = obs.read_records(flight_path)[0]
    assert header["reason"] == "factorization_exhausted"
    assert header["attrs"]["attempts"] == 2


def test_accuracy_breach_trips_flight(tmp_path):
    path = _metrics_on(tmp_path, flight_recorder=32, accuracy="1")
    from dlaf_tpu.obs import accuracy as acc

    # bound_ratio > 1: value far above c * n * eps
    acc.emit("test", "cholesky_residual", 1.0, n=8, nb=4,
             dtype=np.float64, c=60.0)
    flight_path = path + ".flight.jsonl"
    assert os.path.exists(flight_path)
    records = obs.read_records(flight_path)
    assert records[0]["reason"] == "accuracy_breach"
    # the breaching accuracy record itself is inside the dump
    assert any(r.get("type") == "accuracy" for r in records[1:])


# ---------------------------------------------------------------------------
# schema / validator
# ---------------------------------------------------------------------------

def _base(rtype, **kw):
    return {"v": 1, "type": rtype, "ts": 0.0, **kw}


def test_trace_stamp_schema_validation():
    ok = _base("log", level="info", logger="x", msg="m")
    assert not obs.validate_records([dict(ok, trace_id="abc")])
    assert not obs.validate_records([dict(ok, trace_id=["a", "b"],
                                          span_id="s")])
    for bad in ({"trace_id": ""}, {"trace_id": []}, {"trace_id": ["a", ""]},
                {"trace_id": 7}, {"span_id": ""}, {"span_id": 3}):
        errs = obs.validate_records([dict(ok, **bad)])
        assert errs, bad


def test_dispatch_stages_schema_validation():
    disp = _base("serve", event="dispatch", op="cholesky", bucket_n=16,
                 nrhs=0, dtype="float64", lanes=2, batch=2, cache="hit",
                 dispatch_s=0.1)
    assert not obs.validate_records([dict(disp)])
    good = dict(disp, stages={"compose_s": 0.0, "program_s": 0.09,
                              "fetch_s": 0.01, "unpad_s": 0.0})
    assert not obs.validate_records([good])
    assert obs.validate_records([dict(disp, stages="nope")])
    assert obs.validate_records(
        [dict(disp, stages={"compose_s": -1.0})])
    assert obs.validate_records(
        [dict(disp, stages={"compose_s": float("nan")})])


def test_require_flight_obligations():
    trig = _base("flight_trigger", reason="breaker_open", dump_seq=1,
                 records=1, attrs={})
    ctx = _base("log", level="info", logger="x", msg="m")
    assert not obs.validate_records([trig, ctx], require_flight=True)
    # no trigger record: fails
    assert obs.validate_records([ctx], require_flight=True)
    # trigger but no captured context: fails (the ring was empty)
    assert obs.validate_records([trig], require_flight=True)
    # unknown reason: schema error
    assert obs.validate_records(
        [dict(trig, reason="bad_reason"), ctx])
    # malformed dump_seq
    assert obs.validate_records([dict(trig, dump_seq="x"), ctx])
    # the flag is wired through the CLI (unreadable path = INVALID, 1)
    r = subprocess.run([sys.executable, "-m", "dlaf_tpu.obs.validate",
                        "--require-flight", "/nonexistent.jsonl"],
                       capture_output=True)
    assert r.returncode == 1


def test_prometheus_snapshot_text_noop_when_inactive(tmp_path):
    """The documented zero-work no-op pin (ISSUE 13 satellite): a
    registry may exist from an annotate-only configuration, but with
    metrics_active() false the exposition is ''."""
    C.initialize(C.Configuration(trace_dir=str(tmp_path / "tr"),
                                 log="off"))
    from dlaf_tpu.obs._state import STATE

    assert STATE.registry is not None      # annotate mode has a registry
    assert not obs.metrics_active()
    assert obs.prometheus_snapshot_text() == ""


def test_config_knob_validation():
    for bad in (dict(metrics_port=-1), dict(metrics_port=70000),
                dict(slo_p99_ms=-1.0), dict(slo_window_s=0.0),
                dict(flight_recorder=-2)):
        with pytest.raises(ValueError):
            C.initialize(C.Configuration(**bad))
        C.finalize()
    # env layer round-trip
    os.environ["DLAF_SLO_P99_MS"] = "250"
    os.environ["DLAF_FLIGHT_RECORDER"] = "128"
    C.finalize()
    cfg = C.initialize()
    assert cfg.slo_p99_ms == 250.0
    assert cfg.flight_recorder == 128


# ---------------------------------------------------------------------------
# ISSUE 14 satellites: healthz SLO windows, slo_breach_burst, degraded
# aggregate --trace inputs
# ---------------------------------------------------------------------------

def test_healthz_slo_windows_roundtrip(tmp_path):
    """/healthz carries the rolling SLO window quantiles per (op,
    bucket) — the SAME values the dlaf_serve_latency_window gauges
    scrape (round-trip pinned like the queue stats), plus the breach
    burn counters — so a scrape-only deployment sees SLO state."""
    _metrics_on(tmp_path, slo_p99_ms=100.0)
    port = exporter.start(0)
    lat = [0.01, 0.02, 0.05, 0.2, 0.3]
    for v in lat:
        obs.observe_latency("serve.cholesky", v, bucket="64")
    obs.observe_latency("serve.eigh", 0.5, bucket="32")
    _, body = _get(port, "/healthz")
    payload = json.loads(body)
    rows = {(w["op"], w["bucket"]): w for w in payload["slo"]["windows"]}
    assert set(rows) == {("serve.cholesky", "64"), ("serve.eigh", "32")}
    gauges = {(m["labels"]["op"], m["labels"]["bucket"],
               m["labels"]["q"]): m["value"]
              for m in obs.registry().snapshot()
              if m["name"] == "dlaf_serve_latency_window"}
    for (op, bucket), row in rows.items():
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            assert row[key] == gauges[(op, bucket, q)]
    assert rows[("serve.cholesky", "64")]["p99"] == \
        quantile(lat, 0.99)
    assert payload["slo"]["breaches"] == {"serve.cholesky": 2.0,
                                          "serve.eigh": 1.0}


def test_healthz_slo_empty_without_observations(tmp_path):
    _metrics_on(tmp_path)
    port = exporter.start(0)
    _, body = _get(port, "/healthz")
    payload = json.loads(body)
    assert payload["slo"] == {"windows": [], "breaches": {}}


def test_slo_breach_burst_trips_flight(tmp_path):
    """The must-trip drill: DLAF_SLO_BURST breaches inside one SLO
    window dump the ring once (reason slo_breach_burst, a known
    FLIGHT_REASONS member), and the artifact passes --require-flight."""
    clock = FakeClock(1000.0)
    slo.set_clock(clock)
    path = _metrics_on(tmp_path, slo_p99_ms=10.0, slo_window_s=60.0,
                       slo_burst=3, flight_recorder=32)
    # pre-trigger context for the ring (the validator rejects an
    # incident dump that captured nothing)
    with obs.span("pre_incident_work", n=1):
        pass
    flight_path = path + ".flight.jsonl"
    for i in range(2):
        obs.observe_latency("cholesky", 0.5)
        clock.t += 1.0
    assert not os.path.exists(flight_path), "tripped below the burst"
    obs.observe_latency("cholesky", 0.5)
    assert os.path.exists(flight_path), "burst did not trip"
    records = obs.read_records(flight_path)
    header = records[0]
    assert header["type"] == "flight_trigger"
    assert header["reason"] == "slo_breach_burst"
    assert header["attrs"]["op"] == "cholesky"
    assert header["attrs"]["breaches"] == 3
    from dlaf_tpu.obs.sinks import validate_records

    assert not validate_records(records, require_flight=True)
    # cooldown: the storm continues but the same reason does not re-dump
    seq = header["dump_seq"]
    for _ in range(5):
        obs.observe_latency("cholesky", 0.5)
    assert obs.read_records(flight_path)[0]["dump_seq"] == seq


def test_slo_breach_burst_window_prunes(tmp_path):
    """Breaches spread wider than one SLO window must NOT trip: the
    stamp pruning keeps only in-window breaches."""
    clock = FakeClock(1000.0)
    slo.set_clock(clock)
    path = _metrics_on(tmp_path, slo_p99_ms=10.0, slo_window_s=5.0,
                       slo_burst=3, flight_recorder=32)
    flight_path = path + ".flight.jsonl"
    for _ in range(6):                      # 6 breaches, 6 s apart
        obs.observe_latency("cholesky", 0.5)
        clock.t += 6.0
    assert not os.path.exists(flight_path)
    # burst=0 disables the trigger entirely
    obs._reset_for_tests()
    slo.set_clock(clock)
    path = _metrics_on(tmp_path / "b0", slo_p99_ms=10.0, slo_burst=0,
                       flight_recorder=32)
    for _ in range(10):
        obs.observe_latency("cholesky", 0.5)
    assert not os.path.exists(str(tmp_path / "b0" / "live.jsonl")
                              + ".flight.jsonl")


def _degraded_trace_artifact(tmp_path):
    """Hand-written records for the aggregate --trace degraded paths:
    a request whose dispatch record is MISSING (no stages to join), and
    a batch-scope-only trace (list trace_id, no request record)."""
    records = [
        {"v": 1, "type": "serve", "ts": 10.0, "event": "request",
         "op": "cholesky", "n": 24, "bucket_n": 32, "dtype": "float64",
         "queue_s": 0.01, "total_s": 0.05, "attrs": {},
         "trace_id": "aaaa000011112222", "span_id": "bbbb000011112222",
         "rank": 0},
        {"v": 1, "type": "resilience", "ts": 11.0, "site": "serve.x",
         "event": "retry", "attempt": 1, "delay_s": 0.0, "attrs": {},
         "trace_id": ["cccc000011112222", "dddd000011112222"],
         "span_id": "eeee000011112222", "rank": 0},
    ]
    path = str(tmp_path / "degraded.jsonl")
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return path


def test_aggregate_trace_dispatch_missing_stages(tmp_path):
    """A request record with no joinable dispatch still renders its
    waterfall — with the explicit no-stages note, not a crash."""
    path = _degraded_trace_artifact(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.aggregate", path,
         "--trace", "aaaa000011112222"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "queue wait" in r.stdout
    assert "no dispatch stage record joined" in r.stdout


def test_aggregate_trace_batch_scope_only(tmp_path):
    """A trace ID that appears only in batch-scope lists (no request
    record) renders the record inventory without a waterfall."""
    path = _degraded_trace_artifact(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.aggregate", path,
         "--trace", "cccc000011112222"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "[batch scope, rank 0]" in r.stdout
    assert "queue wait" not in r.stdout     # no request => no waterfall
    assert "resilience" in r.stdout


def test_aggregate_trace_unknown_id_and_usage_exit_codes(tmp_path):
    """The exit-code contract: an unknown trace ID is loud exit 1; a
    --trace flag with no value is a usage error, exit 2."""
    path = _degraded_trace_artifact(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.aggregate", path,
         "--trace", "ffff000011112222"], capture_output=True, text=True)
    assert r.returncode == 1
    assert "appears in no record" in r.stderr
    r2 = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.aggregate", path,
         "--trace"], capture_output=True, text=True)
    assert r2.returncode == 2
