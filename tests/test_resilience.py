"""Tests for ISSUE 12: the production resilience layer.

Covers: the declarative RetryPolicy engine (deterministic seeded
backoff, error classification, exhaustion, per-attempt deadlines via the
clock-aware ``inject.hang`` stall), the circuit-breaker state machine
(open/half-open/close, gauge + resilience records, registry reset-safety)
and its ``run_with_fallback`` integration, the serving queue's overload
protection (``DLAF_SERVE_MAX_DEPTH``/``DLAF_SERVE_SHED`` shed vs
backpressure, per-request deadlines cancelling at dispatch composition,
retried breaker-guarded dispatch, ``Queue.stats()``), a 16-thread soak
against a flapping ``fail_dispatch`` fault (no deadlock, no
double-dispatch, no stranded tickets), the stage-checkpoint substrate
(atomic manifests, fingerprint/version rejection, matrix payload
round trips) and the eigensolver kill->resume pin (bitwise vs the
uninterrupted run at EVERY stage boundary), and the
``--require-resilience`` validator obligation (docs/robustness.md).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import dlaf_tpu.config as C
from dlaf_tpu import health, obs
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import circuit, inject, policy
from dlaf_tpu.health.errors import (CircuitOpenError, DeadlineExceededError,
                                    OverloadError, PreemptionError,
                                    ResumeError)
from dlaf_tpu.matrix import checkpoint as ckpt
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.serve import ProgramService, Queue, Request
from dlaf_tpu.serve import programs as serve_programs


@pytest.fixture(autouse=True)
def resilience_reset():
    """Every test leaves default config, no metrics, no breakers, and an
    empty default program service behind."""
    yield
    for key in ("DLAF_METRICS_PATH", "DLAF_SERVE_MAX_DEPTH",
                "DLAF_SERVE_SHED", "DLAF_RESUME_DIR",
                "DLAF_CIRCUIT_THRESHOLD", "DLAF_CIRCUIT_COOLDOWN_S"):
        os.environ.pop(key, None)
    obs._reset_for_tests()
    circuit.reset()
    serve_programs._reset_for_tests()
    C.finalize()
    C.initialize()


def _metrics_on(tmp_path, **cfg):
    path = str(tmp_path / "resilience.jsonl")
    C.initialize(C.Configuration(metrics_path=path, log="off", **cfg))
    return path


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _hpd(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


def _records(path):
    obs.flush()
    return obs.read_records(path)


# ---------------------------------------------------------------------------
# RetryPolicy / with_policy
# ---------------------------------------------------------------------------

def test_policy_backoff_deterministic_seeded_jitter():
    p = policy.RetryPolicy(max_attempts=5, backoff_base_s=1.0,
                           backoff_growth=2.0, jitter=0.2, seed=7)
    delays = [p.delay_s(i) for i in range(4)]
    assert delays == [p.delay_s(i) for i in range(4)]   # replayable
    # jitter stays within +-20% of the exponential envelope
    for i, d in enumerate(delays):
        assert 0.8 * 2.0**i <= d <= 1.2 * 2.0**i
    # different seed => different jitter draw
    q = policy.RetryPolicy(max_attempts=5, backoff_base_s=1.0,
                           backoff_growth=2.0, jitter=0.2, seed=8)
    assert q.delay_s(0) != p.delay_s(0)
    # cap applies
    capped = policy.RetryPolicy(backoff_base_s=10.0, backoff_max_s=15.0,
                                jitter=0.0)
    assert capped.delay_s(5) == 15.0


def test_policy_validation():
    with pytest.raises(ValueError):
        policy.RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        policy.RetryPolicy(jitter=1.5)
    with pytest.raises(ValueError):
        policy.RetryPolicy(backoff_growth=0.5)
    with pytest.raises(ValueError):
        policy.RetryPolicy(attempt_deadline_s=0.0)


def test_with_policy_retries_then_succeeds(tmp_path):
    path = _metrics_on(tmp_path)
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    p = policy.RetryPolicy(max_attempts=4, backoff_base_s=0.5, jitter=0.0)
    out = policy.with_policy("t.flaky", flaky, policy=p, sleep=slept.append)
    assert out == "ok" and len(calls) == 3
    assert slept == [0.5, 1.0]           # exponential, no jitter
    assert obs.registry().counter("dlaf_retry_total", site="t.flaky"
                                  ).snapshot()["value"] == 2
    recs = [r for r in _records(path) if r.get("type") == "resilience"]
    assert [r["event"] for r in recs] == ["retry", "retry"]
    assert [r["attempt"] for r in recs] == [0, 1]
    assert all(r["site"] == "t.flaky" and r["delay_s"] > 0 for r in recs)


def test_with_policy_classification_and_exhaustion(tmp_path):
    path = _metrics_on(tmp_path)
    # caller bugs are never retried
    calls = []

    def bug():
        calls.append(1)
        raise ValueError("caller bug")

    with pytest.raises(ValueError):
        policy.with_policy("t.bug", bug)
    assert len(calls) == 1
    # HealthError decisions are never retried either
    with pytest.raises(OverloadError):
        policy.with_policy("t.bug2", lambda: (_ for _ in ()).throw(
            OverloadError(1, 1)))
    # exhaustion re-raises the LAST error and leaves a give_up record
    with pytest.raises(TimeoutError):
        policy.with_policy(
            "t.dead", lambda: (_ for _ in ()).throw(TimeoutError("down")),
            policy=policy.RetryPolicy(max_attempts=2, backoff_base_s=0.0))
    recs = [r for r in _records(path) if r.get("type") == "resilience"
            and r["site"] == "t.dead"]
    assert [r["event"] for r in recs] == ["retry", "give_up"]


def test_with_policy_deadline_via_clock_aware_hang(tmp_path):
    """inject.hang charges its stall against the attempt deadline WITHOUT
    real wall time: the late success raises DeadlineExceededError and
    counts dlaf_deadline_exceeded_total{site}."""
    path = _metrics_on(tmp_path)
    clock = FakeClock()
    p = policy.RetryPolicy(max_attempts=1, attempt_deadline_s=0.5)
    t0 = time.monotonic()
    with inject.hang("t.hang", 30.0):
        with pytest.raises(DeadlineExceededError) as ei:
            policy.with_policy("t.hang", lambda: "late", policy=p,
                               clock=clock)
    assert time.monotonic() - t0 < 5.0       # no real 30 s burned
    assert ei.value.site == "t.hang" and ei.value.elapsed_s == 30.0
    assert ei.value.deadline_s == 0.5
    assert obs.registry().counter("dlaf_deadline_exceeded_total",
                                  site="t.hang").snapshot()["value"] == 1
    recs = [r for r in _records(path) if r.get("type") == "resilience"]
    assert [r["event"] for r in recs] == ["deadline"]
    # unarmed: the same call passes (hang is reset-safe)
    assert policy.with_policy("t.hang", lambda: "fine", policy=p,
                              clock=clock) == "fine"


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_state_machine(tmp_path):
    path = _metrics_on(tmp_path)
    clock = FakeClock()
    br = circuit.CircuitBreaker("t.br", threshold=3, cooldown_s=10.0,
                                clock=clock)
    for _ in range(2):
        br.allow()
        br.record_failure()
    assert br.state() == "closed"            # under threshold
    br.allow()
    br.record_failure()
    assert br.state() == "open"              # threshold-th consecutive
    with pytest.raises(CircuitOpenError) as ei:
        br.allow()
    assert 0 < ei.value.retry_in_s <= 10.0
    clock.t = 11.0
    br.allow()                               # the half-open probe
    assert br.state() == "half_open"
    with pytest.raises(CircuitOpenError):
        br.allow()                           # one probe at a time
    br.record_failure()                      # probe failed: re-open
    assert br.state() == "open"
    clock.t = 30.0
    br.allow()
    br.record_success()                      # probe succeeded: close
    assert br.state() == "closed"
    br.allow()                               # closed admits freely
    # gauge followed every transition; records carry the trail
    assert obs.registry().gauge("dlaf_circuit_state",
                                site="t.br").snapshot()["value"] == 0
    events = [r["event"] for r in _records(path)
              if r.get("type") == "resilience"]
    assert events == ["circuit_open", "circuit_half_open", "circuit_open",
                      "circuit_half_open", "circuit_close"]


def test_breaker_success_resets_consecutive_count():
    br = circuit.CircuitBreaker("t.br2", threshold=2, cooldown_s=10.0)
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state() == "closed"            # never 2 CONSECUTIVE


def test_breaker_registry_and_reset():
    a = circuit.breaker("t.reg.a", threshold=1, cooldown_s=99.0)
    assert circuit.breaker("t.reg.a") is a   # get-or-create
    a.record_failure()
    assert circuit.peek("t.reg.a") == "open"
    assert circuit.peek("t.reg.never") is None
    dropped = circuit.reset("t.reg.")
    assert dropped == 1 and circuit.peek("t.reg.a") is None


def test_run_with_fallback_breaker_skips_failing_primary(tmp_path):
    """After threshold consecutive primary failures the breaker opens and
    the primary is SKIPPED (fallback reason circuit_open) until the
    cooldown probe; a succeeding probe closes it again."""
    _metrics_on(tmp_path, circuit_threshold=2, circuit_cooldown_s=3600.0)
    calls = []

    def primary():
        calls.append(1)
        raise RuntimeError("native down")

    for _ in range(2):
        assert health.run_with_fallback("t_site", primary,
                                        lambda: "fb") == "fb"
    assert circuit.peek("fallback.t_site") == "open"
    assert health.run_with_fallback("t_site", primary, lambda: "fb") == "fb"
    assert len(calls) == 2                   # third call skipped primary
    c = obs.registry().counter(health.FALLBACK_COUNTER, site="t_site",
                               reason="circuit_open").snapshot()
    assert c["value"] == 1
    # cooldown elapsed (fake it by resetting): the primary runs again
    circuit.reset("fallback.")
    assert health.run_with_fallback("t_site", lambda: "native",
                                    lambda: "fb") == "native"
    assert circuit.peek("fallback.t_site") == "closed"


# ---------------------------------------------------------------------------
# Queue: overload protection + deadlines + retried breaker-guarded dispatch
# ---------------------------------------------------------------------------

def test_queue_sheds_at_max_depth_with_structured_error(tmp_path):
    path = _metrics_on(tmp_path)
    clock = FakeClock()
    q = Queue(ProgramService(), batch=64, deadline_s=1e9, buckets=(16,),
              clock=clock, max_depth=4, shed=True)
    tickets = [q.submit(Request(op="cholesky", a=_hpd(8, i)))
               for i in range(4)]
    with pytest.raises(OverloadError) as ei:
        q.submit(Request(op="cholesky", a=_hpd(8, 99)))
    assert ei.value.depth == 4 and ei.value.max_depth == 4
    assert ei.value.op == "cholesky" and ei.value.bucket_n == 16
    assert q.pending() == 4                  # depth never exceeded
    st = q.stats()
    assert st["shed"] == 1 and st["max_depth"] == 4
    assert st["shed_policy"] == "shed"
    (bucket,) = st["buckets"].values()
    assert bucket["shed"] == 1 and bucket["depth"] == 4
    q.flush()
    assert all(t.done for t in tickets)      # accepted work still served
    assert obs.registry().counter("dlaf_serve_shed_total", op="cholesky",
                                  bucket_n=16).snapshot()["value"] == 1
    sheds = [r for r in _records(path) if r.get("type") == "resilience"
             and r.get("event") == "shed"]
    assert len(sheds) == 1 and sheds[0]["site"] == "serve.queue"


def test_queue_backpressure_mode_bounds_depth_without_shedding():
    clock = FakeClock()
    q = Queue(ProgramService(), batch=64, deadline_s=1e9, buckets=(16,),
              clock=clock, max_depth=2, shed=False)
    t1 = q.submit(Request(op="cholesky", a=_hpd(8, 1)))
    t2 = q.submit(Request(op="cholesky", a=_hpd(8, 2)))
    assert q.pending() == 2
    t3 = q.submit(Request(op="cholesky", a=_hpd(8, 3)))
    # the bound forced an inline dispatch of the fullest bucket
    assert t1.done and t2.done and not t3.done
    assert q.pending() == 1 and q.stats()["shed"] == 0
    # a FAILING inline dispatch must not be misattributed to this
    # submit: the failed batch's tickets carry the cause, room was made
    # either way, and the new request is still admitted and ticketed
    t4 = q.submit(Request(op="cholesky", a=_hpd(8, 4)))
    assert q.pending() == 2
    with inject.fail_dispatch(nth=0, count=q.retry_attempts):
        t5 = q.submit(Request(op="cholesky", a=_hpd(8, 5)))
    assert t3.error is not None and t4.error is not None   # the cause
    assert t5.error is None and not t5.done                # admitted
    assert q.pending() == 1
    circuit.reset("serve.")
    q.flush()
    assert t5.done


def test_queue_request_deadline_cancels_at_dispatch(tmp_path):
    path = _metrics_on(tmp_path)
    clock = FakeClock()
    q = Queue(ProgramService(), batch=2, deadline_s=1e9, buckets=(16,),
              clock=clock)
    te = q.submit(Request(op="cholesky", a=_hpd(8, 1), deadline_s=0.5))
    clock.t = 1.0
    tl = q.submit(Request(op="cholesky", a=_hpd(8, 2)))   # fills the batch
    assert tl.done and not te.done
    with pytest.raises(RuntimeError, match="expired before dispatch"):
        te.result()
    assert isinstance(te.error, DeadlineExceededError)
    assert te.error.deadline_s == 0.5 and te.error.elapsed_s == 1.0
    assert q.stats()["expired"] == 1
    assert obs.registry().counter("dlaf_deadline_exceeded_total",
                                  site="serve.queue"
                                  ).snapshot()["value"] == 1
    recs = [r for r in _records(path) if r.get("type") == "resilience"
            and r.get("event") == "expired"]
    assert len(recs) == 1 and recs[0]["attrs"]["rid"] == te.request.rid


def test_queue_all_expired_skips_the_program_entirely():
    clock = FakeClock()

    class _Counting(ProgramService):
        runs = 0

        def run(self, spec, *args):
            _Counting.runs += 1
            return super().run(spec, *args)

    q = Queue(_Counting(), batch=4, deadline_s=1e9, buckets=(16,),
              clock=clock)
    t = q.submit(Request(op="cholesky", a=_hpd(8), deadline_s=0.1))
    clock.t = 5.0
    q.flush()
    assert t.error is not None and _Counting.runs == 0


def test_queue_dispatch_retries_transient_fault(tmp_path):
    path = _metrics_on(tmp_path)
    q = Queue(ProgramService(), batch=2, deadline_s=1e9, buckets=(16,),
              clock=FakeClock(), retry_attempts=3)
    with inject.fail_dispatch(nth=0, count=2):
        t1 = q.submit(Request(op="cholesky", a=_hpd(8, 1)))
        t2 = q.submit(Request(op="cholesky", a=_hpd(8, 2)))
    assert t1.done and t2.done               # recovered within one dispatch
    fac = np.tril(t1.result())
    ref = np.tril(_hpd(8, 1)) + np.tril(_hpd(8, 1), -1).T
    np.testing.assert_allclose(fac @ fac.T, ref, atol=1e-10)
    recs = [r for r in _records(path) if r.get("type") == "resilience"
            and r.get("event") == "retry"]
    assert len(recs) == 2
    assert not obs.validate_records(obs.read_records(path),
                                    require_resilience=True)


def test_queue_sustained_fault_opens_breaker_and_fails_fast(tmp_path):
    path = _metrics_on(tmp_path)
    q = Queue(ProgramService(), batch=1, deadline_s=1e9, buckets=(16,),
              clock=FakeClock(), retry_attempts=3)
    with inject.fail_dispatch(nth=0, count=100):
        with pytest.raises(RuntimeError, match="injected dispatch fault"):
            q.submit(Request(op="cholesky", a=_hpd(8, 1)))
        (bucket,) = q.stats()["buckets"].values()
        assert bucket["breaker"] == "open" and bucket["failures"] == 1
        # open breaker: fail fast, ticket poisoned with the cause
        with pytest.raises(CircuitOpenError):
            q.submit(Request(op="cholesky", a=_hpd(8, 2)))
        # the artifact carries the open state: --require-resilience rejects
        obs.flush()
        errors = obs.validate_records(obs.read_records(path),
                                      require_resilience=True)
        assert any("left open" in e for e in errors)
    # fail_dispatch exit resets serve breakers (reset-safety): traffic OK
    t = q.submit(Request(op="cholesky", a=_hpd(8, 3)))
    assert t.done


def test_queue_soak_threaded_flapping_fault_no_deadlock_no_double():
    """The 16-thread soak (ISSUE 12 satellite): a flapping fail_dispatch
    behind retry_attempts=1 trips the breaker open, the cooldown
    half-open probe closes it again, and through it all no submit
    deadlocks, no request dispatches twice, and no concurrent shed
    decision strands a ticket."""
    C.initialize(C.Configuration(log="off", circuit_threshold=3,
                                 circuit_cooldown_s=0.05))
    served, errors = [], []
    lock = threading.Lock()

    class _Tracking(ProgramService):
        def run(self, spec, *args):
            out = super().run(spec, *args)
            with lock:
                served.append(args[0].shape[0])   # lanes per dispatch
            return out

    q = Queue(_Tracking(), batch=4, deadline_s=1e9, buckets=(8,),
              max_depth=64, shed=True, retry_attempts=1)
    q.warmup([Request(op="cholesky", a=_hpd(8))])
    tickets = []

    def worker(seed):
        try:
            t = q.submit(Request(op="cholesky", a=_hpd(8, seed)))
            with lock:
                tickets.append(t)
        except (OverloadError, CircuitOpenError, RuntimeError) as e:
            with lock:
                errors.append(e)

    def storm(phase):
        threads = [threading.Thread(target=worker, args=(phase * 100 + i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "soak deadlocked"
        try:
            q.flush()
        except (CircuitOpenError, RuntimeError):
            pass                     # flush dispatch poisoned its tickets

    # phase 1: every dispatch attempt fails -> breaker opens mid-storm,
    # later submits fail fast; every ticket must end terminal
    with inject.fail_dispatch(nth=0, count=10_000):
        storm(1)
        (bucket,) = q.stats()["buckets"].values()
        assert bucket["breaker"] == "open"
        assert not served                     # nothing actually dispatched
    circuit.reset("serve.")                   # context reset + explicit
    # phase 2: fault gone -> the half-open probe (or fresh breaker)
    # serves everything; flapping fault every 5th attempt still recovers
    with inject.fail_dispatch(nth=0, count=1, every=5):
        storm(2)
    q.flush()
    terminal = [t for t in tickets if t.done or t.error is not None]
    assert len(terminal) == len(tickets), "stranded tickets"
    # exactly-once dispatch: the program ran once per successful dispatch,
    # never twice for one bucket pop
    assert q.dispatches == len(served)
    assert all(not (t.done and t.error is not None) for t in tickets)
    done = [t for t in tickets if t.done]
    assert len(done) >= 10                    # phase 2 really served


# ---------------------------------------------------------------------------
# Stage checkpoints + eigensolver kill-and-resume
# ---------------------------------------------------------------------------

def test_stage_checkpoint_roundtrip_and_manifest(tmp_path):
    d = str(tmp_path / "ck")
    arrays = {"x": np.arange(6.0).reshape(2, 3), "y": np.int64(7)}
    ckpt.save_stage(d, "s1", arrays, {"n": 8, "dtype": "float64"})
    man = ckpt.stage_manifest(d, "s1")
    assert man["version"] == ckpt.STAGE_MANIFEST_VERSION
    assert man["fingerprint"] == {"n": 8, "dtype": "float64"}
    out, man2 = ckpt.load_stage(d, "s1")
    np.testing.assert_array_equal(out["x"], arrays["x"])
    assert int(out["y"]) == 7 and man2 == man
    assert ckpt.stage_manifest(d, "nope") is None
    with pytest.raises(ValueError, match="not completed"):
        ckpt.load_stage(d, "nope")
    # no temp files left behind (atomic write-rename discipline)
    assert not [f for f in os.listdir(d) if ".tmp." in f]
    with pytest.raises(ValueError, match="bare identifier"):
        ckpt.save_stage(d, "../evil", arrays, {})


def test_stage_checkpoint_corrupt_manifest_is_loud(tmp_path):
    d = str(tmp_path / "ck")
    ckpt.save_stage(d, "s1", {"x": np.zeros(2)}, {})
    with open(os.path.join(d, "s1.json"), "w") as f:
        f.write("{torn")
    with pytest.raises(ValueError, match="corrupt"):
        ckpt.stage_manifest(d, "s1")


@pytest.mark.parametrize("grid_shape", [None, (2, 2)])
def test_matrix_payload_roundtrip_bitwise(grid_shape, devices8):
    from dlaf_tpu.comm.grid import Grid

    grid = Grid(*grid_shape) if grid_shape else None
    a = np.arange(13 * 13, dtype=np.float64).reshape(13, 13)
    mat = Matrix.from_global(a, TileElementSize(4, 4), grid=grid)
    arrays = ckpt.matrix_arrays(mat, "m")
    back = ckpt.matrix_from_arrays(arrays, "m", grid)
    np.testing.assert_array_equal(np.asarray(back.storage),
                                  np.asarray(mat.storage))
    np.testing.assert_array_equal(back.to_numpy(), a)
    if grid is not None:
        with pytest.raises(ValueError, match="grid"):
            ckpt.matrix_from_arrays(arrays, "m", None)


STAGES = ("red2band", "b2t", "tridiag", "bt_b2t", "bt_r2b")


@pytest.mark.parametrize("stage", STAGES)
def test_eigensolver_preempt_resume_bitwise(stage, tmp_path):
    """Kill at EVERY stage boundary -> resume -> eigenpairs bitwise
    identical to the uninterrupted run (the §5 acceptance pin)."""
    from dlaf_tpu.eigensolver.eigensolver import eigensolver

    rng = np.random.default_rng(0)
    n, nb = 32, 8
    x = rng.standard_normal((n, n))
    a = (x + x.T) / 2

    C.initialize(C.Configuration(log="off"))
    ref = eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb)))
    refw = np.asarray(ref.eigenvalues)
    refv = ref.eigenvectors.to_numpy()

    C.initialize(C.Configuration(log="off",
                                 resume_dir=str(tmp_path / "rd")))
    with pytest.raises(PreemptionError) as ei:
        with inject.preempt(stage):
            eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb)))
    assert ei.value.stage == stage
    # the killed stage's checkpoint IS on disk (kill after the write)
    assert ckpt.stage_manifest(str(tmp_path / "rd" / "eigensolver"),
                               stage) is not None
    res = eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb)),
                      resume=True)
    np.testing.assert_array_equal(np.asarray(res.eigenvalues), refw)
    np.testing.assert_array_equal(res.eigenvectors.to_numpy(), refv)


def test_eigensolver_resume_guards(tmp_path):
    from dlaf_tpu.eigensolver.eigensolver import eigensolver

    rng = np.random.default_rng(1)
    n, nb = 24, 8
    x = rng.standard_normal((n, n))
    a = (x + x.T) / 2
    mat = lambda: Matrix.from_global(a, TileElementSize(nb, nb))  # noqa: E731
    # resume without a configured dir refuses loudly
    C.initialize(C.Configuration(log="off"))
    with pytest.raises(ResumeError, match="DLAF_RESUME_DIR"):
        eigensolver("L", mat(), resume=True)
    # fingerprint mismatch (different uplo) refuses loudly
    C.initialize(C.Configuration(log="off",
                                 resume_dir=str(tmp_path / "rd")))
    eigensolver("L", mat())
    with pytest.raises(ResumeError, match="fingerprint mismatch"):
        eigensolver("U", mat(), resume=True)
    # different input DATA at the same shape/config refuses loudly too —
    # resume must never silently return another run's eigenpairs
    x2 = rng.standard_normal((n, n))
    a2 = (x2 + x2.T) / 2
    with pytest.raises(ResumeError, match="input_sha"):
        eigensolver("L", Matrix.from_global(a2, TileElementSize(nb, nb)),
                    resume=True)


def test_resume_emits_checkpoint_and_resume_records(tmp_path):
    from dlaf_tpu.eigensolver.eigensolver import eigensolver

    path = str(tmp_path / "art.jsonl")
    rng = np.random.default_rng(2)
    n, nb = 24, 8
    x = rng.standard_normal((n, n))
    a = (x + x.T) / 2
    C.initialize(C.Configuration(log="off", metrics_path=path,
                                 resume_dir=str(tmp_path / "rd")))
    eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb)))
    eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb)),
                resume=True)
    recs = [r for r in _records(path) if r.get("type") == "resilience"]
    checkpoints = [r for r in recs if r["event"] == "checkpoint"]
    resumes = [r for r in recs if r["event"] == "resume"]
    assert len(checkpoints) == 5             # one per stage
    assert len(resumes) == 5                 # full skip on resume
    assert not obs.validate_records(obs.read_records(path),
                                    require_resilience=True)


# ---------------------------------------------------------------------------
# Schema + validator obligation
# ---------------------------------------------------------------------------

def test_resilience_record_schema_rejections():
    base = {"v": 1, "ts": 1.0, "type": "resilience"}
    ok = [dict(base, site="s", event="retry", attempt=0, delay_s=0.1),
          dict(base, site="s", event="resume", attrs={"stage": "b2t"})]
    assert not obs.validate_records(ok)
    bad = [
        dict(base, event="retry", attempt=0, delay_s=0.1),    # no site
        dict(base, site="s", event="explode"),                # bad event
        dict(base, site="s", event="retry", delay_s=0.1),     # no attempt
        dict(base, site="s", event="retry", attempt=0),       # no delay
        dict(base, site="s", event="retry", attempt=0,
             delay_s=float("nan")),                           # nan delay
        dict(base, site="s", event="shed", attrs="notdict"),  # bad attrs
    ]
    for rec in bad:
        assert obs.validate_records([rec]), rec


def test_require_resilience_obligation_legs():
    base = {"v": 1, "ts": 1.0}
    retry = dict(base, type="resilience", site="s", event="retry",
                 attempt=0, delay_s=0.0)
    # no proof at all
    errors = obs.validate_records([], require_resilience=True)
    assert any("no resilience retry/resume" in e for e in errors)
    # retry proof satisfies
    assert not obs.validate_records([retry], require_resilience=True)
    # a breaker left open in the LAST snapshot rejects
    def snap(value):
        return dict(base, type="metrics", metrics=[
            {"name": "dlaf_circuit_state", "kind": "gauge",
             "labels": {"site": "serve.x"}, "value": value}])
    errors = obs.validate_records([retry, snap(2.0)],
                                  require_resilience=True)
    assert any("left open" in e for e in errors)
    # ...but a LATER snapshot showing recovery passes (last state wins)
    assert not obs.validate_records([retry, snap(2.0), snap(0.0)],
                                    require_resilience=True)


def test_validator_cli_require_resilience_flag(tmp_path):
    from dlaf_tpu.obs import validate as vcli

    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps({"v": 1, "ts": 1.0, "type": "resilience",
                                "site": "s", "event": "retry",
                                "attempt": 0, "delay_s": 0.0}) + "\n")
    assert vcli.main([str(good), "--require-resilience"]) == 0
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"v": 1, "ts": 1.0, "type": "log",
                                 "level": "info", "logger": "x",
                                 "msg": "hi"}) + "\n")
    assert vcli.main([str(empty), "--require-resilience"]) == 1


# ---------------------------------------------------------------------------
# profile_summary serve section
# ---------------------------------------------------------------------------

def test_profile_summary_prints_serve_section(tmp_path, capsys):
    import sys

    path = _metrics_on(tmp_path)
    q = Queue(ProgramService(), batch=64, deadline_s=1e9, buckets=(16,),
              clock=FakeClock(), max_depth=2, shed=True)
    q.submit(Request(op="cholesky", a=_hpd(8, 0)))
    q.submit(Request(op="cholesky", a=_hpd(8, 1)))
    with pytest.raises(OverloadError):
        q.submit(Request(op="cholesky", a=_hpd(8, 2)))
    q.flush()
    obs.flush()
    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import profile_summary

    profile_summary.summarize_jsonl(path, 25)
    out = capsys.readouterr().out
    assert "serve / resilience" in out
    assert "dlaf_serve_shed_total" in out
    assert "resilience events" in out and "shed=1" in out
