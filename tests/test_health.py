"""Robustness-layer tests (dlaf_tpu.health — ISSUE 3).

Covers: the potrf_info tile contract across dtypes x uplo (pinning the
backend NaN semantics the docstring claims), the in-graph ``with_info``
plumbing through all four cholesky builders (bitwise-identical factors,
no host sync — transfer-guard and jaxpr proofs), the singular-diagonal
detection of the triangular solve and HEGST, the shift-retry
``robust_cholesky`` driver (recovery, exhaustion, spans, counters, the
DLAF_CHECK finite guard), and — via ``health.inject`` — every
degradation path end-to-end: non-SPD -> shift-retry, native-load failure
-> numpy, pallas-off -> XLA, ozaki-off -> plain dot, strict mode ->
raise; each with its ``dlaf_fallback_total`` accounting asserted, local
and distributed.
"""

import os

import jax
import numpy as np
import pytest

import dlaf_tpu.config as C
from dlaf_tpu import health, obs
from dlaf_tpu.algorithms.cholesky import (_cholesky_local, cholesky)
from dlaf_tpu.algorithms.gen_to_std import gen_to_std
from dlaf_tpu.algorithms.triangular import triangular_solve
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.tile_ops import lapack as tl

DTYPES = [np.float32, np.float64, np.complex64, np.complex128]


@pytest.fixture(autouse=True)
def health_reset():
    """Leave every test with the suite's default config and no metrics."""
    yield
    os.environ.pop("DLAF_METRICS_PATH", None)
    obs._reset_for_tests()
    health.circuit.reset()            # no tripped breaker leaks between
    C.finalize()                      # tests (docs/robustness.md §3)
    C.initialize()


def _metrics_on(tmp_path, **cfg):
    path = str(tmp_path / "health.jsonl")
    C.initialize(C.Configuration(metrics_path=path, **cfg))
    return path


def hpd_matrix(n, dtype=np.float64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    return (x @ x.conj().T + n * np.eye(n)).astype(dtype)


def Matrix_from(a, nb, grid=None):
    return Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)


def fallback_count(site, reason="native_unavailable"):
    return obs.registry().counter(health.FALLBACK_COUNTER, site=site,
                                  reason=reason).snapshot()["value"]


# ---------------------------------------------------------------------------
# potrf_info tile contract (satellite: pin the docstring's claims)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_potrf_info_semantics(uplo, dtype):
    """SPD -> info 0 with the factor byte-equal to plain potrf; non-SPD ->
    nonzero info = first non-finite diagonal. On CPU, XLA NaNs the WHOLE
    factor (the docstring's claim at tile_ops/lapack.py:84, previously
    untested): even a failure at column 4 reports info == 1."""
    a = hpd_matrix(6, dtype)
    f_ref = np.asarray(tl.potrf(uplo, a))
    f, info = tl.potrf_info(uplo, a)
    assert int(info) == 0
    np.testing.assert_array_equal(np.asarray(f), f_ref)

    bad = a.copy()
    bad[3, 3] = -1000.0          # leading minor fails at column 4 (1-based)
    f2, info2 = tl.potrf_info(uplo, bad)
    d = np.diagonal(np.asarray(f2)).real
    assert int(info2) >= 1
    assert int(info2) == int(np.argmax(~np.isfinite(d))) + 1
    if jax.default_backend() == "cpu":
        # CPU semantics: the whole factor is NaN'd, so the locator
        # degrades to the first column — a success/failure signal first
        assert not np.isfinite(d).any()
        assert int(info2) == 1
    # the pass-through triangle is NOT part of the info signal
    other = np.tril(np.asarray(f2), -1) if uplo == "U" \
        else np.triu(np.asarray(f2), 1)
    assert np.isfinite(other.real).all()


# ---------------------------------------------------------------------------
# with_info plumbing: all four builders, bitwise factors, no host sync
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("trailing", ["loop", "biggemm", "scan", "xla"])
def test_with_info_factor_bitwise_local(trailing, monkeypatch):
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", trailing)
    C.initialize()
    a = hpd_matrix(13)
    plain = cholesky("L", Matrix_from(a, 4)).to_numpy()
    fac, info = cholesky("L", Matrix_from(a, 4), with_info=True)
    assert int(info) == 0
    np.testing.assert_array_equal(fac.to_numpy(), plain)


@pytest.mark.parametrize("scan", [False, True])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_with_info_factor_bitwise_distributed(uplo, scan, devices8,
                                              monkeypatch):
    if scan:
        monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "scan")
    C.initialize()
    grid = Grid(2, 4)
    a = hpd_matrix(16)
    plain = cholesky(uplo, Matrix_from(a, 4, grid)).to_numpy()
    fac, info = cholesky(uplo, Matrix_from(a, 4, grid), with_info=True)
    assert int(info) == 0
    np.testing.assert_array_equal(fac.to_numpy(), plain)


@pytest.mark.parametrize("grid_shape", [None, (2, 2)])
def test_with_info_detects_failing_column(grid_shape, devices8):
    """A non-SPD pivot in the second diagonal tile must report a failing
    column inside that tile (backend NaN prefix bounds the precision to
    the tile's first column), identically local and distributed."""
    a = hpd_matrix(16)
    a[6, 6] = -1e6               # tile 1 spans 1-based columns 5..8
    grid = Grid(*grid_shape) if grid_shape else None
    _, info = cholesky("L", Matrix_from(a, 4, grid), with_info=True)
    assert 5 <= int(info) <= 7


def test_with_info_no_host_sync():
    """The acceptance proof: with_info adds NO host sync to the hot path —
    the call completes under a device->host transfer guard (fetching info
    stays the caller's explicit decision), and the traced program carries
    no callback/infeed/outfeed primitives."""
    a = hpd_matrix(16)
    mat = Matrix_from(a, 4)
    cholesky("L", Matrix_from(a, 4), with_info=True)   # warm the caches
    with jax.transfer_guard_device_to_host("disallow"):
        fac, info = cholesky("L", mat, with_info=True)
    assert isinstance(info, jax.Array)                 # still on device
    assert int(info) == 0                              # fetch AFTER guard

    from dlaf_tpu.analysis import depgraph

    jaxpr = depgraph.trace(
        lambda x: _cholesky_local(x, uplo="L", nb=4, trailing="loop",
                                  with_info=True), a)
    assert not depgraph.callbacks(jaxpr), \
        "hot path grew a host-callback/transfer primitive"


@pytest.mark.parametrize("grid_shape", [None, (2, 2)])
def test_triangular_solve_with_info(grid_shape, devices8):
    n = 8
    a = np.tril(hpd_matrix(n)) + n * np.eye(n)
    b = np.arange(n * 4, dtype=np.float64).reshape(n, 4) / 7.0
    grid = Grid(*grid_shape) if grid_shape else None
    x, info = triangular_solve("L", "L", "N", "N", 1.0,
                               Matrix_from(a, 4, grid),
                               Matrix_from(b, 4, grid), with_info=True)
    assert int(info) == 0
    sing = a.copy()
    sing[5, 5] = 0.0
    x2, info2 = triangular_solve("L", "L", "N", "N", 1.0,
                                 Matrix_from(sing, 4, grid),
                                 Matrix_from(b, 4, grid), with_info=True)
    assert int(info2) == 6       # 1-based first singular global column
    # implicit unit diagonal is never singular
    _, info3 = triangular_solve("L", "L", "N", "U", 1.0,
                                Matrix_from(sing, 4, grid),
                                Matrix_from(b, 4, grid), with_info=True)
    assert int(info3) == 0


def test_gen_to_std_with_info():
    n = 8
    a = hpd_matrix(n, seed=1)
    l = np.tril(hpd_matrix(n)) + n * np.eye(n)
    out, info = gen_to_std("L", Matrix_from(a, 4), Matrix_from(l, 4),
                           with_info=True)
    assert int(info) == 0
    l[2, 2] = 0.0
    out2, info2 = gen_to_std("L", Matrix_from(a, 4), Matrix_from(l, 4),
                             with_info=True)
    assert int(info2) == 3


# ---------------------------------------------------------------------------
# shift_diagonal / robust_cholesky
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
def test_shift_diagonal_exact(grid_shape, devices8):
    n = 13                        # non-divisible: exercises the edge tile
    a = hpd_matrix(n)
    grid = Grid(*grid_shape) if grid_shape else None
    shifted = health.shift_diagonal(Matrix_from(a, 4, grid), 2.5)
    np.testing.assert_array_equal(shifted.to_numpy(), a + 2.5 * np.eye(n))


@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
def test_robust_cholesky_recovers(grid_shape, devices8, tmp_path):
    """The non-SPD -> shift-retry -> success path, local AND distributed,
    with the retry spans and counters landing in the JSONL artifact."""
    path = _metrics_on(tmp_path)
    n = 16
    a = hpd_matrix(n)
    indef = a - 2 * n * np.eye(n)          # strongly indefinite
    grid = Grid(*grid_shape) if grid_shape else None
    res = health.robust_cholesky("L", Matrix_from(indef, 4, grid))
    assert res.attempts > 1
    assert res.infos[-1] == 0 and all(i != 0 for i in res.infos[:-1])
    assert res.shifts[0] == 0.0 and res.shifts[-1] > 0
    # the factor factorizes the SHIFTED matrix
    f = np.tril(res.matrix.to_numpy())
    target = indef + res.shifts[-1] * np.eye(n)
    resid = np.linalg.norm(f @ f.T - target) / np.linalg.norm(target)
    assert resid < 60 * n * np.finfo(np.float64).eps
    obs.flush()
    records = obs.read_records(path)
    assert not obs.validate_records(records, require_retries=True)
    attempts = [r for r in records if r.get("type") == "span"
                and r.get("name") == "robust_cholesky.attempt"]
    assert len(attempts) == res.attempts
    assert [r["attrs"]["attempt"] for r in attempts] == \
        list(range(res.attempts))
    assert [r["attrs"]["shift"] for r in attempts] == list(res.shifts)
    assert [r["attrs"]["info"] for r in attempts] == list(res.infos)


def test_robust_cholesky_exhaustion_raises():
    a = hpd_matrix(8)
    a[2, 1] = a[1, 2] = np.nan             # unrecoverable by shifting
    with pytest.raises(health.FactorizationError) as ei:
        health.robust_cholesky("L", Matrix_from(a, 4), max_attempts=2)
    e = ei.value
    assert e.attempts == 2
    assert len(e.shifts) == 2 and e.shifts[0] == 0.0
    assert e.failing_column >= 1
    assert all(i != 0 for i in e.infos)


def test_robust_cholesky_first_try_spd():
    a = hpd_matrix(8)
    res = health.robust_cholesky("L", Matrix_from(a, 4))
    assert res.attempts == 1 and res.shifts == (0.0,) and res.infos == (0,)
    plain = cholesky("L", Matrix_from(a, 4)).to_numpy()
    np.testing.assert_array_equal(res.matrix.to_numpy(), plain)


def test_dlaf_check_finite_guard(tmp_path):
    _metrics_on(tmp_path, check=True)
    a = hpd_matrix(8)
    health.robust_cholesky("L", Matrix_from(a, 4))     # clean input passes
    a[3, 0] = np.nan
    with pytest.raises(health.CheckError) as ei:
        health.robust_cholesky("L", Matrix_from(a, 4))
    assert ei.value.what == "cholesky input" and ei.value.count == 1
    assert obs.registry().counter("dlaf_check_failures_total",
                                  what="cholesky input"
                                  ).snapshot()["value"] == 1


# ---------------------------------------------------------------------------
# fault injection: data corruption
# ---------------------------------------------------------------------------

def test_nan_tile_deterministic_and_detected():
    a = hpd_matrix(16)
    m1 = inject.nan_tile(Matrix_from(a, 4), seed=7)
    m2 = inject.nan_tile(Matrix_from(a, 4), seed=7)
    np.testing.assert_array_equal(m1.to_numpy(), m2.to_numpy())
    assert np.isnan(m1.to_numpy()).sum() == 1
    poisoned = inject.nan_tile(Matrix_from(a, 4), tile=(1, 0),
                               element=(2, 3))
    out = poisoned.to_numpy()
    assert np.isnan(out[6, 3]) and np.isnan(out).sum() == 1
    _, info = cholesky("L", poisoned, with_info=True)
    assert int(info) != 0


def test_corrupt_collective_detected_and_contained(devices8):
    """Poisoning one bcast payload must surface as nonzero info on the
    distributed factorization — and must NOT leak into later runs (the
    injection context clears compiled-program caches both ways)."""
    grid = Grid(2, 4)
    a = hpd_matrix(16)
    with inject.corrupt_collective("bcast", nth=0, seed=3):
        _, info = cholesky("L", Matrix_from(a, 4, grid), with_info=True)
        assert int(info) != 0
    _, clean = cholesky("L", Matrix_from(a, 4, grid), with_info=True)
    assert int(clean) == 0
    # deterministic: the same (nth, seed) poisons the same position
    with inject.corrupt_collective("bcast", nth=0, seed=3):
        _, info2 = cholesky("L", Matrix_from(a, 4, grid), with_info=True)
    assert int(info2) == int(info)


# ---------------------------------------------------------------------------
# fault injection: native-load failure -> numpy (+ bindings cache contract)
# ---------------------------------------------------------------------------

def test_bindings_cached_error_reraise_and_once_log(tmp_path, monkeypatch):
    """The cached-error re-raise path (bindings.get_lib): a failed build is
    cached — the compiler is NOT respawned per call — and the error-level
    log lands exactly once."""
    from dlaf_tpu.native import bindings

    path = _metrics_on(tmp_path)
    calls = []

    def failing_build():
        calls.append(1)
        raise RuntimeError("synthetic toolchain failure")

    monkeypatch.setattr(bindings, "_build", failing_build)
    # point at a nonexistent artifact so the build path always runs
    monkeypatch.setattr(bindings, "_LIB", str(tmp_path / "no-such-lib.so"))
    bindings._reset_for_tests()
    try:
        for _ in range(3):
            with pytest.raises(RuntimeError, match="synthetic"):
                bindings.get_lib()
        assert len(calls) == 1, "cached error must not respawn the build"
    finally:
        bindings._reset_for_tests()
    obs.flush()
    errors = [r for r in obs.read_records(path)
              if r.get("type") == "log" and r.get("level") == "error"
              and r.get("logger") == "native"]
    assert len(errors) == 1


def test_force_native_failure_degrades_to_numpy(tmp_path):
    from dlaf_tpu.eigensolver.band_to_tridiag import (band_to_tridiag,
                                                      band_to_tridiag_numpy)
    from dlaf_tpu.eigensolver.tridiag_solver import (_secular_roots,
                                                     _secular_roots_host)

    _metrics_on(tmp_path)
    band = np.zeros((3, 12))
    band[0] = np.arange(1.0, 13.0)
    band[1, :-1] = 0.5
    band[2, :-2] = 0.1
    d = np.arange(1.0, 7.0)
    z = np.full(6, 0.4)
    with inject.force_native_failure():
        chased = band_to_tridiag(band, 2)
        anchor, mu = _secular_roots_host(d, z, 0.5)
    ref = band_to_tridiag_numpy(band, 2)
    np.testing.assert_allclose(chased.d, ref.d)
    np.testing.assert_allclose(chased.e, ref.e)
    a_ref, m_ref = _secular_roots(d, z, 0.5)
    np.testing.assert_allclose(d[anchor] + mu, d[a_ref] + m_ref, rtol=1e-10)
    assert fallback_count("band_to_tridiag") >= 1
    assert fallback_count("secular") >= 1
    # outside the context the native library loads again
    from dlaf_tpu.native import bindings

    try:
        bindings.get_lib()
    except Exception:
        pytest.skip("no native toolchain in this environment")


def test_strict_mode_raises_instead_of_degrading(tmp_path):
    from dlaf_tpu.eigensolver.band_to_tridiag import band_to_tridiag

    _metrics_on(tmp_path, strict=True)
    band = np.zeros((3, 8))
    band[0] = np.arange(1.0, 9.0)
    with inject.force_native_failure():
        with pytest.raises(health.DegradationError) as ei:
            band_to_tridiag(band, 2)
    assert ei.value.site == "band_to_tridiag"
    assert ei.value.reason == "native_unavailable"


# ---------------------------------------------------------------------------
# fault injection: route degradations (pallas -> XLA, ozaki -> plain dot)
# ---------------------------------------------------------------------------

def test_pallas_off_degrades_to_xla(tmp_path, monkeypatch, devices8):
    """pallas-off -> XLA on the distributed f32 trailing update: with the
    route forced available (interpret mode off-TPU), disabling it via
    injection must register the degradation and still produce a correct
    factor through the einsum route."""
    monkeypatch.setenv("DLAF_FORCE_PALLAS_UPDATE", "1")
    _metrics_on(tmp_path)
    grid = Grid(2, 2)
    n = 8
    a = hpd_matrix(n, np.float32)
    via_pallas = cholesky("L", Matrix_from(a, 4, grid)).to_numpy()
    assert fallback_count("pallas_update", "injected_off") == 0
    with inject.disable_pallas():
        degraded = cholesky("L", Matrix_from(a, 4, grid)).to_numpy()
    assert fallback_count("pallas_update", "injected_off") >= 1
    for out in (via_pallas, degraded):
        f = np.tril(out)
        resid = np.linalg.norm(f @ f.T - a) / np.linalg.norm(a)
        assert resid < 60 * n * np.finfo(np.float32).eps


def test_ozaki_off_degrades_to_plain_dot(tmp_path):
    from dlaf_tpu.tile_ops import blas as tb

    path = str(tmp_path / "oz.jsonl")
    C.initialize(C.Configuration(metrics_path=path, f64_gemm="mxu",
                                 f64_gemm_min_dim=4))
    assert tb.f64_gemm_uses_mxu(np.float64, 8)
    with inject.disable_ozaki():
        assert not tb.f64_gemm_uses_mxu(np.float64, 8)
        # the plain-dot route still factorizes correctly
        a = hpd_matrix(8)
        out = cholesky("L", Matrix_from(a, 4)).to_numpy()
        f = np.tril(out)
        assert np.linalg.norm(f @ f.T - a) / np.linalg.norm(a) < 1e-12
    assert fallback_count("ozaki_gemm", "injected_off") >= 1
    assert tb.f64_gemm_uses_mxu(np.float64, 8)   # restored on exit


# ---------------------------------------------------------------------------
# multihost bring-up timeout
# ---------------------------------------------------------------------------

def test_multihost_timeout_actionable_error(monkeypatch):
    from dlaf_tpu.comm import multihost

    seen = {}

    def fake_initialize(coordinator_address=None, num_processes=None,
                        process_id=None, initialization_timeout=None):
        seen["timeout"] = initialization_timeout
        raise TimeoutError("deadline exceeded waiting for coordinator")

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    with pytest.raises(RuntimeError) as ei:
        multihost.initialize_multihost("10.0.0.1:8476", num_processes=4,
                                       process_id=1, timeout=5,
                                       connect_attempts=1)
    msg = str(ei.value)
    assert "10.0.0.1:8476" in msg and "timeout=5s" in msg
    assert "firewall" in msg and "SAME" in msg
    assert seen["timeout"] == 5
    # single-process worlds stay a no-op (no coordinator required)
    multihost.initialize_multihost(None, num_processes=1)


def test_multihost_connect_retries_transient_failures(monkeypatch,
                                                      tmp_path):
    """The coordinator connect rides the shared policy engine (PR 12):
    a transient bring-up failure retries with backoff and the world
    comes up on a later attempt; a caller bug raises immediately with
    its own message (never retried)."""
    from dlaf_tpu.comm import multihost
    from dlaf_tpu.health import policy as hpolicy

    _metrics_on(tmp_path)     # arm the registry: the counter assertion
                              # below must have teeth, not read a no-op

    calls = []

    def flaky_initialize(coordinator_address=None, num_processes=None,
                         process_id=None, initialization_timeout=None):
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("connection refused")

    slept = []
    monkeypatch.setattr(jax.distributed, "initialize", flaky_initialize)
    monkeypatch.setattr(hpolicy.time, "sleep", slept.append)
    multihost.initialize_multihost("10.0.0.1:8476", num_processes=4,
                                   process_id=1, connect_attempts=3,
                                   connect_backoff_s=0.25)
    assert len(calls) == 3 and len(slept) == 2
    assert slept[0] < slept[1]           # exponential backoff applied
    assert obs.registry().counter("dlaf_retry_total",
                                  site="multihost.connect"
                                  ).snapshot()["value"] == 2  # one per retry

    calls.clear()

    def buggy_initialize(**kw):
        calls.append(1)
        raise ValueError("already initialized")

    monkeypatch.setattr(jax.distributed, "initialize", buggy_initialize)
    with pytest.raises(ValueError, match="already initialized"):
        multihost.initialize_multihost("10.0.0.1:8476", num_processes=4,
                                       process_id=1)
    assert len(calls) == 1               # caller bugs are never retried


# ---------------------------------------------------------------------------
# DLAF_STRICT coverage audit (PR 12 satellite): EVERY report_fallback site
# must have a strict-raise assertion in this file — secular and
# band_to_tridiag are covered by the tests above/below; the rest here. The
# audit test at the end greps the source so a NEW site cannot land without
# extending this block.
# ---------------------------------------------------------------------------

def test_strict_deflate_site_raises(tmp_path):
    from dlaf_tpu.eigensolver.tridiag_solver import _deflation_scan

    _metrics_on(tmp_path, strict=True)
    ds = np.array([1.0, 1.0 + 1e-14, 2.0])
    zs = np.array([0.5, 0.5, 0.5])
    live = np.ones(3, dtype=bool)
    with inject.force_native_failure():
        with pytest.raises(health.DegradationError) as ei:
            _deflation_scan(ds, zs, live, 1e-8)
    assert ei.value.site == "deflate"


def test_strict_pallas_update_site_raises(tmp_path, monkeypatch, devices8):
    monkeypatch.setenv("DLAF_FORCE_PALLAS_UPDATE", "1")
    _metrics_on(tmp_path, strict=True)
    a = hpd_matrix(8, np.float32)
    with inject.disable_pallas():
        with pytest.raises(health.DegradationError) as ei:
            cholesky("L", Matrix_from(a, 4, Grid(2, 2)))
    assert ei.value.site == "pallas_update"
    assert ei.value.reason == "injected_off"


def test_strict_ozaki_gemm_site_raises(tmp_path):
    from dlaf_tpu.tile_ops import blas as tb

    path = str(tmp_path / "strict_oz.jsonl")
    C.initialize(C.Configuration(metrics_path=path, strict=True,
                                 f64_gemm="mxu", f64_gemm_min_dim=4))
    with inject.disable_ozaki():
        with pytest.raises(health.DegradationError) as ei:
            tb.f64_gemm_uses_mxu(np.float64, 8)
    assert ei.value.site == "ozaki_gemm"


def test_strict_ozaki_pallas_site_raises(tmp_path, devices8):
    path = str(tmp_path / "strict_ozp.jsonl")
    C.initialize(C.Configuration(metrics_path=path, strict=True,
                                 ozaki_impl="pallas", f64_gemm="mxu",
                                 f64_gemm_min_dim=4))
    a = hpd_matrix(16)
    with inject.disable_pallas():
        with pytest.raises(health.DegradationError) as ei:
            cholesky("L", Matrix_from(a, 4, Grid(2, 2)))
    assert ei.value.site == "ozaki_pallas"


def test_strict_panel_site_raises(tmp_path):
    path = str(tmp_path / "strict_panel.jsonl")
    C.initialize(C.Configuration(metrics_path=path, strict=True,
                                 panel_impl="fused"))
    a = hpd_matrix(16, np.float32)
    with inject.disable_pallas():
        with pytest.raises(health.DegradationError) as ei:
            cholesky("L", Matrix_from(a, 4))
    assert ei.value.site == "panel"


def test_strict_step_site_raises(tmp_path):
    path = str(tmp_path / "strict_step.jsonl")
    C.initialize(C.Configuration(metrics_path=path, strict=True,
                                 step_impl="fused", step_vmem_limit=1024))
    a = hpd_matrix(16, np.float32)
    with pytest.raises(health.DegradationError) as ei:
        cholesky("L", Matrix_from(a, 4))
    assert ei.value.site == "step"
    assert ei.value.reason == "vmem_budget"


def test_strict_coverage_audit_no_unlisted_site():
    """The audit itself: every ``report_fallback``/``route_available``
    site literal in dlaf_tpu/ must be in the strict-covered list below
    (each entry has a strict-raise test in this file). A new degradation
    site cannot land without a strict assertion riding along."""
    import re

    covered = {"secular", "deflate", "band_to_tridiag", "pallas_update",
               "ozaki_gemm", "ozaki_pallas", "panel", "step"}
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "dlaf_tpu")
    found = set()
    pat = re.compile(
        r"report_fallback\(\s*['\"]([a-z0-9_]+)['\"]"
        r"|route_available\(\s*['\"][a-z0-9_]+['\"]\s*,"
        r"\s*['\"]([a-z0-9_]+)['\"]")
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            src = open(os.path.join(dirpath, fn)).read()
            for m in pat.finditer(src):
                found.add(m.group(1) or m.group(2))
    # registry.py's own "circuit_open" reason-path and docstring mentions
    # are not sites; the regex only matches call-site literals
    assert found, "audit found no degradation sites — regex rotted?"
    assert found <= covered, \
        f"degradation site(s) {sorted(found - covered)} have no strict-" \
        "raise test in tests/test_health.py — add one and list it here"


# ---------------------------------------------------------------------------
# fault injection parity: the PR-6 eigensolver pipeline paths
# (hoisted bt collectives + the level-batched secular route)
# ---------------------------------------------------------------------------

def test_corrupt_all_gather_reaches_bt_chain(devices8, monkeypatch):
    """corrupt_collective("all_gather") must reach the bt_reduction_to_band
    panel gather even when bt_lookahead hoists it ahead of the bulk
    (the drill targets "a collective on the back-transform chain"; the
    hoist must not move the payload out of the corruption's reach) — and
    the poison must NOT leak into later runs."""
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.eigensolver.back_transform import bt_reduction_to_band
    from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band

    monkeypatch.setenv("DLAF_BT_LOOKAHEAD", "1")
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "unrolled")
    C.initialize()
    try:
        rng = np.random.default_rng(7)
        n, nb = 24, 4
        x = rng.standard_normal((n, n))
        a = x @ x.T + n * np.eye(n)
        c = rng.standard_normal((n, n))
        grid = Grid(2, 2)

        def run():
            red = reduction_to_band(Matrix.from_global(
                a, TileElementSize(nb, nb), grid=grid))
            return bt_reduction_to_band(red, Matrix.from_global(
                c, TileElementSize(nb, nb), grid=grid)).to_numpy()

        clean = run()
        assert np.isfinite(clean).all()
        with inject.corrupt_collective("all_gather", nth=0, seed=5):
            poisoned = run()
        assert np.isnan(poisoned).any(), \
            "all_gather corruption never reached the hoisted bt gather"
        again = run()
        np.testing.assert_array_equal(again, clean)
    finally:
        monkeypatch.delenv("DLAF_BT_LOOKAHEAD", raising=False)
        monkeypatch.delenv("DLAF_DIST_STEP_MODE", raising=False)
        C.initialize()


def test_level_batched_secular_native_failure(tmp_path, monkeypatch):
    """Batched D&C + injected native failure: every merge's host secular
    solve must degrade to the numpy bisection THROUGH the registry
    (dlaf_fallback_total{site="secular"} counted), and the batched
    decomposition must stay correct."""
    import scipy.linalg as sla

    from dlaf_tpu.eigensolver.tridiag_solver import tridiag_solver

    _metrics_on(tmp_path, dc_level_batch="1")
    rng = np.random.default_rng(9)
    n = 64
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    with inject.force_native_failure():
        lam, q = tridiag_solver(d, e, 8, use_device=True)
    assert fallback_count("secular", "native_unavailable") >= 1
    np.testing.assert_allclose(lam, sla.eigvalsh_tridiagonal(d, e),
                               atol=1e-11)
    q = np.asarray(q)
    t = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    assert np.linalg.norm(t @ q - q * lam[None, :]) < 1e-10 * n


def test_level_batched_strict_mode_raises(tmp_path):
    """DLAF_STRICT under the batched route: the first secular degradation
    raises DegradationError instead of silently taking the ~100x numpy
    path (same contract as the serialized walk)."""
    from dlaf_tpu.eigensolver.tridiag_solver import tridiag_solver
    from dlaf_tpu.health.errors import DegradationError

    _metrics_on(tmp_path, dc_level_batch="1", strict=True)
    rng = np.random.default_rng(2)
    d = rng.standard_normal(48)
    e = rng.standard_normal(47)
    with inject.force_native_failure():
        with pytest.raises(DegradationError):
            tridiag_solver(d, e, 8, use_device=True)
