"""Test harness configuration.

Mirrors the reference's "6 oversubscribed MPI ranks" strategy
(``test/include/dlaf_test/comm_grids/grids_6_ranks.h``) by forcing an
8-device virtual CPU platform so distributed code paths (2D meshes, ICI
collective verbs, shard_map algorithms) run on any host. Must run before the
first ``import jax`` anywhere in the test session.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Run the full assertion ladder in tests (reference CI enables heavy asserts).
os.environ.setdefault("DLAF_ASSERT_HEAVY_ENABLE", "1")

import jax  # noqa: E402

# A TPU plugin's register() may have force-set jax_platforms at interpreter
# start (overriding the env var); the config-level update wins and keeps the
# test session on the 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# The suite is XLA-compile-dominated (the 30 slowest tests are 5-30 s of
# compile each); persist compiled programs across test sessions like the
# bench/product path does (bench.py _cache_dir -> the
# config.compilation_cache_dir knob). Cache key includes platform +
# device count, so TPU/product entries never collide with these.
#
# Threshold 5 s (not 0.5): on this container's jaxlib, cache-LOADED small
# custom-call-dense programs (the local red2band family) intermittently
# compute garbage when many deserialized executables run in one session —
# reproduced as random test_reduction_to_band scan-vs-unrolled mismatches
# that vanish with the cache off and never occur on cold (writing) runs.
# Keeping sub-5s compiles out of the cache sidesteps the corruption where
# it was observed while retaining the big-program compile savings.
# An explicit JAX_COMPILATION_CACHE_DIR wins over the repo-local default:
# CI's slow job restores a cross-run cache there (.github/workflows/ci.yml)
# and an unconditional override would silently leave that cache empty.
_cache = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

import pytest  # noqa: E402

#: The `quick` smoke tier (``pytest -m quick``): ONE representative config
#: per algorithm family / core layer, for hardware-session sanity checks
#: where the full suite's ~11 min wall is unaffordable (tunnel windows are
#: ~1 h). The FIRST collected parametrization of each named test gets the
#: marker, so the tier tracks parametrize changes without hand-pinned ids.
_QUICK_TESTS = {
    ("test_cholesky.py", "test_cholesky_local"),
    ("test_cholesky.py", "test_cholesky_distributed"),
    ("test_cholesky.py", "test_cholesky_local_trailing_variants"),
    ("test_cholesky.py", "test_cholesky_scan_native_dtypes"),
    ("test_triangular.py", "test_solve_local_all_combos"),
    ("test_triangular.py", "test_solve_distributed"),
    ("test_qr.py", "test_t_factor_local_matrix"),
    ("test_qr.py", "test_t_factor_distributed"),
    ("test_gen_to_std.py", "test_gen_to_std_local"),
    ("test_gen_to_std.py", "test_gen_to_std_distributed"),
    ("test_gen_to_std.py", "test_general_sub_multiply"),
    ("test_reduction_to_band.py", "test_red2band_local"),
    ("test_reduction_to_band.py", "test_red2band_distributed_band_size"),
    ("test_band_to_tridiag.py", "test_band_to_tridiag"),
    ("test_band_to_tridiag.py", "test_native_matches_numpy"),
    ("test_tridiag_solver.py", "test_random"),
    ("test_eigensolver.py", "test_eigensolver"),
    ("test_eigensolver.py", "test_eigensolver_distributed"),
    ("test_eigensolver.py", "test_gen_eigensolver"),
    ("test_eigensolver.py", "test_bt_reduction_to_band"),
    ("test_eigensolver.py", "test_bt_band_to_tridiag"),
    ("test_eigensolver.py", "test_permutations"),
    ("test_ozaki.py", "test_accuracy_f64_grade"),
    ("test_ozaki.py", "test_syrk_matches_matmul"),
    ("test_pallas_kernels.py", "test_masked_trailing_update"),
    ("test_pallas_panel.py", "test_fused_potrf_parity"),
    ("test_pallas_panel.py", "test_fused_step_emits_one_kernel_per_panel_op"),
    ("test_tile_ops.py", "test_gemm"),
    ("test_tile_ops.py", "test_lange"),
    ("test_matrix.py", "test_matrix_roundtrip_local"),
    ("test_matrix.py", "test_matrix_sharded_over_mesh"),
    ("test_comm.py", "test_bcast"),
    ("test_comm.py", "test_grid_shapes"),
    ("test_config.py", "test_defaults"),
    ("test_config.py", "test_cli_overrides_env"),
    ("test_distribution.py", "test_distribution_2d"),
    ("test_index2d.py", "test_basic_coords"),
    ("test_types.py", "test_flop_weights"),
    ("test_aux_components.py", "test_max_norm_local_and_distributed"),
    ("test_aux_components.py", "test_bench_headline_fallback_replays_history"),
    ("test_serve.py", "test_cholesky_batched_bitwise_vs_singles"),
    ("test_serve.py", "test_warmed_queue_artifact_passes_require_serve"),
    ("test_resilience.py", "test_queue_dispatch_retries_transient_fault"),
    ("test_resilience.py", "test_eigensolver_preempt_resume_bitwise"),
    ("test_obs.py", "test_noop_fast_path_when_disabled"),
    ("test_obs.py", "test_jsonl_schema_roundtrip"),
    ("test_obs.py", "test_miniapp_cholesky_metrics_integration"),
    ("test_telemetry.py", "test_telemetry_call_records_compile_and_retrace"),
    ("test_telemetry.py", "test_bench_gate_committed_history_replays_clean"),
    ("test_accuracy.py", "test_probe_within_variance_bound"),
    ("test_accuracy.py", "test_gate_legs"),
    ("test_analysis.py", "test_drills_trip_their_rules"),
    ("test_analysis.py", "test_lint_repo_is_clean"),
    ("test_live_telemetry.py", "test_serve_trace_join_end_to_end"),
    ("test_live_telemetry.py",
     "test_metrics_scrape_monotone_across_two_scrapes"),
}


#: Tier-1 wall-clock budget control. Fixing the `jax.shard_map` imports
#: (PR 1 satellite) grew the collected ``not slow`` selection from ~400
#: to ~1340 tests, and the suite is compile-dominated with sub-5s
#: compiles deliberately kept out of the persistent cache (see above) —
#: running every distributed parametrization per push no longer fits the
#: ~15 min tier budget. For the heavy algorithm files, keep every
#: STRIDE-th parametrization of each test function in the default tier
#: and move the rest to the ``slow`` deep tier (``ci/run.sh full`` still
#: runs everything). Selection is deterministic (sorted by nodeid, so
#: independent of collection order), tracks parametrize changes, and
#: never demotes a ``quick``-marked item.
_TIER1_STRIDE = {
    "test_cholesky.py": 8,
    # PR-6 rebalance: the quick tier had crept to 761 s of the 870 s
    # budget; the eigensolver files carry the compile-heaviest
    # parametrizations (full-pipeline + distributed grids), so their
    # strides widen and the new batched-vs-serial D&C pins are strided
    # from day one (every parametrization still runs in ci/run.sh full).
    # Post-rebalance tier-1: 742 passed in ~545-615 s warm-cache.
    "test_eigensolver.py": 8,
    "test_reduction_to_band.py": 6,
    "test_gen_to_std.py": 4,
    "test_triangular.py": 4,
    "test_ozaki.py": 2,
    "test_tridiag_solver.py": 2,
}


def pytest_collection_modifyitems(config, items):
    seen = set()
    thinned = {}
    for item in items:
        key = (item.path.name, getattr(item, "originalname", item.name))
        if key in _QUICK_TESTS and key not in seen:
            seen.add(key)
            item.add_marker(pytest.mark.quick)
        if item.path.name in _TIER1_STRIDE:
            # group by class too: same-named methods in different classes
            # (e.g. test_ozaki.py's per-route Test* classes) must stride
            # independently, or one class's parametrize edits shift which
            # of another's parametrizations stay in the default tier
            cls = getattr(item, "cls", None)
            gkey = (item.path.name, cls.__name__ if cls else None,
                    getattr(item, "originalname", item.name))
            thinned.setdefault(gkey, []).append(item)
    for key, group in thinned.items():
        stride = _TIER1_STRIDE[key[0]]
        for i, item in enumerate(sorted(group, key=lambda it: it.nodeid)):
            if i % stride and \
                    not any(m.name == "quick" for m in item.own_markers):
                item.add_marker(pytest.mark.slow)


_exit_status = None


@pytest.hookimpl(trylast=True)
def pytest_sessionfinish(session, exitstatus):
    global _exit_status
    _exit_status = int(exitstatus)


def pytest_unconfigure(config):
    # Interpreter teardown of a full-tier session — hundreds of live XLA
    # executables plus the 8-device virtual CPU client — costs 1-2 min of
    # pure destructor time AFTER the summary prints, real wall the tier
    # budget cannot spare. Everything durable (persistent compile cache,
    # obs JSONL artifacts, junit files) has been written synchronously by
    # now (trylast: the terminal reporter's summary is already out), so
    # skip the teardown. Embedders that call pytest.main() in-process and
    # need control back (IDE runners, meta-runners) opt out via
    # DLAF_PYTEST_TEARDOWN=1; coverage saves its data via atexit, which
    # os._exit would bypass, so a live coverage module also opts out.
    import sys

    if _exit_status is not None and \
            not os.environ.get("DLAF_PYTEST_TEARDOWN") and \
            "coverage" not in sys.modules:

        try:
            # what the obs layer's atexit hook would have done (os._exit
            # skips atexit): land the profiler trace + final snapshot of
            # a session run with DLAF_TRACE_DIR/DLAF_METRICS_PATH set
            from dlaf_tpu import obs

            obs._shutdown()
        except Exception:
            pass
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(_exit_status)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
