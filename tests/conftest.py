"""Test harness configuration.

Mirrors the reference's "6 oversubscribed MPI ranks" strategy
(``test/include/dlaf_test/comm_grids/grids_6_ranks.h``) by forcing an
8-device virtual CPU platform so distributed code paths (2D meshes, ICI
collective verbs, shard_map algorithms) run on any host. Must run before the
first ``import jax`` anywhere in the test session.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Run the full assertion ladder in tests (reference CI enables heavy asserts).
os.environ.setdefault("DLAF_ASSERT_HEAVY_ENABLE", "1")

import jax  # noqa: E402

# A TPU plugin's register() may have force-set jax_platforms at interpreter
# start (overriding the env var); the config-level update wins and keeps the
# test session on the 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# The suite is XLA-compile-dominated (the 30 slowest tests are 5-30 s of
# compile each); persist compiled programs across test sessions like the
# bench/product path does (bench.py _cache_dir -> the
# config.compilation_cache_dir knob). Cache key includes platform +
# device count, so TPU/product entries never collide with these.
_cache = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
