"""Cholesky tests (reference: test/unit/factorization/test_cholesky.cpp).

Verification style follows the reference: residual-based checks
|A - L L^H| / |A| <= c * n * eps plus direct comparison against
numpy.linalg.cholesky, over a size sweep including degenerate cases (m=0,
m<=mb, non-divisible m/mb), both uplos, several grid shapes, and non-zero
source-rank offsets.
"""

import jax
import numpy as np
import pytest

from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize

SIZES = [(0, 4), (3, 4), (4, 4), (13, 4), (16, 4), (29, 8)]
DTYPES = [np.float32, np.float64, np.complex64, np.complex128]


def hpd_matrix(n, dtype, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    a = x @ x.conj().T + n * np.eye(n)
    return a.astype(dtype)


def _eps(dtype):
    return np.finfo(np.dtype(dtype).type(0).real.dtype).eps


#: XLA:CPU under the jax 0.4.x line cannot alias buffers through the
#: local path's layout transform, so donation documentedly degrades to a
#: copy there (matrix.tiling.quiet_donation). Only that environment may
#: skip the invalidation assertion — anywhere else an unconsumed donated
#: buffer is a regression of the OOM-headroom property and must FAIL.
_CPU_DONATION_COPY_FALLBACK = (
    jax.default_backend() == "cpu"
    and tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5))


def assert_storage_consumed(storage):
    """Donated storage must be dead; results were already checked
    bit-identical before this is called."""
    if storage.is_deleted():
        with pytest.raises(RuntimeError):
            np.asarray(jax.device_get(storage))
    elif _CPU_DONATION_COPY_FALLBACK:
        pytest.skip("old-jax XLA:CPU copy fallback; donation invalidation "
                    "not observable")
    else:
        pytest.fail("donated storage was not consumed — donation plumbing "
                    "regressed on a backend that can alias")


def check_factor(uplo, a, out, dtype):
    n = a.shape[0]
    if n == 0:
        return
    tol = 60 * max(n, 1) * _eps(dtype)
    if uplo == "L":
        f = np.tril(out)
        resid = np.linalg.norm(f @ f.conj().T - a) / np.linalg.norm(a)
        # untouched triangle passes through
        np.testing.assert_array_equal(np.triu(out, 1), np.triu(a, 1))
    else:
        f = np.triu(out)
        resid = np.linalg.norm(f.conj().T @ f - a) / np.linalg.norm(a)
        np.testing.assert_array_equal(np.tril(out, -1), np.tril(a, -1))
    assert resid < tol, f"residual {resid} >= {tol}"


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("n,nb", SIZES)
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_cholesky_local(uplo, n, nb, dtype):
    a = hpd_matrix(n, dtype)
    mat = Matrix_from(a, nb)
    out = cholesky(uplo, mat).to_numpy()
    check_factor(uplo, a, out, dtype)


@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
def test_cholesky_donate_matches_and_invalidates(grid_shape, devices8):
    """``donate=True`` (the reference's in-place semantics,
    factorization/cholesky.h:36) must produce bit-identical factors while
    consuming the input's device storage — the HBM lever that fits
    N=16384 on one chip."""
    n, nb = 24, 4
    a = hpd_matrix(n, np.float64)
    grid = Grid(*grid_shape) if grid_shape else None
    kept = cholesky("L", Matrix_from(a, nb, grid=grid)).to_numpy()
    mat = Matrix_from(a, nb, grid=grid)
    donated = cholesky("L", mat, donate=True)
    np.testing.assert_array_equal(donated.to_numpy(), kept)
    # the donated storage is dead — any later read must fail loudly
    assert_storage_consumed(mat.storage)


@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
def test_triangular_solve_donate_b(grid_shape, devices8):
    """``donate_b=True`` is bit-identical and consumes only ``b``."""
    import jax

    from dlaf_tpu.algorithms.triangular import triangular_solve

    n, nb = 24, 4
    rng = np.random.default_rng(11)
    a = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    b = rng.standard_normal((n, n))
    grid = Grid(*grid_shape) if grid_shape else None
    am = Matrix_from(a, nb, grid=grid)
    kept = triangular_solve("L", "L", "N", "N", 1.0, am,
                            Matrix_from(b, nb, grid=grid)).to_numpy()
    bm = Matrix_from(b, nb, grid=grid)
    donated = triangular_solve("L", "L", "N", "N", 1.0, am, bm,
                               donate_b=True)
    np.testing.assert_array_equal(donated.to_numpy(), kept)
    # the triangular operand is never consumed — checked BEFORE the
    # consumed-storage helper, which may skip on backends that can't alias
    np.asarray(jax.device_get(am.storage))
    assert_storage_consumed(bm.storage)


@pytest.mark.parametrize("grid_shape", [None, (2, 4)])
def test_red2band_donate_matches_and_invalidates(grid_shape, devices8):
    from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band

    n, nb = 24, 4
    a = hpd_matrix(n, np.float64)
    ah = a + a.T - np.diag(np.diag(a))
    grid = Grid(*grid_shape) if grid_shape else None
    kept = reduction_to_band(Matrix_from(ah, nb, grid=grid))
    am = Matrix_from(ah, nb, grid=grid)
    donated = reduction_to_band(am, donate=True)
    np.testing.assert_array_equal(donated.matrix.to_numpy(),
                                  kept.matrix.to_numpy())
    np.testing.assert_array_equal(np.asarray(donated.taus),
                                  np.asarray(kept.taus))
    assert_storage_consumed(am.storage)


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_cholesky_distributed_col_major_grid(uplo, devices8):
    """Algorithms must be ordering-agnostic: the reference's 6-rank fixture
    includes a col-major 2x3 grid (grids_6_ranks.h); here a col-major 2x4."""
    n, nb = 24, 4
    a = hpd_matrix(n, np.float64)
    grid = Grid(2, 4, ordering="col-major")
    out = cholesky(uplo, Matrix_from(a, nb, grid=grid,
                                     src=RankIndex2D(1, 2))).to_numpy()
    check_factor(uplo, a, out, np.float64)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("trailing", ["biggemm", "invgemm", "xla", "scan"])
@pytest.mark.parametrize("n,nb", [(32, 8), (29, 8)])
def test_cholesky_local_trailing_variants(uplo, trailing, n, nb, dtype, monkeypatch):
    """MXU-shaped trailing-update strategies must match the reference loop
    (config knob ``cholesky_trailing``; see bench.py for the perf A/B)."""
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", trailing)
    import dlaf_tpu.config as config

    config.initialize()
    try:
        a = hpd_matrix(n, dtype)
        out = cholesky(uplo, Matrix_from(a, nb)).to_numpy()
        check_factor(uplo, a, out, dtype)
    finally:
        monkeypatch.delenv("DLAF_CHOLESKY_TRAILING")
        config.initialize()


@pytest.mark.parametrize("dtype", [np.float32, np.complex64])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("n,nb", [(32, 8), (29, 8), (5, 8), (0, 8)])
def test_cholesky_scan_native_dtypes(uplo, n, nb, dtype, monkeypatch):
    """trailing="scan" native branch (non-emulated dtypes), both uplos +
    degenerate sizes: n < nb (single ragged block) and n = 0."""
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "scan")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        a = hpd_matrix(n, dtype)
        out = cholesky(uplo, Matrix_from(a, nb)).to_numpy()
        check_factor(uplo, a, out, dtype)
    finally:
        monkeypatch.delenv("DLAF_CHOLESKY_TRAILING")
        config.initialize()


def Matrix_from(a, nb, grid=None, src=RankIndex2D(0, 0)):
    from dlaf_tpu.matrix.matrix import Matrix
    return Matrix.from_global(a, TileElementSize(nb, nb), grid=grid, source_rank=src)


GRIDS = [(1, 1, 0, 0), (2, 2, 0, 0), (2, 4, 1, 2), (4, 2, 3, 1), (1, 8, 0, 5),
         (8, 1, 2, 0)]


@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128, np.float32])
@pytest.mark.parametrize("rows,cols,sr,sc", GRIDS)
@pytest.mark.parametrize("n,nb", [(16, 4), (13, 4), (29, 8), (8, 8), (3, 4)])
def test_cholesky_distributed(uplo, rows, cols, sr, sc, n, nb, dtype, devices8):
    grid = Grid(rows, cols)
    a = hpd_matrix(n, dtype, seed=n + rows)
    mat = Matrix_from(a, nb, grid=grid, src=RankIndex2D(sr % rows, sc % cols))
    out = cholesky(uplo, mat).to_numpy()
    check_factor(uplo, a, out, dtype)


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_cholesky_distributed_matches_local(uplo, devices8):
    n, nb = 24, 4
    a = hpd_matrix(n, np.float64, seed=9)
    local = cholesky(uplo, Matrix_from(a, nb)).to_numpy()
    dist = cholesky(uplo, Matrix_from(a, nb, grid=Grid(2, 4))).to_numpy()
    np.testing.assert_allclose(dist, local, rtol=1e-12, atol=1e-12)


def test_cholesky_vs_numpy():
    n = 32
    a = hpd_matrix(n, np.float64, seed=1)
    out = cholesky("L", Matrix_from(a, 8)).to_numpy()
    np.testing.assert_allclose(np.tril(out), np.linalg.cholesky(a),
                               rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("mode", ["native", "mxu+mixed"])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128, np.float32])
@pytest.mark.parametrize("rows,cols,sr,sc", [(2, 4, 1, 2), (4, 2, 3, 1),
                                             (2, 2, 0, 0)])
@pytest.mark.parametrize("n,nb", [(29, 8), (16, 4)])
def test_cholesky_distributed_scan(uplo, rows, cols, sr, sc, n, nb, dtype,
                                   mode, devices8, monkeypatch):
    """lax.scan distributed step (trailing="scan"): one compiled body,
    traced per-k index math — must match the analytic factor on offset
    grids, ragged sizes, all dtypes, native and mxu+mixed knob routes."""
    if mode == "mxu+mixed" and dtype == np.float32:
        pytest.skip("mxu/mixed knobs are no-ops for float32 (dtype gate)")
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "scan")
    if mode == "mxu+mixed":
        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "1")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        grid = Grid(rows, cols)
        a = hpd_matrix(n, dtype, seed=n + rows)
        mat = Matrix_from(a, nb, grid=grid,
                          src=RankIndex2D(sr % rows, sc % cols))
        out = cholesky(uplo, mat).to_numpy()
        check_factor(uplo, a, out, dtype)
    finally:
        for k in ("DLAF_CHOLESKY_TRAILING", "DLAF_F64_GEMM",
                  "DLAF_F64_TRSM", "DLAF_F64_GEMM_MIN_DIM"):
            monkeypatch.delenv(k, raising=False)
        config.initialize()


@pytest.mark.parametrize("mode", ["native", "mxu+mixed"])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_cholesky_distributed_scan_multisegment(dtype, mode, devices8,
                                                monkeypatch):
    """nt=11 crosses the telescoping threshold (_telescope_segments -> two
    segments, the second with NONZERO slice offsets lu_r0/lu_c0) — the
    small-nt parametrizations above all run single-segment, so this is the
    coverage for the offset slot math on an offset grid."""
    from dlaf_tpu.algorithms.cholesky import _telescope_segments

    n, nb = 41, 4   # nt = 11
    assert len(_telescope_segments(11)) > 1
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "scan")
    if mode == "mxu+mixed":
        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "1")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        for uplo in ("L", "U"):
            grid = Grid(2, 4)
            a = hpd_matrix(n, dtype, seed=97)
            mat = Matrix_from(a, nb, grid=grid, src=RankIndex2D(1, 2))
            out = cholesky(uplo, mat).to_numpy()
            check_factor(uplo, a, out, dtype)
    finally:
        for k in ("DLAF_CHOLESKY_TRAILING", "DLAF_F64_GEMM",
                  "DLAF_F64_TRSM", "DLAF_F64_GEMM_MIN_DIM"):
            monkeypatch.delenv(k, raising=False)
        config.initialize()


# ---------------------------------------------------------------------------
# Look-ahead (software-pipelined) step order — docs/lookahead.md
# ---------------------------------------------------------------------------

def _cholesky_la(uplo, a, nb, la, monkeypatch, trailing=None, grid=None,
                 src=RankIndex2D(0, 0), comm="0"):
    import dlaf_tpu.config as config

    monkeypatch.setenv("DLAF_CHOLESKY_LOOKAHEAD", la)
    monkeypatch.setenv("DLAF_COMM_LOOKAHEAD", comm)
    if trailing:
        monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", trailing)
    config.initialize()
    try:
        return cholesky(uplo, Matrix_from(a, nb, grid=grid,
                                          src=src)).to_numpy()
    finally:
        monkeypatch.delenv("DLAF_CHOLESKY_LOOKAHEAD")
        monkeypatch.delenv("DLAF_COMM_LOOKAHEAD")
        monkeypatch.delenv("DLAF_CHOLESKY_TRAILING", raising=False)
        config.initialize()


@pytest.mark.parametrize("trailing", [None, "scan"])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_cholesky_lookahead_bitwise_local(uplo, dtype, trailing, monkeypatch):
    """cholesky_lookahead=1 must be BITWISE identical to =0: the pipelined
    order computes the same dots and applies them per cell in the same
    order (docs/lookahead.md) — local, default (loop) + scan step modes,
    ragged edge tile included."""
    n, nb = 29, 8
    a = hpd_matrix(n, dtype, seed=5)
    r0 = _cholesky_la(uplo, a, nb, "0", monkeypatch, trailing)
    r1 = _cholesky_la(uplo, a, nb, "1", monkeypatch, trailing)
    np.testing.assert_array_equal(r1, r0)
    check_factor(uplo, a, r1, dtype)


@pytest.mark.parametrize("trailing", [None, "scan"])
@pytest.mark.parametrize("rows,cols,sr,sc", [(2, 2, 0, 0), (2, 4, 1, 2)])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_cholesky_lookahead_bitwise_distributed(uplo, rows, cols, sr, sc,
                                                trailing, devices8,
                                                monkeypatch):
    """Distributed bitwise A/B at nt=11 (multi-segment telescoped scan +
    cross-step carries on an offset grid): the carried next-column values
    are only trusted where the owner-column masks select them, so every
    rank's result must still match the serialized order exactly."""
    n, nb = 41, 4
    a = hpd_matrix(n, np.float64, seed=n + rows)
    grid, src = Grid(rows, cols), RankIndex2D(sr % rows, sc % cols)
    r0 = _cholesky_la(uplo, a, nb, "0", monkeypatch, trailing, grid, src)
    r1 = _cholesky_la(uplo, a, nb, "1", monkeypatch, trailing, grid, src)
    np.testing.assert_array_equal(r1, r0)
    # comm_lookahead=1 (panel collectives hoisted ahead of the bulk,
    # docs/comm_overlap.md) must also be bitwise-identical
    r2 = _cholesky_la(uplo, a, nb, "1", monkeypatch, trailing, grid, src,
                      comm="1")
    np.testing.assert_array_equal(r2, r0)
    check_factor(uplo, a, r1, np.float64)


@pytest.mark.quick
def test_cholesky_lookahead_quick(monkeypatch, tmp_path):
    """Smoke-tier pin: pipelined == serialized bitwise on the default
    route, and the compiled program's trace-time step accounting reports
    the overlapped step modes (dlaf_cholesky_steps_total)."""
    import dlaf_tpu.config as config
    from dlaf_tpu import obs

    n, nb = 16, 4
    a = hpd_matrix(n, np.float64, seed=2)
    r0 = _cholesky_la("L", a, nb, "0", monkeypatch)
    monkeypatch.setenv("DLAF_CHOLESKY_LOOKAHEAD", "1")
    monkeypatch.setenv("DLAF_METRICS_PATH", str(tmp_path / "m.jsonl"))
    config.initialize()
    try:
        r1 = cholesky("L", Matrix_from(a, nb)).to_numpy()
        snap = obs.registry().snapshot()
        modes = {m["labels"].get("mode"): m["value"] for m in snap
                 if m["name"] == "dlaf_cholesky_steps_total"}
        # nt=4: 3 pipelined steps + the carry-less last one
        assert modes.get("overlapped", 0) >= 3
        assert modes.get("serialized", 0) >= 1
    finally:
        monkeypatch.delenv("DLAF_CHOLESKY_LOOKAHEAD")
        monkeypatch.delenv("DLAF_METRICS_PATH")
        config.initialize()
        obs._reset_for_tests()
    np.testing.assert_array_equal(r1, r0)
    check_factor("L", a, r1, np.float64)


def test_lookahead_breaks_serial_chain():
    """Structural evidence for the pipeline (the bench-level A/B is
    throughput-noise-bound on CPU, where XLA's thunk executor runs ops
    serially): in the pipelined program, step k+1's potrf must NOT
    transitively depend on step k's bulk trailing product, while the
    serialized program's potrf must. Checked on the traced jaxpr of the
    local biggemm form (bulk product = the (m-w, m-w)/(m, m) trailing
    dot), which is exactly the dependency XLA's scheduler sees — via the
    shared walker vocabulary in dlaf_tpu.analysis.depgraph."""
    from dlaf_tpu.algorithms.cholesky import _cholesky_local
    from dlaf_tpu.analysis import depgraph

    import jax.numpy as jnp

    n, nb = 24, 8   # 3 blocks: step 0 bulk is (16,16) or (8,8) rest
    a = jnp.asarray(hpd_matrix(n, np.float64, seed=3))

    def deps_of_second_potrf(lookahead):
        eqns = depgraph.trace(
            lambda x: _cholesky_local.__wrapped__(
                x, uplo="L", nb=nb, trailing="biggemm",
                lookahead=lookahead), a).jaxpr.eqns
        chol = depgraph.positions(eqns, "cholesky")
        assert len(chol) == 3, [e.primitive.name for e in eqns]
        # step 0's bulk trailing product: a dot_general with a square
        # output of the trailing(-rest) extent. w=8, m=16: rest is (8,8)
        # under lookahead, full (16,16) without.
        bulk_shapes = {(16, 16)} if not lookahead else {(8, 8)}
        # transitive producer closure of the SECOND potrf's inputs
        return depgraph.depends_on(
            eqns, chol[1],
            lambda e: (e.primitive.name == "dot_general"
                       and tuple(e.outvars[0].aval.shape) in bulk_shapes))

    assert deps_of_second_potrf(lookahead=False), \
        "serialized form lost its bulk dependency — test is stale"
    assert not deps_of_second_potrf(lookahead=True), \
        "pipelined potrf still depends on the bulk trailing product"
