"""Tests for tile storage transforms and the Matrix container.

Mirrors the reference's ``test/unit/matrix/test_matrix.cpp`` /
``test_layout_info.cpp`` scope: round-trips, edge tiles, non-trivial grids
with source-rank offsets, and sharded placement over the 8-device mesh.
"""

import numpy as np
import pytest

import jax

from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import (GlobalElementSize, GlobalTileIndex, GridSize2D,
                                     LocalElementSize, LocalTileIndex, RankIndex2D,
                                     TileElementSize)
from dlaf_tpu.matrix import layout_info as li
from dlaf_tpu.matrix import tiling
from dlaf_tpu.matrix.distribution import Distribution
from dlaf_tpu.matrix.matrix import Matrix

CASES = [
    # (m, n, mb, nb, P, Q, src_r, src_c)
    (10, 10, 4, 4, 1, 1, 0, 0),
    (12, 12, 4, 4, 2, 2, 0, 0),
    (13, 26, 5, 5, 2, 3, 1, 2),   # edge tiles + source-rank offset
    (7, 7, 8, 8, 2, 2, 1, 1),     # single (short) tile, offset source
    (26, 13, 4, 8, 4, 2, 3, 0),
    (0, 0, 4, 4, 2, 2, 0, 0),
]


def _dist(m, n, mb, nb, P, Q, sr, sc):
    return Distribution(GlobalElementSize(m, n), TileElementSize(mb, nb),
                        GridSize2D(P, Q), RankIndex2D(0, 0), RankIndex2D(sr, sc))


@pytest.mark.parametrize("m,n,mb,nb,P,Q,sr,sc", CASES)
def test_tiling_roundtrip(m, n, mb, nb, P, Q, sr, sc):
    d = _dist(m, n, mb, nb, P, Q, sr, sc)
    rng = np.random.default_rng(42)
    a = rng.standard_normal((m, n))
    t = tiling.global_to_tiles(a, d)
    Sr, Sc, ltr, ltc = tiling.storage_tile_grid(d)
    assert t.shape == (Sr, Sc, mb, nb)
    back = tiling.tiles_to_global(t, d)
    np.testing.assert_array_equal(np.asarray(back), a)


def test_tiling_places_tiles_correctly():
    d = _dist(13, 26, 5, 5, 2, 3, 1, 2)
    a = np.arange(13 * 26, dtype=np.float64).reshape(13, 26)
    t = np.asarray(tiling.global_to_tiles(a, d))
    nt = d.nr_tiles
    for tr in range(nt.row):
        for tc in range(nt.col):
            r, c = tiling.global_tile_to_storage_index(d, tr, tc)
            ts = d.tile_size_of(GlobalTileIndex(tr, tc))
            expect = a[tr * 5: tr * 5 + ts.row, tc * 5: tc * 5 + ts.col]
            np.testing.assert_array_equal(t[r, c, : ts.row, : ts.col], expect)
            # padding region is zero
            assert np.all(t[r, c, ts.row:, :] == 0)
            assert np.all(t[r, c, :, ts.col:] == 0)


@pytest.mark.parametrize("m,n,mb,nb,P,Q,sr,sc", CASES)
def test_matrix_roundtrip_local(m, n, mb, nb, P, Q, sr, sc):
    rng = np.random.default_rng(7)
    a = rng.standard_normal((m, n))
    # without a grid the distribution is 1x1 (source rank must be (0,0) then)
    mat = Matrix.from_global(a, TileElementSize(mb, nb), grid=None)
    np.testing.assert_array_equal(mat.to_numpy(), a)


def test_matrix_sharded_over_mesh(devices8):
    grid = Grid(2, 4)
    rng = np.random.default_rng(3)
    a = rng.standard_normal((24, 24))
    mat = Matrix.from_global(a, TileElementSize(4, 4), grid=grid,
                             source_rank=RankIndex2D(1, 2))
    assert len(mat.storage.sharding.device_set) == 8
    np.testing.assert_array_equal(mat.to_numpy(), a)
    # per-tile reads see the right data
    t = mat.tile(GlobalTileIndex(2, 3))
    np.testing.assert_array_equal(t, a[8:12, 12:16])


def test_matrix_from_global_device_array_retiles_sharded(devices8):
    """A device-resident (sharded) global array re-tiles inside one
    compiled program with the tile sharding on the output — the handoff
    path from mesh-sharded D&C eigenvectors; result must match the numpy
    construction bit for bit."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    grid = Grid(2, 4)
    rng = np.random.default_rng(5)
    a = rng.standard_normal((24, 24))
    a_dev = jax.device_put(
        a, NamedSharding(grid.mesh, PartitionSpec(None, ("row", "col"))))
    mat = Matrix.from_global(a_dev, TileElementSize(4, 4), grid=grid,
                             source_rank=RankIndex2D(1, 2))
    ref = Matrix.from_global(a, TileElementSize(4, 4), grid=grid,
                             source_rank=RankIndex2D(1, 2))
    assert mat.storage.sharding == grid.tile_sharding()
    np.testing.assert_array_equal(np.asarray(mat.storage),
                                  np.asarray(ref.storage))
    # an array committed to a single device (outside the grid layout) must
    # take the eager fallback, not crash the compiled fast path
    a_one = jax.device_put(a, jax.devices()[0])
    mat1 = Matrix.from_global(a_one, TileElementSize(4, 4), grid=grid,
                              source_rank=RankIndex2D(1, 2))
    np.testing.assert_array_equal(np.asarray(mat1.storage),
                                  np.asarray(ref.storage))


def test_matrix_from_element_fn():
    fn = lambda i, j: 1.0 / (1 + i + j)  # noqa: E731
    mat = Matrix.from_element_fn(fn, GlobalElementSize(9, 9), TileElementSize(4, 4))
    a = mat.to_numpy()
    i, j = np.meshgrid(np.arange(9), np.arange(9), indexing="ij")
    np.testing.assert_allclose(a, 1.0 / (1 + i + j))


def test_matrix_complex_dtype():
    a = (np.arange(36).reshape(6, 6) + 1j * np.ones((6, 6))).astype(np.complex128)
    mat = Matrix.from_global(a, TileElementSize(4, 4))
    assert mat.dtype == np.complex128
    np.testing.assert_array_equal(mat.to_numpy(), a)


# -- layout info (reference test_layout_info.cpp) ---------------------------

def test_col_major_layout():
    sz = LocalElementSize(10, 7)
    bl = TileElementSize(4, 3)
    lay = li.col_major_layout(sz, bl, ld=12)
    assert lay.tile_offset(LocalTileIndex(0, 0)) == 0
    assert lay.tile_offset(LocalTileIndex(1, 0)) == 4
    assert lay.tile_offset(LocalTileIndex(0, 1)) == 3 * 12
    assert lay.tile_offset(LocalTileIndex(2, 2)) == 8 + 6 * 12
    # min mem: last tile (2,2) has size (2,1); offset + (1-1)*ld + 2
    assert lay.min_mem_size() == (8 + 6 * 12) + 2


def test_tile_layout():
    sz = LocalElementSize(10, 7)
    bl = TileElementSize(4, 4)
    lay = li.tile_layout(sz, bl)
    # 3x2 tiles, tile area 16, column stride 16*3
    assert lay.tile_offset(LocalTileIndex(1, 0)) == 16
    assert lay.tile_offset(LocalTileIndex(0, 1)) == 48
    last = lay.tile_offset(LocalTileIndex(2, 1))
    assert lay.min_mem_size() == last + (3 - 1) * 4 + 2


def test_layout_empty():
    lay = li.tile_layout(LocalElementSize(0, 0), TileElementSize(4, 4))
    assert lay.min_mem_size() == 0


def test_sharding_matches_distribution_ownership(devices8):
    """The design's central invariant (DESIGN.md par.1): NamedSharding over the
    cyclic-permuted 4D storage places on device (p, q) EXACTLY the tiles the
    block-cyclic Distribution assigns to rank (p, q) — every algorithm's
    shard_map masks assume it. Verified shard-by-shard against the
    Distribution's own ownership math, with a source-rank offset."""
    from dlaf_tpu.matrix.util_distribution import rank_global_tile

    grid = Grid(2, 4)
    P, Q = 2, 4
    src = RankIndex2D(1, 2)
    rng = np.random.default_rng(8)
    n, nb = 28, 4                      # 7x7 tiles: uneven per-rank counts
    a = rng.standard_normal((n, n))
    mat = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid,
                             source_rank=src)
    nt = (n + nb - 1) // nb
    mesh_devs = mat.grid.mesh.devices  # (P, Q) device array
    dev_rank = {d: (p, q) for p in range(P) for q in range(Q)
                for d in [mesh_devs[p, q]]}
    for shard in mat.storage.addressable_shards:
        p, q = dev_rank[shard.device]
        owned = np.asarray(shard.data)   # (ltr, ltc, nb, nb) local tiles
        # collect this rank's global tiles in cyclic (slot) order
        g_rows = [g for g in range(nt) if rank_global_tile(g, P, src.row) == p]
        g_cols = [g for g in range(nt) if rank_global_tile(g, Q, src.col) == q]
        for li_r, g_r in enumerate(g_rows):
            for li_c, g_c in enumerate(g_cols):
                r0, c0 = g_r * nb, g_c * nb
                expect = np.zeros((nb, nb))
                blk = a[r0:min(r0 + nb, n), c0:min(c0 + nb, n)]
                expect[:blk.shape[0], :blk.shape[1]] = blk
                np.testing.assert_array_equal(owned[li_r, li_c], expect,
                                              err_msg=f"tile ({g_r},{g_c}) on rank ({p},{q})")


def test_complex_pair_transfer_mode(monkeypatch):
    """memory.place/fetch pair fallback (PJRT paths that reject complex128
    transfers, docs in matrix/memory.py): with the mode forced on, c128
    Matrix construction and gather round-trip bit-identically through
    paired f64 transfers."""
    from dlaf_tpu.matrix import memory

    rng = np.random.default_rng(11)
    a = rng.standard_normal((24, 24)) + 1j * rng.standard_normal((24, 24))
    ref = Matrix.from_global(a, TileElementSize(8, 8)).to_numpy()

    monkeypatch.setattr(memory, "_complex_pair_mode", True)
    m = Matrix.from_global(a, TileElementSize(8, 8))
    got = m.to_numpy()
    assert got.dtype == np.complex128
    assert got.tobytes() == np.asarray(ref).tobytes()
    t = m.tile(GlobalTileIndex(1, 2))
    assert t.tobytes() == np.asarray(ref[8:16, 16:24]).tobytes()

    # distributed construction reshards device-resident complex storage
    # (Matrix._shard) — must stay on device in pair mode, no direct
    # complex transfer
    from dlaf_tpu.comm.grid import Grid

    md = Matrix.from_global(a, TileElementSize(8, 8), grid=Grid(2, 4))
    assert np.asarray(md.to_numpy()).tobytes() == np.asarray(ref).tobytes()


def test_complex_pair_fallback_detection(monkeypatch):
    """The try/except detection path: a direct complex device_put failing
    (while the probe also fails) falls back to the pair route, latches the
    mode with a warning, and still round-trips bit-exactly. Non-complex
    failures re-raise untouched."""
    import warnings as _warnings

    import jax as _jax

    from dlaf_tpu.matrix import memory

    real_put = _jax.device_put

    def flaky_put(x, sharding=None):
        if np.iscomplexobj(x):
            # the PJRT error type place() recognizes as a transfer
            # rejection (a bare RuntimeError must NOT trigger the retry)
            from jax.errors import JaxRuntimeError

            raise JaxRuntimeError("synthetic: backend rejects complex128")
        return real_put(x, sharding)

    monkeypatch.setattr(memory, "_complex_pair_mode", None)
    monkeypatch.setattr(_jax, "device_put", flaky_put)
    a = (np.arange(12.0) + 1j * np.arange(12.0)[::-1]).reshape(3, 4)
    with _warnings.catch_warnings(record=True) as w:
        _warnings.simplefilter("always")
        out = memory.place(a)
    assert memory._complex_pair_mode is True
    assert any("pair mode" in str(x.message) for x in w)
    assert np.asarray(out).tobytes() == a.tobytes()
    # real arrays that fail must re-raise, not loop into the pair path
    monkeypatch.setattr(memory, "_complex_pair_mode", None)
    monkeypatch.setattr(
        _jax, "device_put",
        lambda x, sharding=None: (_ for _ in ()).throw(RuntimeError("down")))
    with pytest.raises(RuntimeError, match="down"):
        memory.place(np.ones((2, 2)))


def test_complex_pair_fallback_ignores_non_transfer_errors(monkeypatch):
    """Round-2 advisory: only recognized transfer-error types trigger the
    pair retry. A bare RuntimeError (interpreter teardown, unrelated bug)
    and a RESOURCE_EXHAUSTED device OOM both re-raise directly — the pair
    path transiently needs MORE memory, and an unrelated failure would
    just fail a second time."""
    import jax as _jax
    from jax.errors import JaxRuntimeError

    from dlaf_tpu.matrix import memory

    a = (np.arange(4.0) + 1j * np.arange(4.0)).reshape(2, 2)

    def put_raising(exc):
        return lambda x, sharding=None: (_ for _ in ()).throw(exc)

    monkeypatch.setattr(memory, "_complex_pair_mode", None)
    monkeypatch.setattr(_jax, "device_put",
                        put_raising(RuntimeError("not a transfer error")))
    with pytest.raises(RuntimeError, match="not a transfer"):
        memory.place(a)
    assert memory._complex_pair_mode is None

    monkeypatch.setattr(
        _jax, "device_put",
        put_raising(JaxRuntimeError("RESOURCE_EXHAUSTED: out of memory")))
    with pytest.raises(JaxRuntimeError, match="RESOURCE_EXHAUSTED"):
        memory.place(a)
    assert memory._complex_pair_mode is None
    # fetch symmetric: device OOM on readback re-raises too
    monkeypatch.setattr(
        _jax, "device_get",
        put_raising(JaxRuntimeError("RESOURCE_EXHAUSTED: host")))
    with pytest.raises(JaxRuntimeError, match="RESOURCE_EXHAUSTED"):
        memory.fetch(jnp_complex_probe())


def jnp_complex_probe():
    import jax.numpy as jnp

    return jnp.asarray(np.ones((2, 2), np.complex128))
