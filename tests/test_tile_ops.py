"""Tile-kernel correctness vs numpy/scipy.

Mirrors the reference's ``test/unit/test_blas_tile/`` and
``test_lapack_tile/`` suites: every op, all four scalar types, square and
rectangular blocks, batched forms.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dlaf_tpu.tile_ops import blas as tb
from dlaf_tpu.tile_ops import lapack as tl

DTYPES = [np.float32, np.float64, np.complex64, np.complex128]


def rand(rng, shape, dtype):
    a = rng.standard_normal(shape)
    if np.dtype(dtype).kind == "c":
        a = a + 1j * rng.standard_normal(shape)
    return a.astype(dtype)


def _tol(dtype):
    eps = np.finfo(np.dtype(dtype).type(0).real.dtype).eps
    return dict(rtol=200 * eps, atol=200 * eps)


def np_op(a, op):
    return {"N": a, "T": a.T, "C": a.conj().T}[op]


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("opa,opb", [("N", "N"), ("T", "N"), ("N", "C"), ("C", "T")])
def test_gemm(dtype, opa, opb):
    rng = np.random.default_rng(0)
    m, n, k = 7, 5, 6
    a = rand(rng, (k, m) if opa != "N" else (m, k), dtype)
    b = rand(rng, (n, k) if opb != "N" else (k, n), dtype)
    c = rand(rng, (m, n), dtype)
    out = tb.gemm(jnp.asarray(a), jnp.asarray(b), jnp.asarray(c),
                  alpha=2.0, beta=0.5, op_a=opa, op_b=opb)
    expect = 2.0 * np_op(a, opa) @ np_op(b, opb) + 0.5 * c
    np.testing.assert_allclose(np.asarray(out), expect, **_tol(dtype))


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_gemm_batched(dtype):
    rng = np.random.default_rng(1)
    a = rand(rng, (4, 3, 6, 5), dtype)
    b = rand(rng, (4, 3, 5, 7), dtype)
    out = np.asarray(tb.gemm(jnp.asarray(a), jnp.asarray(b)))
    for i in range(4):
        for j in range(3):
            np.testing.assert_allclose(out[i, j], a[i, j] @ b[i, j], **_tol(dtype))


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("side,uplo", [("L", "L"), ("L", "U"), ("R", "L")])
def test_hemm(dtype, side, uplo):
    rng = np.random.default_rng(2)
    n, m = 6, 6
    a = rand(rng, (n, n), dtype)
    b = rand(rng, (n, m), dtype)
    c = rand(rng, (n, m), dtype)
    # reference semantics: only the uplo triangle of a is read
    afull = np.tril(a, -1) + np.tril(a, -1).conj().T + np.diag(np.real(np.diag(a))) \
        if uplo == "L" else np.triu(a, 1) + np.triu(a, 1).conj().T + np.diag(np.real(np.diag(a)))
    expect = 1.5 * (afull @ b if side == "L" else b @ afull) + 0.5 * c
    out = tb.hemm(side, uplo, jnp.asarray(a), jnp.asarray(b), jnp.asarray(c),
                  alpha=1.5, beta=0.5)
    np.testing.assert_allclose(np.asarray(out), expect, **_tol(dtype))


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("uplo,op", [("L", "N"), ("U", "N"), ("L", "C")])
def test_herk(dtype, uplo, op):
    rng = np.random.default_rng(3)
    n, k = 6, 4
    a = rand(rng, (n, k) if op == "N" else (k, n), dtype)
    c = rand(rng, (n, n), dtype)
    if np.dtype(dtype).kind == "c":
        # zherk assumes the imaginary part of C's diagonal is zero
        np.fill_diagonal(c, np.real(np.diag(c)))
    out = np.asarray(tb.herk(uplo, op, jnp.asarray(a), jnp.asarray(c),
                             alpha=0.5, beta=2.0))
    oa = a if op == "N" else a.conj().T
    expect_full = 0.5 * (oa @ oa.conj().T) + 2.0 * c
    if uplo == "L":
        np.testing.assert_allclose(np.tril(out), np.tril(expect_full), **_tol(dtype))
        np.testing.assert_allclose(np.triu(out, 1), np.triu(c, 1), **_tol(dtype))
    else:
        np.testing.assert_allclose(np.triu(out), np.triu(expect_full), **_tol(dtype))
        np.testing.assert_allclose(np.tril(out, -1), np.tril(c, -1), **_tol(dtype))
    if np.dtype(dtype).kind == "c":
        assert np.allclose(np.imag(np.diag(out)), 0)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_her2k(dtype, uplo):
    rng = np.random.default_rng(4)
    n, k = 5, 3
    a = rand(rng, (n, k), dtype)
    b = rand(rng, (n, k), dtype)
    c = rand(rng, (n, n), dtype)
    alpha = 1.5 - 0.5j if np.dtype(dtype).kind == "c" else 1.5
    out = np.asarray(tb.her2k(uplo, "N", jnp.asarray(a), jnp.asarray(b),
                              jnp.asarray(c), alpha=alpha, beta=0.5))
    expect = alpha * a @ b.conj().T + np.conj(alpha) * b @ a.conj().T + 0.5 * c
    if uplo == "L":
        np.testing.assert_allclose(np.tril(out), np.tril(expect), **_tol(dtype))
    else:
        np.testing.assert_allclose(np.triu(out), np.triu(expect), **_tol(dtype))


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("side,uplo,op,diag",
                         [("L", "L", "N", "N"), ("L", "U", "T", "N"),
                          ("R", "L", "C", "N"), ("L", "L", "N", "U")])
def test_trmm(dtype, side, uplo, op, diag):
    rng = np.random.default_rng(5)
    n, m = 6, 4
    adim = n if side == "L" else m
    a = rand(rng, (adim, adim), dtype)
    b = rand(rng, (n, m), dtype)
    t = np.tril(a) if uplo == "L" else np.triu(a)
    if diag == "U":
        np.fill_diagonal(t, 1.0)
    expect = 2.0 * (np_op(t, op) @ b if side == "L" else b @ np_op(t, op))
    out = tb.trmm(side, uplo, op, diag, jnp.asarray(a), jnp.asarray(b), alpha=2.0)
    np.testing.assert_allclose(np.asarray(out), expect, **_tol(dtype))


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("side,uplo,op,diag",
                         [("L", "L", "N", "N"), ("L", "L", "C", "N"),
                          ("L", "U", "T", "N"), ("R", "L", "C", "N"),
                          ("R", "U", "N", "U")])
def test_trsm(dtype, side, uplo, op, diag):
    rng = np.random.default_rng(6)
    n, m = 6, 4
    adim = n if side == "L" else m
    a = rand(rng, (adim, adim), dtype)
    a = a + adim * np.eye(adim, dtype=dtype)  # well-conditioned
    b = rand(rng, (n, m), dtype)
    out = np.asarray(tb.trsm(side, uplo, op, diag, jnp.asarray(a), jnp.asarray(b),
                             alpha=2.0))
    t = np.tril(a) if uplo == "L" else np.triu(a)
    if diag == "U":
        np.fill_diagonal(t, 1.0)
    ot = np_op(t, op)
    residual = (ot @ out if side == "L" else out @ ot) - 2.0 * b
    np.testing.assert_allclose(residual, np.zeros_like(b), **_tol(dtype))


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("side", ["L", "R"])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("op", ["N", "T", "C"])
def test_trsm_recursive_matches_native(monkeypatch, dtype, side, uplo, op):
    """The recursive blocked solve (large-n memory/MXU path) must agree with
    the native lowering on every side/uplo/op combo."""
    monkeypatch.setattr(tb, "TRSM_RECURSE_MIN", 48)
    rng = np.random.default_rng(11)
    n, m = 160, 96  # non-power-of-two, crosses several recursion levels
    adim = n if side == "L" else m
    a = rand(rng, (adim, adim), dtype)
    a = a + adim * np.eye(adim, dtype=dtype)
    b = rand(rng, (n, m), dtype)
    out = np.asarray(tb.trsm(side, uplo, op, "N", jnp.asarray(a),
                             jnp.asarray(b), alpha=0.5))
    t = np.tril(a) if uplo == "L" else np.triu(a)
    ot = np_op(t, op)
    residual = (ot @ out if side == "L" else out @ ot) - 0.5 * b
    np.testing.assert_allclose(residual, np.zeros_like(b), **_tol(dtype))


# -- lapack tile ops --------------------------------------------------------

@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("uplo", ["L", "U", "G"])
def test_laset_lacpy(dtype, uplo):
    rng = np.random.default_rng(7)
    a = np.asarray(tl.laset(uplo, 2.0, 5.0, (4, 6), dtype))
    full = np.full((4, 6), 2.0) + 3.0 * np.eye(4, 6)
    expect = {"G": full, "L": np.tril(full), "U": np.triu(full)}[uplo]
    np.testing.assert_allclose(a, expect.astype(dtype))

    src = rand(rng, (5, 5), dtype)
    dst = rand(rng, (5, 5), dtype)
    out = np.asarray(tl.lacpy(uplo, jnp.asarray(src), jnp.asarray(dst)))
    if uplo == "G":
        np.testing.assert_allclose(out, src)
    elif uplo == "L":
        np.testing.assert_allclose(np.tril(out), np.tril(src))
        np.testing.assert_allclose(np.triu(out, 1), np.triu(dst, 1))
    else:
        np.testing.assert_allclose(np.triu(out), np.triu(src))
        np.testing.assert_allclose(np.tril(out, -1), np.tril(dst, -1))


@pytest.mark.parametrize("norm", ["M", "1", "I", "F"])
def test_lange(norm):
    rng = np.random.default_rng(8)
    a = rng.standard_normal((5, 7))
    expect = {"M": np.max(np.abs(a)), "1": np.max(np.abs(a).sum(0)),
              "I": np.max(np.abs(a).sum(1)), "F": np.linalg.norm(a)}[norm]
    np.testing.assert_allclose(float(tl.lange(norm, jnp.asarray(a))), expect, rtol=1e-14)


def test_lantr():
    rng = np.random.default_rng(9)
    a = rng.standard_normal((5, 5))
    t = np.tril(a)
    np.testing.assert_allclose(float(tl.lantr("M", "L", "N", jnp.asarray(a))),
                               np.max(np.abs(t)), rtol=1e-14)
    tu = np.tril(a, -1) + np.eye(5)
    np.testing.assert_allclose(float(tl.lantr("F", "L", "U", jnp.asarray(a))),
                               np.linalg.norm(tu), rtol=1e-14)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_potrf(dtype, uplo):
    rng = np.random.default_rng(10)
    n = 6
    x = rand(rng, (n, n), dtype)
    spd = x @ x.conj().T + n * np.eye(n, dtype=dtype)
    out = np.asarray(tl.potrf(uplo, jnp.asarray(spd)))
    if uplo == "L":
        f = np.tril(out)
        np.testing.assert_allclose(f @ f.conj().T, spd, **_tol(dtype))
        np.testing.assert_allclose(np.triu(out, 1), np.triu(spd, 1), **_tol(dtype))
    else:
        f = np.triu(out)
        np.testing.assert_allclose(f.conj().T @ f, spd, **_tol(dtype))
        np.testing.assert_allclose(np.tril(out, -1), np.tril(spd, -1), **_tol(dtype))


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_hegst(dtype, uplo):
    rng = np.random.default_rng(11)
    n = 6
    x = rand(rng, (n, n), dtype)
    a = x @ x.conj().T + n * np.eye(n, dtype=dtype)  # Hermitian PD
    y = rand(rng, (n, n), dtype)
    bfull = y @ y.conj().T + n * np.eye(n, dtype=dtype)
    bf = np.linalg.cholesky(bfull) if uplo == "L" else np.linalg.cholesky(bfull).conj().T
    out = np.asarray(tl.hegst(1, uplo, jnp.asarray(a), jnp.asarray(bf)))
    if uplo == "L":
        expect = np.linalg.solve(bf, a) @ np.linalg.inv(bf).conj().T
        np.testing.assert_allclose(np.tril(out), np.tril(expect), **_tol(dtype))
    else:
        expect = np.linalg.solve(bf.conj().T, a) @ np.linalg.inv(bf)
        np.testing.assert_allclose(np.triu(out), np.triu(expect), **_tol(dtype))


@pytest.mark.parametrize("dtype", DTYPES)
def test_larft_matches_reflector_product(dtype):
    rng = np.random.default_rng(12)
    m, k = 8, 4
    v = rand(rng, (m, k), dtype)
    v = np.tril(v, -1) + np.eye(m, k, dtype=dtype)
    # proper Householder taus: tau = 2 / (v^H v) makes each I - tau v v^H unitary
    taus = np.array([2.0 / np.real(np.vdot(v[:, i], v[:, i])) for i in range(k)],
                    dtype=dtype)
    t = np.asarray(tl.larft(jnp.asarray(v), jnp.asarray(taus)))
    q_block = np.eye(m, dtype=dtype) - v @ t @ v.conj().T
    q_prod = np.eye(m, dtype=dtype)
    for i in range(k):
        q_prod = q_prod @ (np.eye(m, dtype=dtype)
                           - taus[i] * np.outer(v[:, i], v[:, i].conj()))
    np.testing.assert_allclose(q_block, q_prod, **_tol(dtype))
    assert np.allclose(np.tril(t, -1), 0)


def test_larft_zero_tau():
    rng = np.random.default_rng(13)
    v = np.tril(rng.standard_normal((6, 3)), -1) + np.eye(6, 3)
    taus = np.array([0.5, 0.0, 0.25])
    t = np.asarray(tl.larft(jnp.asarray(v), jnp.asarray(taus)))
    assert np.allclose(t[1, :], 0) and np.allclose(t[:, 1], 0)
    assert np.isfinite(t).all()


def test_larft_zero_tau_stale_column_wy_identity():
    """Interior tau==0 with a NONZERO stored sub-diagonal in that column:
    LAPACK dlarft treats the column as a null reflector; the closed form
    must not route cross terms through it (round-1 advisor finding). The
    check is the full WY identity against the explicit reflector product."""
    rng = np.random.default_rng(113)
    m, k = 8, 4
    v = np.tril(rng.standard_normal((m, k)), -1) + np.eye(m, k)
    taus = np.array([2.0 / np.dot(v[:, i], v[:, i]) for i in range(k)])
    taus[1] = 0.0  # interior null reflector, stale column data left in v
    t = np.asarray(tl.larft(jnp.asarray(v), jnp.asarray(taus)))
    q_block = np.eye(m) - v @ t @ v.T
    q_prod = np.eye(m)
    for i in range(k):
        q_prod = q_prod @ (np.eye(m) - taus[i] * np.outer(v[:, i], v[:, i]))
    np.testing.assert_allclose(q_block, q_prod, rtol=1e-12, atol=1e-12)


def test_stedc_vs_scipy():
    rng = np.random.default_rng(14)
    n = 12
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    w, v = tl.stedc(d, e)
    tri = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    np.testing.assert_allclose(v @ np.diag(w) @ v.T, tri, atol=1e-12)
    assert np.all(np.diff(w) >= 0)


def test_axpy_gemv_trmv():
    rng = np.random.default_rng(15)
    a = rng.standard_normal((4, 4))
    x = rng.standard_normal(4)
    y = rng.standard_normal(4)
    np.testing.assert_allclose(np.asarray(tb.axpy(x, y, alpha=2.5)),
                               y + 2.5 * x, atol=1e-14)
    np.testing.assert_allclose(np.asarray(tb.gemv(a, x, y, alpha=2.0, beta=-1.0)),
                               2.0 * a @ x - y, atol=1e-13)
    np.testing.assert_allclose(np.asarray(tb.gemv(a, x, op_a="T", alpha=1.0)),
                               a.T @ x, atol=1e-13)
    t = np.tril(a)
    np.testing.assert_allclose(np.asarray(tb.trmv("L", "N", "N", a, x)),
                               t @ x, atol=1e-13)
    tu = np.tril(a, -1) + np.eye(4)
    np.testing.assert_allclose(np.asarray(tb.trmv("L", "C", "U", a, x)),
                               tu.T @ x, atol=1e-13)


def test_potrf_info():
    rng = np.random.default_rng(16)
    x = rng.standard_normal((5, 5))
    spd = x @ x.T + 5 * np.eye(5)
    f, info = tl.potrf_info("L", jnp.asarray(spd))
    assert int(info) == 0
    np.testing.assert_allclose(np.tril(np.asarray(f)) @ np.tril(np.asarray(f)).T,
                               spd, atol=1e-10)
    # indefinite input: info = 1-based first failing column, factor has NaNs
    bad = np.diag([1.0, -1.0, 1.0, 1.0, 1.0])
    f2, info2 = tl.potrf_info("L", jnp.asarray(bad))
    assert int(info2) >= 1


def test_laed4_secular_roots():
    rng = np.random.default_rng(17)
    k = 8
    d = np.sort(rng.standard_normal(k))
    z = rng.standard_normal(k)
    z /= np.linalg.norm(z)
    rho = 0.7
    lam = tl.laed4(d, z, rho)
    # roots of the rank-one-updated matrix == eigvals of D + rho z z^T
    w = np.linalg.eigvalsh(np.diag(d) + rho * np.outer(z, z))
    np.testing.assert_allclose(np.sort(lam), w, atol=1e-10)
