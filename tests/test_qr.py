"""QR T-factor public API (reference factorization/qr: test via the
compact-WY identity (I - V T V^H) == product of the k reflectors, local and
distributed, against a scipy-built reflector panel)."""

import numpy as np
import pytest

from dlaf_tpu.algorithms.qr import t_factor
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize
from dlaf_tpu.matrix.matrix import Matrix


def reflector_panel(m, k, dtype, seed=0):
    """Random reflector panel + taus. The compact-WY identity
    ``I - V T V^H == prod_j (I - tau_j w_j w_j^H)`` holds for ANY taus with
    T from the accumulation recurrence (unitarity of the factors is not
    required), so random data tests larft/t_factor fully."""
    rng = np.random.default_rng(seed)
    v = rng.standard_normal((m, k))
    if np.dtype(dtype).kind == "c":
        v = v + 1j * rng.standard_normal((m, k))
    # tau = 2 / ||w||^2 with w = [1; v_below_diag] makes each factor unitary,
    # so the accumulated product stays O(1) and tolerances are clean
    taus = np.empty(k, dtype=dtype)
    for j in range(k):
        taus[j] = 2.0 / (1.0 + np.sum(np.abs(v[j + 1:, j]) ** 2))
    return v.astype(dtype), taus.astype(dtype)


def q_from_reflectors(v, taus):
    m, k = v.shape
    q = np.eye(m, dtype=v.dtype)
    for j in range(k):
        w = np.zeros(m, dtype=v.dtype)
        w[j] = 1.0
        w[j + 1:] = v[j + 1:, j]
        q = q @ (np.eye(m, dtype=v.dtype) - taus[j] * np.outer(w, w.conj()))
    return q


def check_t(v, taus, t):
    m, k = v.shape
    vv = np.tril(v, -1) + np.eye(m, k, dtype=v.dtype)
    q_wy = np.eye(m, dtype=v.dtype) - vv @ t @ vv.conj().T
    q_ref = q_from_reflectors(v, taus)
    assert np.linalg.norm(q_wy - q_ref) < 1e-12 * m


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("m,k", [(24, 8), (16, 16), (13, 5)])
def test_t_factor_local_array(m, k, dtype):
    v, taus = reflector_panel(m, k, dtype, seed=m)
    t = np.asarray(t_factor(v, taus))
    check_t(v, taus, t)


def test_t_factor_local_matrix(devices8):
    v, taus = reflector_panel(24, 8, np.float64, seed=1)
    vm = Matrix.from_global(v, TileElementSize(8, 8))
    t = np.asarray(t_factor(vm, taus))
    check_t(v, taus, t)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("grid_shape,src", [((2, 2), (0, 0)), ((2, 4), (1, 2)),
                                            ((4, 2), (3, 0))])
def test_t_factor_distributed(grid_shape, src, dtype, devices8):
    m, k = 40, 8
    v, taus = reflector_panel(m, k, dtype, seed=3)
    grid = Grid(*grid_shape)
    srk = RankIndex2D(src[0] % grid_shape[0], src[1] % grid_shape[1])
    vm = Matrix.from_global(v, TileElementSize(8, 8), grid=grid,
                            source_rank=srk)
    t = np.asarray(t_factor(vm, taus))
    check_t(v, taus, t)
    # matches the local closed form exactly (same math, distributed Gram)
    t_local = np.asarray(t_factor(v, taus))
    np.testing.assert_allclose(t, t_local, rtol=1e-12, atol=1e-13)


def test_t_factor_zero_tau_rows(devices8):
    v, taus = reflector_panel(24, 8, np.float64, seed=4)
    taus = taus.copy()
    taus[3] = 0.0   # null reflector -> zero row/col in T (LAPACK semantics)
    t = np.asarray(t_factor(v, taus))
    assert np.all(t[3, :] == 0) and np.all(t[:, 3] == 0)
